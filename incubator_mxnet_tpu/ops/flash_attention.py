"""Flash attention as a Pallas TPU kernel.

The framework's subgraph/Pallas escape hatch earning its keep (the role
TensorRT plays behind the reference's subgraph framework,
`src/operator/subgraph/partition_graph.cc:767`): plain XLA attention
materializes the (B, H, T, T) score tensor in HBM; this kernel streams KV
blocks through VMEM with the online-softmax recurrence, so HBM traffic is
O(T·D) instead of O(T²) — the standard flash-attention win, implemented
here as a `pl.pallas_call` grid over (batch·heads, query blocks).

Two surfaces:

* `flash_attention(q, k, v, causal=...)` — full attention, differentiable
  (custom VJP recomputes blockwise on the backward pass, keeping the
  no-T²-residual property).
* `flash_attention_partial(q, k, v, ...)` — returns the UNNORMALIZED
  accumulator plus per-row (max, sumexp): the exact contract of one ring
  step, so `parallel.ring_attention(..., use_pallas=True)` fuses its local
  block with this kernel while `ppermute` rotates the KV shards.

Layout: (B, T, H, D) at the API (the framework's attention layout); the
kernel runs on (B·H, T, D).  On non-TPU backends both surfaces fall back
to the jnp blockwise implementation — same math, same signatures, so the
CPU test mesh exercises the identical call graph.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["flash_attention", "flash_attention_partial"]

_NEG = -1e30


def _use_kernel():
    """Run the Pallas kernel on TPU; MXNET_FLASH_INTERPRET=1 forces it in
    interpreter mode so the CPU suite tests the KERNEL, not the fallback."""
    import os
    if os.environ.get("MXNET_FLASH_INTERPRET") == "1":
        return True, True
    try:
        return jax.extend.backend.get_backend().platform == "tpu", False
    except Exception:
        return False, False


# ---------------------------------------------------------------------------
# Pallas kernel: one (BH, q-block) program; fori_loop over KV blocks
# ---------------------------------------------------------------------------

def _fwd_kernel(qoff_ref, koff_ref, q_ref, k_ref, v_ref,
                o_ref, m_ref, l_ref, acc_scr, m_scr, l_scr,
                *, block_k, causal, kv_len):
    from jax.experimental import pallas as pl

    q = q_ref[0]                                # (BQ, D), PRE-SCALED
    bq = q.shape[0]
    nk = pl.cdiv(kv_len, block_k)

    m_scr[:] = jnp.full(m_scr.shape, _NEG, jnp.float32)
    l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
    acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    q_start = qoff_ref[0] + pl.program_id(1) * bq
    if causal:
        q_pos = q_start + \
            jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)

    def compute(i, masked=True):
        ks = k_ref[0, pl.ds(i * block_k, block_k), :]   # (BK, D)
        vs = v_ref[0, pl.ds(i * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, ks, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # (BQ, BK)
        if causal and masked:
            # only blocks touching the diagonal need the mask; interior
            # blocks skip the iota/compare/select VPU passes
            k_pos = koff_ref[0] + i * block_k + \
                jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG)
        m_prev = m_scr[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:, 0] = l_scr[:, 0] * alpha + jnp.sum(p, axis=-1)
        acc_scr[:] = acc_scr[:] * alpha[:, None] + jax.lax.dot_general(
            p.astype(vs.dtype), vs, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:, 0] = m_new

    if causal:
        # split at the diagonal: blocks strictly above it are fully masked
        # and never execute (the structural causal win the unfused path
        # cannot have — it always materializes all T x T scores); blocks
        # strictly below need no mask at all; only diagonal-touching
        # blocks pay the mask's VPU passes.  Offsets are traced ring
        # positions, so both bounds are dynamic.
        koff = koff_ref[0]
        n_unmasked = jnp.clip((q_start - koff) // block_k, 0, nk)
        last = (q_start + bq - 1 - koff) // block_k
        nk_run = jnp.clip(last + 1, 0, nk)
        jax.lax.fori_loop(0, n_unmasked,
                          lambda i, _: (compute(i, masked=False), 0)[1], 0)
        jax.lax.fori_loop(n_unmasked, nk_run,
                          lambda i, _: (compute(i, masked=True), 0)[1], 0)
    else:
        jax.lax.fori_loop(0, nk,
                          lambda i, _: (compute(i, masked=False), 0)[1], 0)
    o_ref[0] = acc_scr[:].astype(o_ref.dtype)
    m_ref[0] = m_scr[:, 0]
    l_ref[0] = l_scr[:, 0]


def _fwd_kernel_stream(qoff_ref, koff_ref, q_ref, k_ref, v_ref,
                       o_ref, m_ref, l_ref, acc_scr, m_scr, l_scr,
                       *, block_k, causal):
    """KV-streaming variant: one (BH, q-block, KV-block) grid step per
    invocation, accumulator carried in VMEM scratch across the innermost
    grid axis.  Holds only ONE (block_k, D) K/V tile in VMEM at a time, so
    kv_len is bounded by HBM, not VMEM — the long-context envelope
    (T=32k+ causal) the whole-KV kernel cannot reach.  Causal grid steps
    entirely above the diagonal skip their compute via pl.when (their
    block DMA still happens — the structural-skip win of the whole-KV
    kernel's dynamic loop bounds is the price of streaming)."""
    from jax.experimental import pallas as pl

    j = pl.program_id(2)
    nk = pl.num_programs(2)
    q = q_ref[0]                                # (BQ, D), PRE-SCALED
    bq = q.shape[0]

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, _NEG, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    q_start = qoff_ref[0] + pl.program_id(1) * bq
    k_start = koff_ref[0] + j * block_k

    def _compute():
        ks = k_ref[0]                           # (BK, D)
        vs = v_ref[0]
        s = jax.lax.dot_general(
            q, ks, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # (BQ, BK)
        if causal:
            q_pos = q_start + \
                jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = k_start + \
                jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG)
        m_prev = m_scr[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:, 0] = l_scr[:, 0] * alpha + jnp.sum(p, axis=-1)
        acc_scr[:] = acc_scr[:] * alpha[:, None] + jax.lax.dot_general(
            p.astype(vs.dtype), vs, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:, 0] = m_new

    if causal:
        @pl.when(q_start + bq - 1 >= k_start)
        def _run():
            _compute()
    else:
        _compute()

    @pl.when(j == nk - 1)
    def _finish():
        o_ref[0] = acc_scr[:].astype(o_ref.dtype)
        m_ref[0] = m_scr[:, 0]
        l_ref[0] = l_scr[:, 0]


def _stream_tpu(q3, k3, v3, q_off, k_off, causal, block_q, block_k,
                interpret=False):
    """KV-streaming pallas_call (see _fwd_kernel_stream)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, Tq, D = q3.shape
    kv_len = k3.shape[1]
    scale = 1.0 / (D ** 0.5)
    q3 = (q3.astype(jnp.float32) * scale).astype(q3.dtype)
    grid = (BH, pl.cdiv(Tq, block_q), pl.cdiv(kv_len, block_k))
    kernel = functools.partial(_fwd_kernel_stream, block_k=block_k,
                               causal=causal)
    o, m, l = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),   # q_off (1,)
            pl.BlockSpec(memory_space=pltpu.SMEM),   # k_off (1,)
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q), lambda b, i, j: (b, i)),
            pl.BlockSpec((1, block_q), lambda b, i, j: (b, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Tq, D), q3.dtype),
            jax.ShapeDtypeStruct((BH, Tq), jnp.float32),
            jax.ShapeDtypeStruct((BH, Tq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(jnp.asarray([q_off], jnp.int32), jnp.asarray([k_off], jnp.int32),
      q3, k3, v3)
    return o, m, l


def _vmem_budget_bytes():
    from .. import config as _config
    return int(float(_config.get("MXNET_FLASH_VMEM_MB")) * 2 ** 20)


def _partial_tpu(q3, k3, v3, q_off, k_off, causal, block_q, block_k,
                 interpret=False):
    """(BH, Tq, D) partial attention on TPU via the Pallas kernel."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, Tq, D = q3.shape
    kv_len = k3.shape[1]
    block_q = min(block_q, Tq)
    block_k = min(block_k, kv_len)
    # blocks must tile exactly (a short tail block would read out of range)
    while Tq % block_q:
        block_q //= 2
    while kv_len % block_k:
        block_k //= 2
    # whole-KV kernel maps (kv_len, D) K and V blocks into VMEM (fast, and
    # its dynamic loop bounds skip above-diagonal blocks entirely); past
    # the VMEM budget, stream KV tiles through the grid instead
    kv_bytes = 2 * kv_len * D * q3.dtype.itemsize
    if kv_bytes > _vmem_budget_bytes():
        return _stream_tpu(q3, k3, v3, q_off, k_off, causal,
                           block_q, block_k, interpret=interpret)
    # fold the softmax scale into q once (saves a full VPU pass over the
    # (BQ, BK) score block per inner iteration)
    scale = 1.0 / (D ** 0.5)
    q3 = (q3.astype(jnp.float32) * scale).astype(q3.dtype)
    grid = (BH, pl.cdiv(Tq, block_q))

    kernel = functools.partial(_fwd_kernel, block_k=block_k, causal=causal,
                               kv_len=kv_len)
    o, m, l = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),   # q_off (1,)
            pl.BlockSpec(memory_space=pltpu.SMEM),   # k_off (1,)
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, kv_len, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, kv_len, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q), lambda b, i: (b, i)),
            pl.BlockSpec((1, block_q), lambda b, i: (b, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Tq, D), q3.dtype),
            jax.ShapeDtypeStruct((BH, Tq), jnp.float32),
            jax.ShapeDtypeStruct((BH, Tq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(jnp.asarray([q_off], jnp.int32), jnp.asarray([k_off], jnp.int32),
      q3, k3, v3)
    return o, m, l


def _partial_ref(q3, k3, v3, q_off, k_off, causal, block_k):
    """jnp blockwise partial (non-TPU fallback; identical contract)."""
    BH, Tq, D = q3.shape
    kv_len = k3.shape[1]
    scale = 1.0 / (D ** 0.5)
    nk = -(-kv_len // block_k)
    m = jnp.full((BH, Tq), _NEG, jnp.float32)
    l = jnp.zeros((BH, Tq), jnp.float32)
    acc = jnp.zeros((BH, Tq, D), jnp.float32)
    q_pos = q_off + jnp.arange(Tq)
    for i in range(nk):
        ks = k3[:, i * block_k:(i + 1) * block_k]
        vs = v3[:, i * block_k:(i + 1) * block_k]
        s = jnp.einsum("bqd,bkd->bqk", q3, ks).astype(jnp.float32) * scale
        if causal:
            k_pos = k_off + i * block_k + jnp.arange(ks.shape[1])
            s = jnp.where(q_pos[:, None] >= k_pos[None, :], s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[..., None] + \
            jnp.einsum("bqk,bkd->bqd", p.astype(vs.dtype), vs)
        m = m_new
    return acc.astype(q3.dtype), m, l


def flash_attention_partial(q, k, v, q_off=0, k_off=0, causal=False,
                            block_q=256, block_k=256):
    """Unnormalized attention over one KV shard.

    q: (B, Tq, H, D), k/v: (B, Tk, H, D).  Returns (o_unnorm, m, l) with
    o_unnorm (B, Tq, H, D) and m/l (B, H, Tq) in fp32 — combinable across
    shards with the online-softmax merge (ring attention's carry).
    q_off/k_off are the global sequence offsets for causal masking (traced
    scalars are fine: they ride SMEM, not the compiled shape).
    """
    B, Tq, H, D = q.shape
    q3 = q.transpose(0, 2, 1, 3).reshape(B * H, Tq, D)
    k3 = k.transpose(0, 2, 1, 3).reshape(B * H, k.shape[1], D)
    v3 = v.transpose(0, 2, 1, 3).reshape(B * H, v.shape[1], D)
    use, interpret = _use_kernel()
    if use:
        o3, m3, l3 = _partial_tpu(q3, k3, v3, q_off, k_off, causal,
                                  block_q, block_k, interpret=interpret)
    else:
        o3, m3, l3 = _partial_ref(q3, k3, v3, q_off, k_off, causal, block_k)
    o = o3.reshape(B, H, Tq, D).transpose(0, 2, 1, 3)
    return o, m3.reshape(B, H, Tq), l3.reshape(B, H, Tq)


# ---------------------------------------------------------------------------
# Full attention with custom VJP (blockwise recompute backward)
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, causal=False, block_q=256, block_k=256):
    """Exact attention without the (T, T) score tensor in HBM.

    q/k/v: (B, T, H, D) -> (B, T, H, D).  Forward is the Pallas kernel on
    TPU; backward recomputes attention blockwise (standard
    flash-attention backward, here via jnp so XLA fuses it — residuals are
    O(T·D), never O(T²))."""
    o, m, l = flash_attention_partial(q, k, v, 0, 0, causal,
                                      block_q, block_k)
    return o / l.transpose(0, 2, 1)[..., None].astype(o.dtype)


def _flash_fwd(q, k, v, causal, block_q, block_k):
    o, m, l = flash_attention_partial(q, k, v, 0, 0, causal,
                                      block_q, block_k)
    out = o / l.transpose(0, 2, 1)[..., None].astype(o.dtype)
    return out, (q, k, v, out, m, l)


def _flash_bwd(causal, block_q, block_k, res, g):
    q, k, v, out, m, l = res
    B, T, H, D = q.shape
    scale = 1.0 / (D ** 0.5)
    # delta_i = rowsum(dO * O) — the softmax-jacobian shortcut
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1).transpose(0, 2, 1)          # (B, H, T)
    qh = q.transpose(0, 2, 1, 3).astype(jnp.float32)     # (B, H, T, D)
    kh = k.transpose(0, 2, 1, 3).astype(jnp.float32)
    vh = v.transpose(0, 2, 1, 3).astype(jnp.float32)
    gh = g.transpose(0, 2, 1, 3).astype(jnp.float32)
    dq = jnp.zeros_like(qh)
    dk = jnp.zeros_like(kh)
    dv = jnp.zeros_like(vh)
    nk = -(-T // block_k)
    q_pos = jnp.arange(T)
    for i in range(nk):
        sl = slice(i * block_k, (i + 1) * block_k)
        ks, vs = kh[:, :, sl], vh[:, :, sl]
        s = jnp.einsum("bhqd,bhkd->bhqk", qh, ks) * scale
        if causal:
            k_pos = jnp.arange(T)[sl]
            s = jnp.where(q_pos[:, None] >= k_pos[None, :], s, _NEG)
        p = jnp.exp(s - m[..., None]) / l[..., None]     # (B, H, T, BK)
        dv = dv.at[:, :, sl].add(jnp.einsum("bhqk,bhqd->bhkd", p, gh))
        dp = jnp.einsum("bhqd,bhkd->bhqk", gh, vs)
        ds = p * (dp - delta[..., None]) * scale
        dq = dq + jnp.einsum("bhqk,bhkd->bhqd", ds, ks)
        dk = dk.at[:, :, sl].add(jnp.einsum("bhqk,bhqd->bhkd", ds, qh))
    back = lambda a, like: a.transpose(0, 2, 1, 3).astype(like.dtype)
    return back(dq, q), back(dk, k), back(dv, v)


flash_attention.defvjp(_flash_fwd, _flash_bwd)
