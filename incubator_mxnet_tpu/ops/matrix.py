"""Tensor shape/indexing/linear-algebra manipulation ops.

Reference: `src/operator/tensor/matrix_op.cc` (reshape w/ special codes,
transpose, slice, dot, …), `indexing_op.cc` (take/Embedding/one_hot/
gather_nd/scatter_nd), `ordering_op.cc` (topk/sort/argsort),
`init_op.cc` handled in init_ops.py, sequence ops from `src/operator/
sequence_{last,mask,reverse}.cc`, `swapaxis.cc`, `pad.cc`, `crop.cc`,
`slice_channel.cc`, `concat.cc`, `diag_op.cc`, `depth_to_space` family.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register, REQUIRED
from ..base import MXNetError


# ---------------------------------------------------------------------------
# Reshape with MXNet's special codes (reference matrix_op-inl.h InferReshapeShape)
# ---------------------------------------------------------------------------

def infer_reshape(target, src_shape, reverse=False):
    """Resolve an MXNet target shape spec (0/-1/-2/-3/-4 codes) to a concrete shape."""
    target = list(target)
    src = list(src_shape)
    if reverse:
        target = target[::-1]
        src = src[::-1]
    out = []
    i = 0  # index into target
    j = 0  # index into src
    while i < len(target):
        t = target[i]
        if t == 0:
            out.append(src[j]); j += 1
        elif t == -1:
            out.append(-1); j += 1
        elif t == -2:
            out.extend(src[j:]); j = len(src)
        elif t == -3:
            out.append(src[j] * src[j + 1]); j += 2
        elif t == -4:
            d1, d2 = target[i + 1], target[i + 2]
            i += 2
            if d1 == -1 and d2 == -1:
                raise MXNetError("Split dims cannot both be -1.")
            if d1 == -1:
                d1 = src[j] // d2
            if d2 == -1:
                d2 = src[j] // d1
            out.extend([d1, d2]); j += 1
        else:
            out.append(int(t)); j += 1
        i += 1
    if reverse:
        out = out[::-1]
    # infer the single -1
    if -1 in out:
        known = 1
        for d in out:
            if d != -1:
                known *= d
        total = int(np.prod(src_shape)) if src_shape else 1
        out[out.index(-1)] = total // max(known, 1)
    return tuple(out)


@register("Reshape", aliases=("reshape",),
          params={"shape": (), "reverse": False, "target_shape": None, "keep_highest": False})
def _reshape(params, x):
    shape = params["shape"]
    if not shape and params["target_shape"]:
        shape = params["target_shape"]  # legacy param
    return jnp.reshape(x, infer_reshape(shape, x.shape, bool(params["reverse"])))


@register("Flatten", aliases=("flatten",))
def _flatten(params, x):
    """Collapse all but the first axis (reference matrix_op.cc Flatten)."""
    return jnp.reshape(x, (x.shape[0], -1))


@register("transpose", params={"axes": ()})
def _transpose(params, x):
    axes = params["axes"] or None
    return jnp.transpose(x, axes)


@register("expand_dims", params={"axis": REQUIRED})
def _expand_dims(params, x):
    return jnp.expand_dims(x, int(params["axis"]))


@register("squeeze", params={"axis": None})
def _squeeze(params, x):
    axis = params["axis"]
    if isinstance(axis, int):
        axis = (axis,)
    return jnp.squeeze(x, axis)


@register("SwapAxis", aliases=("swapaxes",), params={"dim1": 0, "dim2": 0})
def _swapaxes(params, x):
    return jnp.swapaxes(x, int(params["dim1"]), int(params["dim2"]))


def _norm_begin_end(shape, begin, end, step=None):
    ndim = len(shape)
    begin = list(begin) + [None] * (ndim - len(begin))
    end = list(end) + [None] * (ndim - len(end))
    step = list(step or []) + [None] * (ndim - len(step or []))
    slices = []
    for b, e, s in zip(begin, end, step):
        slices.append(slice(b, e, s))
    return tuple(slices)


@register("slice", params={"begin": REQUIRED, "end": REQUIRED, "step": None},
          aliases=("crop",))
def _slice(params, x):
    """Reference matrix_op.cc slice (begin/end/step, None-able entries)."""
    return x[_norm_begin_end(x.shape, params["begin"], params["end"], params["step"])]


@register("slice_axis", params={"axis": REQUIRED, "begin": REQUIRED, "end": None})
def _slice_axis(params, x):
    axis = int(params["axis"]) % x.ndim
    sl = [slice(None)] * x.ndim
    sl[axis] = slice(params["begin"], params["end"])
    return x[tuple(sl)]


@register("slice_like", nin=2, params={"axes": ()})
def _slice_like(params, x, like):
    axes = params["axes"] or tuple(range(x.ndim))
    sl = [slice(None)] * x.ndim
    for a in axes:
        a = a % x.ndim
        sl[a] = slice(0, like.shape[a])
    return x[tuple(sl)]


@register("reverse", aliases=("flip",), params={"axis": REQUIRED})
def _reverse(params, x):
    axis = params["axis"]
    if isinstance(axis, int):
        axis = (axis,)
    return jnp.flip(x, axis)


@register("tile", params={"reps": REQUIRED})
def _tile(params, x):
    return jnp.tile(x, params["reps"])


@register("repeat", params={"repeats": REQUIRED, "axis": None})
def _repeat(params, x):
    axis = params["axis"]
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return jnp.repeat(x, int(params["repeats"]), axis=int(axis))


@register("Pad", aliases=("pad",),
          params={"mode": "constant", "pad_width": REQUIRED, "constant_value": 0.0})
def _pad(params, x):
    pw = params["pad_width"]
    pairs = [(int(pw[2 * i]), int(pw[2 * i + 1])) for i in range(len(pw) // 2)]
    mode = params["mode"]
    if mode == "constant":
        return jnp.pad(x, pairs, mode="constant",
                       constant_values=params["constant_value"])
    if mode == "edge":
        return jnp.pad(x, pairs, mode="edge")
    if mode == "reflect":
        return jnp.pad(x, pairs, mode="reflect")
    raise MXNetError(f"Pad: unknown mode {mode}")


# ---------------------------------------------------------------------------
# Concat / stack / split
# ---------------------------------------------------------------------------

@register("Concat", aliases=("concat",), nin=-1, variadic_param="num_args",
          params={"num_args": 0, "dim": 1})
def _concat(params, *xs):
    return jnp.concatenate(xs, axis=int(params["dim"]))


@register("stack", nin=-1, variadic_param="num_args",
          params={"num_args": 0, "axis": 0})
def _stack(params, *xs):
    return jnp.stack(xs, axis=int(params["axis"]))


@register("add_n", aliases=("ElementWiseSum", "_sum"), nin=-1,
          variadic_param="num_args", params={"num_args": 0})
def _add_n(params, *xs):
    """Reference `ElementwiseSum` (`src/ndarray/ndarray.cc:1243`)."""
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


def _split_nout(params):
    return int(params["num_outputs"])


@register("SliceChannel", aliases=("split",), nout=_split_nout,
          params={"num_outputs": REQUIRED, "axis": 1, "squeeze_axis": False})
def _split(params, x):
    """Reference `slice_channel.cc` — split along axis into num_outputs parts."""
    n = int(params["num_outputs"])
    axis = int(params["axis"]) % x.ndim
    parts = jnp.split(x, n, axis=axis)
    if params["squeeze_axis"]:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


# ---------------------------------------------------------------------------
# dot / batch_dot
# ---------------------------------------------------------------------------

@register("dot", nin=2, params={"transpose_a": False, "transpose_b": False,
                                "forward_stype": None})
def _dot(params, a, b):
    """Reference `src/operator/tensor/dot.cc`: contract last axis of a with
    first axis of b (after optional transposes).  Lowers to a single MXU matmul."""
    if params["transpose_a"]:
        a = jnp.transpose(a)
    if params["transpose_b"]:
        b = jnp.transpose(b)
    if a.ndim == 1 and b.ndim == 1:
        return jnp.dot(a, b)
    return jnp.tensordot(a, b, axes=1)


@register("batch_dot", nin=2, params={"transpose_a": False, "transpose_b": False,
                                      "forward_stype": None})
def _batch_dot(params, a, b):
    ta, tb = params["transpose_a"], params["transpose_b"]
    if ta:
        a = jnp.swapaxes(a, -1, -2)
    if tb:
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b)


# ---------------------------------------------------------------------------
# Indexing
# ---------------------------------------------------------------------------

@register("take", nin=2, params={"axis": 0, "mode": "clip"})
def _take(params, a, indices):
    mode = params["mode"]
    idx = indices.astype("int32")
    axis = int(params["axis"]) % a.ndim
    n = a.shape[axis]
    if mode == "wrap":
        idx = jnp.mod(idx, n)
    else:
        idx = jnp.clip(idx, 0, n - 1)
    return jnp.take(a, idx, axis=axis)


@register("batch_take", nin=2)
def _batch_take(params, a, indices):
    idx = jnp.clip(indices.astype("int32"), 0, a.shape[1] - 1)
    return jnp.take_along_axis(a, idx[:, None], axis=1)[:, 0]


@register("Embedding", nin=2,
          params={"input_dim": REQUIRED, "output_dim": REQUIRED,
                  "dtype": "float32", "sparse_grad": False},
          input_names=["data", "weight"])
def _embedding(params, data, weight):
    """Reference `indexing_op.cc` Embedding: weight[data] gather."""
    idx = jnp.clip(data.astype("int32"), 0, int(params["input_dim"]) - 1)
    return jnp.take(weight, idx, axis=0)


@register("one_hot", params={"depth": REQUIRED, "on_value": 1.0,
                             "off_value": 0.0, "dtype": "float32"})
def _one_hot(params, indices):
    depth = int(params["depth"])
    on, off = params["on_value"], params["off_value"]
    oh = jax.nn.one_hot(indices.astype("int32"), depth, dtype=params["dtype"])
    return oh * (on - off) + off


@register("gather_nd", nin=2)
def _gather_nd(params, data, indices):
    """Reference indexing_op.cc gather_nd: indices (M, Y...) selects
    data[idx_0,...,idx_{M-1}] -> output (Y..., data.shape[M:])."""
    m = indices.shape[0]
    idx = tuple(indices[i].astype("int32") for i in range(m))
    return data[idx]


@register("scatter_nd", nin=2, params={"shape": REQUIRED})
def _scatter_nd(params, data, indices):
    shape = tuple(params["shape"])
    m = indices.shape[0]
    out = jnp.zeros(shape, dtype=data.dtype)
    idx = tuple(indices[i].astype("int32") for i in range(m))
    return out.at[idx].set(data)


@register("_index", params={"key": REQUIRED})
def _index(params, x):
    """Basic indexing as a differentiable op (the reference routes basic
    `__getitem__` through the slice op so gradients flow; `matrix_op.cc`)."""
    return x[params["key"]]


@register("_index_nd", nin=2)
def _index_nd(params, x, idx):
    """Advanced (integer-array) indexing along axis 0, differentiable."""
    return x[idx.astype("int32")]


@register("reshape_like", nin=2, params={"lhs_begin": None, "lhs_end": None,
                                         "rhs_begin": None, "rhs_end": None})
def _reshape_like(params, lhs, rhs):
    """Reference matrix_op.cc reshape_like."""
    return jnp.reshape(lhs, rhs.shape)


@register("pick", nin=2, params={"axis": -1, "keepdims": False, "mode": "clip"})
def _pick(params, data, index):
    """Reference broadcast_reduce_op_index.cc pick: select one element along
    axis per position of index."""
    axis = int(params["axis"]) % data.ndim
    idx = index.astype("int32")
    n = data.shape[axis]
    if params["mode"] == "wrap":
        idx = jnp.mod(idx, n)
    else:
        idx = jnp.clip(idx, 0, n - 1)
    idx_exp = jnp.expand_dims(idx, axis)
    out = jnp.take_along_axis(data, idx_exp, axis=axis)
    if params["keepdims"]:
        return out
    return jnp.squeeze(out, axis)


@register("where", nin=3)
def _where(params, cond, x, y):
    return jnp.where(cond != 0, x, y)


# ---------------------------------------------------------------------------
# Ordering (reference ordering_op.cc)
# ---------------------------------------------------------------------------

def _topk_nout(params):
    return 2 if params.get("ret_typ") == "both" else 1


@register("topk", nout=_topk_nout,
          params={"axis": -1, "k": 1, "ret_typ": "indices", "is_ascend": False,
                  "dtype": "float32"})
def _topk(params, x):
    axis = int(params["axis"]) % x.ndim
    k = int(params["k"])
    ret = params["ret_typ"]
    neg = not params["is_ascend"]
    xm = jnp.moveaxis(x, axis, -1)
    vals, idxs = jax.lax.top_k(xm if neg else -xm, k)
    if not neg:
        vals = -vals
    vals = jnp.moveaxis(vals, -1, axis)
    idxs = jnp.moveaxis(idxs, -1, axis).astype(params["dtype"])
    if ret == "value":
        return vals
    if ret == "indices":
        return idxs
    if ret == "both":
        return vals, idxs
    if ret == "mask":
        oh = jax.nn.one_hot(jnp.moveaxis(idxs, axis, -1).astype("int32"),
                            x.shape[axis], dtype=x.dtype).sum(axis=-2)
        return jnp.moveaxis(oh, -1, axis)
    raise MXNetError(f"topk: bad ret_typ {ret}")


@register("sort", params={"axis": -1, "is_ascend": True})
def _sort(params, x):
    out = jnp.sort(x, axis=int(params["axis"]))
    if not params["is_ascend"]:
        out = jnp.flip(out, axis=int(params["axis"]))
    return out


@register("argsort", params={"axis": -1, "is_ascend": True, "dtype": "float32"})
def _argsort(params, x):
    axis = int(params["axis"])
    idx = jnp.argsort(x, axis=axis)
    if not params["is_ascend"]:
        idx = jnp.flip(idx, axis=axis)
    return idx.astype(params["dtype"])


# ---------------------------------------------------------------------------
# Misc structure ops
# ---------------------------------------------------------------------------

@register("Cast", aliases=("cast",), params={"dtype": REQUIRED})
def _cast(params, x):
    return x.astype(params["dtype"])


@register("shape_array")
def _shape_array(params, x):
    return jnp.asarray(x.shape, dtype="int64")


@register("size_array")
def _size_array(params, x):
    return jnp.asarray([x.size], dtype="int64")


@register("diag", params={"k": 0, "axis1": 0, "axis2": 1})
def _diag(params, x):
    if x.ndim == 1:
        return jnp.diag(x, k=int(params["k"]))
    return jnp.diagonal(x, offset=int(params["k"]),
                        axis1=int(params["axis1"]), axis2=int(params["axis2"]))


@register("depth_to_space", params={"block_size": REQUIRED})
def _depth_to_space(params, x):
    b = int(params["block_size"])
    n, c, h, w = x.shape
    x = x.reshape(n, b, b, c // (b * b), h, w)
    x = x.transpose(0, 3, 4, 1, 5, 2)
    return x.reshape(n, c // (b * b), h * b, w * b)


@register("space_to_depth", params={"block_size": REQUIRED})
def _space_to_depth(params, x):
    b = int(params["block_size"])
    n, c, h, w = x.shape
    x = x.reshape(n, c, h // b, b, w // b, b)
    x = x.transpose(0, 5, 3, 1, 2, 4)
    return x.reshape(n, c * b * b, h // b, w // b)


# ---------------------------------------------------------------------------
# Sequence ops (reference sequence_last/mask/reverse.cc): data is
# (seq_len, batch, ...) with optional per-batch sequence_length input.
# ---------------------------------------------------------------------------

@register("SequenceLast", nin=-1, params={"use_sequence_length": False, "axis": 0})
def _sequence_last(params, data, *rest):
    axis = int(params["axis"])
    if params["use_sequence_length"] and rest:
        seqlen = rest[0].astype("int32")
        idx = jnp.maximum(seqlen - 1, 0)
        dm = jnp.moveaxis(data, axis, 0)
        return jax.vmap(lambda i, col: col[i], in_axes=(0, 1), out_axes=0)(idx, dm)
    sl = [slice(None)] * data.ndim
    sl[axis] = -1
    return data[tuple(sl)]


@register("SequenceMask", nin=-1,
          params={"use_sequence_length": False, "value": 0.0, "axis": 0})
def _sequence_mask(params, data, *rest):
    if not params["use_sequence_length"] or not rest:
        return data + 0
    axis = int(params["axis"])
    seqlen = rest[0].astype("int32")
    T = data.shape[axis]
    steps = jnp.arange(T)
    mask = steps[:, None] < seqlen[None, :]  # (T, B)
    if axis == 1:
        mask = mask.T
        shape = [1] * data.ndim
        shape[0], shape[1] = data.shape[0], data.shape[1]
    else:
        shape = [1] * data.ndim
        shape[0], shape[1] = data.shape[0], data.shape[1]
    mask = mask.reshape(shape)
    return jnp.where(mask, data, jnp.asarray(params["value"], data.dtype))


@register("SequenceReverse", nin=-1, params={"use_sequence_length": False, "axis": 0})
def _sequence_reverse(params, data, *rest):
    axis = int(params["axis"])
    if not params["use_sequence_length"] or not rest:
        return jnp.flip(data, axis=axis)
    seqlen = rest[0].astype("int32")
    T = data.shape[axis]
    steps = jnp.arange(T)

    def rev_col(col, n):
        idx = jnp.where(steps < n, n - 1 - steps, steps)
        return col[idx]

    dm = jnp.moveaxis(data, axis, 0)
    out = jax.vmap(rev_col, in_axes=(1, 0), out_axes=1)(dm, seqlen)
    return jnp.moveaxis(out, 0, axis)
