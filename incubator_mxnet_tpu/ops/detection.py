"""Object-detection operators (reference `src/operator/contrib/` —
multibox_prior.cc, multibox_target.cc, multibox_detection.cc,
bounding_box.cc box_nms/box_iou, roi_align.cc; legacy `roi_pooling.cc`).

These feed the SSD config (BASELINE config #5).  All are jax-traceable with
static shapes: NMS keeps a fixed-size output with -1 padding (the reference
does the same), matching semantics over XLA-friendly dense math.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register, REQUIRED


def _parse_floats(v, default):
    if v is None or v == ():
        return tuple(default)
    if isinstance(v, (int, float)):
        return (float(v),)
    return tuple(float(x) for x in v)


@register("_contrib_MultiBoxPrior", aliases=("MultiBoxPrior",),
          params={"sizes": (1.0,), "ratios": (1.0,), "clip": False,
                  "steps": (-1.0, -1.0), "offsets": (0.5, 0.5)})
def _multibox_prior(params, data):
    """Anchor generation (reference multibox_prior-inl.h): per feature-map
    cell, anchors for (sizes[0], r) x ratios plus extra sizes at ratio 1."""
    sizes = _parse_floats(params["sizes"], [1.0])
    ratios = _parse_floats(params["ratios"], [1.0])
    offsets = _parse_floats(params["offsets"], [0.5, 0.5])
    steps = _parse_floats(params["steps"], [-1.0, -1.0])
    h, w = data.shape[2], data.shape[3]
    step_y = steps[0] if steps[0] > 0 else 1.0 / h
    step_x = steps[1] if steps[1] > 0 else 1.0 / w

    cy = (jnp.arange(h) + offsets[0]) * step_y
    cx = (jnp.arange(w) + offsets[1]) * step_x
    cyx = jnp.stack(jnp.meshgrid(cy, cx, indexing="ij"), axis=-1)  # (h,w,2)

    # anchor list: (size[0], ratio[0]..), then (size[1:], ratio[0])
    whs = []
    for r in ratios:
        sr = np.sqrt(r)
        whs.append((sizes[0] * sr, sizes[0] / sr))
    for s in sizes[1:]:
        sr = np.sqrt(ratios[0])
        whs.append((s * sr, s / sr))
    whs = jnp.asarray(whs)  # (A, 2) of (w, h)
    na = whs.shape[0]

    cxy = jnp.stack([cyx[..., 1], cyx[..., 0]], axis=-1)  # (h, w, 2) x,y
    cxy = jnp.broadcast_to(cxy[:, :, None, :], (h, w, na, 2))
    half = jnp.broadcast_to(whs[None, None] / 2, (h, w, na, 2))
    boxes = jnp.concatenate([cxy - half, cxy + half], axis=-1)
    boxes = boxes.reshape(1, h * w * na, 4)
    if params["clip"]:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return boxes.astype(data.dtype)


def _box_iou_xyxy(a, b):
    """IoU between (..., Na, 4) and (..., Nb, 4)."""
    tl = jnp.maximum(a[..., :, None, :2], b[..., None, :, :2])
    br = jnp.minimum(a[..., :, None, 2:], b[..., None, :, 2:])
    wh = jnp.maximum(br - tl, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.maximum((a[..., 2] - a[..., 0]) * (a[..., 3] - a[..., 1]), 0)
    area_b = jnp.maximum((b[..., 2] - b[..., 0]) * (b[..., 3] - b[..., 1]), 0)
    union = area_a[..., :, None] + area_b[..., None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


@register("_contrib_box_iou", nin=2, params={"format": "corner"})
def _box_iou(params, lhs, rhs):
    """Reference bounding_box.cc box_iou."""
    if params["format"] == "center":
        def to_corner(b):
            xy, wh = b[..., :2], b[..., 2:]
            return jnp.concatenate([xy - wh / 2, xy + wh / 2], -1)
        lhs, rhs = to_corner(lhs), to_corner(rhs)
    return _box_iou_xyxy(lhs, rhs)


@register("_contrib_MultiBoxTarget", aliases=("MultiBoxTarget",), nin=3,
          nout=3,
          params={"overlap_threshold": 0.5, "ignore_label": -1.0,
                  "negative_mining_ratio": -1.0, "negative_mining_thresh": 0.5,
                  "minimum_negative_samples": 0,
                  "variances": (0.1, 0.1, 0.2, 0.2)})
def _multibox_target(params, anchors, labels, cls_preds):
    """Anchor matching + target encoding (reference multibox_target-inl.h).

    anchors (1, N, 4); labels (B, M, 5) [cls, x1, y1, x2, y2] padded with -1;
    cls_preds (B, C+1, N).  Returns (loc_target (B, N*4), loc_mask (B, N*4),
    cls_target (B, N))."""
    var = _parse_floats(params["variances"], [0.1, 0.1, 0.2, 0.2])
    thresh = float(params["overlap_threshold"])
    anc = anchors[0]                                  # (N, 4)
    N = anc.shape[0]

    def per_sample(lab):
        valid = lab[:, 0] >= 0                         # (M,)
        gt = lab[:, 1:5]
        ious = _box_iou_xyxy(anc, gt)                  # (N, M)
        ious = jnp.where(valid[None, :], ious, -1.0)
        best_gt = jnp.argmax(ious, axis=1)             # (N,)
        best_iou = jnp.max(ious, axis=1)
        matched = best_iou >= thresh
        # force-match: each gt claims its best anchor
        best_anchor = jnp.argmax(ious, axis=0)         # (M,)
        forced = jnp.zeros(N, bool).at[best_anchor].set(valid)
        forced_gt = jnp.zeros(N, jnp.int32).at[best_anchor].set(
            jnp.arange(lab.shape[0], dtype=jnp.int32))
        use_forced = forced
        gt_idx = jnp.where(use_forced, forced_gt, best_gt)
        pos = matched | forced

        m_gt = gt[gt_idx]                              # (N, 4)
        acx = (anc[:, 0] + anc[:, 2]) / 2
        acy = (anc[:, 1] + anc[:, 3]) / 2
        aw = jnp.maximum(anc[:, 2] - anc[:, 0], 1e-8)
        ah = jnp.maximum(anc[:, 3] - anc[:, 1], 1e-8)
        gcx = (m_gt[:, 0] + m_gt[:, 2]) / 2
        gcy = (m_gt[:, 1] + m_gt[:, 3]) / 2
        gw = jnp.maximum(m_gt[:, 2] - m_gt[:, 0], 1e-8)
        gh = jnp.maximum(m_gt[:, 3] - m_gt[:, 1], 1e-8)
        loc = jnp.stack([(gcx - acx) / aw / var[0],
                         (gcy - acy) / ah / var[1],
                         jnp.log(gw / aw) / var[2],
                         jnp.log(gh / ah) / var[3]], axis=-1)  # (N, 4)
        mask = pos[:, None].astype(anc.dtype) * jnp.ones((N, 4), anc.dtype)
        cls_t = jnp.where(pos, lab[gt_idx, 0] + 1, 0.0)
        return (loc * mask).reshape(-1), mask.reshape(-1), cls_t

    loc_t, loc_m, cls_t = jax.vmap(per_sample)(labels)
    return loc_t, loc_m, cls_t


@register("_contrib_MultiBoxDetection", aliases=("MultiBoxDetection",), nin=3,
          params={"clip": True, "threshold": 0.01, "background_id": 0,
                  "nms_threshold": 0.5, "force_suppress": False,
                  "variances": (0.1, 0.1, 0.2, 0.2), "nms_topk": -1})
def _multibox_detection(params, cls_prob, loc_pred, anchors):
    """Decode + NMS (reference multibox_detection-inl.h).
    cls_prob (B, C+1, N), loc_pred (B, N*4), anchors (1, N, 4).
    Output (B, N, 6) rows [cls_id, score, x1, y1, x2, y2], -1 padded."""
    var = _parse_floats(params["variances"], [0.1, 0.1, 0.2, 0.2])
    nms_thresh = float(params["nms_threshold"])
    score_thresh = float(params["threshold"])
    B, C1, N = cls_prob.shape

    anc = anchors[0]
    acx = (anc[:, 0] + anc[:, 2]) / 2
    acy = (anc[:, 1] + anc[:, 3]) / 2
    aw = anc[:, 2] - anc[:, 0]
    ah = anc[:, 3] - anc[:, 1]

    def per_sample(probs, loc):
        loc = loc.reshape(N, 4)
        cx = loc[:, 0] * var[0] * aw + acx
        cy = loc[:, 1] * var[1] * ah + acy
        w = jnp.exp(loc[:, 2] * var[2]) * aw
        h = jnp.exp(loc[:, 3] * var[3]) * ah
        boxes = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], -1)
        if params["clip"]:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        cls_id = jnp.argmax(probs[1:], axis=0).astype(jnp.float32)  # (N,)
        score = jnp.max(probs[1:], axis=0)
        keep = score > score_thresh
        score = jnp.where(keep, score, 0.0)

        order = jnp.argsort(-score)
        boxes_o = boxes[order]
        score_o = score[order]
        cls_o = cls_id[order]
        ious = _box_iou_xyxy(boxes_o, boxes_o)
        same_cls = (cls_o[:, None] == cls_o[None, :]) | \
            bool(params["force_suppress"])
        sup = (ious > nms_thresh) & same_cls

        def body(i, alive):
            row = sup[i] & alive[i] & (jnp.arange(N) > i)
            return alive & ~row

        alive = jax.lax.fori_loop(0, N, body, score_o > 0)
        out_cls = jnp.where(alive, cls_o, -1.0)
        out_score = jnp.where(alive, score_o, 0.0)
        return jnp.concatenate([out_cls[:, None], out_score[:, None],
                                boxes_o], axis=-1)

    return jax.vmap(per_sample)(cls_prob, loc_pred)


@register("_contrib_box_nms", aliases=("_contrib_box_non_maximum_suppression",),
          nout=1,
          params={"overlap_thresh": 0.5, "valid_thresh": 0.0, "topk": -1,
                  "coord_start": 2, "score_index": 1, "id_index": -1,
                  "background_id": -1, "force_suppress": False,
                  "in_format": "corner", "out_format": "corner"})
def _box_nms(params, data):
    """Reference bounding_box.cc box_nms: suppressed rows become -1."""
    cs = int(params["coord_start"])
    si = int(params["score_index"])
    ii = int(params["id_index"])
    thresh = float(params["overlap_thresh"])
    valid_thresh = float(params["valid_thresh"])
    orig_shape = data.shape
    flat = data.reshape((-1,) + data.shape[-2:])  # (B, N, K)
    N = flat.shape[1]

    def per_batch(rows):
        score = rows[:, si]
        boxes = jax.lax.dynamic_slice_in_dim(rows, cs, 4, axis=1)
        if params["in_format"] == "center":
            xy, wh = boxes[:, :2], boxes[:, 2:]
            boxes = jnp.concatenate([xy - wh / 2, xy + wh / 2], -1)
        valid = score > valid_thresh
        order = jnp.argsort(-jnp.where(valid, score, -jnp.inf))
        rows_o = rows[order]
        boxes_o = boxes[order]
        valid_o = valid[order]
        ious = _box_iou_xyxy(boxes_o, boxes_o)
        if ii >= 0 and not params["force_suppress"]:
            ids = rows_o[:, ii]
            same = ids[:, None] == ids[None, :]
        else:
            same = jnp.ones((N, N), bool)
        sup = (ious > thresh) & same

        def body(i, alive):
            row = sup[i] & alive[i] & (jnp.arange(N) > i)
            return alive & ~row

        alive = jax.lax.fori_loop(0, N, body, valid_o)
        return jnp.where(alive[:, None], rows_o, -jnp.ones_like(rows_o))

    out = jax.vmap(per_batch)(flat)
    return out.reshape(orig_shape)


@register("ROIPooling", nin=2,
          params={"pooled_size": REQUIRED, "spatial_scale": REQUIRED})
def _roi_pooling(params, data, rois):
    """Reference `src/operator/roi_pooling.cc`: max-pool each ROI into a
    fixed (ph, pw) grid.  rois (R, 5): [batch_idx, x1, y1, x2, y2]."""
    ph, pw = (params["pooled_size"] if not isinstance(params["pooled_size"],
                                                      int)
              else (params["pooled_size"],) * 2)
    scale = float(params["spatial_scale"])
    B, C, H, W = data.shape

    ys = jnp.arange(H, dtype=jnp.float32)
    xs = jnp.arange(W, dtype=jnp.float32)

    def one_roi(roi):
        bidx = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * scale)
        y1 = jnp.round(roi[2] * scale)
        x2 = jnp.round(roi[3] * scale)
        y2 = jnp.round(roi[4] * scale)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        bin_w = rw / pw
        bin_h = rh / ph
        img = data[bidx]                              # (C, H, W)

        def pool_bin(iy, ix):
            ys_lo = y1 + iy * bin_h
            ys_hi = y1 + (iy + 1) * bin_h
            xs_lo = x1 + ix * bin_w
            xs_hi = x1 + (ix + 1) * bin_w
            ymask = (ys >= jnp.floor(ys_lo)) & (ys < jnp.ceil(ys_hi))
            xmask = (xs >= jnp.floor(xs_lo)) & (xs < jnp.ceil(xs_hi))
            mask = ymask[:, None] & xmask[None, :]
            masked = jnp.where(mask[None], img, -jnp.inf)
            out = jnp.max(masked, axis=(1, 2))
            return jnp.where(jnp.any(mask), out, 0.0)

        grid = jnp.stack([jnp.stack([pool_bin(iy, ix) for ix in range(pw)],
                                    axis=-1) for iy in range(ph)], axis=-2)
        return grid                                    # (C, ph, pw)

    return jax.vmap(one_roi)(rois)


@register("_contrib_ROIAlign", nin=2,
          params={"pooled_size": REQUIRED, "spatial_scale": REQUIRED,
                  "sample_ratio": -1, "position_sensitive": False})
def _roi_align(params, data, rois):
    """Reference `contrib/roi_align.cc`: bilinear-sampled average pooling."""
    ps = params["pooled_size"]
    ph, pw = (ps, ps) if isinstance(ps, int) else tuple(ps)
    scale = float(params["spatial_scale"])
    B, C, H, W = data.shape

    def bilinear(img, y, x):
        y0 = jnp.clip(jnp.floor(y), 0, H - 1)
        x0 = jnp.clip(jnp.floor(x), 0, W - 1)
        y1 = jnp.clip(y0 + 1, 0, H - 1)
        x1 = jnp.clip(x0 + 1, 0, W - 1)
        wy = y - y0
        wx = x - x0
        y0i, y1i = y0.astype(jnp.int32), y1.astype(jnp.int32)
        x0i, x1i = x0.astype(jnp.int32), x1.astype(jnp.int32)
        v = (img[:, y0i, x0i] * (1 - wy) * (1 - wx) +
             img[:, y1i, x0i] * wy * (1 - wx) +
             img[:, y0i, x1i] * (1 - wy) * wx +
             img[:, y1i, x1i] * wy * wx)
        return v

    def one_roi(roi):
        bidx = roi[0].astype(jnp.int32)
        x1, y1, x2, y2 = roi[1] * scale, roi[2] * scale, roi[3] * scale, \
            roi[4] * scale
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)
        img = data[bidx]
        iy = (jnp.arange(ph) + 0.5) * rh / ph + y1
        ix = (jnp.arange(pw) + 0.5) * rw / pw + x1
        vals = jax.vmap(lambda y: jax.vmap(lambda x: bilinear(img, y, x))(ix))(iy)
        return jnp.moveaxis(vals, -1, 0)               # (C, ph, pw)

    return jax.vmap(one_roi)(rois)


@register("_contrib_bipartite_matching", nin=1, nout=2,
          params={"is_ascend": False, "threshold": REQUIRED, "topk": -1})
def _bipartite_matching(params, dist):
    """Greedy bipartite matching (reference bounding_box.cc)."""
    thresh = float(params["threshold"])
    asc = bool(params["is_ascend"])

    def per_batch(mat):
        n, m = mat.shape
        score = -mat if asc else mat

        def body(carry, _):
            s, row_match, col_match = carry
            idx = jnp.argmax(s)
            i, j = idx // m, idx % m
            ok = s[i, j] > (-thresh if asc else thresh)
            row_match = jnp.where(ok, row_match.at[i].set(j.astype(jnp.float32)),
                                  row_match)
            col_match = jnp.where(ok, col_match.at[j].set(i.astype(jnp.float32)),
                                  col_match)
            s = jnp.where(ok, s.at[i, :].set(-jnp.inf).at[:, j].set(-jnp.inf),
                          jnp.full_like(s, -jnp.inf))
            return (s, row_match, col_match), None

        init = (score, -jnp.ones(n), -jnp.ones(m))
        (_, rm, cm), _ = jax.lax.scan(body, init, None,
                                      length=min(n, m))
        return rm, cm

    if dist.ndim == 2:
        return per_batch(dist)
    rm, cm = jax.vmap(per_batch)(dist)
    return rm, cm
