"""Contrib ops, wave 1 (reference `src/operator/contrib/`).

Detection heads (multibox*, proposal, roi ops) land with the SSD model family;
this module carries the general-purpose contrib ops: quadratic (the tutorial
op, `quadratic_op.cc`), arange_like, interleaved attention matmuls
(`transformer-inl.h`), adaptive pooling, bilinear resize, count_sketch-free
basics, and the index ops used by detection pipelines.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register, REQUIRED


@register("_contrib_quadratic", aliases=("quadratic",),
          params={"a": 0.0, "b": 0.0, "c": 0.0})
def _quadratic(params, x):
    """Reference `contrib/quadratic_op.cc`: a*x^2 + b*x + c."""
    return params["a"] * jnp.square(x) + params["b"] * x + params["c"]


@register("_contrib_arange_like", params={"start": 0.0, "step": 1.0,
                                          "repeat": 1, "axis": None})
def _arange_like(params, x):
    axis = params["axis"]
    repeat = max(int(params["repeat"]), 1)
    if axis is None:
        n = -(-x.size // repeat)
        out = params["start"] + params["step"] * jnp.arange(n, dtype=x.dtype)
        if repeat > 1:
            out = jnp.repeat(out, repeat)[:x.size]
        return out.reshape(x.shape)
    n = x.shape[int(axis)]
    out = params["start"] + params["step"] * jnp.arange(
        -(-n // repeat), dtype=x.dtype)
    if repeat > 1:
        out = jnp.repeat(out, repeat)[:n]
    return out


@register("_contrib_AdaptiveAvgPooling2D", params={"output_size": ()})
def _adaptive_avg_pool(params, x):
    """Reference `contrib/adaptive_avg_pooling.cc`."""
    os = params["output_size"]
    if not os:
        oh = ow = 1
    elif isinstance(os, int):
        oh = ow = int(os)
    else:
        oh, ow = int(os[0]), int(os[1])
    n, c, h, w = x.shape
    if h % oh == 0 and w % ow == 0:
        x2 = x.reshape(n, c, oh, h // oh, ow, w // ow)
        return x2.mean(axis=(3, 5))
    return jax.image.resize(x, (n, c, oh, ow), method="linear")


@register("_contrib_BilinearResize2D",
          params={"height": 1, "width": 1, "scale_height": None,
                  "scale_width": None, "mode": "size"})
def _bilinear_resize(params, x):
    n, c, h, w = x.shape
    if params["scale_height"] is not None:
        oh = int(round(h * float(params["scale_height"])))
        ow = int(round(w * float(params["scale_width"] or params["scale_height"])))
    else:
        oh, ow = int(params["height"]), int(params["width"])
    return jax.image.resize(x, (n, c, oh, ow), method="bilinear")


# -- attention matmuls (reference contrib/transformer-inl.h): interleaved
# qkv projections used by the transformer example.
@register("_contrib_div_sqrt_dim")
def _div_sqrt_dim(params, x):
    return x / jnp.sqrt(jnp.asarray(x.shape[-1], x.dtype))


@register("_contrib_interleaved_matmul_selfatt_qk", nin=1,
          params={"heads": REQUIRED})
def _interleaved_qk(params, qkv):
    """qkv: (L, B, H*3*D) interleaved; returns (B*H, L, L) scores."""
    heads = int(params["heads"])
    L, B, E = qkv.shape
    D = E // heads // 3
    x = qkv.reshape(L, B, heads, 3, D)
    q = x[:, :, :, 0, :].transpose(1, 2, 0, 3).reshape(B * heads, L, D)
    k = x[:, :, :, 1, :].transpose(1, 2, 0, 3).reshape(B * heads, L, D)
    return jnp.matmul(q, k.transpose(0, 2, 1)) / jnp.sqrt(jnp.asarray(D, qkv.dtype))


@register("_contrib_interleaved_matmul_selfatt_valatt", nin=2,
          params={"heads": REQUIRED})
def _interleaved_valatt(params, qkv, att):
    heads = int(params["heads"])
    L, B, E = qkv.shape
    D = E // heads // 3
    x = qkv.reshape(L, B, heads, 3, D)
    v = x[:, :, :, 2, :].transpose(1, 2, 0, 3).reshape(B * heads, L, D)
    out = jnp.matmul(att, v)  # (B*H, L, D)
    return out.reshape(B, heads, L, D).transpose(2, 0, 1, 3).reshape(L, B, heads * D)


@register("_contrib_boolean_mask_supported", nin=0, params={})
def _boolean_mask_supported(params):
    # dynamic-shape boolean_mask is XLA-incompatible; kept as an explicit stub
    return jnp.zeros((1,))


@register("_contrib_index_copy", nin=3)
def _index_copy(params, old, idx, new):
    return old.at[idx.astype("int32")].set(new)


@register("_contrib_index_array", nin=1, params={"axes": None})
def _index_array(params, x):
    axes = params["axes"]
    if axes is None:
        axes = tuple(range(x.ndim))
    elif isinstance(axes, int):
        axes = (axes,)
    grids = jnp.meshgrid(*[jnp.arange(x.shape[a]) for a in axes], indexing="ij")
    return jnp.stack(grids, axis=-1).astype("int64")


@register("_contrib_getnnz", nin=1, params={"axis": None})
def _getnnz(params, x):
    axis = params["axis"]
    nz = (x != 0).astype("int64")
    if axis is None:
        return jnp.sum(nz)
    return jnp.sum(nz, axis=int(axis))
