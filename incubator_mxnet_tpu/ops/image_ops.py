"""On-device input preprocessing ops.

The reference normalizes and lays out images on the HOST inside its C++
iterator (`src/io/iter_normalize.h`, `iter_image_recordio_2.cc` — mean
subtract, std divide, HWC->CHW), then ships fp32 NCHW over PCIe.  On TPU
the right split is the opposite: ship the decoded uint8 HWC bytes (4x
fewer than fp32) and make normalize/cast/layout GRAPH ops — XLA fuses
them into the first convolution, so they cost nothing, and the batch
rides the interconnect at a quarter of the bandwidth.

`ImageNormalize` is the graph-side half of `ImageRecordIter
(device_augment=True)`; the iterator's `normalize_symbol(data)` method
composes the two with its own mean/std.
"""
from __future__ import annotations

import ast

import jax.numpy as jnp

from .registry import register
from ..base import MXNetError


def _floats(v, n):
    if isinstance(v, str):
        v = ast.literal_eval(v)
    if isinstance(v, (int, float)):
        return (float(v),) * n
    out = tuple(float(x) for x in v)
    if len(out) == 1:
        return out * n
    return out


@register("ImageNormalize", nin=1,
          params={"mean": 0.0, "std": 1.0, "input_layout": "NHWC",
                  "output_layout": "NCHW", "dtype": "float32"})
def _image_normalize(params, x):
    """(x - mean) / std with a layout move, as ONE graph node.

    Input: a batch in `input_layout` (typically uint8 NHWC straight from
    the data pipeline).  Output: `dtype` in `output_layout`.  mean/std are
    per-channel tuples (or scalars).  Reference semantics match the
    iterator-side normalization of `src/io/iter_normalize.h:mean_r/g/b`
    + `std_r/g/b`, relocated into the compiled program.
    """
    ilay = str(params.get("input_layout", "NHWC")).upper()
    olay = str(params.get("output_layout", "NCHW")).upper()
    if ilay not in ("NHWC", "NCHW") or olay not in ("NHWC", "NCHW"):
        raise MXNetError("ImageNormalize: layouts must be NHWC or NCHW")
    c = x.shape[-1] if ilay == "NHWC" else x.shape[1]
    mean = jnp.asarray(_floats(params.get("mean", 0.0), c), jnp.float32)
    stdinv = 1.0 / jnp.asarray(_floats(params.get("std", 1.0), c),
                               jnp.float32)
    if ilay == "NHWC":
        shape = (1, 1, 1, c)
    else:
        shape = (1, c, 1, 1)
    out = (x.astype(jnp.float32) - mean.reshape(shape)) \
        * stdinv.reshape(shape)
    if ilay != olay:
        out = out.transpose((0, 3, 1, 2) if olay == "NCHW"
                            else (0, 2, 3, 1))
    return out.astype(jnp.dtype(str(params.get("dtype", "float32"))))
