"""Contrib/tensor op tail (reference `src/operator/contrib/` +
`src/operator/tensor/`): fft/ifft, count_sketch, khatri_rao, histogram,
ravel/unravel, square_sum, cast_storage, sparse_retain, SyncBatchNorm,
DeformableConvolution, DeformablePSROIPooling.

All are single jax-traceable compute functions: XLA generates the TPU
kernels, `jax.vjp` the gradients (the reference hand-writes CUDA forward
+ backward for each)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register, REQUIRED
from ..base import MXNetError


# ---------------------------------------------------------------------------
# FFT family (reference `contrib/fft-inl.h`, `ifft-inl.h`)
# ---------------------------------------------------------------------------

@register("_contrib_fft", aliases=("fft",), params={"compute_size": 128})
def _fft(params, x):
    """reference contrib/fft.cc: 1D FFT over the last axis of a real
    input; output's last dim is 2*d with interleaved (re, im) pairs (the
    cufft complex layout).  `compute_size` is a CUDA sub-batching knob —
    XLA tiles as it sees fit, so it is accepted and ignored."""
    c = jnp.fft.fft(x.astype(jnp.float32))
    out = jnp.stack([jnp.real(c), jnp.imag(c)], axis=-1)
    return out.reshape(*x.shape[:-1], 2 * x.shape[-1]).astype(x.dtype)


@register("_contrib_ifft", aliases=("ifft",), params={"compute_size": 128})
def _ifft(params, x):
    """reference contrib/ifft.cc: UNNORMALIZED inverse FFT (cufft
    CUFFT_INVERSE semantics — the reference never divides by N) of an
    interleaved-complex input (..., 2d); output (..., d) keeps the real
    part."""
    d = x.shape[-1] // 2
    pairs = x.reshape(*x.shape[:-1], d, 2).astype(jnp.float32)
    c = jax.lax.complex(pairs[..., 0], pairs[..., 1])
    return (jnp.real(jnp.fft.ifft(c)) * d).astype(x.dtype)


# ---------------------------------------------------------------------------
# count_sketch / khatri_rao (reference `contrib/count_sketch-inl.h`,
# `contrib/krprod.cc`)
# ---------------------------------------------------------------------------

@register("_contrib_count_sketch", nin=3,
          params={"out_dim": REQUIRED, "processing_batch_size": 32})
def _count_sketch(params, data, h, s):
    """reference contrib/count_sketch.cc: out[:, h[i]] += s[i] * x[:, i]
    (the Count Sketch projection of compact bilinear pooling).  One XLA
    scatter-add instead of the reference's atomic-add CUDA kernel."""
    out_dim = int(params["out_dim"])
    idx = h.reshape(-1).astype(jnp.int32)
    sign = s.reshape(-1).astype(data.dtype)
    vals = data * sign[None, :]
    out = jnp.zeros((data.shape[0], out_dim), data.dtype)
    return out.at[:, idx].add(vals)


@register("khatri_rao", nin=-1, variadic_param="num_args",
          params={"num_args": REQUIRED})
def _khatri_rao(params, *mats):
    """reference contrib/krprod.cc: column-wise Khatri-Rao product —
    inputs (M_i, N) -> (prod M_i, N), column k = kron of the k-th
    columns."""
    if not mats:
        raise MXNetError("khatri_rao needs at least one matrix")
    out = mats[0]
    for m in mats[1:]:
        n = out.shape[-1]
        out = (out[:, None, :] * m[None, :, :]).reshape(-1, n)
    return out


# ---------------------------------------------------------------------------
# histogram / ravel / unravel / square_sum (reference
# `tensor/histogram.cc`, `tensor/ravel.cc`, `tensor/square_sum-inl.h`)
# ---------------------------------------------------------------------------

@register("_histogram", nin=-1, variadic_param="num_args", nout=2,
          aliases=("histogram",),
          params={"num_args": 1, "bin_cnt": None, "range": None})
def _histogram(params, *arrays):
    """reference tensor/histogram.cc: counts + bin edges.  Either uniform
    bins (`bin_cnt` + `range`) over the data, or explicit `bins` as a
    second input."""
    data = arrays[0].reshape(-1)
    bin_cnt = params.get("bin_cnt")
    if bin_cnt is not None:
        lo, hi = params["range"]
        counts, edges = jnp.histogram(
            data.astype(jnp.float32), bins=int(bin_cnt),
            range=(float(lo), float(hi)))
    else:
        if len(arrays) < 2:
            raise MXNetError("_histogram: provide bins input or bin_cnt")
        counts, edges = jnp.histogram(data.astype(jnp.float32),
                                      bins=arrays[1].astype(jnp.float32))
    return counts, edges.astype(arrays[-1].dtype if len(arrays) > 1
                                else jnp.float32)


@register("_ravel_multi_index", aliases=("ravel_multi_index",),
          params={"shape": REQUIRED})
def _ravel_multi_index(params, idx):
    """reference tensor/ravel.cc: (ndim, n) index columns -> (n,) flat."""
    shape = tuple(int(s) for s in params["shape"])
    flat = jnp.zeros(idx.shape[1:], jnp.int64 if idx.dtype == jnp.int64
                     else jnp.int32)
    for d, s in enumerate(shape):
        flat = flat * s + idx[d].astype(flat.dtype)
    return flat.astype(idx.dtype)


@register("_unravel_index", aliases=("unravel_index",),
          params={"shape": REQUIRED})
def _unravel_index(params, flat):
    """reference tensor/ravel.cc: (n,) flat -> (ndim, n) index columns."""
    shape = tuple(int(s) for s in params["shape"])
    rows = []
    rem = flat.astype(jnp.int32)
    for s in reversed(shape):
        rows.append(rem % s)
        rem = rem // s
    return jnp.stack(rows[::-1], axis=0).astype(flat.dtype)


@register("_square_sum", params={"axis": None, "keepdims": False,
                                 "exclude": False})
def _square_sum(params, x):
    """reference tensor/square_sum-inl.h: sum(x*x) over `axis` — the
    row-sparse fast path there is a storage optimization; on TPU the
    dense multiply-reduce is one fused XLA loop either way."""
    axis = params["axis"]
    if axis is not None and not isinstance(axis, (tuple, list)):
        axis = (int(axis),)
    if axis is not None and params.get("exclude"):
        axis = tuple(i for i in range(x.ndim) if i not in
                     tuple(a % x.ndim for a in axis))
    return jnp.sum(jnp.square(x), axis=None if axis is None
                   else tuple(axis), keepdims=bool(params["keepdims"]))


@register("cast_storage", params={"stype": REQUIRED})
def _cast_storage(params, x):
    """reference tensor/cast_storage.cc.  XLA arrays are dense; the
    graph-level op is the identity for every target stype (sparse
    STORAGE lives host-side in ndarray/sparse.py, whose tostype() handles
    the imperative conversions)."""
    if params["stype"] not in ("default", "row_sparse", "csr"):
        raise MXNetError(f"cast_storage: unknown stype {params['stype']}")
    return x


@register("sparse_retain", nin=2)
def _sparse_retain(params, data, indices):
    """reference tensor/sparse_retain.cc: keep the rows listed in
    `indices`, zero the rest (dense semantics of the row_sparse op)."""
    idx = indices.reshape(-1).astype(jnp.int32)
    out = jnp.zeros_like(data)
    return out.at[idx].set(data[idx])


# ---------------------------------------------------------------------------
# SyncBatchNorm (reference `contrib/sync_batch_norm-inl.h`)
# ---------------------------------------------------------------------------

def _bn_nout(params):
    return 3 if params.get("output_mean_var") else 1


@register("_contrib_SyncBatchNorm", nin=3, naux=2, nout=_bn_nout,
          mode_dependent=True, aliases=("SyncBatchNorm",),
          params={"eps": 1e-3, "momentum": 0.9, "fix_gamma": True,
                  "use_global_stats": False, "output_mean_var": False,
                  "ndev": 1, "key": ""},
          input_names=["data", "gamma", "beta", "moving_mean", "moving_var"])
def _sync_batch_norm(params, x, gamma, beta, moving_mean, moving_var):
    """reference contrib/sync_batch_norm-inl.h: BatchNorm whose batch
    statistics span all devices.  The reference synchronizes through a
    host-side key-matched all-reduce across `ndev` workers; here the op
    IS plain BatchNorm math — under SPMD (pjit over a dp-sharded batch)
    the mean/var reductions run over the full logical batch, XLA inserts
    the cross-device all-reduce, and `key`/`ndev` are accepted for API
    compatibility."""
    from .nn import _batch_norm
    sub = {k: params[k] for k in ("eps", "momentum", "fix_gamma",
                                  "use_global_stats", "output_mean_var")}
    sub["axis"] = 1
    sub["_train"] = params.get("_train", False)
    return _batch_norm(sub, x, gamma, beta, moving_mean, moving_var)


# ---------------------------------------------------------------------------
# Deformable ops (reference `contrib/deformable_convolution-inl.h`,
# `contrib/deformable_psroi_pooling-inl.h` — the Deformable ConvNets /
# R-FCN pair).  Both are bilinear-gather + contract formulations: XLA
# lowers the gathers and the MXU does the contraction, replacing the
# reference's hand-written deformable_im2col CUDA kernels.
# ---------------------------------------------------------------------------

def _pair(v, default):
    if not v:
        return (default, default)
    if isinstance(v, int):
        return (int(v), int(v))
    return tuple(int(x) for x in v)


def _bilinear_gather(img, py, px):
    """img (C, H, W); py/px (...) float sample positions.  Zero outside
    [0, H)x[0, W) (the reference's dmcn_im2col_bilinear semantics).
    Returns (C, ...)."""
    H, W = img.shape[-2:]
    y0 = jnp.floor(py)
    x0 = jnp.floor(px)
    out = 0.0
    for dy in (0, 1):
        for dx in (0, 1):
            yi = y0 + dy
            xi = x0 + dx
            w = ((1 - jnp.abs(py - yi)) * (1 - jnp.abs(px - xi)))
            valid = (yi >= 0) & (yi <= H - 1) & (xi >= 0) & (xi <= W - 1)
            yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
            xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
            out = out + img[:, yc, xc] * (w * valid)[None]
    return out


@register("_contrib_DeformableConvolution", nin=-1,
          aliases=("DeformableConvolution",),
          params={"kernel": REQUIRED, "stride": (), "dilate": (), "pad": (),
                  "num_filter": REQUIRED, "num_group": 1,
                  "num_deformable_group": 1, "workspace": 1024,
                  "no_bias": False, "layout": None},
          input_names=lambda p: ["data", "offset", "weight"] +
          ([] if p.get("no_bias") else ["bias"]))
def _deformable_convolution(params, data, offset, weight, *rest):
    """reference contrib/deformable_convolution.cc (Deformable ConvNets
    v1): each kernel tap samples at base + dilation + learned offset via
    bilinear interpolation, then a grouped contraction applies the
    weights."""
    kh, kw = _pair(params["kernel"], 1)
    sh, sw = _pair(params["stride"], 1)
    dh, dw = _pair(params["dilate"], 1)
    ph, pw = _pair(params["pad"], 0)
    F = int(params["num_filter"])
    G = int(params["num_group"])
    DG = int(params["num_deformable_group"])
    N, C, H, W = data.shape
    Ho = (H + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    Wo = (W + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
    K = kh * kw

    # offset channel layout (deformable_im2col): per deformable group a
    # block of 2*K channels, (y_k, x_k) interleaved
    off = offset.reshape(N, DG, K, 2, Ho, Wo)
    kyx = jnp.stack(jnp.meshgrid(jnp.arange(kh) * dh, jnp.arange(kw) * dw,
                                 indexing="ij"), -1).reshape(K, 2)
    base_y = (jnp.arange(Ho) * sh - ph).astype(off.dtype)
    base_x = (jnp.arange(Wo) * sw - pw).astype(off.dtype)
    py = off[:, :, :, 0] + base_y[None, None, None, :, None] + \
        kyx[:, 0].astype(off.dtype)[None, None, :, None, None]
    px = off[:, :, :, 1] + base_x[None, None, None, None, :] + \
        kyx[:, 1].astype(off.dtype)[None, None, :, None, None]

    Cg = C // DG
    data_g = data.reshape(N, DG, Cg, H, W)
    # (N, DG, Cg, K, Ho, Wo)
    cols = jax.vmap(jax.vmap(_bilinear_gather))(data_g, py, px)
    cols = cols.reshape(N, C, K, Ho, Wo)

    w_g = weight.reshape(G, F // G, C // G, K)
    cols_g = cols.reshape(N, G, C // G, K, Ho, Wo)
    out = jnp.einsum("ngckhw,gfck->ngfhw", cols_g, w_g,
                     preferred_element_type=jnp.float32)
    out = out.reshape(N, F, Ho, Wo).astype(data.dtype)
    if rest and not params.get("no_bias"):
        out = out + rest[0][None, :, None, None]
    return out


@register("_contrib_DeformablePSROIPooling", nin=-1, nout=2,
          aliases=("DeformablePSROIPooling",),
          params={"spatial_scale": REQUIRED, "output_dim": REQUIRED,
                  "group_size": REQUIRED, "pooled_size": REQUIRED,
                  "part_size": 0, "sample_per_part": 1, "trans_std": 0.0,
                  "no_trans": False},
          input_names=lambda p: ["data", "rois"] +
          ([] if p.get("no_trans") else ["trans"]))
def _deformable_psroi_pooling(params, data, rois, *rest):
    """reference contrib/deformable_psroi_pooling.cc (R-FCN deformable
    head): position-sensitive ROI pooling whose bins shift by learned,
    roi-normalized offsets.  Outputs (output, top_count) like the
    reference (top_count = valid samples per bin)."""
    scale = float(params["spatial_scale"])
    od = int(params["output_dim"])
    gs = int(params["group_size"])
    ps = int(params["pooled_size"])
    part = int(params["part_size"]) or ps
    spp = int(params["sample_per_part"])
    tstd = float(params["trans_std"])
    no_trans = bool(params["no_trans"]) or not rest
    trans = None if no_trans else rest[0]
    N, C, H, W = data.shape

    # channel map c(ctop, ph, pw) = (ctop*gs + gh)*gs + gw
    phs = jnp.arange(ps)
    gh = jnp.clip(jnp.floor(phs * gs / ps), 0, gs - 1).astype(jnp.int32)
    gw = gh
    c_idx = (jnp.arange(od)[:, None, None] * gs + gh[None, :, None]) * gs \
        + gw[None, None, :]                       # (od, ps, ps)
    part_h = jnp.clip(jnp.floor(phs * part / ps), 0, part - 1).astype(
        jnp.int32)

    if trans is not None:
        num_classes = trans.shape[1] // 2
        cls_of = (jnp.arange(od) // max(od // num_classes, 1)).astype(
            jnp.int32)

    def per_roi(roi, tr):
        b = roi[0].astype(jnp.int32)
        start_w = jnp.round(roi[1]) * scale - 0.5
        start_h = jnp.round(roi[2]) * scale - 0.5
        end_w = (jnp.round(roi[3]) + 1.0) * scale - 0.5
        end_h = (jnp.round(roi[4]) + 1.0) * scale - 0.5
        roi_w = jnp.maximum(end_w - start_w, 0.1)
        roi_h = jnp.maximum(end_h - start_h, 0.1)
        bin_h = roi_h / ps
        bin_w = roi_w / ps
        sub_h = bin_h / spp
        sub_w = bin_w / spp
        if trans is not None:
            # trans (2*num_classes, part, part): channel 2c = x, 2c+1 = y
            tx = tr[cls_of * 2][:, part_h][:, :, part_h] * tstd   # (od,ps,ps)
            ty = tr[cls_of * 2 + 1][:, part_h][:, :, part_h] * tstd
        else:
            tx = ty = jnp.zeros((od, ps, ps), data.dtype)
        hstart = start_h + phs.astype(data.dtype)[None, :, None] * bin_h \
            + ty * roi_h                                        # (od,ps,ps)
        wstart = start_w + phs.astype(data.dtype)[None, None, :] * bin_w \
            + tx * roi_w
        # reference kernel (deformable_psroi_pooling.cu:144) samples at
        # wstart + i*sub_bin_size — NO half-bin offset; adding one shifts
        # every sample half a sub-bin and diverges from reference-trained
        # Deformable R-FCN checkpoints
        iy = jnp.arange(spp) * sub_h                             # (spp,)
        ix = jnp.arange(spp) * sub_w
        hh = hstart[..., None, None] + iy[:, None]               # od,ps,ps,spp,1
        ww = wstart[..., None, None] + ix[None, :]
        hh, ww = jnp.broadcast_arrays(hh, ww)                    # od,ps,ps,spp,spp
        # reference skips only when h < -0.5 or h > H-0.5: the bounds are
        # INCLUSIVE (a sample exactly at -0.5 counts), which matters now
        # that the grid starts at hstart itself
        valid = (hh >= -0.5) & (hh <= H - 0.5) & \
            (ww >= -0.5) & (ww <= W - 0.5)
        hc = jnp.clip(hh, 0, H - 1)
        wc = jnp.clip(ww, 0, W - 1)
        img = data[b]                                            # (C,H,W)
        # bilinear-gather per (od,ps,ps,spp,spp) from the mapped channel
        cc = jnp.broadcast_to(c_idx[..., None, None], hh.shape)
        y0 = jnp.floor(hc)
        x0 = jnp.floor(wc)
        acc = 0.0
        for dy in (0, 1):
            for dx in (0, 1):
                yi = jnp.clip(y0 + dy, 0, H - 1).astype(jnp.int32)
                xi = jnp.clip(x0 + dx, 0, W - 1).astype(jnp.int32)
                wgt = (1 - jnp.abs(hc - (y0 + dy))) * \
                    (1 - jnp.abs(wc - (x0 + dx)))
                acc = acc + img[cc, yi, xi] * wgt
        acc = jnp.where(valid, acc, 0.0)
        count = valid.sum((-1, -2)).astype(data.dtype)
        total = acc.sum((-1, -2))
        out = jnp.where(count > 0, total / jnp.maximum(count, 1), 0.0)
        return out.astype(data.dtype), count

    if trans is not None:
        outs, counts = jax.vmap(per_roi)(rois, trans)
    else:
        outs, counts = jax.vmap(lambda r: per_roi(r, None))(rois)
    return outs, counts
