"""Loss-head output ops with implicit gradients.

Reference: `src/operator/softmax_output.cc` (SoftmaxOutput — the classic
classification head whose *backward ignores the incoming gradient* and emits
softmax-minus-onehot), `regression_output.cc` (Linear/Logistic/MAE regression
outputs), `make_loss.cc`, `svm_output.cc`.  These require custom vjps — they
are the reference ops whose FGradient is NOT the autodiff of their forward.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register
from ..base import MXNetError

_SOFTMAX_OUT_PARAMS = {
    "grad_scale": 1.0, "ignore_label": -1.0, "multi_output": False,
    "use_ignore": False, "preserve_shape": False, "normalization": "null",
    "out_grad": False, "smooth_alpha": 0.0,
}


@register("SoftmaxOutput", nin=2, params=dict(_SOFTMAX_OUT_PARAMS),
          aliases=("Softmax",), input_names=["data", "label"])
def _softmax_output(params, data, label):
    """Forward = softmax; backward = (softmax - onehot(label)) * grad_scale,
    with ignore-label masking and normalization (reference
    `softmax_output-inl.h` SoftmaxOutputBackward)."""
    multi = bool(params["multi_output"])
    preserve = bool(params["preserve_shape"])
    axis = 1 if multi else -1
    gs = float(params["grad_scale"])
    ignore = float(params["ignore_label"])
    use_ignore = bool(params["use_ignore"])
    normalization = params["normalization"]
    smooth = float(params["smooth_alpha"])

    orig_shape = data.shape
    flattened = False
    if not multi and not preserve and data.ndim > 2:
        # reference default mode flattens trailing dims into one class axis:
        # data is treated as (batch, prod(rest)) (softmax_output-inl.h)
        data = data.reshape(orig_shape[0], -1)
        label = label.reshape(orig_shape[0])
        flattened = True

    # softmax and its (softmax - onehot) gradient run in fp32 even for
    # bf16 activations: exp/sum in 8-bit mantissa loses real accuracy and
    # costs nothing to avoid (the matmuls stay bf16 on the MXU)
    in_dtype = data.dtype

    @jax.custom_vjp
    def f(d, l):
        return jax.nn.softmax(d.astype(jnp.float32), axis=axis) \
            .astype(in_dtype)

    def fwd(d, l):
        out = jax.nn.softmax(d.astype(jnp.float32), axis=axis)
        return out.astype(in_dtype), (out, l)

    def bwd(res, g):
        out, l = res
        k = out.shape[axis]
        li = l.astype("int32")
        onehot = jax.nn.one_hot(li, k, dtype=out.dtype, axis=axis)
        if smooth > 0:
            onehot = onehot * (1 - smooth) + smooth / (k - 1) * (1 - onehot)
        grad = out - onehot
        if use_ignore:
            mask = (l != ignore)
            mshape = list(l.shape)
            mask_b = jnp.expand_dims(mask, axis if axis != -1 else l.ndim)
            grad = grad * mask_b.astype(out.dtype)
        scale = gs
        if normalization == "batch":
            grad = grad / out.shape[0]
        elif normalization == "valid":
            if use_ignore:
                valid = jnp.maximum(jnp.sum((l != ignore).astype(out.dtype)), 1.0)
            else:
                valid = float(l.size)
            grad = grad / valid
        grad = grad * scale
        if params["out_grad"]:
            grad = grad * g.astype(out.dtype)
        return grad.astype(in_dtype), jnp.zeros_like(l)

    f.defvjp(fwd, bwd)
    out = f(data, label)
    if flattened:
        out = out.reshape(orig_shape)
    return out


def _regression(link, grad_fn):
    def fn(params, data, label):
        gs = float(params["grad_scale"])

        @jax.custom_vjp
        def f(d, l):
            return link(d)

        def fwd(d, l):
            out = link(d)
            return out, (out, l)

        def bwd(res, g):
            out, l = res
            # reference scales by grad_scale / num_output (regression_output-inl.h)
            num_out = max(out.size // out.shape[0], 1)
            grad = grad_fn(out, l.reshape(out.shape)) * (gs / num_out)
            return grad.astype(out.dtype), jnp.zeros_like(l)

        f.defvjp(fwd, bwd)
        return f(data, label)
    return fn


# reference regression_output-inl.h: grad = (pred - label) (linear/logistic),
# sign(pred - label) for MAE; scaled by grad_scale / num_output.
register("LinearRegressionOutput", nin=2, params={"grad_scale": 1.0},
         input_names=["data", "label"])(
    _regression(lambda d: d, lambda o, l: (o - l)))
register("LogisticRegressionOutput", nin=2, params={"grad_scale": 1.0},
         input_names=["data", "label"])(
    _regression(jax.nn.sigmoid, lambda o, l: (o - l)))
register("MAERegressionOutput", nin=2, params={"grad_scale": 1.0},
         input_names=["data", "label"])(
    _regression(lambda d: d, lambda o, l: jnp.sign(o - l)))


@register("MakeLoss", nin=1,
          params={"grad_scale": 1.0, "valid_thresh": 0.0, "normalization": "null"})
def _make_loss_op(params, data):
    """Reference `make_loss.cc`: forward identity, backward = grad_scale
    (ignores incoming gradient; optional valid normalization)."""
    gs = float(params["grad_scale"])
    normalization = params["normalization"]
    thresh = float(params["valid_thresh"])

    @jax.custom_vjp
    def f(d):
        return d

    def fwd(d):
        return d, (d,)

    def bwd(res, g):
        (d,) = res
        grad = jnp.full_like(d, gs)
        if normalization == "batch":
            grad = grad / d.shape[0]
        elif normalization == "valid":
            valid = jnp.maximum(jnp.sum((d > thresh).astype(d.dtype)), 1.0)
            grad = grad / valid
        return (grad,)

    f.defvjp(fwd, bwd)
    return f(data)


@register("SVMOutput", nin=2,
          params={"margin": 1.0, "regularization_coefficient": 1.0,
                  "use_linear": False}, input_names=["data", "label"])
def _svm_output(params, data, label):
    """Reference `svm_output.cc`: forward identity; backward hinge-loss grad."""
    margin = float(params["margin"])
    reg = float(params["regularization_coefficient"])
    linear = bool(params["use_linear"])

    @jax.custom_vjp
    def f(d, l):
        return d

    def fwd(d, l):
        return d, (d, l)

    def bwd(res, g):
        d, l = res
        k = d.shape[1]
        onehot = jax.nn.one_hot(l.astype("int32"), k, dtype=d.dtype)
        target = 2 * onehot - 1  # +1 for true class, -1 otherwise
        viol = (margin - target * d) > 0
        if linear:
            grad = jnp.where(viol, -target * reg, 0.0)
        else:
            grad = jnp.where(viol, -2 * (margin - target * d) * target * reg, 0.0)
        return grad.astype(d.dtype), jnp.zeros_like(l)

    f.defvjp(fwd, bwd)
    return f(data, label)


@register("IdentityAttachKLSparseReg", nin=1,
          params={"sparseness_target": 0.1, "penalty": 0.001, "momentum": 0.9})
def _identity_kl(params, data):
    return data + 0
