"""Core neural-network operators.

Reference: `src/operator/nn/` (fully_connected.cc, convolution.cc,
deconvolution.cc, batch_norm.cc, layer_norm.cc, pooling.cc, softmax.cc,
activation.cc, dropout.cc, lrn.cc, upsampling.cc) and legacy top-level ops
(`leaky_relu.cc`, `instance_norm.cc`, `l2_normalization.cc`, `rnn.cc`).

TPU mapping: FullyConnected/Convolution lower to single MXU matmul/conv HLOs;
BatchNorm & friends are elementwise chains XLA fuses around them; the fused
RNN op (reference cudnn_rnn-inl.h) is a `lax.scan` over time steps whose body
is one fused XLA computation — the TPU-native analogue of cuDNN's fused
multi-layer kernel.  All data layouts follow the reference (NCHW / TNC); XLA's
layout assignment maps them onto TPU-friendly tilings internally.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register, REQUIRED
from ..base import MXNetError


# ---------------------------------------------------------------------------
# FullyConnected (reference src/operator/nn/fully_connected.cc:239-328)
# ---------------------------------------------------------------------------

@register("FullyConnected", nin=-1,
          params={"num_hidden": REQUIRED, "no_bias": False, "flatten": True},
          input_names=lambda p: ["data", "weight"] + ([] if p.get("no_bias") else ["bias"]))
def _fully_connected(params, x, weight, *rest):
    weight = weight.astype(x.dtype)  # mixed-precision: params may be fp32
    if params["flatten"]:
        x2 = x.reshape(x.shape[0], -1)
        out = jnp.dot(x2, weight.T)
    else:
        out = jnp.dot(x, weight.T)
    if not params["no_bias"]:
        bias = rest[0].astype(out.dtype)
        out = out + bias
    return out


# ---------------------------------------------------------------------------
# Convolution / Deconvolution (reference convolution.cc, deconvolution.cc)
# ---------------------------------------------------------------------------

def _conv_dims(kernel):
    nd = len(kernel)
    if nd == 1:
        return ("NCH", "OIH", "NCH")
    if nd == 2:
        return ("NCHW", "OIHW", "NCHW")
    if nd == 3:
        return ("NCDHW", "OIDHW", "NCDHW")
    raise MXNetError("Convolution supports 1D/2D/3D kernels")


def _tup(v, n, default):
    if not v:
        return (default,) * n
    if isinstance(v, int):
        return (v,) * n
    return tuple(int(x) for x in v)


_CONV_PARAMS = {
    "kernel": REQUIRED, "stride": (), "dilate": (), "pad": (),
    "num_filter": REQUIRED, "num_group": 1, "no_bias": False,
    "workspace": 1024, "cudnn_tune": None, "cudnn_off": False, "layout": None,
}


@register("Convolution", nin=-1, params=dict(_CONV_PARAMS),
          input_names=lambda p: ["data", "weight"] + ([] if p.get("no_bias") else ["bias"]))
def _convolution(params, x, weight, *rest):
    kernel = tuple(params["kernel"])
    nd = len(kernel)
    stride = _tup(params["stride"], nd, 1)
    dilate = _tup(params["dilate"], nd, 1)
    pad = _tup(params["pad"], nd, 0)
    dn = jax.lax.conv_dimension_numbers(x.shape, weight.shape, _conv_dims(kernel))
    out = jax.lax.conv_general_dilated(
        x, weight.astype(x.dtype), window_strides=stride,
        padding=[(p, p) for p in pad],
        lhs_dilation=(1,) * nd, rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=int(params["num_group"]),
        preferred_element_type=None)
    if not params["no_bias"]:
        bias = rest[0].astype(out.dtype)
        out = out + bias.reshape((1, -1) + (1,) * nd)
    return out


_DECONV_PARAMS = dict(_CONV_PARAMS)
_DECONV_PARAMS.update({"adj": (), "target_shape": ()})


@register("Deconvolution", nin=-1, params=_DECONV_PARAMS,
          input_names=lambda p: ["data", "weight"] + ([] if p.get("no_bias") else ["bias"]))
def _deconvolution(params, x, weight, *rest):
    """Transposed convolution = gradient of Convolution w.r.t. its input
    (reference deconvolution-inl.h).  weight layout: (Cin, Cout/g, *kernel)."""
    kernel = tuple(params["kernel"])
    nd = len(kernel)
    stride = _tup(params["stride"], nd, 1)
    dilate = _tup(params["dilate"], nd, 1)
    pad = _tup(params["pad"], nd, 0)
    adj = _tup(params["adj"], nd, 0)
    groups = int(params["num_group"])
    if params["target_shape"]:
        tgt = _tup(params["target_shape"], nd, 0)
        adj = tuple(
            tgt[i] - ((x.shape[2 + i] - 1) * stride[i] + (
                (kernel[i] - 1) * dilate[i] + 1) - 2 * pad[i])
            for i in range(nd))
    # flip kernel spatially; swap I/O axes per group
    w = jnp.flip(weight, axis=tuple(range(2, 2 + nd)))
    cin, cog = w.shape[0], w.shape[1]
    w = w.reshape((groups, cin // groups, cog) + kernel)
    w = jnp.swapaxes(w, 1, 2)  # (g, cog, cin/g, *k)
    w = w.reshape((groups * cog, cin // groups) + kernel)
    dn = jax.lax.conv_dimension_numbers(x.shape, w.shape, _conv_dims(kernel))
    eff_k = tuple((kernel[i] - 1) * dilate[i] + 1 for i in range(nd))
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=(1,) * nd,
        padding=[(eff_k[i] - 1 - pad[i], eff_k[i] - 1 - pad[i] + adj[i])
                 for i in range(nd)],
        lhs_dilation=stride, rhs_dilation=dilate,
        dimension_numbers=dn, feature_group_count=groups)
    if not params["no_bias"]:
        out = out + rest[0].reshape((1, -1) + (1,) * nd)
    return out


# ---------------------------------------------------------------------------
# Pooling (reference pooling.cc + pool.h)
# ---------------------------------------------------------------------------

@register("Pooling", aliases=("Pooling_v1",),
          params={"kernel": (), "pool_type": "max", "global_pool": False,
                  "cudnn_off": False, "pooling_convention": "valid",
                  "stride": (), "pad": (), "count_include_pad": True})
def _pooling(params, x):
    nd = x.ndim - 2
    if params["global_pool"]:
        axes = tuple(range(2, 2 + nd))
        if params["pool_type"] == "max":
            out = jnp.max(x, axis=axes, keepdims=True)
        elif params["pool_type"] in ("avg", "sum"):
            red = jnp.sum if params["pool_type"] == "sum" else jnp.mean
            out = red(x, axis=axes, keepdims=True)
        else:
            raise MXNetError("bad pool_type")
        return out
    kernel = _tup(params["kernel"], nd, 1)
    stride = _tup(params["stride"], nd, 1)
    pad = _tup(params["pad"], nd, 0)
    ceil_mode = params["pooling_convention"] == "full"

    pads = []
    for i in range(nd):
        lo = pad[i]
        hi = pad[i]
        if ceil_mode:
            size = x.shape[2 + i] + 2 * pad[i]
            rem = (size - kernel[i]) % stride[i]
            if rem != 0:
                hi += stride[i] - rem
        pads.append((lo, hi))

    window = (1, 1) + kernel
    strides = (1, 1) + stride
    full_pads = [(0, 0), (0, 0)] + pads
    ptype = params["pool_type"]
    # NOTE: init values must be python/np scalars so jax recognizes the
    # max/add monoids and uses the differentiable reduce_window primitives
    if ptype == "max":
        if jnp.issubdtype(x.dtype, jnp.floating):
            init = np.array(-np.inf, x.dtype)[()]
        else:
            init = np.array(np.iinfo(np.dtype(x.dtype)).min, x.dtype)[()]
        return jax.lax.reduce_window(x, init, jax.lax.max,
                                     window, strides, full_pads)
    if ptype in ("avg", "sum"):
        s = jax.lax.reduce_window(x, np.zeros((), x.dtype)[()], jax.lax.add,
                                  window, strides, full_pads)
        if ptype == "sum":
            return s
        if params["count_include_pad"]:
            denom = 1
            for k in kernel:
                denom *= k
            return s / jnp.asarray(denom, x.dtype)
        ones = jnp.ones_like(x)
        cnt = jax.lax.reduce_window(ones, jnp.asarray(0, x.dtype), jax.lax.add,
                                    window, strides, full_pads)
        return s / jnp.maximum(cnt, 1)
    raise MXNetError(f"Pooling: bad pool_type {ptype}")


# ---------------------------------------------------------------------------
# Normalization ops
# ---------------------------------------------------------------------------

def _bn_nout(params):
    return 3 if params.get("output_mean_var") else 1


def _bn_axis_bound(name):
    """True when the named mesh axis is bound in the current trace (a
    `shard_map`/pmap region): probing with a zero-size psum either
    traces fine or raises NameError — never dispatches real work."""
    try:
        jax.lax.psum(jnp.zeros(()), name)
        return True
    except NameError:
        return False


@register("BatchNorm", nin=3, naux=2, nout=_bn_nout, mode_dependent=True,
          params={"eps": 1e-3, "momentum": 0.9, "fix_gamma": True,
                  "use_global_stats": False, "output_mean_var": False,
                  "axis": 1, "cudnn_off": False, "sync": False,
                  "sync_axis": "dp"},
          aliases=("BatchNorm_v1",),
          input_names=["data", "gamma", "beta", "moving_mean", "moving_var"])
def _batch_norm(params, x, gamma, beta, moving_mean, moving_var):
    """Reference `src/operator/nn/batch_norm.cc`.  Aux states
    (moving_mean/var) are inputs 4-5 and returned as updates in train mode.

    ``sync=True`` asks for GLOBAL-batch statistics (the reference's
    `sync_batch_norm-inl.h` distributed BatchNorm, per the MLPerf-pods
    recipe): inside an explicit SPMD region (`shard_map` over a mesh
    with the ``sync_axis`` axis bound — `parallel.data_parallel_step`,
    `zero_train_step`) the moments psum over that axis.  Inside the
    fused train step the whole program is GLOBAL-view (the batch is
    merely sharded over dp), so the plain reductions already ARE
    global-batch statistics and ``sync`` adds nothing — sync-BN is the
    fused path's default semantics."""
    axis = int(params["axis"]) % x.ndim
    eps = float(params["eps"])
    momentum = float(params["momentum"])
    train = params.get("_train", False) and not params["use_global_stats"]
    sync = bool(params.get("sync", False))
    sync_axis = str(params.get("sync_axis", "dp"))

    if params["fix_gamma"]:
        gamma = jnp.ones_like(gamma)

    red_axes = tuple(i for i in range(x.ndim) if i != axis)
    bshape = [1] * x.ndim
    bshape[axis] = x.shape[axis]

    # statistics in float32 even for low-precision activations (matches the
    # reference's cuDNN path which accumulates in fp32)
    xs = x.astype(jnp.float32) if x.dtype != jnp.float32 else x
    if train:
        mean = jnp.mean(xs, axis=red_axes)
        if sync and _bn_axis_bound(sync_axis):
            # distributed BN: psum of moments over the dp axis — with
            # equal per-device batches, pmean of local moments around
            # the GLOBAL mean is exactly the big-batch statistics
            mean = jax.lax.pmean(mean, sync_axis)
            var = jnp.mean(jnp.square(xs - mean.reshape(bshape)),
                           axis=red_axes)
            var = jax.lax.pmean(var, sync_axis)
        else:
            var = jnp.mean(jnp.square(xs - mean.reshape(bshape)),
                           axis=red_axes)
    else:
        mean, var = moving_mean, moving_var

    inv = jax.lax.rsqrt(var + eps).reshape(bshape)
    out = (xs - mean.reshape(bshape)) * inv * gamma.reshape(bshape) \
        + beta.reshape(bshape)
    out = out.astype(x.dtype)

    outs = (out,)
    if params["output_mean_var"]:
        outs = (out, mean, jax.lax.rsqrt(var + eps))
    if params.get("_train", False):
        new_mean = moving_mean * momentum + mean * (1 - momentum)
        new_var = moving_var * momentum + var * (1 - momentum)
        return outs + (new_mean, new_var)
    return outs if len(outs) > 1 else out


def _ln_nout(params):
    return 3 if params.get("output_mean_var") else 1


@register("LayerNorm", nin=3, nout=_ln_nout,
          params={"axis": -1, "eps": 1e-5, "output_mean_var": False},
          input_names=["data", "gamma", "beta"])
def _layer_norm(params, x, gamma, beta):
    """Reference `src/operator/nn/layer_norm.cc`."""
    axis = int(params["axis"]) % x.ndim
    eps = float(params["eps"])
    mean = jnp.mean(x, axis=axis, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=axis, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    bshape = [1] * x.ndim
    bshape[axis] = x.shape[axis]
    out = (x - mean) * inv * gamma.reshape(bshape) + beta.reshape(bshape)
    if params["output_mean_var"]:
        return out, jnp.squeeze(mean, axis), jnp.squeeze(inv, axis)
    return out


@register("InstanceNorm", nin=3, params={"eps": 1e-3},
          input_names=["data", "gamma", "beta"])
def _instance_norm(params, x, gamma, beta):
    """Reference `src/operator/instance_norm.cc`: normalize over spatial dims
    per (n, c)."""
    eps = float(params["eps"])
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=axes, keepdims=True)
    bshape = (1, -1) + (1,) * (x.ndim - 2)
    return (x - mean) * jax.lax.rsqrt(var + eps) * gamma.reshape(bshape) \
        + beta.reshape(bshape)


@register("L2Normalization", params={"eps": 1e-10, "mode": "instance"})
def _l2_normalization(params, x):
    """Reference `src/operator/l2_normalization.cc`."""
    eps = float(params["eps"])
    mode = params["mode"]
    if mode == "instance":
        axes = tuple(range(1, x.ndim))
        norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=True) + eps)
    elif mode == "channel":
        norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=1, keepdims=True) + eps)
    elif mode == "spatial":
        axes = tuple(range(2, x.ndim))
        norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=True) + eps)
    else:
        raise MXNetError("bad L2Normalization mode")
    return x / norm


@register("LRN", params={"alpha": 1e-4, "beta": 0.75, "knorm": 2.0, "nsize": REQUIRED})
def _lrn(params, x):
    """Local response norm across channels (reference `src/operator/nn/lrn.cc`)."""
    n = int(params["nsize"])
    alpha, beta, k = float(params["alpha"]), float(params["beta"]), float(params["knorm"])
    sq = jnp.square(x)
    half = n // 2
    pad = [(0, 0), (half, half)] + [(0, 0)] * (x.ndim - 2)
    sq_p = jnp.pad(sq, pad)
    acc = jnp.zeros_like(x)
    for i in range(n):
        acc = acc + jax.lax.dynamic_slice_in_dim(sq_p, i, x.shape[1], axis=1)
    return x * jnp.power(k + (alpha / n) * acc, -beta)


# ---------------------------------------------------------------------------
# Activations
# ---------------------------------------------------------------------------

@register("Activation", params={"act_type": REQUIRED})
def _activation(params, x):
    t = params["act_type"]
    if t == "relu":
        return jax.nn.relu(x)
    if t == "sigmoid":
        return jax.nn.sigmoid(x)
    if t == "tanh":
        return jnp.tanh(x)
    if t == "softrelu":
        return jax.nn.softplus(x)
    if t == "softsign":
        return jax.nn.soft_sign(x)
    raise MXNetError(f"Activation: unknown act_type {t}")


@register("LeakyReLU", nin=-1,
          params={"act_type": "leaky", "slope": 0.25, "lower_bound": 0.125,
                  "upper_bound": 0.334},
          input_names=lambda p: ["data"] + (["gamma"] if p.get("act_type") == "prelu" else []))
def _leaky_relu(params, x, *rest):
    """Reference `src/operator/leaky_relu.cc` (leaky/prelu/elu/selu/gelu/rrelu)."""
    t = params["act_type"]
    if t == "leaky":
        return jnp.where(x > 0, x, x * params["slope"])
    if t == "prelu":
        gamma = rest[0]
        bshape = [1] * x.ndim
        if gamma.ndim == 1 and x.ndim > 1:
            bshape[1] = gamma.shape[0] if gamma.shape[0] > 1 else 1
            gamma = gamma.reshape(bshape)
        return jnp.where(x > 0, x, x * gamma)
    if t == "elu":
        return jnp.where(x > 0, x, params["slope"] * jnp.expm1(x))
    if t == "selu":
        alpha, scale = 1.6732632423543772, 1.0507009873554805
        return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))
    if t == "gelu":
        return jax.nn.gelu(x, approximate=False)
    if t == "rrelu":
        # inference behavior (mean slope); train-time random slope documented
        slope = (params["lower_bound"] + params["upper_bound"]) / 2
        return jnp.where(x > 0, x, x * slope)
    raise MXNetError(f"LeakyReLU: unknown act_type {t}")


@register("softmax", params={"axis": -1, "temperature": None, "dtype": None})
def _softmax(params, x):
    t = params["temperature"]
    if t:
        x = x / t
    out = jax.nn.softmax(x, axis=int(params["axis"]))
    if params["dtype"]:
        out = out.astype(params["dtype"])
    return out


@register("log_softmax", params={"axis": -1, "temperature": None, "dtype": None})
def _log_softmax(params, x):
    t = params["temperature"]
    if t:
        x = x / t
    out = jax.nn.log_softmax(x, axis=int(params["axis"]))
    if params["dtype"]:
        out = out.astype(params["dtype"])
    return out


@register("softmin", params={"axis": -1, "temperature": None, "dtype": None})
def _softmin(params, x):
    t = params["temperature"]
    if t:
        x = x / t
    return jax.nn.softmax(-x, axis=int(params["axis"]))


@register("SoftmaxActivation", params={"mode": "instance"})
def _softmax_activation(params, x):
    if params["mode"] == "channel":
        return jax.nn.softmax(x, axis=1)
    return jax.nn.softmax(x.reshape(x.shape[0], -1), axis=-1).reshape(x.shape)


@register("Dropout", needs_rng=True, mode_dependent=True,
          params={"p": 0.5, "mode": "training", "axes": ()})
def _dropout(params, x, key):
    """Reference `src/operator/nn/dropout.cc`: inverted dropout."""
    p = float(params["p"])
    train = params.get("_train", False) or params["mode"] == "always"
    if not train or p <= 0:
        return x + 0
    axes = params["axes"]
    shape = list(x.shape)
    if axes:
        for i in range(len(shape)):
            if i not in axes:
                shape[i] = 1
    keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
    return jnp.where(keep, x / (1.0 - p), jnp.zeros_like(x))


# ---------------------------------------------------------------------------
# Fused RNN (reference src/operator/rnn.cc + cudnn_rnn-inl.h): multi-layer,
# optionally bidirectional vanilla/LSTM/GRU over (T, B, I) inputs with
# cuDNN-compatible flat parameter packing.  TPU-native: lax.scan time loop.
# ---------------------------------------------------------------------------

def _gates(mode):
    return {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]


def rnn_param_size(mode, input_size, state_size, num_layers, bidirectional):
    """Total flat parameter count (matches cudnn packing; reference rnn-inl.h
    GetParamSize)."""
    g = _gates(mode)
    d = 2 if bidirectional else 1
    size = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else state_size * d
        size += d * g * state_size * (in_sz + state_size)  # Wx + Wh
    size += num_layers * d * g * state_size * 2  # bx + bh
    return size


def _unpack_rnn_params(flat, mode, input_size, state_size, num_layers, bidir):
    """Slice the flat cuDNN-layout parameter vector into per-layer weights.

    Layout (reference cudnn GetParams / rnn_impl.h): all weight matrices
    (layer-major, direction-minor, Wx then Wh), then all biases (same order,
    bx then bh)."""
    g = _gates(mode)
    d = 2 if bidir else 1
    ws = []
    off = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else state_size * d
        dirs = []
        for _dir in range(d):
            wx = flat[off: off + g * state_size * in_sz].reshape(g * state_size, in_sz)
            off += g * state_size * in_sz
            wh = flat[off: off + g * state_size * state_size].reshape(g * state_size, state_size)
            off += g * state_size * state_size
            dirs.append([wx, wh])
        ws.append(dirs)
    bs = []
    for layer in range(num_layers):
        dirs = []
        for _dir in range(d):
            bx = flat[off: off + g * state_size]; off += g * state_size
            bh = flat[off: off + g * state_size]; off += g * state_size
            dirs.append([bx, bh])
        bs.append(dirs)
    return ws, bs


def _cell_step(mode, state_size):
    if mode == "lstm":
        def step(carry, xw, wh, bh):
            h, c = carry
            gates = xw + jnp.dot(h, wh.T) + bh
            i, f, gg, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            gg = jnp.tanh(gg)
            c2 = f * c + i * gg
            h2 = o * jnp.tanh(c2)
            return (h2, c2), h2
    elif mode == "gru":
        def step(carry, xw, wh, bh):
            (h,) = carry
            xr, xz, xn = jnp.split(xw, 3, axis=-1)
            hr, hz, hn = jnp.split(jnp.dot(h, wh.T) + bh, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            h2 = (1 - z) * n + z * h
            return (h2,), h2
    else:
        act = jax.nn.relu if mode == "rnn_relu" else jnp.tanh
        def step(carry, xw, wh, bh):
            (h,) = carry
            h2 = act(xw + jnp.dot(h, wh.T) + bh)
            return (h2,), h2
    return step


def _rnn_nout(params):
    if not params.get("state_outputs"):
        return 1
    return 3 if params.get("mode") == "lstm" else 2


@register("RNN", nin=-1, nout=_rnn_nout, mode_dependent=True, needs_rng=True,
          input_names=lambda p: ["data", "parameters", "state"] + (
              ["state_cell"] if p.get("mode") == "lstm" else []),
          params={"state_size": REQUIRED, "num_layers": REQUIRED,
                  "bidirectional": False, "mode": REQUIRED, "p": 0.0,
                  "state_outputs": False, "projection_size": None,
                  "lstm_state_clip_min": None, "lstm_state_clip_max": None,
                  "lstm_state_clip_nan": False})
def _rnn(params, *args):
    """Fused multi-layer RNN.  Inputs: data (T,B,I), params (flat), state
    (L*D,B,H) [, state_cell for lstm]; trailing key from the RNG chain."""
    mode = params["mode"]
    key = args[-1]
    args = args[:-1]
    data, flat, state0 = args[0], args[1], args[2]
    cell0 = args[3] if mode == "lstm" and len(args) > 3 else None
    L = int(params["num_layers"])
    H = int(params["state_size"])
    bidir = bool(params["bidirectional"])
    d = 2 if bidir else 1
    T, B, I = data.shape
    dropout_p = float(params["p"])
    train = params.get("_train", False)

    ws, bs = _unpack_rnn_params(flat, mode, I, H, L, bidir)
    step = _cell_step(mode, H)

    x = data
    h_states, c_states = [], []
    for layer in range(L):
        outs = []
        for dr in range(d):
            wx, wh = ws[layer][dr]
            bx, bh = bs[layer][dr]
            h0 = state0[layer * d + dr]
            carry = (h0, cell0[layer * d + dr]) if mode == "lstm" else (h0,)
            xseq = x if dr == 0 else jnp.flip(x, axis=0)
            xw = jnp.dot(xseq, wx.T) + bx  # (T, B, g*H): one big MXU matmul

            def body(c, xw_t, _wh=wh, _bh=bh):
                return step(c, xw_t, _wh, _bh)

            carry_f, seq = jax.lax.scan(body, carry, xw)
            if dr == 1:
                seq = jnp.flip(seq, axis=0)
            outs.append(seq)
            h_states.append(carry_f[0])
            if mode == "lstm":
                c_states.append(carry_f[1])
        x = outs[0] if d == 1 else jnp.concatenate(outs, axis=-1)
        if train and dropout_p > 0 and layer < L - 1:
            key, sub = jax.random.split(key)
            keep = jax.random.bernoulli(sub, 1 - dropout_p, x.shape)
            x = jnp.where(keep, x / (1 - dropout_p), 0.0)

    outputs = (x,)
    if params["state_outputs"]:
        hN = jnp.stack(h_states, axis=0)
        if mode == "lstm":
            cN = jnp.stack(c_states, axis=0)
            outputs = (x, hN, cN)
        else:
            outputs = (x, hN)
    return outputs if len(outputs) > 1 else x


# ---------------------------------------------------------------------------
# UpSampling (reference upsampling.cc)
# ---------------------------------------------------------------------------

@register("UpSampling", nin=-1, variadic_param="num_args",
          params={"scale": REQUIRED, "num_filter": 0, "sample_type": REQUIRED,
                  "multi_input_mode": "concat", "num_args": 1, "workspace": 512})
def _upsampling(params, *xs):
    scale = int(params["scale"])
    stype = params["sample_type"]
    outs = []
    for x in xs:
        if stype == "nearest":
            out = jnp.repeat(jnp.repeat(x, scale, axis=2), scale, axis=3)
        elif stype == "bilinear":
            n, c, h, w = x.shape
            out = jax.image.resize(x, (n, c, h * scale, w * scale), "bilinear")
        else:
            raise MXNetError("UpSampling: bad sample_type")
        outs.append(out)
    if len(outs) == 1:
        return outs[0]
    if params["multi_input_mode"] == "sum":
        o = outs[0]
        for t in outs[1:]:
            o = o + t
        return o
    return jnp.concatenate(outs, axis=1)
