"""Image pipeline: decode → augment → batch → prefetch.

Reference: `src/io/iter_image_recordio_2.cc` (ImageRecordIter),
`image_aug_default.cc` (augmenters: resize, random-resized-crop, mirror,
HSL jitter), python surface `python/mxnet/image/image.py` (ImageIter,
CreateAugmenter).  Decode uses PIL (no OpenCV in this environment — the C++
decode pool lands with the native IO module in `src/`); the threaded
prefetcher overlaps host decode with device compute, and `part_index/
num_parts` sharding matches the reference's multi-worker input splitting.
"""
from __future__ import annotations

import ctypes
import os
import random as _pyrandom
import threading
import queue as _queue

import numpy as np

from .analysis import locks as _alocks

from .base import MXNetError
from .io import DataIter, DataBatch, DataDesc
from .ndarray.ndarray import NDArray, array
from . import native as _native
from . import recordio as _recordio


# ---------------------------------------------------------------------------
# numpy augmenter primitives (reference image_aug_default.cc)
# ---------------------------------------------------------------------------

def imdecode(buf, to_rgb=1, **kwargs):
    """Decode image bytes to NDArray HWC (reference `image_io.cc imdecode`)."""
    import io as _io
    from PIL import Image
    img = Image.open(_io.BytesIO(buf))
    img = img.convert("RGB" if to_rgb else "BGR")
    return array(np.asarray(img, dtype=np.uint8), dtype="uint8")


def _resize_np(img, w, h, interp=2):
    from PIL import Image
    return np.asarray(Image.fromarray(img).resize((w, h), Image.BILINEAR))


def resize_short(src, size, interp=2):
    """Resize shorter edge to size (reference `image.py resize_short`)."""
    img = src.asnumpy() if isinstance(src, NDArray) else src
    h, w = img.shape[:2]
    if h > w:
        new_w, new_h = size, int(h * size / w)
    else:
        new_w, new_h = int(w * size / h), size
    return array(_resize_np(img, new_w, new_h), dtype="uint8")


def center_crop(src, size, interp=2):
    img = src.asnumpy() if isinstance(src, NDArray) else src
    h, w = img.shape[:2]
    cw, ch = size
    x0 = max((w - cw) // 2, 0)
    y0 = max((h - ch) // 2, 0)
    out = img[y0:y0 + ch, x0:x0 + cw]
    if out.shape[:2] != (ch, cw):
        out = _resize_np(out, cw, ch)
    return array(out, dtype="uint8"), (x0, y0, cw, ch)


def random_crop(src, size, interp=2):
    img = src.asnumpy() if isinstance(src, NDArray) else src
    h, w = img.shape[:2]
    cw, ch = size
    if w < cw or h < ch:
        img = _resize_np(img, max(w, cw), max(h, ch))
        h, w = img.shape[:2]
    x0 = _pyrandom.randint(0, w - cw)
    y0 = _pyrandom.randint(0, h - ch)
    return array(img[y0:y0 + ch, x0:x0 + cw], dtype="uint8"), (x0, y0, cw, ch)


def random_size_crop(src, size, area, ratio, interp=2):
    """Random-resized-crop (reference image_aug_default.cc / image.py)."""
    img = src.asnumpy() if isinstance(src, NDArray) else src
    h, w = img.shape[:2]
    src_area = h * w
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = _pyrandom.uniform(*area) * src_area
        log_ratio = (np.log(ratio[0]), np.log(ratio[1]))
        aspect = np.exp(_pyrandom.uniform(*log_ratio))
        cw = int(round(np.sqrt(target_area * aspect)))
        ch = int(round(np.sqrt(target_area / aspect)))
        if cw <= w and ch <= h:
            x0 = _pyrandom.randint(0, w - cw)
            y0 = _pyrandom.randint(0, h - ch)
            crop = img[y0:y0 + ch, x0:x0 + cw]
            return array(_resize_np(crop, size[0], size[1]), dtype="uint8"), \
                (x0, y0, cw, ch)
    return center_crop(array(_resize_np(img, size[0], size[1]), dtype="uint8"),
                       size)


class Augmenter:
    """Base augmenter (reference `image.py:Augmenter`)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json
        return json.dumps([self.__class__.__name__.lower(), self._kwargs],
                          default=lambda o: o.tolist()
                          if hasattr(o, "tolist") else str(o))

    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size

    def __call__(self, src):
        return resize_short(src, self.size)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size

    def __call__(self, src):
        img = src.asnumpy() if isinstance(src, NDArray) else src
        return array(_resize_np(img, self.size[0], self.size[1]), dtype="uint8")


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size

    def __call__(self, src):
        return random_crop(src, self.size)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size

    def __call__(self, src):
        return center_crop(src, self.size)[0]


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, area, ratio, interp=2):
        super().__init__(size=size, area=area, ratio=ratio, interp=interp)
        self.size = size
        self.area = area
        self.ratio = ratio

    def __call__(self, src):
        return random_size_crop(src, self.size, self.area, self.ratio)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _pyrandom.random() < self.p:
            img = src.asnumpy() if isinstance(src, NDArray) else src
            return array(img[:, ::-1].copy(), dtype="uint8")
        return src


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.brightness, self.brightness)
        img = (src.asnumpy().astype("float32") * alpha).clip(0, 255)
        return array(img.astype("uint8"), dtype="uint8")


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__(mean=mean, std=std)
        self.mean = np.asarray(mean, dtype="float32") if mean is not None else None
        self.std = np.asarray(std, dtype="float32") if std is not None else None

    def __call__(self, src):
        img = src.asnumpy().astype("float32") if isinstance(src, NDArray) else \
            src.astype("float32")
        if self.mean is not None:
            img = img - self.mean
        if self.std is not None:
            img = img / self.std
        return array(img, dtype="float32")


class CastAug(Augmenter):
    def __call__(self, src):
        return array(src.asnumpy().astype("float32"), dtype="float32")


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Reference `image.py CreateAugmenter`."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        auglist.append(RandomSizedCropAug(crop_size, (0.08, 1.0),
                                          (3 / 4.0, 4 / 3.0), inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness:
        auglist.append(BrightnessJitterAug(brightness))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None or std is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter(DataIter):
    """Python image iterator over .rec or image list
    (reference `python/mxnet/image/image.py:ImageIter`)."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root=None,
                 shuffle=False, part_index=None, num_parts=None,
                 aug_list=None, imglist=None, data_name="data",
                 label_name="softmax_label", **kwargs):
        super().__init__(batch_size)
        assert path_imgrec or path_imglist or imglist
        self.data_shape = tuple(data_shape)
        self.batch_size = batch_size
        self.label_width = label_width
        self.shuffle = shuffle
        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter(data_shape, **{k: v for k, v in kwargs.items()
                                           if k in ("resize", "rand_crop",
                                                    "rand_resize", "rand_mirror",
                                                    "mean", "std")})
        self.imgrec = None
        self.imglist = None
        self.path_root = path_root
        if path_imgrec:
            idx_path = os.path.splitext(path_imgrec)[0] + ".idx"
            if os.path.exists(idx_path):
                self.imgrec = _recordio.MXIndexedRecordIO(idx_path, path_imgrec,
                                                          "r")
                self.seq = list(self.imgrec.keys)
            else:
                self.imgrec = _recordio.MXRecordIO(path_imgrec, "r")
                self.seq = None
        elif path_imglist:
            with open(path_imglist) as fin:
                imglist = {}
                for line in fin:
                    parts = line.strip().split("\t")
                    label = np.asarray(parts[1:-1], dtype="float32")
                    imglist[int(parts[0])] = (label, parts[-1])
                self.imglist = imglist
                self.seq = list(imglist.keys())
        else:
            self.imglist = {i: (np.asarray(l, dtype="float32"), p)
                            for i, (l, p) in enumerate(imglist)}
            self.seq = list(self.imglist.keys())
        # per-host sharding over the sequence: `recordio.shard_range`
        # (disjoint/exhaustive — the old `len//num_parts` slice silently
        # DROPPED the remainder records); num_parts=None/'auto' resolves
        # from the dist environment, re-checked at reset() so a shrunk
        # pod re-shards on the epoch fence
        self._full_seq = list(self.seq) if self.seq is not None else None
        self._part_index_req = part_index
        self._num_parts_req = num_parts
        self._quarantined_ids = set()
        self._reshard_seq()
        self.cur = 0
        self.data_name = data_name
        self.label_name = label_name
        self.corrupt_records = 0   # undecodable/corrupt samples skipped
        self._quarantine = None
        self.reset()

    def set_quarantine(self, log):
        """Attach a quarantine log (resilience.guardian.QuarantineLog):
        corrupt samples this iterator skips append one entry each, and
        the underlying RecordIO reader's structural skips do too."""
        self._quarantine = log
        if self.imgrec is not None and hasattr(self.imgrec,
                                               "set_quarantine"):
            self.imgrec.set_quarantine(log)

    def apply_quarantine(self, entries):
        """Drop records previously quarantined for this source (resume
        path): their ids never enter the epoch sequence again — held on
        the quarantine set so an epoch-fence re-shard cannot resurrect
        them."""
        if self.seq is None:
            return
        bad = {int(e["record"]) for e in entries
               if e.get("record") is not None and e.get("source") in (
                   None, getattr(self.imgrec, "uri", None))}
        if bad:
            self._quarantined_ids.update(bad)
            self.seq = [k for k in self.seq if k not in bad]

    def _reshard_seq(self):
        """This epoch's sequence from the full list: the resolved shard
        window (`recordio.shard_range`) minus quarantined ids."""
        if self._full_seq is None:
            return
        pi, nparts = self._part_index_req, self._num_parts_req
        if nparts == "auto":
            # explicit opt-in only (an unset num_parts must not shard
            # eval iterators in dist runs); MXNET_IO_AUTO_SHARD=0 is
            # the ops off-switch
            from . import config as _config
            from . import io_plane as _io_plane
            if _config.get("MXNET_IO_AUTO_SHARD"):
                pi, nparts = _io_plane.auto_shard(
                    pi if pi != "auto" else None, None)
            else:
                pi, nparts = 0, 1
        elif nparts in (None, 0):
            pi, nparts = 0, 1
        lo, hi = _recordio.shard_range(len(self._full_seq), int(nparts),
                                       int(pi or 0))
        bad = self._quarantined_ids
        self.seq = [k for k in self._full_seq[lo:hi] if k not in bad]

    def _corrupt_sample(self, idx, exc):
        self.corrupt_records += 1
        import logging
        logging.getLogger(__name__).warning(
            "ImageIter: skipping corrupt record %s (%s) — "
            "corrupt_records=%d", idx, str(exc)[:120],
            self.corrupt_records)
        if self._quarantine is not None:
            try:
                self._quarantine.append(
                    reason="corrupt_record",
                    source=getattr(self.imgrec, "uri", None),
                    record=idx if isinstance(idx, int) else None,
                    detail=str(exc)[:200])
            except Exception:
                pass
        try:
            from .resilience import faults as _faults
            _faults.note("corrupt-record", site="io.corrupt_record",
                         record=idx if isinstance(idx, int) else -1)
        except Exception:
            pass

    @property
    def provide_data(self):
        return [DataDesc(self.data_name, (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        return [DataDesc(self.label_name, shape)]

    def reset(self):
        # the epoch fence: re-resolve the shard (a shrunk pod's
        # rewritten rank/world re-splits the sequence here)
        self._reshard_seq()
        if self.shuffle and self.seq is not None:
            _pyrandom.shuffle(self.seq)
        if self.imgrec is not None and self.seq is None:
            self.imgrec.reset()
        self.cur = 0

    def next_sample(self):
        self._last_idx = None
        if self.seq is not None:
            if self.cur >= len(self.seq):
                raise StopIteration
            idx = self.seq[self.cur]
            self.cur += 1
            self._last_idx = idx
            if self.imgrec is not None:
                s = self.imgrec.read_idx(idx)
                header, img = _recordio.unpack(s)
                return header.label, img
            label, fname = self.imglist[idx]
            with open(os.path.join(self.path_root or "", fname), "rb") as f:
                return label, f.read()
        s = self.imgrec.read()
        if s is None:
            raise StopIteration
        header, img = _recordio.unpack(s)
        return header.label, img

    def next(self):
        c, h, w = self.data_shape
        batch_data = np.zeros((self.batch_size, c, h, w), dtype="float32")
        batch_label = np.zeros((self.batch_size, self.label_width), dtype="float32")
        i = 0
        pad = 0
        try:
            while i < self.batch_size:
                try:
                    label, buf = self.next_sample()
                    img = imdecode(buf)
                except StopIteration:
                    raise
                except Exception as e:
                    # a corrupt record (torn payload, bit-flipped JPEG,
                    # bad header) must not kill the epoch: skip it with
                    # a counted warning and feed the quarantine log
                    self._corrupt_sample(self._last_idx, e)
                    continue
                for aug in self.auglist:
                    img = aug(img)
                arr = img.asnumpy()
                batch_data[i] = arr.transpose(2, 0, 1)
                lab = np.asarray(label, dtype="float32").reshape(-1)
                batch_label[i, :len(lab[:self.label_width])] = \
                    lab[:self.label_width]
                i += 1
        except StopIteration:
            if i == 0:
                raise
            pad = self.batch_size - i
        label_out = batch_label[:, 0] if self.label_width == 1 else batch_label
        return DataBatch(data=[array(batch_data)], label=[array(label_out)],
                         pad=pad, provide_data=self.provide_data,
                         provide_label=self.provide_label)


class ImageRecordIterImpl(DataIter):
    """Param-compatible `ImageRecordIter` (reference
    `iter_image_recordio_2.cc:727` registration).

    Throughput design (same shape as the reference's C++ iterator): the
    whole .rec is mapped into memory and indexed in one native scan
    (`src/io_native.cc mxtpu_recordio_index`); `preprocess_threads`
    workers each build complete batches — cv2 JPEG decode and the native
    crop/mirror/normalize/HWC->CHW kernel both release the GIL, so the
    pool scales — and a reorder buffer hands batches out in order.
    """

    def __init__(self, path_imgrec, data_shape, batch_size, label_width=1,
                 shuffle=False, rand_crop=False, rand_mirror=False,
                 mean_r=0.0, mean_g=0.0, mean_b=0.0, std_r=1.0, std_g=1.0,
                 std_b=1.0, resize=0, part_index=None, num_parts=None,
                 preprocess_threads=None, prefetch_buffer=4,
                 round_batch=True, data_name="data",
                 label_name="softmax_label", seed=0, fast_decode=True,
                 device_augment=False, **kwargs):
        super().__init__(batch_size)
        if preprocess_threads is None:
            from . import config as _config
            preprocess_threads = _config.get("MXNET_CPU_WORKER_NTHREADS")
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self._shuffle = shuffle
        self._rand_crop = rand_crop
        self._rand_mirror = rand_mirror
        self._resize = resize
        self._mean = np.array([mean_r, mean_g, mean_b], dtype="float32")
        # keep the ORIGINAL std too: normalize_symbol passes it to the
        # in-graph ImageNormalize, whose f32 reciprocal then matches the
        # host kernel's `_stdinv` bit-for-bit (uint8-wire parity)
        self._std = np.array([std_r, std_g, std_b], dtype="float32")
        self._stdinv = 1.0 / np.array([std_r, std_g, std_b], dtype="float32")
        # clamp to physical cores: batch builders are CPU-bound (decode +
        # augment), so threads beyond the core count only add GIL ping-pong
        # and working-set thrash (measured −47% at 16 threads on a 1-core
        # host).  The reference's C++ pool is bounded the same way in
        # practice by its decode thread count.
        self._threads = max(1, min(int(preprocess_threads),
                                   os.cpu_count() or 1))
        self._prefetch = max(2, int(prefetch_buffer))
        self._data_name = data_name
        self._label_name = label_name
        self._seed = seed
        self._rng = np.random.RandomState(seed)
        self._epoch = 0
        self._round_batch = round_batch
        # fast_decode: decode JPEGs at 1/2 (or 1/4) resolution straight in
        # libjpeg when the source is comfortably larger than every consumer
        # (resize target / crop window) — the fused decode+downscale trick
        # the reference leaves to full decode + cv::resize.  Falls back to
        # a full decode per image when the reduced frame comes up short.
        self._fast_decode = bool(fast_decode)
        self._fd_tries = 0
        self._fd_wins = 0
        # device_augment: the host stops at crop+mirror and ships uint8
        # NHWC (4x fewer bytes than the fp32 finish, and no float/layout
        # passes on a busy CPU); normalize/cast/NCHW become graph ops —
        # compose the model with `self.normalize_symbol(data)` (the
        # ImageNormalize op), which XLA fuses into the first conv.
        # 'auto'/None-as-string resolves from MXNET_IO_UINT8_WIRE — the
        # production data-plane default (bench io lane, run_io_bench);
        # an explicit True/False always wins.
        if isinstance(device_augment, str) and \
                device_augment.lower() in ("auto", "none"):
            from . import config as _config
            device_augment = bool(_config.get("MXNET_IO_UINT8_WIRE"))
        self._device_augment = bool(device_augment)

        import mmap
        self._path_imgrec = path_imgrec
        self._file = open(path_imgrec, "rb")
        self._buf = mmap.mmap(self._file.fileno(), 0,
                              access=mmap.ACCESS_READ)
        self._records, n_corrupt = _index_records_tolerant(self._buf)
        # structural damage found at index time (torn tail, bad magic)
        # plus per-sample decode failures found by the batch builders
        self.corrupt_records = n_corrupt
        self._corrupt_lock = _alocks.make_lock("image.corrupt")
        self._quarantine = None
        if n_corrupt:
            import logging
            logging.getLogger(__name__).warning(
                "ImageRecordIter: %s holds %d corrupt region(s); the "
                "damaged records are skipped (corrupt_records counts "
                "them)", path_imgrec, n_corrupt)
        # per-host input sharding: record ids stay GLOBAL (indexes into
        # the full record list) so quarantine entries keep attributing
        # after a re-shard; the shard only restricts the epoch ORDER.
        # num_parts=None/0/'auto' auto-resolves from this process's
        # (rank, world) — re-resolved at every reset(), so the
        # supervisor's shrink-and-resume re-shards on the epoch fence.
        self._part_index_req = part_index
        self._num_parts_req = num_parts
        self._quarantined = set()
        self.part_index = 0
        self.num_parts = 1
        self._pool = None
        self.reset()

    def _resolve_parts(self):
        """(part_index, num_parts) for the NEXT epoch.  Only an
        EXPLICIT ``num_parts='auto'`` consults the dist environment
        (`io_plane.auto_shard`) — an unset num_parts must stay
        unsharded, or every validation/eval iterator in a dist run
        would silently score 1/N of its data.  MXNET_IO_AUTO_SHARD=0
        is the ops off-switch forcing even 'auto' to a single part."""
        pi, nparts = self._part_index_req, self._num_parts_req
        if nparts == "auto":
            from . import config as _config
            if _config.get("MXNET_IO_AUTO_SHARD"):
                from . import io_plane as _io_plane
                return _io_plane.auto_shard(pi if pi != "auto" else None,
                                            None)
            return 0, 1
        if nparts in (None, 0):
            return 0, 1
        return int(pi or 0), int(nparts)

    def _reshard(self):
        """Recompute this epoch's record order from the resolved shard
        (`recordio.shard_range`: disjoint, exhaustive, deterministic),
        minus quarantined ids."""
        self.part_index, self.num_parts = self._resolve_parts()
        lo, hi = _recordio.shard_range(len(self._records),
                                       self.num_parts, self.part_index)
        if self._quarantined:
            self._order = np.asarray(
                [i for i in range(lo, hi) if i not in self._quarantined],
                dtype=np.int64)
        else:
            self._order = np.arange(lo, hi, dtype=np.int64)

    @property
    def provide_data(self):
        if self._device_augment:
            c, h, w = self.data_shape
            return [DataDesc(self._data_name, (self.batch_size, h, w, c),
                             dtype=np.uint8)]
        return [DataDesc(self._data_name,
                         (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        return [DataDesc(self._label_name, shape)]

    def normalize_symbol(self, data, dtype="float32"):
        """The graph-side half of device_augment mode: wrap the model's
        input variable so normalize/cast/NCHW run IN the compiled program
        with this iterator's mean/std."""
        from . import symbol as _sym
        mean = tuple(float(v) for v in self._mean)
        # the ORIGINAL std values, not a 1/(1/std) float roundtrip: the
        # op's own f32 reciprocal then equals the host kernel's _stdinv
        # bit-for-bit, so uint8-wire + in-graph normalize reproduces the
        # host-side fp32 path EXACTLY
        std = tuple(float(v) for v in self._std)
        return _sym.ImageNormalize(
            data, mean=mean, std=std, input_layout="NHWC",
            output_layout="NCHW", dtype=dtype)

    def _rebuild_pool(self):
        """(Re)build the batch pool over the current epoch order.
        Reference round_batch semantics: the tail partial batch wraps
        around to the epoch start and reports the wrapped count as
        pad."""
        if self._pool is not None:
            self._pool.stop()
        n = len(self._order)
        n_batches = (-(-n // self.batch_size) if self._round_batch and
                     n % self.batch_size else n // self.batch_size)
        self._pool = _BatchPool(self._build_batch, n_batches, self._threads,
                                self._prefetch)

    def reset(self):
        # the epoch fence: the shard re-resolves here, so a pod that
        # shrank (DMLC_NUM_WORKER rewritten by shrink-and-resume) walks
        # the re-split record set from the next epoch on
        # (_rebuild_pool below stops the previous pool)
        self._reshard()
        if self._shuffle:
            self._rng.shuffle(self._order)
        self._epoch += 1
        self._rebuild_pool()

    def set_quarantine(self, log):
        """Attach a quarantine log: corrupt records the batch builders
        skip append one entry each (source path + record id)."""
        self._quarantine = log

    def apply_quarantine(self, entries):
        """Drop previously quarantined record ids for this .rec file
        from the epoch order (resume path: a poisoned record is read
        exactly zero times after diagnosis).  `self._records` is left
        INTACT — record ids must stay stable so entries this run logs
        later still attribute correctly on the next resume; only the
        epoch order loses the poisoned ids."""
        bad = {int(e["record"]) for e in entries
               if e.get("record") is not None and
               e.get("source") in (None, self._path_imgrec)}
        if bad:
            # poisoned ids are remembered on the QUARANTINE SET (not by
            # editing one epoch's order): every future _reshard()
            # excludes them, so a re-shard on the epoch fence cannot
            # resurrect a diagnosed record — and a quarantined record on
            # ANOTHER host's shard simply never intersects this order
            # (the poison stays local to the shard that read it)
            self._quarantined.update(bad)
            self._order = np.asarray(
                [i for i in self._order if int(i) not in bad],
                dtype=np.int64)
            # rebuild the batch pool for the shorter order without
            # advancing the epoch counter (reset() increments it, and
            # the augmentation RNG streams key on the epoch)
            self._rebuild_pool()

    def record_range(self, nbatch):
        """(source, lo, hi) record-position range batch `nbatch` of this
        epoch draws from — the guardian's shard attribution for
        quarantine entries and TrainingDivergedError."""
        lo = int(nbatch) * self.batch_size
        return (self._path_imgrec, lo,
                min(lo + self.batch_size, len(self._order)))

    def _corrupt_record(self, rec_id, exc):
        with self._corrupt_lock:
            self.corrupt_records += 1
            n = self.corrupt_records
        import logging
        logging.getLogger(__name__).warning(
            "ImageRecordIter: record %d of %s is corrupt (%s) — "
            "substituting zeros and quarantining (corrupt_records=%d)",
            rec_id, self._path_imgrec, str(exc)[:120], n)
        if self._quarantine is not None:
            try:
                self._quarantine.append(reason="corrupt_record",
                                        source=self._path_imgrec,
                                        record=int(rec_id),
                                        detail=str(exc)[:200])
            except Exception:
                pass
        try:
            from .resilience import faults as _faults
            _faults.note("corrupt-record", site="io.corrupt_record",
                         record=int(rec_id))
        except Exception:
            pass

    def close(self):
        if self._pool is not None:
            self._pool.stop()
            self._pool = None

    def __del__(self):
        try:
            self.close()
            self._buf.close()
            self._file.close()
        except Exception:
            pass

    def _decode(self, payload, cv2, need):
        """JPEG decode, at reduced libjpeg scale when the frame stays large
        enough for every consumer (`need` = min acceptable shorter side).

        Adaptive: a failed reduced attempt costs a second (full) decode, so
        after a sampling window the reduced path stays on only if most
        images in this corpus are big enough for it."""
        raw = np.frombuffer(payload, np.uint8)
        # only when a resize step follows: the resize renormalizes scale, so
        # decoding at 1/2 changes nothing but cost.  Without resize, a
        # reduced decode would silently double the crop's field of view.
        if self._fast_decode and self._resize > 0 and need > 0 and \
                (self._fd_tries < 16 or self._fd_wins * 2 >= self._fd_tries):
            self._fd_tries += 1
            img = cv2.imdecode(raw, cv2.IMREAD_REDUCED_COLOR_2)
            if img is not None and min(img.shape[:2]) >= need:
                self._fd_wins += 1
                return img
        return cv2.imdecode(raw, cv2.IMREAD_COLOR)

    def _build_batch(self, bidx):
        import cv2
        c, h, w = self.data_shape
        bs = self.batch_size
        label = np.zeros((bs, self.label_width), dtype="float32")
        nat = _native.lib()
        base = bidx * bs
        n_rec = len(self._order)
        pad = max(0, base + bs - n_rec)
        # a per-batch stream keeps augmentation reproducible under any
        # thread schedule: (seed, epoch, batch) fully determines the draws
        rng = np.random.RandomState(
            (self._seed * 1000003 + self._epoch * 8191 + bidx) % (2**31))
        # one vectorized draw per batch (not one python call per record)
        crop_u = rng.rand(bs, 2) if self._rand_crop else None
        mirrors = (rng.rand(bs) < 0.5).astype(np.int32) \
            if self._rand_mirror else np.zeros(bs, np.int32)
        need = self._resize if self._resize else max(h, w)

        imgs = []
        # row-major per-field layout: each row is contiguous for ctypes
        dims = np.empty((4, bs), np.int64)  # rows: ih, iw, y0, x0
        from .resilience import faults as _faults
        for i in range(bs):
            rec_id = int(self._order[(base + i) % n_rec])
            header = img = None
            try:
                raw = _record_payload(self._buf, self._records[rec_id])
                # the payload fault site: a `corrupt` clause bit-flips
                # this record's bytes deterministically
                raw = _faults.mutate("io.corrupt_record", bytes(raw),
                                     record=rec_id)
                header, payload = _recordio.unpack(raw)
                img = self._decode(payload, cv2, need)
                if img is None:
                    raise MXNetError("not a decodable image")
            except Exception as e:
                # a corrupt record must not kill the epoch: substitute a
                # zero image (deterministic), count, and quarantine —
                # the resumed run drops the record entirely
                self._corrupt_record(rec_id, e)
                header, img = None, np.zeros((h, w, c), np.uint8)
            if self._resize:
                ih, iw = img.shape[:2]
                if ih > iw:
                    img = cv2.resize(img, (self._resize,
                                           int(ih * self._resize / iw)))
                else:
                    img = cv2.resize(img, (int(iw * self._resize / ih),
                                           self._resize))
            ih, iw = img.shape[:2]
            if ih < h or iw < w:
                img = cv2.resize(img, (max(iw, w), max(ih, h)))
                ih, iw = img.shape[:2]
            if self._rand_crop:
                y0 = int(crop_u[i, 0] * (ih - h + 1))
                x0 = int(crop_u[i, 1] * (iw - w + 1))
            else:
                y0, x0 = (ih - h) // 2, (iw - w) // 2
            if not img.flags["C_CONTIGUOUS"]:
                img = np.ascontiguousarray(img)
            imgs.append(img)
            dims[:, i] = (ih, iw, y0, x0)
            if header is not None:
                lab = np.asarray(header.label, dtype="float32").reshape(-1)
                label[i, :min(len(lab), self.label_width)] = \
                    lab[:self.label_width]

        # fresh buffer each batch: handed to jax ZERO-COPY below (cpu) or
        # consumed by an async transfer (accelerator) — never recycled, so
        # no defensive copy is needed anywhere on the path
        u8 = self._device_augment
        native_ok = nat is not None and \
            (not u8 or hasattr(nat, "mxtpu_crop_batch_u8"))
        if native_ok:
            # shared ctypes marshalling for both native finishes
            dims = np.ascontiguousarray(dims)
            ptrs = (ctypes.c_void_p * bs)(
                *(img.ctypes.data for img in imgs))
            i64p = ctypes.POINTER(ctypes.c_int64)
            mirrors_p = np.ascontiguousarray(mirrors).ctypes.data_as(
                ctypes.POINTER(ctypes.c_int))
        if u8:
            # host stops at crop+mirror: uint8 NHWC out (the normalize/
            # cast/layout finish runs in the training program, see
            # normalize_symbol) — no float pass, quarter the bytes
            data = np.empty((bs, h, w, c), dtype=np.uint8)
            if native_ok:
                nat.mxtpu_crop_batch_u8(
                    ptrs, dims[0].ctypes.data_as(i64p),
                    dims[1].ctypes.data_as(i64p), c,
                    dims[2].ctypes.data_as(i64p),
                    dims[3].ctypes.data_as(i64p), h, w, mirrors_p,
                    data.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                    bs, 1)
            else:
                for i, img in enumerate(imgs):
                    ih, iw, y0, x0 = dims[:, i]
                    crop = img[y0:y0 + h, x0:x0 + w, ::-1]  # BGR -> RGB
                    if mirrors[i]:
                        crop = crop[:, ::-1]
                    data[i] = crop
            return self._emit(data, label, pad)
        data = np.empty((bs, c, h, w), dtype="float32")
        if native_ok:
            # decoded frames are BGR; the kernel reverses channels on the
            # fly into RGB planes (no cvtColor pass)
            f32p = ctypes.POINTER(ctypes.c_float)
            nat.mxtpu_augment_batch(
                ptrs, dims[0].ctypes.data_as(i64p),
                dims[1].ctypes.data_as(i64p), c,
                dims[2].ctypes.data_as(i64p),
                dims[3].ctypes.data_as(i64p), h, w, mirrors_p,
                self._mean.ctypes.data_as(f32p),
                self._stdinv.ctypes.data_as(f32p),
                data.ctypes.data_as(f32p), bs, 1)
        else:
            for i, img in enumerate(imgs):
                ih, iw, y0, x0 = dims[:, i]
                crop = img[y0:y0 + h, x0:x0 + w, ::-1]  # BGR -> RGB
                if mirrors[i]:
                    crop = crop[:, ::-1]
                data[i] = ((crop.astype("float32") - self._mean)
                           * self._stdinv).transpose(2, 0, 1)
        return self._emit(data, label, pad)

    def _emit(self, data, label, pad):
        label_out = label[:, 0] if self.label_width == 1 else label

        from .context import current_context
        ctx = current_context()
        if ctx.device_type == "cpu":
            # keep the batch as host numpy behind the NDArray: the
            # training step's input staging sends it STRAIGHT to its
            # target device/sharding in one transfer, and eager consumers
            # promote host-backed arrays on first use (invoke()); wrapping
            # in a cpu-backend jax array here would add a slow
            # cross-backend hop on the training hot path
            batch_nd = NDArray(data, ctx=ctx)
            label_nd = NDArray(label_out, ctx=ctx)
        else:
            import jax
            batch_nd = NDArray(jax.device_put(data, ctx.jax_device), ctx=ctx)
            label_nd = array(label_out, ctx=ctx)
        return DataBatch(data=[batch_nd], label=[label_nd],
                         pad=pad, provide_data=self.provide_data,
                         provide_label=self.provide_label)

    def next(self):
        batch = self._pool.next()
        if batch is None:
            raise StopIteration
        return batch


class _WorkerError:
    """A worker exception in transit to the consumer thread."""

    __slots__ = ("exc",)

    def __init__(self, exc):
        self.exc = exc


class _BatchPool:
    """N workers building whole batches; results handed out in order."""

    def __init__(self, build, n_batches, n_threads, prefetch):
        self._build = build
        self._n = n_batches
        self._stop_evt = threading.Event()
        self._results = {}
        self._cond = _alocks.make_condition(name="image.batchpool")
        self._next_out = 0
        self._max_ahead = max(prefetch, n_threads + 1)
        self._task = iter(range(n_batches))
        self._task_lock = _alocks.make_lock("image.batchpool.tasks")
        self._threads = [threading.Thread(target=self._work, daemon=True,
                                          name=f"mx-io-decode-{i}")
                         for i in range(n_threads)]
        for t in self._threads:
            t.start()

    def _work(self):
        from .obs import metrics as _metrics, trace as _trace
        while not self._stop_evt.is_set():
            with self._task_lock:
                bidx = next(self._task, None)
            if bidx is None:
                return
            with self._cond:
                # bounded read-ahead keeps memory flat
                self._cond.wait_for(
                    lambda: self._stop_evt.is_set()
                    or bidx < self._next_out + self._max_ahead)
                if self._stop_evt.is_set():
                    return
            try:
                with _trace.span("io.decode", cat="io", batch=bidx):
                    out = self._build(bidx)
            except BaseException as e:   # deliver to the consumer, always
                out = _WorkerError(e)
            with self._cond:
                self._results[bidx] = out
                _metrics.registry().gauge("io.decode.queue_depth").set(
                    len(self._results))
                self._cond.notify_all()

    def next(self):
        if self._next_out >= self._n:
            return None
        with self._cond:
            self._cond.wait_for(lambda: self._next_out in self._results)
            out = self._results.pop(self._next_out)
            self._next_out += 1
            self._cond.notify_all()
        if isinstance(out, _WorkerError):
            self.stop()
            raise out.exc
        return out

    def stop(self):
        self._stop_evt.set()
        with self._cond:
            self._cond.notify_all()
        for t in self._threads:
            t.join(timeout=5)


def _group_parts(parts):
    """Group (offset, length, cflag) physical parts into logical records:
    cflag 0 stands alone; 1/2*/3 sequences form one multi-part record
    (dmlc writers split payloads containing the magic word; see
    `recordio.MXRecordIO.read`).  Structural violations — a truncated
    multi-part sequence, a continuation without a start — drop the
    damaged record and count it instead of raising: a torn tail must not
    make the whole .rec unreadable.  Returns (records, n_corrupt)."""
    records = []
    pending = None
    corrupt = 0
    for off, ln, cf in parts:
        if cf == 0:
            if pending is not None:
                corrupt += 1     # interrupted multi-part: drop it
                pending = None
            records.append([(off, ln)])
        elif cf == 1:
            if pending is not None:
                corrupt += 1
            pending = [(off, ln)]
        elif cf in (2, 3):
            if pending is None:
                corrupt += 1     # continuation without a start
                continue
            pending.append((off, ln))
            if cf == 3:
                records.append(pending)
                pending = None
        else:
            corrupt += 1
            pending = None
    if pending is not None:
        corrupt += 1             # truncated multi-part record at EOF
    return records, corrupt


_REC_MAGIC = __import__("struct").pack("<I", 0xced7230a)


def _record_payload(buf, segments):
    """Payload bytes of one logical record: single-part records slice
    straight from the mapped file; multi-part records are re-joined with
    the magic word the writer dropped at each split."""
    if len(segments) == 1:
        off, ln = segments[0]
        return buf[off:off + ln]
    return _REC_MAGIC.join(bytes(buf[off:off + ln]) for off, ln in segments)


def _index_records_tolerant(buf):
    """Segment lists of every logical record payload — native scan when
    the library is built, struct-walk fallback otherwise.  Each entry is
    a list of (offset, length) parts; pass to `_record_payload`.

    Tolerant of damage: a magic mismatch resynchronizes on the next
    magic word (the bytes in between are one counted corrupt region), a
    truncated tail record stops the scan, and broken multi-part
    sequences are dropped — see `_group_parts`.  A native scan that
    reports invalid structure (-1) falls back to the tolerant walk
    instead of raising.  Returns (records, n_corrupt)."""
    nat = _native.lib()
    parts = None
    corrupt = 0
    if nat is not None:
        cap = max(1024, len(buf) // 12)
        offs = np.empty(cap, dtype=np.int64)
        lens = np.empty(cap, dtype=np.int64)
        cfls = np.empty(cap, dtype=np.int32)
        # zero-copy view works for bytes and (read-only) mmap alike
        view = np.frombuffer(buf, dtype=np.uint8)
        n = nat.mxtpu_recordio_index(
            view.ctypes.data_as(ctypes.c_void_p), len(buf),
            offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            cfls.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), cap)
        if n >= 0:
            parts = list(zip(offs[:n].tolist(), lens[:n].tolist(),
                             cfls[:n].tolist()))
            # the native scan stops silently at a truncated tail; any
            # unconsumed bytes past the last indexed part are one
            # corrupt region (a torn header/payload a writer left)
            end = 0
            if parts:
                off, ln, _ = parts[-1]
                end = off + ln + (4 - ln % 4) % 4
            if len(buf) - end > 0:
                corrupt += 1
        # n == -1: the native scan found invalid structure — take the
        # tolerant python walk below instead of refusing the file
    if parts is None:
        import struct as _struct
        magic_bytes = _struct.pack("<I", 0xced7230a)
        out = []
        pos = 0
        while pos + 8 <= len(buf):
            magic, lrec = _struct.unpack_from("<II", buf, pos)
            if magic != 0xced7230a:
                # resynchronize on the next magic word; the skipped
                # bytes are one corrupt region
                corrupt += 1
                hit = buf.find(magic_bytes, pos + 1)
                if hit == -1:
                    break
                pos = hit
                continue
            length = lrec & ((1 << 29) - 1)
            if pos + 8 + length > len(buf):
                corrupt += 1     # truncated tail record
                break
            out.append((pos + 8, length, lrec >> 29))
            pos += 8 + length + (4 - length % 4) % 4
        parts = out
    records, n_bad = _group_parts(parts)
    return records, corrupt + n_bad


def _index_records(buf):
    """Back-compat face of `_index_records_tolerant`: records only."""
    return _index_records_tolerant(buf)[0]


# detection pipeline shares this namespace in the reference (mx.image.*)
from .image_detection import (DetAugmenter, DetBorrowAug,   # noqa: E402
                              DetRandomSelectAug, DetHorizontalFlipAug,
                              DetRandomCropAug, DetRandomPadAug,
                              CreateDetAugmenter, ImageDetIter)
