"""Library/build information (reference `python/mxnet/libinfo.py`).

The reference locates libmxnet.so; here the "library" is the JAX/XLA
runtime plus the optional native IO extension, so this reports what is
actually loadable.
"""
from __future__ import annotations

import os

__version__ = "0.1.0"


def find_lib_path():
    """Paths of loadable native components (reference `find_lib_path`).

    Returns the native IO library when built; empty list otherwise (the
    compute path needs no framework .so — XLA executables are produced
    at trace time).
    """
    from . import native
    if native.lib() is not None:
        return [native._LIB_PATH]
    return []


def find_include_path():
    """Reference `find_include_path`: headers for native extensions."""
    src = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "src")
    return src if os.path.isdir(src) else ""


def features():
    """Runtime feature flags (the role of `libinfo.cc` feature list)."""
    import jax
    from . import native
    from .context import num_tpus
    return {
        "TPU": num_tpus() > 0,
        "NATIVE_IO": native.lib() is not None,
        "JAX_VERSION": jax.__version__,
        "BACKENDS": sorted({d.platform for d in jax.devices()}),
    }
