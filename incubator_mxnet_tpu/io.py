"""Data iterators (reference `python/mxnet/io.py` + `src/io/`).

`DataIter`/`DataBatch`/`DataDesc` keep the reference API; `NDArrayIter`
(reference `io.py:546`) is the workhorse; `PrefetchingIter` (reference
`io.py:349`, C side `iter_prefetcher.h`) overlaps producer threads with
compute; `CSVIter`/`MNISTIter`/`ImageRecordIter` re-express the C++
iterators (`src/io/iter_csv.cc:218`, `iter_mnist.cc:260`,
`iter_image_recordio_2.cc`) over the RecordIO/host pipeline.
"""
from __future__ import annotations

import os
import struct
import threading
import queue as _queue

import numpy as _np

from .base import MXNetError
from .context import cpu
from .ndarray.ndarray import NDArray, array

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "CSVIter", "MNISTIter", "ImageRecordIter",
           "LibSVMIter", "pad_to_bucket", "DevicePrefetchIter",
           "H2DRing", "RingPlacement", "auto_shard"]


def __getattr__(name):
    # the h2d staging ring lives in io_plane.py (which imports this
    # module for DataIter/DataBatch): re-exported lazily to avoid the
    # circular import while keeping the public `mx.io.*` surface
    if name in ("DevicePrefetchIter", "H2DRing", "RingPlacement",
                "auto_shard"):
        from . import io_plane
        return getattr(io_plane, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


class DataDesc:
    """Named shape/type descriptor (reference `io.py:DataDesc`)."""

    def __init__(self, name, shape, dtype=_np.float32, layout="NCHW"):
        self.name = name
        self.shape = tuple(shape)
        self.dtype = dtype
        self.layout = layout

    def __repr__(self):
        return f"DataDesc[{self.name},{self.shape},{self.dtype},{self.layout}]"

    def __iter__(self):
        # unpack like the namedtuple in the reference
        yield self.name
        yield self.shape

    def __getitem__(self, i):
        return (self.name, self.shape)[i]

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")


class DataBatch:
    """One batch (reference `io.py:DataBatch`)."""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        if data is not None and not isinstance(data, (list, tuple)):
            data = [data]
        if label is not None and not isinstance(label, (list, tuple)):
            label = [label]
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        data_shapes = [d.shape for d in self.data] if self.data else None
        label_shapes = [l.shape for l in self.label] if self.label else None
        return f"{self.__class__.__name__}: data shapes: {data_shapes} " \
               f"label shapes: {label_shapes}"

    def pad_to_bucket(self, buckets):
        """Pad this batch up to the nearest shape bucket — see the
        module-level `pad_to_bucket`."""
        return pad_to_bucket(self, buckets)


def _pad_rows(arr, pad):
    """Append `pad` replicas of the final row (NDArray or numpy)."""
    if isinstance(arr, NDArray):
        from .ndarray.ndarray import concatenate
        tail = arr[arr.shape[0] - 1:arr.shape[0]]
        return concatenate([arr] + [tail] * pad, axis=0)
    arr = _np.asarray(arr)
    return _np.concatenate([arr, _np.repeat(arr[-1:], pad, axis=0)])


def pad_to_bucket(batch, buckets):
    """Pad a `DataBatch` along the batch axis to the smallest bucket that
    fits it, accounting the padding in ``batch.pad``.

    A ragged final batch (a non-divisible dataset) is the classic TPU
    recompile hazard `analysis/recompile.py` diagnoses: its novel batch
    dimension forces a fresh multi-second XLA compile every epoch.
    `Module.predict`/`iter_predict` route every batch through here with
    the iterator's batch size as the single bucket, so the tail reuses
    the full-batch compiled program and its pad rows are sliced off with
    the existing ``pad`` machinery.  Pad rows replicate the final sample
    (row-independent inference never reads them).

    Returns `batch` unchanged when its size already matches a bucket or
    exceeds them all; otherwise a NEW DataBatch (the input is not
    mutated)."""
    if not batch.data:
        return batch
    n = int(batch.data[0].shape[0])
    target = None
    for b in sorted(int(x) for x in buckets):
        if n <= b:
            target = b
            break
    if target is None or target == n:
        return batch
    pad = target - n
    return DataBatch(
        data=[_pad_rows(d, pad) for d in batch.data],
        label=[_pad_rows(l, pad) for l in (batch.label or [])] or None,
        pad=(batch.pad or 0) + pad, index=batch.index,
        bucket_key=batch.bucket_key, provide_data=batch.provide_data,
        provide_label=batch.provide_label)


class DataIter:
    """Base iterator (reference `io.py:182 DataIter`)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError

    # -- checkpoint/resume support (checkpoint/state.py) -----------------------
    def seek(self, nbatch):
        """Position so the next batch is batch `nbatch` of the epoch.
        Generic reset+skip; iterators with cheap native positioning
        override (NDArrayIter does)."""
        self.reset()
        for _ in range(int(nbatch)):
            self.next()

    def checkpoint_state(self):
        """Epoch-internal state a checkpoint must carry for exact resume
        beyond the batch counter (e.g. a shuffle permutation).  Empty ->
        resume uses plain ``seek(nbatch)``."""
        return {}

    def set_checkpoint_state(self, state, nbatch=0):
        self.seek(nbatch)

    # -- guardian attribution (resilience/guardian.py) -------------------------
    def record_range(self, nbatch):
        """(source, lo, hi) describing where batch `nbatch` of this epoch
        draws its records from — the training guardian's shard
        attribution for quarantine entries and TrainingDivergedError.
        None when the iterator cannot attribute (the default)."""
        return None


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (reference `io.py:546 NDArrayIter`):
    supports dict/list inputs, shuffle, pad/discard/roll_over last batch."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True, default_name=label_name)
        self.idx = _np.arange(self.data[0][1].shape[0])
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.batch_size = batch_size
        self.cursor = -batch_size
        self.num_data = self.idx.shape[0]
        if last_batch_handle == "discard":
            self.num_batches = self.num_data // batch_size
        else:
            self.num_batches = (self.num_data + batch_size - 1) // batch_size
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def reset(self):
        if self.shuffle:
            _np.random.shuffle(self.idx)
        if self.last_batch_handle == "roll_over" and \
                -self.batch_size < self.cursor < 0:
            self.cursor = self.num_data + self.cursor
        else:
            self.cursor = -self.batch_size
        # epoch-start cursor: batch n of THIS epoch begins at
        # _epoch_cursor0 + (n+1)*batch_size — under roll_over the epoch
        # carries leftover samples, so batches are NOT aligned to
        # n*batch_size and seek() must anchor here
        self._epoch_cursor0 = self.cursor

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def next(self):
        if not self.iter_next():
            raise StopIteration
        return DataBatch(data=self.getdata(), label=self.getlabel(),
                         pad=self.getpad(), index=None,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)

    def _getdata(self, data_source):
        assert self.cursor < self.num_data
        end = self.cursor + self.batch_size
        if end <= self.num_data:
            sel = self.idx[self.cursor:end]
            # keep the source dtype so batches match provide_data/provide_label
            # (reference converts once at construction)
            return [array(v[sel], dtype=v.dtype) for _, v in data_source]
        if self.last_batch_handle == "discard":
            raise StopIteration
        pad = end - self.num_data
        sel = _np.concatenate([self.idx[self.cursor:], self.idx[:pad]])
        return [array(v[sel], dtype=v.dtype) for _, v in data_source]

    def getdata(self):
        return self._getdata(self.data)

    def getlabel(self):
        return self._getdata(self.label) if self.label else []

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0

    def seek(self, nbatch):
        """Native seek: pure cursor math, no data touched (iter_next
        advances the cursor before the bounds check).  Anchored at the
        epoch-start cursor so roll_over epochs — which begin mid-stride
        with carried samples — seek to the same windows the interrupted
        run walked."""
        self.cursor = self._epoch_cursor0 + int(nbatch) * self.batch_size

    def checkpoint_state(self):
        # the shuffle permutation IS the epoch: without it, resume after a
        # shuffled epoch would walk a different batch order than the run
        # it is continuing; the epoch-start cursor carries roll_over's
        # mid-stride alignment
        return {"idx": self.idx.copy(),
                "epoch_cursor0": int(self._epoch_cursor0)}

    def set_checkpoint_state(self, state, nbatch=0):
        idx = state.get("idx")
        if idx is not None:
            idx = _np.asarray(idx)
            if idx.shape != self.idx.shape:
                raise MXNetError(
                    f"checkpoint iterator order has {idx.shape[0]} samples, "
                    f"this iterator has {self.idx.shape[0]} — resuming "
                    "against a different dataset?")
            self.idx = idx
        if "epoch_cursor0" in state:
            self._epoch_cursor0 = int(state["epoch_cursor0"])
        self.seek(nbatch)

    def record_range(self, nbatch):
        """Sample-index window batch `nbatch` of this epoch draws from
        (guardian shard attribution; the shuffle permutation maps the
        window onto actual rows).  Batch n's data starts at the
        epoch-start cursor plus (n+1) strides (see `seek`)."""
        lo = max(self._epoch_cursor0 + (int(nbatch) + 1) * self.batch_size,
                 0)
        return ("ndarray", lo, min(lo + self.batch_size, self.num_data))


def _init_data(data, allow_empty, default_name):
    """Normalize to [(name, np.ndarray)] (reference `io.py _init_data`)."""
    if data is None:
        if not allow_empty:
            raise ValueError("Data must be provided")
        return []
    if isinstance(data, (NDArray, _np.ndarray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        if not allow_empty and len(data) == 0:
            raise ValueError("Data must not be empty")
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {f"_{i}_{default_name}": d for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise TypeError("Input must be NDArray, numpy.ndarray, list or dict")
    out = []
    for k, v in data.items():
        if isinstance(v, NDArray):
            v = v.asnumpy()
        out.append((k, _np.asarray(v)))
    return out


class ResizeIter(DataIter):
    """Resize an iterator to a fixed number of batches (reference
    `io.py:ResizeIter`)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__()
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        self.batch_size = data_iter.batch_size

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Thread-prefetching wrapper (reference `io.py:349 PrefetchingIter`,
    C++ `iter_prefetcher.h`): producer threads pull from the underlying
    iterators while the consumer trains — host-side pipelining that the
    reference implements with dmlc ThreadedIter."""

    def __init__(self, iters, rename_data=None, rename_label=None,
                 prefetch_depth=2):
        super().__init__()
        if not isinstance(iters, list):
            iters = [iters]
        self.n_iter = len(iters)
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self.batch_size = self.provide_data[0].shape[0]
        self._queue = _queue.Queue(maxsize=prefetch_depth)
        self._stop = threading.Event()
        self._thread = None
        self._start()

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     if isinstance(r, dict) else x
                     for x in i.provide_data]
                    for r, i in zip(self.rename_data, self.iters)], [])

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     if isinstance(r, dict) else x
                     for x in i.provide_label]
                    for r, i in zip(self.rename_label, self.iters)], [])

    def _producer(self):
        while not self._stop.is_set():
            try:
                batches = [i.next() for i in self.iters]
            except StopIteration:
                self._queue.put(None)
                return
            self._queue.put(batches)

    def _start(self):
        self._stop.clear()
        self._thread = threading.Thread(target=self._producer, daemon=True,
                                        name="mx-io-prefetch")
        self._thread.start()

    def reset(self):
        self._stop.set()
        try:
            while True:
                self._queue.get_nowait()
        except _queue.Empty:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5)
        for i in self.iters:
            i.reset()
        self._start()

    def next(self):
        batches = self._queue.get()
        if batches is None:
            raise StopIteration
        data = sum([b.data for b in batches], [])
        label = sum([(b.label or []) for b in batches], [])
        return DataBatch(data=data, label=label, pad=batches[0].pad,
                         index=batches[0].index,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)

    def iter_next(self):
        try:
            self._cached = self.next()
            return True
        except StopIteration:
            return False


class CSVIter(DataIter):
    """Reference `src/io/iter_csv.cc:218` re-expressed in the host pipeline."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, **kwargs):
        super().__init__(batch_size)
        data = _np.loadtxt(data_csv, delimiter=",", ndmin=2, dtype="float32")
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = _np.loadtxt(label_csv, delimiter=",", ndmin=2,
                                dtype="float32")
            label = label.reshape((-1,) + tuple(label_shape))
            if label_shape == (1,):
                label = label.reshape(-1)
        else:
            label = _np.zeros(data.shape[0], dtype="float32")
        self._inner = NDArrayIter(data, label, batch_size,
                                  last_batch_handle="pad" if round_batch
                                  else "discard", label_name="label")

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()


class MNISTIter(DataIter):
    """Reference `src/io/iter_mnist.cc:260`: reads idx-format MNIST files."""

    def __init__(self, image, label, batch_size=128, shuffle=True, flat=False,
                 silent=False, seed=None, **kwargs):
        super().__init__(batch_size)
        imgs = _read_idx_images(image)
        lbls = _read_idx_labels(label)
        imgs = imgs.astype("float32") / 255.0
        if flat:
            imgs = imgs.reshape(imgs.shape[0], -1)
        else:
            imgs = imgs.reshape(imgs.shape[0], 1, imgs.shape[1], imgs.shape[2])
        self._inner = NDArrayIter(imgs, lbls.astype("float32"), batch_size,
                                  shuffle=shuffle, last_batch_handle="discard")

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()


def _read_idx_images(path):
    import gzip
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        if magic != 2051:
            raise MXNetError(f"bad MNIST image magic {magic}")
        return _np.frombuffer(f.read(n * rows * cols),
                              dtype=_np.uint8).reshape(n, rows, cols)


def _read_idx_labels(path):
    import gzip
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        if magic != 2049:
            raise MXNetError(f"bad MNIST label magic {magic}")
        return _np.frombuffer(f.read(n), dtype=_np.uint8)


def ImageRecordIter(**kwargs):
    """Reference `src/io/iter_image_recordio_2.cc` (param-compatible factory).
    Implemented over the RecordIO reader + host decode/augment pool in
    `image.py`; see that module for the pipeline."""
    from .image import ImageRecordIterImpl
    return ImageRecordIterImpl(**kwargs)


def ImageRecordIter_v1(**kwargs):
    return ImageRecordIter(**kwargs)


class LibSVMIter(DataIter):
    """Reference `src/io/iter_libsvm.cc:200`: batches from libsvm-format
    text (`label idx:val idx:val ...`).  Data batches are CSR
    (`ndarray.sparse.CSRNDArray`, the host-resident shell — SURVEY §7(d));
    labels are dense unless a separate `label_libsvm` file with a
    multi-dimensional `label_shape` is given, in which case they are CSR
    too, matching the reference's storage types."""

    def __init__(self, data_libsvm, data_shape, label_libsvm=None,
                 label_shape=(1,), batch_size=1, round_batch=True,
                 **kwargs):
        super().__init__(batch_size)
        self._data_shape = tuple(data_shape)
        self._label_shape = tuple(label_shape) \
            if not isinstance(label_shape, int) else (int(label_shape),)
        self._round_batch = round_batch
        vals, idxs, ptr, labels = self._parse(data_libsvm,
                                              self._data_shape[0])
        self._vals, self._idxs, self._ptr = vals, idxs, ptr
        if label_libsvm is not None:
            lv, li, lp, _ = self._parse(label_libsvm, self._label_shape[0])
            self._lvals, self._lidxs, self._lptr = lv, li, lp
            self._labels = None
        else:
            # inline labels: every leading non-feature field, laid out to
            # label_shape width (reference LibSVMIter label_width)
            k = 1 if self._label_shape == (1,) else self._label_shape[0]
            lab = _np.zeros((len(labels), k), dtype="float32")
            for i, row in enumerate(labels):
                if row:
                    lab[i, :min(len(row), k)] = row[:k]
            self._labels = lab[:, 0] if k == 1 else lab
            self._lvals = None
        self._n = len(ptr) - 1
        self._cur = 0

    @staticmethod
    def _parse(path, width):
        vals, idxs, ptr, labels = [], [], [0], []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = line.split()
                # leading fields without ':' are labels (possibly several)
                i = 0
                lab = []
                while i < len(parts) and ":" not in parts[i]:
                    lab.append(float(parts[i]))
                    i += 1
                labels.append(lab)
                for tok in parts[i:]:
                    k, v = tok.split(":")
                    if int(k) >= width:
                        raise MXNetError(
                            f"LibSVMIter: feature index {k} >= data_shape "
                            f"width {width}")
                    idxs.append(int(k))
                    vals.append(float(v))
                ptr.append(len(vals))
        return (_np.asarray(vals, "float32"), _np.asarray(idxs, _np.int64),
                _np.asarray(ptr, _np.int64), labels)

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size, self._data_shape[0]))]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self._label_shape == (1,) else \
            (self.batch_size,) + self._label_shape
        return [DataDesc("softmax_label", shape)]

    def reset(self):
        self._cur = 0

    @staticmethod
    def _csr_rows(vals, idxs, ptr, ranges, width):
        """CSR batch over concatenated [lo, hi) row ranges — pure pointer
        splicing, never densified (libsvm feature widths are often huge)."""
        from .ndarray.sparse import CSRNDArray
        v_parts, i_parts, new_ptr = [], [], [0]
        n = 0
        for lo, hi in ranges:
            seg = ptr[lo:hi + 1]
            v_parts.append(vals[seg[0]:seg[-1]])
            i_parts.append(idxs[seg[0]:seg[-1]])
            base = new_ptr[-1] - seg[0]
            new_ptr.extend((seg[1:] + base).tolist())
            n += hi - lo
        return CSRNDArray(
            _np.concatenate(v_parts) if v_parts else vals[:0],
            _np.concatenate(i_parts) if i_parts else idxs[:0],
            _np.asarray(new_ptr, _np.int64), (n, width))

    def next(self):
        if self._cur >= self._n:
            raise StopIteration
        lo = self._cur
        hi = min(lo + self.batch_size, self._n)
        pad = self.batch_size - (hi - lo)
        if pad and not self._round_batch:
            raise StopIteration
        self._cur = hi
        # reference round_batch: the tail wraps rows from the epoch start
        ranges = [(lo, hi)] + ([(0, pad)] if pad else [])
        data = self._csr_rows(self._vals, self._idxs, self._ptr, ranges,
                              self._data_shape[0])
        if self._labels is not None:
            lab = self._labels[lo:hi]
            if pad:
                lab = _np.concatenate([lab, self._labels[:pad]])
            label = array(lab)
        else:
            label = self._csr_rows(self._lvals, self._lidxs, self._lptr,
                                   ranges, self._label_shape[0])
        return DataBatch(data=[data], label=[label], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)
