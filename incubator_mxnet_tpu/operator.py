"""Custom operators in Python (reference `python/mxnet/operator.py`, backend
`src/operator/custom/custom.cc` CustomOperator).

`CustomOp`/`CustomOpProp` + `register` keep the reference API: user forward/
backward callbacks run on the host.  In the reference these run on a
dedicated worker pool so engine threads never block (`custom-inl.h:50-148`);
here they run eagerly at dispatch (JAX async dispatch continues around them)
and are recorded on the autograd tape so gradients flow through the custom
backward.  Inside jit-compiled graphs custom ops are not traceable — same
restriction as TensorRT/subgraph partitioning in the reference, where custom
ops stay outside fused subgraphs.
"""
from __future__ import annotations

from .base import MXNetError
from .ndarray.ndarray import NDArray
from . import ndarray as nd
from . import autograd

__all__ = ["CustomOp", "CustomOpProp", "register", "get_all_registered_operators"]

_CUSTOM_REGISTRY = {}


class CustomOp:
    """Base custom operator (reference `operator.py:CustomOp`)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        if req == "null":
            return
        if req in ("write", "inplace"):
            dst._set_data(src._data if isinstance(src, NDArray) else src)
        elif req == "add":
            dst._set_data(dst._data + (src._data if isinstance(src, NDArray)
                                       else src))


class CustomOpProp:
    """Operator properties (reference `operator.py:CustomOpProp`)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), []

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes):
        raise NotImplementedError


def register(reg_name):
    """Register a CustomOpProp class (reference `operator.py register`)."""
    def do_register(prop_cls):
        _CUSTOM_REGISTRY[reg_name] = prop_cls
        return prop_cls
    return do_register


def get_all_registered_operators():
    return list(_CUSTOM_REGISTRY)


class _CustomFunction(autograd.Function):
    """Bridge a CustomOp instance onto the autograd tape."""

    def __init__(self, op, prop, n_out, n_in, is_train=False):
        super().__init__()
        self._op = op
        self._prop = prop
        self._n_out = n_out
        self._n_in = n_in
        self._is_train = is_train

    def forward(self, *inputs):
        out_shapes = self._prop.infer_shape([list(i.shape) for i in inputs])[1]
        outputs = [nd.zeros(tuple(s), ctx=inputs[0].context)
                   for s in out_shapes]
        self._op.forward(is_train=self._is_train,
                         req=["write"] * len(outputs),
                         in_data=list(inputs), out_data=outputs, aux=[])
        self.save_for_backward(list(inputs), outputs)
        return outputs[0] if len(outputs) == 1 else tuple(outputs)

    def backward(self, *out_grads):
        inputs, outputs = self.saved_tensors
        in_grads = [nd.zeros(i.shape, ctx=i.context) for i in inputs]
        self._op.backward(req=["write"] * len(in_grads),
                          out_grad=list(out_grads), in_data=inputs,
                          out_data=outputs, in_grad=in_grads, aux=[])
        return in_grads[0] if len(in_grads) == 1 else tuple(in_grads)


def invoke_custom(op_type, *inputs, **kwargs):
    """Run a registered custom op eagerly (`mx.nd.Custom` equivalent)."""
    if op_type not in _CUSTOM_REGISTRY:
        raise MXNetError(f"Custom operator {op_type} is not registered "
                         f"(available: {get_all_registered_operators()})")
    prop = _CUSTOM_REGISTRY[op_type](**{k: str(v) for k, v in kwargs.items()})
    op = prop.create_operator(inputs[0].context,
                              [list(i.shape) for i in inputs],
                              [i.dtype for i in inputs])
    fn = _CustomFunction(op, prop, len(prop.list_outputs()), len(inputs),
                         is_train=autograd.is_training())
    return fn(*inputs)


def _attach_nd_custom():
    """Expose nd.Custom(*data, op_type=...) like the reference."""
    def Custom(*data, **kwargs):
        op_type = kwargs.pop("op_type")
        return invoke_custom(op_type, *data, **kwargs)
    nd.Custom = Custom


_attach_nd_custom()
