"""Legacy symbolic RNN API (reference `python/mxnet/rnn/`): cell classes
that unroll into Symbol graphs, plus `BucketSentenceIter` for
variable-length corpora.  The modern path is `gluon.rnn`; this package
exists for reference-API parity (`example/rnn/bucketing`)."""
from .rnn_cell import (BaseRNNCell, RNNCell, LSTMCell, GRUCell,
                       FusedRNNCell, SequentialRNNCell, DropoutCell,
                       ZoneoutCell, ResidualCell)
from .io import BucketSentenceIter, encode_sentences

__all__ = ["BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell", "FusedRNNCell",
           "SequentialRNNCell", "DropoutCell", "ZoneoutCell", "ResidualCell",
           "BucketSentenceIter", "encode_sentences"]
