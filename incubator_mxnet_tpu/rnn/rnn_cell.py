"""Symbolic RNN cells (reference `python/mxnet/rnn/rnn_cell.py`).

Each cell composes Symbol ops; `unroll` builds the time-major graph that
BucketingModule jit-compiles once per bucket length.  On TPU the unrolled
graph is a single XLA program — for long sequences prefer
`FusedRNNCell`, which lowers to the framework's `RNN` operator
(`ops/nn.py`), i.e. one `lax.scan` the compiler can pipeline, rather than
T separate cell bodies.
"""
from __future__ import annotations

from ..base import MXNetError
from .. import symbol as sym


class BaseRNNCell:
    """Reference `rnn_cell.py:BaseRNNCell`."""

    def __init__(self, prefix="", params=None):
        self._prefix = prefix
        self._own_params = params is None
        self._params = params if params is not None else _RNNParams(prefix)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1

    @property
    def params(self):
        return self._params

    @property
    def state_info(self):
        raise NotImplementedError

    @property
    def _gate_names(self):
        return ()

    def state_shape(self):
        return [info["shape"] for info in self.state_info]

    def begin_state(self, func=None, **kwargs):
        """Initial-state symbols.  Default: plain Variables the executor
        zero-fills (the reference uses `sym.zeros`; a Variable keeps the
        bucketed graph's input list explicit)."""
        assert not self._modified
        states = []
        for info in self.state_info:
            self._init_counter += 1
            name = f"{self._prefix}begin_state_{self._init_counter}"
            if func is None:
                state = sym.Variable(name, shape=info.get("shape"),
                                     init='["zero", {}]',
                                     __layout__=info.get("__layout__"))
            else:
                state = func(name=name, **info, **kwargs)
            states.append(state)
        return states

    def __call__(self, inputs, states):
        raise NotImplementedError

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        """Reference `BaseRNNCell.unroll`: returns (outputs, states).

        A merged-output unroll over a symbolic sequence emits ONE
        `_foreach` node (`lax.scan` in the compiled program) instead of T
        copies of the cell body — so a bucketed LSTM graph's size is
        independent of sequence length.  Cells whose body cannot scan
        fall back to the classic static unroll."""
        self.reset()
        axis = layout.find("T")
        if isinstance(inputs, sym.Symbol) and merge_outputs:
            if begin_state is None:
                begin_state = self.begin_state()
            try:
                from ..symbol.contrib import foreach_unroll
                return foreach_unroll(lambda x, st: self(x, st), inputs,
                                      begin_state, layout, length)
            except Exception:
                self.reset()   # e.g. aux-state layers: static unroll
        if isinstance(inputs, sym.Symbol):
            inputs = sym.split(inputs, num_outputs=length, axis=axis,
                               squeeze_axis=1)
            inputs = [inputs[i] for i in range(length)]
        if begin_state is None:
            begin_state = self.begin_state()
        states = begin_state
        outputs = []
        for i in range(length):
            out, states = self(inputs[i], states)
            outputs.append(out)
        if merge_outputs:
            outputs = sym.concat(*[sym.expand_dims(o, axis=axis)
                                   for o in outputs], dim=axis)
        return outputs, states

    def _get_weight(self, name, **kwargs):
        return self._params.get(f"{self._prefix}{name}", **kwargs)


class _RNNParams:
    def __init__(self, prefix):
        self._prefix = prefix
        self._params = {}

    def get(self, name, **kwargs):
        if name not in self._params:
            self._params[name] = sym.Variable(name, **kwargs)
        return self._params[name]


class RNNCell(BaseRNNCell):
    """tanh Elman cell (reference `rnn_cell.py:RNNCell`)."""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_",
                 params=None):
        super().__init__(prefix, params)
        self._num_hidden = num_hidden
        self._activation = activation

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    def __call__(self, inputs, states):
        self._counter += 1
        name = f"{self._prefix}t{self._counter}_"
        i2h = sym.FullyConnected(inputs, self._get_weight("i2h_weight"),
                                 self._get_weight("i2h_bias"),
                                 num_hidden=self._num_hidden,
                                 name=f"{name}i2h")
        h2h = sym.FullyConnected(states[0], self._get_weight("h2h_weight"),
                                 self._get_weight("h2h_bias"),
                                 num_hidden=self._num_hidden,
                                 name=f"{name}h2h")
        output = sym.Activation(i2h + h2h, act_type=self._activation,
                                name=f"{name}out")
        return output, [output]


class LSTMCell(BaseRNNCell):
    """Reference `rnn_cell.py:LSTMCell`."""

    def __init__(self, num_hidden, prefix="lstm_", params=None,
                 forget_bias=1.0):
        super().__init__(prefix, params)
        self._num_hidden = num_hidden
        self._forget_bias = forget_bias

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"},
                {"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_i", "_f", "_c", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = f"{self._prefix}t{self._counter}_"
        # forget_bias is applied through the i2h_bias initializer
        # (reference init.LSTMBias) rather than an inline graph term, so
        # reference-trained checkpoints — whose saved bias already absorbed
        # it — load without shifting the forget gate
        import json as _json
        i2h_bias = self._get_weight(
            "i2h_bias",
            init=_json.dumps(["lstmbias",
                              {"forget_bias": self._forget_bias}]))
        i2h = sym.FullyConnected(inputs, self._get_weight("i2h_weight"),
                                 i2h_bias,
                                 num_hidden=4 * self._num_hidden,
                                 name=f"{name}i2h")
        h2h = sym.FullyConnected(states[0], self._get_weight("h2h_weight"),
                                 self._get_weight("h2h_bias"),
                                 num_hidden=4 * self._num_hidden,
                                 name=f"{name}h2h")
        gates = i2h + h2h
        slices = sym.split(gates, num_outputs=4, axis=1)
        in_gate = sym.Activation(slices[0], act_type="sigmoid")
        forget_gate = sym.Activation(slices[1], act_type="sigmoid")
        in_trans = sym.Activation(slices[2], act_type="tanh")
        out_gate = sym.Activation(slices[3], act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_trans
        next_h = out_gate * sym.Activation(next_c, act_type="tanh")
        return next_h, [next_h, next_c]


class GRUCell(BaseRNNCell):
    """Reference `rnn_cell.py:GRUCell`."""

    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(prefix, params)
        self._num_hidden = num_hidden

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    def __call__(self, inputs, states):
        self._counter += 1
        name = f"{self._prefix}t{self._counter}_"
        i2h = sym.FullyConnected(inputs, self._get_weight("i2h_weight"),
                                 self._get_weight("i2h_bias"),
                                 num_hidden=3 * self._num_hidden,
                                 name=f"{name}i2h")
        h2h = sym.FullyConnected(states[0], self._get_weight("h2h_weight"),
                                 self._get_weight("h2h_bias"),
                                 num_hidden=3 * self._num_hidden,
                                 name=f"{name}h2h")
        i2h_s = sym.split(i2h, num_outputs=3, axis=1)
        h2h_s = sym.split(h2h, num_outputs=3, axis=1)
        reset = sym.Activation(i2h_s[0] + h2h_s[0], act_type="sigmoid")
        update = sym.Activation(i2h_s[1] + h2h_s[1], act_type="sigmoid")
        cand = sym.Activation(i2h_s[2] + reset * h2h_s[2], act_type="tanh")
        next_h = update * states[0] + (1.0 - update) * cand
        return next_h, [next_h]


class FusedRNNCell(BaseRNNCell):
    """Whole-sequence cell lowering to the `RNN` op — the cuDNN fused path
    of the reference (`rnn_cell.py:FusedRNNCell`), here one `lax.scan`
    XLA program over the sequence."""

    def __init__(self, num_hidden, num_layers=1, mode="lstm",
                 bidirectional=False, dropout=0.0, prefix=None, params=None):
        prefix = f"{mode}_" if prefix is None else prefix
        super().__init__(prefix, params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout

    @property
    def state_info(self):
        d = 2 if self._bidirectional else 1
        info = [{"shape": (self._num_layers * d, 0, self._num_hidden),
                 "__layout__": "LNC"}]
        if self._mode == "lstm":
            info.append({"shape": (self._num_layers * d, 0,
                                   self._num_hidden),
                         "__layout__": "LNC"})
        return info

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        if isinstance(inputs, (list, tuple)):
            axis = layout.find("T")
            inputs = sym.concat(*[sym.expand_dims(i, axis=axis)
                                  for i in inputs], dim=axis)
        if layout == "NTC":
            inputs = sym.transpose(inputs, axes=(1, 0, 2))   # RNN op is TNC
        if begin_state is None:
            begin_state = self.begin_state()
        states = list(begin_state)
        args = [inputs, self._get_weight("parameters"), states[0]]
        if self._mode == "lstm":
            args.append(states[1])
        out = sym.RNN(*args, state_size=self._num_hidden,
                      num_layers=self._num_layers, mode=self._mode,
                      bidirectional=self._bidirectional, p=self._dropout,
                      state_outputs=True,
                      name=f"{self._prefix}rnn")
        outputs = out[0]
        if layout == "NTC":
            outputs = sym.transpose(outputs, axes=(1, 0, 2))
        n_states = len(self.state_info)
        new_states = [out[1 + i] for i in range(n_states)]
        if merge_outputs is False:
            outputs = [o for o in sym.split(outputs, num_outputs=length,
                                            axis=layout.find("T"),
                                            squeeze_axis=1)]
        return outputs, new_states

    def unfuse(self):
        """Reference `FusedRNNCell.unfuse`: equivalent stacked plain cells."""
        stack = SequentialRNNCell()
        get = {"rnn_tanh": lambda p: RNNCell(self._num_hidden, "tanh", p),
               "rnn_relu": lambda p: RNNCell(self._num_hidden, "relu", p),
               "lstm": lambda p: LSTMCell(self._num_hidden, p),
               "gru": lambda p: GRUCell(self._num_hidden, p)}[self._mode]
        for i in range(self._num_layers):
            stack.add(get(f"{self._prefix}l{i}_"))
            if self._dropout > 0 and i < self._num_layers - 1:
                stack.add(DropoutCell(self._dropout,
                                      prefix=f"{self._prefix}_dropout{i}_"))
        return stack


class SequentialRNNCell(BaseRNNCell):
    """Reference `rnn_cell.py:SequentialRNNCell`."""

    def __init__(self, params=None):
        super().__init__("", params)
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)
        return self

    @property
    def state_info(self):
        return [info for c in self._cells for info in c.state_info]

    def begin_state(self, **kwargs):
        return [s for c in self._cells for s in c.begin_state(**kwargs)]

    def __call__(self, inputs, states):
        next_states = []
        pos = 0
        for cell in self._cells:
            n = len(cell.state_info)
            inputs, st = cell(inputs, states[pos:pos + n])
            pos += n
            next_states.extend(st)
        return inputs, next_states


class DropoutCell(BaseRNNCell):
    """Reference `rnn_cell.py:DropoutCell`."""

    def __init__(self, dropout, prefix="dropout_", params=None):
        super().__init__(prefix, params)
        self._dropout = dropout

    @property
    def state_info(self):
        return []

    def __call__(self, inputs, states):
        if self._dropout > 0:
            inputs = sym.Dropout(inputs, p=self._dropout)
        return inputs, states


class ZoneoutCell(BaseRNNCell):
    """Reference `rnn_cell.py:ZoneoutCell` (state-preserving dropout)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        super().__init__(base_cell._prefix + "zoneout_", base_cell.params)
        self.base_cell = base_cell
        self._zo = zoneout_outputs
        self._zs = zoneout_states
        self._prev_output = None

    def reset(self):
        super().reset()
        if hasattr(self, "base_cell"):
            self.base_cell.reset()
        # forget cross-graph state: a fresh unroll (e.g. the next bucket's
        # graph) must not reference the previous graph's output symbols
        self._prev_output = None

    @property
    def state_info(self):
        return self.base_cell.state_info

    def begin_state(self, **kwargs):
        return self.base_cell.begin_state(**kwargs)

    @staticmethod
    def _binary_mask(like, p):
        # Dropout emits {0, 1/(1-p)} (inverted dropout); scale back to a
        # true 0/1 keep-mask so the convex blend keeps magnitudes intact
        return sym.Dropout(sym.ones_like(like), p=p) * (1.0 - p)

    def __call__(self, inputs, states):
        out, next_states = self.base_cell(inputs, states)
        if self._zo > 0:
            mask = self._binary_mask(out, self._zo)
            prev = self._prev_output if self._prev_output is not None \
                else sym.zeros_like(out)
            out = mask * out + (1.0 - mask) * prev
        self._prev_output = out
        if self._zs > 0:
            blended = []
            for ns, s in zip(next_states, states):
                mask = self._binary_mask(ns, self._zs)  # ONE mask per state
                blended.append(mask * ns + (1.0 - mask) * s)
            next_states = blended
        return out, next_states


class ResidualCell(BaseRNNCell):
    """Reference `rnn_cell.py:ResidualCell`."""

    def __init__(self, base_cell):
        super().__init__(base_cell._prefix + "residual_", base_cell.params)
        self.base_cell = base_cell

    @property
    def state_info(self):
        return self.base_cell.state_info

    def begin_state(self, **kwargs):
        return self.base_cell.begin_state(**kwargs)

    def __call__(self, inputs, states):
        out, next_states = self.base_cell(inputs, states)
        return out + inputs, next_states
