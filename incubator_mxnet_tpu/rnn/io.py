"""BucketSentenceIter (reference `python/mxnet/rnn/io.py`): group
variable-length sequences into length buckets; BucketingModule compiles
one XLA program per bucket (`module/bucketing_module.py`) instead of one
per length — the TPU answer to dynamic shapes."""
from __future__ import annotations

import random as _pyrandom

import numpy as np

from ..base import MXNetError
from ..io import DataIter, DataBatch, DataDesc
from ..ndarray.ndarray import array


def encode_sentences(sentences, vocab=None, invalid_label=-1,
                     invalid_key="\n", start_label=0):
    """Reference `rnn/io.py encode_sentences`: build/extend a vocab."""
    idx = start_label
    if vocab is None:
        vocab = {invalid_key: invalid_label}
        new_vocab = True
    else:
        new_vocab = False
    res = []
    for sent in sentences:
        coded = []
        for word in sent:
            if word not in vocab:
                if not new_vocab:
                    raise MXNetError(f"Unknown token {word}")
                if idx == invalid_label:
                    idx += 1
                vocab[word] = idx
                idx += 1
            coded.append(vocab[word])
        res.append(coded)
    return res, vocab


class BucketSentenceIter(DataIter):
    """Reference `rnn/io.py:BucketSentenceIter`."""

    def __init__(self, sentences, batch_size, buckets=None,
                 invalid_label=-1, data_name="data",
                 label_name="softmax_label", dtype="float32",
                 layout="NT"):
        super().__init__(batch_size)
        if buckets is None:
            lens = np.bincount([len(s) for s in sentences])
            buckets = [i for i, n in enumerate(lens)
                       if n >= batch_size]
        buckets.sort()
        self.buckets = buckets
        self.data = [[] for _ in buckets]
        for sent in sentences:
            buck = np.searchsorted(buckets, len(sent))
            if buck == len(buckets):
                continue
            buff = np.full((buckets[buck],), invalid_label, dtype=dtype)
            buff[:len(sent)] = sent
            self.data[buck].append(buff)
        self.data = [np.asarray(x, dtype=dtype) for x in self.data]

        self.batch_size = batch_size
        self.invalid_label = invalid_label
        self.data_name = data_name
        self.label_name = label_name
        self.dtype = dtype
        self.layout = layout
        self.default_bucket_key = max(buckets)

        shape = ((batch_size, self.default_bucket_key)
                 if layout == "NT" else (self.default_bucket_key, batch_size))
        self.provide_data = [DataDesc(data_name, shape, dtype,
                                      layout=layout)]
        self.provide_label = [DataDesc(label_name, shape, dtype,
                                       layout=layout)]
        self.idx = []
        for i, buck in enumerate(self.data):
            self.idx.extend((i, j) for j in
                            range(0, len(buck) - batch_size + 1, batch_size))
        self.curr_idx = 0
        self.reset()

    def reset(self):
        self.curr_idx = 0
        _pyrandom.shuffle(self.idx)
        for buck in self.data:
            np.random.shuffle(buck)

    def next(self):
        if self.curr_idx == len(self.idx):
            raise StopIteration
        i, j = self.idx[self.curr_idx]
        self.curr_idx += 1
        data = self.data[i][j:j + self.batch_size]
        label = np.empty_like(data)
        label[:, :-1] = data[:, 1:]
        label[:, -1] = self.invalid_label
        if self.layout == "TN":
            data = data.T
            label = label.T
        shape = data.shape
        return DataBatch([array(data, dtype=self.dtype)],
                         [array(label, dtype=self.dtype)],
                         pad=0, bucket_key=self.buckets[i],
                         provide_data=[DataDesc(self.data_name, shape,
                                                self.dtype,
                                                layout=self.layout)],
                         provide_label=[DataDesc(self.label_name, shape,
                                                 self.dtype,
                                                 layout=self.layout)])
