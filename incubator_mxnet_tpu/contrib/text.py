"""Text vocab/embedding utilities (reference `python/mxnet/contrib/text/`).

Vocabulary + token indexing; pretrained embedding download is unavailable
(zero egress) but `CustomEmbedding` loads local files.
"""
from __future__ import annotations

import collections

import numpy as np

from ..base import MXNetError
from .. import ndarray as nd


class Vocabulary:
    """Token vocabulary (reference `contrib/text/vocab.py`)."""

    def __init__(self, counter=None, most_freq_count=None, min_freq=1,
                 unknown_token="<unk>", reserved_tokens=None):
        assert min_freq > 0
        self._unknown_token = unknown_token
        reserved_tokens = list(reserved_tokens or [])
        self._idx_to_token = [unknown_token] + reserved_tokens
        self._token_to_idx = {t: i for i, t in enumerate(self._idx_to_token)}
        if counter is not None:
            pairs = sorted(counter.items(), key=lambda kv: (-kv[1], kv[0]))
            if most_freq_count is not None:
                pairs = pairs[:most_freq_count]
            for tok, freq in pairs:
                if freq >= min_freq and tok not in self._token_to_idx:
                    self._token_to_idx[tok] = len(self._idx_to_token)
                    self._idx_to_token.append(tok)

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def unknown_token(self):
        return self._unknown_token

    def to_indices(self, tokens):
        single = isinstance(tokens, str)
        if single:
            tokens = [tokens]
        out = [self._token_to_idx.get(t, 0) for t in tokens]
        return out[0] if single else out

    def to_tokens(self, indices):
        single = isinstance(indices, int)
        if single:
            indices = [indices]
        out = [self._idx_to_token[i] for i in indices]
        return out[0] if single else out


def count_tokens_from_str(source_str, token_delim=" ", seq_delim="\n",
                          to_lower=False, counter_to_update=None):
    """Reference `contrib/text/utils.py count_tokens_from_str`."""
    if to_lower:
        source_str = source_str.lower()
    counter = counter_to_update if counter_to_update is not None \
        else collections.Counter()
    for seq in source_str.split(seq_delim):
        counter.update(t for t in seq.split(token_delim) if t)
    return counter


class CustomEmbedding:
    """Token embedding from a local file of 'token v1 v2 ...' lines
    (reference `contrib/text/embedding.py CustomEmbedding`)."""

    def __init__(self, pretrained_file_path, elem_delim=" ", encoding="utf8",
                 vocabulary=None):
        tokens = []
        vecs = []
        with open(pretrained_file_path, encoding=encoding) as f:
            for line in f:
                parts = line.rstrip().split(elem_delim)
                if len(parts) < 2:
                    continue
                tokens.append(parts[0])
                vecs.append([float(x) for x in parts[1:]])
        dim = len(vecs[0])
        self._token_to_idx = {}
        rows = [np.zeros(dim, dtype="float32")]  # unk row
        self._idx_to_token = ["<unk>"]
        for tok, vec in zip(tokens, vecs):
            if vocabulary is not None and tok not in vocabulary.token_to_idx:
                continue
            self._token_to_idx[tok] = len(self._idx_to_token)
            self._idx_to_token.append(tok)
            rows.append(np.asarray(vec, dtype="float32"))
        self._mat = np.stack(rows)

    @property
    def vec_len(self):
        return self._mat.shape[1]

    def get_vecs_by_tokens(self, tokens):
        single = isinstance(tokens, str)
        if single:
            tokens = [tokens]
        idx = [self._token_to_idx.get(t, 0) for t in tokens]
        out = nd.array(self._mat[idx])
        return out[0] if single else out
