"""INT8 model quantization (reference `python/mxnet/contrib/quantization.py`
`quantize_model:412` + C++ `quantize_graph_pass.cc`).

Graph rewrite: walk a Symbol and replace quantizable FullyConnected nodes
with quantize → int8 matmul → dequantize chains; weights are pre-quantized
into the returned params with their ranges.  Calibration: 'none' (dynamic
per-batch ranges) or 'naive' (min/max over calibration batches).  INT8
matmuls lower through XLA's integer dot support on TPU.
"""
from __future__ import annotations

import logging

import numpy as np

from ..base import MXNetError
from .. import ndarray as nd

QUANTIZABLE = {"FullyConnected", "Convolution", "Pooling"}


def _smooth_distribution(d, eps=0.0001):
    """Move epsilon mass onto zero bins so KL stays finite (the reference's
    `_smooth_distribution`, itself the TensorRT calibration recipe)."""
    is_zero = d == 0
    n_zero = int(is_zero.sum())
    n_nonzero = d.size - n_zero
    if n_nonzero == 0:
        return None
    d = d.astype(np.float64)
    if n_zero:
        d[is_zero] = eps
        d[~is_zero] -= eps * n_zero / n_nonzero
        if (d[~is_zero] <= 0).any():
            return None
    return d / d.sum()


_NUM_BINS = 8001


def _merge_histograms(parts):
    """Rebin per-batch histograms (each over its own symmetric range) onto
    the widest range.  Bin centers are reassigned by linear index scaling —
    the small rebinned blur is irrelevant to a threshold search."""
    absmax = max(a for _, a in parts)
    total = np.zeros(_NUM_BINS, np.int64)
    for hist, a in parts:
        if a == absmax:
            total += hist
            continue
        centers = (np.arange(_NUM_BINS) + 0.5) / _NUM_BINS * 2 * a - a
        idx = np.clip(((centers + absmax) / (2 * absmax)
                       * _NUM_BINS).astype(int), 0, _NUM_BINS - 1)
        np.add.at(total, idx, hist)
    return total, absmax


def _kl_optimal_threshold(arr, num_bins=_NUM_BINS, num_quantized_bins=255):
    """Minimum-KL clipping threshold for one layer's activations."""
    arr = np.asarray(arr).ravel()
    absmax = float(np.abs(arr).max()) or 1e-8
    hist, _ = np.histogram(arr, bins=num_bins, range=(-absmax, absmax))
    return _kl_threshold_from_hist(hist, absmax, num_quantized_bins)


def _kl_threshold_from_hist(hist, absmax, num_quantized_bins=255):
    """Minimum-KL clipping threshold from a symmetric histogram.

    The reference's entropy calibration (`python/mxnet/contrib/
    quantization.py _get_optimal_threshold`, after TensorRT's KL recipe):
    for each candidate symmetric threshold, measure the KL divergence
    between the clipped fp32 histogram P and its int8-requantized
    reconstruction Q; keep the threshold that loses the least information.
    """
    num_bins = len(hist)
    edges = np.linspace(-absmax, absmax, num_bins + 1)
    zero = num_bins // 2
    best_kl, best_thr = None, absmax
    for i in range(num_quantized_bins // 2, zero + 1,
                   max(1, zero // 128)):
        lo, hi = zero - i, zero + i + 1
        sliced = hist[lo:hi].astype(np.float64)
        p = sliced.copy()
        p[0] += hist[:lo].sum()            # outliers clamp to the edges
        p[-1] += hist[hi:].sum()
        # requantize the slice into the int8 bin count, then expand back
        factor = len(sliced) / num_quantized_bins
        q = np.zeros_like(p)
        for j in range(num_quantized_bins):
            a = int(np.floor(j * factor))
            b = int(np.ceil((j + 1) * factor))
            chunk = sliced[a:b]
            count = (chunk != 0).sum()
            if count:
                q[a:b][chunk != 0] = chunk[chunk != 0].sum() / count
        p = _smooth_distribution(p)
        q = _smooth_distribution(q)
        if p is None or q is None:
            continue
        kl = float(np.sum(p * np.log(p / q)))
        if best_kl is None or kl < best_kl:
            best_kl = kl
            best_thr = float(edges[hi]) if hi < len(edges) else absmax
    return best_thr


def _collect_calib_ranges(sym, arg_params, aux_params, calib_data,
                          num_batches, ctx, mode="naive"):
    """fp32 forward over calibration batches.

    'naive': per-output running min/max (reference _LayerOutputMinMax
    collector).  'entropy': keep the activations and compute the
    minimum-KL threshold per layer (reference _LayerHistogramCollector +
    _get_optimal_threshold)."""
    internals = sym.get_internals()
    ranges = {}
    samples = {}
    exe = None
    for i, batch in enumerate(calib_data):
        if i >= num_batches:
            break
        data = batch.data[0]
        if exe is None:
            exe = internals.simple_bind(ctx=ctx, grad_req="null",
                                        data=data.shape)
            exe.copy_params_from(arg_params, aux_params,
                                 allow_extra_params=True)
        outs = exe.forward(is_train=False, data=data)
        for name, out in zip(internals.list_outputs(), outs):
            a = out.asnumpy()
            if mode == "entropy":
                # fold each batch into a fixed-size histogram so memory is
                # O(layers x bins), not O(activations) — the reference's
                # _LayerHistogramCollector strategy
                absmax = float(np.abs(a).max()) or 1e-8
                hist, _ = np.histogram(a, bins=_NUM_BINS,
                                       range=(-absmax, absmax))
                samples.setdefault(name, []).append((hist, absmax))
                continue
            mn, mx = float(a.min()), float(a.max())
            if name in ranges:
                omn, omx = ranges[name]
                ranges[name] = (min(mn, omn), max(mx, omx))
            else:
                ranges[name] = (mn, mx)
    if mode == "entropy":
        for name, parts in samples.items():
            hist, absmax = _merge_histograms(parts)
            thr = _kl_threshold_from_hist(hist, absmax)
            ranges[name] = (-thr, thr)
    return ranges


def quantize_model(sym, arg_params, aux_params, data_names=("data",),
                   label_names=("softmax_label",), ctx=None,
                   excluded_sym_names=None, calib_mode="none",
                   calib_data=None, num_calib_examples=None,
                   quantized_dtype="int8", logger=logging):
    """Reference `quantization.py:412 quantize_model` →
    (quantized symbol, new arg_params, aux_params)."""
    import jax.numpy as jnp
    from ..symbol.symbol import Symbol, _Node, _sym_apply
    from ..symbol import Variable
    from ..ndarray.ndarray import NDArray
    from ..context import cpu

    excluded = set(excluded_sym_names or [])
    ctx = ctx or cpu()

    if calib_mode not in ("none", "naive", "entropy"):
        raise MXNetError("calib_mode must be 'none', 'naive' or 'entropy'")
    calib_ranges = {}
    if calib_mode in ("naive", "entropy"):
        if calib_data is None:
            raise MXNetError(f"calib_data required for calib_mode="
                             f"'{calib_mode}'")
        nb = max(1, (num_calib_examples or 32) // calib_data.batch_size)
        calib_ranges = _collect_calib_ranges(sym, arg_params, aux_params,
                                             calib_data, nb, ctx,
                                             mode=calib_mode)

    new_args = dict(arg_params)
    memo = {}

    def transform(node):
        """Rebuild the graph bottom-up, returning a Symbol per node."""
        if id(node) in memo:
            return memo[id(node)]
        if node.is_variable:
            out = Symbol([(node, 0)])
            memo[id(node)] = out
            return out
        in_syms = []
        for src, idx in node.inputs:
            s = transform(src)
            in_syms.append(s[idx] if len(s._entries) > 1 else s)

        if node.op.name in QUANTIZABLE and node.name not in excluded \
                and _supported(node):
            qdata = _sym_apply("_contrib_quantize_v2", [in_syms[0]],
                               {"out_type": quantized_dtype,
                                **_calib_kwargs(calib_ranges, node)})

            if node.op.name == "Pooling":
                qp = _sym_apply("_contrib_quantized_pooling",
                                [qdata[0], qdata[1], qdata[2]],
                                {k: node.attrs[k] for k in
                                 ("kernel", "pool_type", "stride", "pad",
                                  "global_pool", "pooling_convention")
                                 if k in node.attrs})
                out = _sym_apply("_contrib_dequantize",
                                 [qp[0], qp[1], qp[2]], {})
                memo[id(node)] = out
                return out

            weight_s = in_syms[1]
            bias_s = in_syms[2] if len(in_syms) > 2 else None
            if bias_s is not None:
                # the rewritten graph feeds bias into a plain Reshape, which
                # has no weight-shape solver rule — pin the known shape on a
                # FRESH variable node (same name) so the caller's fp32 graph
                # is not mutated
                bnode = node.inputs[2][0]
                if bnode.is_variable and bnode.name in arg_params:
                    nb = _Node(None, bnode.name, {}, [])
                    nb._extra_attrs.update(bnode._extra_attrs)
                    nb._extra_attrs["__shape__"] = tuple(
                        arg_params[bnode.name].shape)
                    bias_s = Symbol([(nb, 0)])
            wname = node.inputs[1][0].name
            w = arg_params[wname].asnumpy()
            wmax = float(np.abs(w).max()) or 1e-8
            qw = np.clip(np.round(w / wmax * 127), -127, 127).astype(np.int8)
            new_args[wname] = NDArray(jnp.asarray(qw), ctx=ctx)
            new_args[wname + "_min"] = nd.array([-wmax])
            new_args[wname + "_max"] = nd.array([wmax])

            if node.op.name == "Convolution":
                qc = _sym_apply(
                    "_contrib_quantized_conv",
                    [qdata[0], weight_s, qdata[1], qdata[2],
                     Variable(wname + "_min"), Variable(wname + "_max")],
                    {**{k: node.attrs[k] for k in
                        ("kernel", "stride", "pad", "dilate", "num_filter",
                         "num_group", "layout") if k in node.attrs},
                     "no_bias": True})
                out = _sym_apply("_contrib_dequantize",
                                 [qc[0], qc[1], qc[2]], {})
                if bias_s is not None:
                    out = _sym_apply("broadcast_add", [
                        out, _sym_apply("Reshape", [bias_s],
                                        {"shape": (1, -1, 1, 1)})], {})
            else:  # FullyConnected
                qfc = _sym_apply(
                    "_contrib_quantized_fully_connected",
                    [qdata[0], weight_s, qdata[1], qdata[2],
                     Variable(wname + "_min"), Variable(wname + "_max")],
                    {"num_hidden": node.attrs["num_hidden"], "no_bias": True,
                     "flatten": node.attrs.get("flatten", True)})
                out = _sym_apply("_contrib_dequantize",
                                 [qfc[0], qfc[1], qfc[2]], {})
                if bias_s is not None:
                    out = out + _sym_apply("Reshape", [bias_s],
                                           {"shape": (1, -1)})
            memo[id(node)] = out
            return out

        new_node = _Node(node.op, node.name, node.attrs,
                         [s._entries[0] for s in in_syms])
        new_node._extra_attrs = dict(node._extra_attrs)
        nout = new_node.num_outputs()
        out = Symbol([(new_node, i) for i in range(nout)])
        memo[id(node)] = out
        return out

    out_entries = []
    for node, idx in sym._entries:
        s = transform(node)
        out_entries.append(s._entries[min(idx, len(s._entries) - 1)])
    qsym = Symbol(out_entries)
    return qsym, new_args, dict(aux_params)


def _supported(node):
    """Only rewrite configurations the int8 ops implement; anything else
    stays fp32 (the reference's quantize_graph_pass likewise skips
    unsupported nodes rather than failing)."""
    p = node.attrs
    if node.op.name == "Pooling":
        if p.get("pool_type", "max") not in ("max", "avg"):
            return False
        if p.get("pooling_convention", "valid") != "valid":
            return False
        kernel = tuple(p.get("kernel") or ())
        if not p.get("global_pool") and len(kernel) != 2:
            return False
        if p.get("count_include_pad") is False:
            return False
        return True
    if node.op.name == "Convolution":
        kernel = tuple(p.get("kernel") or ())
        return len(kernel) == 2 and p.get("layout", "NCHW") == "NCHW"
    return True


def _calib_kwargs(ranges, node):
    src = node.inputs[0][0]
    key = f"{src.name}_output"
    if key in ranges:
        mn, mx = ranges[key]
        return {"min_calib_range": mn, "max_calib_range": mx}
    return {}
