"""INT8 model quantization (reference `python/mxnet/contrib/quantization.py`
`quantize_model:412` + C++ `quantize_graph_pass.cc`).

Graph rewrite: walk a Symbol and replace quantizable FullyConnected nodes
with quantize → int8 matmul → dequantize chains; weights are pre-quantized
into the returned params with their ranges.  Calibration: 'none' (dynamic
per-batch ranges) or 'naive' (min/max over calibration batches).  INT8
matmuls lower through XLA's integer dot support on TPU.
"""
from __future__ import annotations

import logging

import numpy as np

from ..base import MXNetError
from .. import ndarray as nd

QUANTIZABLE = {"FullyConnected"}


def _collect_calib_ranges(sym, arg_params, aux_params, calib_data,
                          num_batches, ctx):
    """fp32 forward over calibration batches, recording per-output min/max."""
    internals = sym.get_internals()
    ranges = {}
    exe = None
    for i, batch in enumerate(calib_data):
        if i >= num_batches:
            break
        data = batch.data[0]
        if exe is None:
            exe = internals.simple_bind(ctx=ctx, grad_req="null",
                                        data=data.shape)
            exe.copy_params_from(arg_params, aux_params,
                                 allow_extra_params=True)
        outs = exe.forward(is_train=False, data=data)
        for name, out in zip(internals.list_outputs(), outs):
            a = out.asnumpy()
            mn, mx = float(a.min()), float(a.max())
            if name in ranges:
                omn, omx = ranges[name]
                ranges[name] = (min(mn, omn), max(mx, omx))
            else:
                ranges[name] = (mn, mx)
    return ranges


def quantize_model(sym, arg_params, aux_params, data_names=("data",),
                   label_names=("softmax_label",), ctx=None,
                   excluded_sym_names=None, calib_mode="none",
                   calib_data=None, num_calib_examples=None,
                   quantized_dtype="int8", logger=logging):
    """Reference `quantization.py:412 quantize_model` →
    (quantized symbol, new arg_params, aux_params)."""
    import jax.numpy as jnp
    from ..symbol.symbol import Symbol, _Node, _sym_apply
    from ..symbol import Variable
    from ..ndarray.ndarray import NDArray
    from ..context import cpu

    excluded = set(excluded_sym_names or [])
    ctx = ctx or cpu()

    if calib_mode not in ("none", "naive"):
        raise MXNetError("calib_mode must be 'none' or 'naive' "
                         "(KL/entropy calibration: future round)")
    calib_ranges = {}
    if calib_mode == "naive":
        if calib_data is None:
            raise MXNetError("calib_data required for calib_mode='naive'")
        nb = max(1, (num_calib_examples or 32) // calib_data.batch_size)
        calib_ranges = _collect_calib_ranges(sym, arg_params, aux_params,
                                             calib_data, nb, ctx)

    new_args = dict(arg_params)
    memo = {}

    def transform(node):
        """Rebuild the graph bottom-up, returning a Symbol per node."""
        if id(node) in memo:
            return memo[id(node)]
        if node.is_variable:
            out = Symbol([(node, 0)])
            memo[id(node)] = out
            return out
        in_syms = []
        for src, idx in node.inputs:
            s = transform(src)
            in_syms.append(s[idx] if len(s._entries) > 1 else s)

        if node.op.name in QUANTIZABLE and node.name not in excluded:
            data_s, weight_s = in_syms[0], in_syms[1]
            bias_s = in_syms[2] if len(in_syms) > 2 else None
            wname = node.inputs[1][0].name
            w = arg_params[wname].asnumpy()
            wmax = float(np.abs(w).max()) or 1e-8
            qw = np.clip(np.round(w / wmax * 127), -127, 127).astype(np.int8)
            new_args[wname] = NDArray(jnp.asarray(qw), ctx=ctx)
            new_args[wname + "_min"] = nd.array([-wmax])
            new_args[wname + "_max"] = nd.array([wmax])

            qdata = _sym_apply("_contrib_quantize_v2", [data_s],
                               {"out_type": quantized_dtype,
                                **_calib_kwargs(calib_ranges, node)})
            qfc = _sym_apply(
                "_contrib_quantized_fully_connected",
                [qdata[0], weight_s, qdata[1], qdata[2],
                 Variable(wname + "_min"), Variable(wname + "_max")],
                {"num_hidden": node.attrs["num_hidden"], "no_bias": True,
                 "flatten": node.attrs.get("flatten", True)})
            out = _sym_apply("_contrib_dequantize",
                             [qfc[0], qfc[1], qfc[2]], {})
            if bias_s is not None:
                out = out + _sym_apply("Reshape", [bias_s], {"shape": (1, -1)})
            memo[id(node)] = out
            return out

        new_node = _Node(node.op, node.name, node.attrs,
                         [s._entries[0] for s in in_syms])
        new_node._extra_attrs = dict(node._extra_attrs)
        nout = new_node.num_outputs()
        out = Symbol([(new_node, i) for i in range(nout)])
        memo[id(node)] = out
        return out

    out_entries = []
    for node, idx in sym._entries:
        s = transform(node)
        out_entries.append(s._entries[min(idx, len(s._entries) - 1)])
    qsym = Symbol(out_entries)
    return qsym, new_args, dict(aux_params)


def _calib_kwargs(ranges, node):
    src = node.inputs[0][0]
    key = f"{src.name}_output"
    if key in ranges:
        mn, mx = ranges[key]
        return {"min_calib_range": mn, "max_calib_range": mx}
    return {}
