"""Legacy contrib autograd surface (reference
`python/mxnet/contrib/autograd.py` — the pre-1.0 API kept for old
scripts).  Thin aliases over the first-class `mx.autograd`."""
from __future__ import annotations

from ..autograd import (backward, grad, is_recording as _is_recording,
                        mark_variables, pause, record,
                        set_recording as _set_recording)

__all__ = ["set_is_training", "train_section", "test_section",
           "mark_variables", "backward", "grad", "compute_gradient"]


def set_is_training(is_train):
    """Reference `contrib/autograd.py set_is_training`."""
    from .. import autograd as ag
    prev_r = ag.set_recording(is_train)
    prev_t = ag.set_training(is_train)
    return prev_r


def train_section():
    """Old name for `autograd.record()`."""
    return record(train_mode=True)


def test_section():
    """Old name for `autograd.pause()`."""
    return pause(train_mode=False)


def compute_gradient(outputs):
    """Reference `contrib/autograd.py compute_gradient`."""
    backward(outputs)
    return [getattr(o, "grad", None) for o in outputs]
