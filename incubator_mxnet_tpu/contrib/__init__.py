"""`mx.contrib` (reference `python/mxnet/contrib/`)."""
from . import quantization  # noqa: F401
from . import text          # noqa: F401
