"""`mx.contrib` (reference `python/mxnet/contrib/`)."""
from . import quantization  # noqa: F401
from . import svrg_optimization  # noqa: F401
from . import tensorboard   # noqa: F401
from . import text          # noqa: F401
from . import io            # noqa: F401
from . import autograd      # noqa: F401


def __getattr__(name):
    # onnx pulls in the protobuf bindings; load on first touch
    if name == "onnx":
        import importlib
        mod = importlib.import_module(__name__ + ".onnx")
        globals()["onnx"] = mod
        return mod
    raise AttributeError(name)
