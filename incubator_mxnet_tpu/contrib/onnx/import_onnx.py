"""ONNX -> Symbol importer (reference
`python/mxnet/contrib/onnx/onnx2mx/import_model.py`)."""
from __future__ import annotations

import numpy as np

from ...base import MXNetError
from . import onnx_subset_pb2 as OP

_NP = {1: "float32", 2: "uint8", 3: "int8", 6: "int32", 7: "int64",
       9: "bool", 10: "float16", 11: "float64"}


def _to_numpy(t):
    dt = np.dtype(_NP[t.data_type])
    if t.raw_data:
        arr = np.frombuffer(t.raw_data, dtype=dt)
    elif t.float_data:
        arr = np.asarray(t.float_data, np.float32).astype(dt)
    elif t.int64_data:
        arr = np.asarray(t.int64_data, np.int64).astype(dt)
    elif t.int32_data:
        arr = np.asarray(t.int32_data, np.int32).astype(dt)
    elif t.double_data:
        arr = np.asarray(t.double_data, np.float64).astype(dt)
    else:
        arr = np.zeros(0, dt)
    return arr.reshape(tuple(t.dims))


def _attrs(node):
    out = {}
    for a in node.attribute:
        if a.type == OP.AttributeProto.INT:
            out[a.name] = int(a.i)
        elif a.type == OP.AttributeProto.FLOAT:
            out[a.name] = float(a.f)
        elif a.type == OP.AttributeProto.STRING:
            out[a.name] = a.s.decode()
        elif a.type == OP.AttributeProto.INTS:
            out[a.name] = [int(v) for v in a.ints]
        elif a.type == OP.AttributeProto.FLOATS:
            out[a.name] = [float(v) for v in a.floats]
        elif a.type == OP.AttributeProto.TENSOR:
            out[a.name] = _to_numpy(a.t)
    return out


def _pads2(a, default=(0, 0)):
    pads = a.get("pads")
    if not pads:
        return default
    # onnx pads: [x1b, x2b, x1e, x2e] — symmetric only (our conv surface)
    half = len(pads) // 2
    begin, end = pads[:half], pads[half:]
    if list(begin) != list(end):
        raise MXNetError("onnx import: asymmetric pads unsupported")
    return tuple(int(v) for v in begin)


def import_model(model_file):
    """Returns (sym, arg_params, aux_params) — reference
    `onnx2mx/import_model.py:import_model`."""
    from ... import symbol as sym_mod
    from ...symbol.symbol import _sym_apply
    from ...ndarray.ndarray import array

    model = OP.ModelProto()
    with open(model_file, "rb") as f:
        model.ParseFromString(f.read())
    g = model.graph

    params = {}
    for t in g.initializer:
        params[t.name] = _to_numpy(t)

    env = {}
    for vi in g.input:
        if vi.name not in params:
            env[vi.name] = sym_mod.Variable(vi.name)
    for name in params:
        env[name] = sym_mod.Variable(name)

    aux_names = set()

    def one(s):
        return s[0] if len(s._entries) > 1 else s

    for node in g.node:
        op = node.op_type
        a = _attrs(node)
        ins = [env[i] for i in node.input if i]
        out = None
        if op in ("Conv", "Gemm", "Gather") and len(node.input) > 1 \
                and node.input[1 if op != "Gather" else 0] not in params:
            raise MXNetError(
                f"onnx import: {op} weight {node.input[1]!r} is a graph "
                "input, not an initializer — externally-fed weights are "
                "not yet supported")
        if op == "Conv":
            out = _sym_apply("Convolution", ins, {
                "kernel": tuple(a.get("kernel_shape", (1, 1))),
                "stride": tuple(a.get("strides", (1, 1))),
                "pad": _pads2(a),
                "dilate": tuple(a.get("dilations", (1, 1))),
                "num_group": a.get("group", 1),
                "num_filter": int(params[node.input[1]].shape[0]),
                "no_bias": len(ins) < 3})
        elif op == "Gemm":
            if a.get("transB", 0) != 1 or a.get("alpha", 1.0) != 1.0 \
                    or a.get("beta", 1.0) != 1.0:
                raise MXNetError("onnx import: general Gemm (alpha/beta/"
                                 "transB beyond FC semantics) unsupported")
            out = _sym_apply("FullyConnected", ins, {
                "num_hidden": int(params[node.input[1]].shape[0]),
                "no_bias": len(ins) < 3, "flatten": False})
        elif op == "MatMul":
            out = _sym_apply("dot", ins, {})
        elif op in ("Relu", "Sigmoid", "Tanh", "Softplus", "Softsign"):
            act = {"Relu": "relu", "Sigmoid": "sigmoid", "Tanh": "tanh",
                   "Softplus": "softrelu", "Softsign": "softsign"}[op]
            out = _sym_apply("Activation", ins, {"act_type": act})
        elif op == "LeakyRelu":
            out = _sym_apply("LeakyReLU", ins,
                             {"slope": a.get("alpha", 0.01)})
        elif op in ("MaxPool", "AveragePool"):
            out = _sym_apply("Pooling", ins, {
                "kernel": tuple(a.get("kernel_shape", (1, 1))),
                "stride": tuple(a.get("strides", (1, 1))),
                "pad": _pads2(a),
                "pool_type": "max" if op == "MaxPool" else "avg"})
        elif op in ("GlobalMaxPool", "GlobalAveragePool"):
            out = _sym_apply("Pooling", ins, {
                "kernel": (1, 1), "global_pool": True,
                "pool_type": "max" if op == "GlobalMaxPool" else "avg"})
        elif op == "BatchNormalization":
            out = _sym_apply("BatchNorm", ins, {
                "eps": a.get("epsilon", 1e-5),
                "momentum": a.get("momentum", 0.9),
                "fix_gamma": False, "use_global_stats": True})
            aux_names.update(node.input[3:5])
        elif op == "Flatten":
            out = _sym_apply("Flatten", ins[:1], {})
        elif op == "Reshape":
            shape = params.get(node.input[1])
            if shape is None:
                raise MXNetError("onnx import: dynamic Reshape unsupported")
            out = _sym_apply("Reshape", ins[:1],
                             {"shape": tuple(int(d) for d in shape)})
            params.pop(node.input[1], None)
        elif op == "Transpose":
            out = _sym_apply("transpose", ins, {"axes": tuple(a["perm"])})
        elif op == "Concat":
            out = _sym_apply("Concat", ins,
                             {"dim": a.get("axis", 1),
                              "num_args": len(ins)})
        elif op in ("Add", "Sub", "Mul", "Div"):
            name = {"Add": "broadcast_add", "Sub": "broadcast_sub",
                    "Mul": "broadcast_mul", "Div": "broadcast_div"}[op]
            out = _sym_apply(name, ins, {})
        elif op == "Softmax":
            out = _sym_apply("softmax", ins, {"axis": a.get("axis", -1)})
        elif op == "Dropout":
            kw = {}
            if len(node.input) > 1 and node.input[1] in params:
                kw["p"] = float(params.pop(node.input[1]))
            out = _sym_apply("Dropout", ins[:1], kw)
        elif op == "Gather":
            if a.get("axis", 0) != 0:
                raise MXNetError("onnx import: Gather axis != 0")
            weight = params.get(node.input[0])
            out = _sym_apply("Embedding", [ins[1], ins[0]], {
                "input_dim": int(weight.shape[0]),
                "output_dim": int(weight.shape[1])})
        else:
            raise MXNetError(f"onnx import: operator {op} not yet mapped")
        outs = [out[i] if len(node.output) > 1 else out
                for i in range(len(node.output))] \
            if len(node.output) > 1 else [out]
        for name, o in zip(node.output, outs):
            env[name] = one(o)

    from ...symbol.symbol import Symbol
    entries = []
    for vi in g.output:
        entries.extend(env[vi.name]._entries)
    sym = Symbol(entries)

    arg_params, aux_params = {}, {}
    for name, arr in params.items():
        nd = array(arr, dtype=arr.dtype)
        if name in aux_names:
            aux_params[name] = nd
        else:
            arg_params[name] = nd
    return sym, arg_params, aux_params
