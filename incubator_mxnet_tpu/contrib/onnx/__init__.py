"""ONNX interchange (reference `python/mxnet/contrib/onnx/`).

The wire format is produced/consumed through a protoc-compiled subset of
the public ONNX schema (`onnx_subset.proto` — field numbers match the
official definition, so files interchange with any ONNX runtime); the
`onnx` python package is not required.
"""
from .export_onnx import export_model
from .import_onnx import import_model

__all__ = ["export_model", "import_model"]
