"""Symbol -> ONNX exporter (reference
`python/mxnet/contrib/onnx/mx2onnx/export_model.py`)."""
from __future__ import annotations

import numpy as np

from ...base import MXNetError
from . import onnx_subset_pb2 as OP

_DT = {np.dtype("float32"): 1, np.dtype("uint8"): 2, np.dtype("int8"): 3,
       np.dtype("int32"): 6, np.dtype("int64"): 7, np.dtype("bool"): 9,
       np.dtype("float16"): 10, np.dtype("float64"): 11}

OPSET = 13


def _tensor(name, arr):
    arr = np.ascontiguousarray(arr)
    t = OP.TensorProto()
    t.name = name
    t.dims.extend(arr.shape)
    t.data_type = _DT[arr.dtype]
    t.raw_data = arr.tobytes()
    return t


def _attr(name, value):
    a = OP.AttributeProto()
    a.name = name
    if isinstance(value, bool):
        a.type = OP.AttributeProto.INT
        a.i = int(value)
    elif isinstance(value, int):
        a.type = OP.AttributeProto.INT
        a.i = value
    elif isinstance(value, float):
        a.type = OP.AttributeProto.FLOAT
        a.f = value
    elif isinstance(value, str):
        a.type = OP.AttributeProto.STRING
        a.s = value.encode()
    elif isinstance(value, (list, tuple)):
        if value and isinstance(value[0], float):
            a.type = OP.AttributeProto.FLOATS
            a.floats.extend(value)
        else:
            a.type = OP.AttributeProto.INTS
            a.ints.extend(int(v) for v in value)
    else:
        raise MXNetError(f"onnx export: bad attribute {name}={value!r}")
    return a


def _pair(p, key, default):
    v = p.get(key) or default
    v = (v, v) if isinstance(v, int) else tuple(v)
    return v if v else default


class _Exporter:
    def __init__(self, sym, params, in_shapes, in_types, graph_name):
        self.sym = sym
        self.params = params
        self.nodes = []
        self.initializers = []
        self.inputs = []
        self.counter = 0
        self.graph_name = graph_name
        self.in_shapes = in_shapes
        self.in_types = in_types

    def _name(self, base):
        self.counter += 1
        return f"{base}_{self.counter}"

    def node(self, op_type, inputs, outputs=None, name=None, **attrs):
        n = OP.NodeProto()
        n.op_type = op_type
        n.name = name or self._name(op_type.lower())
        n.input.extend(inputs)
        outputs = outputs or [n.name + "_out"]
        n.output.extend(outputs)
        for k, v in attrs.items():
            if v is not None:
                n.attribute.append(_attr(k, v))
        self.nodes.append(n)
        return outputs[0]

    def add_initializer(self, name, arr):
        self.initializers.append(_tensor(name, np.asarray(arr)))

    def const_i64(self, values):
        name = self._name("const")
        self.add_initializer(name, np.asarray(values, np.int64))
        return name

    # -- op translators ------------------------------------------------------
    def convert(self, node, in_names):
        op = node.op.name
        p = node.attrs
        nm = node.name

        if op == "Convolution":
            k = _pair(p, "kernel", (1, 1))
            pad = _pair(p, "pad", (0, 0))
            out = self.node(
                "Conv", in_names, name=nm,
                kernel_shape=k, strides=_pair(p, "stride", (1, 1)),
                pads=list(pad) + list(pad),
                dilations=_pair(p, "dilate", (1, 1)),
                group=int(p.get("num_group", 1)))
            return out
        if op == "FullyConnected":
            data = in_names[0]
            if p.get("flatten", True):
                data = self.node("Flatten", [data], axis=1)
            ins = [data, in_names[1]]
            if len(in_names) > 2:
                ins.append(in_names[2])
            return self.node("Gemm", ins, name=nm, alpha=1.0, beta=1.0,
                             transB=1)
        if op == "Activation":
            table = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
                     "softrelu": "Softplus", "softsign": "Softsign"}
            act = table.get(p["act_type"])
            if act is None:
                raise MXNetError(f"onnx export: Activation act_type="
                                 f"{p['act_type']!r} not yet mapped")
            return self.node(act, in_names, name=nm)
        if op == "LeakyReLU":
            return self.node("LeakyRelu", in_names, name=nm,
                             alpha=float(p.get("slope", 0.25)))
        if op == "Pooling":
            ptype = p.get("pool_type", "max")
            if ptype not in ("max", "avg"):
                raise MXNetError(f"onnx export: pool_type={ptype!r} has no "
                                 "ONNX counterpart (only max/avg)")
            if p.get("global_pool"):
                return self.node("GlobalMaxPool" if ptype == "max"
                                 else "GlobalAveragePool", in_names, name=nm)
            k = _pair(p, "kernel", (1, 1))
            pad = _pair(p, "pad", (0, 0))
            return self.node(
                "MaxPool" if ptype == "max" else "AveragePool", in_names,
                name=nm, kernel_shape=k,
                strides=_pair(p, "stride", (1, 1)),
                pads=list(pad) + list(pad))
        if op in ("BatchNorm", "BatchNorm_v1"):
            return self.node("BatchNormalization", in_names, name=nm,
                             epsilon=float(p.get("eps", 1e-5)),
                             momentum=float(p.get("momentum", 0.9)))
        if op == "Flatten":
            return self.node("Flatten", in_names, name=nm, axis=1)
        if op == "Reshape":
            shape = [int(d) for d in p["shape"]]
            if any(d < -1 for d in shape):
                # MXNet's -2/-3/-4 split/merge codes have no ONNX meaning
                raise MXNetError(
                    f"onnx export: Reshape shape {tuple(shape)} uses MXNet "
                    "special codes (<-1) that ONNX Reshape cannot express")
            # 0 = copy-dim in both conventions (ONNX allowzero=0 default)
            return self.node("Reshape",
                             [in_names[0], self.const_i64(shape)], name=nm)
        if op == "transpose":
            return self.node("Transpose", in_names, name=nm,
                             perm=list(p["axes"]))
        if op in ("concat", "Concat"):
            return self.node("Concat", in_names, name=nm,
                             axis=int(p.get("dim", 1)))
        if op in ("elemwise_add", "broadcast_add", "_plus"):
            return self.node("Add", in_names, name=nm)
        if op in ("elemwise_sub", "broadcast_sub"):
            return self.node("Sub", in_names, name=nm)
        if op in ("elemwise_mul", "broadcast_mul"):
            return self.node("Mul", in_names, name=nm)
        if op in ("elemwise_div", "broadcast_div"):
            return self.node("Div", in_names, name=nm)
        if op == "dot":
            return self.node("MatMul", in_names, name=nm)
        if op in ("softmax", "SoftmaxActivation"):
            return self.node("Softmax", in_names, name=nm,
                             axis=int(p.get("axis", -1)))
        if op == "SoftmaxOutput":
            # inference semantics: plain softmax over the class axis
            return self.node("Softmax", in_names[:1], name=nm, axis=1)
        if op == "Dropout":
            # opset 13 takes ratio as an optional input tensor
            ratio = self._name("dropout_ratio")
            self.add_initializer(ratio,
                                 np.float32(p.get("p", 0.5)))
            return self.node("Dropout", [in_names[0], ratio], name=nm)
        if op == "Embedding":
            # onnx Gather(weight, indices)
            return self.node("Gather", [in_names[1], in_names[0]], name=nm,
                             axis=0)
        raise MXNetError(f"onnx export: operator {op} not yet mapped "
                         "(extend mx2onnx op table)")

    def run(self):
        memo = {}
        topo = self.sym._topo()
        for node in topo:
            if node.is_variable:
                if node.name in self.params:
                    self.add_initializer(node.name,
                                         self.params[node.name].asnumpy())
                else:
                    vi = OP.ValueInfoProto()
                    vi.name = node.name
                    vi.type.tensor_type.elem_type = _DT[np.dtype(
                        self.in_types.get(node.name, "float32"))]
                    for d in self.in_shapes.get(node.name, ()):
                        dim = vi.type.tensor_type.shape.dim.add()
                        dim.dim_value = int(d)
                    self.inputs.append(vi)
                memo[id(node)] = [node.name]
                continue
            ins = []
            for src, idx in node.inputs:
                outs = memo[id(src)]
                if idx >= len(outs):
                    raise MXNetError(
                        f"onnx export: {src.name} output {idx} is consumed "
                        "but only its first output is exported (multi-"
                        "output ops are not yet mapped)")
                ins.append(outs[idx])
            out = self.convert(node, ins)
            memo[id(node)] = [out]

        g = OP.GraphProto()
        g.name = self.graph_name
        g.node.extend(self.nodes)
        g.initializer.extend(self.initializers)
        g.input.extend(self.inputs)
        for node, idx in self.sym._entries:
            outs = memo[id(node)]
            if idx >= len(outs):
                raise MXNetError(
                    f"onnx export: graph output {node.name}[{idx}] refers "
                    "to an unexported secondary output")
            vi = OP.ValueInfoProto()
            vi.name = outs[idx]
            vi.type.tensor_type.elem_type = 1
            g.output.append(vi)

        m = OP.ModelProto()
        m.ir_version = 8
        m.producer_name = "incubator_mxnet_tpu"
        m.graph.CopyFrom(g)
        ops = m.opset_import.add()
        ops.domain = ""
        ops.version = OPSET
        return m


def export_model(sym, params, in_shapes=None, in_types=None,
                 onnx_file_path="model.onnx", verbose=False, **kwargs):
    """Reference `mx2onnx/export_model.py:export_model` surface.

    sym: Symbol (or path to -symbol.json); params: dict (or .params path);
    returns the path written.
    """
    from ... import symbol as _sym
    from ...ndarray import utils as _nd_utils
    if isinstance(sym, str):
        sym = _sym.load(sym)
    if isinstance(params, str):
        params = _nd_utils.load(params)
        params = {k.split(":", 1)[-1]: v for k, v in params.items()}
    shapes = {}
    types = {}
    data_names = [n for n in sym.list_arguments() if n not in params]
    if in_shapes is not None:
        for name, s in zip(data_names, in_shapes):
            shapes[name] = tuple(s)
    if in_types is not None:
        for name, t in zip(data_names, in_types):
            types[name] = np.dtype(t).name
    model = _Exporter(sym, params, shapes, types, "incubator_mxnet_tpu").run()
    with open(onnx_file_path, "wb") as f:
        f.write(model.SerializeToString())
    return onnx_file_path
