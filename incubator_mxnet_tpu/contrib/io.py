"""contrib IO adapters (reference `python/mxnet/contrib/io.py`)."""
from __future__ import annotations

import numpy as np

from ..io import DataIter, DataBatch, DataDesc

__all__ = ["DataLoaderIter"]


class DataLoaderIter(DataIter):
    """Wrap a gluon DataLoader as a classic DataIter
    (reference `contrib/io.py:25 DataLoaderIter`): lets Module.fit train
    from gluon datasets."""

    def __init__(self, loader, data_name="data", label_name="softmax_label"):
        self._loader = loader
        self._iter = iter(loader)
        self.data_name = data_name
        self.label_name = label_name
        first = next(self._iter)
        self._first = first
        data, label = first
        super().__init__(batch_size=data.shape[0])
        self.provide_data = [DataDesc(data_name, tuple(data.shape),
                                      np.dtype(data.dtype))]
        self.provide_label = [DataDesc(label_name, tuple(label.shape),
                                       np.dtype(label.dtype))]

    def reset(self):
        self._iter = iter(self._loader)
        self._first = None

    def next(self):
        if self._first is not None:
            data, label = self._first
            self._first = None
        else:
            data, label = next(self._iter)
        return DataBatch(data=[data], label=[label], pad=0,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)
