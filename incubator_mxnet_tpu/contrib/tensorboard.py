"""Training-metric logging bridge (reference
`python/mxnet/contrib/tensorboard.py`: LogMetricsCallback).

The reference forwards eval metrics to a TensorBoard SummaryWriter.  The
same callback shape is kept; the sink degrades gracefully:

* `tensorboardX`/`torch.utils.tensorboard` present -> real event files
* otherwise -> newline-delimited JSON (`events.jsonl`) in the logging
  dir — trivially greppable/plottable, and convertible later.
"""
from __future__ import annotations

import json
import os

__all__ = ["LogMetricsCallback"]


class _JsonlWriter:
    def __init__(self, logging_dir):
        os.makedirs(logging_dir, exist_ok=True)
        self._f = open(os.path.join(logging_dir, "events.jsonl"), "a")

    def add_scalar(self, tag, value, global_step=None):
        self._f.write(json.dumps({"tag": tag, "value": float(value),
                                  "step": global_step}) + "\n")
        self._f.flush()

    def close(self):
        self._f.close()


def _make_writer(logging_dir):
    for mod, cls in (("tensorboardX", "SummaryWriter"),
                     ("torch.utils.tensorboard", "SummaryWriter")):
        try:
            import importlib
            m = importlib.import_module(mod)
            return getattr(m, cls)(logging_dir)
        except Exception:
            continue
    return _JsonlWriter(logging_dir)


class LogMetricsCallback:
    """Batch-end callback pushing eval metrics to the writer
    (reference `tensorboard.py:LogMetricsCallback`)."""

    def __init__(self, logging_dir, prefix=None):
        self.prefix = prefix
        self.step = 0
        self._writer = _make_writer(logging_dir)

    def __call__(self, param):
        self.step += 1
        if param.eval_metric is None:
            return
        names, values = param.eval_metric.get()
        if not isinstance(names, list):
            names, values = [names], [values]
        for name, value in zip(names, values):
            if self.prefix is not None:
                name = f"{self.prefix}-{name}"
            self._writer.add_scalar(name, value, self.step)

    def close(self):
        self._writer.close()
