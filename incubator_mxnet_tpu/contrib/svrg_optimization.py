"""SVRG (stochastic variance-reduced gradient) training (reference
`python/mxnet/contrib/svrg_optimization/`: SVRGModule + SVRGOptimizer).

Every `update_freq` epochs the module snapshots the parameters and runs
one full pass to compute the exact gradient mu at the snapshot; each step
then updates with  g_i(w) - g_i(w_snap) + mu  — the variance-reduced
estimator.  On TPU both gradient evaluations are the SAME compiled XLA
program applied at two parameter sets, so the extra cost is one more
executable invocation per step, not a second compile.
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from ..module import Module

__all__ = ["SVRGModule"]


class SVRGModule(Module):
    """Reference `svrg_module.py:SVRGModule`."""

    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), update_freq=2, **kwargs):
        super().__init__(symbol, data_names=data_names,
                         label_names=label_names, **kwargs)
        if update_freq < 1:
            raise MXNetError("update_freq must be >= 1")
        self.update_freq = update_freq
        self._snap_params = None      # w_snap
        self._mu = None               # full gradient at w_snap

    def _live_grads(self):
        """name -> live grad NDArray (single-context SVRG, like the
        reference module's single-device constraint)."""
        eg = self._exec_group
        return {name: eg.grad_arrays[i][0]
                for i, name in enumerate(eg.param_names)}

    # -- snapshot machinery ---------------------------------------------------
    def _take_snapshot(self, train_data):
        """w_snap <- w; mu <- (1/N) sum_i grad_i(w_snap)."""
        arg_params, aux_params = self.get_params()
        self._snap_params = {k: v.copyto(v.context)
                             for k, v in arg_params.items()}
        sums = None
        n_batches = 0
        train_data.reset()
        for batch in train_data:
            self.forward_backward(batch)
            grads = self._live_grads()
            if sums is None:
                sums = {k: g.copyto(g.context) for k, g in grads.items()}
            else:
                for k, g in grads.items():
                    sums[k] += g
            n_batches += 1
        if not n_batches:
            raise MXNetError("SVRG snapshot: train_data yielded no batches")
        self._mu = {k: v / float(n_batches) for k, v in sums.items()}
        train_data.reset()

    def _grad_at_snapshot(self, batch):
        """grad_i(w_snap) with the live executor: swap params, run, swap
        back (one extra invocation of the compiled step)."""
        live, aux = self.get_params()
        self.set_params(self._snap_params, aux, force_init=True)
        self.forward_backward(batch)
        snap_grads = {k: g.copyto(g.context)
                      for k, g in self._live_grads().items()}
        self.set_params(live, aux, force_init=True)
        return snap_grads

    # -- training loop --------------------------------------------------------
    def fit(self, train_data, eval_data=None, eval_metric="acc",
            num_epoch=None, optimizer="sgd", optimizer_params=None,
            initializer=None, kvstore=None,
            batch_end_callback=None, epoch_end_callback=None,
            validation_metric=None, **kwargs):
        from .. import metric as metric_mod
        from .. import initializer as init_mod
        from ..model import BatchEndParam
        if num_epoch is None:
            raise MXNetError("num_epoch required")
        if kvstore not in (None, "local"):
            raise MXNetError("SVRGModule is single-context (matching the "
                             "reference module's constraint); kvstore is "
                             "not supported")
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label, for_training=True)
        self.init_params(initializer or init_mod.Uniform(0.01))
        self.init_optimizer(kvstore=None, optimizer=optimizer,
                            optimizer_params=optimizer_params or
                            (("learning_rate", 0.01),))
        metric = metric_mod.create(eval_metric)
        val_metric = (metric_mod.create(validation_metric)
                      if validation_metric is not None else
                      metric_mod.create(eval_metric))
        log = logging.getLogger("SVRGModule")
        for epoch in range(num_epoch):
            if epoch % self.update_freq == 0:
                self._take_snapshot(train_data)
            metric.reset()
            train_data.reset()
            for nbatch, batch in enumerate(train_data):
                self.forward_backward(batch)
                # snapshot the LIVE gradients and outputs by value: the
                # snapshot pass below reuses the same executor buffers
                live_vals = {k: g.copyto(g.context)
                             for k, g in self._live_grads().items()}
                live_outputs = [o.copyto(o.context)
                                for o in self.get_outputs()]
                snap_grads = self._grad_at_snapshot(batch)
                # g <- g_live - g_snap + mu, written into the live arrays
                for k, g in self._live_grads().items():
                    corr = live_vals[k] - snap_grads[k] + self._mu[k]
                    g._set_data(corr._data)
                self.update()
                metric.update(batch.label, live_outputs)
                if batch_end_callback is not None:
                    cbs = batch_end_callback if isinstance(
                        batch_end_callback, (list, tuple)) else \
                        [batch_end_callback]
                    for cb in cbs:
                        cb(BatchEndParam(epoch=epoch, nbatch=nbatch,
                                         eval_metric=metric, locals=None))
            log.info("Epoch[%d] %s", epoch,
                     " ".join(f"{n}={v:.6f}" for n, v in
                              zip(*[x if isinstance(x, list) else [x]
                                    for x in metric.get()])))
            if eval_data is not None:
                res = self.score(eval_data, val_metric)
                log.info("Epoch[%d] validation %s", epoch,
                         " ".join(f"{n}={v:.6f}" for n, v in res))
            if epoch_end_callback:
                epoch_end_callback(epoch, self._symbol, *self.get_params())
        return self
