"""Atomic checkpoint manifests.

A checkpoint is a DIRECTORY of shard files plus a ``manifest.json``
recording step/epoch/RNG state, the framework version, and a byte count +
CRC32 per shard.  Two rules make a checkpoint impossible to mistake for
valid when its writer died mid-flight:

* shards are written into a hidden temp directory which is renamed into
  place with ``os.replace`` only after every shard landed — the commit is
  one rename;
* the manifest itself is written temp-file + ``os.replace`` and is the
  LAST file written, and ``validate`` re-checks every shard's size and
  checksum against it — so even a checkpoint assembled in place (the
  per-rank dist layout) is only trusted once it is internally consistent.

``latest``/``list_checkpoints`` only ever surface directories that pass
``validate``; a torn write is garbage-collected, never resumed from.
"""
from __future__ import annotations

import json
import os
import re
import shutil
import zlib

from ..base import MXNetError

MANIFEST_NAME = "manifest.json"
REJECTED_STAMP_NAME = "rejected.json"
CHECKPOINT_FORMAT = "incubator_mxnet_tpu.checkpoint/1"
_CKPT_RE = re.compile(r"^ckpt-(\d+)$")
_TMP_PREFIX = ".tmp-ckpt-"


def checkpoint_dirname(step):
    return "ckpt-%010d" % int(step)


def file_crc32(path, chunk_size=1 << 20):
    crc = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(chunk_size)
            if not chunk:
                return crc
            crc = zlib.crc32(chunk, crc)


def atomic_write_json(path, obj):
    """Write JSON so a killed writer leaves either the old file or the new
    one, never a torn hybrid (temp file + ``os.replace``).  No fsync on
    the hot path: a torn manifest after power loss fails ``validate`` and
    resume falls back one checkpoint — the checksum gate, not the disk
    cache, is the integrity contract (fsync per snapshot would serialize
    the train loop against disk latency)."""
    tmp = "%s.tmp.%d" % (path, os.getpid())
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=1, sort_keys=True)
    os.replace(tmp, path)


def shard_entry(path):
    """Manifest entry for one shard file: size + CRC32 of its bytes."""
    return {"bytes": os.path.getsize(path), "crc32": file_crc32(path)}


def write_manifest(ckpt_dir, *, step, epoch=0, nbatch=0, shards=None,
                   rng=None, meta=None, num_ranks=1):
    from .. import __version__
    manifest = {
        "format": CHECKPOINT_FORMAT,
        "framework_version": __version__,
        "step": int(step),
        "epoch": int(epoch),
        "nbatch": int(nbatch),
        "num_ranks": int(num_ranks),
        "shards": shards or {},
        "rng": rng,
        "meta": meta or {},
    }
    atomic_write_json(os.path.join(ckpt_dir, MANIFEST_NAME), manifest)
    return manifest


def read_manifest(ckpt_dir):
    path = os.path.join(ckpt_dir, MANIFEST_NAME)
    with open(path) as f:
        manifest = json.load(f)
    if manifest.get("format") != CHECKPOINT_FORMAT:
        raise MXNetError(
            f"{path}: unknown checkpoint format {manifest.get('format')!r}")
    return manifest


def validate(ckpt_dir, deep=True):
    """Whether `ckpt_dir` holds a complete, uncorrupted checkpoint.

    Shallow: manifest parses and every listed shard file exists with the
    recorded byte count.  Deep (default) additionally re-hashes each
    shard against its recorded CRC32 — the contract `latest()` relies on:
    a half-written shard or a bit-flipped file is never selected.
    """
    try:
        manifest = read_manifest(ckpt_dir)
    except (OSError, ValueError, MXNetError):
        return False
    for name, entry in manifest.get("shards", {}).items():
        path = os.path.join(ckpt_dir, name)
        try:
            if os.path.getsize(path) != int(entry["bytes"]):
                return False
            if deep and file_crc32(path) != int(entry["crc32"]):
                return False
        except (OSError, KeyError, ValueError, TypeError):
            return False
    return True


def stamp_rejected(ckpt_dir, reason="", **info):
    """Stamp a checkpoint rejected — a sidecar file, not a manifest edit.

    Written by the serving-side canary gate (loop/controller.py) when a
    published version fails its holdout canary: the checkpoint stays on
    disk (forensics, gc retention) but `latest()`/`latest_healthy()`
    skip it from then on, so neither trainer resume nor a freshly booted
    replica can ever select it again.  Idempotent: the FIRST stamp wins
    and later calls return it unchanged — the original rejection
    evidence (scores, reason) is never overwritten.  Being a plain file,
    the stamp survives process restart.
    """
    path = os.path.join(ckpt_dir, REJECTED_STAMP_NAME)
    existing = rejection(ckpt_dir)
    if existing is not None:
        return existing
    rec = {"rejected": True, "reason": str(reason)}
    rec.update(info)
    atomic_write_json(path, rec)
    return rec


def rejection(ckpt_dir):
    """The rejection stamp of `ckpt_dir`, or None if not stamped."""
    try:
        with open(os.path.join(ckpt_dir, REJECTED_STAMP_NAME)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def is_rejected(ckpt_dir):
    return rejection(ckpt_dir) is not None


def _excluded(step, path, exclude):
    """Whether `exclude` — a callable(step)->bool or a collection of
    steps and/or paths — blocks this checkpoint."""
    if exclude is None:
        return False
    if callable(exclude):
        return bool(exclude(step))
    return step in exclude or path in exclude


def list_checkpoints(root, valid_only=True, deep=True,
                     include_rejected=True):
    """Sorted [(step, path)] of checkpoints under `root` (oldest first)."""
    out = []
    try:
        names = os.listdir(root)
    except OSError:
        return out
    for name in names:
        m = _CKPT_RE.match(name)
        if not m:
            continue
        path = os.path.join(root, name)
        if not os.path.isdir(path):
            continue
        if valid_only and not validate(path, deep=deep):
            continue
        if not include_rejected and is_rejected(path):
            continue
        out.append((int(m.group(1)), path))
    out.sort()
    return out


def latest(root, deep=True, include_rejected=False):
    """Path of the newest VALID checkpoint under `root`, or None.

    Torn checkpoints — missing/corrupt manifest, truncated shard, bad
    checksum — are skipped, so resume always lands on the last write that
    fully committed.  Canary-rejected checkpoints (see `stamp_rejected`)
    are skipped by default: a version the serving fleet refused must not
    come back through resume or replica boot.
    """
    ckpts = list_checkpoints(root, valid_only=True, deep=deep,
                             include_rejected=include_rejected)
    return ckpts[-1][1] if ckpts else None


def latest_healthy(root, max_step=None, deep=True, exclude=None):
    """Path of the newest VALID checkpoint stamped healthy, or None.

    The training guardian (resilience/guardian.py) stamps every
    manifest's ``meta.health``; rollback-to-last-good selects with this:
    checkpoints stamped ``suspect`` (taken inside an active anomaly) are
    passed over, and ``max_step`` bounds the search to snapshots at or
    before the last known-good step — the newest checkpoint may already
    carry a loss spike's damage.  Manifests without a stamp (pre-
    guardian, foreign writers) count as healthy.

    Canary-rejected checkpoints are always skipped.  ``exclude`` narrows
    further: a callable(step)->bool, or a collection of steps/paths —
    the train-to-serve publisher passes the registry's fence windows
    here so a guardian-fenced step is never re-published.
    """
    for step, path in reversed(list_checkpoints(root, valid_only=True,
                                                deep=deep,
                                                include_rejected=False)):
        if max_step is not None and step > int(max_step):
            continue
        if _excluded(step, path, exclude):
            continue
        try:
            manifest = read_manifest(path)
        except (OSError, ValueError, MXNetError):
            continue
        health = (manifest.get("meta") or {}).get("health") or {}
        if health.get("status", "healthy") == "healthy":
            return path
    return None


def gc(root, keep_last):
    """Retention: drop all but the newest `keep_last` VALID checkpoints,
    plus any torn directory older than the newest valid one (a torn dir
    NEWER than it may be a concurrent writer mid-commit — left alone)."""
    keep_last = max(1, int(keep_last))
    valid = list_checkpoints(root, valid_only=True, deep=False)
    removed = []
    for _, path in valid[:-keep_last] if len(valid) > keep_last else []:
        shutil.rmtree(path, ignore_errors=True)
        removed.append(path)
    newest_step = valid[-1][0] if valid else None
    try:
        names = os.listdir(root)
    except OSError:
        return removed
    for name in names:
        path = os.path.join(root, name)
        m = _CKPT_RE.match(name)
        torn = (m and os.path.isdir(path) and newest_step is not None and
                int(m.group(1)) < newest_step and not validate(path,
                                                               deep=False))
        # a temp dir for a step older than the newest commit can only be a
        # dead writer's leftovers; a newer one may be a live writer mid-build
        tm = re.match(re.escape(_TMP_PREFIX) + r"(\d+)-", name)
        stale_tmp = (tm and newest_step is not None and
                     int(tm.group(1)) < newest_step)
        if torn or (stale_tmp and os.path.isdir(path)):
            shutil.rmtree(path, ignore_errors=True)
            removed.append(path)
    return removed
