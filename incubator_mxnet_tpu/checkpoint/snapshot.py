"""Async snapshot engine.

``snapshot()`` splits a checkpoint into a cheap synchronous phase and a
background phase so the train step keeps running while bytes hit disk:

* **sync phase** — device arrays are gathered into pooled host buffers
  (`storage.HostStagingPool`, the same size-class pool the input pipeline
  recycles) and small python state (optimizer blobs, RNG) is captured.
  This is the only part that must see a consistent view of training state.
* **background phase** — a single daemon thread serializes the staged
  buffers into shard files, hashes them, writes the manifest, and commits
  the checkpoint directory with one ``os.replace`` rename.

Double-buffering: at most ONE snapshot is in flight.  Submitting a new
one first waits for the previous write to land (so a fast checkpoint
period degrades to back-to-back writes, never to an unbounded queue of
staged param copies), and ``flush()`` blocks until the in-flight write —
if any — has committed.  Background failures are re-raised on the next
``submit``/``flush`` so a dying disk cannot silently drop checkpoints.
"""
from __future__ import annotations

import os
import pickle
import shutil
import struct
import threading

from ..analysis import locks as _alocks
from ..analysis import tsan as _tsan
import zlib

import numpy as np

from ..base import MXNetError
from .. import storage
from ..resilience import faults as _faults
from . import manifest as _manifest

ARRAYS_SHARD = "arrays.npk"
_PICKLE_PROTO = 4
_HDR = struct.Struct("<Q")


def write_array_shard(path, arrays):
    """Stream ``{name: host ndarray}`` to one shard file:
    ``[8-byte header length][pickled (name, dtype, shape, offset, nbytes)
    table][raw array bytes...]``.

    Raw buffers go straight from the staging pool to ``file.write`` and
    ``zlib.crc32`` — both release the GIL on large buffers — so the
    background writer never serializes a big pickle while the train
    loop's host thread needs the interpreter.  Returns (bytes, crc32)
    for the manifest without re-reading the file.
    """
    table = []
    views = []
    offset = 0
    for name, arr in arrays.items():
        a = np.ascontiguousarray(arr)
        view = memoryview(a).cast("B")
        table.append((name, str(a.dtype), tuple(a.shape), offset,
                      len(view)))
        views.append(view)
        offset += len(view)
    header = pickle.dumps(table, protocol=_PICKLE_PROTO)
    crc = 0
    with open(path, "wb") as f:
        for chunk in (_HDR.pack(len(header)), header):
            f.write(chunk)
            crc = zlib.crc32(chunk, crc)
        for view in views:
            f.write(view)
            crc = zlib.crc32(view, crc)
    return _HDR.size + len(header) + offset, crc


def read_array_shard(path):
    """{name: np.ndarray} back out of a `write_array_shard` file."""
    with open(path, "rb") as f:
        hlen = _HDR.unpack(f.read(_HDR.size))[0]
        table = pickle.loads(f.read(hlen))
        payload = f.read()
    out = {}
    for name, dtype, shape, offset, nbytes in table:
        dt = np.dtype(dtype)
        arr = np.frombuffer(payload, dtype=dt, count=nbytes // dt.itemsize,
                            offset=offset)
        out[name] = arr.reshape(shape).copy()
    return out


def _as_host_array(value):
    """Host ndarray view of an NDArray / jax array / numpy array (zero-copy
    where the backend allows it)."""
    data = getattr(value, "_data", value)
    try:
        return np.asarray(data)
    except Exception:
        # device-resident array that refuses a direct view: explicit fetch
        import jax
        return np.asarray(jax.device_get(data))


def gather_to_pool(named_arrays, pool=None):
    """Stage ``{name: array}`` into pooled host buffers.

    Returns ``(staged, release)``: `staged` maps each name to a host
    ndarray backed by the pool; `release()` hands every buffer back (the
    background writer calls it once the bytes are on disk).
    """
    pool = pool or storage.default_pool()
    staged = {}
    bufs = []
    for name, value in named_arrays.items():
        src = _as_host_array(value)
        buf = pool.acquire(src.shape, src.dtype)
        np.copyto(buf, src)
        staged[name] = buf
        bufs.append(buf)

    def release():
        for b in bufs:
            pool.release(b)
    return staged, release


class SnapshotJob:
    """One staged checkpoint: everything the background writer needs."""

    def __init__(self, root, step, epoch=0, nbatch=0, arrays=None,
                 blobs=None, rng=None, meta=None, retire=None,
                 rank=0, num_ranks=1, release=None):
        self.root = root
        self.step = int(step)
        self.epoch = int(epoch)
        self.nbatch = int(nbatch)
        self.arrays = arrays or {}
        self.blobs = dict(blobs or {})
        self.rng = rng
        self.meta = meta or {}
        self.retire = retire    # committed-path -> [stale paths to delete]
        self.rank = int(rank)
        self.num_ranks = int(num_ranks)
        self.release = release

    # -- background phase ----------------------------------------------------
    def write(self):
        try:
            if self.rank == 0:
                self._write_primary()
            else:
                self._write_rank_shard()
        finally:
            if self.release is not None:
                self.release()

    def _serialize_shards(self, into_dir):
        shards = {}
        if self.arrays:
            path = os.path.join(into_dir, ARRAYS_SHARD)
            size, crc = write_array_shard(path, self.arrays)
            shards[ARRAYS_SHARD] = {"bytes": size, "crc32": crc}
        for name, blob in self.blobs.items():
            fname = f"{name}.bin"
            with open(os.path.join(into_dir, fname), "wb") as f:
                f.write(blob)
            shards[fname] = {"bytes": len(blob), "crc32": zlib.crc32(blob)}
        return shards

    def _write_primary(self):
        os.makedirs(self.root, exist_ok=True)
        tmp = os.path.join(
            self.root, "%s%d-%d" % (_manifest._TMP_PREFIX, self.step,
                                    os.getpid()))
        if os.path.isdir(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        try:
            shards = self._serialize_shards(tmp)
            # per-rank shards (dist layout) live OUTSIDE the renamed dir —
            # other processes wrote them; the manifest records what rank 0
            # expects so validate() still covers them after adoption
            shards.update(self._adopt_rank_shards(tmp))
            try:
                _faults.fire("checkpoint.commit", step=self.step)
            except _faults.TornWrite:
                # emulate the writer dying between the directory landing
                # and the manifest write: the torn directory is committed
                # WITHOUT a manifest and the write "succeeds" silently —
                # exactly what a killed process leaves behind.  validate()
                # must reject it and latest() must fall back one commit.
                final = os.path.join(self.root,
                                     _manifest.checkpoint_dirname(self.step))
                if os.path.isdir(final):
                    shutil.rmtree(final)
                os.replace(tmp, final)
                return
            _manifest.write_manifest(
                tmp, step=self.step, epoch=self.epoch, nbatch=self.nbatch,
                shards=shards, rng=self.rng, meta=self.meta,
                num_ranks=self.num_ranks)
            final = os.path.join(self.root,
                                 _manifest.checkpoint_dirname(self.step))
            if os.path.isdir(final):
                shutil.rmtree(final)
            os.replace(tmp, final)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        if self.retire is not None:
            # O(1) retention: the manager tracks its own commit history,
            # so steady-state retirement deletes ONE known directory
            # instead of re-scanning and re-validating the whole root on
            # every snapshot (a full `manifest.gc` sweep runs once at
            # manager construction to clear prior-run leftovers)
            for stale in self.retire(final):
                shutil.rmtree(stale, ignore_errors=True)

    def _adopt_rank_shards(self, tmp):
        """Move this step's per-rank shard files (written by other worker
        processes into ``root/rank-shards/``) inside the checkpoint dir so
        the atomic rename commits them together with rank 0's shards."""
        shards = {}
        pool_dir = os.path.join(self.root, "rank-shards")
        if self.num_ranks <= 1 or not os.path.isdir(pool_dir):
            return shards
        prefix = "step-%d-" % self.step
        for name in sorted(os.listdir(pool_dir)):
            if not name.startswith(prefix):
                continue
            dst = os.path.join(tmp, name)
            os.replace(os.path.join(pool_dir, name), dst)
            shards[name] = _manifest.shard_entry(dst)
        return shards

    def _write_rank_shard(self):
        """Non-primary ranks publish their shards into a shared side pool;
        rank 0's manifest+rename is the only commit point.  Shards for
        steps older than this one are this rank's own superseded
        publications — retire them here so the pool cannot grow without
        bound when commits lag."""
        pool_dir = os.path.join(self.root, "rank-shards")
        os.makedirs(pool_dir, exist_ok=True)
        payload = {"arrays": self.arrays, "blobs": self.blobs,
                   "rng": self.rng}
        fname = "step-%d-rank-%d.bin" % (self.step, self.rank)
        tmp = os.path.join(pool_dir, ".%s.tmp.%d" % (fname, os.getpid()))
        with open(tmp, "wb") as f:
            pickle.dump(payload, f, protocol=_PICKLE_PROTO)
        os.replace(tmp, os.path.join(pool_dir, fname))
        suffix = "-rank-%d.bin" % self.rank
        for name in os.listdir(pool_dir):
            if name.startswith("step-") and name.endswith(suffix):
                try:
                    if int(name[5:-len(suffix)]) < self.step:
                        os.remove(os.path.join(pool_dir, name))
                except (ValueError, OSError):
                    continue


class SnapshotWriter:
    """Background serializer with double-buffering (one in-flight write)."""

    def __init__(self):
        self._cond = _alocks.make_condition(name="checkpoint.writer")
        self._job = None
        self._busy = False
        self._error = None
        self._closed = False
        self._thread = None

    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name="mx-ckpt-writer", daemon=True)
            self._thread.start()

    def _run(self):
        while True:
            with self._cond:
                while self._job is None and not self._closed:
                    self._cond.wait()
                if self._job is None and self._closed:
                    return
                job, self._job = self._job, None
                self._busy = True
            try:
                job.write()
            except BaseException as e:  # surfaced on next submit/flush
                with self._cond:
                    self._error = e
            finally:
                with self._cond:
                    self._busy = False
                    self._cond.notify_all()

    def _raise_pending(self):
        if self._error is not None:
            err, self._error = self._error, None
            raise MXNetError(f"background checkpoint write failed: {err!r}") \
                from err

    def submit(self, job, sync=False):
        """Queue `job`; waits for any in-flight write first (double-buffer:
        at most one snapshot in flight).  ``sync=True`` additionally waits
        for THIS job to land before returning."""
        self._ensure_thread()
        with self._cond:
            while self._job is not None or self._busy:
                self._cond.wait()
            self._raise_pending()
            self._job = job
            self._cond.notify_all()
        if sync:
            self.flush()

    def flush(self):
        """Block until no snapshot is queued or being written (the
        ``waitall()`` of the checkpoint plane); re-raise deferred errors."""
        with self._cond:
            while self._job is not None or self._busy:
                self._cond.wait()
            self._raise_pending()

    def close(self):
        self.flush()
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        if self._thread is not None:
            _tsan.join_thread(self._thread, 10, owner="SnapshotWriter")
            self._thread = None
        self._closed = False
