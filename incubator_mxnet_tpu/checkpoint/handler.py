"""Elastic checkpoint handler for the gluon Estimator.

Unlike `estimator.CheckpointHandler` (parameters only, once per epoch),
this handler captures the FULL training state — net parameters, Trainer
optimizer slots + update counts, RNG streams, epoch/batch position —
through the async snapshot plane, restores all of it on ``fit`` (resume
continues mid-epoch), and arms the preemption hook for the duration of
training.
"""
from __future__ import annotations

from ..gluon.contrib.estimator import EventHandler
from . import manager as _manager
from . import state as _state

__all__ = ["ElasticCheckpointHandler"]


class ElasticCheckpointHandler(EventHandler):
    def __init__(self, directory, period=100, keep_last=5, resume=True,
                 preemption_hook=True, manager=None):
        self.period = max(1, int(period))
        self.resume = bool(resume)
        self.preemption_hook = bool(preemption_hook)
        self.manager = manager or _manager.CheckpointManager(
            directory, keep_last=keep_last)
        self._step = 0

    # -- capture ---------------------------------------------------------------
    def _snapshot(self, est, epoch, nbatch, sync=False, meta=None):
        arrays = _state.capture_gluon_net(est.net)
        blobs = {}
        trainer_blob = _state.capture_trainer(est.trainer)
        if trainer_blob:
            blobs[_state.TRAINER_BLOB] = trainer_blob
        self.manager.snapshot(arrays=arrays, blobs=blobs, step=self._step,
                              epoch=epoch, nbatch=nbatch, sync=sync,
                              meta=meta)

    # -- events ----------------------------------------------------------------
    def train_begin(self, est):
        if self.resume:
            data = self.manager.load_latest()
            if data is not None:
                _state.restore_gluon_net(est.net, data.arrays)
                _state.restore_trainer(est.trainer,
                                       data.blobs.get(_state.TRAINER_BLOB))
                _state.restore_rng(data.rng)
                est._epochs_done = data.epoch
                est._resume_batches = data.nbatch
                # relaunch-the-same-command semantics: fit(epochs=N) after
                # resume trains TO N total epochs, not N more
                est._resume_total_epochs = True
                self._step = data.step
        if self.preemption_hook:
            self.manager.install_preemption_hook()

    def batch_end(self, est):
        self._step += 1
        # the resume position is the batches whose updates LANDED, which
        # in fused block mode runs ahead of batch_idx during the
        # post-block handler burst (estimator.fit applies the whole block
        # before firing its batch_end events) — recording batch_idx there
        # would make resume replay already-applied updates
        nbatch = getattr(est, "_applied_batches", est.batch_idx + 1)
        # batch boundary = the consistent point where a requested
        # preemption may snapshot (see CheckpointManager.honor_preemption)
        self.manager.honor_preemption(
            lambda: self._snapshot(est, est.epoch, nbatch, sync=True,
                                   meta={"preempted": True}))
        if self._step % self.period == 0:
            self._snapshot(est, est.epoch, nbatch)

    def epoch_end(self, est):
        # epoch boundary: resume starts the NEXT epoch from its first batch
        self._snapshot(est, est.epoch + 1, 0)

    def train_end(self, est):
        self.manager.flush()
        self.manager.uninstall_preemption_hook()
