"""Full training-state capture & restore.

What a resumable checkpoint must hold beyond the weights (reference
`save_checkpoint` loses all of it): optimizer slots (momentum / Adam
moments via the `optimizer.Updater` state store, including the pickled
optimizer itself so `num_update` and the LR-scheduler position travel
along), Module/Trainer update counts, the data iterator's position, and
every RNG stream that shapes the run (framework threefry chain, host
SeedSequence counter, numpy's global generator — the one `NDArrayIter`
shuffles with).  Restoring all of it makes a resumed run bit-for-bit
identical to an uninterrupted one on the same backend.
"""
from __future__ import annotations

import pickle

import numpy as np

from ..base import MXNetError

OPTIMIZER_BLOB = "optimizer"
ITERATOR_BLOB = "iterator"
TRAINER_BLOB = "trainer"
NET_ARRAYS_PREFIX = "param:"


# -- RNG ---------------------------------------------------------------------
def capture_rng():
    """JSON-able snapshot of every RNG stream training consumes."""
    from .. import random as _random
    state = {}
    key = getattr(_random._state, "key", None)
    if key is not None:
        state["key"] = np.asarray(key).tolist()
    host_seq = getattr(_random._state, "host_seq", None)
    if host_seq is not None:
        state["host_seq"] = list(host_seq)
    name, keys, pos, has_gauss, cached = np.random.get_state()
    state["numpy"] = [name, np.asarray(keys).tolist(), int(pos),
                      int(has_gauss), float(cached)]
    return state


def restore_rng(state):
    if not state:
        return
    from .. import random as _random
    if "key" in state:
        import jax.numpy as jnp
        _random._state.key = jnp.asarray(np.asarray(state["key"],
                                                    dtype=np.uint32))
    if "host_seq" in state:
        _random._state.host_seq = [int(x) for x in state["host_seq"]]
    if "numpy" in state:
        name, keys, pos, has_gauss, cached = state["numpy"]
        np.random.set_state((name, np.asarray(keys, dtype=np.uint32),
                             int(pos), int(has_gauss), float(cached)))


# -- data iterators ----------------------------------------------------------
def capture_iterator(data_iter):
    """Pickled native iterator state (``DataIter.checkpoint_state``), or
    None when the iterator has nothing beyond its batch position — resume
    then falls back to ``seek(nbatch)`` (reset + skip)."""
    getter = getattr(data_iter, "checkpoint_state", None)
    if getter is None:
        return None
    state = getter()
    if not state:
        return None
    return pickle.dumps(state, protocol=4)


def restore_iterator(data_iter, blob, nbatch):
    """Native restore when the iterator supports it, reset+skip otherwise."""
    state = pickle.loads(blob) if blob else {}
    setter = getattr(data_iter, "set_checkpoint_state", None)
    if setter is not None:
        setter(state, nbatch=nbatch)
        return
    seek = getattr(data_iter, "seek", None)
    if seek is not None:
        seek(nbatch)
        return
    for _ in range(int(nbatch)):
        next(data_iter)


# -- Module ------------------------------------------------------------------
def capture_module(mod, data_iter=None):
    """(arrays, blobs) for a bound+initialized Module: params + aux under
    the classic ``arg:``/``aux:`` prefixes, optimizer slots as one pickled
    blob (kvstore-aware), the iterator's native state when given."""
    arg_params, aux_params = mod.get_params()
    arrays = {f"arg:{k}": v for k, v in arg_params.items()}
    arrays.update({f"aux:{k}": v for k, v in aux_params.items()})
    blobs = {}
    if mod.optimizer_initialized:
        blobs[OPTIMIZER_BLOB] = mod.get_optimizer_states_blob()
    if data_iter is not None:
        it_blob = capture_iterator(data_iter)
        if it_blob is not None:
            blobs[ITERATOR_BLOB] = it_blob
    return arrays, blobs


def split_params(arrays):
    """{'arg:...'/'aux:...': np.ndarray} -> (arg_params, aux_params) of
    NDArrays, the shape Module.init_params consumes."""
    from ..ndarray.ndarray import array
    arg_params, aux_params = {}, {}
    for key, value in arrays.items():
        kind, _, name = key.partition(":")
        if kind == "arg":
            arg_params[name] = array(value)
        elif kind == "aux":
            aux_params[name] = array(value)
        else:
            raise MXNetError(f"checkpoint array key {key!r} is neither "
                             "arg: nor aux:")
    return arg_params, aux_params


def restore_module_optimizer(mod, blob):
    if blob:
        mod.set_optimizer_states_blob(blob)


# -- Gluon -------------------------------------------------------------------
def capture_gluon_net(net):
    """{param: first-context value} for every parameter of a gluon block."""
    arrays = {}
    for name, param in net.collect_params().items():
        try:
            arrays[NET_ARRAYS_PREFIX + name] = param.list_data()[0]
        except Exception:
            continue  # deferred-init param with no value yet
    return arrays


def restore_gluon_net(net, arrays):
    from .. import ndarray as nd
    params = net.collect_params()
    for key, value in arrays.items():
        if not key.startswith(NET_ARRAYS_PREFIX):
            continue
        name = key[len(NET_ARRAYS_PREFIX):]
        if name not in params:
            raise MXNetError(
                f"checkpoint has parameter {name!r} the net does not")
        params[name].set_data(nd.array(np.asarray(value)))


def capture_trainer(trainer):
    return trainer.get_checkpoint_state() if trainer is not None else None


def restore_trainer(trainer, blob):
    if trainer is not None and blob:
        trainer.set_checkpoint_state(blob)
