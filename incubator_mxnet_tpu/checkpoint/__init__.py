"""Elastic checkpointing & auto-resume.

The fault-tolerance primitive the reference lacks (its `save_checkpoint`
is synchronous, whole-model, and loses optimizer/iterator state): async
snapshots that overlap the train step, atomic manifests a killed writer
can never tear, full training-state capture, and auto-resume that
continues mid-epoch — including after SIGTERM preemption.

Entry points:

* ``CheckpointManager`` — owns a checkpoint directory (async writer,
  retention GC, resume, preemption hook)
* ``latest(dir)`` / ``load(path)`` — find and read valid checkpoints
* ``Module.fit(..., checkpoint_dir=..., resume=True)`` — classic API
  integration (see `module/base_module.py`)
* ``ElasticCheckpointHandler`` — gluon Estimator integration
* ``install_preemption_hook`` — final synchronous snapshot on SIGTERM

See the README section "Checkpointing & fault tolerance" for the
manifest format and the dist (multi-rank) layout.
"""
from __future__ import annotations

from . import manifest
from . import snapshot
from . import state
from .manifest import latest_healthy, stamp_rejected, rejection, is_rejected
from .manager import (CheckpointManager, CheckpointData, latest, load,
                      install_preemption_hook)
from .handler import ElasticCheckpointHandler

__all__ = ["CheckpointManager", "CheckpointData", "latest", "load",
           "latest_healthy", "stamp_rejected", "rejection", "is_rejected",
           "install_preemption_hook",
           "ElasticCheckpointHandler", "manifest", "snapshot", "state"]
