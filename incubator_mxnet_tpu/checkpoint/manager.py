"""CheckpointManager: the user-facing elastic checkpointing handle.

One manager per checkpoint directory owns the async writer, retention,
and resume.  ``snapshot()`` stages training state into pooled host
buffers and returns while a background thread serializes and atomically
commits the checkpoint (see `snapshot.py`); ``flush()`` waits for the
in-flight write; ``load_latest()`` returns the newest checkpoint whose
manifest and shard checksums verify.  ``install_preemption_hook`` wires
a SIGTERM handler that takes one final SYNCHRONOUS snapshot when the
scheduler serves an eviction notice, then exits.

Dist layout (``kvstore='dist_*'``): rank 0 writes params + manifest and
owns the atomic commit; every other rank publishes its shard into
``<dir>/rank-shards/`` where rank 0's next commit adopts it (so a torn
multi-rank write is still invisible to ``latest()``).
"""
from __future__ import annotations

import os
import pickle
import signal
import threading

from ..base import MXNetError
from . import manifest as _manifest
from . import snapshot as _snapshot
from . import state as _state

__all__ = ["CheckpointManager", "CheckpointData", "latest", "load",
           "install_preemption_hook"]


class CheckpointData:
    """One loaded checkpoint: host arrays, raw blobs, and the manifest."""

    def __init__(self, path, manifest, arrays, blobs):
        self.path = path
        self.manifest = manifest
        self.step = int(manifest.get("step", 0))
        self.epoch = int(manifest.get("epoch", 0))
        self.nbatch = int(manifest.get("nbatch", 0))
        self.rng = manifest.get("rng")
        self.meta = manifest.get("meta", {})
        self.arrays = arrays      # {name: np.ndarray}
        self.blobs = blobs        # {name: bytes} (shard stem -> contents)

    def rank_shard(self, rank):
        """The payload dict ({'arrays', 'blobs', 'rng'}) a given rank
        published for this step, or None when that rank's shard did not
        make this commit (a lagging rank — its state falls back to
        position-only resume)."""
        blob = self.blobs.get("step-%d-rank-%d" % (self.step, int(rank)))
        if blob is None:
            return None
        return pickle.loads(blob)


def latest(root, deep=True, include_rejected=False):
    """Newest VALID checkpoint directory under `root`, or None (torn
    checkpoints never selected — see `manifest.validate`; canary-
    rejected ones skipped unless `include_rejected`)."""
    return _manifest.latest(root, deep=deep,
                            include_rejected=include_rejected)


def load(path):
    """Read one checkpoint directory back into host memory."""
    if not _manifest.validate(path):
        raise MXNetError(f"{path}: not a valid checkpoint (torn write or "
                         "corrupt shard)")
    manifest = _manifest.read_manifest(path)
    arrays, blobs = {}, {}
    for name in manifest.get("shards", {}):
        fpath = os.path.join(path, name)
        if name == _snapshot.ARRAYS_SHARD:
            arrays = _snapshot.read_array_shard(fpath)
        else:
            stem = name[:-4] if name.endswith(".bin") else name
            with open(fpath, "rb") as f:
                blobs[stem] = f.read()
    return CheckpointData(path, manifest, arrays, blobs)


class CheckpointManager:
    def __init__(self, directory, keep_last=5, async_snapshots=True,
                 rank=0, num_ranks=1):
        self.directory = str(directory)
        self.keep_last = int(keep_last)
        self.async_snapshots = bool(async_snapshots)
        self.rank = int(rank)
        self.num_ranks = int(num_ranks)
        self._writer = _snapshot.SnapshotWriter()
        self._preemption_capture = None
        self._uninstall_hook = None
        self.preempt_requested = False
        self.preempt_exit_code = 143
        os.makedirs(self.directory, exist_ok=True)
        if self.rank == 0:
            # one full sweep at construction clears a prior run's torn
            # directories; steady-state retention is then O(1) per commit
            # (see _retire) — a full rescan per snapshot costs real wall
            # time on metadata-slow filesystems
            _manifest.gc(self.directory, self.keep_last)
            self._committed = [path for _, path in
                               _manifest.list_checkpoints(
                                   self.directory, valid_only=True,
                                   deep=False)]
        else:
            self._committed = []

    def _retire(self, committed_path):
        """Called by the background writer after each commit: returns the
        directories that just fell off the retention window."""
        if committed_path in self._committed:
            return []
        self._committed.append(committed_path)
        stale, self._committed = (self._committed[:-self.keep_last],
                                  self._committed[-self.keep_last:])
        return stale

    # -- writing ---------------------------------------------------------------
    def snapshot(self, arrays=None, blobs=None, step=0, epoch=0, nbatch=0,
                 meta=None, include_rng=True, sync=False):
        """Stage a checkpoint and hand it to the background writer.

        `arrays` values may be NDArrays / jax arrays / numpy arrays; they
        are copied into pooled host buffers BEFORE this returns, so the
        caller may keep training (and mutating the originals) while the
        write is in flight.  `blobs` are opaque bytes, one shard file
        each.  ``sync=True`` waits for the commit (the preemption path).
        """
        staged, release = _snapshot.gather_to_pool(arrays or {})
        # every rank's RNG streams are rank-local state: rank 0's ride the
        # manifest, other ranks' ride their shard payload
        rng = _state.capture_rng() if include_rng else None
        job = _snapshot.SnapshotJob(
            self.directory, step=step, epoch=epoch, nbatch=nbatch,
            arrays=staged, blobs=blobs, rng=rng, meta=meta,
            retire=self._retire if self.rank == 0 else None,
            rank=self.rank, num_ranks=self.num_ranks, release=release)
        if self.async_snapshots and not sync:
            self._writer.submit(job)
        else:
            self._writer.submit(job, sync=True)
        return job.step

    def flush(self):
        """Wait until no snapshot is in flight (checkpoint `waitall()`)."""
        self._writer.flush()

    def close(self):
        self.uninstall_preemption_hook()
        self._writer.close()

    # -- reading ---------------------------------------------------------------
    def latest(self):
        return latest(self.directory)

    def load_latest(self):
        path = self.latest()
        return load(path) if path is not None else None

    # -- preemption ------------------------------------------------------------
    def install_preemption_hook(self, signals=("SIGTERM",), exit_code=143):
        """Arm SIGTERM (by default) to REQUEST preemption: the handler
        only sets `preempt_requested`; the training loop observes it at
        the next batch boundary, takes one final SYNCHRONOUS snapshot
        there, and exits with `exit_code` (`honor_preemption`).

        The two-phase protocol exists for consistency: a signal lands
        between arbitrary bytecodes, where the loop's (epoch, batch,
        step) bookkeeping can lag the already-updated parameters —
        snapshotting directly from the handler would capture a position
        the params have moved past, and resume would replay applied
        batches.  At a batch boundary state and position agree.

        Returns an uninstall callable; no-op off the main thread
        (CPython restricts signal handlers to it)."""
        if self._uninstall_hook is not None:
            return self._uninstall_hook
        self.preempt_exit_code = exit_code

        def request():
            self.preempt_requested = True

        try:
            self._uninstall_hook = install_preemption_hook(
                request, signals=signals, exit_code=None)
        except (ValueError, OSError):  # not the main thread / no signals
            self._uninstall_hook = None
        return self._uninstall_hook

    def honor_preemption(self, capture):
        """Called by training loops at a consistent boundary when
        `preempt_requested` is set: run `capture()` (which must snapshot
        synchronously), then exit with the armed exit code.

        Best-effort by design: a deferred error from an EARLIER async
        write (submit/flush re-raise those) must not cost the final
        snapshot — the first attempt clears the stale error, so one retry
        gets a clean writer; and whatever happens, the process still
        exits with the code the scheduler keys on."""
        if not self.preempt_requested:
            return
        try:
            try:
                capture()
            except MXNetError:
                capture()   # stale background-write error cleared above
            self.flush()
        except BaseException:
            import logging
            logging.getLogger(__name__).exception(
                "final preemption snapshot failed; exiting anyway — "
                "resume will use the last committed checkpoint")
            if self.preempt_exit_code is None:
                raise
        finally:
            if self.preempt_exit_code is not None:
                os._exit(self.preempt_exit_code)
        self.preempt_requested = False

    def uninstall_preemption_hook(self):
        if self._uninstall_hook is not None:
            self._uninstall_hook()
            self._uninstall_hook = None


def install_preemption_hook(capture, signals=("SIGTERM",), exit_code=143):
    """Run ``capture()`` when a preemption signal lands, then exit with
    `exit_code` (143 = 128+SIGTERM, the conventional code
    preemption-aware schedulers expect; None = return to the program).
    The previous handler is restored by the returned uninstall callable.
    Must be called from the main thread.

    Standalone users: `capture` runs INSIDE the signal handler, between
    two arbitrary bytecodes of whatever was executing — only use this
    directly when the captured state is consistent at every bytecode.
    Training loops should go through `CheckpointManager`'s two-phase
    request/honor protocol instead (see `install_preemption_hook` on the
    manager)."""
    sigs = []
    for s in signals:
        sigs.append(getattr(signal, s) if isinstance(s, str) else s)

    def handler(signum, frame):
        try:
            capture()
        finally:
            if exit_code is not None:
                # handlers run between bytecodes of the main thread: the
                # capture above fully committed, so a hard exit is safe
                # and beats unwinding through arbitrary training code
                os._exit(exit_code)

    previous = {s: signal.signal(s, handler) for s in sigs}

    def uninstall():
        for s, prev in previous.items():
            try:
                signal.signal(s, prev)
            except (ValueError, TypeError, OSError):
                pass
    return uninstall
