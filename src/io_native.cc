// Native IO hot paths (the role of the reference's C++ data plane:
// dmlc-core recordio parsing + src/io/iter_image_recordio_2.cc's
// decode/augment inner loops).  Python orchestrates (threads, cv2 JPEG
// decode which releases the GIL); these kernels do the byte work without
// the interpreter: record scanning, and the crop/mirror/normalize/
// HWC->CHW finish that dominates post-decode time.
//
// Built as a plain shared library, bound via ctypes (no pybind11 in this
// image).  ctypes releases the GIL for the duration of every call, so N
// worker threads get true parallelism here.
#include <cstdint>
#include <cstring>

// 3-channel inner row: fixed channel mapping and no per-pixel branches so
// the compiler can vectorize (the c==3 case is every image pipeline).
template <int kStep, int kC0, int kC1, int kC2>
static inline void row3(const uint8_t* px, float* d0, float* d1, float* d2,
                        int64_t n, const float* mean, const float* stdinv) {
  const float m0 = mean[0], m1 = mean[1], m2 = mean[2];
  const float s0 = stdinv[0], s1 = stdinv[1], s2 = stdinv[2];
  for (int64_t x = 0; x < n; ++x, px += kStep) {
    d0[x] = (static_cast<float>(px[kC0]) - m0) * s0;
    d1[x] = (static_cast<float>(px[kC1]) - m1) * s1;
    d2[x] = (static_cast<float>(px[kC2]) - m2) * s2;
  }
}

extern "C" {

// dmlc recordio framing: [u32 magic 0xced7230a][u32 cflag<<29|len][payload]
// padded to 4 bytes (python/mxnet/recordio.py, dmlc-core/recordio.h).
// Fills payload offsets+lengths+cflags (0 whole, 1 start, 2 middle,
// 3 end of a multi-part record — dmlc writers split payloads containing
// the magic word); returns part count, or -1 on a bad magic (corrupt
// file), -2 if max_n too small.  Callers group 1/2*/3 sequences into one
// logical record, re-inserting the magic word between parts.
int64_t mxtpu_recordio_index(const uint8_t* buf, int64_t len,
                             int64_t* offsets, int64_t* lengths,
                             int32_t* cflags, int64_t max_n) {
  static const uint32_t kMagic = 0xced7230a;
  int64_t pos = 0, n = 0;
  while (pos + 8 <= len) {
    uint32_t magic, lrec;
    std::memcpy(&magic, buf + pos, 4);
    std::memcpy(&lrec, buf + pos + 4, 4);
    if (magic != kMagic) return -1;
    int64_t dlen = lrec & ((1u << 29) - 1);
    if (pos + 8 + dlen > len) break;  // truncated tail record
    if (n >= max_n) return -2;
    offsets[n] = pos + 8;
    lengths[n] = dlen;
    cflags[n] = static_cast<int32_t>(lrec >> 29);
    ++n;
    int64_t pad = (4 - dlen % 4) % 4;
    pos += 8 + dlen + pad;
  }
  return n;
}

// Crop + optional horizontal mirror + per-channel normalize + HWC u8 ->
// CHW f32.  `stdinv` is 1/std (precomputed; multiply beats divide).
// The three channel planes are written contiguously: dst[(c)(out_h)(out_w)].
// `channel_reverse` flips the channel order on the way through (BGR
// source -> RGB planes), letting callers skip a separate cvtColor pass.
void mxtpu_augment_to_chw(const uint8_t* src, int64_t h, int64_t w,
                          int64_t c, int64_t crop_y, int64_t crop_x,
                          int64_t out_h, int64_t out_w, int mirror,
                          const float* mean, const float* stdinv,
                          float* dst, int channel_reverse) {
  (void)h;
  const int64_t plane = out_h * out_w;
  if (c == 3) {
    for (int64_t y = 0; y < out_h; ++y) {
      const uint8_t* row = src + ((crop_y + y) * w + crop_x) * 3;
      float* d0 = dst + y * out_w;
      float* d1 = d0 + plane;
      float* d2 = d1 + plane;
      const uint8_t* px = mirror ? row + (out_w - 1) * 3 : row;
      if (channel_reverse) {  // BGR source -> RGB planes
        if (mirror)
          row3<-3, 2, 1, 0>(px, d0, d1, d2, out_w, mean, stdinv);
        else
          row3<3, 2, 1, 0>(px, d0, d1, d2, out_w, mean, stdinv);
      } else {
        if (mirror)
          row3<-3, 0, 1, 2>(px, d0, d1, d2, out_w, mean, stdinv);
        else
          row3<3, 0, 1, 2>(px, d0, d1, d2, out_w, mean, stdinv);
      }
    }
    return;
  }
  for (int64_t y = 0; y < out_h; ++y) {
    const uint8_t* row = src + ((crop_y + y) * w + crop_x) * c;
    float* drow = dst + y * out_w;
    for (int64_t x = 0; x < out_w; ++x) {
      int64_t sx = mirror ? (out_w - 1 - x) : x;
      const uint8_t* px = row + sx * c;
      for (int64_t ch = 0; ch < c; ++ch) {
        int64_t oc = channel_reverse ? (c - 1 - ch) : ch;
        drow[oc * plane + x] = (static_cast<float>(px[ch]) - mean[oc])
                               * stdinv[oc];
      }
    }
  }
}

// Batched variant: one ctypes call finishes a whole batch (OpenMP when
// cores exist; on a 1-core host it simply amortizes call overhead).
void mxtpu_augment_batch(const uint8_t** srcs, const int64_t* hs,
                         const int64_t* ws, int64_t c,
                         const int64_t* crop_ys, const int64_t* crop_xs,
                         int64_t out_h, int64_t out_w, const int* mirrors,
                         const float* mean, const float* stdinv, float* dst,
                         int64_t n, int channel_reverse) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    mxtpu_augment_to_chw(srcs[i], hs[i], ws[i], c, crop_ys[i], crop_xs[i],
                         out_h, out_w, mirrors[i], mean, stdinv,
                         dst + i * c * out_h * out_w, channel_reverse);
  }
}

// Device-augment mode: crop + optional mirror + BGR->RGB into uint8 HWC.
// No float math, no layout change — normalize/cast/NCHW happen IN the
// training program on the accelerator (ops ImageNormalize), so the host
// only moves a quarter of the bytes the fp32 finish wrote.
void mxtpu_crop_u8_hwc(const uint8_t* src, int64_t w, int64_t c,
                       int64_t crop_y, int64_t crop_x, int64_t out_h,
                       int64_t out_w, int mirror, uint8_t* dst,
                       int channel_reverse) {
  for (int64_t y = 0; y < out_h; ++y) {
    const uint8_t* row = src + ((crop_y + y) * w + crop_x) * c;
    uint8_t* drow = dst + y * out_w * c;
    if (c == 3) {
      if (!mirror && !channel_reverse) {
        std::memcpy(drow, row, static_cast<size_t>(out_w) * 3);
        continue;
      }
      const uint8_t* px = mirror ? row + (out_w - 1) * 3 : row;
      const int64_t step = mirror ? -3 : 3;
      if (channel_reverse) {
        for (int64_t x = 0; x < out_w; ++x, px += step) {
          drow[x * 3 + 0] = px[2];
          drow[x * 3 + 1] = px[1];
          drow[x * 3 + 2] = px[0];
        }
      } else {
        for (int64_t x = 0; x < out_w; ++x, px += step) {
          drow[x * 3 + 0] = px[0];
          drow[x * 3 + 1] = px[1];
          drow[x * 3 + 2] = px[2];
        }
      }
      continue;
    }
    for (int64_t x = 0; x < out_w; ++x) {
      const uint8_t* px = row + (mirror ? (out_w - 1 - x) : x) * c;
      for (int64_t ch = 0; ch < c; ++ch) {
        int64_t oc = channel_reverse ? (c - 1 - ch) : ch;
        drow[x * c + oc] = px[ch];
      }
    }
  }
}

void mxtpu_crop_batch_u8(const uint8_t** srcs, const int64_t* hs,
                         const int64_t* ws, int64_t c,
                         const int64_t* crop_ys, const int64_t* crop_xs,
                         int64_t out_h, int64_t out_w, const int* mirrors,
                         uint8_t* dst, int64_t n, int channel_reverse) {
  (void)hs;
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    mxtpu_crop_u8_hwc(srcs[i], ws[i], c, crop_ys[i], crop_xs[i], out_h,
                      out_w, mirrors[i], dst + i * out_h * out_w * c,
                      channel_reverse);
  }
}

}  // extern "C"
