// Native IO hot paths (the role of the reference's C++ data plane:
// dmlc-core recordio parsing + src/io/iter_image_recordio_2.cc's
// decode/augment inner loops).  Python orchestrates (threads, cv2 JPEG
// decode which releases the GIL); these kernels do the byte work without
// the interpreter: record scanning, and the crop/mirror/normalize/
// HWC->CHW finish that dominates post-decode time.
//
// Built as a plain shared library, bound via ctypes (no pybind11 in this
// image).  ctypes releases the GIL for the duration of every call, so N
// worker threads get true parallelism here.
#include <cstdint>
#include <cstring>

extern "C" {

// dmlc recordio framing: [u32 magic 0xced7230a][u32 cflag<<29|len][payload]
// padded to 4 bytes (python/mxnet/recordio.py, dmlc-core/recordio.h).
// Fills payload offsets+lengths+cflags (0 whole, 1 start, 2 middle,
// 3 end of a multi-part record — dmlc writers split payloads containing
// the magic word); returns part count, or -1 on a bad magic (corrupt
// file), -2 if max_n too small.  Callers group 1/2*/3 sequences into one
// logical record, re-inserting the magic word between parts.
int64_t mxtpu_recordio_index(const uint8_t* buf, int64_t len,
                             int64_t* offsets, int64_t* lengths,
                             int32_t* cflags, int64_t max_n) {
  static const uint32_t kMagic = 0xced7230a;
  int64_t pos = 0, n = 0;
  while (pos + 8 <= len) {
    uint32_t magic, lrec;
    std::memcpy(&magic, buf + pos, 4);
    std::memcpy(&lrec, buf + pos + 4, 4);
    if (magic != kMagic) return -1;
    int64_t dlen = lrec & ((1u << 29) - 1);
    if (pos + 8 + dlen > len) break;  // truncated tail record
    if (n >= max_n) return -2;
    offsets[n] = pos + 8;
    lengths[n] = dlen;
    cflags[n] = static_cast<int32_t>(lrec >> 29);
    ++n;
    int64_t pad = (4 - dlen % 4) % 4;
    pos += 8 + dlen + pad;
  }
  return n;
}

// Crop + optional horizontal mirror + per-channel normalize + HWC u8 ->
// CHW f32.  `stdinv` is 1/std (precomputed; multiply beats divide).
// The three channel planes are written contiguously: dst[(c)(out_h)(out_w)].
void mxtpu_augment_to_chw(const uint8_t* src, int64_t h, int64_t w,
                          int64_t c, int64_t crop_y, int64_t crop_x,
                          int64_t out_h, int64_t out_w, int mirror,
                          const float* mean, const float* stdinv,
                          float* dst) {
  (void)h;
  const int64_t plane = out_h * out_w;
  for (int64_t y = 0; y < out_h; ++y) {
    const uint8_t* row = src + ((crop_y + y) * w + crop_x) * c;
    float* drow = dst + y * out_w;
    for (int64_t x = 0; x < out_w; ++x) {
      int64_t sx = mirror ? (out_w - 1 - x) : x;
      const uint8_t* px = row + sx * c;
      for (int64_t ch = 0; ch < c; ++ch) {
        drow[ch * plane + x] = (static_cast<float>(px[ch]) - mean[ch])
                               * stdinv[ch];
      }
    }
  }
}

// Batched variant: one call finishes a whole batch with OpenMP threads.
void mxtpu_augment_batch(const uint8_t** srcs, const int64_t* hs,
                         const int64_t* ws, int64_t c,
                         const int64_t* crop_ys, const int64_t* crop_xs,
                         int64_t out_h, int64_t out_w, const int* mirrors,
                         const float* mean, const float* stdinv, float* dst,
                         int64_t n) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    mxtpu_augment_to_chw(srcs[i], hs[i], ws[i], c, crop_ys[i], crop_xs[i],
                         out_h, out_w, mirrors[i], mean, stdinv,
                         dst + i * c * out_h * out_w);
  }
}

}  // extern "C"
