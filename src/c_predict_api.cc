// C predict ABI implementation: embeds CPython and drives
// incubator_mxnet_tpu.c_predict (see c_predict_api.h for the contract).
//
// The reference implements its predict ABI over the full C++ runtime
// (`src/c_api/c_predict_api.cc`); here the runtime under the ABI is the
// framework's XLA executor, reached through an embedded interpreter.  The
// interpreter is initialized lazily on first create and shared by all
// predictors; every entry point holds the GIL only for its own duration,
// so multiple threads may run separate predictors.
#include "c_predict_api.h"

#include <Python.h>

#include <string>
#include <vector>

namespace {

thread_local std::string g_last_error;

struct Predictor {
  PyObject *obj;          // incubator_mxnet_tpu.c_predict.Predictor
  std::vector<uint32_t> shape_buf;  // backs MXTPUPredGetOutputShape
};

void set_error_from_python() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  g_last_error = "unknown python error";
  if (value != nullptr) {
    PyObject *s = PyObject_Str(value);
    if (s != nullptr) {
      const char *msg = PyUnicode_AsUTF8(s);
      if (msg != nullptr) g_last_error = msg;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

bool ensure_interpreter() {
  if (Py_IsInitialized()) return true;
  Py_InitializeEx(0);
  if (!Py_IsInitialized()) {
    g_last_error = "failed to initialize embedded Python";
    return false;
  }
  // release the GIL acquired by initialization so entry points can take it
  PyEval_SaveThread();
  return true;
}

class GilGuard {
 public:
  GilGuard() : state_(PyGILState_Ensure()) {}
  ~GilGuard() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

PyObject *call_method(PyObject *obj, const char *name, PyObject *args) {
  PyObject *fn = PyObject_GetAttrString(obj, name);
  if (fn == nullptr) return nullptr;
  PyObject *ret = PyObject_CallObject(fn, args);
  Py_DECREF(fn);
  return ret;
}

}  // namespace

extern "C" {

const char *MXTPUGetLastError(void) { return g_last_error.c_str(); }

int MXTPUPredCreate(const char *symbol_json, const void *param_bytes,
                    size_t param_size, int dev_type, int dev_id,
                    uint32_t num_input_nodes, const char **input_keys,
                    const uint32_t *input_shape_indptr,
                    const uint32_t *input_shape_data,
                    PredictorHandle *out) {
  if (!ensure_interpreter()) return -1;
  GilGuard gil;
  PyObject *mod = PyImport_ImportModule("incubator_mxnet_tpu.c_predict");
  if (mod == nullptr) {
    set_error_from_python();
    return -1;
  }
  PyObject *names = PyList_New(num_input_nodes);
  PyObject *shapes = PyList_New(num_input_nodes);
  for (uint32_t i = 0; i < num_input_nodes; ++i) {
    PyList_SetItem(names, i, PyUnicode_FromString(input_keys[i]));
    uint32_t lo = input_shape_indptr[i], hi = input_shape_indptr[i + 1];
    PyObject *shp = PyTuple_New(hi - lo);
    for (uint32_t j = lo; j < hi; ++j)
      PyTuple_SetItem(shp, j - lo, PyLong_FromUnsignedLong(
                                       input_shape_data[j]));
    PyList_SetItem(shapes, i, shp);
  }
  PyObject *params = PyBytes_FromStringAndSize(
      static_cast<const char *>(param_bytes),
      static_cast<Py_ssize_t>(param_size));
  PyObject *args = Py_BuildValue("(sOiiOO)", symbol_json, params, dev_type,
                                 dev_id, names, shapes);
  Py_DECREF(params);
  Py_DECREF(names);
  Py_DECREF(shapes);
  PyObject *pred = call_method(mod, "create", args);
  Py_DECREF(args);
  Py_DECREF(mod);
  if (pred == nullptr) {
    set_error_from_python();
    return -1;
  }
  auto *h = new Predictor{pred, {}};
  *out = h;
  return 0;
}

int MXTPUPredSetInput(PredictorHandle handle, const char *key,
                      const float *data, uint32_t size) {
  auto *h = static_cast<Predictor *>(handle);
  GilGuard gil;
  PyObject *view = PyMemoryView_FromMemory(
      reinterpret_cast<char *>(const_cast<float *>(data)),
      static_cast<Py_ssize_t>(size) * 4, PyBUF_READ);
  PyObject *args = Py_BuildValue("(sO)", key, view);
  Py_DECREF(view);
  PyObject *ret = call_method(h->obj, "set_input_bytes", args);
  Py_DECREF(args);
  if (ret == nullptr) {
    set_error_from_python();
    return -1;
  }
  Py_DECREF(ret);
  return 0;
}

int MXTPUPredForward(PredictorHandle handle) {
  auto *h = static_cast<Predictor *>(handle);
  GilGuard gil;
  PyObject *ret = call_method(h->obj, "forward", nullptr);
  if (ret == nullptr) {
    set_error_from_python();
    return -1;
  }
  Py_DECREF(ret);
  return 0;
}

int MXTPUPredGetOutputShape(PredictorHandle handle, uint32_t index,
                            uint32_t **shape_data, uint32_t *shape_ndim) {
  auto *h = static_cast<Predictor *>(handle);
  GilGuard gil;
  PyObject *args = Py_BuildValue("(I)", index);
  PyObject *shp = call_method(h->obj, "output_shape", args);
  Py_DECREF(args);
  if (shp == nullptr) {
    set_error_from_python();
    return -1;
  }
  Py_ssize_t n = PyTuple_Size(shp);
  h->shape_buf.resize(static_cast<size_t>(n));
  for (Py_ssize_t i = 0; i < n; ++i)
    h->shape_buf[static_cast<size_t>(i)] = static_cast<uint32_t>(
        PyLong_AsUnsignedLong(PyTuple_GetItem(shp, i)));
  Py_DECREF(shp);
  *shape_data = h->shape_buf.data();
  *shape_ndim = static_cast<uint32_t>(n);
  return 0;
}

int MXTPUPredGetOutput(PredictorHandle handle, uint32_t index, float *data,
                       uint32_t size) {
  auto *h = static_cast<Predictor *>(handle);
  GilGuard gil;
  PyObject *args = Py_BuildValue("(I)", index);
  PyObject *bytes = call_method(h->obj, "output", args);
  Py_DECREF(args);
  if (bytes == nullptr) {
    set_error_from_python();
    return -1;
  }
  char *buf = nullptr;
  Py_ssize_t blen = 0;
  if (PyBytes_AsStringAndSize(bytes, &buf, &blen) != 0) {
    Py_DECREF(bytes);
    set_error_from_python();
    return -1;
  }
  if (static_cast<size_t>(blen) != static_cast<size_t>(size) * 4) {
    g_last_error = "output size mismatch";
    Py_DECREF(bytes);
    return -1;
  }
  memcpy(data, buf, static_cast<size_t>(blen));
  Py_DECREF(bytes);
  return 0;
}

int MXTPUPredFree(PredictorHandle handle) {
  auto *h = static_cast<Predictor *>(handle);
  if (h != nullptr) {
    GilGuard gil;
    Py_XDECREF(h->obj);
    delete h;
  }
  return 0;
}

}  // extern "C"
