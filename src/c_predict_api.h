/* Standalone C inference ABI for incubator_mxnet_tpu.
 *
 * Role of the reference's predict-only ABI
 * (`include/mxnet/c_predict_api.h:78-200`): load an exported model
 * (symbol JSON + params container), feed float32 inputs, run forward,
 * read float32 outputs — from any language with a C FFI, no Python
 * required at the call site.  The implementation embeds CPython and
 * drives the framework's compiled-executor path
 * (incubator_mxnet_tpu/c_predict.py).
 *
 * All functions return 0 on success, -1 on failure; call
 * MXTPUGetLastError() for the message.
 */
#ifndef MXTPU_C_PREDICT_API_H_
#define MXTPU_C_PREDICT_API_H_

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void *PredictorHandle;

/* Latest error message (thread-local). */
const char *MXTPUGetLastError(void);

/* Create a predictor.
 *   symbol_json       : NUL-terminated symbol JSON (the -symbol.json file)
 *   param_bytes/size  : contents of the .params container
 *   dev_type          : 1 = cpu, 2 = accelerator (tpu)
 *   dev_id            : device ordinal
 *   num_input_nodes   : number of model inputs
 *   input_keys        : input names
 *   input_shape_indptr: CSR-style offsets into input_shape_data,
 *                       length num_input_nodes + 1
 *   input_shape_data  : concatenated input shapes
 */
int MXTPUPredCreate(const char *symbol_json,
                    const void *param_bytes, size_t param_size,
                    int dev_type, int dev_id,
                    uint32_t num_input_nodes,
                    const char **input_keys,
                    const uint32_t *input_shape_indptr,
                    const uint32_t *input_shape_data,
                    PredictorHandle *out);

/* Copy a float32 input by name (size = element count). */
int MXTPUPredSetInput(PredictorHandle handle, const char *key,
                      const float *data, uint32_t size);

/* Run the forward pass. */
int MXTPUPredForward(PredictorHandle handle);

/* Shape of output `index`; *shape_data stays owned by the predictor
 * until the next call on this handle. */
int MXTPUPredGetOutputShape(PredictorHandle handle, uint32_t index,
                            uint32_t **shape_data, uint32_t *shape_ndim);

/* Copy output `index` into caller memory (size = element count). */
int MXTPUPredGetOutput(PredictorHandle handle, uint32_t index,
                       float *data, uint32_t size);

/* Release the predictor. */
int MXTPUPredFree(PredictorHandle handle);

#ifdef __cplusplus
}  /* extern "C" */
#endif

#endif  /* MXTPU_C_PREDICT_API_H_ */
