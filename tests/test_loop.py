"""Continuous train-to-serve loop (the ISSUE-20 acceptance gates).

Covers: registry semantics (atomic versioned publishes, torn manifests
invisible to watchers, ordering under concurrent publishes, rejected-
stamp idempotence, structured error when the registry directory
disappears mid-poll), the publisher's cadence / suspect filter /
guardian-rollback fencing / torn-publish retry, the checkpoint-level
rejected stamp surviving a process restart, the router's structured
`SwapInProgressError` + single-replica `swap_one`, the LoopController's
canary gate (promote on match, reject + swap-back + stamp on a poisoned
candidate, fail-closed on an unscorable canary, back-off on a busy
swap, keep-serving on a vanished registry), the `publish.commit` /
`canary.eval` fault sites' seeded determinism, freshness-lag metrics in
the obs plane, and the `unguarded-model-swap` source lint.
"""
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import analysis, checkpoint as ckpt, sym
from incubator_mxnet_tpu.base import MXNetError
from incubator_mxnet_tpu.loop import (CanaryRejectedError,
                                      CheckpointPublisher, LoopController,
                                      ModelRegistry,
                                      RegistryUnavailableError)
from incubator_mxnet_tpu.obs import metrics as obs_metrics
from incubator_mxnet_tpu.resilience import faults
from incubator_mxnet_tpu.serving import (LocalReplica, ReplicaRouter,
                                         SwapInProgressError)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


# ---------------------------------------------------------------------------
# fixtures: a 4-class model whose holdout score is fully deterministic —
# identity weights classify one-hot rows perfectly (accuracy 1.0), the
# "poisoned" negated weights misclassify every row (accuracy 0.0)
# ---------------------------------------------------------------------------

IDENT = np.eye(4, dtype=np.float32)
HOLDOUT = ({"data": IDENT}, np.arange(4))


def _net():
    net = sym.Variable("data")
    net = sym.FullyConnected(net, num_hidden=4, no_bias=True, name="fc")
    return sym.SoftmaxOutput(net, name="softmax")


def _served(weight, name="m", buckets=(1, 2, 4)):
    args = {"fc_weight": mx.nd.array(np.asarray(weight, np.float32))}
    return mx.serving.ServedModel(_net(), args, {},
                                  data_shapes=[("data", (1, 4))],
                                  buckets=buckets, ctx=mx.cpu(), name=name)


def _fleet(n=2, weight=IDENT):
    reps = [LocalReplica(_served(weight, name=f"m{i}"), replica_id=f"r{i}")
            for i in range(n)]
    return ReplicaRouter(reps, name="loop-test", health_interval_s=5.0)


def _write_ckpt(root, weight, step, health="healthy"):
    """One elastic checkpoint holding `weight`, guardian-stamped."""
    mgr = ckpt.CheckpointManager(str(root), keep_last=64)
    mgr.snapshot(arrays={"arg:fc_weight": np.asarray(weight, np.float32)},
                 step=step, epoch=0, nbatch=step,
                 meta={"health": {"status": health}}, sync=True)
    mgr.close()
    return os.path.join(str(root), "ckpt-%010d" % step)


def _publish(registry, path, step, score=None):
    return registry.publish(path, step=step,
                            health={"status": "healthy"},
                            watermark={"step": step, "time": time.time()},
                            score=score)


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------

def test_registry_publish_and_latest(tmp_path):
    reg = ModelRegistry(str(tmp_path / "reg"))
    _publish(reg, "/ck/a", 3, score=0.9)
    _publish(reg, "/ck/b", 7)
    recs = reg.versions()
    assert [r["version"] for r in recs] == [3, 7]
    top = reg.latest()
    assert top["version"] == 7 and top["checkpoint"] == "/ck/b"
    assert top["health"]["status"] == "healthy"
    assert "time" in top["watermark"]
    assert reg.get(3)["score"] == 0.9
    assert reg.stats()["latest_version"] == 7


def test_registry_pin_survives_trainer_retention(tmp_path):
    """publish(pin=True) hardlinks the checkpoint into the registry's
    own blobs/ tier, so the published version stays loadable after the
    trainer's keep_last retention prunes the source ckpt directory."""
    import shutil
    reg = ModelRegistry(str(tmp_path / "reg"))
    src = _write_ckpt(tmp_path / "ck", IDENT * 3.0, 5)
    rec = reg.publish(src, step=5, health={"status": "healthy"}, pin=True)
    pinned = rec["checkpoint"]
    assert pinned == os.path.join(str(tmp_path / "reg"), "blobs",
                                  "v-0000000005")
    assert reg.latest()["checkpoint"] == pinned
    # idempotent: re-publishing the same step reuses the existing pin
    assert reg.publish(src, step=5, pin=True)["checkpoint"] == pinned
    shutil.rmtree(src)                    # trainer retention prunes it
    data = ckpt.load(pinned)
    assert np.allclose(np.asarray(data.arrays["arg:fc_weight"]),
                       IDENT * 3.0)


def test_registry_torn_manifest_invisible(tmp_path):
    """A torn/unstamped version manifest is counted, never surfaced."""
    reg = ModelRegistry(str(tmp_path / "reg"))
    _publish(reg, "/ck/a", 1)
    # torn: truncated JSON under the final name
    with open(os.path.join(reg.root, "v-0000000002.json"), "w") as f:
        f.write('{"format": "incubator_mxnet_tpu.registry/1", "vers')
    # unstamped: parses, but carries no format stamp
    with open(os.path.join(reg.root, "v-0000000003.json"), "w") as f:
        f.write('{"version": 3, "checkpoint": "/ck/evil"}')
    assert [r["version"] for r in reg.versions()] == [1]
    assert reg.latest()["version"] == 1
    assert reg.stats()["torn_manifests"] == 2


def test_registry_ordering_under_concurrent_publishes(tmp_path):
    reg = ModelRegistry(str(tmp_path / "reg"))
    steps = list(range(1, 9))
    threads = [threading.Thread(target=_publish, name=f"mx-test-pub-{s}",
                                args=(reg, f"/ck/{s}", s))
               for s in steps]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert [r["version"] for r in reg.versions()] == steps
    assert reg.latest()["version"] == 8


def test_registry_reject_idempotent(tmp_path):
    reg = ModelRegistry(str(tmp_path / "reg"))
    _publish(reg, "/ck/a", 1)
    _publish(reg, "/ck/b", 2)
    first = reg.reject(2, reason="canary", canary_score=0.1)
    again = reg.reject(2, reason="something-else", canary_score=0.99)
    assert again["reason"] == "canary" and again["canary_score"] == 0.1
    assert first["rejected_unix"] == again["rejected_unix"]
    assert reg.latest()["version"] == 1
    rec = reg.versions(include_rejected=True)[-1]
    assert rec["version"] == 2 and rec["rejected"]
    # a second registry handle (restart) still sees the stamp
    assert ModelRegistry(reg.root).rejected(2)["reason"] == "canary"


def test_registry_fence_hides_window(tmp_path):
    reg = ModelRegistry(str(tmp_path / "reg"))
    for s in (2, 6, 11):
        _publish(reg, f"/ck/{s}", s)
    reg.fence(5, 10, reason="guardian-rollback")
    assert [r["version"] for r in reg.versions()] == [2, 11]
    assert reg.fenced(6) and not reg.fenced(11)
    assert reg.get(6)["fenced"]
    # fences persist across a new handle (restart)
    assert ModelRegistry(reg.root).fences() == [(5, 10)]


def test_registry_dir_disappears_structured_error(tmp_path):
    import shutil
    root = str(tmp_path / "reg")
    reg = ModelRegistry(root)
    _publish(reg, "/ck/a", 1)
    shutil.rmtree(root)
    with pytest.raises(RegistryUnavailableError) as ei:
        reg.versions()
    assert ei.value.root == root
    with pytest.raises(RegistryUnavailableError):
        _publish(reg, "/ck/b", 2)


# ---------------------------------------------------------------------------
# fault sites: publish.commit / canary.eval (seeded determinism)
# ---------------------------------------------------------------------------

def test_publish_commit_torn_fault_and_retry(tmp_path):
    reg = ModelRegistry(str(tmp_path / "reg"))
    faults.configure("seed=3;publish.commit:torn(at=2)")
    committed = []
    for step in (1, 2, 3):
        try:
            _publish(reg, f"/ck/{step}", step)
            committed.append(step)
        except faults.TornWrite:
            pass
    assert committed == [1, 3]
    # the torn manifest sits on disk under the FINAL name yet is invisible
    assert os.path.exists(os.path.join(reg.root, "v-0000000002.json"))
    assert [r["version"] for r in reg.versions()] == [1, 3]
    assert reg.stats()["torn_manifests"] == 1
    # a clean re-publish atomically replaces the torn garbage
    faults.clear()
    _publish(reg, "/ck/2", 2)
    assert [r["version"] for r in reg.versions()] == [1, 2, 3]


def test_publish_commit_seeded_schedule_is_deterministic(tmp_path):
    def run():
        reg = ModelRegistry(str(tmp_path / f"reg-{time.monotonic_ns()}"))
        faults.configure("seed=11;publish.commit:error(p=0.4)")
        pattern = []
        for step in range(1, 21):
            try:
                _publish(reg, f"/ck/{step}", step)
                pattern.append(True)
            except MXNetError:
                pattern.append(False)
        faults.clear()
        return pattern
    first, second = run(), run()
    assert first == second
    assert False in first and True in first


def test_canary_eval_seeded_schedule_is_deterministic():
    def run():
        faults.configure("seed=17;canary.eval:error(p=0.5)")
        pattern = []
        for i in range(20):
            try:
                faults.fire("canary.eval", version=i, phase="canary")
                pattern.append(True)
            except MXNetError:
                pattern.append(False)
        faults.clear()
        return pattern
    first, second = run(), run()
    assert first == second
    assert False in first and True in first


# ---------------------------------------------------------------------------
# checkpoint satellite: rejected stamps + exclude=
# ---------------------------------------------------------------------------

def test_latest_healthy_exclude_filters(tmp_path):
    paths = {s: _write_ckpt(tmp_path, IDENT * s, s) for s in (1, 2, 3)}
    man = ckpt.manifest
    assert man.latest_healthy(str(tmp_path)) == paths[3]
    assert man.latest_healthy(str(tmp_path), exclude={3}) == paths[2]
    assert man.latest_healthy(str(tmp_path), exclude={paths[3]}) == paths[2]
    assert man.latest_healthy(str(tmp_path),
                              exclude=lambda s: s >= 2) == paths[1]


def test_rejected_stamp_never_selected_and_survives_restart(tmp_path):
    good = _write_ckpt(tmp_path, IDENT, 1)
    bad = _write_ckpt(tmp_path, -IDENT, 2)
    stamp = ckpt.stamp_rejected(bad, reason="canary", canary_score=0.0)
    assert stamp["reason"] == "canary"
    # idempotent: a re-stamp keeps the original evidence
    assert ckpt.stamp_rejected(bad, reason="other")["reason"] == "canary"
    assert ckpt.is_rejected(bad) and not ckpt.is_rejected(good)
    assert ckpt.latest(str(tmp_path)) == good
    assert ckpt.manifest.latest_healthy(str(tmp_path)) == good
    assert ckpt.latest(str(tmp_path), include_rejected=True) == bad
    # the fence holds in a FRESH process: resume/serving there must make
    # the same choice from nothing but the on-disk state
    code = ("import incubator_mxnet_tpu as mx\n"
            "print(mx.checkpoint.latest(%r))\n"
            "print(mx.checkpoint.latest_healthy(%r))\n"
            % (str(tmp_path), str(tmp_path)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=180)
    assert out.returncode == 0, out.stderr
    lines = out.stdout.strip().splitlines()
    assert lines == [good, good]


# ---------------------------------------------------------------------------
# router satellite: SwapInProgressError + swap_one
# ---------------------------------------------------------------------------

def test_swap_busy_raises_structured_error(tmp_path):
    router = _fleet(1)
    try:
        router._acquire_swap(42)
        with pytest.raises(SwapInProgressError) as ei:
            router.swap_weights(checkpoint_dir="/nowhere")
        assert ei.value.version == 42 and "42" in str(ei.value)
        with pytest.raises(SwapInProgressError) as ei:
            router.swap_one(checkpoint_dir="/nowhere")
        assert ei.value.version == 42
        router._release_swap()
        assert isinstance(ei.value, MXNetError)
    finally:
        router.shutdown()


def test_swap_one_touches_exactly_one_replica(tmp_path):
    router = _fleet(2)
    try:
        ck = _write_ckpt(tmp_path, IDENT * 2.0, 1)
        out = router.swap_one("r1", checkpoint_dir=ck, version=1)
        assert out == {"swapped": ["r1"], "version": 1}
        versions = {rid: s["version"]
                    for rid, s in router.stats()["replicas"].items()}
        assert versions == {"r0": 0, "r1": 1}
        assert router._swap_inflight is None   # lock released
    finally:
        router.shutdown()


# ---------------------------------------------------------------------------
# publisher
# ---------------------------------------------------------------------------

def test_publisher_cadence_and_watermark(tmp_path):
    ck_root = tmp_path / "ck"
    reg = ModelRegistry(str(tmp_path / "reg"))
    _write_ckpt(ck_root, IDENT, 2)
    pub = CheckpointPublisher(reg, str(ck_root), publish_steps=4,
                              publish_secs=0)
    for step in range(3):
        pub.poll(step)
    assert reg.latest() is None           # cadence not reached
    pub.poll(3)                           # 4 steps seen -> publish
    rec = reg.latest()
    assert rec["version"] == 2
    wm = rec["watermark"]
    assert wm["step"] == 2 and wm["nbatch"] == 2 and wm["time"] > 0
    for step in range(4, 7):
        pub.poll(step)                    # nothing new to publish
    assert pub.stats()["published"] == 1
    _write_ckpt(ck_root, IDENT, 6)
    pub.poll(7)                           # next cadence tick
    assert reg.latest()["version"] == 6
    assert pub.stats()["published"] == 2


def test_publisher_never_publishes_suspect_checkpoints(tmp_path):
    ck_root = tmp_path / "ck"
    reg = ModelRegistry(str(tmp_path / "reg"))
    _write_ckpt(ck_root, IDENT, 2, health="healthy")
    _write_ckpt(ck_root, -IDENT, 4, health="suspect")
    pub = CheckpointPublisher(reg, str(ck_root), publish_steps=1,
                              publish_secs=0)
    pub.poll(5)
    assert reg.latest()["version"] == 2   # the suspect step 4 passed over


def test_publisher_fences_rollback_window(tmp_path):
    """A step regression across callbacks == a guardian rollback: the
    disowned window is fenced, and a fenced checkpoint can never be
    re-published afterwards."""
    ck_root = tmp_path / "ck"
    reg = ModelRegistry(str(tmp_path / "reg"))
    pub = CheckpointPublisher(reg, str(ck_root), publish_steps=100,
                              publish_secs=0)
    pub.poll(10)
    pub.poll(4)                           # regression -> fence (5..10)
    assert reg.fences() == [(5, 10)]
    assert pub.stats()["fences"] == 1
    # step 7 lands INSIDE the fenced window: healthy stamp or not, the
    # publisher must never hand it to the fleet
    _write_ckpt(ck_root, -IDENT, 7)
    pub2 = CheckpointPublisher(reg, str(ck_root), publish_steps=1,
                               publish_secs=0)
    pub2.poll(20)
    assert reg.latest() is None
    _write_ckpt(ck_root, IDENT, 20)
    pub2.poll(21)
    assert reg.latest()["version"] == 20  # clean step sails through


def test_publisher_retries_after_torn_publish(tmp_path):
    ck_root = tmp_path / "ck"
    reg = ModelRegistry(str(tmp_path / "reg"))
    _write_ckpt(ck_root, IDENT, 2)
    pub = CheckpointPublisher(reg, str(ck_root), publish_steps=2,
                              publish_secs=0)
    faults.configure("seed=5;publish.commit:torn(at=1)")
    pub.poll(1)                           # cadence fires, publish torn
    assert pub.stats()["torn_publishes"] == 1
    assert reg.latest() is None           # torn manifest invisible
    pub.poll(2)                           # fault exhausted -> clean retry
    assert reg.latest()["version"] == 2


# ---------------------------------------------------------------------------
# controller: the canary gate
# ---------------------------------------------------------------------------

def _loop_rig(tmp_path, n=2):
    ck_root = tmp_path / "ck"
    reg = ModelRegistry(str(tmp_path / "reg"))
    boot = _write_ckpt(ck_root, IDENT, 1)
    router = _fleet(n)
    ctrl = LoopController(router, reg, HOLDOUT, canary_tol=0.25,
                          poll_interval_s=0.05, freshness_slo_s=120.0,
                          incumbent_checkpoint=boot)
    return ck_root, reg, router, ctrl, boot


def test_canary_promotes_matching_version_and_measures_freshness(tmp_path):
    ck_root, reg, router, ctrl, boot = _loop_rig(tmp_path)
    try:
        assert ctrl.poll_once()["status"] == "idle"
        ck2 = _write_ckpt(ck_root, IDENT, 2)   # same weights: must match
        _publish(reg, ck2, 2)
        res = ctrl.poll_once()
        assert res["status"] == "promoted" and res["version"] == 2
        assert res["canary_score"] == pytest.approx(1.0)
        assert res["incumbent_score"] == pytest.approx(1.0)
        assert 0.0 <= res["freshness_lag_s"] < 60.0
        versions = {rid: s["version"]
                    for rid, s in router.stats()["replicas"].items()}
        assert all(v >= 1 for v in versions.values())   # whole fleet rolled
        # the loop namespace reaches the scrape plane
        snap = obs_metrics.registry().collect()
        assert snap.get("loop.freshness_lag_s") == \
            pytest.approx(res["freshness_lag_s"])
        assert snap.get("loop.promotions") == 1
        assert snap.get("loop.freshness_slo_met") == 1
        # re-poll: same version is not re-canaried
        assert ctrl.poll_once()["status"] == "idle"
    finally:
        router.shutdown()


def test_canary_rejects_poisoned_version(tmp_path):
    ck_root, reg, router, ctrl, boot = _loop_rig(tmp_path)
    try:
        _publish(reg, _write_ckpt(ck_root, IDENT, 2), 2)
        assert ctrl.poll_once()["status"] == "promoted"
        poisoned = _write_ckpt(ck_root, -IDENT, 3)   # accuracy 0.0
        _publish(reg, poisoned, 3)
        with pytest.raises(CanaryRejectedError) as ei:
            ctrl.poll_once()
        err = ei.value
        assert err.version == 3
        assert err.canary_score == pytest.approx(0.0)
        assert err.incumbent_score == pytest.approx(1.0)
        # the registry stamp is durable and the version disappears
        assert reg.rejected(3)["canary_score"] == pytest.approx(0.0)
        assert reg.latest()["version"] == 2
        # the checkpoint itself is fenced for resume/boot too
        assert ckpt.is_rejected(poisoned)
        # the canary replica is BACK on the incumbent: the fleet still
        # classifies perfectly through the real request path
        out = router.predict({"data": IDENT}, timeout_ms=10000)
        first = out[0] if isinstance(out, (list, tuple)) else out
        first = np.asarray(first.asnumpy() if hasattr(first, "asnumpy")
                           else first)
        assert (first.argmax(axis=-1) == np.arange(4)).all()
        # never retried: the rejected version is invisible from now on
        assert ctrl.poll_once()["status"] == "idle"
        assert ctrl.stats()["canary_rejections"] == 1
    finally:
        router.shutdown()


def test_canary_eval_failure_fails_closed(tmp_path):
    """`canary.eval:error` on the CANDIDATE eval: a model that cannot be
    scored is rejected, never promoted."""
    ck_root, reg, router, ctrl, boot = _loop_rig(tmp_path)
    try:
        ck2 = _write_ckpt(ck_root, IDENT, 2)     # a GOOD candidate
        _publish(reg, ck2, 2)
        # hit 1 = incumbent eval (passes), hit 2 = candidate eval (fails)
        faults.configure("seed=7;canary.eval:error(at=2)")
        with pytest.raises(CanaryRejectedError) as ei:
            ctrl.poll_once()
        assert ei.value.canary_score == float("-inf")
        assert reg.rejected(2) is not None
        assert ctrl.stats()["eval_failures"] == 1
    finally:
        router.shutdown()


def test_controller_survives_replica_lost_mid_swap(tmp_path):
    """A replica dying mid-canary must not crash the watch loop: the
    router's swap contract keeps the fleet serving, the controller
    returns a structured ``swap-failed``, and the SAME candidate is
    retried — and promoted — on the next poll."""
    ck_root, reg, router, ctrl, boot = _loop_rig(tmp_path)
    try:
        _publish(reg, _write_ckpt(ck_root, IDENT, 2), 2)
        canary_rid = ctrl._pick_canary()[0]
        rep = router.replica(canary_rid)
        real_swap, hits = rep.swap, []

        def dying_swap(*a, **kw):
            if not hits:
                hits.append(1)
                from incubator_mxnet_tpu.serving import ReplicaLostError
                raise ReplicaLostError(canary_rid,
                                       reason="killed mid-swap")
            return real_swap(*a, **kw)

        rep.swap = dying_swap
        res = ctrl.poll_once()
        assert res["status"] == "swap-failed" and res["candidate"] == 2
        assert "lost" in res["error"]
        assert ctrl.stats()["swap_failures"] == 1
        assert ctrl.stats()["live_version"] == -1   # never advanced
        # the incumbent kept serving through the failed swap
        out = router.predict({"data": IDENT}, timeout_ms=10000)
        first = out[0] if isinstance(out, (list, tuple)) else out
        first = np.asarray(first.asnumpy() if hasattr(first, "asnumpy")
                           else first)
        assert (first.argmax(axis=-1) == np.arange(4)).all()
        # candidate still eligible: the retry promotes it
        assert ctrl.poll_once()["status"] == "promoted"
        assert router._swap_inflight is None        # lock released
    finally:
        router.shutdown()


def test_controller_backs_off_while_swap_in_progress(tmp_path):
    ck_root, reg, router, ctrl, boot = _loop_rig(tmp_path)
    try:
        _publish(reg, _write_ckpt(ck_root, IDENT, 2), 2)
        router._acquire_swap("operator-roll")
        res = ctrl.poll_once()
        assert res["status"] == "swap-busy"
        assert res["in_flight"] == "operator-roll"
        assert reg.rejected(2) is None           # NOT a failed canary
        router._release_swap()
        assert ctrl.poll_once()["status"] == "promoted"
        assert ctrl.stats()["swap_busy"] == 1
    finally:
        router.shutdown()


def test_controller_keeps_serving_when_registry_vanishes(tmp_path):
    import shutil
    ck_root, reg, router, ctrl, boot = _loop_rig(tmp_path)
    try:
        _publish(reg, _write_ckpt(ck_root, IDENT, 2), 2)
        assert ctrl.poll_once()["status"] == "promoted"
        shutil.rmtree(reg.root)
        res = ctrl.poll_once()
        assert res["status"] == "registry-unavailable"
        assert ctrl.stats()["registry_errors"] == 1
        assert ctrl.stats()["live_version"] == 2   # incumbent stays live
        out = router.predict({"data": IDENT[:2]}, timeout_ms=10000)
        first = out[0] if isinstance(out, (list, tuple)) else out
        first = np.asarray(first.asnumpy() if hasattr(first, "asnumpy")
                           else first)
        assert (first.argmax(axis=-1) == np.arange(2)).all()
    finally:
        router.shutdown()


def test_controller_background_thread_promotes(tmp_path):
    ck_root, reg, router, ctrl, boot = _loop_rig(tmp_path)
    try:
        ctrl.start()
        _publish(reg, _write_ckpt(ck_root, IDENT, 2), 2)
        deadline = time.monotonic() + 30.0
        while ctrl.stats()["live_version"] != 2 and \
                time.monotonic() < deadline:
            time.sleep(0.05)
        assert ctrl.stats()["live_version"] == 2
    finally:
        ctrl.stop()
        router.shutdown()


def test_hung_canary_eval_fails_closed(tmp_path):
    """A canary eval that HANGS raises concurrent.futures.TimeoutError
    (pre-3.11 NOT the builtin TimeoutError) — it must still hit the
    fail-closed path: reject the candidate, restore the canary replica,
    never let the exception escape the handlers."""
    import concurrent.futures
    ck_root, reg, router, ctrl, boot = _loop_rig(tmp_path)
    try:
        _publish(reg, _write_ckpt(ck_root, IDENT, 2), 2)
        rid = ctrl._pick_canary()[0]
        rep = router.replica(rid)
        real_submit, calls = rep.submit, []

        class _Hung:
            def result(self, timeout=None):
                raise concurrent.futures.TimeoutError()

        def submit(*a, **kw):
            calls.append(1)
            if len(calls) == 2:        # hit 2 = the CANDIDATE eval
                return _Hung()
            return real_submit(*a, **kw)

        rep.submit = submit
        with pytest.raises(CanaryRejectedError) as ei:
            ctrl.poll_once()
        assert ei.value.canary_score == float("-inf")
        assert ctrl.stats()["eval_failures"] == 1
        assert reg.rejected(2) is not None
        # the canary replica was RESTORED, not abandoned on the
        # unvetted candidate or declared lost
        assert router.stats()["replicas_lost"] == 0
        rep.submit = real_submit
        out = router.predict({"data": IDENT}, timeout_ms=10000)
        first = out[0] if isinstance(out, (list, tuple)) else out
        first = np.asarray(first.asnumpy() if hasattr(first, "asnumpy")
                           else first)
        assert (first.argmax(axis=-1) == np.arange(4)).all()
    finally:
        router.shutdown()


def test_incumbent_eval_failure_is_eval_failed_not_swap_failed(tmp_path):
    """A fault while scoring the INCUMBENT (before any swap) is an eval
    failure with its own status — not a swap_failure — and the candidate
    stays eligible for the next poll."""
    ck_root, reg, router, ctrl, boot = _loop_rig(tmp_path)
    try:
        _publish(reg, _write_ckpt(ck_root, IDENT, 2), 2)
        faults.configure("seed=7;canary.eval:error(at=1)")  # incumbent hit
        res = ctrl.poll_once()
        assert res["status"] == "eval-failed"
        assert res["phase"] == "incumbent" and res["candidate"] == 2
        assert ctrl.stats()["eval_failures"] == 1
        assert ctrl.stats()["swap_failures"] == 0
        assert ctrl.stats()["canary_rejections"] == 0
        assert reg.rejected(2) is None     # no canary verdict was reached
        # fault exhausted: the retry canaries and promotes
        assert ctrl.poll_once()["status"] == "promoted"
    finally:
        router.shutdown()


def test_restore_backs_off_when_swap_lock_held(tmp_path):
    """A canary rollback that collides with an external in-flight swap
    must NOT declare the replica lost — the restore is deferred and
    retried on the next poll."""
    ck_root, reg, router, ctrl, boot = _loop_rig(tmp_path)
    try:
        _publish(reg, _write_ckpt(ck_root, IDENT, 2), 2)
        assert ctrl.poll_once()["status"] == "promoted"
        _publish(reg, _write_ckpt(ck_root, -IDENT, 3), 3)
        real_swap_one, state = router.swap_one, {"n": 0}

        def swap_one(*a, **kw):
            state["n"] += 1
            if state["n"] == 2:        # call 2 = the restore swap-back
                raise SwapInProgressError(router.name, "operator-roll")
            return real_swap_one(*a, **kw)

        router.swap_one = swap_one
        with pytest.raises(CanaryRejectedError):
            ctrl.poll_once()
        assert router.stats()["replicas_lost"] == 0   # capacity kept
        assert ctrl._pending_restore is not None
        assert ctrl.stats()["swap_busy"] == 1
        # next poll finishes the restore first, then sees only the
        # already-rejected version -> idle
        assert ctrl.poll_once()["status"] == "idle"
        assert ctrl._pending_restore is None
        assert state["n"] == 3
        out = router.predict({"data": IDENT}, timeout_ms=10000)
        first = out[0] if isinstance(out, (list, tuple)) else out
        first = np.asarray(first.asnumpy() if hasattr(first, "asnumpy")
                           else first)
        assert (first.argmax(axis=-1) == np.arange(4)).all()
    finally:
        router.shutdown()


def test_aborted_promote_resumes_without_recanary(tmp_path):
    """After the canary PASSED, a promote roll that aborts partway
    leaves some replicas on the candidate; the next poll must resume the
    roll on the standing verdict — not re-canary against a partially
    rolled fleet, where the pick could score the candidate as its own
    incumbent."""
    ck_root, reg, router, ctrl, boot = _loop_rig(tmp_path)
    try:
        _publish(reg, _write_ckpt(ck_root, IDENT, 2), 2)
        scored = []
        real_score = ctrl._score_replica

        def counting_score(*a, **kw):
            scored.append(1)
            return real_score(*a, **kw)

        ctrl._score_replica = counting_score
        rep1 = router.replica("r1")
        real_swap, hits = rep1.swap, []

        def failing_swap(*a, **kw):
            if not hits:
                hits.append(1)
                raise MXNetError("transient swap fault")
            return real_swap(*a, **kw)

        rep1.swap = failing_swap
        res = ctrl.poll_once()
        assert res["status"] == "swap-failed" and res["candidate"] == 2
        assert len(scored) == 2            # incumbent + canary evals ran
        assert ctrl.stats()["live_version"] == -1
        # the retry resumes the promote directly: no third/fourth eval
        res = ctrl.poll_once()
        assert res["status"] == "promoted" and res["version"] == 2
        assert res["canary_score"] == pytest.approx(1.0)
        assert len(scored) == 2
        assert ctrl.stats()["live_version"] == 2
    finally:
        router.shutdown()


def test_rejection_stamps_source_checkpoint_through_pin(tmp_path):
    """publish(pin=True) hands watchers the registry-owned blobs/ copy;
    a canary rejection must stamp the trainer's ORIGINAL ckpt-* dir too,
    so resume / replica boot skip it without ever reading the registry."""
    ck_root, reg, router, ctrl, boot = _loop_rig(tmp_path)
    try:
        poisoned = _write_ckpt(ck_root, -IDENT, 2)
        rec = reg.publish(poisoned, step=2,
                          health={"status": "healthy"}, pin=True)
        assert rec["checkpoint"] != str(poisoned)     # the pinned copy
        assert rec["source_checkpoint"] == str(poisoned)
        with pytest.raises(CanaryRejectedError):
            ctrl.poll_once()
        assert ckpt.is_rejected(rec["checkpoint"])    # registry blob
        assert ckpt.is_rejected(str(poisoned))        # trainer-side dir
        # trainer-side selection skips it with no registry in sight
        assert ckpt.latest_healthy(str(ck_root)) == boot
    finally:
        router.shutdown()


# ---------------------------------------------------------------------------
# knobs + lint
# ---------------------------------------------------------------------------

def test_loop_knobs_registered():
    from incubator_mxnet_tpu.config import KNOBS
    for name in ("MXNET_LOOP_PUBLISH_STEPS", "MXNET_LOOP_PUBLISH_SECS",
                 "MXNET_LOOP_CANARY_TOL", "MXNET_LOOP_POLL_S",
                 "MXNET_LOOP_FRESHNESS_SLO_S"):
        assert name in KNOBS
        assert KNOBS[name][2] == "honored"
        assert mx.config.get(name) == KNOBS[name][1]


def test_unguarded_model_swap_lint():
    guarded = ("ctrl = LoopController(router, registry, holdout)\n"
               "router.swap_weights(checkpoint_dir=ck)\n"
               "replica.swap(checkpoint_dir=ck)\n")
    report = analysis.check_source(guarded, filename="s.py")
    hits = [f for f in report if f.code == "unguarded-model-swap"]
    assert sorted(f.location for f in hits) == ["s.py:2", "s.py:3"]
    # no LoopController in the script -> swapping directly is the
    # caller's explicit choice, not a bypass: no finding
    bare = "router.swap_weights(checkpoint_dir=ck)\n"
    assert not [f for f in analysis.check_source(bare)
                if f.code == "unguarded-model-swap"]
