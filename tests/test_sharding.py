"""mxshard static SPMD sharding analysis (ISSUE-18 acceptance).

Gates: megatron rule coverage is checked STATICALLY (every TransformerLM
matrix param matches exactly one rule, with zero trace work); a dropped
rule is a `rule-coverage` ERROR carrying the exact param name; a forced
producer/consumer spec mismatch is a `hidden-reshard` WARN naming both
nodes and the statically computed bytes, and both seeded defects exit
nonzero through the `mxlint --shard-report --fail-on` CLI contract; the
static dp ICI plan is BYTE-EXACT against measured `KVStore.stats()`
under dp=4 and dp=2,tp=2; the committed COST_BUDGETS "sharding" section
passes on HEAD and fails on a seeded regression; the bench program set
and examples/ produce zero non-hint findings (no false positives); plus
`parse_spec` error messages naming the offending token and grammar.
"""
import glob
import importlib.util
import json
import os

import pytest

from jax.sharding import PartitionSpec as P

import incubator_mxnet_tpu as mx          # noqa: F401  (device census)
from incubator_mxnet_tpu import sym
from incubator_mxnet_tpu.analysis import budgets as mxbudgets
from incubator_mxnet_tpu.analysis import sharding as mxshard
from incubator_mxnet_tpu.base import MXNetError
from incubator_mxnet_tpu.parallel.mesh import parse_spec
from incubator_mxnet_tpu.parallel.tensor_parallel import ShardingRules

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BUDGETS_PATH = os.path.join(REPO, "COST_BUDGETS.json")


def _cli():
    spec = importlib.util.spec_from_file_location(
        "_mxlint_cli_shard", os.path.join(REPO, "tools", "mxlint.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _lm_params():
    symb, shapes, dtypes = mxshard.lm_bench_symbol()
    arg_shapes, _, _ = symb.infer_shape(**shapes)
    step = set(shapes)
    return symb, dtypes, shapes, {
        n: tuple(s) for n, s in zip(symb.list_arguments(), arg_shapes)
        if n not in step}


# ---------------------------------------------------------------------------
# mesh spec parsing (the error-message contract)
# ---------------------------------------------------------------------------

def test_parse_spec_roundtrip():
    assert parse_spec("dp=4,tp=2") == {"dp": 4, "tp": 2}
    assert list(parse_spec("pp=2,dp=4")) == ["pp", "dp"]  # order kept


@pytest.mark.parametrize("bad,token,reason", [
    ("dp:4", "'dp:4'", "missing '='"),
    ("dp=four", "'dp=four'", "not an integer"),
    ("dp=4,=2", "'=2'", "empty axis name"),
    ("dp=0", "'dp=0'", "positive"),
    ("dp=2,dp=4", "'dp=4'", "twice"),
])
def test_parse_spec_error_names_token_and_grammar(bad, token, reason):
    with pytest.raises(MXNetError) as ei:
        parse_spec(bad)
    msg = str(ei.value)
    assert "bad token " + token in msg, msg      # the offending token
    assert reason in msg                         # why it is bad
    assert "mesh spec grammar" in msg            # the accepted grammar
    assert "'dp=4,tp=2'" in msg                  # with a worked example


# ---------------------------------------------------------------------------
# rule coverage: the static twin of test_llm's dynamic megatron check
# ---------------------------------------------------------------------------

def test_megatron_rules_cover_every_lm_matrix_param_exactly_once():
    _, _, _, params = _lm_params()
    rules = ShardingRules.megatron(tp_axis="tp")
    matrices = 0
    for name, shape in sorted(params.items()):
        nmatch = sum(1 for prog, _ in rules.rules if prog.search(name))
        if len(shape) >= 2:
            assert nmatch == 1, (name, nmatch)   # exactly one rule
            matrices += 1
        else:                                    # bias/gamma/beta may
            assert nmatch <= 1                   # fall to the default
    # embed + (qkv, out_proj, fc1, fc2) x 2 blocks
    assert matrices == 1 + 4 * 2
    rep = mxshard.check_rule_coverage(params, rules)
    assert [f for f in rep if f.code == "rule-coverage"] == []


def test_dropped_megatron_rule_is_error_with_exact_param_name():
    symb, dtypes, shapes, _ = _lm_params()
    dropped = ShardingRules([              # row-parallel rule DROPPED
        (r"(qkv|query|key|value|gate|up|fc1|ffn_in).*weight",
         P("tp", None)),
        (r"embed.*weight", P("tp", None)),
        (r"bias", P()),
    ])
    rep = mxshard.analyze_sharding(symb, shapes=shapes, dtypes=dtypes,
                                   mesh="dp=2,tp=2", rules=dropped)
    errs = [f for f in rep.findings if f.code == "rule-coverage"]
    assert errs and all(f.severity == "error" for f in errs)
    flagged = {f.node for f in errs}
    for name in ("lm_block0_out_proj_weight", "lm_block1_fc2_weight"):
        assert name in flagged
        assert any(name in f.message for f in errs)


def test_ambiguous_rule_match_is_error_listing_patterns():
    rules = ShardingRules([(r"fc1.*weight", P("tp", None)),
                           (r"weight", P(None, "tp"))])
    rep = mxshard.check_rule_coverage({"blk_fc1_weight": (64, 32)}, rules)
    errs = [f for f in rep if f.code == "rule-coverage"]
    assert len(errs) == 1
    assert "2 sharding rules" in errs[0].message
    assert "fc1.*weight" in errs[0].message


def test_rule_set_not_applicable_to_model_is_silent():
    # a convnet under megatron rules is not a coverage gap
    rep = mxshard.check_rule_coverage(
        {"conv0_weight": (16, 3, 3, 3), "fc0_weight": (32, 4096)},
        ShardingRules.megatron(tp_axis="tp"))
    assert len(rep) == 0


# ---------------------------------------------------------------------------
# propagation: megatron algebra on the LM bench symbol
# ---------------------------------------------------------------------------

def test_lm_megatron_propagation_collectives_and_peak_hbm():
    symb, dtypes, shapes, _ = _lm_params()
    rep = mxshard.analyze_sharding(
        symb, shapes=shapes, dtypes=dtypes, mesh="dp=2,tp=2",
        rules=ShardingRules.megatron(tp_axis="tp"))
    # row-parallel psums: embedding + (out_proj + fc2) per block
    psums = [c for c in rep.collectives
             if c["kind"] == "psum" and c["axis"] == "tp"]
    assert len(psums) == 1 + 2 * 2
    # clean model: no warnings/errors, every op modeled
    assert [f for f in rep.findings
            if f.severity in ("error", "warn")] == []
    assert rep.fallback_ops == {}
    # sharding genuinely shrinks the per-device footprint
    assert rep.per_device_peak_hbm_bytes < rep.replicated_peak_hbm_bytes
    assert rep.ici_bytes_per_step > 0


def test_forced_spec_mismatch_hidden_reshard_names_nodes_and_bytes():
    data = sym.Variable("data")
    h = sym.FullyConnected(data, num_hidden=2048, name="blk_qkv",
                           no_bias=True)          # col-parallel under
    out = sym.LayerNorm(h, name="blk_ln")         # megatron: last dim tp
    rep = mxshard.analyze_sharding(
        out, shapes={"data": (256, 2048)}, mesh="dp=2,tp=2",
        rules=ShardingRules.megatron(tp_axis="tp"))
    hr = [f for f in rep.findings if f.code == "hidden-reshard"]
    assert len(hr) >= 1
    f = hr[0]
    assert f.severity == "warn"
    assert "blk_qkv" in f.message and "blk_ln" in f.message  # both nodes
    assert str(256 * 2048 * 4) in f.message       # static bytes
    # classified: dp survives on dim 0 while tp gathers -> all-to-all
    assert "all-to-all" in f.message


def test_hidden_reshard_gated_by_min_mb():
    data = sym.Variable("data")
    h = sym.FullyConnected(data, num_hidden=64, name="blk_qkv",
                           no_bias=True)          # 2 KB edge: recorded,
    out = sym.LayerNorm(h, name="blk_ln")         # never a finding
    rep = mxshard.analyze_sharding(
        out, shapes={"data": (8, 64)}, mesh="dp=2,tp=2",
        rules=ShardingRules.megatron(tp_axis="tp"))
    assert [f for f in rep.findings if f.code == "hidden-reshard"] == []
    assert any(r["kind"] in ("all-gather", "all-to-all")
               for r in rep.reshards)


def test_implicit_replication_flagged_and_gated_by_min_mb():
    data = sym.Variable("data")
    out = sym.FullyConnected(data, num_hidden=512, name="plain",
                             no_bias=True)        # weight 512x1024 = 2MB
    kw = dict(shapes={"data": (8, 1024)}, mesh="dp=2,tp=2", rules=None)
    rep = mxshard.analyze_sharding(out, **kw)
    hits = [f for f in rep.findings if f.code == "implicit-replication"]
    assert any(f.node == "plain_weight" for f in hits)
    assert all(f.severity == "warn" for f in hits)
    # raising the floor past the tensor silences it
    rep = mxshard.analyze_sharding(out, min_mb=4.0, **kw)
    assert [f for f in rep.findings
            if f.code == "implicit-replication"] == []


def test_unknown_op_falls_back_replicated_and_is_recorded():
    data = sym.Variable("data")
    out = sym.tile(data, reps=(1, 2), name="tile0")
    rep = mxshard.analyze_sharding(out, shapes={"data": (8, 64)},
                                   mesh="dp=2")
    assert rep.fallback_ops.get("tile") == 1
    assert any(f.code == "shard-fallback" for f in rep.findings)


# ---------------------------------------------------------------------------
# zero false positives on the committed bench programs and examples/
# ---------------------------------------------------------------------------

def test_bench_set_zero_nonhint_findings_and_zero_fallbacks():
    results = mxshard.analyze_shard_bench_set("dp=2,tp=2")
    assert set(results) == {"llm.lm_micro", "quantization.convnet_fp32",
                            "quantization.convnet_bf16",
                            "quantization.convnet_int8"}
    for name, entry in results.items():
        bad = [f for f in entry["findings"]
               if f["severity"] in ("error", "warn")]
        assert bad == [], (name, bad)
        assert entry["fallback_ops"] == {}, name
        assert entry["per_device_peak_hbm_bytes"] > 0
        assert entry["ici_bytes_per_step"] > 0


def test_unsharded_device_put_zero_findings_on_examples():
    from incubator_mxnet_tpu import analysis
    found = []
    for path in glob.glob(os.path.join(REPO, "examples", "**", "*.py"),
                          recursive=True):
        found += [f.format() for f in analysis.check_source_file(path)
                  if f.code == "unsharded-device-put"]
    assert found == []


# ---------------------------------------------------------------------------
# static ICI vs measured KVStore counters (dp plan is byte-exact)
# ---------------------------------------------------------------------------

def test_measured_ici_check_dp4_byte_exact():
    res = mxshard.measured_ici_check("dp=4")
    assert res["dp"] == 4
    assert res["agreement_pct"] <= 10.0
    assert res["static_bytes_per_step"] == res["measured_bytes_per_step"]
    assert res["static_collectives_per_step"] == \
        res["measured_allreduce_dispatches"]
    assert res["ok"]


def test_measured_ici_check_dp2_tp2():
    res = mxshard.measured_ici_check("dp=2,tp=2")
    assert res["dp"] == 2
    assert res["agreement_pct"] <= 10.0
    assert res["static_bytes_per_step"] == res["measured_bytes_per_step"]
    assert res["ok"]


# ---------------------------------------------------------------------------
# budget gate: COST_BUDGETS.json "sharding" section
# ---------------------------------------------------------------------------

def test_committed_shard_budgets_pass_on_head():
    results = mxshard.analyze_shard_bench_set("dp=2,tp=2")
    budgets = mxbudgets.load(BUDGETS_PATH)
    assert budgets.get("sharding", {}).get("mesh") == "dp=2,tp=2"
    rep, deltas = mxshard.check_shard_budgets(results, budgets)
    assert [f for f in rep if f.severity == "error"] == []
    assert all(m["ok"] for prog in deltas.values() for m in prog.values())


def test_seeded_budget_regression_is_error():
    results = mxshard.analyze_shard_bench_set("dp=2,tp=2")
    budgets = {"sharding":
               mxshard.snapshot_shard_budgets(results, "dp=2,tp=2")}
    rep, _ = mxshard.check_shard_budgets(results, budgets)
    assert [f for f in rep if f.code == "budget-regression"] == []
    # shrink one committed budget under the measured value: regression
    budgets["sharding"]["programs"]["llm.lm_micro"][
        "ici_bytes_per_step"] //= 2
    rep, deltas = mxshard.check_shard_budgets(results, budgets)
    regs = [f for f in rep if f.code == "budget-regression"]
    assert regs and all(f.severity == "error" for f in regs)
    assert any("llm.lm_micro" in (f.node or "") + f.message for f in regs)
    assert not deltas["sharding.llm.lm_micro"]["ici_bytes_per_step"]["ok"]


# ---------------------------------------------------------------------------
# the mxlint --shard-report CLI contract
# ---------------------------------------------------------------------------

def test_cli_shard_report_clean_on_head(capsys):
    cli = _cli()
    rc = cli.main(["--shard-report", "--json", "--fail-on=warn",
                   "--budgets", BUDGETS_PATH])
    summary = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert summary["failing"] == 0
    assert set(summary["programs"]) >= {"llm.lm_micro"}


def test_cli_seeded_spec_mismatch_exits_nonzero(tmp_path, capsys):
    data = sym.Variable("data")
    h = sym.FullyConnected(data, num_hidden=2048, name="blk_qkv",
                           no_bias=True)
    out = sym.LayerNorm(h, name="blk_ln")
    path = tmp_path / "mismatch-symbol.json"
    path.write_text(out.tojson())
    cli = _cli()
    rc = cli.main(["--shard-report", str(path), "--json",
                   "--fail-on=warn", "--shape", "data=256,2048"])
    summary = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert summary["failing"] >= 1
    prog = summary["programs"]["mismatch-symbol.json"]
    assert any(f["code"] == "hidden-reshard" and "blk_qkv" in f["message"]
               and "blk_ln" in f["message"] for f in prog["findings"])


def test_cli_seeded_coverage_gap_exits_nonzero(tmp_path, capsys):
    data = sym.Variable("data")
    h = sym.FullyConnected(data, num_hidden=64, name="enc_qkv",
                           no_bias=True)           # matches a rule, so
    out = sym.FullyConnected(h, num_hidden=64, name="enc_attn",
                             no_bias=True)         # the set applies;
    path = tmp_path / "gap-symbol.json"            # enc_attn_weight
    path.write_text(out.tojson())                  # matches NONE
    cli = _cli()
    rc = cli.main(["--shard-report", str(path), "--json",
                   "--fail-on=error", "--shape", "data=8,64"])
    summary = json.loads(capsys.readouterr().out)
    assert rc == 1
    prog = summary["programs"]["gap-symbol.json"]
    assert any(f["code"] == "rule-coverage" and
               "enc_attn_weight" in f["message"]
               for f in prog["findings"])


# ---------------------------------------------------------------------------
# scaling-lane static block (BENCH_SCALING.json `shard_static`)
# ---------------------------------------------------------------------------

def test_run_scaling_shard_static_block():
    spec = importlib.util.spec_from_file_location(
        "_run_scaling_shard", os.path.join(REPO, "tools",
                                           "run_scaling.py"))
    rs = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(rs)
    block = rs._shard_static(2)
    for lane in ("img", "tok"):
        ent = block[lane]
        assert ent["per_device_peak_hbm_bytes"] > 0
        assert ent["per_device_peak_hbm_bytes"] < \
            ent["replicated_peak_hbm_bytes"]
        assert ent["dp_collectives_per_step"] >= 1
        assert ent["dp_ici_bytes_per_step"] > 0
