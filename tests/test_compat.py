"""Byte-compatibility tests: reference `.params` container and legacy
symbol JSON (reference formats: `src/ndarray/ndarray.cc:1531-1761`,
`src/nnvm/legacy_json_util.cc:49-219`)."""
import struct

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd
from incubator_mxnet_tpu.compat import load_params, save_params
from incubator_mxnet_tpu.compat.legacy_json import upgrade_json


def _ref_bytes_one_f4(name, arr):
    """Hand-pack a reference-format file, independent of the writer."""
    arr = np.asarray(arr, "<f4")
    out = struct.pack("<QQ", 0x112, 0)          # list magic + reserved
    out += struct.pack("<Q", 1)                 # one array
    out += struct.pack("<I", 0xF993FAC9)        # NDARRAY_V2_MAGIC
    out += struct.pack("<i", 0)                 # dense stype
    out += struct.pack("<I", arr.ndim) + struct.pack(
        f"<{arr.ndim}q", *arr.shape)            # TShape: u32 ndim + i64s
    out += struct.pack("<ii", 1, 0)             # Context cpu(0)
    out += struct.pack("<i", 0)                 # kFloat32
    out += arr.tobytes()
    out += struct.pack("<Q", 1)                 # one name
    b = name.encode()
    out += struct.pack("<Q", len(b)) + b
    return out


def test_load_synthesized_reference_file(tmp_path):
    arr = np.arange(12, dtype="<f4").reshape(3, 4)
    f = tmp_path / "ref.params"
    f.write_bytes(_ref_bytes_one_f4("conv0_weight", arr))
    out = load_params(str(f))
    assert list(out) == ["conv0_weight"]
    np.testing.assert_array_equal(out["conv0_weight"].asnumpy(), arr)


def test_writer_matches_reference_layout():
    arr = np.arange(6, dtype="<f4").reshape(2, 3)
    blob = save_params(None, {"w": nd.array(arr)})
    assert blob == _ref_bytes_one_f4("w", arr)


def test_roundtrip_dtypes_and_list(tmp_path):
    data = {
        "f4": nd.array(np.random.rand(2, 3).astype("f4")),
        "f8": nd.array(np.random.rand(4).astype("f8"), dtype="float64"),
        "u1": nd.array(np.arange(5, dtype="u1"), dtype="uint8"),
        "i4": nd.array(np.arange(5, dtype="i4"), dtype="int32"),
        "i8": nd.array(np.arange(3, dtype="i8"), dtype="int64"),
    }
    f = str(tmp_path / "mixed.params")
    save_params(f, data)
    out = load_params(f)
    for k, v in data.items():
        np.testing.assert_array_equal(out[k].asnumpy(), v.asnumpy())
        assert out[k].dtype == v.dtype, k
    # unnamed list round trip
    save_params(f, [nd.ones((2, 2)), nd.zeros((3,))])
    out = load_params(f)
    assert isinstance(out, list) and len(out) == 2
    np.testing.assert_array_equal(out[0].asnumpy(), np.ones((2, 2), "f4"))


def test_roundtrip_sparse(tmp_path):
    from incubator_mxnet_tpu.ndarray import sparse as sp
    rs = sp.RowSparseNDArray(data=np.ones((2, 4), "f4"),
                             indices=[1, 3], shape=(5, 4))
    csr = sp.CSRNDArray(data=np.array([1.0, 2.0, 3.0], "f4"),
                        indices=[0, 2, 1], indptr=[0, 2, 2, 3],
                        shape=(3, 3))
    f = str(tmp_path / "sparse.params")
    save_params(f, {"rs": rs, "csr": csr})
    out = load_params(f)
    np.testing.assert_array_equal(out["rs"].asnumpy(), rs.asnumpy())
    np.testing.assert_array_equal(out["csr"].asnumpy(), csr.asnumpy())
    assert type(out["rs"]).__name__ == "RowSparseNDArray"
    assert type(out["csr"]).__name__ == "CSRNDArray"


def test_load_legacy_v1_and_prev1_headers(tmp_path):
    arr = np.arange(4, dtype="<f4").reshape(2, 2)
    # V1 per-array header: V1 magic + i64 shape, no stype section
    body_v1 = struct.pack("<I", 0xF993FAC8)
    body_v1 += struct.pack("<I", 2) + struct.pack("<2q", 2, 2)
    body_v1 += struct.pack("<ii", 1, 0) + struct.pack("<i", 0) + arr.tobytes()
    # pre-V1: leading u32 IS the ndim, u32 dims
    body_v0 = struct.pack("<I", 2) + struct.pack("<2I", 2, 2)
    body_v0 += struct.pack("<ii", 1, 0) + struct.pack("<i", 0) + arr.tobytes()
    blob = struct.pack("<QQQ", 0x112, 0, 2) + body_v1 + body_v0
    blob += struct.pack("<Q", 0)            # no names -> list
    out = load_params(blob)
    assert isinstance(out, list) and len(out) == 2
    for o in out:
        np.testing.assert_array_equal(o.asnumpy(), arr)


def test_nd_save_load_is_reference_format(tmp_path):
    f = str(tmp_path / "x.params")
    nd.save(f, {"a": nd.ones((2, 2))})
    head = open(f, "rb").read(8)
    assert struct.unpack("<Q", head)[0] == 0x112
    out = nd.load(f)
    np.testing.assert_array_equal(out["a"].asnumpy(), np.ones((2, 2), "f4"))


def test_legacy_json_upgrade_aux_vars_and_hidden_keys():
    # an 0.8-era graph: BatchNorm missing its aux inputs, `param` attr key,
    # lr_mult stored as a plain attr
    g = {
        "nodes": [
            {"op": "null", "name": "data", "param": {}, "inputs": []},
            {"op": "null", "name": "fc_weight",
             "param": {"lr_mult": "2.0"}, "inputs": []},
            {"op": "FullyConnected", "name": "fc",
             "param": {"num_hidden": "8"},
             "inputs": [[0, 0, 0], [1, 0, 0]]},
            {"op": "BatchNorm", "name": "bn", "param": {},
             "inputs": [[2, 0, 0]]},
        ],
        "arg_nodes": [0, 1],
        "heads": [[3, 0, 0]],
    }
    up = upgrade_json(dict(g))
    names = [n["name"] for n in up["nodes"]]
    # FC grew its bias var; BatchNorm grew gamma/beta + moving stats vars
    assert "fc_bias" in names
    assert {"bn_gamma", "bn_beta", "bn_moving_mean",
            "bn_moving_var"} <= set(names)
    fc_w = next(n for n in up["nodes"] if n["name"] == "fc_weight")
    assert fc_w["attrs"].get("__lr_mult__") == "2.0"
    # and the upgraded graph actually loads as a Symbol
    import json as _json
    sym = mx.sym.load_json(_json.dumps(up))
    assert "fc_bias" in sym.list_arguments()
    assert set(sym.list_auxiliary_states()) == {"bn_moving_mean",
                                                "bn_moving_var"}


def test_legacy_json_argmax_axis():
    g = {"nodes": [
            {"op": "null", "name": "data", "attrs": {}, "inputs": []},
            {"op": "argmax", "name": "am", "attrs": {"axis": "-1"},
             "inputs": [[0, 0, 0]]}],
         "arg_nodes": [0], "heads": [[1, 0, 0]],
         "attrs": {"mxnet_version": ["int", 904]}}
    up = upgrade_json(g)
    assert "axis" not in up["nodes"][1]["attrs"]
