"""Mesh parallelism driven through the USER-FACING Gluon API.

VERDICT round-2 item 7: tensor parallelism + ZeRO must be reachable from
Block/Trainer, not only from hand-written shard_map.  A small transformer
trains on the 8-device CPU mesh with Megatron-sharded parameters and
ZeRO-sharded optimizer state, via the ordinary autograd/Trainer loop, and
must match the single-device run.
"""
import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, nd, parallel
from incubator_mxnet_tpu import test_utils as tu
from incubator_mxnet_tpu.parallel import ShardingRules

requires_shard_map = pytest.mark.skipif(
    not tu.has_stable_shard_map(),
    reason="this jax build lacks the stable jax.shard_map API; the "
           "TP+ZeRO parity tolerances are calibrated against that jax "
           "generation's sharded-reduction numerics")


class MiniTransformer(gluon.HybridBlock):
    """One attention + FFN block over embeddings — enough structure for
    column/row-parallel rules to engage on qkv/proj/fc1/fc2."""

    def __init__(self, vocab=32, dim=16, heads=2, **kw):
        super().__init__(**kw)
        self.dim = dim
        self.heads = heads
        with self.name_scope():
            self.embed = gluon.nn.Embedding(vocab, dim, prefix="embed_")
            self.qkv = gluon.nn.Dense(3 * dim, use_bias=False, flatten=False,
                                      prefix="qkv_")
            self.proj = gluon.nn.Dense(dim, use_bias=False, flatten=False,
                                       prefix="proj_")
            self.fc1 = gluon.nn.Dense(4 * dim, use_bias=False, flatten=False,
                                      prefix="fc1_")
            self.fc2 = gluon.nn.Dense(dim, use_bias=False, flatten=False,
                                      prefix="fc2_")
            self.norm = gluon.nn.LayerNorm(prefix="ln_")
            self.head = gluon.nn.Dense(vocab, use_bias=False, flatten=False,
                                       prefix="head_")

    def hybrid_forward(self, F, x):
        h = self.embed(x)                      # (B, T, D)
        qkv = self.qkv(h)                      # (B, T, 3D)
        q, k, v = (F.slice_axis(qkv, axis=2, begin=i * self.dim,
                                end=(i + 1) * self.dim) for i in range(3))
        att = F.batch_dot(q, k, transpose_b=True) / float(np.sqrt(self.dim))
        att = F.softmax(att, axis=-1)
        h = h + self.proj(F.batch_dot(att, v))
        h = self.norm(h)
        h = h + self.fc2(F.relu(self.fc1(h)))
        return self.head(h)


def _train(mesh=None, zero=False, steps=4, hybridize=False):
    np.random.seed(11)
    mx.random.seed(11)
    net = MiniTransformer()
    net.initialize(mx.initializer.Xavier(), ctx=mx.cpu())
    x = nd.array(np.random.randint(0, 32, (8, 6)).astype("f4"))
    y_np = np.random.randint(0, 32, (8, 6)).astype("f4")
    y = nd.array(y_np)
    # materialize deferred-init params with one forward before sharding
    net(x)
    if hybridize:
        net.hybridize()
    shardings = None
    if mesh is not None:
        rules = ShardingRules.megatron("tp")
        shardings = parallel.shard_block(net, mesh, rules)
        parallel.put(x, mesh, P("dp"))      # batch sharded over dp
        parallel.put(y, mesh, P("dp"))
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.05},
                            zero=(mesh, "dp") if (zero and mesh) else None)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    losses = []
    for _ in range(steps):
        with autograd.record():
            out = net(x)
            loss = loss_fn(out.reshape((-1, 32)), y.reshape((-1,)))
        loss.backward()
        trainer.step(x.shape[0])
        losses.append(float(loss.mean().asnumpy()))
    import re
    params = {re.sub(r"^minitransformer_\d+_", "", p.name):
              p.data().asnumpy()
              for p in net.collect_params().values()}
    return params, losses, net, trainer, shardings


@requires_shard_map
def test_gluon_tp_zero_matches_single_device():
    ref_params, ref_losses, _, _, _ = _train(mesh=None)
    mesh = parallel.make_mesh({"dp": 4, "tp": 2})
    params, losses, net, trainer, shardings = _train(mesh=mesh, zero=True)
    np.testing.assert_allclose(losses, ref_losses, rtol=2e-4, atol=1e-5)
    for k in ref_params:
        # sharded vs single-device sums reassociate floats; a few ULP-scale
        # outliers per thousand elements are expected
        np.testing.assert_allclose(params[k], ref_params[k], rtol=1e-3,
                                   atol=5e-5, err_msg=k)
    # the column-parallel qkv weight must ACTUALLY be sharded over tp
    qkv = [p for p in net.collect_params().values()
           if "qkv" in p.name][0]
    arr = qkv.data()._data
    assert arr.sharding.spec == P("tp", None), arr.sharding
    shard = arr.addressable_shards[0].data
    assert shard.shape[0] == arr.shape[0] // 2, "qkv not split over tp"
    # ZeRO: adam state tensors are sharded over dp (1/4 per rank)
    st = trainer._updaters[0].states
    some = [s for s in jax.tree_util.tree_leaves(
        list(st.values()),
        is_leaf=lambda a: hasattr(a, "_data"))
        if hasattr(a := s, "_data") and s.ndim >= 1 and s.shape[0] % 4 == 0]
    assert some, "no shardable state found"
    sharded = [s for s in some
               if s._data.sharding.spec and s._data.sharding.spec[0] == "dp"]
    assert sharded, "optimizer state is not ZeRO-sharded over dp"


def test_gluon_tp_hybridized_matches_eager():
    mesh = parallel.make_mesh({"dp": 4, "tp": 2})
    p_eager, l_eager, _, _, _ = _train(mesh=mesh)
    p_hyb, l_hyb, _, _, _ = _train(mesh=mesh, hybridize=True)
    np.testing.assert_allclose(l_hyb, l_eager, rtol=2e-4, atol=1e-5)
    for k in p_eager:
        np.testing.assert_allclose(p_hyb[k], p_eager[k], rtol=2e-4,
                                   atol=2e-5, err_msg=k)
