"""Production data plane (io_plane.py): h2d staging ring, per-host
sharded readers, device-resident prefetch, uint8-on-the-wire parity."""
import hashlib
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import io_plane, recordio
from incubator_mxnet_tpu.image import ImageRecordIterImpl
from incubator_mxnet_tpu.io import DataBatch, DataDesc, NDArrayIter

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _mlp():
    data = mx.sym.Variable("data")
    x = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    x = mx.sym.BatchNorm(x, name="bn1")
    x = mx.sym.Activation(x, act_type="relu")
    x = mx.sym.FullyConnected(x, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(x, name="softmax")


def _iter(n=48, bs=8, dim=12, seed=0):
    rng = np.random.RandomState(seed)
    return NDArrayIter(rng.randn(n, dim).astype("f4"),
                       rng.randint(0, 4, n).astype("f4"), batch_size=bs)


def _fit(num_epoch=2, seed=0):
    mx.random.seed(7)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.fit(_iter(seed=seed), num_epoch=num_epoch, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05, "momentum": 0.9},
            eval_metric="acc", initializer=mx.initializer.Xavier(),
            kvstore=None)
    return mod


def _sha(mod):
    args, auxs = mod.get_params()
    h = hashlib.sha256()
    for d in (args, auxs):
        for k in sorted(d):
            h.update(k.encode())
            h.update(d[k].asnumpy().tobytes())
    return h.hexdigest()


def _write_rec(path, n=16, size=28, seed=1):
    """A small .rec of decodable PNGs, label i on record i."""
    w = recordio.MXRecordIO(path, "w")
    rng = np.random.RandomState(seed)
    for i in range(n):
        img = (rng.rand(size, size + 2, 3) * 255).astype("uint8")
        w.write(recordio.pack_img(
            recordio.IRHeader(0, float(i), i, 0), img, img_fmt=".png"))
    w.close()


# ---------------------------------------------------------------------------
# the ring itself
# ---------------------------------------------------------------------------

def test_ring_preserves_content_and_order():
    it = _iter(n=40, bs=8)
    ref = [(b.data[0].asnumpy().copy(), b.label[0].asnumpy().copy())
           for b in it]
    it.reset()
    w = io_plane.DevicePrefetchIter(it)
    got = [(np.asarray(b.data[0]._data).copy(),
            np.asarray(b.label[0]._data).copy()) for b in w]
    w.close()
    assert len(got) == len(ref)
    for (rd, rl), (gd, gl) in zip(ref, got):
        np.testing.assert_array_equal(rd, gd)
        np.testing.assert_array_equal(rl, gl)


def test_ring_slot_reuse_never_corrupts_in_flight_batches():
    """Hold EVERY emitted device batch alive across the whole epoch and
    verify afterwards — the zero-copy-adoption hazard (a refilled
    staging slot mutating an already-emitted batch) regression test."""
    it = _iter(n=80, bs=8)
    ref = [b.data[0].asnumpy().copy() for b in it]
    it.reset()
    w = io_plane.DevicePrefetchIter(it)
    held = [b.data[0] for b in w]
    for r, h in zip(ref, held):
        np.testing.assert_array_equal(r, np.asarray(h._data))
    w.close()


def test_feeder_failure_surfaces_not_hangs():
    """A transfer/iterator failure on the mx-io-h2d thread must raise on
    the consumer, never leave it waiting on a dead feeder."""
    class Exploding(NDArrayIter):
        def next(self):
            b = super().next()
            if self.cursor >= 2 * self.batch_size:
                raise ValueError("decode exploded")
            return b

    w = io_plane.DevicePrefetchIter(
        Exploding(np.zeros((32, 4), "f4"), np.zeros(32, "f4"),
                  batch_size=8))
    with pytest.raises(ValueError, match="decode exploded"):
        for _ in range(10):
            w.next()
    w.close()


def test_iter_next_protocol_returns_every_batch():
    """The DataIter protocol (iter_next()/next() pairs) must yield every
    batch exactly once — iter_next buffers, next returns the buffer."""
    w = io_plane.DevicePrefetchIter(_iter(n=40, bs=8))
    seen = []
    while w.iter_next():
        seen.append(w.next().data[0].asnumpy().copy())
    w.close()
    ref = [b.data[0].asnumpy() for b in _iter(n=40, bs=8)]
    assert len(seen) == len(ref)
    for r, g in zip(ref, seen):
        np.testing.assert_array_equal(r, g)


def test_unset_num_parts_never_shards(tmp_path, monkeypatch):
    """An unset num_parts must read the FULL record set even in a dist
    environment (eval iterators must not silently score 1/N)."""
    rec = str(tmp_path / "imgs.rec")
    _write_rec(rec, n=9)
    monkeypatch.setenv("DMLC_NUM_WORKER", "3")
    monkeypatch.setenv("DMLC_RANK", "1")
    it = ImageRecordIterImpl(path_imgrec=rec, data_shape=(3, 24, 24),
                             batch_size=1, preprocess_threads=1,
                             round_batch=False)
    assert len(it._order) == 9
    it.close()


def test_exhausted_wrapper_keeps_raising_stopiteration():
    """Iterating a drained DevicePrefetchIter again WITHOUT reset()
    must raise StopIteration immediately (DataIter contract) — not
    hang waiting on a feeder that already exited."""
    w = io_plane.DevicePrefetchIter(_iter(n=16, bs=8))
    assert len(list(w)) == 2
    assert list(w) == []          # second pass: immediate StopIteration
    w.reset()
    assert len(list(w)) == 2      # reset restores a full epoch
    w.close()


def test_ring_bit_parity_vs_blocking(monkeypatch):
    """Training through the ring must be BIT-identical to the blocking
    input path (staging = copy + cast, nothing else)."""
    monkeypatch.setenv("MXNET_IO_RING", "0")
    sha_block = _sha(_fit())
    monkeypatch.setenv("MXNET_IO_RING", "1")
    before = io_plane.stats()["batches"]
    mod = _fit()
    assert io_plane.stats()["batches"] > before, "ring was not engaged"
    assert _sha(mod) == sha_block


def test_ring_delegation_and_stats():
    it = _iter(n=40, bs=8)
    w = io_plane.DevicePrefetchIter(it)
    # checkpoint-state and record-range delegate to the inner iterator
    assert w.record_range(2) == it.record_range(2)
    st = w.checkpoint_state()
    assert "idx" in st
    first = next(iter(w)).data[0].asnumpy()
    w.set_checkpoint_state(st, nbatch=0)
    again = next(iter(w)).data[0].asnumpy()
    np.testing.assert_array_equal(first, again)
    s = w.ring_stats()
    assert s["depth"] >= 2 and s["batches"] >= 1
    w.close()
    # the io producer is registered with the obs registry
    from incubator_mxnet_tpu.obs import metrics as obs_metrics
    snap = obs_metrics.registry().collect()
    assert any(k.startswith("io.") for k in snap)


def test_device_prefetch_loader_pairs():
    pairs = [(mx.nd.array(np.full((4, 3), i, "f4")),
              mx.nd.array(np.full((4,), i, "f4"))) for i in range(6)]
    loader = io_plane.DevicePrefetchLoader(pairs, ctx=mx.cpu())
    got = list(loader)
    loader.close()
    assert len(got) == 6
    for i, (d, l) in enumerate(got):
        assert float(d.asnumpy()[0, 0]) == i
        assert float(l.asnumpy()[0]) == i


# ---------------------------------------------------------------------------
# uint8-on-the-wire + in-graph normalize parity
# ---------------------------------------------------------------------------

def test_uint8_wire_in_graph_parity_bit_exact(tmp_path):
    """device_augment uint8 NHWC + normalize_symbol must reproduce the
    host-side fp32 path BIT-FOR-BIT (same crops, same f32 ops, and the
    symbol carries the ORIGINAL std so the op's reciprocal equals the
    host kernel's)."""
    rec = str(tmp_path / "imgs.rec")
    _write_rec(rec, n=12, size=30)
    kw = dict(path_imgrec=rec, data_shape=(3, 24, 24), batch_size=4,
              rand_crop=True, rand_mirror=True, seed=9,
              mean_r=123.68, mean_g=116.78, mean_b=103.94,
              std_r=58.4, std_g=57.1, std_b=57.4, preprocess_threads=1)
    host = ImageRecordIterImpl(device_augment=False, **kw)
    wire = ImageRecordIterImpl(device_augment=True, **kw)
    data = mx.sym.Variable("data")
    norm = wire.normalize_symbol(data)
    seen = 0
    for bh, bw in zip(host, wire):
        assert bw.data[0].dtype == np.uint8
        ex = norm.bind(mx.cpu(), {"data": bw.data[0]})
        y = ex.forward()[0].asnumpy()
        np.testing.assert_array_equal(y, bh.data[0].asnumpy())
        seen += 1
    assert seen >= 2
    host.close()
    wire.close()


def test_uint8_wire_auto_resolves_from_knob(monkeypatch, tmp_path):
    rec = str(tmp_path / "imgs.rec")
    _write_rec(rec, n=8)
    kw = dict(path_imgrec=rec, data_shape=(3, 24, 24), batch_size=4,
              preprocess_threads=1)
    monkeypatch.setenv("MXNET_IO_UINT8_WIRE", "1")
    it = ImageRecordIterImpl(device_augment="auto", **kw)
    assert it.provide_data[0].dtype == np.uint8
    it.close()
    monkeypatch.setenv("MXNET_IO_UINT8_WIRE", "0")
    it = ImageRecordIterImpl(device_augment="auto", **kw)
    assert it.provide_data[0].dtype != np.uint8
    it.close()


# ---------------------------------------------------------------------------
# per-host sharded readers
# ---------------------------------------------------------------------------

def test_shard_range_disjoint_exhaustive_deterministic():
    for n in (0, 1, 7, 16, 100, 1001):
        for parts in (1, 2, 3, 7, 16):
            ranges = recordio.shard_ranges(n, parts)
            # exhaustive + disjoint + ordered
            covered = []
            for lo, hi in ranges:
                covered.extend(range(lo, hi))
            assert covered == list(range(n)), (n, parts)
            # balanced: sizes differ by at most one
            sizes = [hi - lo for lo, hi in ranges]
            assert max(sizes) - min(sizes) <= 1
            # deterministic across calls (the resume invariant)
            assert ranges == recordio.shard_ranges(n, parts)
    with pytest.raises(mx.base.MXNetError):
        recordio.shard_range(10, 2, 2)


def test_record_iter_shards_are_exact(tmp_path):
    rec = str(tmp_path / "imgs.rec")
    _write_rec(rec, n=13)
    kw = dict(path_imgrec=rec, data_shape=(3, 24, 24), batch_size=1,
              preprocess_threads=1, round_batch=False)
    seen = []
    for p in range(3):
        it = ImageRecordIterImpl(part_index=p, num_parts=3, **kw)
        labels = [float(b.label[0].asnumpy()[0]) for b in it]
        it.close()
        seen.append(labels)
    flat = sorted(x for part in seen for x in part)
    assert flat == [float(i) for i in range(13)]          # exhaustive
    assert len(set(map(tuple, seen))) == 3                # disjoint
    # deterministic across a fresh construction (resume)
    it = ImageRecordIterImpl(part_index=1, num_parts=3, **kw)
    again = [float(b.label[0].asnumpy()[0]) for b in it]
    it.close()
    assert again == seen[1]


def test_auto_shard_env_and_epoch_fence_reshard(tmp_path, monkeypatch):
    rec = str(tmp_path / "imgs.rec")
    _write_rec(rec, n=12)
    kw = dict(path_imgrec=rec, data_shape=(3, 24, 24), batch_size=1,
              preprocess_threads=1, round_batch=False)
    # auto resolution from the dist environment
    monkeypatch.setenv("DMLC_NUM_WORKER", "3")
    monkeypatch.setenv("DMLC_RANK", "1")
    assert io_plane.auto_shard() == (1, 3)
    it = ImageRecordIterImpl(num_parts="auto", **kw)
    assert (it.part_index, it.num_parts) == (1, 3)
    assert len(it._order) == 4
    # shrink-and-resume rewrites the env; the NEXT epoch re-shards
    monkeypatch.setenv("DMLC_NUM_WORKER", "2")
    monkeypatch.setenv("DMLC_RANK", "0")
    it.reset()
    assert (it.part_index, it.num_parts) == (0, 2)
    assert len(it._order) == 6
    it.close()
    monkeypatch.delenv("DMLC_NUM_WORKER")
    monkeypatch.delenv("DMLC_RANK")
    assert io_plane.auto_shard() == (0, 1)


def test_quarantined_record_stays_local_to_its_shard(tmp_path):
    """A poisoned record quarantined on shard 0 disappears from shard
    0's order — including after an epoch-fence re-shard — and shard 1
    never sees any of it."""
    rec = str(tmp_path / "imgs.rec")
    _write_rec(rec, n=10)
    kw = dict(path_imgrec=rec, data_shape=(3, 24, 24), batch_size=1,
              preprocess_threads=1, round_batch=False)
    s0 = ImageRecordIterImpl(part_index=0, num_parts=2, **kw)
    s1 = ImageRecordIterImpl(part_index=1, num_parts=2, **kw)
    bad_id = int(s0._order[2])
    entries = [{"record": bad_id, "source": rec,
                "reason": "corrupt_record"}]
    s0.apply_quarantine(entries)
    s1.apply_quarantine(entries)
    assert bad_id not in set(int(i) for i in s0._order)
    assert len(s1._order) == 5                     # other shard untouched
    labels0 = [float(b.label[0].asnumpy()[0]) for b in s0]
    assert float(bad_id) not in labels0
    s0.reset()                                     # re-shard on the fence
    assert bad_id not in set(int(i) for i in s0._order)
    s0.close()
    s1.close()


# ---------------------------------------------------------------------------
# recompiles + concurrency
# ---------------------------------------------------------------------------

def test_zero_steady_state_recompiles_with_ring(monkeypatch):
    """With the ring enabled, epoch 2 of a fixed-shape fit must not
    compile anything new (the ring's staged batches keep the dispatch
    signature constant)."""
    monkeypatch.setenv("MXNET_IO_RING", "1")
    from incubator_mxnet_tpu import compile as mxcompile

    compiles = []

    def cb(param):
        compiles.append((param.epoch, param.nbatch,
                         mxcompile.stats()["counters"]["compiles"]))

    mx.random.seed(7)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.fit(_iter(n=48, bs=8), num_epoch=3, optimizer="sgd",
            eval_metric="acc", initializer=mx.initializer.Xavier(),
            batch_end_callback=cb, kvstore=None)
    assert mod._fused_step is not None and not mod._fused_step.broken
    first_epoch2 = next(c for e, n, c in compiles if e == 1)
    assert compiles[-1][2] == first_epoch2, \
        f"steady-state compiles moved: {compiles}"


def test_tsan_clean_ring_and_decode(tmp_path):
    """The new mx-io-* threads (ring feeder + decode pool) sweep clean
    under MXNET_TSAN=1 in a throwaway process."""
    rec = str(tmp_path / "imgs.rec")
    _write_rec(rec, n=8)
    log = str(tmp_path / "tsan.json")
    child = f"""
import numpy as np
import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import io_plane
from incubator_mxnet_tpu.image import ImageRecordIterImpl
rng = np.random.RandomState(0)
it = mx.io.NDArrayIter(rng.randn(32, 8).astype('f4'),
                       rng.randint(0, 4, 32).astype('f4'), batch_size=8)
w = io_plane.DevicePrefetchIter(it)
for _ in range(2):
    for b in w:
        pass
    w.reset()
w.close()
img = ImageRecordIterImpl(path_imgrec={rec!r}, data_shape=(3, 24, 24),
                          batch_size=4, preprocess_threads=2)
for b in img:
    pass
img.close()
"""
    env = dict(os.environ, MXNET_TSAN="1", MXNET_TSAN_LOG=log,
               JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", child], cwd=REPO,
                          capture_output=True, text=True, timeout=300,
                          env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    with open(log) as f:
        dumps = [json.loads(ln) for ln in f.read().splitlines()
                 if ln.strip()]
    found = [fi for d in dumps for fi in d.get("findings", [])]
    assert not found, found


# ---------------------------------------------------------------------------
# lint + knobs
# ---------------------------------------------------------------------------

def test_blocking_h2d_lint_fires_and_spares_ring_feeds():
    from incubator_mxnet_tpu import analysis
    src = ("import jax\n"
           "for batch in it:\n"
           "    x = jax.device_put(batch)\n"
           "    mod.fit_step(x, metric)\n")
    rep = analysis.check_source(src, filename="t.py")
    assert any(f.code == "blocking-h2d-in-loop" for f in rep)
    # a non-training loop is not flagged
    src2 = ("import jax\n"
            "for batch in it:\n"
            "    x = jax.device_put(batch)\n"
            "    outs.append(x)\n")
    rep2 = analysis.check_source(src2, filename="t.py")
    assert not any(f.code == "blocking-h2d-in-loop" for f in rep2)


def test_io_knobs_registered():
    from incubator_mxnet_tpu import config
    for knob in ("MXNET_IO_RING", "MXNET_IO_PREFETCH", "MXNET_IO_STAGING",
                 "MXNET_IO_UINT8_WIRE", "MXNET_IO_AUTO_SHARD"):
        assert knob in config.KNOBS, knob
        assert config.KNOBS[knob][2] == "honored", knob
    assert config.get("MXNET_IO_PREFETCH") >= 2
