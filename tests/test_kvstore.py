"""KVStore tests (reference tests/python/unittest/test_kvstore.py)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd

SHAPE = (4, 4)
KEYS = [5, 7, 11]


def _check(kv_type):
    kv = mx.kv.create(kv_type)
    kv.init(3, nd.ones(SHAPE))
    out = nd.zeros(SHAPE)
    kv.pull(3, out=out)
    np.testing.assert_allclose(out.asnumpy(), 1)
    kv.push(3, nd.ones(SHAPE) * 4)
    kv.pull(3, out=out)
    np.testing.assert_allclose(out.asnumpy(), 4)


def test_single_kv_pair():
    for kv_type in ("local", "device", "tpu"):
        _check(kv_type)


def test_list_kv_pair():
    kv = mx.kv.create("local")
    kv.init(KEYS, [nd.ones(SHAPE)] * len(KEYS))
    kv.push(KEYS, [nd.ones(SHAPE) * 4] * len(KEYS))
    outs = [nd.zeros(SHAPE) for _ in KEYS]
    kv.pull(KEYS, out=outs)
    for o in outs:
        np.testing.assert_allclose(o.asnumpy(), 4)


def test_aggregate_multi_device():
    """Multi-device push is reduced (reference comm.h Reduce semantics)."""
    import jax
    ndev = min(4, len(jax.devices()))
    kv = mx.kv.create("tpu")
    kv.init(9, nd.zeros(SHAPE))
    vals = [nd.ones(SHAPE, ctx=mx.tpu(i)) * (i + 1) for i in range(ndev)]
    kv.push(9, vals)
    out = nd.zeros(SHAPE)
    kv.pull(9, out=out)
    np.testing.assert_allclose(out.asnumpy(), sum(range(1, ndev + 1)))
    # pull back to each device
    outs = [nd.zeros(SHAPE, ctx=mx.tpu(i)) for i in range(ndev)]
    kv.pull(9, out=outs)
    for o in outs:
        np.testing.assert_allclose(o.asnumpy(), sum(range(1, ndev + 1)))


def test_tpu_reduce_is_one_collective():
    """kvstore='tpu' must lower the multi-device reduce to ONE XLA
    all-reduce over the participating devices (reference comm.h:451
    CommDevice / kvstore_nccl.h:285 ncclAllReduce), not serial
    device-to-device adds."""
    import jax
    ndev = min(8, len(jax.devices()))
    if ndev < 2:
        pytest.skip("needs >=2 devices")
    kv = mx.kv.create("tpu")
    devices = [mx.tpu(i).jax_device for i in range(ndev)]
    mesh = kv._mesh_for(devices)
    fn = kv._allreduce(mesh)
    from jax.sharding import NamedSharding, PartitionSpec as P
    import jax.numpy as jnp
    x = jax.device_put(jnp.ones((ndev,) + SHAPE),
                       NamedSharding(mesh, P("dev")))
    hlo = fn.lower(x).compile().as_text()
    assert "all-reduce" in hlo, "expected an all-reduce collective in HLO"


def test_tpu_training_step_matches_single_device():
    """DP-8 training through kvstore='tpu' == the same step on one device."""
    import jax
    ndev = min(8, len(jax.devices()))
    if ndev < 2:
        pytest.skip("needs >=2 devices")
    lr = 0.1
    w0 = np.random.RandomState(0).randn(*SHAPE).astype(np.float32)
    grads = [np.random.RandomState(i + 1).randn(*SHAPE).astype(np.float32)
             for i in range(ndev)]

    # single-device reference step: w -= lr * sum(grads)
    expect = w0 - lr * np.sum(grads, axis=0)

    kv = mx.kv.create("tpu")
    kv.init("w", nd.array(w0))
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=lr, rescale_grad=1.0))
    kv.push("w", [nd.array(g, ctx=mx.tpu(i)) for i, g in enumerate(grads)])
    outs = [nd.zeros(SHAPE, ctx=mx.tpu(i)) for i in range(ndev)]
    kv.pull("w", out=outs)
    for o in outs:
        np.testing.assert_allclose(o.asnumpy(), expect, rtol=1e-5, atol=1e-5)


def test_updater():
    kv = mx.kv.create("local")
    kv.init(3, nd.ones(SHAPE))

    def updater(key, recv, stored):
        stored += recv * 2

    kv.set_updater(updater)
    kv.push(3, nd.ones(SHAPE))
    out = nd.zeros(SHAPE)
    kv.pull(3, out=out)
    np.testing.assert_allclose(out.asnumpy(), 3)


def test_set_optimizer_updates_weights():
    kv = mx.kv.create("local")
    kv.init("w", nd.ones(SHAPE))
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1))
    kv.push("w", nd.ones(SHAPE))  # grad of ones
    out = nd.zeros(SHAPE)
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), 0.9, rtol=1e-6)


def test_gradient_compression():
    kv = mx.kv.create("device")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv.init(0, nd.zeros((4,)))
    kv.push(0, nd.array([1.0, -1.0, 0.2, 0.0]))
    out = nd.zeros((4,))
    kv.pull(0, out=out)
    np.testing.assert_allclose(out.asnumpy(), [0.5, -0.5, 0.0, 0.0])
    # error feedback: residual carries over
    kv.push(0, nd.array([0.0, 0.0, 0.4, 0.0]))
    kv.pull(0, out=out)
    np.testing.assert_allclose(out.asnumpy(), [0.5, -0.5, 0.5, 0.0])


def test_type_and_rank():
    kv = mx.kv.create("local")
    assert kv.type == "local"
    assert kv.rank == 0
    assert kv.num_workers == 1
    kvd = mx.kv.create("dist_sync")
    assert "dist" in kvd.type


def test_errors():
    kv = mx.kv.create("local")
    with pytest.raises(mx.MXNetError):
        kv.push(42, nd.ones(SHAPE))  # not initialized
    kv.init(1, nd.ones(SHAPE))
    with pytest.raises(mx.MXNetError):
        kv.init(1, nd.ones(SHAPE))  # double init


def test_tpu_kvstore_bucketed_multikey_push():
    """Multi-key push over a device mesh rides ONE fused all-reduce
    (bucketed `_reduce_many`), not one collective per key — and matches
    per-key results exactly (reference batched NCCL push, model.py:125)."""
    import jax
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd

    devs = [mx.cpu(i) for i in range(4)]
    kv = mx.kv.create("device")
    keys = ["a", "b", "c"]
    shapes = [(3,), (2, 2), (5, 1)]
    rng = np.random.RandomState(0)
    vals = {k: [rng.randn(*s).astype("f4") for _ in devs]
            for k, s in zip(keys, shapes)}
    for k, s in zip(keys, shapes):
        kv.init(k, nd.zeros(s))
    before = kv.allreduce_dispatches
    kv.push(keys, [[nd.array(v, ctx=d) for v, d in zip(vals[k], devs)]
                   for k in keys])
    assert kv.allreduce_dispatches == before + 1, \
        "batched multi-key push must issue ONE bucketed all-reduce"
    for k, s in zip(keys, shapes):
        out = nd.zeros(s)
        kv.pull(k, out=out)
        np.testing.assert_allclose(out.asnumpy(),
                                   np.sum(vals[k], axis=0), rtol=1e-6)

    # per-key push gives identical results (semantics unchanged)
    kv2 = mx.kv.create("device")
    for k, s in zip(keys, shapes):
        kv2.init(k, nd.zeros(s))
        kv2.push(k, [nd.array(v, ctx=d) for v, d in zip(vals[k], devs)])
        o1, o2 = nd.zeros(s), nd.zeros(s)
        kv.pull(k, out=o1)
        kv2.pull(k, out=o2)
        np.testing.assert_allclose(o1.asnumpy(), o2.asnumpy(), rtol=1e-6)
