"""Contrib/tensor op tail (ops/contrib_tail.py): fft/ifft, count_sketch,
khatri_rao, histogram, ravel/unravel, square_sum, cast_storage,
sparse_retain, SyncBatchNorm, DeformableConvolution,
DeformablePSROIPooling — each checked against an independent numpy
rendering of the reference semantics."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd


def test_fft_ifft_roundtrip():
    rng = np.random.RandomState(0)
    x = rng.randn(4, 8).astype("f4")
    y = nd.contrib.fft(nd.array(x)).asnumpy()
    assert y.shape == (4, 16)
    ref = np.fft.fft(x)
    np.testing.assert_allclose(y[:, 0::2], ref.real, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(y[:, 1::2], ref.imag, rtol=1e-4, atol=1e-4)
    # reference ifft is UNNORMALIZED: ifft(fft(x)) == N * x
    back = nd.contrib.ifft(nd.array(y)).asnumpy()
    np.testing.assert_allclose(back, 8 * x, rtol=1e-4, atol=1e-3)


def test_count_sketch():
    rng = np.random.RandomState(1)
    n, d, out_dim = 3, 10, 5
    x = rng.randn(n, d).astype("f4")
    h = rng.randint(0, out_dim, d).astype("f4")
    s = rng.choice([-1.0, 1.0], d).astype("f4")
    y = nd.contrib.count_sketch(nd.array(x), nd.array(h), nd.array(s),
                                out_dim=out_dim).asnumpy()
    ref = np.zeros((n, out_dim), "f4")
    for i in range(d):
        ref[:, int(h[i])] += s[i] * x[:, i]
    np.testing.assert_allclose(y, ref, rtol=1e-5, atol=1e-5)


def test_khatri_rao():
    A = np.array([[1., -1.], [2., -3.]], "f4")
    B = np.array([[1., 4.], [2., 5.], [3., 6.]], "f4")
    y = nd.khatri_rao(nd.array(A), nd.array(B)).asnumpy()
    # the reference docstring's worked example
    ref = np.array([[1, -4], [2, -5], [3, -6],
                    [2, -12], [4, -15], [6, -18]], "f4")
    np.testing.assert_allclose(y, ref)


def test_histogram():
    rng = np.random.RandomState(2)
    x = rng.uniform(0, 10, 50).astype("f4")
    cnt, edges = nd.histogram(nd.array(x), bin_cnt=5, range=(0, 10))
    ref_cnt, ref_edges = np.histogram(x, bins=5, range=(0, 10))
    np.testing.assert_allclose(cnt.asnumpy(), ref_cnt)
    np.testing.assert_allclose(edges.asnumpy(), ref_edges, rtol=1e-6)
    bins = np.array([0.0, 2.5, 5.0, 10.0], "f4")
    cnt2, edges2 = nd.histogram(nd.array(x), nd.array(bins))
    ref2, _ = np.histogram(x, bins=bins)
    np.testing.assert_allclose(cnt2.asnumpy(), ref2)


def test_ravel_unravel():
    shape = (3, 4, 5)
    rng = np.random.RandomState(3)
    flat = rng.randint(0, 60, 7).astype("f4")
    multi = nd.unravel_index(nd.array(flat), shape=shape).asnumpy()
    ref = np.stack(np.unravel_index(flat.astype("i8"), shape), 0)
    np.testing.assert_allclose(multi, ref)
    back = nd.ravel_multi_index(nd.array(multi), shape=shape).asnumpy()
    np.testing.assert_allclose(back, flat)


def test_square_sum_and_sparse_retain_and_cast_storage():
    rng = np.random.RandomState(4)
    x = rng.randn(4, 5).astype("f4")
    from incubator_mxnet_tpu.ndarray.ndarray import invoke
    from incubator_mxnet_tpu.ops import registry
    y = invoke(registry.get("_square_sum"), [nd.array(x)],
               {"axis": 1, "keepdims": True}).asnumpy()
    np.testing.assert_allclose(y, (x * x).sum(1, keepdims=True), rtol=1e-5)
    idx = np.array([0, 2], "f4")
    r = nd.sparse_retain(nd.array(x), nd.array(idx)).asnumpy()
    ref = np.zeros_like(x)
    ref[[0, 2]] = x[[0, 2]]
    np.testing.assert_allclose(r, ref)
    c = nd.cast_storage(nd.array(x), stype="default").asnumpy()
    np.testing.assert_allclose(c, x)


def test_sync_batch_norm_matches_batch_norm():
    rng = np.random.RandomState(5)
    x = rng.randn(4, 3, 2, 2).astype("f4")
    gamma = np.ones(3, "f4")
    beta = np.zeros(3, "f4")
    mean = np.zeros(3, "f4")
    var = np.ones(3, "f4")
    a = nd.contrib.SyncBatchNorm(
        nd.array(x), nd.array(gamma), nd.array(beta), nd.array(mean),
        nd.array(var), key="bn0").asnumpy()
    b = nd.BatchNorm(nd.array(x), nd.array(gamma), nd.array(beta),
                     nd.array(mean), nd.array(var)).asnumpy()
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)


def _np_bilinear(img, y, x):
    """numpy bilinear sample with zero outside bounds; img (C,H,W)."""
    C, H, W = img.shape
    y0, x0 = int(np.floor(y)), int(np.floor(x))
    out = np.zeros(C, img.dtype)
    for dy in (0, 1):
        for dx in (0, 1):
            yi, xi = y0 + dy, x0 + dx
            if 0 <= yi <= H - 1 and 0 <= xi <= W - 1:
                w = (1 - abs(y - yi)) * (1 - abs(x - xi))
                out += img[:, yi, xi] * w
    return out


def test_deformable_convolution_zero_offset_equals_conv():
    """With zero offsets the op IS a standard convolution."""
    rng = np.random.RandomState(6)
    N, C, H, W, F, k = 2, 4, 6, 6, 3, 3
    x = rng.randn(N, C, H, W).astype("f4")
    w = rng.randn(F, C, k, k).astype("f4")
    b = rng.randn(F).astype("f4")
    Ho = Wo = H - k + 1
    off = np.zeros((N, 2 * k * k, Ho, Wo), "f4")
    y = nd.contrib.DeformableConvolution(
        nd.array(x), nd.array(off), nd.array(w), nd.array(b),
        kernel=(k, k), num_filter=F).asnumpy()
    ref = nd.Convolution(nd.array(x), nd.array(w), nd.array(b),
                         kernel=(k, k), num_filter=F).asnumpy()
    np.testing.assert_allclose(y, ref, rtol=2e-4, atol=2e-4)


def test_deformable_convolution_offsets():
    """Nonzero offsets: compare against a direct numpy sampling loop."""
    rng = np.random.RandomState(7)
    N, C, H, W, F, k = 1, 2, 5, 5, 2, 3
    x = rng.randn(N, C, H, W).astype("f4")
    w = rng.randn(F, C, k, k).astype("f4")
    Ho = Wo = H - k + 1
    off = (rng.rand(N, 2 * k * k, Ho, Wo).astype("f4") - 0.5) * 2
    y = nd.contrib.DeformableConvolution(
        nd.array(x), nd.array(off), nd.array(w), kernel=(k, k),
        num_filter=F, no_bias=True).asnumpy()
    ref = np.zeros((N, F, Ho, Wo), "f4")
    for n in range(N):
        for ho in range(Ho):
            for wo in range(Wo):
                acc = np.zeros((C, k * k), "f4")
                for ki in range(k):
                    for kj in range(k):
                        kk = ki * k + kj
                        py = ho + ki + off[n, 2 * kk, ho, wo]
                        px = wo + kj + off[n, 2 * kk + 1, ho, wo]
                        acc[:, kk] = _np_bilinear(x[n], py, px)
                for f in range(F):
                    ref[n, f, ho, wo] = (acc * w[f].reshape(C, k * k)).sum()
    np.testing.assert_allclose(y, ref, rtol=2e-3, atol=2e-3)


def test_deformable_psroi_pooling_no_trans():
    """no_trans + group_size=1 + sample_per_part=1: check one bin against
    a direct numpy sample."""
    rng = np.random.RandomState(8)
    od, ps = 2, 2
    C = od * 1 * 1   # output_dim * group_size^2
    x = rng.randn(1, C, 8, 8).astype("f4")
    rois = np.array([[0, 0, 0, 7, 7]], "f4")
    out, cnt = nd.contrib.DeformablePSROIPooling(
        nd.array(x), nd.array(rois), spatial_scale=1.0, output_dim=od,
        group_size=1, pooled_size=ps, no_trans=True, sample_per_part=1)
    out = out.asnumpy()
    cnt = cnt.asnumpy()
    assert out.shape == (1, od, ps, ps)
    assert (cnt > 0).all()
    # bin (0,0): roi [start=-0.5, end=7.5), bin_h=4; the reference kernel
    # (deformable_psroi_pooling.cu:144) samples at hstart + i*sub_bin
    # with NO half-offset, so spp=1 samples at the bin start — and clips
    # the sample into [0, dim-1] before the bilinear interp
    start = -0.5
    bin_sz = 8.0 / ps
    for ctop in range(od):
        for ph in range(ps):
            for pw in range(ps):
                sy = min(max(start + ph * bin_sz, 0.0), 7.0)
                sx = min(max(start + pw * bin_sz, 0.0), 7.0)
                want = _np_bilinear(x[0, ctop:ctop + 1], sy, sx)[0]
                np.testing.assert_allclose(out[0, ctop, ph, pw], want,
                                           rtol=1e-4, atol=1e-4,
                                           err_msg=f"{ctop},{ph},{pw}")


def test_deformable_ops_in_symbol_and_grad():
    """Symbolic composition + gradient flow through the deformable conv."""
    data = mx.sym.Variable("data")
    off = mx.sym.Variable("off")
    out = mx.sym.contrib.DeformableConvolution(
        data, off, kernel=(3, 3), num_filter=2, no_bias=True,
        name="dconv")
    loss = mx.sym.sum(out)
    rng = np.random.RandomState(9)
    args = {"data": mx.nd.array(rng.randn(1, 2, 5, 5).astype("f4")),
            "off": mx.nd.array(np.zeros((1, 18, 3, 3), "f4")),
            "dconv_weight": mx.nd.array(rng.randn(2, 2, 3, 3).astype("f4"))}
    ex = loss.bind(mx.cpu(), args,
                   args_grad={k: mx.nd.zeros(v.shape)
                              for k, v in args.items()})
    ex.forward(is_train=True)
    ex.backward([mx.nd.ones(())])
    for k in args:
        assert np.isfinite(ex.grad_dict[k].asnumpy()).all(), k
    assert float(np.abs(ex.grad_dict["off"].asnumpy()).sum()) >= 0


def test_libsvm_iter(tmp_path):
    """LibSVMIter (reference src/io/iter_libsvm.cc:200): CSR data batches,
    dense labels, round_batch wrap."""
    p = tmp_path / "train.libsvm"
    p.write_text(
        "1 0:1.5 3:2.0\n"
        "0 1:1.0\n"
        "2 2:3.0 4:4.0\n"
        "1 0:0.5\n"
        "0 3:1.0\n")
    it = mx.io.LibSVMIter(data_libsvm=str(p), data_shape=(5,),
                          batch_size=2)
    batches = list(it)
    assert len(batches) == 3
    b0 = batches[0]
    assert b0.data[0].stype == "csr" if hasattr(b0.data[0], "stype") else True
    np.testing.assert_allclose(
        b0.data[0].asnumpy(),
        [[1.5, 0, 0, 2.0, 0], [0, 1.0, 0, 0, 0]])
    np.testing.assert_allclose(b0.label[0].asnumpy(), [1, 0])
    # round_batch tail: 5 rows, batch 2 -> last batch pad=1, wraps row 0
    b2 = batches[2]
    assert b2.pad == 1
    np.testing.assert_allclose(
        b2.data[0].asnumpy(),
        [[0, 0, 0, 1.0, 0], [1.5, 0, 0, 2.0, 0]])
    it.reset()
    assert len(list(it)) == 3


def test_libsvm_iter_csr_labels_and_multilabel(tmp_path):
    """CSR labels from a separate label file pad on wrapped tails like the
    data; inline multi-labels fill label_shape."""
    d = tmp_path / "d.libsvm"
    d.write_text("0 0:1.0\n0 1:2.0\n0 2:3.0\n")
    lab = tmp_path / "l.libsvm"
    lab.write_text("0 0:1\n0 1:1\n0 0:1\n")
    it = mx.io.LibSVMIter(data_libsvm=str(d), data_shape=(4,),
                          label_libsvm=str(lab), label_shape=(2,),
                          batch_size=2)
    batches = list(it)
    assert len(batches) == 2
    b1 = batches[1]
    assert b1.pad == 1
    # data and label row counts agree on the wrapped batch
    assert b1.data[0].shape[0] == 2
    assert b1.label[0].shape[0] == 2
    np.testing.assert_allclose(b1.label[0].asnumpy(),
                               [[1, 0], [1, 0]])
    # inline multi-label fills label_shape
    m = tmp_path / "m.libsvm"
    m.write_text("1 2 0:1.0\n3 4 1:1.0\n")
    it2 = mx.io.LibSVMIter(data_libsvm=str(m), data_shape=(4,),
                           label_shape=(2,), batch_size=2)
    b = next(iter(it2))
    np.testing.assert_allclose(b.label[0].asnumpy(), [[1, 2], [3, 4]])
