"""Multi-process distributed kvstore tests.

The reference exercises dist kvstores by launching real localhost worker
processes against a parameter server (`tests/nightly/dist_sync_kvstore.py:30-60`
via `tools/launch.py`); this does the same with small tensors so it runs in
CI: every worker pushes rank-dependent values and asserts the aggregated
result is identical everywhere.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

from incubator_mxnet_tpu import test_utils as tu


def _require_mp_collectives():
    """Capability guard: collective-mode tests execute a real XLA
    reduction across worker PROCESSES on the CPU backend, which older
    jaxlib rejects at dispatch ("Multiprocess computations aren't
    implemented on the CPU backend").  The probe (two throwaway
    subprocesses running the collective plane's exact recipe, cached
    per session) runs LAZILY inside the guarded tests so plain
    collection — and deselected runs — never pay for it."""
    if not tu.has_multiprocess_cpu_collectives():
        pytest.skip("this jaxlib cannot execute multiprocess XLA "
                    "collectives on the CPU backend (the collective "
                    "data plane's recipe)")


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import os, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd

kv = mx.kv.create("dist_sync")
rank, nw = kv.rank, kv.num_workers
assert nw == int(os.environ["DMLC_NUM_WORKER"]), (rank, nw)
if os.environ.get("MXNET_KVSTORE_COLLECTIVE") == "1":
    assert kv._collective is not None, "collective data plane must engage"
    # gradient bytes must never transit the socket in collective mode
    from incubator_mxnet_tpu.dist import transport
    _orig_send = transport.send_msg
    def _no_push(sock, obj):
        assert not (isinstance(obj, dict) and obj.get("cmd") == "push"), \
            "gradient push escaped to the socket in collective mode"
        return _orig_send(sock, obj)
    transport.send_msg = _no_push

# round-trip 1: plain aggregation (no optimizer -> pull returns the sum)
kv.init("3", nd.zeros((4, 2)))
kv.push("3", nd.ones((4, 2)) * (rank + 1))
out = nd.zeros((4, 2))
kv.pull("3", out=out)
expect = np.full((4, 2), sum(r + 1 for r in range(nw)), "f4")
np.testing.assert_allclose(out.asnumpy(), expect)

# round-trip 2: versioned second round must not mix with round 1
kv.push("3", nd.ones((4, 2)) * 10 * (rank + 1))
out2 = nd.zeros((4, 2))
kv.pull("3", out=out2)
np.testing.assert_allclose(out2.asnumpy(), 10 * expect)

# two pushes before a pull: ps-lite timestamp semantics — each push joins
# its own round, rounds aggregate across all workers in order
kv.push("3", nd.ones((4, 2)) * 100 * (rank + 1))
kv.push("3", nd.ones((4, 2)) * 1000 * (rank + 1))
out3 = nd.zeros((4, 2))
kv.pull("3", out=out3)
np.testing.assert_allclose(out3.asnumpy(), 1000 * expect)

# multi-device push: per-device shards reduce locally before the wire
devs = [mx.cpu(i) for i in range(min(4, len(jax.devices())))]
kv.init("md", nd.zeros((2, 2)))
kv.push("md", [nd.ones((2, 2), ctx=d) for d in devs])
md = nd.zeros((2, 2))
kv.pull("md", out=md)
np.testing.assert_allclose(md.asnumpy(), len(devs) * nw)

# batched multi-key push/pull: the whole key list rides ONE fused
# collective dispatch (bucketed all-reduce), not one per key
if os.environ.get("MXNET_KVSTORE_COLLECTIVE") == "1":
    bkeys = ["b0", "b1", "b2"]
    bshapes = [(3,), (2, 2), (5,)]
    for k, s in zip(bkeys, bshapes):
        kv.init(k, nd.zeros(s))
    before = kv._collective.dispatch_count
    kv.push(bkeys, [nd.ones(s) * (rank + 1) for s in bshapes])
    after = kv._collective.dispatch_count
    assert after == before + 1, ("batched push must issue ONE collective",
                                 before, after)
    bouts = [nd.zeros(s) for s in bshapes]
    kv.pull(bkeys, out=bouts)
    tot = sum(r + 1 for r in range(nw))
    for o in bouts:
        np.testing.assert_allclose(o.asnumpy(), tot)

# server-side optimizer: weight = w0 - lr * sum(grads) each round
kv.init("w", nd.ones((3,)))
kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1, rescale_grad=1.0 / nw))
for step in range(3):
    kv.push("w", nd.ones((3,)) * (rank + 1))
    w = nd.zeros((3,))
    kv.pull("w", out=w)
    grad_mean = sum(r + 1 for r in range(nw)) / nw
    np.testing.assert_allclose(
        w.asnumpy(), 1.0 - 0.1 * grad_mean * (step + 1), rtol=1e-5)

kv._barrier()
kv.close()
print("worker %d OK" % rank)
"""


@pytest.mark.parametrize("n_workers,collective", [(2, "0"), (4, "0"),
                                                  (2, "1")])
def test_dist_sync_multiprocess(tmp_path, n_workers, collective):
    """collective="0": gradients transit the parameter server (socket data
    plane).  collective="1": gradients all-reduce over the global device
    mesh (XLA collectives; server = control plane) — same observable
    semantics either way."""
    if collective == "1":
        _require_mp_collectives()
    from incubator_mxnet_tpu.dist.server import ParameterServer

    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    server = ParameterServer(num_workers=n_workers).start()
    env = dict(os.environ,
               DMLC_PS_ROOT_URI="127.0.0.1",
               DMLC_PS_ROOT_PORT=str(server.port),
               DMLC_NUM_WORKER=str(n_workers),
               DMLC_ROLE="worker",
               MXNET_KVSTORE_COLLECTIVE=collective,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=4",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    procs = [subprocess.Popen([sys.executable, str(script)],
                              env=dict(env, DMLC_RANK=str(r)),
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT)
             for r in range(n_workers)]
    outs = [p.communicate(timeout=240)[0].decode() for p in procs]
    server.shutdown()
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {r} failed:\n{out}"
        assert f"worker {r} OK" in out


def test_2bit_wire_codec_roundtrip():
    """pack/unpack identity + the 16x wire-size contract
    (reference gradient_compression.h packs 16 grads per 32-bit word)."""
    from incubator_mxnet_tpu.dist.compression import (pack_2bit, unpack_2bit,
                                                      is_packed)
    rng = np.random.RandomState(0)
    for shape in [(7,), (16,), (5, 9), (128, 3)]:
        thr = 0.5
        g = rng.randn(*shape).astype("f4")
        q = np.where(g >= thr, thr,
                     np.where(g <= -thr, -thr, 0.0)).astype("f4")
        msg = pack_2bit(q, thr)
        assert is_packed(msg)
        n = int(np.prod(shape))
        assert msg["packed2bit"].nbytes == (n + 3) // 4, \
            "wire payload must be ~n/4 bytes (16x smaller than fp32)"
        np.testing.assert_array_equal(unpack_2bit(msg), q)


WORKER_COMPRESS = r"""
import os
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd
from incubator_mxnet_tpu.dist import transport
from incubator_mxnet_tpu.dist.compression import is_packed

# spy on the wire: every push frame must carry the packed payload
sent = []
orig = transport.send_msg
def spy(sock, obj):
    if isinstance(obj, dict) and obj.get("cmd") == "push":
        sent.append(obj["value"])
    return orig(sock, obj)
transport.send_msg = spy

os.environ["MXNET_KVSTORE_COLLECTIVE"] = "0"  # this test probes the socket wire
kv = mx.kv.create("dist_sync")
rank, nw = kv.rank, kv.num_workers
kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
n = 64
kv.init("g", nd.zeros((n,)))
grad = np.linspace(-1, 1, n).astype("f4") * (rank + 1)
kv.push("g", nd.array(grad))
out = nd.zeros((n,))
kv.pull("g", out=out)
# every worker's contribution was quantized to {-.5, 0, +.5} then summed
expect = np.zeros(n, "f4")
for r in range(nw):
    g = np.linspace(-1, 1, n).astype("f4") * (r + 1)
    expect += np.where(g >= .5, .5, np.where(g <= -.5, -.5, 0.)).astype("f4")
np.testing.assert_allclose(out.asnumpy(), expect, rtol=1e-6)
assert sent and all(is_packed(v) for v in sent), "gradient bytes left the " \
    "socket dense — compression must pack the wire"
assert all(v["packed2bit"].nbytes == (n + 3) // 4 for v in sent)
kv._barrier()
kv.close()
print("worker %d OK" % rank)
"""


def test_dist_compression_packs_the_wire(tmp_path):
    from incubator_mxnet_tpu.dist.server import ParameterServer

    n_workers = 2
    script = tmp_path / "worker_c.py"
    script.write_text(WORKER_COMPRESS)
    server = ParameterServer(num_workers=n_workers).start()
    env = dict(os.environ,
               DMLC_PS_ROOT_URI="127.0.0.1",
               DMLC_PS_ROOT_PORT=str(server.port),
               DMLC_NUM_WORKER=str(n_workers),
               DMLC_ROLE="worker",
               JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    procs = [subprocess.Popen([sys.executable, str(script)],
                              env=dict(env, DMLC_RANK=str(r)),
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT)
             for r in range(n_workers)]
    outs = [p.communicate(timeout=240)[0].decode() for p in procs]
    server.shutdown()
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {r} failed:\n{out}"


def test_launcher(tmp_path):
    """tools/launch.py spawns server+workers and propagates exit codes.
    (Launched workers default to MXNET_KVSTORE_COLLECTIVE=1, so the data
    plane needs multiprocess CPU collectives.)"""
    _require_mp_collectives()
    script = tmp_path / "trivial.py"
    script.write_text(
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "import incubator_mxnet_tpu as mx\n"
        "from incubator_mxnet_tpu import nd\n"
        "kv = mx.kv.create('dist_sync')\n"
        "kv.init('0', nd.zeros((2,)))\n"
        "kv.push('0', nd.ones((2,)))\n"
        "o = nd.zeros((2,))\n"
        "kv.pull('0', out=o)\n"
        "assert o.asnumpy()[0] == kv.num_workers\n"
        "kv.close()\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    rc = subprocess.call(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", sys.executable, str(script)],
        env=env, timeout=240)
    assert rc == 0


def test_async_push_applies_immediately():
    """dist_async: a push applies without waiting for the other worker
    (two in-process clients; only rank 0 pushes)."""
    import threading

    from incubator_mxnet_tpu.dist.server import ParameterServer
    from incubator_mxnet_tpu.dist.kvstore_dist import KVStoreDist
    from incubator_mxnet_tpu import nd

    server = ParameterServer(num_workers=2).start()
    old = {k: os.environ.get(k) for k in
           ("DMLC_PS_ROOT_URI", "DMLC_PS_ROOT_PORT", "DMLC_RANK")}
    os.environ.update(DMLC_PS_ROOT_URI="127.0.0.1",
                      DMLC_PS_ROOT_PORT=str(server.port), DMLC_RANK="0")
    try:
        kv0 = KVStoreDist("dist_async")
        os.environ["DMLC_RANK"] = "1"
        kv1 = KVStoreDist("dist_async")
        # init barriers across all workers: run rank 1's from a thread
        t = threading.Thread(target=kv1.init, args=("k", nd.zeros((2,))))
        t.start()
        kv0.init("k", nd.zeros((2,)))
        t.join(timeout=60)
        assert not t.is_alive()
        kv0.push("k", nd.ones((2,)))   # rank 1 never pushes
        out = nd.zeros((2,))
        kv0.pull("k", out=out)
        np.testing.assert_allclose(out.asnumpy(), 1.0)
        kv0.close()
        kv1.close()
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        server.shutdown()


WORKER_COLLECTIVE_COMPRESS = r"""
import os
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd
from incubator_mxnet_tpu.dist import kvstore_dist

os.environ["MXNET_KVSTORE_COLLECTIVE"] = "1"
kv = mx.kv.create("dist_sync")
rank, nw = kv.rank, kv.num_workers
assert kv._collective is not None

# spy on the collective payload dtype: compressed gradients must ride the
# interconnect at bf16 (half of fp32) — the collective-mode reading of the
# reference's wire compression (gradient_compression.h)
payload_dtypes = []
orig_many = kv._collective.allreduce_many
orig_one = kv._collective.allreduce
def spy_many(arrs):
    payload_dtypes.extend(str(a.dtype) for a in arrs)
    return orig_many(arrs)
def spy_one(a):
    payload_dtypes.append(str(a.dtype))
    return orig_one(a)
kv._collective.allreduce_many = spy_many
kv._collective.allreduce = spy_one

kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
n = 64
kv.init("g", nd.zeros((n,)))
payload_dtypes.clear()           # init broadcast stays full width
grad = np.linspace(-1, 1, n).astype("f4") * (rank + 1)
kv.push("g", nd.array(grad))
out = nd.zeros((n,))
kv.pull("g", out=out)
expect = np.zeros(n, "f4")
for r in range(nw):
    g = np.linspace(-1, 1, n).astype("f4") * (r + 1)
    expect += np.where(g >= .5, .5, np.where(g <= -.5, -.5, 0.)).astype("f4")
np.testing.assert_allclose(out.asnumpy(), expect, rtol=1e-2, atol=1e-3)
assert payload_dtypes and all(d == "bfloat16" for d in payload_dtypes), \
    payload_dtypes
kv._barrier()
kv.close()
print("worker %d OK" % rank)
"""


def test_dist_collective_compression_halves_payload(tmp_path):
    """Collective mode + 2-bit compression: gradients quantize with error
    feedback device-side and the global all-reduce payload is bf16."""
    _require_mp_collectives()
    from incubator_mxnet_tpu.dist.server import ParameterServer

    n_workers = 2
    script = tmp_path / "worker_cc.py"
    script.write_text(WORKER_COLLECTIVE_COMPRESS)
    server = ParameterServer(num_workers=n_workers).start()
    env = dict(os.environ,
               DMLC_PS_ROOT_URI="127.0.0.1",
               DMLC_PS_ROOT_PORT=str(server.port),
               DMLC_NUM_WORKER=str(n_workers),
               DMLC_ROLE="worker",
               JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    procs = [subprocess.Popen([sys.executable, str(script)],
                              env=dict(env, DMLC_RANK=str(r)),
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT)
             for r in range(n_workers)]
    outs = [p.communicate(timeout=240)[0].decode() for p in procs]
    server.shutdown()
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {r} failed:\n{out}"


SHARDED_WORKER = r"""
import os, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd

kv = mx.kv.create("dist_sync")
rank, nw = kv.rank, kv.num_workers
assert kv._num_servers == 2, kv._num_servers
assert len(kv._chans) == 2

# small key: lands whole on ONE hashed server
kv.init("tiny", nd.zeros((3,)))
kv.push("tiny", nd.ones((3,)) * (rank + 1))
out = nd.zeros((3,))
kv.pull("tiny", out=out)
tot = sum(r + 1 for r in range(nw))
np.testing.assert_allclose(out.asnumpy(), tot)

# big key: over MXNET_KVSTORE_BIGARRAY_BOUND -> flat-split, one
# contiguous range per server, reassembled on pull
big = np.arange(40, dtype="f4").reshape(5, 8)
kv.init("big", nd.array(big * 0))
kv.push("big", nd.array(big * (rank + 1)))
bout = nd.zeros((5, 8))
kv.pull("big", out=bout)
np.testing.assert_allclose(bout.asnumpy(), big * tot)

# server-side optimizer applies per range: weight = w0 - lr*mean over rounds
kv.init("w", nd.ones((30,)))
kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1, rescale_grad=1.0 / nw))
for step in range(2):
    kv.push("w", nd.ones((30,)) * (rank + 1))
    w = nd.zeros((30,))
    kv.pull("w", out=w)
    gm = tot / nw
    np.testing.assert_allclose(w.asnumpy(), 1.0 - 0.1 * gm * (step + 1),
                               rtol=1e-5)

kv._barrier()
kv.close()
print("worker %d OK" % rank)
"""


def test_dist_sync_sharded_servers(tmp_path):
    """Key-range sharding over TWO parameter servers (reference
    kvstore_dist.h:44 + MXNET_KVSTORE_BIGARRAY_BOUND splitting,
    docs/faq/distributed_training.md:50-53): big arrays flat-split one
    range per server; small keys hash to one; server-side optimizer runs
    per range."""
    from incubator_mxnet_tpu.dist.server import (ParameterServer,
                                                 register_with_root)

    n_workers = 2
    script = tmp_path / "worker.py"
    script.write_text(SHARDED_WORKER)
    root = ParameterServer(num_workers=n_workers, num_servers=2).start()
    second = ParameterServer(num_workers=n_workers, num_servers=2,
                             port=0).start()
    register_with_root("127.0.0.1", root.port, 1, "127.0.0.1", second.port)
    env = dict(os.environ,
               DMLC_PS_ROOT_URI="127.0.0.1",
               DMLC_PS_ROOT_PORT=str(root.port),
               DMLC_NUM_WORKER=str(n_workers),
               DMLC_NUM_SERVER="2",
               DMLC_ROLE="worker",
               MXNET_KVSTORE_COLLECTIVE="0",
               MXNET_KVSTORE_BIGARRAY_BOUND="16",
               JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    procs = [subprocess.Popen([sys.executable, str(script)],
                              env=dict(env, DMLC_RANK=str(r)),
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT)
             for r in range(n_workers)]
    outs = [p.communicate(timeout=240)[0].decode() for p in procs]
    root.shutdown()
    second.shutdown()
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {r} failed:\n{out}"
        assert f"worker {r} OK" in out
    # both servers actually held key ranges of the big arrays
    assert "big" in root._state.store and "big" in second._state.store
    assert root._state.store["big"].size + \
        second._state.store["big"].size == 40


THREE_SERVER_WORKER = r"""
import os, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd

kv = mx.kv.create("dist_sync")
rank, nw = kv.rank, kv.num_workers
assert kv._num_servers == 3, kv._num_servers
assert len(kv._chans) == 3

# uneven key ranges: 40 elements over 3 servers -> bounds [0,13,26,40],
# slice sizes 13/13/14 — every boundary crossed inside one array
big = np.arange(40, dtype="f4").reshape(8, 5)
shards = kv._shards("big", 40)
sizes = [sl.stop - sl.start for _, sl in shards]
assert sizes == [13, 13, 14], sizes
assert [srv for srv, _ in shards] == [0, 1, 2]
kv.init("big", nd.array(big * 0))
kv.push("big", nd.array(big * (rank + 1)))
out = nd.zeros((8, 5))
kv.pull("big", out=out)
tot = sum(r + 1 for r in range(nw))
np.testing.assert_allclose(out.asnumpy(), big * tot)

# several small keys: hashed placement must stay within the server set
# and every round trip reassembles exactly
for i, shape in enumerate([(3,), (2, 2), (7,), (5,)]):
    k = "k%d" % i
    kv.init(k, nd.zeros(shape))
    kv.push(k, nd.ones(shape) * (rank + 1) * (i + 1))
    o = nd.zeros(shape)
    kv.pull(k, out=o)
    np.testing.assert_allclose(o.asnumpy(), tot * (i + 1))

# server-side optimizer over uneven ranges + state pull-back through the
# control channel (the checkpoint plane's dist resume path)
kv.init("w", nd.ones((40,)))
kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1, momentum=0.9,
                                  rescale_grad=1.0 / nw))
kv.push("w", nd.ones((40,)) * (rank + 1))
w = nd.zeros((40,))
kv.pull("w", out=w)
gm = tot / nw
np.testing.assert_allclose(w.asnumpy(), 1.0 - 0.1 * gm, rtol=1e-5)
blob = kv.get_optimizer_states(dump_optimizer=True)
import pickle
per_server = pickle.loads(blob)["dist_server_states"]
assert set(per_server) == {0, 1, 2}
# every server holds the momentum slots for exactly ITS range of "w"
sizes = []
for srv, s in sorted(per_server.items()):
    states = pickle.loads(s)
    states = states[0] if isinstance(states, tuple) else states
    mom = states["w"]
    sizes.append(int(mom.size))
assert sorted(sizes) == [13, 13, 14], sizes
# restore round-trips cleanly (rank 0 writes back, everyone barriers)
kv.set_optimizer_states(blob)

kv._barrier()
kv.close()
print("worker %d OK" % rank)
"""


def test_dist_sync_three_servers_uneven_ranges(tmp_path):
    """num_servers=3 with UNEVEN key ranges (40 elements -> 13/13/14), a
    big-array split crossing every server boundary, and server-side
    optimizer state pulled back through the control channel — the dist
    layout the elastic checkpoint resume path depends on."""
    from incubator_mxnet_tpu.dist.server import (ParameterServer,
                                                 register_with_root)

    n_workers = 2
    script = tmp_path / "worker3.py"
    script.write_text(THREE_SERVER_WORKER)
    root = ParameterServer(num_workers=n_workers, num_servers=3).start()
    secondaries = []
    for sid in (1, 2):
        srv = ParameterServer(num_workers=n_workers, num_servers=3,
                              port=0).start()
        register_with_root("127.0.0.1", root.port, sid, "127.0.0.1",
                           srv.port)
        secondaries.append(srv)
    env = dict(os.environ,
               DMLC_PS_ROOT_URI="127.0.0.1",
               DMLC_PS_ROOT_PORT=str(root.port),
               DMLC_NUM_WORKER=str(n_workers),
               DMLC_NUM_SERVER="3",
               DMLC_ROLE="worker",
               MXNET_KVSTORE_COLLECTIVE="0",
               MXNET_KVSTORE_BIGARRAY_BOUND="16",
               JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    procs = [subprocess.Popen([sys.executable, str(script)],
                              env=dict(env, DMLC_RANK=str(r)),
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT)
             for r in range(n_workers)]
    outs = [p.communicate(timeout=240)[0].decode() for p in procs]
    root.shutdown()
    for srv in secondaries:
        srv.shutdown()
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {r} failed:\n{out}"
        assert f"worker {r} OK" in out
    # all three servers held a range of the big keys
    for key in ("big", "w"):
        sizes = sorted(s._state.store[key].size
                       for s in [root] + secondaries)
        assert sizes == [13, 13, 14], (key, sizes)


def test_dist_killed_server_surfaces_clean_error():
    """A killed secondary server must surface as a structured
    ServerLostError naming the server AND the keys it owned, not a raw
    socket traceback: run the secondary as a real subprocess and SIGKILL
    it mid-training."""
    from incubator_mxnet_tpu.resilience import ServerLostError
    from incubator_mxnet_tpu.dist.server import ParameterServer
    from incubator_mxnet_tpu.dist.kvstore_dist import KVStoreDist
    from incubator_mxnet_tpu import nd

    root = ParameterServer(num_workers=1, num_servers=2).start()
    env = dict(os.environ, DMLC_SERVER_ID="1",
               DMLC_PS_ROOT_URI="127.0.0.1",
               DMLC_PS_ROOT_PORT=str(root.port),
               JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    proc = subprocess.Popen(
        [sys.executable, "-m", "incubator_mxnet_tpu.dist.server"], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    old = {k: os.environ.get(k) for k in
           ("DMLC_PS_ROOT_URI", "DMLC_PS_ROOT_PORT", "DMLC_RANK",
            "DMLC_NUM_WORKER", "DMLC_NUM_SERVER", "MXNET_KVSTORE_COLLECTIVE",
            "MXNET_KVSTORE_BIGARRAY_BOUND")}
    os.environ.update(DMLC_PS_ROOT_URI="127.0.0.1",
                      DMLC_PS_ROOT_PORT=str(root.port), DMLC_RANK="0",
                      DMLC_NUM_WORKER="1", DMLC_NUM_SERVER="2",
                      MXNET_KVSTORE_COLLECTIVE="0",
                      MXNET_KVSTORE_BIGARRAY_BOUND="16")
    try:
        kv = KVStoreDist("dist_sync")
        kv.init("w", nd.ones((30,)))
        kv.push("w", nd.ones((30,)))
        out = nd.zeros((30,))
        kv.pull("w", out=out)
        np.testing.assert_allclose(out.asnumpy(), 1.0)

        proc.kill()
        proc.wait(timeout=30)
        with pytest.raises(ServerLostError, match="parameter server 1 .* "
                                                  "is lost") as err:
            kv.push("w", nd.ones((30,)))
            kv.pull("w", out=out)
        assert err.value.server == 1
        assert "w" in err.value.keys
        kv.close()
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        if proc.poll() is None:
            proc.kill()
        root.shutdown()


def test_server_profiler_commands(tmp_path):
    """profiler.set_config/set_state/dump(profile_process='server') drive
    the parameter server's profiler over the control channel (reference
    set_kvstore_handle + MXKVStoreSendCommmandToServers)."""
    from incubator_mxnet_tpu.dist.server import ParameterServer
    from incubator_mxnet_tpu.dist.transport import Channel

    server = ParameterServer(num_workers=1).start()
    chan = Channel("127.0.0.1", server.port)
    try:
        out = str(tmp_path / "server_prof.json")
        r = chan.request({"cmd": "profiler", "action": "set_config",
                          "config": {"filename": out,
                                     "aggregate_stats": True}})
        assert r.get("ok"), r
        r = chan.request({"cmd": "profiler", "action": "dump"})
        assert r.get("ok"), r
        assert os.path.exists(out)
        r = chan.request({"cmd": "profiler", "action": "bogus"})
        assert "error" in r
    finally:
        chan.request({"cmd": "stop"})
        chan.close()
        server.shutdown()
        # the in-process test server shares this process's profiler
        # module: restore the global config for later tests
        from incubator_mxnet_tpu import profiler as _p
        _p.set_config(filename="profile.json", aggregate_stats=False)
        _p.set_kvstore_handle(None)
