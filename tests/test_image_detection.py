"""Detection augmenter tests (reference
tests/python/unittest/test_image.py det section)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd
from incubator_mxnet_tpu.image import (DetHorizontalFlipAug,
                                       DetRandomCropAug, DetRandomPadAug,
                                       CreateDetAugmenter, ImageDetIter)
from incubator_mxnet_tpu.ndarray.ndarray import array


def _sample():
    rng = np.random.RandomState(0)
    img = array(rng.randint(0, 255, (60, 80, 3), np.uint8), dtype="uint8")
    label = np.full((4, 5), -1.0, np.float32)
    label[0] = [1, 0.25, 0.25, 0.75, 0.75]
    label[1] = [0, 0.10, 0.10, 0.30, 0.40]
    return img, label


def test_flip_moves_boxes():
    img, label = _sample()
    aug = DetHorizontalFlipAug(p=1.0)
    out, lab = aug(img, label)
    np.testing.assert_array_equal(out.asnumpy(), img.asnumpy()[:, ::-1])
    np.testing.assert_allclose(lab[0, [1, 3]], [0.25, 0.75], atol=1e-6)
    np.testing.assert_allclose(lab[1, [1, 3]], [0.70, 0.90], atol=1e-6)
    assert (lab[2:, 0] == -1).all()


def test_random_crop_clips_boxes():
    img, label = _sample()
    aug = DetRandomCropAug(min_object_covered=0.5, area_range=(0.3, 0.8))
    found_smaller = False
    for _ in range(10):
        out, lab = aug(img, label)
        valid = lab[lab[:, 0] >= 0]
        assert len(valid) >= 1             # coverage constraint held
        assert (valid[:, 1:5] >= -1e-6).all()
        assert (valid[:, 1:5] <= 1 + 1e-6).all()
        if out.shape != img.shape:
            found_smaller = True
    assert found_smaller


def test_random_pad_shrinks_boxes():
    img, label = _sample()
    aug = DetRandomPadAug(area_range=(2.0, 2.5))
    out, lab = aug(img, label)
    assert out.shape[0] >= img.shape[0] and out.shape[1] >= img.shape[1]
    v = lab[lab[:, 0] >= 0]
    orig = label[label[:, 0] >= 0]
    assert ((v[:, 3] - v[:, 1]) <= (orig[:, 3] - orig[:, 1]) + 1e-6).all()


def test_image_det_iter(tmp_path):
    import cv2
    from incubator_mxnet_tpu import recordio
    rng = np.random.RandomState(1)
    rec = recordio.MXRecordIO(str(tmp_path / "det.rec"), "w")
    for i in range(12):
        img = rng.randint(0, 255, (48, 48, 3), np.uint8)
        ok, enc = cv2.imencode(".png", img)
        label = np.array([i % 3, 0.2, 0.2, 0.8, 0.8], np.float32)
        rec.write(recordio.pack(
            recordio.IRHeader(0, label, i, 0), enc.tobytes()))
    rec.close()
    it = ImageDetIter(batch_size=4, data_shape=(3, 32, 32),
                      path_imgrec=str(tmp_path / "det.rec"),
                      rand_mirror=True, max_objects=3)
    n = 0
    for batch in it:
        assert batch.data[0].shape == (4, 3, 32, 32)
        assert batch.label[0].shape == (4, 3, 5)
        lab = batch.label[0].asnumpy()
        valid = lab[..., 0] >= 0
        assert valid.any()
        n += 4 - batch.pad
    assert n == 12


def test_create_det_augmenter_pipeline():
    img, label = _sample()
    augs = CreateDetAugmenter((3, 32, 32), rand_crop=0.5, rand_mirror=True,
                              rand_pad=0.5, mean=True, std=True)
    out, lab = img, label
    for aug in augs:
        out, lab = aug(out, lab)
    assert out.shape == (32, 32, 3)
    assert out.dtype == np.float32


def test_parse_label_header_format():
    it = ImageDetIter.__new__(ImageDetIter)
    it.max_objects = 3
    # reference header convention: [A=4, B=6, extra, extra, objects...]
    raw = np.array([4, 6, 9.9, 9.9,
                    1, 0.1, 0.2, 0.3, 0.4, 0.0,
                    2, 0.5, 0.5, 0.9, 0.9, 0.0], np.float32)
    out = it._parse_label(raw)
    np.testing.assert_allclose(out[0], [1, 0.1, 0.2, 0.3, 0.4])
    np.testing.assert_allclose(out[1], [2, 0.5, 0.5, 0.9, 0.9])
    assert out[2, 0] == -1
    # flat rows still accepted
    flat = np.array([0, 0.1, 0.1, 0.2, 0.2], np.float32)
    out2 = it._parse_label(flat)
    np.testing.assert_allclose(out2[0], flat)
