"""Pod-scale SPMD fast path: bucketed gradient exchange, composed
meshes, distributed BatchNorm (ISSUE 11).

The contracts certified here are the ones BENCH_SCALING.json benches:

* bucket boundaries are a pure scheduling choice — bucketed,
  single-bucket, streaming, and per-key exchanges produce bit-identical
  numbers, deterministically across runs;
* the overlapped path composes with the guardian — a non-finite bucket
  neither poisons its neighbor buckets (kvstore) nor the training state
  (in-graph skip under the pod fast path);
* `SyncBatchNorm` / `sym.BatchNorm(sync=True)` at dp=4 computes the
  single-device big-batch statistics;
* composed dp×tp meshes drive `Module` through `mesh=` / `MXNET_MESH`.
"""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import analysis, io, nd, sym
from incubator_mxnet_tpu.resilience import faults


def _multi_key_vals(devs, shapes, seed=0):
    rng = np.random.RandomState(seed)
    vals = [rng.randn(len(devs), *s).astype("f4") for s in shapes]
    return [[nd.array(v[d], ctx=dev) for d, dev in enumerate(devs)]
            for v in vals]


def _pull_all(kv, keys, shapes):
    outs = []
    for k, s in zip(keys, shapes):
        o = nd.zeros(s)
        kv.pull(k, out=o)
        outs.append(o.asnumpy())
    return outs


# ---------------------------------------------------------------------------
# bucket-boundary invariance + determinism (kvstore plane)
# ---------------------------------------------------------------------------

SHAPES = [(64,), (8, 8), (128,), (3, 5), (256,), (64,), (2, 2)]
KEYS = ["k%d" % i for i in range(len(SHAPES))]


def _push_with_cap(cap_mb, monkeypatch, ndev=4, seed=0):
    monkeypatch.setenv("MXNET_KVSTORE_BUCKET_MB", str(cap_mb))
    devs = [mx.cpu(i) for i in range(ndev)]
    kv = mx.kv.create("device")
    for k, s in zip(KEYS, SHAPES):
        kv.init(k, nd.zeros(s))
    kv.push(KEYS, _multi_key_vals(devs, SHAPES, seed))
    return kv, _pull_all(kv, KEYS, SHAPES)


def test_bucketed_vs_single_bucket_bit_parity(monkeypatch):
    """Bucket boundaries must not change the numbers: a tiny cap (one
    key per bucket), the old single-flatten-concat dataflow (huge cap),
    and the per-key path all produce BIT-identical reduced values."""
    kv_many, outs_many = _push_with_cap(0.0001, monkeypatch)  # ~100 B cap
    kv_one, outs_one = _push_with_cap(4096, monkeypatch)      # one bucket
    st_many, st_one = kv_many.stats(), kv_one.stats()
    assert st_many["buckets"] > 1, st_many
    assert st_one["buckets"] == 1, st_one
    # per-key reference (the base reduce, no bucketing at all)
    devs = [mx.cpu(i) for i in range(4)]
    kv_ref = mx.kv.create("device")
    vals = _multi_key_vals(devs, SHAPES, 0)
    for k, s, v in zip(KEYS, SHAPES, vals):
        kv_ref.init(k, nd.zeros(s))
        kv_ref.push(k, v)
    outs_ref = _pull_all(kv_ref, KEYS, SHAPES)
    for a, b, r, k in zip(outs_many, outs_one, outs_ref, KEYS):
        assert np.array_equal(a, b), k
        assert np.array_equal(a, r), k


def test_bucket_boundaries_deterministic_across_runs(monkeypatch):
    """Two identical runs cut identical bucket boundaries (the plan is a
    pure function of order/shapes/dtypes/cap) and produce bit-identical
    results — the reproducibility half of the scheduling claim."""
    kv1, outs1 = _push_with_cap(0.0005, monkeypatch)
    kv2, outs2 = _push_with_cap(0.0005, monkeypatch)
    s1, s2 = kv1.stats(), kv2.stats()
    assert s1["buckets"] == s2["buckets"]
    assert s1["bucket_fill_hist"] == s2["bucket_fill_hist"]
    assert s1["allreduce_dispatches"] == s2["allreduce_dispatches"]
    for a, b in zip(outs1, outs2):
        assert np.array_equal(a, b)
    # the plan itself is deterministic (unit face of the same claim)
    values = [[type("V", (), {"shape": s, "dtype": np.dtype("f4")})()]
              for s in SHAPES]
    order = list(reversed(range(len(SHAPES))))
    plans = {tuple(map(tuple, kv1._plan_buckets(order, values)))
             for _ in range(3)}
    assert len(plans) == 1


def test_streaming_push_matches_batched(monkeypatch):
    """`begin_push`/`push_part`/`end_push` (gradients arriving one at a
    time, as backward materializes them) produces the same numbers as
    one batched push, while dispatching multiple capped buckets."""
    monkeypatch.setenv("MXNET_KVSTORE_BUCKET_MB", "0.0005")
    devs = [mx.cpu(i) for i in range(4)]
    vals = _multi_key_vals(devs, SHAPES, 3)
    kv_s = mx.kv.create("device")
    for k, s in zip(KEYS, SHAPES):
        kv_s.init(k, nd.zeros(s))
    kv_s.begin_push()
    for k, v in zip(KEYS, vals):
        kv_s.push_part(k, v)
    kv_s.end_push()
    assert kv_s.stats()["buckets"] > 1
    kv_b = mx.kv.create("device")
    for k, s in zip(KEYS, SHAPES):
        kv_b.init(k, nd.zeros(s))
    kv_b.push(KEYS, vals)
    for a, b in zip(_pull_all(kv_s, KEYS, SHAPES),
                    _pull_all(kv_b, KEYS, SHAPES)):
        assert np.array_equal(a, b)
    # streaming misuse is a structured error, not silent corruption
    with pytest.raises(mx.MXNetError):
        kv_s.push_part("k0", vals[0])
    with pytest.raises(mx.MXNetError):
        kv_s.end_push()


def test_nonfinite_bucket_does_not_poison_neighbors(monkeypatch):
    """Guardian-skip composition, kvstore face: a NaN gradient reduces
    inside ITS bucket only — every other bucket's values stay exact.
    (The training-state face is test_pod_guardian_skip_deterministic.)"""
    monkeypatch.setenv("MXNET_KVSTORE_BUCKET_MB", "0.0001")
    devs = [mx.cpu(i) for i in range(4)]
    vals = _multi_key_vals(devs, SHAPES, 5)
    expect = [sum(v.asnumpy() for v in vs) for vs in vals]
    vals[2][1][:] = nd.array(np.full(SHAPES[2], np.nan, "f4"),
                             ctx=devs[1])
    kv = mx.kv.create("device")
    for k, s in zip(KEYS, SHAPES):
        kv.init(k, nd.zeros(s))
    kv.push(KEYS, vals)
    assert kv.stats()["buckets"] > 1
    outs = _pull_all(kv, KEYS, SHAPES)
    assert np.isnan(outs[2]).all(), "the poisoned bucket reduces to NaN"
    for i, (o, e) in enumerate(zip(outs, expect)):
        if i == 2:
            continue
        assert np.isfinite(o).all(), KEYS[i]
        np.testing.assert_allclose(o, e, rtol=1e-6, err_msg=KEYS[i])


def test_kvstore_stats_and_runtime_report(monkeypatch):
    """`KVStore.stats()` exposes the communication economy (dispatches,
    bytes, bucket fill, overlap) and `analysis.runtime_report()` carries
    it as a kvstore.buckets finding — the BENCH_SCALING read path."""
    kv, _ = _push_with_cap(0.0005, monkeypatch)
    st = kv.stats()
    for field in ("allreduce_dispatches", "bytes_reduced", "buckets",
                  "bucket_cap_mb", "bucket_fill_hist", "avg_bucket_fill",
                  "overlap_ratio", "batched_pushes", "pull_broadcasts"):
        assert field in st, field
    assert st["bytes_reduced"] == sum(
        int(np.prod(s)) * 4 for s in SHAPES)
    assert st["allreduce_dispatches"] == st["buckets"] > 1
    findings = [f for f in analysis.runtime_report()
                if f.pass_name == "kvstore.buckets"]
    assert findings and any("batched pushes" in f.message
                            for f in findings)


def test_gradient_compression_composes_or_raises():
    """2-bit compression composes with bucketing (in-bucket quantize +
    error feedback, elementwise-identical to the per-key reference);
    any other type is a STRUCTURED unsupported error — never the base
    class stub silently half-applying."""
    kv = mx.kv.create("tpu")
    with pytest.raises(mx.MXNetError, match="unsupported"):
        kv.set_gradient_compression({"type": "1bit"})
    devs = [mx.cpu(i) for i in range(4)]
    shapes = [(6,), (4,), (8,)]
    keys = ["c%d" % i for i in range(3)]
    rng = np.random.RandomState(9)
    raw = [rng.uniform(-1, 1, (len(devs),) + s).astype("f4")
           for s in shapes]
    vals = [[nd.array(r[d], ctx=dev) for d, dev in enumerate(devs)]
            for r in raw]
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    for k, s in zip(keys, shapes):
        kv.init(k, nd.zeros(s))
    # two pushes: the second proves the residual (error feedback) lives
    # per bucket position exactly as the reference's per-key residual
    resid = [np.zeros(s, "f4") for s in shapes]
    for _ in range(2):
        kv.push(keys, vals)
        outs = _pull_all(kv, keys, shapes)
        for i, (r, s) in enumerate(zip(raw, shapes)):
            g = r.sum(axis=0) + resid[i]
            q = np.where(g >= 0.5, 0.5,
                         np.where(g <= -0.5, -0.5, 0.0)).astype("f4")
            resid[i] = g - q
            np.testing.assert_allclose(outs[i], q, rtol=1e-6,
                                       err_msg=keys[i])


def test_gradient_compression_residual_survives_path_switch():
    """The error-feedback residual lives PER KEY, shared by the bucketed
    and per-key fallback reduce paths: alternating between a batched
    (bucketed) push and single-key (fallback) pushes accumulates the
    exact residual the pure per-key reference does — no quantization
    error is dropped or double-counted at a path switch.  None clears
    the compression state cleanly."""
    devs = [mx.cpu(i) for i in range(4)]
    shapes = [(6,), (4,)]
    keys = ["r0", "r1"]
    rng = np.random.RandomState(11)
    raw = [rng.uniform(-1, 1, (len(devs),) + s).astype("f4")
           for s in shapes]

    def vals():
        return [[nd.array(r[d], ctx=dev) for d, dev in enumerate(devs)]
                for r in raw]

    kv = mx.kv.create("device")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    for k, s in zip(keys, shapes):
        kv.init(k, nd.zeros(s))
    rounds = []
    kv.push(keys, vals())                # bucketed
    rounds.append(_pull_all(kv, keys, shapes))
    for k, v in zip(keys, vals()):       # per-key fallback
        kv.push(k, v)
    rounds.append(_pull_all(kv, keys, shapes))
    kv.push(keys, vals())                # bucketed again
    rounds.append(_pull_all(kv, keys, shapes))
    resid = [np.zeros(s, "f4") for s in shapes]
    for outs in rounds:
        for i, r in enumerate(raw):
            g = r.sum(axis=0) + resid[i]
            q = np.where(g >= 0.5, 0.5,
                         np.where(g <= -0.5, -0.5, 0.0)).astype("f4")
            resid[i] = g - q
            np.testing.assert_allclose(outs[i], q, rtol=1e-6,
                                       err_msg=keys[i])
    kv.set_gradient_compression(None)
    assert kv._compression is None and kv._residuals == {}


# ---------------------------------------------------------------------------
# pod SPMD fast path (fused train step plane)
# ---------------------------------------------------------------------------

def _scaling_model(sync_bn=None, seed=0, hidden=16):
    np.random.seed(seed)
    mx.random.seed(seed)
    net = sym.Variable("data")
    net = sym.FullyConnected(net, num_hidden=hidden, name="fc1")
    if sync_bn is not None:
        net = sym.BatchNorm(net, name="bn1", sync=sync_bn,
                            fix_gamma=False)
    net = sym.Activation(net, act_type="tanh")
    net = sym.FullyConnected(net, num_hidden=4, name="fc2")
    return sym.SoftmaxOutput(net, name="softmax")


def _scaling_data(n=128, bs=16):
    rng = np.random.RandomState(3)
    x = rng.standard_normal((n, 10)).astype("float32")
    # row-dependent scale: each dp shard of a batch sees a DIFFERENT
    # local variance, so shard-local BN statistics are measurably wrong
    x *= (1.0 + (np.arange(n) % bs)[:, None] / 4.0).astype("float32")
    y = rng.randint(0, 4, n).astype("float32")
    return io.NDArrayIter(x, y, batch_size=bs, shuffle=False)


def _fit(net, ctxs, num_epoch=2):
    mod = mx.mod.Module(net, context=ctxs)
    mod.fit(_scaling_data(), kvstore="device", optimizer="sgd",
            optimizer_params={"learning_rate": 0.05}, eval_metric="acc",
            initializer=mx.initializer.Xavier(), num_epoch=num_epoch)
    return mod


def _params(mod):
    args, auxs = mod.get_params()
    return ({k: v.asnumpy() for k, v in args.items()},
            {k: v.asnumpy() for k, v in auxs.items()})


def test_pod_fast_path_matches_gspmd_lowering(monkeypatch):
    """The shard_map+bucketed-psum program computes what the GSPMD
    global-view program computes (the psum of per-shard gradients IS the
    cross-device sum)."""
    monkeypatch.setenv("MXNET_POD_SPMD", "1")
    a = _fit(_scaling_model(), [mx.cpu(i) for i in range(4)])
    assert a._fused_step.pod_stats is not None, "pod path must engage"
    assert a._fused_step.pod_stats["collectives_per_step"] <= \
        a._fused_step.pod_stats["params"]
    monkeypatch.setenv("MXNET_POD_SPMD", "0")
    b = _fit(_scaling_model(), [mx.cpu(i) for i in range(4)])
    assert b._fused_step.pod_stats is None
    pa, aa = _params(a)
    pb, ab = _params(b)
    for k in pa:
        np.testing.assert_allclose(pa[k], pb[k], rtol=2e-5, atol=2e-6,
                                   err_msg=k)
    for k in aa:
        np.testing.assert_allclose(aa[k], ab[k], rtol=2e-5, atol=2e-6,
                                   err_msg=k)


def test_pod_bucket_cap_bit_parity(monkeypatch):
    """In-graph bucket boundaries (MXNET_KVSTORE_BUCKET_MB caps the pod
    exchange's buckets too) are bit-invariant on the final params."""
    monkeypatch.setenv("MXNET_POD_SPMD", "1")
    monkeypatch.setenv("MXNET_KVSTORE_BUCKET_MB", "0.0001")
    a = _fit(_scaling_model(), [mx.cpu(i) for i in range(4)])
    assert a._fused_step.pod_stats["buckets"] > 1
    monkeypatch.setenv("MXNET_KVSTORE_BUCKET_MB", "4096")
    b = _fit(_scaling_model(), [mx.cpu(i) for i in range(4)])
    assert b._fused_step.pod_stats["buckets"] == 1
    pa, aa = _params(a)
    pb, ab = _params(b)
    for k in pa:
        assert np.array_equal(pa[k], pb[k]), k
    for k in aa:
        assert np.array_equal(aa[k], ab[k]), k


def test_pod_guardian_skip_deterministic(monkeypatch):
    """Overlap path under guardian skip-batch: an injected non-finite
    gradient inside the bundled pod exchange skips THAT step on every
    shard — deterministically (two runs bit-identical), leaving every
    parameter finite."""
    monkeypatch.setenv("MXNET_POD_SPMD", "1")
    monkeypatch.setenv("MXNET_GUARDIAN_INTERVAL", "4")
    monkeypatch.setenv("MXNET_GUARDIAN_SPIKE_WINDOW", "4")

    def run():
        faults.configure("seed=7;grad.nonfinite:error(at=3)")
        mod = _fit(_scaling_model(), [mx.cpu(i) for i in range(2)])
        st = mod._guardian.stats()
        faults.clear()
        return _params(mod), st, mod

    (pa, aa), st1, mod = run()
    (pb, ab), st2, _ = run()
    assert mod._fused_step.pod_stats is not None, "pod path must engage"
    assert st1["skips"] == 1 and st1["injected_nonfinite"] == 1
    assert st1["skips"] == st2["skips"]
    for k in pa:
        assert np.array_equal(pa[k], pb[k]), k
        assert np.isfinite(pa[k]).all(), k
    for k in aa:
        assert np.array_equal(aa[k], ab[k]), k


# ---------------------------------------------------------------------------
# distributed BatchNorm
# ---------------------------------------------------------------------------

def test_sync_batchnorm_dp4_matches_big_batch():
    """`sym.BatchNorm(sync=True)` at dp=4 == the single-device big-batch
    reference: same params AND same moving statistics, because the
    moments are exchanged over the dp axis (the fused global-view path
    and the single device both see the global batch; the pod shard_map
    path psums the moments)."""
    a = _fit(_scaling_model(sync_bn=True), [mx.cpu(i) for i in range(4)])
    b = _fit(_scaling_model(sync_bn=True), mx.cpu(0))
    pa, aa = _params(a)
    pb, ab = _params(b)
    for k in pa:
        np.testing.assert_allclose(pa[k], pb[k], rtol=1e-4, atol=1e-5,
                                   err_msg=k)
    for k in aa:
        np.testing.assert_allclose(aa[k], ab[k], rtol=1e-4, atol=1e-5,
                                   err_msg=k)


def test_pod_plain_batchnorm_falls_back_to_global_view():
    """Plain (sync=False) train-mode BatchNorm must NOT ride the pod
    shard_map path: inside shard_map its mean would reduce over the
    SHARD batch, silently changing the fused path's documented
    global-batch BN semantics.  The graph falls back to the GSPMD
    global-view lowering, where dp=4 still computes the single-device
    big-batch statistics."""
    a = _fit(_scaling_model(sync_bn=False), [mx.cpu(i) for i in range(4)])
    assert a._fused_step.pod_stats is None, \
        "unsynced BN must disable the pod fast path"
    b = _fit(_scaling_model(sync_bn=False), mx.cpu(0))
    pa, aa = _params(a)
    pb, ab = _params(b)
    for k in pa:
        np.testing.assert_allclose(pa[k], pb[k], rtol=1e-4, atol=1e-5,
                                   err_msg=k)
    for k in aa:
        np.testing.assert_allclose(aa[k], ab[k], rtol=1e-4, atol=1e-5,
                                   err_msg=k)


def test_sync_batchnorm_non_dp_axis_name_falls_back(monkeypatch):
    """A mesh whose data-parallel axis is NOT named 'dp' must not let
    sync BN go silently shard-local under the pod fast path: the op
    psums over its `sync_axis` NAME, so an axis-name mismatch falls
    back to the global-view lowering — which computes the single-device
    big-batch statistics regardless of axis names."""
    monkeypatch.setenv("MXNET_MESH", "data=4")
    a = _fit(_scaling_model(sync_bn=True), [mx.cpu(i) for i in range(4)])
    assert a._fused_step._dp_axis == "data"
    assert a._fused_step.pod_stats is None, \
        "sync_axis != mesh dp axis must disable the pod fast path"
    monkeypatch.delenv("MXNET_MESH")
    b = _fit(_scaling_model(sync_bn=True), mx.cpu(0))
    pa, aa = _params(a)
    pb, ab = _params(b)
    for k in pa:
        np.testing.assert_allclose(pa[k], pb[k], rtol=1e-4, atol=1e-5,
                                   err_msg=k)
    for k in aa:
        np.testing.assert_allclose(aa[k], ab[k], rtol=1e-4, atol=1e-5,
                                   err_msg=k)


def test_gluon_sync_batchnorm_sets_sync_attr():
    bn = mx.gluon.nn.SyncBatchNorm(in_channels=8)
    assert bn._kwargs["sync"] is True
    assert bn._kwargs["sync_axis"] == "dp"
    # historical contrib path stays importable and identical
    cbn = mx.gluon.contrib.nn.SyncBatchNorm(in_channels=8)
    assert cbn._kwargs["sync"] is True


# ---------------------------------------------------------------------------
# composed meshes under Module
# ---------------------------------------------------------------------------

def test_mesh_spec_parsing():
    from incubator_mxnet_tpu.parallel.mesh import (dp_axis_of,
                                                   mesh_from_spec,
                                                   parse_spec)
    assert parse_spec("dp=4,tp=2") == {"dp": 4, "tp": 2}
    assert parse_spec(" dp=8 ") == {"dp": 8}
    with pytest.raises(mx.MXNetError):
        parse_spec("dp:4")
    with pytest.raises(mx.MXNetError):
        parse_spec("dp=four")
    assert mesh_from_spec("") is None
    import jax
    mesh = mesh_from_spec("dp=4,tp=2", devices=jax.devices()[:8])
    assert tuple(mesh.axis_names) == ("dp", "tp")
    assert mesh.shape["dp"] == 4 and mesh.shape["tp"] == 2
    assert dp_axis_of(mesh) == "dp"
    tp_first = mesh_from_spec({"tp": 2, "x": 4},
                              devices=jax.devices()[:8])
    assert dp_axis_of(tp_first) == "tp"   # no 'dp' -> first axis


def test_module_fit_composed_mesh(monkeypatch):
    """A composed dp×tp mesh drives the fused step from the public
    `Module` API: the batch shards over the 4-wide dp axis (not the raw
    8-device count), and training completes with finite params."""
    net = _scaling_model()
    ctxs = [mx.cpu(i) for i in range(8)]
    mod = mx.mod.Module(net, context=ctxs)
    it = _scaling_data()
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(kvstore="device", optimizer="sgd",
                       optimizer_params={"learning_rate": 0.05},
                       mesh="dp=4,tp=2")
    metric = mx.metric.create("acc")
    for batch in it:
        mod.fit_step(batch, metric)
    fs = mod._fused_step
    assert fs is not None and not fs.broken
    assert fs._dp_size == 4
    assert tuple(fs._mesh.axis_names) == ("dp", "tp")
    assert fs._pod_axis is None   # composed mesh -> global-view lowering
    for k, v in _params(mod)[0].items():
        assert np.isfinite(v).all(), k
    # MXNET_MESH env drives the same lever without code changes
    monkeypatch.setenv("MXNET_MESH", "dp=2")
    mod2 = _fit(_scaling_model(), [mx.cpu(i) for i in range(2)])
    assert mod2._fused_step._dp_size == 2


def test_trainer_zero_flags():
    """`Trainer(zero=...)` boolean contract: False is a no-op (not a
    crash), True without a mesh is a structured error, and True on a
    composed mesh shards over the DATA-parallel axis by name — never
    whatever axis happens to be listed first."""
    import jax
    from incubator_mxnet_tpu.parallel.mesh import mesh_from_spec

    def make(**kw):
        net = mx.gluon.nn.Dense(4)
        net.initialize()
        net(nd.zeros((2, 8)))
        return mx.gluon.Trainer(net.collect_params(), "sgd", **kw)

    assert make(zero=False)._zero is None
    with pytest.raises(mx.MXNetError, match="mesh"):
        make(zero=True)
    mesh = mesh_from_spec("tp=2,dp=4", devices=jax.devices()[:8])
    assert make(zero=True, mesh=mesh)._zero == (mesh, "dp")
    assert make(zero=mesh)._zero == (mesh, "dp")


# ---------------------------------------------------------------------------
# unbucketed-push lint
# ---------------------------------------------------------------------------

def test_unbucketed_push_lint_fixtures():
    """Per-parameter kv.push/pull inside a training loop is the classic
    pod-scale throughput killer: one collective per key instead of
    O(buckets).  The lint names it; batched calls and non-loop pushes
    stay quiet; the disable comment suppresses."""
    bad = (
        "kv = mx.kv.create('tpu')\n"                     # 1
        "for i, p in enumerate(params):\n"               # 2
        "    kv.push(i, p.list_grad())\n"                # 3
        "    kv.pull(i, p.list_grad())\n"                # 4
        "for j in range(3):\n"                           # 5
        "    kv.push(j, grads[j])  # mxlint: disable\n"  # 6
    )
    report = analysis.check_source(bad, "train.py")
    locs = sorted(f.location for f in report
                  if f.code == "unbucketed-push")
    assert locs == ["train.py:3", "train.py:4"], report.format()
    good = (
        "kv = mx.kv.create('tpu')\n"
        "keys = list(range(len(params)))\n"
        "for epoch in range(10):\n"
        "    kv.push(keys, grads)\n"         # whole key list: batched
        "    kv.pull(keys, grads)\n"
        "kv.push(0, g0)\n"                   # outside any loop
    )
    assert not [f for f in analysis.check_source(good, "ok.py")
                if f.code == "unbucketed-push"]
