"""Test configuration: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's `tests/python/unittest/common.py` fixtures: seeded
tests + a `default_context()` switch; multi-device collective tests use the
8 virtual host devices (the TPU-mesh stand-in, per the build contract).
"""
import os

# must be set before jax initializes
os.environ["JAX_PLATFORMS"] = "cpu"  # tests always run on the virtual CPU mesh
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_ENABLE_X64"] = "1"  # fp64 for numeric-gradient reference checks

# the environment pre-imports jax at interpreter startup, which freezes config
# defaults before this file runs — override via the config API as well
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
assert len(jax.devices()) == 8, "virtual 8-device CPU mesh not active"

import numpy as np
import pytest


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long multi-subprocess tests excluded from the "
        "tier-1 run (-m 'not slow'); tools/run_chaos.py --serving covers "
        "the same contracts as a gated artifact")


@pytest.fixture(autouse=True)
def _seeded():
    """Seed numpy + framework RNG per test (reference `with_seed()` decorator)."""
    np.random.seed(0)
    import incubator_mxnet_tpu as mx
    mx.random.seed(0)
    yield
