"""Unified telemetry plane (the ISSUE-14 acceptance gates).

Covers: MetricsRegistry counter/gauge/histogram semantics (including
thread-safety of the hot path and weak producer registration), the
Prometheus text round-trip under the strict parser, the shared JSONL
sink (line atomicity, stamping, pre-stamped fields, the rendered span
fast path), the bounded profiler event buffer with its dropped-events
metric, in-process span trees, the scrape plane over real transport
frames, fleet-wide scrape aggregation, mxtop --json, mxtrace merge
semantics (orphan detection, flow arrows, cross-process trees), the
`untracked-stats` lint sweep over the package, and — the headline — a
REAL two-process router + subprocess-worker request whose merged span
tree is connected across both pids with zero orphans.
"""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import io, obs, sym
from incubator_mxnet_tpu.obs import jsonl_sink, metrics as obs_metrics
from incubator_mxnet_tpu.obs import trace as obs_trace

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


@pytest.fixture(autouse=True)
def _trace_clean():
    """Every test starts with tracing off and an empty span buffer."""
    obs_trace.enabled()
    obs_trace.reset()
    yield
    obs_trace.disable()
    obs_trace._path = None
    obs_trace.reset()


# ---------------------------------------------------------------------------
# MetricsRegistry semantics
# ---------------------------------------------------------------------------

def test_counter_gauge_histogram_semantics():
    reg = obs_metrics.MetricsRegistry()
    c = reg.counter("x.hits")
    c.inc()
    c.inc(4)
    assert c.value == 5
    g = reg.gauge("x.depth")
    g.set(3)
    g.inc()
    g.dec(2)
    assert g.value == 2
    h = reg.histogram("x.lat_ms", buckets=(1, 10, 100))
    for v in (0.5, 5, 50, 500):
        h.observe(v)
    snap = h.snapshot()
    assert snap["count"] == 4
    assert snap["sum"] == pytest.approx(555.5)
    assert snap["buckets"][1.0] == 1
    assert snap["buckets"][10.0] == 2
    assert snap["buckets"][100.0] == 3
    assert snap["buckets"][float("inf")] == 4
    # boundary lands in its own le bucket (cumulative semantics)
    h.observe(10)
    assert h.snapshot()["buckets"][10.0] == 3
    q = h.quantile(0.5)
    assert q is not None and 1 <= q <= 100
    # same name returns the SAME instrument; kind mismatch is an error
    assert reg.counter("x.hits") is c
    with pytest.raises(TypeError):
        reg.gauge("x.hits")


def test_counter_hot_path_is_thread_safe():
    reg = obs_metrics.MetricsRegistry()
    c = reg.counter("t.hits")

    def worker():
        for _ in range(2000):
            c.inc()
    threads = [threading.Thread(target=worker, name=f"mx-test-inc-{i}")
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 16000


def test_producer_registration_flatten_and_weakref():
    reg = obs_metrics.MetricsRegistry()
    reg.register_producer("demo", lambda: {
        "a": 1, "flag": True, "skipped": "str",
        "nested": {"b": 2.5, "deep": {"c": 3}}, "list": [1, 2]})
    vals = reg.collect()
    assert vals["demo.a"] == 1
    assert vals["demo.flag"] == 1
    assert vals["demo.nested.b"] == 2.5
    assert vals["demo.nested.deep.c"] == 3
    assert "demo.skipped" not in vals and "demo.list" not in vals

    class Sub:
        def stats(self):
            return {"n": 7}
    sub = Sub()
    reg.register_producer("sub", sub.stats)
    assert reg.collect()["sub.n"] == 7
    del sub
    import gc
    gc.collect()
    # dead bound method drops out of scrapes instead of erroring
    vals = reg.collect()
    assert "sub.n" not in vals
    assert "sub" not in reg.producers()


def test_broken_producer_never_takes_the_scrape_down():
    reg = obs_metrics.MetricsRegistry()
    def boom():
        raise RuntimeError("broken stats")
    reg.register_producer("bad", boom)
    reg.register_producer("good", lambda: {"v": 1})
    vals = reg.collect()
    assert vals["good.v"] == 1
    assert vals["obs.producer_errors.bad"] == 1


def test_prometheus_render_parse_round_trip():
    reg = obs_metrics.MetricsRegistry()
    reg.counter("rt.hits").inc(3)
    reg.gauge("rt.depth").set(1.5)
    h = reg.histogram("rt.lat", buckets=(1, 10))
    h.observe(0.5)
    h.observe(20)
    reg.register_producer("ns", lambda: {"x": 2, "weird/name": 1})
    text = reg.render_prometheus()
    parsed = obs_metrics.parse_prometheus(text)
    assert parsed[("mx_rt_hits", ())] == 3
    assert parsed[("mx_rt_depth", ())] == 1.5
    assert parsed[("mx_ns_x", ())] == 2
    assert parsed[("mx_ns_weird_name", ())] == 1
    assert parsed[("mx_rt_lat_bucket", (("le", "1"),))] == 1
    assert parsed[("mx_rt_lat_bucket", (("le", "+Inf"),))] == 2
    assert parsed[("mx_rt_lat_count", ())] == 2
    # the strict parser REJECTS malformed text (the CI validity gate)
    with pytest.raises(ValueError):
        obs_metrics.parse_prometheus("not a metric line!!!")
    with pytest.raises(ValueError):
        obs_metrics.parse_prometheus("mx_ok {\n")


# ---------------------------------------------------------------------------
# Shared JSONL sink
# ---------------------------------------------------------------------------

def test_jsonl_sink_stamps_and_preserves_prestamped(tmp_path):
    path = str(tmp_path / "events.jsonl")
    s = jsonl_sink.JsonlSink(path)
    s.write({"event": "a"})
    s.write({"event": "b", "pid": 42, "thread": "custom"})
    s.close()
    entries = jsonl_sink.read_jsonl(path)
    assert len(entries) == 2
    assert entries[0]["pid"] == os.getpid()
    assert entries[0]["thread"]
    assert "time" in entries[0] and "rank" in entries[0]
    # pre-stamped fields win (a forwarded event keeps its provenance)
    assert entries[1]["pid"] == 42
    assert entries[1]["thread"] == "custom"


def test_jsonl_sink_concurrent_writers_line_atomic(tmp_path):
    path = str(tmp_path / "shared.jsonl")

    def writer(wid):
        s = jsonl_sink.JsonlSink(path)   # own fd per writer, one file
        for i in range(200):
            s.write({"w": wid, "i": i, "pad": "x" * 64})
        s.close()
    threads = [threading.Thread(target=writer, args=(w,),
                                name=f"mx-test-sink-{w}")
               for w in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    entries = jsonl_sink.read_jsonl(path)
    assert len(entries) == 1200          # no torn/interleaved lines
    assert {(e["w"], e["i"]) for e in entries} == {
        (w, i) for w in range(6) for i in range(200)}


def test_faults_log_rides_the_shared_sink(tmp_path):
    from incubator_mxnet_tpu.resilience import faults
    log = str(tmp_path / "faults.jsonl")
    faults.clear()
    faults._log_path = log
    try:
        faults.inject("server.dispatch", "error", n=1)
        with pytest.raises(Exception):
            faults.fire("server.dispatch", cmd="push")
        entries = jsonl_sink.read_jsonl(log)
        assert len(entries) == 1
        e = entries[0]
        assert e["site"] == "server.dispatch" and e["kind"] == "error"
        assert e["pid"] == os.getpid() and e["thread"]
        # the in-memory trace got the same stamped event
        assert faults.trace()[0]["pid"] == os.getpid()
    finally:
        faults._log_path = None
        faults.clear()


def test_quarantine_log_round_trip_on_sink(tmp_path):
    from incubator_mxnet_tpu.resilience.guardian import QuarantineLog
    q = QuarantineLog(str(tmp_path / "quarantine.jsonl"))
    q.append(epoch=0, nbatch=3, reason="nonfinite")
    q.append(source="train.rec", record=17, reason="corrupt_record")
    q.close()
    q2 = QuarantineLog(q.path)
    assert q2.batch_positions() == {(0, 3)}
    assert q2.records("train.rec") == {17}
    assert all("pid" in e for e in q2.load())


# ---------------------------------------------------------------------------
# Profiler buffer cap
# ---------------------------------------------------------------------------

def test_profiler_event_buffer_is_bounded_with_dropped_metric():
    from incubator_mxnet_tpu import profiler
    profiler.set_event_cap(100)
    try:
        with profiler._lock:
            profiler._custom_events.clear()
            profiler._dropped[0] = 0
        for i in range(250):
            profiler._emit({"name": f"ev{i}", "ph": "X", "dur": 1.0,
                            "ts": 0, "pid": 0, "tid": 0})
        st = profiler.buffer_stats()
        assert st["events"] == 100           # bounded, not 250
        assert st["dropped_events"] == 150   # counted, not silent
        # the OLDEST dropped: the newest window survives
        with profiler._lock:
            names = [e["name"] for e in profiler._custom_events]
        assert names[0] == "ev150" and names[-1] == "ev249"
        # surfaced through the registry under the 'profiler' namespace
        vals = obs.registry().collect()
        assert vals["profiler.dropped_events"] == 150
        assert vals["profiler.events"] == 100
    finally:
        profiler.set_event_cap(None)
        with profiler._lock:
            profiler._custom_events.clear()
            profiler._dropped[0] = 0


# ---------------------------------------------------------------------------
# Tracing: in-process span trees
# ---------------------------------------------------------------------------

def test_span_nesting_and_context_propagation():
    obs_trace.enable()           # file-less: spans stay buffered
    with obs_trace.span("root", cat="test", x=1) as root:
        assert obs_trace.current_frame()["s"] == root.span
        with obs_trace.span("child") as child:
            assert child.trace == root.trace
    spans = {s["name"]: s for s in obs_trace.buffered()}
    assert spans["child"]["pa"] == spans["root"]["sp"]
    assert spans["root"]["pa"] is None
    assert spans["root"]["args"] == {"x": 1}
    assert spans["child"]["tr"] == spans["root"]["tr"]
    assert spans["root"]["dur"] >= spans["child"]["dur"]
    # context is clean after the blocks
    assert obs_trace.current_frame() is None


def test_disabled_tracing_is_a_shared_null_object():
    obs_trace.disable()
    sp = obs_trace.start_span("x", rid="r")
    assert sp is obs_trace.NULL_SPAN
    sp.end()
    with obs_trace.span("y") as sp2:
        assert sp2 is obs_trace.NULL_SPAN
    assert obs_trace.buffered() == []


def test_span_buffer_drop_oldest_counted():
    obs_trace.enable()
    obs_trace._cap = 50
    try:
        for i in range(120):
            obs_trace.start_span(f"s{i}").end()
        st = obs_trace.stats()
        assert st["buffered"] <= 50
        assert st["dropped"] >= 70
    finally:
        obs_trace._cap = None


def test_flush_writes_rendered_lines_any_args(tmp_path):
    path = str(tmp_path / "spans.jsonl")
    obs_trace.enable(path)
    obs_trace.start_span('we"ird', note='va"l\\ue', n=1).end()
    obs_trace.start_span("plain", rid="r-1").end()
    assert obs_trace.flush() == 2
    entries = jsonl_sink.read_jsonl(path)
    assert {e["name"] for e in entries} == {'we"ird', "plain"}
    weird = next(e for e in entries if e["name"] == 'we"ird')
    assert weird["args"]["note"] == 'va"l\\ue'
    assert all(e["pid"] == os.getpid() and e["thread"]
               for e in entries)


def test_server_span_adopts_frame_and_rpc_span_injects():
    obs_trace.enable()
    with obs_trace.span("client.request") as root:
        msg = {"cmd": "infer", "rid": "r1"}
        rpc = obs_trace.rpc_span(msg, "127.0.0.1:9")
        assert msg["tr"]["s"] == rpc.span
        rpc.end()
    # "the other process": adopt the frame that rode the wire
    with obs_trace.server_span(msg, "worker.infer", rid="r1") as srv:
        assert srv.parent == msg["tr"]["s"]
        assert srv.trace == root.trace
    spans = {s["name"]: s for s in obs_trace.buffered()}
    assert spans["worker.infer"]["pa"] == spans["rpc.infer"]["sp"]
    assert spans["rpc.infer"]["pa"] == spans["client.request"]["sp"]


# ---------------------------------------------------------------------------
# mxtrace merge
# ---------------------------------------------------------------------------

def _mxtrace():
    import mxtrace
    return mxtrace


def test_mxtrace_merge_flow_arrows_and_orphans(tmp_path):
    mxtrace = _mxtrace()
    spans = [
        {"k": "span", "tr": "t1", "sp": "a", "pa": None,
         "name": "router.request", "cat": "serving", "ts": 100,
         "dur": 500, "args": {}, "pid": 1, "thread": "main"},
        {"k": "span", "tr": "t1", "sp": "b", "pa": "a",
         "name": "worker.infer", "cat": "serving", "ts": 200,
         "dur": 300, "args": {}, "pid": 2, "thread": "w"},
        {"k": "span", "tr": "t2", "sp": "c", "pa": "missing",
         "name": "lost.child", "cat": "x", "ts": 1, "dur": 1,
         "args": {}, "pid": 1, "thread": "main"},
    ]
    trace, summary = mxtrace.merge(spans, events=[
        {"event": "fault", "site": "router.dispatch", "pid": 1,
         "thread": "main", "time": 0.001}])
    assert summary["spans"] == 3
    assert summary["orphan_spans"] == 1
    assert summary["orphans"][0]["span"] == "c"
    evs = trace["traceEvents"]
    # the cross-pid edge got its flow arrow pair
    starts = [e for e in evs if e.get("ph") == "s"]
    finishes = [e for e in evs if e.get("ph") == "f"]
    assert len(starts) == 1 and len(finishes) == 1
    assert starts[0]["id"] == finishes[0]["id"]
    assert starts[0]["pid"] == 1 and finishes[0]["pid"] == 2
    # fault event landed as an instant in its process lane
    assert any(e.get("ph") == "i" and e["name"] == "router.dispatch"
               for e in evs)
    # lane metadata for both processes
    assert {e["pid"] for e in evs if e.get("ph") == "M"
            and e["name"] == "process_name"} == {1, 2}
    tree = mxtrace.trace_tree(spans, "t1")
    assert tree["roots"] == ["a"]
    assert tree["children"] == {"a": ["b"]}


def test_mxtrace_cli_merges_span_file(tmp_path):
    path = str(tmp_path / "spans.jsonl")
    obs_trace.enable(path)
    with obs_trace.span("outer"):
        with obs_trace.span("inner"):
            pass
    obs_trace.flush()
    out = str(tmp_path / "merged.json")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "mxtrace.py"),
         path, "--out", out, "--json", "--check"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    summary = json.loads(proc.stdout)
    assert summary["spans"] == 2 and summary["orphan_spans"] == 0
    merged = json.load(open(out))
    names = {e["name"] for e in merged["traceEvents"]
             if e.get("ph") == "X"}
    assert {"outer", "inner"} <= names


# ---------------------------------------------------------------------------
# Scrape plane over the transport
# ---------------------------------------------------------------------------

def test_scrape_round_trip_over_transport():
    obs.registry().counter("scrape.test_hits").inc(9)
    from incubator_mxnet_tpu.obs.scrape import MetricsEndpoint, scrape
    with MetricsEndpoint() as ep:
        snap = scrape(f"127.0.0.1:{ep.port}")
    assert snap["values"]["scrape.test_hits"] == 9
    parsed = obs_metrics.parse_prometheus(snap["prom"])
    assert parsed[("mx_scrape_test_hits", ())] == 9


def test_mxtop_json_returns_fleet_namespaces():
    """`mxtop --json` over a live endpoint returns fleet-wide metrics
    with (at least) the kvstore, router, and guardian namespaces —
    the ISSUE-14 acceptance shape."""
    from incubator_mxnet_tpu.obs.scrape import MetricsEndpoint
    from incubator_mxnet_tpu.resilience.guardian import TrainingGuardian
    from incubator_mxnet_tpu.serving import ReplicaRouter
    kv = mx.kv.create("device")
    guardian = TrainingGuardian(interval=4)
    router = ReplicaRouter(name="router", health_interval_s=5.0)
    try:
        with MetricsEndpoint() as ep:
            proc = subprocess.run(
                [sys.executable, os.path.join(REPO, "tools", "mxtop.py"),
                 f"127.0.0.1:{ep.port}", "--json"],
                capture_output=True, text=True, timeout=120)
            assert proc.returncode == 0, proc.stdout + proc.stderr
            snap = json.loads(proc.stdout)
        fleet = snap["fleet"]
        namespaces = {k.split(".")[0] for k in fleet}
        assert {"kvstore", "router", "guardian"} <= namespaces
        assert not snap["unreachable"]
        # the text renderer digests the same snapshot
        import mxtop
        frame = mxtop.render(snap)
        assert "KVSTORE" in frame and "ROUTER" in frame
    finally:
        router.shutdown()
        guardian.close()
        del kv


def test_mxtop_reports_unreachable_endpoints_nonfatal():
    import mxtop
    snap = mxtop.snapshot(["127.0.0.1:1"], timeout=0.3)
    assert snap["endpoints"] == {}
    assert len(snap["unreachable"]) == 1


def test_fleet_manager_scrape_aggregates(tmp_path):
    """FleetManager.scrape(): local registry + host daemon legs."""
    from incubator_mxnet_tpu.serving.fleet import (FleetManager,
                                                   InProcessHost,
                                                   ReplicaSpec)
    from incubator_mxnet_tpu.serving import LocalReplica, ServedModel
    net = sym.Variable("data")
    net = sym.FullyConnected(net, num_hidden=4, name="fc")
    net = sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[io.DataDesc("data", (1, 3))],
             label_shapes=[io.DataDesc("softmax_label", (1,))],
             for_training=False, grad_req="null")
    mod.init_params(mx.initializer.Xavier())
    args, auxs = mod.get_params()

    def spawn(spec, rid):
        return LocalReplica(
            ServedModel(net, args, auxs, data_shapes=[("data", (1, 3))],
                        buckets=(1, 2), ctx=mx.cpu(), name="m"),
            replica_id=rid)
    hosts = [InProcessHost("h0", spawn)]
    spec = ReplicaSpec(data_shapes=[("data", (1, 3))], name="m",
                       buckets=(1, 2))
    fleet = FleetManager(hosts, spec, name="fleet", target_replicas=1,
                        tick_s=0.1, host_heartbeat_s=0.1)
    try:
        snap = fleet.scrape()
        assert snap["fleet"] == "fleet"
        vals = snap["local"]["values"]
        assert any(k.startswith("fleet.") for k in vals)
        obs_metrics.parse_prometheus(snap["local"]["prom"])
        # in-process hosts have no scrape leg and are not "unreachable"
        assert snap["hosts"] == {} and snap["unreachable"] == []
    finally:
        fleet.shutdown(drain=False)


# ---------------------------------------------------------------------------
# untracked-stats lint: zero findings on the package
# ---------------------------------------------------------------------------

def test_untracked_stats_lint_fires_and_package_is_clean():
    from incubator_mxnet_tpu import analysis
    rep = analysis.check_source(
        "class KV:\n"
        "    def stats(self):\n"
        "        return {'pushes': 1}\n", filename="demo.py")
    assert [f.code for f in rep] == ["untracked-stats"]
    # a file that registers its producer is clean
    rep = analysis.check_source(
        "from .obs import metrics\n"
        "class KV:\n"
        "    def __init__(self):\n"
        "        metrics.register_producer('kv', self.stats)\n"
        "    def stats(self):\n"
        "        return {'pushes': 1}\n", filename="demo.py")
    assert not [f for f in rep if f.code == "untracked-stats"]
    # ... and after the ISSUE-14 conversion the PACKAGE is clean
    pkg = os.path.join(REPO, "incubator_mxnet_tpu")
    findings = []
    for root, dirs, files in os.walk(pkg):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for fname in files:
            if fname.endswith(".py"):
                rep = analysis.check_source_file(os.path.join(root, fname))
                findings += [f for f in rep if f.code == "untracked-stats"]
    assert not findings, [f.format() for f in findings]


# ---------------------------------------------------------------------------
# Cross-process: the headline gate
# ---------------------------------------------------------------------------

def test_cross_process_span_tree_complete_after_merge(tmp_path):
    """A routed request through a REAL subprocess worker merges into
    one connected cross-process span tree with zero orphans — the
    ISSUE-14 acceptance criterion, at tier-1 scale (1 worker)."""
    mxtrace = _mxtrace()
    from incubator_mxnet_tpu.serving import RemoteReplica, ReplicaRouter
    net = sym.Variable("data")
    net = sym.FullyConnected(net, num_hidden=8, name="fc")
    net = sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[io.DataDesc("data", (2, 6))],
             label_shapes=[io.DataDesc("softmax_label", (2,))],
             for_training=False, grad_req="null")
    mod.init_params(mx.initializer.Xavier())
    prefix = str(tmp_path / "m")
    mod.save_checkpoint(prefix, 0)

    span_path = str(tmp_path / "spans.jsonl")
    obs_trace.enable(span_path)
    rep = RemoteReplica.spawn(
        prefix=prefix, epoch=0, data_shapes=[("data", (1, 6))],
        buckets=(1, 2), name="m", replica_id="w0",
        env={"MXNET_OBS_TRACE": span_path, "JAX_PLATFORMS": "cpu"})
    router = ReplicaRouter([rep], health_interval_s=0.5,
                           health_deadline_s=10.0)
    try:
        x = np.random.randn(1, 6).astype(np.float32)
        rids = []
        for _ in range(3):
            fut = router.submit({"data": x}, timeout_ms=30000)
            rids.append(fut.request_id)
            fut.result(60)
    finally:
        router.shutdown(drain=True)   # stops the worker: it flushes
    obs_trace.flush()

    spans, events, chrome = mxtrace.load_inputs([span_path])
    merged, summary = mxtrace.merge(spans, events, chrome)
    assert summary["orphan_spans"] == 0
    assert summary["processes"] >= 2       # router pid + worker pid
    by_id = {s["sp"]: s for s in spans}
    roots = [s for s in spans if s["name"] == "router.request"]
    assert len(roots) == 3
    pids = {s["pid"] for s in spans}
    assert len(pids) >= 2
    for root in roots:
        # walk this request's tree: it must reach a worker.infer span
        # in the OTHER process
        tree = mxtrace.trace_tree(spans, root["tr"])
        reached, frontier = set(), [root["sp"]]
        while frontier:
            cur = frontier.pop()
            reached.add(cur)
            frontier += tree["children"].get(cur, [])
        names = {by_id[sp]["name"] for sp in reached}
        assert "worker.infer" in names, sorted(names)
        worker_pids = {by_id[sp]["pid"] for sp in reached
                       if by_id[sp]["name"] == "worker.infer"}
        assert worker_pids and worker_pids != {root["pid"]}
        assert root["args"]["rid"] in rids


def test_scrape_worker_over_control_channel(tmp_path):
    """RemoteReplica.scrape() returns the WORKER process's registry —
    the per-replica leg of the fleet-wide scrape."""
    from incubator_mxnet_tpu.serving import RemoteReplica
    net = sym.Variable("data")
    net = sym.FullyConnected(net, num_hidden=4, name="fc")
    net = sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[io.DataDesc("data", (1, 4))],
             label_shapes=[io.DataDesc("softmax_label", (1,))],
             for_training=False, grad_req="null")
    mod.init_params(mx.initializer.Xavier())
    prefix = str(tmp_path / "m")
    mod.save_checkpoint(prefix, 0)
    rep = RemoteReplica.spawn(
        prefix=prefix, epoch=0, data_shapes=[("data", (1, 4))],
        buckets=(1,), name="m", replica_id="w0",
        env={"JAX_PLATFORMS": "cpu"})
    try:
        x = np.random.randn(1, 4).astype(np.float32)
        rep.submit({"data": x}, rid="req-1").result(60)
        snap = rep.scrape()
        assert snap["values"]["worker.executed"] >= 1
        obs_metrics.parse_prometheus(snap["prom"])
    finally:
        rep.close(drain=True)
