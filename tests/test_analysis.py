"""mxlint static & trace analysis: every lint class detects its seeded
defect with exact names/locations, and the clean example graphs produce
zero false positives (the ISSUE-3 acceptance gate).

Covers: graph passes (f64 promotion, dead outputs, unbound inputs, bad
layout, duplicate/empty names, shared aux), JSON structural passes,
script AST lints + suppression, the mxlint CLI over examples/, runtime
donation tracking (use-after-donate raises MXNetError naming the
parameter), host-sync attribution inside Module.fit, the recompilation
audit for ragged batches, and the NaiveEngine contextful error chain.
"""
import importlib.util
import json
import os

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import analysis, engine, fused, io, nd, rnn, sym
from incubator_mxnet_tpu.base import MXNetError
from incubator_mxnet_tpu.io import DataBatch

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
THIS_FILE = os.path.abspath(__file__)


@pytest.fixture(autouse=True)
def _analysis_clean():
    analysis.reset_runtime()
    yield
    analysis.disable()
    analysis.reset_runtime()


def _load_example(relpath, name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, relpath))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _mlp_symbol():
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=8, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=4, name="fc2")
    return sym.SoftmaxOutput(net, name="softmax")


def _fused_module(batch_size=16):
    X = np.random.randn(64, 16).astype("f4")
    y = np.random.randint(0, 4, 64).astype("f4")
    it = io.NDArrayIter(X, y, batch_size=batch_size,
                        label_name="softmax_label")
    mod = mx.mod.Module(_mlp_symbol(), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(kvstore="device", optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    return mod, list(it), X, y


# ---------------------------------------------------------------------------
# graph passes: seeded defects
# ---------------------------------------------------------------------------

def test_f64_promotion_detected_with_node_name():
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=8, name="fca")
    net = sym.Cast(net, dtype="float64", name="to64")
    net = sym.SoftmaxOutput(sym.FullyConnected(net, num_hidden=4,
                                               name="fcb"), name="sm")
    report = analysis.check(net, shapes={"data": (8, 16), "sm_label": (8,)})
    hits = [f for f in report if f.code == "f64-promotion"]
    assert len(hits) == 1
    assert hits[0].node == "to64"
    assert "float64" in hits[0].message
    # declared-f64 variable is an origin too
    v64 = sym.Variable("big", dtype="float64")
    out = sym.SoftmaxOutput(sym.FullyConnected(v64, num_hidden=4,
                                               name="fcv"), name="sv")
    hits = [f for f in analysis.check(out) if f.code == "f64-promotion"]
    assert [f.node for f in hits] == ["big"]


def test_dead_output_detected():
    split = sym.SliceChannel(sym.Variable("x"), num_outputs=3, name="spl")
    only_first = split[0]
    hits = [f for f in analysis.check(only_first)
            if f.code == "dead-output"]
    assert sorted(f.message for f in hits)
    assert len(hits) == 2 and all(f.node == "spl" for f in hits)
    assert any("spl_output1" in f.message for f in hits)
    assert any("spl_output2" in f.message for f in hits)
    # all outputs consumed -> clean
    joined = sym.Group([split[0], split[1], split[2]])
    assert not [f for f in analysis.check(joined)
                if f.code == "dead-output"]


def test_unbound_input_detected():
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=8, name="fc1")
    net = sym.broadcast_add(net, sym.Variable("mystery"))
    net = sym.SoftmaxOutput(net, name="softmax")
    report = analysis.check(net, shapes={"data": (8, 16),
                                         "softmax_label": (8,)})
    hits = [f for f in report if f.code == "unbound-input"]
    assert [f.node for f in hits] == ["mystery"]
    # with no shapes given the pass stays quiet (nothing is inferable)
    assert not [f for f in analysis.check(net)
                if f.code == "unbound-input"]


def test_bad_layout_hint_and_severity():
    net = sym.SoftmaxOutput(
        sym.FullyConnected(sym.Variable("data"), num_hidden=100,
                           name="odd_fc"), name="softmax")
    report = analysis.check(net)
    hits = [f for f in report if f.code == "tpu-layout"]
    assert [f.node for f in hits] == ["odd_fc"]
    assert hits[0].severity == "hint" and "100" in hits[0].message
    # hints never survive a warn-level filter (CLI default)
    assert not [f for f in report.filter(max_severity=analysis.WARN)
                if f.code == "tpu-layout"]
    # aligned dims are clean
    ok = sym.SoftmaxOutput(
        sym.FullyConnected(sym.Variable("data"), num_hidden=256,
                           name="fc"), name="softmax")
    assert not [f for f in analysis.check(ok) if f.code == "tpu-layout"]
    # per-node suppression via the __lint__ attr
    sup = sym.SoftmaxOutput(
        sym.FullyConnected(sym.Variable("data"), num_hidden=100,
                           name="odd2", attr={"__lint__": "tpu-layout"}),
        name="softmax")
    assert not [f for f in analysis.check(sup) if f.code == "tpu-layout"]


def test_shared_aux_detected():
    data = sym.Variable("data")
    mm, mv = sym.Variable("shared_mean"), sym.Variable("shared_var")
    bn1 = sym.BatchNorm(data, sym.Variable("g1"), sym.Variable("b1"),
                        mm, mv, name="bn1")
    bn2 = sym.BatchNorm(bn1, sym.Variable("g2"), sym.Variable("b2"),
                        mm, mv, name="bn2")
    hits = [f for f in analysis.check(bn2) if f.code == "shared-aux"]
    assert sorted(f.node for f in hits) == ["shared_mean", "shared_var"]
    assert "bn1" in hits[0].message and "bn2" in hits[0].message


def test_duplicate_and_empty_names_rejected_at_build_time():
    data = sym.Variable("data")
    first = sym.FullyConnected(data, num_hidden=8, name="fc1")
    with pytest.raises(MXNetError, match="fc1"):
        sym.FullyConnected(first, num_hidden=8, name="fc1")
    with pytest.raises(MXNetError, match="non-empty"):
        sym.FullyConnected(data, num_hidden=8, name="  ")
    with pytest.raises(MXNetError, match="non-empty"):
        sym.Variable("")
    # an op name shadowing an input VARIABLE is rejected too
    with pytest.raises(MXNetError, match="data"):
        sym.FullyConnected(data, num_hidden=8, name="data")


def test_duplicate_names_in_json_detected_and_bind_rejects():
    graph = {
        "nodes": [
            {"op": "null", "name": "x", "inputs": []},
            {"op": "relu", "name": "x", "inputs": [[0, 0, 0]]},
            {"op": "null", "name": "orphan_moving_mean", "inputs": []},
        ],
        "arg_nodes": [0, 2],
        "heads": [[1, 0, 0]],
    }
    report = analysis.check_json(json.dumps(graph), target="g.json")
    codes = report.by_code()
    assert codes.get("duplicate-name") == 1
    assert codes.get("unreachable-node") == 1
    unreachable = [f for f in report if f.code == "unreachable-node"]
    assert unreachable[0].node == "orphan_moving_mean"
    # binding a graph whose op shadows a VARIABLE name fails loudly
    # instead of training the wrong arrays
    loaded = mx.sym.load_json(json.dumps(graph))
    with pytest.raises(MXNetError, match="'x'"):
        loaded.simple_bind(ctx=mx.cpu(), x=(2, 3))
    # op-op duplicates are the gluon `fwd` idiom: lint-warn, not an error
    dup_ops = sym.Group([
        sym.Activation(sym.Variable("a"), act_type="relu", name="fwd"),
        sym.Activation(sym.Variable("b"), act_type="relu", name="fwd")])
    hits = [f for f in analysis.check(dup_ops)
            if f.code == "duplicate-name"]
    assert len(hits) == 1 and hits[0].severity == "warn"
    dup_ops.simple_bind(ctx=mx.cpu(), a=(2, 2), b=(2, 2))  # binds fine


# ---------------------------------------------------------------------------
# zero false positives on the example graphs
# ---------------------------------------------------------------------------

def _assert_clean(symbol, shapes, what):
    report = analysis.check(symbol, shapes=shapes)
    bad = report.filter(max_severity=analysis.WARN)
    assert not bad, f"{what}: unexpected findings:\n{bad.format()}"


def test_zero_false_positives_image_classification_graphs():
    mnist = _load_example(
        "examples/image_classification/train_mnist.py", "_ex_mnist")
    _assert_clean(mnist.get_mlp(),
                  {"data": (8, 1, 28, 28), "softmax_label": (8,)}, "mlp")
    _assert_clean(mnist.get_lenet(),
                  {"data": (8, 1, 28, 28), "softmax_label": (8,)}, "lenet")
    resnet = _load_example(
        "examples/image_classification/symbols/resnet.py", "_ex_resnet")
    _assert_clean(resnet.get_symbol(10, 8, "3,28,28"),
                  {"data": (4, 3, 28, 28), "softmax_label": (4,)},
                  "resnet-8")


def test_zero_false_positives_rnn_graph():
    # the lstm_bucketing sym_gen graph (examples/rnn) rebuilt verbatim
    stack = rnn.SequentialRNNCell()
    for i in range(2):
        stack.add(rnn.LSTMCell(50, prefix=f"lstm_l{i}_"))
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    embed = mx.sym.Embedding(data, input_dim=100, output_dim=32,
                             name="embed")
    stack.reset()
    outputs, _ = stack.unroll(10, inputs=embed, merge_outputs=True)
    pred = mx.sym.Reshape(outputs, shape=(-1, 50))
    pred = mx.sym.FullyConnected(pred, num_hidden=100, name="pred")
    lab = mx.sym.Reshape(label, shape=(-1,))
    pred = mx.sym.SoftmaxOutput(pred, lab, name="softmax")
    _assert_clean(pred, {"data": (8, 10), "softmax_label": (8, 10)},
                  "lstm-bucketing")


def test_module_check_clean_and_roundtrip_json():
    X = np.random.randn(32, 16).astype("f4")
    y = np.random.randint(0, 4, 32).astype("f4")
    it = io.NDArrayIter(X, y, batch_size=8, label_name="softmax_label")
    mod = mx.mod.Module(_mlp_symbol(), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    report = mod.check()
    assert not report.filter(max_severity=analysis.WARN), report.format()
    # saved-JSON front end agrees with the Symbol front end
    jreport = analysis.check_json(_mlp_symbol().tojson(), target="mlp")
    assert not jreport.filter(max_severity=analysis.WARN), jreport.format()


# ---------------------------------------------------------------------------
# script AST lints + CLI
# ---------------------------------------------------------------------------

def test_source_lints_detect_and_suppress():
    src = (
        "import incubator_mxnet_tpu as mx\n"            # 1
        "ctx = mx.tpu()\n"                              # 2
        "for i in range(10):\n"                         # 3
        "    v = out.asnumpy()\n"                       # 4
        "    w = other.asnumpy()  # mxlint: disable\n"  # 5
        "    u = x.wait_to_read()"
        "  # mxlint: disable=kvstore-local-on-tpu\n"    # 6 (wrong code)
        "mod.fit(data, kvstore='local')\n"              # 7
    )
    report = analysis.check_source(src, "demo.py")
    locs = {f.code: f.location for f in report}
    assert locs["kvstore-local-on-tpu"] == "demo.py:7"
    syncs = sorted(f.location for f in report
                   if f.code == "host-sync-in-loop")
    assert syncs == ["demo.py:4", "demo.py:6"]   # line 5 suppressed
    # no tpu usage -> kvstore lint stays quiet
    quiet = analysis.check_source("mod.fit(d, kvstore='local')\n", "q.py")
    assert not [f for f in quiet if f.code == "kvstore-local-on-tpu"]
    # function defined inside a loop is not a per-iteration sync
    fn_src = "for i in r:\n    def cb(p):\n        q = o.asnumpy()\n"
    assert not analysis.check_source(fn_src, "f.py").findings


def test_unbounded_retry_lint_fixtures():
    """ISSUE-5 satellite: `while True` around connect/request with no
    deadline and no raise is an unbounded retry loop."""
    bad = (
        "import socket, time\n"                          # 1
        "while True:\n"                                  # 2
        "    try:\n"                                     # 3
        "        s = socket.create_connection(addr)\n"   # 4
        "        break\n"                                # 5
        "    except OSError:\n"                          # 6
        "        time.sleep(0.3)\n"                      # 7
        "def poll(chan):\n"                              # 8
        "    while True:\n"                              # 9
        "        try:\n"                                 # 10
        "            r = chan.request({'cmd': 'x'})\n"   # 11
        "        except OSError:\n"                      # 12
        "            continue\n"                         # 13
    )
    report = analysis.check_source(bad, "retry.py")
    locs = sorted(f.location for f in report
                  if f.code == "unbounded-retry")
    assert locs == ["retry.py:2", "retry.py:9"]
    # a bare call with NO try is not a retry loop: a dead peer's
    # exception escapes the loop (a server's read loop, for instance)
    serve = ("while True:\n"
             "    msg = recv_msg(sock)\n"
             "    handle(msg)\n")
    assert not analysis.check_source(serve, "srv.py").findings
    # `except: break` exits the loop on peer death — a bound (the
    # conventional connection-handler read loop)
    read_loop = ("while True:\n"
                 "    try:\n"
                 "        msg = recv_msg(sock)\n"
                 "    except (EOFError, OSError):\n"
                 "        break\n"
                 "    handle(msg)\n")
    assert not analysis.check_source(read_loop, "rl.py").findings

    # a deadline reference OR a raise bounds the loop -> clean
    good = (
        "import time\n"
        "deadline = time.monotonic() + 5\n"
        "while True:\n"
        "    try:\n"
        "        s = socket.create_connection(addr)\n"
        "        break\n"
        "    except OSError:\n"
        "        if time.monotonic() >= deadline:\n"
        "            raise\n"
    )
    assert not analysis.check_source(good, "g.py").findings
    raises = ("while True:\n"
              "    try:\n"
              "        return chan.request(m)\n"
              "    except OSError:\n"
              "        raise RuntimeError('dead')\n")
    assert not [f for f in analysis.check_source(raises, "r.py")
                if f.code == "unbounded-retry"]
    # a while-True loop with no connect/request call is not a retry loop
    assert not analysis.check_source("while True:\n    step()\n",
                                     "w.py").findings
    # suppression on the loop line
    sup = ("while True:  # mxlint: disable=unbounded-retry\n"
           "    chan.request(m)\n")
    assert not analysis.check_source(sup, "s.py").findings


def test_bare_except_lint_fixtures():
    """ISSUE-5 satellite: bare `except` swallowing MXNetError in
    training scripts."""
    bad = (
        "try:\n"                                 # 1
        "    mod.fit(it, num_epoch=2)\n"         # 2
        "except:\n"                              # 3
        "    print('oh well')\n"                 # 4
        "try:\n"                                 # 5
        "    kv.push(k, v)\n"                    # 6
        "except Exception:\n"                    # 7
        "    pass\n"                             # 8
    )
    report = analysis.check_source(bad, "swallow.py")
    locs = sorted(f.location for f in report if f.code == "bare-except")
    assert locs == ["swallow.py:3", "swallow.py:7"]
    assert "ServerLostError" in next(
        f.message for f in report if f.code == "bare-except")

    # re-raising, or catching something specific, is fine
    ok = (
        "try:\n"
        "    mod.fit(it, num_epoch=2)\n"
        "except:\n"
        "    cleanup()\n"
        "    raise\n"
        "try:\n"
        "    kv.push(k, v)\n"
        "except ValueError:\n"
        "    pass\n"
        "try:\n"
        "    f()\n"
        "except Exception as e:\n"
        "    log(e)\n"                # broad but does real handling
    )
    assert not analysis.check_source(ok, "ok.py").findings
    sup = "try:\n    f()\nexcept:  # mxlint: disable\n    pass\n"
    assert not analysis.check_source(sup, "s.py").findings


def test_router_bypass_lint_fixtures():
    """ISSUE-8 satellite: direct ServedModel.infer / ModelServer use in
    a script that configures a ReplicaRouter bypasses failover + QoS."""
    bad = (
        "import incubator_mxnet_tpu as mx\n"                        # 1
        "router = mx.serving.ReplicaRouter(reps)\n"                 # 2
        "m = mx.serving.ServedModel(sym, a, x, data_shapes=ds)\n"   # 3
        "out = m.infer({'data': batch})\n"                          # 4
        "srv = mx.serving.ModelServer()\n"                          # 5
        "y = mx.serving.ServedModel.load('p', 0).infer(batch)\n"    # 6
    )
    report = analysis.check_source(bad, "bypass.py")
    locs = sorted(f.location for f in report if f.code == "router-bypass")
    assert locs == ["bypass.py:4", "bypass.py:5", "bypass.py:6"]
    assert "failover" in next(
        f.message for f in report if f.code == "router-bypass")

    # the SAME direct calls in a router-less script are fine (serving a
    # single model without a fleet is a legitimate topology) ...
    ok = (
        "import incubator_mxnet_tpu as mx\n"
        "m = mx.serving.ServedModel(sym, a, x, data_shapes=ds)\n"
        "out = m.infer({'data': batch})\n"
        "srv = mx.serving.ModelServer()\n"
    )
    assert not [f for f in analysis.check_source(ok, "ok.py")
                if f.code == "router-bypass"]
    # ... routed traffic is fine, and suppression is honored
    routed = (
        "import incubator_mxnet_tpu as mx\n"
        "router = mx.serving.ReplicaRouter(reps)\n"
        "out = router.predict({'data': batch})\n"
    )
    assert not analysis.check_source(routed, "routed.py").findings
    sup = (
        "router = ReplicaRouter(reps)\n"
        "srv = ModelServer()  # mxlint: disable=router-bypass\n"
    )
    assert not analysis.check_source(sup, "s.py").findings


def test_mxlint_cli_examples_zero_findings_and_seeded_defects(tmp_path,
                                                              capsys):
    import importlib
    spec = importlib.util.spec_from_file_location(
        "_mxlint_cli", os.path.join(REPO, "tools", "mxlint.py"))
    cli = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cli)

    # acceptance gate: zero findings over the clean examples tree
    rc = cli.main([os.path.join(REPO, "examples"), "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0 and out["failing"] == 0 and out["findings"] == 0

    # seeded defects: a hot-loop script and a shadowed-graph JSON
    bad_py = tmp_path / "train_bad.py"
    bad_py.write_text("import incubator_mxnet_tpu as mx\n"
                      "ctx = mx.tpu()\n"
                      "for b in it:\n"
                      "    print(loss.asnumpy())\n"
                      "m.fit(it, kvstore='local')\n")
    bad_json = tmp_path / "net-symbol.json"
    bad_json.write_text(json.dumps({
        "nodes": [{"op": "null", "name": "w", "inputs": []},
                  {"op": "null", "name": "w", "inputs": []}],
        "arg_nodes": [0, 1], "heads": [[0, 0, 0]]}))
    rc = cli.main([str(tmp_path), "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["by_code"]["host-sync-in-loop"] == 1
    assert out["by_code"]["kvstore-local-on-tpu"] == 1
    assert out["by_code"]["duplicate-name"] == 1
    assert out["by_code"]["unreachable-node"] == 1
    items = {i["code"]: i for i in out["items"]}
    assert items["host-sync-in-loop"]["location"] == f"{bad_py}:4"


# ---------------------------------------------------------------------------
# runtime trace passes
# ---------------------------------------------------------------------------

def test_use_after_donation_raises_naming_parameter():
    analysis.enable()
    mod, batches, _, _ = _fused_module()
    metric = mx.metric.create("acc")
    mod.fit_step(batches[0], metric)   # cold step: flushes through
    mod.fit_step(batches[1], metric)   # steady step: donates the flushed
    assert mod._fused_step is not None and not mod._fused_step.broken
    stale = mod._exec_group.execs[0].arg_dict["fc1_weight"]
    with pytest.raises(MXNetError, match=r"fc1_weight.*donated"):
        stale.asnumpy()
    # eager ops on the stale buffer get the same named error
    with pytest.raises(MXNetError, match="use-after-donation"):
        (stale * 2).asnumpy()
    # the public path flushes and keeps working
    args, _ = mod.get_params()
    assert np.isfinite(args["fc1_weight"].asnumpy()).all()


def test_use_after_donation_generic_message_when_disabled():
    analysis.disable()
    mod, batches, _, _ = _fused_module()
    metric = mx.metric.create("acc")
    mod.fit_step(batches[0], metric)
    mod.fit_step(batches[1], metric)
    stale = mod._exec_group.execs[0].arg_dict["fc1_weight"]
    with pytest.raises(MXNetError, match="use-after-donation"):
        stale.asnumpy()


def test_unrecoverable_failure_names_consumed_parameters():
    import jax
    import jax.numpy as jnp
    arr = jax.device_put(jnp.zeros((2,)))
    arr.delete()
    live = jax.device_put(jnp.ones((2,)))
    with pytest.raises(MXNetError, match=r"'fc9_weight'.*unrecoverable"):
        fused._raise_if_unrecoverable(
            "fused train step", ValueError("boom"),
            [("ok_param", [live]), ("fc9_weight", [arr])])
    # intact buffers: triage returns, fallback is allowed
    fused._raise_if_unrecoverable("fused train step", ValueError("x"),
                                  [("ok_param", [live])])


def test_ragged_batch_retraces_and_audit_names_the_arg():
    analysis.enable()
    mod, batches, X, y = _fused_module()
    metric = mx.metric.create("acc")
    mod.fit_step(batches[0], metric)
    mod.fit_step(batches[1], metric)
    ragged = DataBatch([nd.array(X[:5])], [nd.array(y[:5])])
    assert mod._fused_step(ragged, metric)        # retrace, not breakage
    assert not mod._fused_step.broken
    assert mod._fused_step(batches[2], metric)    # cached program swaps back
    hits = [f for f in analysis.runtime_report()
            if f.code == "shape-churn"]
    assert len(hits) == 1, [f.message for f in hits]
    msg = hits[0].message
    assert "'data' shape (16, 16) -> (5, 16)" in msg
    assert "'softmax_label' shape (16,) -> (5,)" in msg
    assert "ragged final batch" in msg
    args, _ = mod.get_params()
    assert np.isfinite(args["fc1_weight"].asnumpy()).all()


def test_gluon_fused_step_ragged_batch_retraces():
    from incubator_mxnet_tpu import gluon
    from incubator_mxnet_tpu.gluon.fused_step import GluonFusedStep
    analysis.enable()
    rng = np.random.RandomState(3)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"))
    net.add(gluon.nn.Dense(3))
    net.initialize()
    net(nd.array(np.zeros((2, 12), "f4")))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    metric = mx.metric.Accuracy()
    step = GluonFusedStep.try_build(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), trainer, [metric])
    assert step is not None
    X = rng.randn(64, 12).astype("f4")
    y = rng.randint(0, 3, 64).astype("f4")
    assert step(nd.array(X[:16]), nd.array(y[:16]), 16)
    assert step(nd.array(X[16:32]), nd.array(y[16:32]), 16)
    assert step(nd.array(X[:7]), nd.array(y[:7]), 7)      # ragged tail
    assert not step.broken, "ragged batch must retrace, not break"
    assert step(nd.array(X[32:48]), nd.array(y[32:48]), 16)  # cache swap
    hits = [f for f in analysis.runtime_report()
            if f.code == "shape-churn" and "GluonFusedStep" in f.location]
    assert len(hits) == 1, [f.message for f in hits]
    assert "'data' shape (16, 12) -> (7, 12)" in hits[0].message


def test_hostsync_attributed_to_callback_line():
    analysis.enable()
    X = np.random.randn(32, 16).astype("f4")
    y = np.random.randint(0, 4, 32).astype("f4")
    it = io.NDArrayIter(X, y, batch_size=8, label_name="softmax_label")
    mod = mx.mod.Module(_mlp_symbol(), context=mx.cpu())
    seen = []

    def peek(_param):
        seen.append(mod.get_outputs()[0].asnumpy())   # the hot-loop sync

    sync_line = peek.__code__.co_firstlineno + 1
    mod.fit(it, num_epoch=1, optimizer="sgd", batch_end_callback=peek)
    hits = [f for f in analysis.runtime_report()
            if f.code == "host-sync-in-loop" and
            f.location == f"{THIS_FILE}:{sync_line}"]
    assert len(hits) == 1, analysis.runtime_report().format()
    assert hits[0].count == len(seen) == 4
    assert "Module.fit" in hits[0].message


def test_hostsync_quiet_when_disabled():
    analysis.disable()
    X = np.random.randn(16, 16).astype("f4")
    y = np.random.randint(0, 4, 16).astype("f4")
    it = io.NDArrayIter(X, y, batch_size=8, label_name="softmax_label")
    mod = mx.mod.Module(_mlp_symbol(), context=mx.cpu())
    mod.fit(it, num_epoch=1, optimizer="sgd",
            batch_end_callback=lambda p: mod.get_outputs()[0].asnumpy())
    assert not [f for f in analysis.runtime_report()
                if f.code == "host-sync-in-loop"]


def test_recompile_auditor_unit():
    key = "unit-test-program"
    sig16 = ((( 16, 16), "float32"), ((16,), "float32"))
    sig5 = (((5, 16), "float32"), ((5,), "float32"))
    assert analysis.recompile.note(key, ("data", "label"), sig16) is None
    assert analysis.recompile.note(key, ("data", "label"), sig16) is None
    f = analysis.recompile.note(key, ("data", "label"), sig5)
    assert f is not None and "'data' shape (16, 16) -> (5, 16)" in f.message
    # a previously-seen signature does not re-fire
    assert analysis.recompile.note(key, ("data", "label"), sig16) is None
    assert len(analysis.recompile.signatures(key)) == 2
    # dtype churn is named as such, without the ragged diagnosis
    f2 = analysis.recompile.note(key, ("data", "label"),
                                 (((16, 16), "float16"), ((16,), "float32")))
    assert "dtype float32 -> float16" in f2.message
    assert "ragged" not in f2.message


def test_naive_engine_track_chains_contextful_error():
    class Boom:
        def block_until_ready(self):
            raise RuntimeError("XLA buffer poisoned")

    prev = os.environ.get("MXNET_ENGINE_TYPE")
    os.environ["MXNET_ENGINE_TYPE"] = "NaiveEngine"
    try:
        with pytest.raises(MXNetError,
                           match=r"NaiveEngine: operator 'dot'") as exc:
            engine.track(Boom(), op="dot")
        assert "XLA buffer poisoned" in str(exc.value)
        assert isinstance(exc.value.__cause__, RuntimeError)
    finally:
        if prev is None:
            os.environ.pop("MXNET_ENGINE_TYPE", None)
        else:
            os.environ["MXNET_ENGINE_TYPE"] = prev


# ---------------------------------------------------------------------------
# the unified finding-code registry (ISSUE-13 satellite)
# ---------------------------------------------------------------------------

def test_code_table_no_duplicates_and_no_orphans():
    """Every code any pass emits registers exactly once in
    findings.CODE_TABLE, and the table carries no code nothing emits."""
    from incubator_mxnet_tpu.analysis import findings as F
    from incubator_mxnet_tpu.analysis import (budgets, cost, graph_passes,
                                              hostsync, recompile,
                                              sharding, source_lint, tsan)

    # duplicate registration is rejected at table build time
    with pytest.raises(ValueError, match="registered twice"):
        F._build_code_table([("x", F.WARN, ("p",), "d"),
                             ("x", F.WARN, ("p",), "d")])

    table = set(F.CODE_TABLE)
    declared = set()
    for codes in graph_passes.PASS_CATALOG.values():
        declared.update(codes)
    declared.update(source_lint._PASS_BY_CODE)
    declared.add("syntax-error")
    declared.update(tsan.CODES)
    declared.update(recompile.CODES)
    declared.update(hostsync.CODES)
    declared.update(cost.CODES)
    declared.update(budgets.CODES)
    declared.update(sharding.CODES)
    missing = declared - table
    assert not missing, f"codes emitted but unregistered: {missing}"

    # reverse orphan check: every registered code appears as a literal
    # in the package source OUTSIDE the table itself (nothing in the
    # table is dead — findings.py is excluded, else the check would be
    # satisfied by the very registration it verifies)
    pkg = os.path.join(REPO, "incubator_mxnet_tpu")
    blob = []
    for root, _dirs, files in os.walk(pkg):
        if "__pycache__" in root:
            continue
        for fname in files:
            if fname.endswith(".py") and fname != "findings.py":
                with open(os.path.join(root, fname),
                          encoding="utf-8") as f:
                    blob.append(f.read())
    blob = "\n".join(blob)
    orphans = {code for code in table if f'"{code}"' not in blob}
    assert not orphans, f"registered codes nothing emits: {orphans}"

    # table hygiene: valid severities, one-line docs, named passes
    for code, (severity, passes, doc) in F.CODE_TABLE.items():
        assert severity in (F.ERROR, F.WARN, F.HINT), code
        assert passes and all(p for p in passes), code
        assert doc and "\n" not in doc, code


# ---------------------------------------------------------------------------
# source-lint suppression: EVERY registered code sweeps through an
# inline `# mxlint: disable=<code>` fixture (ISSUE-13 satellite)
# ---------------------------------------------------------------------------

# code -> (fixture source, 1-based line the finding lands on)
_SUPPRESSION_FIXTURES = {
    "host-sync-in-loop": (
        "for b in it:\n"
        "    x.asnumpy()\n", 2),
    "host-transfer-in-graph": (
        "import jax\n"
        "import numpy as np\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return np.asarray(x)\n", 5),
    "kvstore-local-on-tpu": (
        "import incubator_mxnet_tpu as mx\n"
        "ctx = mx.tpu()\n"
        "m.fit(it, kvstore='local')\n", 3),
    "unbucketed-push": (
        "for name in names:\n"
        "    kv.push(name, grads[name])\n", 2),
    "unbounded-retry": (
        "while True:\n"
        "    try:\n"
        "        s.connect(addr)\n"
        "    except OSError:\n"
        "        pass\n", 1),
    "bare-except": (
        "try:\n"
        "    f()\n"
        "except:\n"
        "    pass\n", 3),
    "nan-swallow": (
        "while True:\n"
        "    try:\n"
        "        trainer.step(1)\n"
        "    except ValueError:\n"
        "        continue\n", 4),
    "unsupervised-collective": (
        "kv.all_reduce(x)\n", 1),
    "router-bypass": (
        "r = ReplicaRouter(replicas)\n"
        "srv = ModelServer()\n", 2),
    "fixed-fleet": (
        "r = ReplicaRouter([LocalReplica(), LocalReplica()])\n"
        "m = FleetManager(r)\n", 1),
    "unguarded-model-swap": (
        "c = LoopController(router, registry, holdout)\n"
        "router.swap_weights(checkpoint_dir=ck)\n", 2),
    "unnamed-thread": (
        "import threading\n"
        "t = threading.Thread(target=f)\n", 2),
    "bare-acquire": (
        "lock.acquire()\n", 1),
    "sleep-under-lock": (
        "import time\n"
        "with lock:\n"
        "    time.sleep(1)\n", 3),
    "unjoined-thread-in-init": (
        "import threading\n"
        "class W:\n"
        "    def __init__(self):\n"
        "        threading.Thread(target=f, name='x').start()\n", 4),
    "untracked-stats": (
        "class KV:\n"
        "    def stats(self):\n"
        "        return {'pushes': 1}\n", 2),
    "dense-grad-for-embedding": (
        "for batch in it:\n"
        "    kv.push('embed_weight', embed_grad)\n", 2),
    "blocking-h2d-in-loop": (
        "import jax\n"
        "for batch in it:\n"
        "    x = jax.device_put(batch)\n"
        "    mod.fit_step(x, metric)\n", 3),
    "kv-cache-recompile": (
        "import jax.numpy as jnp\n"
        "for t in range(max_new):\n"
        "    kv_cache = jnp.concatenate([kv_cache, new_kv], axis=1)\n"
        "    tok = decode_step(params, kv_cache, tok)\n", 3),
    "unsharded-device-put": (
        "import jax\n"
        "from incubator_mxnet_tpu.parallel.mesh import make_mesh\n"
        "mesh = make_mesh({'dp': 4, 'tp': 2})\n"
        "w = jax.device_put(big_weights)\n", 4),
}


def test_every_source_lint_code_has_a_suppression_fixture():
    """The sweep below covers the COMPLETE registered source-lint code
    set (syntax-error aside: an unparseable file has no line to carry
    the directive), so a new lint cannot land without a fixture."""
    from incubator_mxnet_tpu.analysis.source_lint import _PASS_BY_CODE
    assert set(_SUPPRESSION_FIXTURES) == set(_PASS_BY_CODE)


@pytest.mark.parametrize("code", sorted(_SUPPRESSION_FIXTURES))
def test_source_lint_inline_suppression_sweep(code):
    source, lineno = _SUPPRESSION_FIXTURES[code]
    report = analysis.check_source(source, filename="fix.py")
    hits = [f for f in report if f.code == code]
    assert hits, f"{code}: fixture did not trigger its lint"
    assert any(f.location == f"fix.py:{lineno}" for f in hits), \
        f"{code}: fired at {[f.location for f in hits]}, " \
        f"fixture expects line {lineno}"

    # the inline directive on the finding line silences EXACTLY it
    lines = source.splitlines()
    lines[lineno - 1] += f"  # mxlint: disable={code}"
    suppressed = analysis.check_source("\n".join(lines) + "\n",
                                       filename="fix.py")
    assert not [f for f in suppressed if f.code == code], \
        f"{code}: inline disable did not suppress"

    # a disable naming a DIFFERENT code must not silence this one
    lines = source.splitlines()
    lines[lineno - 1] += "  # mxlint: disable=tpu-layout"
    other = analysis.check_source("\n".join(lines) + "\n",
                                  filename="fix.py")
    assert [f for f in other if f.code == code], \
        f"{code}: a foreign disable code suppressed it"
