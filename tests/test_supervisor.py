"""Elastic multi-host supervisor (ISSUE-7).

Covers: membership liveness with an injectable clock (death within the
heartbeat deadline) and epoch fencing (a stale host cannot rejoin);
the epoch-fenced shrink barrier (dense re-rank, idempotent replay, late
proposers fenced out); `dist.collective` shutdown/re-init returning the
actual (coordinator, world_size, rank); the hung-collective watchdog
converting a stall into a structured `CollectiveTimeoutError` naming the
absent host (value passthrough and exception relay on the happy path);
straggler findings landing in `analysis.runtime_report()`;
`JobSupervisor.stats()` exporting the PR 5 kvstore retry/breaker
counters; the faults JSONL log carrying pid+rank with line-atomic
appends; `parallel.mesh.rebuild` post-shrink; the mxlint
``unsupervised-collective`` AST lint; and the subprocess pod tests —
a SIGKILLed worker detected within the heartbeat deadline with the
stalled round raised as `CollectiveTimeoutError` (no indefinite hang),
and full shrink-and-resume: 3 workers mid-`Module.fit`, one host killed,
survivors shrink to world 2 and resume from the last checkpoint with
final params bit-identical to an uninterrupted 2-worker run resumed from
the same checkpoint, with zero compilations through the unified program
cache.
"""
import json
import os
import re
import shutil
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import resilience
from incubator_mxnet_tpu.base import MXNetError
from incubator_mxnet_tpu.dist.membership import MembershipTable
from incubator_mxnet_tpu.resilience import (CollectiveTimeoutError,
                                            JobSupervisor, StaleEpochError)
from incubator_mxnet_tpu.resilience import supervisor as supmod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean():
    resilience.clear()
    supmod.reset_findings()
    supmod.deactivate()
    yield
    resilience.clear()
    supmod.reset_findings()
    supmod.deactivate()


@pytest.fixture()
def fast_pod(monkeypatch):
    """Pod clocks scaled for CI: death in ~0.6s, watchdog in 2s."""
    monkeypatch.setenv("MXNET_SUPERVISOR_HEARTBEAT_S", "0.1")
    monkeypatch.setenv("MXNET_SUPERVISOR_DEADLINE_S", "0.6")
    monkeypatch.setenv("MXNET_SUPERVISOR_COLLECTIVE_TIMEOUT_S", "2.0")
    monkeypatch.setenv("MXNET_SUPERVISOR_SHRINK_BARRIER_S", "8.0")


# -- membership: liveness, deadline, epoch fence ------------------------------

def test_membership_liveness_and_epoch_fence():
    t = [0.0]
    mt = MembershipTable(3, deadline_s=1.0, clock=lambda: t[0])
    for r in range(3):
        reply = mt.heartbeat(r, 0, step=1, step_time=0.01)
        assert reply["ok"]
    view = reply["view"]
    assert view["alive"] == [0, 1, 2] and view["dead"] == []
    assert view["epoch"] == 0 and view["world_size"] == 3
    # rank 1 goes silent past the deadline: dead in the next view
    t[0] += 0.5
    mt.heartbeat(0, 0)
    mt.heartbeat(2, 0)
    t[0] += 0.6          # rank 1 now 1.1s silent; 0 and 2 only 0.6s
    view = mt.view()
    assert view["dead"] == [1] and view["alive"] == [0, 2]
    assert view["age"][1] > 1.0
    # epoch fence: a heartbeat from a past epoch is rejected, not folded in
    err = mt.heartbeat(1, -1)
    assert "stale epoch" in err["error"]
    # per-host telemetry rides the view
    assert view["steps"][0] >= 1 and view["ewma"][1] == 0.01


def test_shrink_barrier_commits_reranks_and_fences():
    t = [0.0]
    mt = MembershipTable(3, deadline_s=1.0, clock=lambda: t[0])
    for r in range(3):
        mt.heartbeat(r, 0)
    t[0] += 2.0              # everyone stale except who re-beats
    mt.heartbeat(0, 0)
    mt.heartbeat(2, 0)       # rank 1 is dead
    committed = []
    results = {}

    def propose(rank):
        results[rank] = mt.propose_shrink(rank, 0, deadline_s=5.0,
                                          on_commit=committed.append)
    th = threading.Thread(target=propose, args=(2,))
    th.start()
    propose(0)
    th.join(timeout=10)
    assert not th.is_alive()
    res = results[0]
    assert res == results[2]
    assert res["epoch"] == 1 and res["world_size"] == 2
    assert res["survivors"] == [0, 2]
    assert res["rank_map"] == {0: 0, 2: 1}   # dense re-rank, sorted order
    assert len(committed) == 1               # on_commit fired exactly once
    # a resent proposal from a survivor replays the committed result
    assert mt.propose_shrink(2, 0, deadline_s=1.0)["epoch"] == 1
    # the dead host proposing late is fenced, not readmitted
    late = mt.propose_shrink(1, 0, deadline_s=1.0)
    assert "stale epoch" in late.get("error", "")
    # and post-shrink, old-epoch heartbeats are fenced too
    assert "stale epoch" in mt.heartbeat(0, 0)["error"]
    assert mt.heartbeat(0, 1)["ok"]


def test_second_shrink_commits_a_new_epoch():
    """Regression: the pod must survive a SECOND host loss — the next
    shrink barrier must commit a fresh epoch, not instantly replay the
    previous shrink's result (which still contains the newly dead
    host)."""
    t = [0.0]
    mt = MembershipTable(3, deadline_s=1.0, clock=lambda: t[0])
    for r in range(3):
        mt.heartbeat(r, 0)
    t[0] += 2.0
    mt.heartbeat(0, 0)
    mt.heartbeat(1, 0)       # rank 2 dead -> shrink #1 to world 2
    results = {}

    def propose(rank, epoch):
        results[rank] = mt.propose_shrink(rank, epoch, deadline_s=5.0)
    th = threading.Thread(target=propose, args=(1, 0))
    th.start()
    propose(0, 0)
    th.join(timeout=10)
    assert results[0]["epoch"] == 1 and results[0]["world_size"] == 2
    # the new epoch's world: survivors re-heartbeat under new ranks 0, 1
    mt.heartbeat(0, 1)
    mt.heartbeat(1, 1)
    t[0] += 2.0
    mt.heartbeat(0, 1)       # new-rank 1 dead -> shrink #2 to world 1
    # a lone proposer is not a majority of world 2, so the second
    # barrier commits only at its deadline — tick the scripted clock
    # past it while the proposal waits

    def tick():
        for _ in range(100):
            time.sleep(0.01)
            t[0] += 0.1
    tick_th = threading.Thread(target=tick)
    tick_th.start()
    res2 = mt.propose_shrink(0, 1, deadline_s=0.5)
    tick_th.join()
    assert "error" not in res2, res2
    assert res2["epoch"] == 2, "second shrink replayed the first commit"
    assert res2["world_size"] == 1 and res2["survivors"] == [0]


def test_shrink_barrier_deadline_needs_quorum():
    """At the deadline the barrier commits only on a strict proposer
    majority of the hosts still alive: one host with a misfiring
    watchdog must NOT be able to shrink a healthy pod down to itself —
    its proposal fails instead."""
    t = [0.0]
    mt = MembershipTable(2, deadline_s=10.0, clock=lambda: t[0])
    mt.heartbeat(0, 0)
    mt.heartbeat(1, 0)       # alive, healthy, never proposes

    def tick():
        for _ in range(100):
            time.sleep(0.01)
            t[0] += 0.1
    th = threading.Thread(target=tick)
    th.start()
    res = mt.propose_shrink(0, 0, deadline_s=0.5)
    th.join()
    assert "quorum" in res["error"]
    assert mt.epoch == 0     # the pod was NOT shrunk


def test_shrink_barrier_deadline_commits_with_majority():
    """A proposer MAJORITY at the deadline commits, excluding an
    alive-but-wedged host (heartbeating, never proposing) — which is
    then fenced out of the new epoch."""
    t = [0.0]
    mt = MembershipTable(4, deadline_s=10.0, clock=lambda: t[0])
    for r in range(4):
        mt.heartbeat(r, 0)
    results = {}

    def propose(rank):
        results[rank] = mt.propose_shrink(rank, 0, deadline_s=0.5)
    threads = [threading.Thread(target=propose, args=(r,))
               for r in (0, 1, 2)]          # rank 3 wedged: hb only
    for th in threads:
        th.start()

    def tick():
        for _ in range(100):
            time.sleep(0.01)
            t[0] += 0.1
    tick_th = threading.Thread(target=tick)
    tick_th.start()
    for th in threads:
        th.join(timeout=15)
        assert not th.is_alive()
    tick_th.join()
    res = results[0]
    assert res["survivors"] == [0, 1, 2] and res["world_size"] == 3
    # the wedged host is fenced out of the committed epoch
    assert "stale epoch" in mt.propose_shrink(3, 0, 0.5).get("error", "")


def test_epoch_fenced_pull_raises_recoverable_signal(monkeypatch):
    """A pull blocked server-side while a shrink commits is released with
    an epoch-fence error that surfaces as CollectiveTimeoutError — the
    recoverable signal fit's restart loop drives through the fence path —
    not as a generic MXNetError."""
    from incubator_mxnet_tpu.dist.server import ParameterServer
    from incubator_mxnet_tpu.dist.kvstore_dist import KVStoreDist
    from incubator_mxnet_tpu import nd

    srv = ParameterServer(num_workers=2).start()
    for k, v in {"DMLC_PS_ROOT_URI": "127.0.0.1",
                 "DMLC_PS_ROOT_PORT": str(srv.port), "DMLC_RANK": "0",
                 "DMLC_NUM_WORKER": "2",
                 "MXNET_KVSTORE_COLLECTIVE": "0"}.items():
        monkeypatch.setenv(k, v)
    kv = KVStoreDist("dist_sync")
    try:
        srv._state.store["w"] = np.zeros(4, "f4")
        srv._state.version["w"] = 0
        kv._store["w"] = nd.zeros((4,))
        kv.push("w", nd.ones((4,)))   # round needs 2 workers: incomplete

        def commit_soon():
            time.sleep(0.3)
            srv._reset_world({"epoch": 1, "world_size": 1})
        th = threading.Thread(target=commit_soon)
        th.start()
        out = nd.zeros((4,))
        with pytest.raises(CollectiveTimeoutError, match="epoch fenced"):
            kv.pull("w", out=out)     # waiting when the commit lands
        th.join()
    finally:
        kv.close(send_stop=False)
        srv.shutdown()


# -- dist.collective: shutdown / re-init (satellite) --------------------------

def test_collective_returns_group_tuple_and_reinitializes():
    from incubator_mxnet_tpu.dist import collective

    collective.shutdown()    # clean slate whatever ran before
    g = collective.init_process_group(num_processes=1, process_id=0)
    assert g == (g[0], 1, 0) and isinstance(g[0], str)
    assert collective.initialized() and collective.group() == g
    # idempotent while live: the SAME group comes back
    assert collective.init_process_group(num_processes=1) == g
    # shutdown -> re-init at a "different world" (still 1 process on CPU,
    # but the state machine is the shrink path's)
    collective.shutdown()
    assert not collective.initialized() and collective.group() is None
    g2 = collective.init_process_group(
        coordinator="127.0.0.1:7777", num_processes=1, process_id=0)
    assert g2 == ("127.0.0.1:7777", 1, 0)
    collective.shutdown()
    # historical alias still works
    assert collective.finalize is collective.shutdown


# -- watchdog -----------------------------------------------------------------

def test_watchdog_passthrough_error_relay_and_timeout(fast_pod):
    from incubator_mxnet_tpu.dist.server import ParameterServer

    srv = ParameterServer(num_workers=2).start()
    s0 = JobSupervisor(0, 2, host="127.0.0.1", port=srv.port).start()
    s1 = JobSupervisor(1, 2, host="127.0.0.1", port=srv.port).start()
    try:
        # passthrough: value and exceptions of the wrapped fn
        assert s0.collective("noop", lambda: 41 + 1) == 42
        with pytest.raises(ValueError, match="boom"):
            s0.collective("err", lambda: (_ for _ in ()).throw(
                ValueError("boom")))
        # kill host 1's heartbeats; detection within the deadline
        s1.stop()
        t0 = time.monotonic()
        while 1 not in (s0.view() or {}).get("dead", ()):
            assert time.monotonic() - t0 < 3.0, \
                "host death not detected within the deadline"
            time.sleep(0.05)
        # a hung collective raises a STRUCTURED timeout naming the host
        s0.record_step(0.01)
        with pytest.raises(CollectiveTimeoutError,
                           match=r"kvstore\.pull.*host\(s\) \[1\] failed "
                                 r"to arrive") as err:
            s0.collective("kvstore.pull", lambda: time.sleep(60),
                          axis="workers", timeout=0.4)
        assert err.value.absent == [1]
        assert err.value.collective == "kvstore.pull"
        assert err.value.axis == "workers"
        stats = s0.stats()
        assert stats["collective_timeouts"] == 1
        assert stats["hosts_lost"] == 1
        # the host loss landed as a runtime finding too
        from incubator_mxnet_tpu import analysis
        codes = analysis.runtime_report().by_code()
        assert codes.get("host-lost", 0) >= 1
    finally:
        s0.stop()
        s1.stop()
        srv.shutdown()


def test_injected_hang_fault_trips_the_watchdog(fast_pod):
    """The collective.dispatch:hang fault site stalls INSIDE the
    dispatched collective — the deterministic stand-in for a lost host's
    stall — and the watchdog must convert it."""
    from incubator_mxnet_tpu.dist.server import ParameterServer

    resilience.inject("collective.dispatch", "hang", at=1)
    srv = ParameterServer(num_workers=1).start()
    sup = JobSupervisor(0, 1, host="127.0.0.1", port=srv.port).start()
    try:
        with pytest.raises(CollectiveTimeoutError, match="allreduce"):
            sup.collective("allreduce", lambda: 1, timeout=0.3)
        assert [e["kind"] for e in resilience.trace()
                if e["event"] == "fault"] == ["hang"]
        # the NEXT collective is unaffected (at=1 fired once)
        assert sup.collective("allreduce", lambda: 7, timeout=5.0) == 7
    finally:
        sup.stop()
        srv.shutdown()


def test_fenced_supervisor_refuses_collectives():
    sup = JobSupervisor(0, 2, host="127.0.0.1", port=1)   # never started
    sup._fenced = True
    with pytest.raises(StaleEpochError, match="fenced"):
        sup.collective("x", lambda: 1)


# -- straggler detection ------------------------------------------------------

def test_straggler_finding_lands_in_runtime_report():
    sup = JobSupervisor(0, 4, host="127.0.0.1", port=1, straggler_k=2.0)
    # a pod view where rank 3's EWMA diverges far beyond k*sigma
    sup._on_view({"epoch": 0, "alive": [0, 1, 2, 3], "dead": [],
                  "age": {}, "steps": {},
                  "ewma": {0: 0.100, 1: 0.101, 2: 0.099, 3: 0.400}})
    assert sup.stats()["stragglers_flagged"] == 1
    from incubator_mxnet_tpu import analysis
    report = analysis.runtime_report()
    strag = [f for f in report if f.code == "straggler-host"]
    assert len(strag) == 1 and "rank 3" in strag[0].message
    assert "sigma" in strag[0].message
    # repeats dedupe into the count, not new findings
    sup._stragglers.clear()
    sup._on_view({"epoch": 0, "alive": [0, 1, 2, 3], "dead": [],
                  "age": {}, "steps": {},
                  "ewma": {0: 0.1, 1: 0.1, 2: 0.1, 3: 0.5}})
    strag = [f for f in analysis.runtime_report()
             if f.code == "straggler-host"]
    assert len(strag) == 1 and strag[0].count == 2
    # a uniform pod flags nothing
    sup2 = JobSupervisor(0, 4, host="127.0.0.1", port=1)
    sup2._on_view({"epoch": 0, "alive": [0, 1], "dead": [], "age": {},
                   "steps": {}, "ewma": {0: 0.1, 1: 0.100001}})
    assert sup2.stats()["stragglers_flagged"] == 0


# -- stats export (satellite) -------------------------------------------------

def test_stats_exports_kvstore_retry_breaker_counters(monkeypatch):
    from incubator_mxnet_tpu.dist.server import ParameterServer
    from incubator_mxnet_tpu.dist.kvstore_dist import KVStoreDist
    from incubator_mxnet_tpu import nd

    srv = ParameterServer(num_workers=1).start()
    for k, v in {"DMLC_PS_ROOT_URI": "127.0.0.1",
                 "DMLC_PS_ROOT_PORT": str(srv.port), "DMLC_RANK": "0",
                 "DMLC_NUM_WORKER": "1",
                 "MXNET_KVSTORE_COLLECTIVE": "0"}.items():
        monkeypatch.setenv(k, v)
    kv = KVStoreDist("dist_sync")
    try:
        kv.init("w", nd.ones((4,)))
        ks = kv.stats()
        assert ks["resends"] == 0 and ks["discarded_stale"] == 0
        assert ks["breakers"][0]["state"] == "closed"
        assert ks["breakers"][0]["server"] == 0
        sup = JobSupervisor.for_kvstore(kv)
        stats = sup.stats()
        assert stats["kvstore"]["breakers"][0]["state"] == "closed"
        assert stats["rank"] == 0 and stats["world_size"] == 1
    finally:
        kv.close()
        srv.shutdown()


# -- faults log: rank + pid, line-atomic appends (satellite) ------------------

def test_faults_log_carries_rank_pid_and_is_line_atomic(tmp_path,
                                                        monkeypatch):
    log = tmp_path / "faults.jsonl"
    monkeypatch.setenv("DMLC_RANK", "3")
    monkeypatch.setenv("MXNET_FAULTS_LOG", str(log))
    resilience.configure("demo.site:slow(ms=0,n=64)")
    # re-read the env log path (configure keeps clauses, not the path);
    # the writes themselves ride the shared obs.jsonl_sink, which
    # opens the fd for this fresh path on first append
    from incubator_mxnet_tpu.resilience import faults as _faults
    monkeypatch.setattr(_faults, "_log_path", str(log))
    threads = [threading.Thread(
        target=lambda: [resilience.fire("demo.site") for _ in range(8)])
        for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    lines = log.read_text().strip().splitlines()
    assert len(lines) == 32
    for line in lines:
        event = json.loads(line)   # every line parses: no interleaving
        assert event["rank"] == 3
        assert event["pid"] == os.getpid()
        assert event["site"] == "demo.site"


# -- mesh rebuild -------------------------------------------------------------

def test_mesh_rebuild_spans_current_world():
    from incubator_mxnet_tpu import parallel

    mesh = parallel.rebuild()
    assert mesh.axis_names == ("dp",)
    assert mesh.size == 8          # the test harness's virtual mesh
    capped = parallel.rebuild(per_host=2)
    assert capped.size == 2


# -- mxlint: unsupervised-collective (satellite) ------------------------------

def test_mxlint_flags_unsupervised_collective():
    from incubator_mxnet_tpu import analysis

    src = (
        "from incubator_mxnet_tpu import parallel\n"
        "def step(bucket):\n"
        "    return parallel.collectives.all_reduce(bucket, 'dp')\n")
    report = analysis.check_source(src, filename="train.py")
    codes = report.by_code()
    assert codes.get("unsupervised-collective") == 1
    finding = [f for f in report
               if f.code == "unsupervised-collective"][0]
    assert "train.py:3" in finding.location
    assert "supervised" in finding.message


def test_mxlint_unsupervised_collective_respects_scopes():
    from incubator_mxnet_tpu import analysis

    # a with-scope naming the supervisor/watchdog is supervised
    src_with = (
        "def step(sup, bucket):\n"
        "    with sup.watchdog('allreduce'):\n"
        "        return coll.all_reduce(bucket, 'dp')\n")
    # the supervised(...) wrapper's own arguments are the supervised scope
    src_wrap = (
        "def step(bucket):\n"
        "    return collectives.supervised('g', lambda: "
        "coll.all_reduce(bucket, 'dp'))\n")
    # in-graph (jitted) collectives are XLA's business
    src_jit = (
        "import jax\n"
        "@jax.jit\n"
        "def step(bucket):\n"
        "    return coll.all_reduce(bucket, 'dp')\n")
    # suppression comment
    src_supp = ("def step(b):\n"
                "    return coll.all_reduce(b, 'dp')"
                "  # mxlint: disable=unsupervised-collective\n")
    for src in (src_with, src_wrap, src_jit, src_supp):
        assert analysis.check_source(src).by_code().get(
            "unsupervised-collective") is None, src
    # a name that SAYS it is not supervised must not silence the lint
    src_unsup = ("def step(b):\n"
                 "    return run_unsupervised(lambda: "
                 "plane.allreduce(b))\n")
    assert analysis.check_source(src_unsup).by_code().get(
        "unsupervised-collective") == 1


# -- subprocess pod tests -----------------------------------------------------

MEMBER_WORKER = r"""
import os, sys, time
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd
from incubator_mxnet_tpu.resilience import (CollectiveTimeoutError,
                                            JobSupervisor)
from incubator_mxnet_tpu.resilience import supervisor as supmod

rank = int(os.environ["DMLC_RANK"])
kv = mx.kv.create("dist_sync")
sup = JobSupervisor.for_kvstore(kv).start()
supmod.activate(sup)
kv.init("w", nd.zeros((4,)))
kv.push("w", nd.ones((4,)))
out = nd.zeros((4,))
kv.pull("w", out=out)
assert out.asnumpy()[0] == kv.num_workers

if rank == 1:
    # die without unwinding: the SIGKILL'd-host stand-in
    os._exit(137)

# rank 0: the peer is gone — detection must land within the heartbeat
# deadline (+ one beat + scheduling slack)
t0 = time.monotonic()
deadline = float(os.environ["MXNET_SUPERVISOR_DEADLINE_S"])
while 1 not in (sup.view() or {}).get("dead", ()):
    assert time.monotonic() - t0 < deadline + 2.0, "death not detected"
    time.sleep(0.05)
print("DETECTED %.3f" % (time.monotonic() - t0))

# the next sync round can never complete: the watchdog must convert the
# stall into a structured error naming the absent host
sup.record_step(0.01)
kv.push("w", nd.ones((4,)))
try:
    kv.pull("w", out=out)
    print("NO_TIMEOUT")
except CollectiveTimeoutError as e:
    assert e.absent == [1], e.absent
    assert e.collective == "kvstore.pull"
    print("TIMEOUT_OK " + str(e)[:120])
sup.stop()
kv.close(send_stop=False)
print("worker %d OK" % rank)
"""


def test_killed_worker_detected_and_hung_round_raises(tmp_path, fast_pod,
                                                      monkeypatch):
    """Two real worker processes: SIGKILL one mid-run — the survivor's
    membership view marks it dead within the heartbeat deadline, and the
    stalled sync round raises CollectiveTimeoutError naming the absent
    host instead of hanging (the acceptance gate's detection half)."""
    from incubator_mxnet_tpu.dist.server import ParameterServer

    script = tmp_path / "member_worker.py"
    script.write_text(MEMBER_WORKER)
    server = ParameterServer(num_workers=2).start()
    env = dict(os.environ,
               DMLC_PS_ROOT_URI="127.0.0.1",
               DMLC_PS_ROOT_PORT=str(server.port),
               DMLC_NUM_WORKER="2", DMLC_ROLE="worker",
               MXNET_KVSTORE_COLLECTIVE="0",
               MXNET_SUPERVISOR_HEARTBEAT_S="0.1",
               MXNET_SUPERVISOR_DEADLINE_S="0.8",
               MXNET_SUPERVISOR_COLLECTIVE_TIMEOUT_S="2.5",
               JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH",
                                                             ""))
    procs = [subprocess.Popen([sys.executable, str(script)],
                              env=dict(env, DMLC_RANK=str(r)),
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT)
             for r in range(2)]
    outs = [p.communicate(timeout=180)[0].decode() for p in procs]
    server.shutdown()
    assert procs[1].returncode == 137
    assert procs[0].returncode == 0, outs[0]
    assert "worker 0 OK" in outs[0]
    m = re.search(r"DETECTED ([\d.]+)", outs[0])
    assert m, outs[0]
    assert float(m.group(1)) <= 0.8 + 2.0, "detection exceeded deadline"
    assert "TIMEOUT_OK" in outs[0] and "NO_TIMEOUT" not in outs[0]
    assert "failed to arrive" in outs[0]


# the worker subprocess body is tools/pod_worker.py — ONE copy shared
# with the run_chaos --pod schedules so this acceptance gate and the
# chaos artifact exercise the identical protocol
POD_WORKER_PATH = os.path.join(REPO, "tools", "pod_worker.py")


def _run_fit_pod(server_port, n_workers, ckpt_dir, faults_by_rank=None,
                 resume=False):
    env = dict(os.environ,
               DMLC_PS_ROOT_URI="127.0.0.1",
               DMLC_PS_ROOT_PORT=str(server_port),
               DMLC_NUM_WORKER=str(n_workers), DMLC_ROLE="worker",
               MXNET_KVSTORE_COLLECTIVE="0",
               MXNET_SUPERVISOR_HEARTBEAT_S="0.1",
               MXNET_SUPERVISOR_DEADLINE_S="0.8",
               MXNET_SUPERVISOR_COLLECTIVE_TIMEOUT_S="2.5",
               MXNET_SUPERVISOR_SHRINK_BARRIER_S="10.0",
               MXNET_PS_RECONNECT_WAIT="1.0",
               POD_CKPT_DIR=str(ckpt_dir),
               POD_RESUME="1" if resume else "0",
               JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH",
                                                             ""))
    env.pop("MXNET_FAULTS", None)
    env.pop("MXNET_SUPERVISOR_EPOCH", None)
    procs = []
    for r in range(n_workers):
        wenv = dict(env, DMLC_RANK=str(r))
        spec = (faults_by_rank or {}).get(r)
        if spec:
            wenv["MXNET_FAULTS"] = spec
        procs.append(subprocess.Popen([sys.executable, POD_WORKER_PATH],
                                      env=wenv, stdout=subprocess.PIPE,
                                      stderr=subprocess.STDOUT))
    outs = [p.communicate(timeout=240)[0].decode() for p in procs]
    return procs, outs


def _sha(out):
    m = re.search(r"PARAMS_SHA (\w+)", out)
    return m.group(1) if m else None


def test_pod_kill_shrink_resume_bit_identical(tmp_path, fast_pod):
    """THE acceptance gate: 3 workers mid-`Module.fit`, one host
    SIGKILLed (host.step:kill) — survivors detect the loss, convert the
    stalled round into CollectiveTimeoutError (no indefinite hang),
    shrink the pod to world 2 via the epoch-fenced barrier, and resume
    from the last committed checkpoint; final params are bit-identical
    to an uninterrupted 2-worker run resumed from that same checkpoint,
    and the run performs zero compilations through the unified program
    cache."""
    from incubator_mxnet_tpu.dist.server import ParameterServer

    # phase 1 — chaos: rank 2 dies at its 4th step
    ckpt = tmp_path / "ckpts"
    server = ParameterServer(num_workers=3).start()
    procs, outs = _run_fit_pod(
        server.port, 3, ckpt,
        faults_by_rank={2: "seed=22;host.step:kill(at=4)"})
    server.shutdown()
    assert procs[2].returncode == 137          # the killed host
    for r in (0, 1):
        assert procs[r].returncode == 0, outs[r]
        assert "worker OK" in outs[r]
        assert "pod shrunk to world_size=2" in outs[r], outs[r]
        assert "COMPILES 0" in outs[r]
    chaos_shas = {_sha(outs[0]), _sha(outs[1])}
    assert len(chaos_shas) == 1 and None not in chaos_shas
    # the survivors' supervisors ended at epoch 1, world 2
    sup_stats = [json.loads(re.search(r"SUPSTATS (.*)", o).group(1))
                 for o in outs[:2]]
    assert all(s["epoch"] == 1 and s["world_size"] == 2
               for s in sup_stats)
    # phase 2 — control: an uninterrupted 2-worker run resumed from the
    # SAME checkpoint the survivors resumed from.  The chaos run's
    # post-shrink snapshots have higher steps; prune back to the resume
    # point (parsed from the survivors' own resume log line).
    m = re.search(r"resuming from .*\(step (\d+),", outs[0])
    assert m, outs[0]
    resume_step = int(m.group(1))
    control = tmp_path / "control"
    shutil.copytree(ckpt, control)
    for entry in os.listdir(control):
        cm = re.match(r"ckpt-(\d+)$", entry)
        if cm and int(cm.group(1)) > resume_step:
            shutil.rmtree(control / entry)
    server = ParameterServer(num_workers=2).start()
    cprocs, couts = _run_fit_pod(server.port, 2, control, resume=True)
    server.shutdown()
    for r in (0, 1):
        assert cprocs[r].returncode == 0, couts[r]
    control_shas = {_sha(couts[0]), _sha(couts[1])}
    assert len(control_shas) == 1 and None not in control_shas
    assert control_shas == chaos_shas, \
        "shrink-and-resume diverged from a clean resume at world 2"
