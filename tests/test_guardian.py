"""Training guardian: in-graph health word, skip/rollback/quarantine
(ISSUE-10).

Covers: the fused step's in-graph health word observes every step with
no per-step host sync; an injected non-finite gradient is refused
in-graph (skip-batch) and two identical seeded runs end bit-identical —
while the same injection WITHOUT the guardian poisons the parameters;
an injected loss spike triggers rollback-to-last-good and the recovered
run ends bit-identical to a clean reference over the same schedule with
zero program-cache compiles during recovery; checkpoints carry a
``health`` stamp and `latest_healthy` honors stamp + max_step; the
consecutive-failure budget escalates to `TrainingDivergedError` naming
step/signal/shard; quarantined positions are skipped on resume;
multi-worker health bits agree through a kvstore-style reduction; the
RecordIO reader skips torn tails and magic mismatches with a
`corrupt_records` count instead of raising; the `corrupt` fault kind
bit-flips payloads deterministically through `faults.mutate`; the
image iterator quarantines corrupt records and never re-reads them;
guardian events surface in `analysis.runtime_report()`; and the
`nan-swallow` mxlint AST lint flags hand-rolled catch-and-continue
training loops.
"""
import json
import os
import struct

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import analysis, config, io, recordio, sym
from incubator_mxnet_tpu import compile as mxcompile
from incubator_mxnet_tpu.resilience import (RollbackRequested,
                                            TrainingDivergedError,
                                            TrainingGuardian, faults)
from incubator_mxnet_tpu.resilience.guardian import QuarantineLog


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()
    analysis.reset_runtime()


@pytest.fixture()
def fast_guardian(monkeypatch):
    monkeypatch.setenv("MXNET_GUARDIAN_INTERVAL", "4")
    monkeypatch.setenv("MXNET_GUARDIAN_SPIKE_WINDOW", "4")


def _model(seed=0):
    np.random.seed(seed)
    mx.random.seed(seed)
    net = sym.Variable("data")
    net = sym.FullyConnected(net, num_hidden=16, name="fc1")
    net = sym.Activation(net, act_type="tanh")
    net = sym.FullyConnected(net, num_hidden=4, name="fc2")
    net = sym.SoftmaxOutput(net, name="softmax")
    return mx.mod.Module(net, context=mx.cpu())


def _data(n=128, bs=8):
    rng = np.random.RandomState(3)
    x = rng.standard_normal((n, 10)).astype("float32")
    y = rng.randint(0, 4, n).astype("float32")
    return io.NDArrayIter(x, y, batch_size=bs, shuffle=False)


def _fit(mod, ckpt=None, n=128, num_epoch=2, resume=False):
    mod.fit(_data(n=n), num_epoch=num_epoch, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05}, eval_metric="acc",
            initializer=mx.initializer.Xavier(),
            checkpoint_dir=ckpt, checkpoint_period=4, resume=resume)
    return mod


def _sha(mod):
    import hashlib
    args, auxs = mod.get_params()
    h = hashlib.sha256()
    for k in sorted(args):
        h.update(args[k].asnumpy().tobytes())
    for k in sorted(auxs):
        h.update(auxs[k].asnumpy().tobytes())
    return h.hexdigest()


# -- in-graph health word ------------------------------------------------------

def test_guardian_observes_every_step_without_fault():
    mod = _fit(_model())
    g = mod._guardian
    assert g is not None
    st = g.stats()
    assert st["steps_observed"] == 32          # 128/8 batches x 2 epochs
    assert st["skips"] == st["spikes"] == st["rollbacks"] == 0
    fs = mod._fused_step
    assert fs is not None and not fs.broken and fs._guard


def test_skip_batch_deterministic(fast_guardian):
    def run():
        faults.configure("seed=7;grad.nonfinite:error(at=5)")
        mod = _fit(_model())
        st = mod._guardian.stats()
        faults.clear()
        return _sha(mod), st

    sha1, st1 = run()
    sha2, st2 = run()
    assert st1["skips"] == 1 and st1["injected_nonfinite"] == 1
    assert st1["quarantined"] == 1
    assert sha1 == sha2
    # the update really was refused: every parameter stays finite
    faults.configure("seed=7;grad.nonfinite:error(at=5)")
    mod = _fit(_model())
    for name, arr in mod.get_params()[0].items():
        assert np.isfinite(arr.asnumpy()).all(), name


def test_nan_batch_guardian_on_vs_off(monkeypatch):
    """The contrast claim: a NaN batch without the guardian poisons the
    parameters; with it (default) the update is refused and params stay
    finite."""
    def run_with_nan_batch():
        mod = _model()
        it = _data(n=32)
        batch = next(iter(it))
        bad = io.DataBatch(
            data=[mx.nd.array(np.full((8, 10), np.nan, np.float32))],
            label=batch.label, pad=0, provide_data=batch.provide_data,
            provide_label=batch.provide_label)
        mod.fit(_NanIter(it, bad), num_epoch=1, optimizer="sgd",
                optimizer_params={"learning_rate": 0.05},
                eval_metric="acc", initializer=mx.initializer.Xavier())
        return [a.asnumpy()
                for a in mod.get_params()[0].values()]

    class _NanIter(io.DataIter):
        def __init__(self, inner, bad):
            super().__init__(inner.batch_size)
            self._inner, self._bad, self._i = inner, bad, 0

        @property
        def provide_data(self):
            return self._inner.provide_data

        @property
        def provide_label(self):
            return self._inner.provide_label

        def reset(self):
            self._inner.reset()
            self._i = 0

        def next(self):
            self._i += 1
            nxt = self._inner.next()
            return self._bad if self._i == 2 else nxt

    vals_on = run_with_nan_batch()
    assert all(np.isfinite(v).all() for v in vals_on)
    monkeypatch.setenv("MXNET_GUARDIAN", "0")
    vals_off = run_with_nan_batch()
    assert not all(np.isfinite(v).all() for v in vals_off)


def test_guarded_matches_unguarded_numerics(monkeypatch):
    """The health word + conditional update must not change healthy
    training: guardian on vs off, same seed, bit-identical params."""
    sha_on = _sha(_fit(_model()))
    monkeypatch.setenv("MXNET_GUARDIAN", "0")
    sha_off = _sha(_fit(_model()))
    assert sha_on == sha_off


# -- rollback ------------------------------------------------------------------

def test_spike_rollback_bit_identical(tmp_path, fast_guardian):
    ck_a = str(tmp_path / "ck-spike")
    ck_b = str(tmp_path / "ck-ref")
    # warm the scan AND 1-step programs: the post-rollback resume trains
    # a partial block (the quarantine break), and the zero-compile claim
    # below covers recovery, not first-of-process cold compiles
    _fit(_model(), n=128, num_epoch=1)
    os.environ["MXNET_FUSED_STEP_BLOCK"] = "1"
    try:
        _fit(_model(), n=32, num_epoch=1)
    finally:
        os.environ.pop("MXNET_FUSED_STEP_BLOCK", None)

    faults.configure("seed=7;loss.spike:error(at=10)")
    c0 = mxcompile.stats()["counters"]["compiles"]
    mod = _fit(_model(), ck_a)
    st = mod._guardian.stats()
    compiles_during_recovery = mxcompile.stats()["counters"]["compiles"] - c0
    faults.clear()
    assert st["rollbacks"] == 1 and st["spikes"] == 1
    assert st["quarantined"] >= 1
    assert compiles_during_recovery == 0

    # clean reference: same schedule, no fault, same quarantined window
    os.makedirs(ck_b)
    q = (tmp_path / "ck-spike" / "quarantine.jsonl").read_text()
    (tmp_path / "ck-ref" / "quarantine.jsonl").write_text(q)
    ref = _fit(_model(), ck_b)
    assert _sha(mod) == _sha(ref)
    assert ref._guardian.stats()["rollbacks"] == 0


def test_health_stamp_in_manifest(tmp_path):
    from incubator_mxnet_tpu import checkpoint as ckpt
    mod = _fit(_model(), str(tmp_path / "ck"))
    path = ckpt.latest(str(tmp_path / "ck"))
    manifest = ckpt.manifest.read_manifest(path)
    health = manifest["meta"]["health"]
    assert health["status"] == "healthy"
    assert health["rollbacks"] == 0


def test_latest_healthy_selection(tmp_path):
    from incubator_mxnet_tpu import checkpoint as ckpt
    root = str(tmp_path / "ck")
    for step, status in ((4, "healthy"), (8, "healthy"), (12, "suspect")):
        mgr = ckpt.CheckpointManager(root, async_snapshots=False)
        mgr.snapshot(arrays={"arg:w": np.zeros(2, np.float32)}, step=step,
                     meta={"health": {"status": status}})
        mgr.close()
    assert ckpt.latest(root).endswith("%010d" % 12)
    assert ckpt.latest_healthy(root).endswith("%010d" % 8)
    assert ckpt.latest_healthy(root, max_step=7).endswith("%010d" % 4)
    assert ckpt.latest_healthy(root, max_step=3) is None


def test_rollback_without_checkpoint_dir_does_not_raise(monkeypatch,
                                                        fast_guardian):
    """No checkpoint_dir -> no rollback rung: the spike is reported as
    an unrecoverable finding and training continues."""
    monkeypatch.setenv("MXNET_GUARDIAN_MAX_FAILURES", "100")
    faults.configure("seed=7;loss.spike:error(at=10)")
    mod = _fit(_model())
    st = mod._guardian.stats()
    assert st["spikes"] >= 1 and st["rollbacks"] == 0
    codes = {f.code for f in analysis.runtime_report().findings}
    assert "spike-unrecoverable" in codes


# -- divergence budget ---------------------------------------------------------

def test_divergence_budget_names_step_and_shard(monkeypatch,
                                                fast_guardian):
    monkeypatch.setenv("MXNET_GUARDIAN_MAX_FAILURES", "2")
    faults.configure("seed=7;grad.nonfinite:error(at=3-12)")
    with pytest.raises(TrainingDivergedError) as exc:
        _fit(_model())
    err = exc.value
    assert err.step > 0
    assert "ndarray[" in str(err)          # shard attribution
    assert "MXNET_GUARDIAN_MAX_FAILURES" in str(err)


def test_rollback_budget_escalates(tmp_path, monkeypatch, fast_guardian):
    monkeypatch.setenv("MXNET_GUARDIAN_MAX_ROLLBACKS", "0")
    faults.configure("seed=7;loss.spike:error(at=10)")
    with pytest.raises(TrainingDivergedError, match="rollback"):
        _fit(_model(), str(tmp_path / "ck"))


# -- quarantine ----------------------------------------------------------------

def test_quarantine_skipped_on_resume(tmp_path, fast_guardian):
    ck = str(tmp_path / "ck")
    faults.configure("seed=7;grad.nonfinite:error(at=5)")
    mod = _fit(_model(), ck, num_epoch=1)
    faults.clear()
    entries = QuarantineLog(os.path.join(ck, "quarantine.jsonl")).load()
    assert len(entries) == 1 and entries[0]["reason"] == "nonfinite"
    pos = (entries[0]["epoch"], entries[0]["nbatch"])
    # resume for a second epoch: the guardian loads the quarantine and
    # the position is skip-listed from the start
    mod2 = _fit(_model(), ck, num_epoch=2, resume=True)
    g = mod2._guardian
    assert g.should_skip(*pos)
    assert g.stats()["skips"] == 0             # no new skips needed


def test_quarantine_log_multiprocess_format(tmp_path):
    log = QuarantineLog(str(tmp_path / "q.jsonl"))
    log.append(reason="nonfinite", epoch=0, nbatch=3, step=4)
    log.append(reason="corrupt_record", source="x.rec", record=17)
    log.close()
    lines = (tmp_path / "q.jsonl").read_text().splitlines()
    assert len(lines) == 2
    assert all("pid" in json.loads(l) for l in lines)
    log2 = QuarantineLog(str(tmp_path / "q.jsonl"))
    assert log2.batch_positions() == {(0, 3)}
    assert log2.records("x.rec") == {17}


# -- multi-worker agreement ----------------------------------------------------

class _StubKV:
    """kvstore-shaped shared store: push sums, pull reads (the dist
    server's aggregation contract for the guardian's health key)."""

    num_workers = 2

    def __init__(self, store):
        self._store = store

    def init(self, key, value):
        self._store.setdefault(key, np.zeros_like(value.asnumpy()))

    def push(self, key, value):
        self._store[key] = self._store[key] + value.asnumpy()

    def pull(self, key, out):
        from incubator_mxnet_tpu import nd
        out._set_data(nd.array(self._store[key])._data)


def test_multi_worker_agreement():
    store = {}
    g_bad = TrainingGuardian(interval=4, window=4)
    g_ok = TrainingGuardian(interval=4, window=4)
    g_bad._wire_kvstore(_StubKV(store))
    g_ok._wire_kvstore(_StubKV(store))
    # worker A diagnosed a spike at step 9; worker B saw a clean window
    agreed_bad = g_bad._agree(np.asarray([0, 1, 9], np.float64))
    agreed_ok = g_ok._agree(np.asarray([0, 0, 0], np.float64))
    assert agreed_bad[1] >= 1 and agreed_ok[1] >= 1
    assert agreed_ok[2] == 9                   # adopts the peer's step
    assert agreed_bad[2] == 9
    # the store SUMS across polls: a later clean window must not replay
    # the old verdict (decisions are taken on deltas)
    again = g_ok._agree(np.asarray([0, 0, 0], np.float64))
    assert again[0] == 0 and again[1] == 0


def test_agreement_degrades_to_local():
    g = TrainingGuardian(interval=4, window=4)

    def broken(vec):
        raise ConnectionError("store down")

    g._allreduce = broken
    local = np.asarray([1, 0, 0], np.float64)
    assert (g._agree(local) == local).all()
    assert g.stats()["sync_degraded"] == 1


# -- recordio corruption tolerance ---------------------------------------------

def _write_rec(path, payloads):
    w = recordio.MXRecordIO(str(path), "w")
    for p in payloads:
        w.write(p)
    w.close()


def test_recordio_torn_tail_skips_not_raises(tmp_path):
    rec = tmp_path / "t.rec"
    _write_rec(rec, [b"a" * 40, b"b" * 40, b"c" * 40])
    raw = rec.read_bytes()
    rec.write_bytes(raw[:-25])                 # torn mid-payload
    r = recordio.MXRecordIO(str(rec), "r")
    assert r.read() == b"a" * 40
    assert r.read() == b"b" * 40
    assert r.read() is None                    # torn tail -> EOF, no raise
    assert r.corrupt_records == 1
    r.close()


def test_recordio_short_header_tail(tmp_path):
    rec = tmp_path / "h.rec"
    _write_rec(rec, [b"x" * 16])
    rec.write_bytes(rec.read_bytes() + b"\x0a\xd7")   # 2 stray bytes
    r = recordio.MXRecordIO(str(rec), "r")
    assert r.read() == b"x" * 16
    assert r.read() is None
    assert r.corrupt_records == 1
    r.close()


def test_recordio_magic_mismatch_resyncs(tmp_path):
    rec = tmp_path / "m.rec"
    _write_rec(rec, [b"a" * 40, b"b" * 40, b"c" * 40])
    raw = bytearray(rec.read_bytes())
    raw[48] ^= 0xFF                            # damage record 2's magic
    rec.write_bytes(bytes(raw))
    r = recordio.MXRecordIO(str(rec), "r")
    got = []
    while True:
        rec_bytes = r.read()
        if rec_bytes is None:
            break
        got.append(rec_bytes)
    assert b"a" * 40 in got                    # before the damage
    assert b"c" * 40 in got                    # resynced past it
    assert r.corrupt_records >= 1
    r.close()


def test_recordio_quarantine_feed(tmp_path):
    rec = tmp_path / "q.rec"
    _write_rec(rec, [b"a" * 40])
    rec.write_bytes(rec.read_bytes()[:-20])
    log = QuarantineLog(str(tmp_path / "q.jsonl"))
    r = recordio.MXRecordIO(str(rec), "r")
    r.set_quarantine(log)
    assert r.read() is None
    r.close()
    entries = log.load()
    assert entries and entries[0]["reason"] == "corrupt_record"
    assert entries[0]["source"] == str(rec)


def test_indexed_read_never_returns_wrong_record(tmp_path):
    """`read_idx` must not leak the resync: a damaged record returns
    None (and quarantines its id) rather than the NEXT record's payload
    — a misaligned sample/label pair would be silent data corruption."""
    rec = tmp_path / "ix.rec"
    w = recordio.MXIndexedRecordIO(str(tmp_path / "ix.idx"), str(rec), "w")
    for i in range(3):
        w.write_idx(i, bytes([65 + i]) * 40)
    w.close()
    raw = bytearray(rec.read_bytes())
    raw[48] ^= 0xFF                            # record 1's magic
    rec.write_bytes(bytes(raw))
    log = QuarantineLog(str(tmp_path / "q.jsonl"))
    r = recordio.MXIndexedRecordIO(str(tmp_path / "ix.idx"), str(rec), "r")
    r.set_quarantine(log)
    assert r.read_idx(0) == b"A" * 40
    assert r.read_idx(1) is None               # damaged: NOT record 2
    assert r.read_idx(2) == b"C" * 40
    r.close()
    assert 1 in log.records(str(rec))


def test_index_records_tolerant(tmp_path):
    from incubator_mxnet_tpu.image import _index_records_tolerant
    rec = tmp_path / "i.rec"
    _write_rec(rec, [b"a" * 40, b"b" * 40, b"c" * 40])
    raw = rec.read_bytes()
    records, corrupt = _index_records_tolerant(raw)
    assert len(records) == 3 and corrupt == 0
    records, corrupt = _index_records_tolerant(raw[:-25])
    assert len(records) == 2 and corrupt == 1


# -- the corrupt fault kind ----------------------------------------------------

def test_corrupt_kind_fires_through_mutate_only():
    faults.configure("seed=5;io.corrupt_record:corrupt(at=2)")
    payload = bytes(range(64)) * 4
    # fire() ignores corrupt clauses entirely (no payload to damage)
    faults.fire("io.corrupt_record")
    assert faults.trace() == []
    a = faults.mutate("io.corrupt_record", payload)
    b = faults.mutate("io.corrupt_record", payload)
    assert a == payload and b != payload       # fires on the 2nd mutate
    assert len(b) == len(payload)
    assert faults.trace()[-1]["kind"] == "corrupt"
    # deterministic: the same seeded schedule flips the same bytes
    faults.reset()
    faults.mutate("io.corrupt_record", payload)
    assert faults.mutate("io.corrupt_record", payload) == b


def test_corrupt_kind_args():
    faults.configure("seed=5;io.corrupt_record:corrupt(at=1,bytes=1,"
                     "offset=0)")
    out = faults.mutate("io.corrupt_record", b"\x00" * 8)
    assert out != b"\x00" * 8
    assert out[1:] == b"\x00" * 7              # only byte 0 flipped


def test_image_iter_corrupt_record_quarantined(tmp_path):
    cv2 = pytest.importorskip("cv2")
    from incubator_mxnet_tpu.image import ImageRecordIterImpl
    rec = str(tmp_path / "c.rec")
    rng = np.random.RandomState(0)
    w = recordio.MXRecordIO(rec, "w")
    for i in range(12):
        ok, enc = cv2.imencode(
            ".png", rng.randint(0, 255, (40, 40, 3), dtype=np.uint8))
        w.write(recordio.pack(recordio.IRHeader(0, float(i), i, 0),
                              enc.tobytes()))
    w.close()
    log = QuarantineLog(str(tmp_path / "q.jsonl"))
    # record= targeting: deterministic under the threaded batch builders
    faults.configure("seed=6;io.corrupt_record:corrupt(record=5)")
    it = ImageRecordIterImpl(path_imgrec=rec, data_shape=(3, 32, 32),
                             batch_size=4, preprocess_threads=2)
    it.set_quarantine(log)
    n = sum(b.data[0].shape[0] - b.pad for b in it)
    assert n == 12 and it.corrupt_records == 1
    it.close()
    faults.clear()
    bad = {e["record"] for e in log.load() if e.get("record") is not None}
    assert bad == {5}
    # resume: the quarantined record is dropped from the epoch order
    it2 = ImageRecordIterImpl(path_imgrec=rec, data_shape=(3, 32, 32),
                              batch_size=4, preprocess_threads=2)
    it2.apply_quarantine(log.load())
    labels = []
    for b in it2:
        labels.extend(
            b.label[0].asnumpy()[:b.data[0].shape[0] - b.pad].tolist())
    it2.close()
    assert len(labels) == 11
    assert not any(float(r) in labels for r in bad)
    assert it2.corrupt_records == 0


# -- observability -------------------------------------------------------------

def test_guardian_events_in_runtime_report(fast_guardian):
    faults.configure("seed=7;grad.nonfinite:error(at=5)")
    _fit(_model())
    report = analysis.runtime_report()
    codes = {f.code for f in report.findings}
    assert "skip-batch" in codes
    analysis.reset_runtime()
    codes = {f.code for f in analysis.runtime_report().findings}
    assert "skip-batch" not in codes


def test_guardian_events_in_fault_trace(fast_guardian):
    faults.configure("seed=7;grad.nonfinite:error(at=5)")
    _fit(_model())
    events = [e.get("event") for e in faults.trace()]
    assert "skip-batch" in events and "quarantine" in events


# -- config / lint -------------------------------------------------------------

def test_guardian_knobs_registered():
    for knob in ("MXNET_GUARDIAN", "MXNET_GUARDIAN_INTERVAL",
                 "MXNET_GUARDIAN_SPIKE_WINDOW", "MXNET_GUARDIAN_SPIKE_K",
                 "MXNET_GUARDIAN_MAX_FAILURES",
                 "MXNET_GUARDIAN_MAX_ROLLBACKS",
                 "MXNET_GUARDIAN_QUARANTINE"):
        assert knob in config.KNOBS, knob
        assert config.KNOBS[knob][2] == "honored"
    assert config.get("MXNET_GUARDIAN_INTERVAL") >= 1


def test_nan_swallow_lint():
    bad = (
        "for epoch in range(10):\n"
        "    for batch in data:\n"
        "        try:\n"
        "            mod.fit_step(batch, metric)\n"
        "        except Exception:\n"
        "            continue\n")
    codes = [f.code for f in analysis.check_source(bad).findings]
    assert "nan-swallow" in codes
    bad2 = (
        "while True:\n"
        "    try:\n"
        "        trainer.step(batch_size)\n"
        "    except FloatingPointError:\n"
        "        if np.isnan(float(loss.asnumpy())):\n"
        "            pass\n")
    codes = [f.code for f in analysis.check_source(bad2).findings]
    assert "nan-swallow" in codes
    good = (
        "try:\n"
        "    mod.fit(it, num_epoch=2)\n"
        "except TrainingDivergedError:\n"
        "    raise\n")
    assert "nan-swallow" not in [
        f.code for f in analysis.check_source(good).findings]
    suppressed = (
        "try:\n"
        "    mod.fit_step(batch, metric)\n"
        "except Exception:  # mxlint: disable=nan-swallow\n"
        "    continue_flag = True\n")
    assert "nan-swallow" not in [
        f.code for f in analysis.check_source(suppressed).findings]
