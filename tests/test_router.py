"""Multi-replica serving router (the ISSUE-8 acceptance gates).

Covers: result parity + load spreading over local replicas, abrupt
replica death with ZERO lost and ZERO duplicated requests (local kill
and real SIGKILLed subprocess workers), health probing where a probe
drop burst suspends but never evicts, dead-replica eviction at the
liveness deadline, rolling hot weight-swap with zero dropped requests
and zero XLA compiles (certified via program counts + the recompile
auditor), torn-swap abort with the fleet still serving, priority-class
shedding (best-effort first, interactive protected), request-id
idempotency, the bounded latency reservoir, and zero-compile replica
fleet spin-up from the shared program-cache disk tier.
"""
import os
import threading
import time

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import analysis, io, sym
from incubator_mxnet_tpu.base import MXNetError
from incubator_mxnet_tpu.resilience import faults
from incubator_mxnet_tpu.serving import (LatencyReservoir, LocalReplica,
                                         RemoteReplica, ReplicaRouter)


def _mlp(in_dim=6, hidden=(16,), n_out=3):
    net = sym.Variable("data")
    for i, h in enumerate(hidden):
        net = sym.FullyConnected(net, num_hidden=h, name=f"fc{i}")
        net = sym.Activation(net, act_type="tanh")
    net = sym.FullyConnected(net, num_hidden=n_out, name="head")
    return sym.SoftmaxOutput(net, name="softmax")


def _make_model(in_dim=6, hidden=(16,), batch=4, seed=0):
    np.random.seed(seed)
    mx.random.seed(seed)
    net = _mlp(in_dim, hidden)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[io.DataDesc("data", (batch, in_dim))],
             label_shapes=[io.DataDesc("softmax_label", (batch,))],
             for_training=False, grad_req="null")
    mod.init_params(mx.initializer.Xavier())
    args, auxs = mod.get_params()
    return net, args, auxs, mod


def _served(net, args, auxs, name, buckets=(1, 2, 4), in_dim=6):
    return mx.serving.ServedModel(net, args, auxs,
                                  data_shapes=[("data", (1, in_dim))],
                                  buckets=buckets, ctx=mx.cpu(), name=name)


def _local_fleet(n, buckets=(1, 2, 4), **replica_knobs):
    net, args, auxs, mod = _make_model()
    reps = [LocalReplica(_served(net, args, auxs, "m", buckets),
                         replica_id=f"r{i}", **replica_knobs)
            for i in range(n)]
    return reps, (net, args, auxs, mod)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def test_router_parity_and_load_spreading():
    reps, (net, args, auxs, mod) = _local_fleet(2)
    x = np.random.randn(3, 6).astype(np.float32)
    mod.forward(io.DataBatch(
        data=[mx.nd.array(np.concatenate([x, x[-1:]]))],
        label=[mx.nd.zeros((4,))]), is_train=False)
    expect = mod.get_outputs()[0].asnumpy()[:3]
    with ReplicaRouter(reps, health_interval_s=0.2) as router:
        got = router.predict({"data": x}, timeout_ms=10000)[0].asnumpy()
        np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-6)
        futs = [router.submit({"data": x[i % 3][None]})
                for i in range(32)]
        for i, f in enumerate(futs):
            np.testing.assert_allclose(f.result(30)[0].asnumpy()[0],
                                       expect[i % 3], rtol=1e-5, atol=1e-6)
        # least-loaded dispatch actually spread the work
        executed = [r.metrics.snapshot()["responses"] for r in reps]
        assert all(n > 0 for n in executed), executed
        snap = router.stats()
        assert snap["responses"] == 33
        assert snap["classes"]["interactive"]["responses"] == 33


def test_replica_kill_zero_lost_zero_duplicated():
    reps, _ = _local_fleet(3)
    with ReplicaRouter(reps, health_interval_s=0.2,
                       health_deadline_s=3.0) as router:
        x = np.random.randn(2, 6).astype(np.float32)
        # park requests on r0 deterministically, then kill it abruptly:
        # queued requests must fail over, none lost, none double-served
        reps[0]._batcher.pause()
        futs = [router.submit({"data": x}) for _ in range(12)]
        time.sleep(0.05)
        reps[0].kill()
        results = [f.result(30) for f in futs]
        assert len(results) == 12
        snap = router.stats()
        assert snap["replicas_lost"] == 1
        assert snap["failovers"] >= 1
        assert snap["duplicates_suppressed"] == 0
        # every request executed exactly once somewhere in the fleet
        executed = sum(r.metrics.snapshot()["responses"] for r in reps)
        assert executed == 12
        assert snap["replicas"]["r0"]["state"] == "dead"
        # the fleet keeps serving at N-1
        assert len(router.predict({"data": x}, timeout_ms=10000)) == 1


def test_probe_drop_burst_suspends_but_never_evicts():
    reps, _ = _local_fleet(2)
    faults.configure("seed=31;replica.health:drop(at=1-3)")
    with ReplicaRouter(reps, health_interval_s=0.05,
                       health_deadline_s=5.0) as router:
        x = np.random.randn(1, 6).astype(np.float32)
        deadline = time.monotonic() + 2.0
        served = 0
        while time.monotonic() < deadline and served < 20:
            router.predict({"data": x}, timeout_ms=10000)
            served += 1
            time.sleep(0.01)
        snap = router.stats()
        # the drop burst verifiably fired ...
        fired = [e for e in faults.trace()
                 if e.get("site") == "replica.health"]
        assert len(fired) >= 3
        # ... yet nothing was evicted and traffic never stopped
        assert snap["replicas_lost"] == 0
        assert all(r["state"] in ("healthy", "suspect")
                   for r in snap["replicas"].values())
        assert served == 20


def test_dead_replica_evicted_at_liveness_deadline():
    reps, _ = _local_fleet(2)
    with ReplicaRouter(reps, health_interval_s=0.05,
                       health_deadline_s=0.4) as router:
        # r1's worker thread dies silently: heartbeats fail from now on
        reps[1]._batcher.kill()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if router.stats()["replicas"]["r1"]["state"] == "dead":
                break
            time.sleep(0.05)
        snap = router.stats()
        assert snap["replicas"]["r1"]["state"] == "dead"
        # N-1 serving continues
        x = np.random.randn(1, 6).astype(np.float32)
        assert len(router.predict({"data": x}, timeout_ms=10000)) == 1


def test_rolling_swap_zero_dropped_zero_compiles():
    reps, (net, args, auxs, _) = _local_fleet(2)
    with ReplicaRouter(reps, health_interval_s=0.2) as router:
        x = np.random.randn(2, 6).astype(np.float32)
        before = router.predict({"data": x}, timeout_ms=10000)[0].asnumpy()
        programs = [r._model.program_count() for r in reps]
        keys = [r._model.audit_key for r in reps]
        sigs = [analysis.recompile.signatures(k) for k in keys]

        stop = threading.Event()
        errors = []
        served = [0]

        def traffic():
            while not stop.is_set():
                try:
                    router.predict({"data": x}, timeout_ms=10000)
                    served[0] += 1
                except Exception as exc:
                    errors.append(repr(exc))

        threads = [threading.Thread(target=traffic) for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.1)
        new_args = {k: mx.nd.array(v.asnumpy() * 2.0)
                    for k, v in args.items()}
        result = router.swap_weights(arg_params=new_args, aux_params=auxs)
        time.sleep(0.1)
        stop.set()
        for t in threads:
            t.join()
        # zero dropped requests through the whole roll
        assert not errors, errors[:5]
        assert served[0] > 0
        assert result["swapped"] == ["r0", "r1"]
        assert all(v == 1 for v in result["versions"].values())
        # the swap changed the weights ...
        after = router.predict({"data": x}, timeout_ms=10000)[0].asnumpy()
        assert not np.allclose(before, after)
        # ... and compiled NOTHING: same programs, no new signatures
        assert [r._model.program_count() for r in reps] == programs
        assert [analysis.recompile.signatures(k) for k in keys] == sigs
        assert router.stats()["swaps_committed"] == 1


def test_torn_swap_aborts_with_fleet_serving():
    reps, (net, args, auxs, _) = _local_fleet(2)
    faults.configure("seed=32;replica.swap:torn(at=2)")
    with ReplicaRouter(reps, health_interval_s=0.5) as router:
        x = np.random.randn(1, 6).astype(np.float32)
        new_args = {k: mx.nd.array(v.asnumpy() * 2.0)
                    for k, v in args.items()}
        with pytest.raises(MXNetError, match=r"ABORTED.*r1.*swapped \[r0\]"):
            router.swap_weights(arg_params=new_args, aux_params=auxs)
        # first replica rolled, second untouched; each request is still
        # served wholly at ONE version and the fleet serves on
        assert reps[0].version == 1
        assert reps[1].version == 0
        assert len(router.predict({"data": x}, timeout_ms=10000)) == 1
        assert router.stats()["swaps_committed"] == 0
        # clearing the fault and re-issuing finishes the roll
        faults.clear()
        result = router.swap_weights(arg_params=new_args, aux_params=auxs)
        assert all(s.replica.version >= 1
                   for s in router._slots.values())
        assert result["swapped"]


def test_priority_shedding_best_effort_first():
    # a deliberately slow single replica: every batch sleeps, so the
    # estimated fleet wait climbs and the router must degrade by CLASS
    reps, _ = _local_fleet(1, max_queue_latency_ms=0.0)
    faults.configure("seed=33;serving.execute:slow(ms=40,n=100000)")
    with ReplicaRouter(
            reps, health_interval_s=5.0,
            shed_ms={"best_effort": 30.0, "batch": 400.0,
                     "interactive": 30000.0}) as router:
        x = np.random.randn(1, 6).astype(np.float32)
        errors = {"interactive": [], "best_effort": []}
        done = {"interactive": 0, "best_effort": 0}
        lock = threading.Lock()

        def client(cls, n):
            for _ in range(n):
                try:
                    router.predict({"data": x}, timeout_ms=60000,
                                   priority=cls)
                    with lock:
                        done[cls] += 1
                except MXNetError as exc:
                    with lock:
                        errors[cls].append(str(exc))

        threads = [threading.Thread(target=client, args=(cls, 12))
                   for cls in ("interactive", "best_effort")
                   for _ in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        snap = router.stats()
        classes = snap["classes"]
        # overload degraded GRACEFULLY: best-effort shed first, every
        # interactive request served
        assert classes["best_effort"]["shed"] > 0
        assert classes["interactive"].get("shed", 0) == 0
        assert done["interactive"] == 36
        assert all("shed threshold" in e for e in errors["best_effort"])


def test_priority_dispatch_order_in_batcher():
    """An admitted best-effort backlog must not delay interactive work:
    replica queues dispatch by rank, FIFO within a rank."""
    reps, _ = _local_fleet(1, buckets=(1,), max_queue_latency_ms=0.0)
    rep = reps[0]
    order = []
    x = np.random.randn(1, 6).astype(np.float32)
    try:
        rep._batcher.pause()
        futs = []
        for i in range(4):   # the best-effort backlog arrives first
            f = rep.submit({"data": x}, priority=2)
            f.add_done_callback(lambda _f, i=i: order.append(("be", i)))
            futs.append(f)
            if i == 0:
                # let the paused worker grab (and hold) the head request
                # so the rest of the backlog is deterministically queued
                time.sleep(0.05)
        fi = rep.submit({"data": x}, priority=0)
        fi.add_done_callback(lambda _f: order.append(("inter", 0)))
        futs.append(fi)
        rep._batcher.resume()
        for f in futs:
            f.result(30)
        # interactive jumped every QUEUED best-effort request; only the
        # head request the worker already held may precede it
        pos = order.index(("inter", 0))
        assert pos <= 1, order
        assert [o for o in order if o[0] == "be"] == \
            [("be", i) for i in range(4)], order
    finally:
        rep.close(drain=False)


def test_best_effort_queue_headroom():
    """The top fifth of a bounded queue is closed to best-effort: a
    flood bounces there while interactive still queues."""
    reps, _ = _local_fleet(1, buckets=(1,), max_queue=5,
                           max_queue_latency_ms=0.0)
    rep = reps[0]
    x = np.random.randn(1, 6).astype(np.float32)
    try:
        rep._batcher.pause()
        accepted = []
        with pytest.raises(MXNetError, match="high-water"):
            for _ in range(6):
                accepted.append(rep.submit({"data": x}, priority=2))
        # best-effort stopped at the 80% mark, interactive still admitted
        fi = rep.submit({"data": x}, priority=0)
        rep._batcher.resume()
        assert len(fi.result(30)) == 1
        for f in accepted:
            f.result(30)
    finally:
        rep.close(drain=False)


def test_request_id_idempotency():
    reps, _ = _local_fleet(1)
    with ReplicaRouter(reps, health_interval_s=0.5) as router:
        x = np.random.randn(1, 6).astype(np.float32)
        out = router.predict({"data": x}, timeout_ms=10000,
                             request_id="req-1")
        assert len(out) == 1
        with pytest.raises(MXNetError, match="already accepted"):
            router.submit({"data": x}, request_id="req-1")


def test_latency_reservoir_bounded_and_uniform():
    res = LatencyReservoir(capacity=512, seed=7)
    for i in range(100_000):
        res.add(float(i % 1000))
    assert len(res) == 512          # memory bounded forever
    assert res.count == 100_000
    p50 = res.percentile(50)
    assert 350 < p50 < 650          # a uniform sample of the stream
    # per-class metrics plumbing
    m = mx.serving.ServingMetrics("t", window=64)
    for i in range(200):
        m.record_response(0.001 * (i + 1), cls="batch")
    m.record_shed("best_effort")
    snap = m.snapshot()
    assert snap["classes"]["batch"]["responses"] == 200
    assert snap["classes"]["best_effort"]["shed"] == 1
    assert snap["classes"]["batch"]["p99_ms"] is not None


def test_no_live_replica_is_structured_error():
    reps, _ = _local_fleet(1)
    with ReplicaRouter(reps, health_interval_s=0.5) as router:
        reps[0].kill()
        x = np.random.randn(1, 6).astype(np.float32)
        with pytest.raises(MXNetError, match="no live replica|failed on"):
            router.predict({"data": x}, timeout_ms=2000)


@pytest.mark.slow
def test_remote_fleet_sigkill_swap_and_zero_compile_spinup(tmp_path):
    """The full remote story in one (subprocess-heavy) test: 3 worker
    processes spin up — replicas 2 and 3 with ZERO XLA compiles off the
    shared program-cache disk tier — traffic flows, one worker is
    SIGKILLed mid-flight with zero requests lost and zero duplicate
    executions (certified from the survivors' rid logs), and a rolling
    checkpoint swap completes with zero compiles."""
    net, args, auxs, mod = _make_model()
    prefix = str(tmp_path / "m")
    mod.save_checkpoint(prefix, 0)
    env = {"MXNET_PROGRAM_CACHE_DIR": str(tmp_path / "pcache"),
           "JAX_PLATFORMS": "cpu"}
    reps = [RemoteReplica.spawn(
        prefix=prefix, epoch=0, data_shapes=[("data", (1, 6))],
        buckets=(1, 2, 4), name="m", replica_id=f"w{i}", env=env)
        for i in range(3)]
    try:
        # fleet spin-up: first worker compiled the ladder, the rest
        # loaded it from the shared disk tier
        assert reps[0].ready_info.get("compiles", 0) >= 1
        for r in reps[1:]:
            assert r.ready_info.get("compiles") == 0, r.ready_info
            assert r.ready_info.get("disk_hits", 0) >= 1
        router = ReplicaRouter(reps, health_interval_s=0.2,
                               health_deadline_s=3.0)
        x = np.random.randn(2, 6).astype(np.float32)
        results, errors = [], []
        accepted = [0]
        killed = [False]
        lock = threading.Lock()

        def client(n):
            for _ in range(n):
                try:
                    f = router.submit({"data": x}, timeout_ms=30000)
                    with lock:
                        accepted[0] += 1
                        if accepted[0] == 40 and not killed[0]:
                            killed[0] = True
                            reps[1].kill()   # real SIGKILL mid-flight
                    results.append(f.result(60))
                except Exception as exc:
                    errors.append(repr(exc))

        threads = [threading.Thread(target=client, args=(30,))
                   for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors[:5]
        assert len(results) == 120           # zero lost
        snap = router.stats()
        assert snap["replicas_lost"] == 1
        assert snap["duplicates_suppressed"] == 0
        rids = []
        for r in (reps[0], reps[2]):
            rids += r.stats().get("executed_rids", [])
        assert len(rids) == len(set(rids))   # zero duplicate execution
        # rolling swap from an elastic checkpoint dir, N-1 fleet
        ckroot = str(tmp_path / "ckpts")
        mgr = mx.checkpoint.CheckpointManager(ckroot,
                                              async_snapshots=False)
        arrays = {f"arg:{k}": v.asnumpy() * 2.0 for k, v in args.items()}
        arrays.update({f"aux:{k}": v.asnumpy() for k, v in auxs.items()})
        mgr.snapshot(arrays=arrays, step=1)
        mgr.close()
        before = router.predict({"data": x},
                                timeout_ms=10000)[0].asnumpy()
        result = router.swap_weights(checkpoint_dir=ckroot)
        assert sorted(result["swapped"]) == ["w0", "w2"]
        after = router.predict({"data": x},
                               timeout_ms=10000)[0].asnumpy()
        assert not np.allclose(before, after)
        # the swap compiled nothing on any survivor
        for r in (reps[0], reps[2]):
            st = r.stats()
            assert st["programs"] == 3
            assert st["cache"]["compiles"] + st["cache"]["disk_hits"] \
                <= 3 + 1   # ladder (+1: the spin-up probe is cache-free)
        router.shutdown()
    finally:
        for r in reps:
            try:
                r.kill()
            except Exception:
                pass
