"""Scan-over-layers graph dedup + auto-donation + coldstart budgets.

The cold-start tentpole: runs of structurally identical layer blocks
are detected on the Symbol graph (`analysis.graph_passes.scan_plan`),
lowered to ONE `lax.scan` body over stacked per-layer parameters
(`symbol.graph_eval_fn(..., scan=plan)`), and the fused train step
donates dying step inputs decided by jaxpr liveness
(`fused._decide_autodonate`).  Parameters and checkpoints keep the
per-layer layout; the deduped jaxpr re-keys the unified program cache.

Parity policy (established empirically on the CPU backend): stacks
whose layer bodies are matmul + elementwise ops (Dense/FC) are BITWISE
identical scan-vs-inlined, forward and through training.  Bodies XLA
compiles with different kernel rounding inside a `while` loop than
inlined (conv, batch-norm reductions, FC-bias grad reductions under a
scanned cotangent chain) agree to float-rounding level only — those
models assert a tight allclose and bitwise determinism of each path
individually, never looser tolerances.
"""
import os

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import io, sym
from incubator_mxnet_tpu.analysis import budgets
from incubator_mxnet_tpu.analysis.graph_passes import (SCAN_HINT_RUN,
                                                       SCAN_MIN_RUN,
                                                       check, scan_plan)


# ---------------------------------------------------------------------------
# model builders
# ---------------------------------------------------------------------------

def _stacked_fc(n_layers=6, hidden=32, classes=4):
    net = sym.Variable("data")
    for i in range(n_layers):
        net = sym.FullyConnected(net, num_hidden=hidden,
                                 name="blk%d_fc" % i)
        net = sym.Activation(net, act_type="relu", name="blk%d_relu" % i)
    net = sym.FullyConnected(net, num_hidden=classes, name="out_fc")
    return sym.SoftmaxOutput(net, name="softmax")


def _shared_weight_fc(n_layers=5, hidden=32):
    w = sym.Variable("w_shared")
    net = sym.Variable("data")
    for i in range(n_layers):
        net = sym.FullyConnected(net, w, num_hidden=hidden, no_bias=True,
                                 name="blk%d_fc" % i)
        net = sym.Activation(net, act_type="relu", name="blk%d_relu" % i)
    net = sym.FullyConnected(net, num_hidden=4, name="out_fc")
    return sym.SoftmaxOutput(net, name="softmax")


def _resnet_ish(n_blocks=4):
    net = sym.Variable("data")
    net = sym.Convolution(net, num_filter=8, kernel=(3, 3), pad=(1, 1),
                          name="stem")
    for i in range(n_blocks):
        net = sym.Convolution(net, num_filter=8, kernel=(3, 3),
                              pad=(1, 1), name="blk%d_conv" % i)
        net = sym.BatchNorm(net, name="blk%d_bn" % i)
        net = sym.Activation(net, act_type="relu", name="blk%d_relu" % i)
    net = sym.Pooling(net, global_pool=True, pool_type="avg",
                      kernel=(1, 1), name="gap")
    net = sym.Flatten(net)
    net = sym.FullyConnected(net, num_hidden=3, name="fc")
    return sym.SoftmaxOutput(net, name="softmax")


def _stacked_lstm(layers=4, T=3, hidden=8, vocab=10):
    """Manually-unrolled LSTM stack: each layer consumes the concat of
    the previous layer's per-step hiddens and emits its own concat, so
    layers >= 1 are structurally identical blocks under a single-tensor
    carry (layer 0 reads the raw data variable and stays inlined)."""
    x = sym.Variable("data")
    for layer in range(layers):
        p = "l%d_" % layer
        h = sym.Variable(p + "h0", shape=(0, hidden), __layout__="NC",
                         init="zeros")
        c = sym.Variable(p + "c0", shape=(0, hidden), __layout__="NC",
                         init="zeros")
        outs = []
        for t in range(T):
            xt = sym.slice_axis(x, axis=1, begin=t * hidden,
                                end=(t + 1) * hidden, name=p + "x%d" % t)
            gates = sym.FullyConnected(xt, num_hidden=4 * hidden,
                                       name=p + "i2h%d" % t) \
                + sym.FullyConnected(h, num_hidden=4 * hidden,
                                     name=p + "h2h%d" % t)
            i = sym.Activation(sym.slice_axis(gates, axis=1, begin=0,
                                              end=hidden),
                               act_type="sigmoid")
            f = sym.Activation(sym.slice_axis(gates, axis=1,
                                              begin=hidden,
                                              end=2 * hidden),
                               act_type="sigmoid")
            o = sym.Activation(sym.slice_axis(gates, axis=1,
                                              begin=2 * hidden,
                                              end=3 * hidden),
                               act_type="sigmoid")
            g = sym.Activation(sym.slice_axis(gates, axis=1,
                                              begin=3 * hidden,
                                              end=4 * hidden),
                               act_type="tanh")
            c = f * c + i * g
            h = o * sym.Activation(c, act_type="tanh")
            outs.append(h)
        x = sym.Concat(*outs, dim=1, name=p + "cat")
    net = sym.FullyConnected(x, num_hidden=vocab, name="pred")
    return sym.SoftmaxOutput(net, name="softmax")


# ---------------------------------------------------------------------------
# training driver
# ---------------------------------------------------------------------------

def _train(symbol, X, y, scan_on, steps=5, batch=16, momentum=0.9,
           autodonate=True, mod=None):
    """Train `steps` fit_steps; returns (arg_params, aux_params, fused,
    module).  Toggles MXNET_FUSED_SCAN / MXNET_FUSED_AUTODONATE for the
    duration of the build."""
    os.environ["MXNET_FUSED_TRAIN_STEP"] = "1"
    os.environ["MXNET_FUSED_SCAN"] = "1" if scan_on else "0"
    os.environ["MXNET_FUSED_AUTODONATE"] = "1" if autodonate else "0"
    try:
        np.random.seed(7)
        mx.random.seed(7)
        it = io.NDArrayIter(X, y, batch_size=batch, shuffle=False,
                            label_name="softmax_label")
        if mod is None:
            mod = mx.mod.Module(symbol, context=mx.cpu())
            mod.bind(data_shapes=it.provide_data,
                     label_shapes=it.provide_label)
            mod.init_params(mx.initializer.Xavier())
            opt = {"learning_rate": 0.1}
            if momentum:
                opt["momentum"] = momentum
            mod.init_optimizer(optimizer="sgd", optimizer_params=opt)
        metric = mx.metric.create("acc")
        batches = list(it)
        for s in range(steps):
            mod.fit_step(batches[s % len(batches)], metric)
        fused = mod._fused_step
        assert fused is not None and not fused.broken, \
            "fused train step must engage"
        args, auxs = mod.get_params()
        return ({k: v.asnumpy() for k, v in args.items()},
                {k: v.asnumpy() for k, v in auxs.items()}, fused, mod)
    finally:
        for k in ("MXNET_FUSED_TRAIN_STEP", "MXNET_FUSED_SCAN",
                  "MXNET_FUSED_AUTODONATE"):
            os.environ.pop(k, None)


def _fc_data(n=64, d=32, k=4, seed=1):
    rng = np.random.RandomState(seed)
    return rng.randn(n, d).astype("f4"), rng.randint(0, k, n).astype("f4")


# ---------------------------------------------------------------------------
# detection
# ---------------------------------------------------------------------------

def test_scan_plan_detects_fc_run():
    plan = scan_plan(_stacked_fc(6))
    assert plan["runs"], "stacked FC must yield an eligible run"
    run = plan["runs"][0]
    assert run["length"] >= 5
    assert run["length"] >= SCAN_MIN_RUN
    # per-layer parameter layout: every param slot stacks one node per
    # layer, and no node repeats across layers
    for slot in run["params"]:
        assert len(slot) == run["length"]
        assert len({id(n) for n in slot}) == run["length"]
    # the carry chains layer boundaries
    assert run["carry"][0] is not None


def test_scan_plan_period_grouping_covers_multi_op_layers():
    # each layer is fc+relu: TWO unit segments per layer — only the
    # period-p grouper can see the repeat
    s = _stacked_fc(6)
    run = scan_plan(s)["runs"][0]
    covered = {id(n) for seg in run["segments"] for n in seg}
    fc = sum(1 for n in s._topo()
             if not n.is_variable and n.name.startswith("blk")
             and id(n) in covered)
    assert fc >= 2 * run["length"], \
        "each scanned layer must cover its fc AND its activation"


def test_scan_plan_rejects_shared_weights():
    plan = scan_plan(_shared_weight_fc())
    assert not plan["runs"], "shared-weight stack must not be scanned"
    assert plan["rejected"], "rejection must be recorded, not silent"
    assert any("shared" in r["reason"] for r in plan["rejected"])


def test_scan_plan_respects_min_run():
    plan = scan_plan(_stacked_fc(6), min_run=7)
    assert not plan["runs"]


def test_stacked_lstm_layers_detected():
    plan = scan_plan(_stacked_lstm(layers=4))
    assert plan["runs"], "identical LSTM layers must form a run"
    # layer 0 reads the raw data variable, so 3 of 4 layers scan
    assert plan["runs"][0]["length"] == 3


# ---------------------------------------------------------------------------
# mxlint hint
# ---------------------------------------------------------------------------

def test_scan_opportunity_hint_when_lowering_disabled():
    s = _stacked_fc(6)
    os.environ["MXNET_FUSED_SCAN"] = "0"
    try:
        rep = check(s, hints=True)
    finally:
        os.environ.pop("MXNET_FUSED_SCAN", None)
    hints = [f for f in rep if f.code == "scan-opportunity"]
    assert hints, "eligible run >= %d must hint when not lowered" \
        % SCAN_HINT_RUN
    assert all(f.severity == "hint" for f in hints)


def test_scan_opportunity_silent_when_lowered():
    s = _stacked_fc(6)
    os.environ["MXNET_FUSED_SCAN"] = "1"
    try:
        rep = check(s, hints=True)
    finally:
        os.environ.pop("MXNET_FUSED_SCAN", None)
    assert not [f for f in rep if f.code == "scan-opportunity"], \
        "a run the fused path lowers must not hint"


def test_scan_opportunity_hint_for_rejected_run():
    # shared weights keep the run un-lowerable — the hint must fire
    # even with lowering enabled, pointing at the blocker
    os.environ["MXNET_FUSED_SCAN"] = "1"
    try:
        rep = check(_shared_weight_fc(), hints=True)
    finally:
        os.environ.pop("MXNET_FUSED_SCAN", None)
    assert [f for f in rep if f.code == "scan-opportunity"]


# ---------------------------------------------------------------------------
# lowering parity
# ---------------------------------------------------------------------------

def test_graph_eval_fn_forward_bitwise():
    import jax
    import jax.numpy as jnp
    from incubator_mxnet_tpu.symbol.symbol import graph_eval_fn

    s = _stacked_fc(6)
    plan = scan_plan(s)
    fn0, args0, _, _ = graph_eval_fn(s, True)
    fn1, args1, _, _ = graph_eval_fn(s, True, scan=plan)
    assert [a.name for a in args0] == [a.name for a in args1], \
        "argument order must not change under scan lowering"
    rng = np.random.RandomState(0)
    vals = []
    for a in args0:
        if a.name == "data":
            vals.append(jnp.asarray(rng.randn(8, 32).astype("f4")))
        elif a.name == "softmax_label":
            vals.append(jnp.asarray(rng.randint(0, 4, 8).astype("f4")))
        elif "bias" in a.name:
            vals.append(jnp.zeros(
                (4,) if a.name.startswith("out") else (32,), "f4"))
        else:
            shape = (4, 32) if a.name.startswith("out") else (32, 32)
            vals.append(jnp.asarray(rng.randn(*shape).astype("f4") * 0.1))
    key = jax.random.PRNGKey(0)
    o0, _ = fn0(tuple(vals), (), key)
    o1, _ = fn1(tuple(vals), (), key)
    for a, b in zip(o0, o1):
        assert np.array_equal(np.asarray(a), np.asarray(b)), \
            "scan-lowered forward must be bitwise equal"

    def eqns(f):
        closed = jax.make_jaxpr(lambda v, k: f(v, (), k))(tuple(vals), key)
        return len(closed.jaxpr.eqns)

    assert eqns(fn1) < eqns(fn0), \
        "scan lowering must shrink the traced graph"


def test_module_training_bitwise_fc_stack():
    X, y = _fc_data()
    a1, _, f1, m1 = _train(_stacked_fc(6), X, y, scan_on=True)
    a0, _, f0, m0 = _train(_stacked_fc(6), X, y, scan_on=False)
    assert f1.scan_runs and not f0.scan_runs
    assert f1._core_closed.num_eqns() < f0._core_closed.num_eqns()
    for k in a0:
        assert np.array_equal(a0[k], a1[k]), \
            "param %s must be bitwise equal after training" % k
    # continued training stays bitwise: momentum state matched too
    a1c, _, _, _ = _train(None, X, y, scan_on=True, steps=2, mod=m1)
    a0c, _, _, _ = _train(None, X, y, scan_on=False, steps=2, mod=m0)
    for k in a0c:
        assert np.array_equal(a0c[k], a1c[k]), \
            "optimizer state diverged: %s differs on continuation" % k


def test_module_training_resnet_style_allclose():
    """Conv/BN bodies: XLA CPU compiles their kernels with different
    rounding inside a while-loop body than inlined — both paths are
    individually deterministic, and agree to float-rounding level."""
    rng = np.random.RandomState(1)
    X = rng.randn(32, 3, 8, 8).astype("f4")
    y = rng.randint(0, 3, 32).astype("f4")
    a1, x1, f1, _ = _train(_resnet_ish(4), X, y, scan_on=True, steps=4)
    a0, x0, f0, _ = _train(_resnet_ish(4), X, y, scan_on=False, steps=4)
    assert f1.scan_runs and not f0.scan_runs
    assert f1._core_closed.num_eqns() < f0._core_closed.num_eqns()
    for k in a0:
        np.testing.assert_allclose(a0[k], a1[k], rtol=2e-5, atol=2e-6,
                                   err_msg=k)
    for k in x0:   # BN running stats ride the scan as stacked aux ys
        np.testing.assert_allclose(x0[k], x1[k], rtol=2e-5, atol=2e-6,
                                   err_msg=k)


def test_module_training_resnet_scan_deterministic():
    rng = np.random.RandomState(1)
    X = rng.randn(32, 3, 8, 8).astype("f4")
    y = rng.randint(0, 3, 32).astype("f4")
    a1, _, _, _ = _train(_resnet_ish(4), X, y, scan_on=True, steps=3)
    a2, _, _, _ = _train(_resnet_ish(4), X, y, scan_on=True, steps=3)
    for k in a1:
        assert np.array_equal(a1[k], a2[k]), \
            "scan path must be deterministic run-to-run (%s)" % k


def test_module_training_stacked_lstm():
    rng = np.random.RandomState(2)
    X = rng.randn(32, 3 * 8).astype("f4")
    y = rng.randint(0, 10, 32).astype("f4")
    a1, _, f1, _ = _train(_stacked_lstm(), X, y, scan_on=True, steps=5)
    a0, _, f0, _ = _train(_stacked_lstm(), X, y, scan_on=False, steps=5)
    assert [(n, l) for n, l in f1.scan_runs] and f1.scan_runs[0][1] == 3
    assert f1._core_closed.num_eqns() < f0._core_closed.num_eqns()
    # FC-bias cotangent reductions under the scanned backward round
    # differently on CPU: rounding-level agreement, tightly bounded
    for k in a0:
        np.testing.assert_allclose(a0[k], a1[k], rtol=1e-6, atol=1e-7,
                                   err_msg=k)


def test_scan_rekeys_program_cache():
    # the deduped jaxpr IS the cache identity: scan on/off must never
    # collide in the unified program cache
    X, y = _fc_data()
    _, _, f1, _ = _train(_stacked_fc(6), X, y, scan_on=True, steps=1)
    _, _, f0, _ = _train(_stacked_fc(6), X, y, scan_on=False, steps=1)
    assert f1._core_closed.graph_hash != f0._core_closed.graph_hash


# ---------------------------------------------------------------------------
# gluon HybridSequential
# ---------------------------------------------------------------------------

def test_gluon_hybrid_sequential_scan_parity():
    from incubator_mxnet_tpu import gluon, nd

    def run(scan_on, depth=6, steps=5):
        os.environ["MXNET_FUSED_TRAIN_STEP"] = "1"
        os.environ["MXNET_FUSED_SCAN"] = "1" if scan_on else "0"
        try:
            rng = np.random.RandomState(9)
            net = gluon.nn.HybridSequential()
            net.add(gluon.nn.Dense(16))
            for _ in range(depth):
                net.add(gluon.nn.Dense(16, activation="relu"))
            net.add(gluon.nn.Dense(3))
            net.initialize()
            net(nd.array(np.zeros((2, 12), "f4")))
            for p in net.collect_params().values():
                if p.name.endswith("bias"):
                    p.set_data(nd.array(np.zeros(p.shape, "f4")))
                else:
                    p.set_data(nd.array(
                        (rng.randn(*p.shape) * 0.2).astype("f4")))
            trainer = gluon.Trainer(net.collect_params(), "sgd",
                                    {"learning_rate": 0.1,
                                     "momentum": 0.9})
            est = gluon.contrib.estimator.Estimator(
                net, gluon.loss.SoftmaxCrossEntropyLoss(),
                train_metrics=[mx.metric.Accuracy()], trainer=trainer)
            X = np.random.RandomState(4).randn(64, 12).astype("f4")
            y = np.random.RandomState(4).randint(0, 3, 64).astype("f4")
            batches = [(nd.array(X[i:i + 16]), nd.array(y[i:i + 16]))
                       for i in range(0, 64, 16)] * 3
            est.fit(iter(batches[:steps]), epochs=1, event_handlers=[])
            fs = est._fused
            assert fs is not None and not fs.broken
            # gluon param names use global counters: compare positionally
            return ([p.data().asnumpy()
                     for p in net.collect_params().values()],
                    fs._core_closed.num_eqns())
        finally:
            os.environ.pop("MXNET_FUSED_TRAIN_STEP", None)
            os.environ.pop("MXNET_FUSED_SCAN", None)

    p1, e1 = run(True)
    p0, e0 = run(False)
    assert e1 < e0, "identical Dense run must scan (eqns %d vs %d)" \
        % (e1, e0)
    for i, (a, b) in enumerate(zip(p0, p1)):
        assert np.array_equal(a, b), "param %d differs" % i


# ---------------------------------------------------------------------------
# auto-donation
# ---------------------------------------------------------------------------

def test_autodonate_engages_on_dying_inputs():
    X, y = _fc_data()
    _, _, fused, _ = _train(_stacked_fc(3), X, y, scan_on=False, steps=2)
    assert fused._autodonate_on, \
        "batch inputs die in a plain train step: donation must engage"


def test_autodonate_never_fires_on_live_buffer():
    """Negative fixture: a head echoes the data variable, so the input
    buffer stays live past the step — liveness must refuse donation."""
    data = sym.Variable("data")
    x = sym.FullyConnected(data, num_hidden=8, name="fc1")
    net = sym.Group([sym.SoftmaxOutput(x, name="softmax"), data])
    X, y = _fc_data(d=32, k=8)
    _, _, fused, _ = _train(net, X, y, scan_on=False, steps=2)
    assert not fused._autodonate_on, \
        "an input that IS a program output must never be donated"


def test_autodonate_env_kill_switch():
    X, y = _fc_data()
    _, _, fused, _ = _train(_stacked_fc(3), X, y, scan_on=False, steps=2,
                            autodonate=False)
    assert not fused._autodonate_on


def test_autodonate_training_parity():
    X, y = _fc_data()
    a1, _, _, _ = _train(_stacked_fc(4), X, y, scan_on=False, steps=4,
                         autodonate=True)
    a0, _, _, _ = _train(_stacked_fc(4), X, y, scan_on=False, steps=4,
                         autodonate=False)
    for k in a0:
        assert np.array_equal(a0[k], a1[k]), \
            "donation must not change results (%s)" % k


def test_jaxpr_dying_inputs_liveness():
    import jax
    import jax.numpy as jnp
    from incubator_mxnet_tpu.analysis import cost

    def f(a, b, c):
        return a + 1.0, b   # b is returned: still live; c unused: dies

    closed = jax.make_jaxpr(f)(jnp.zeros(3), jnp.zeros(3), jnp.zeros(3))
    dying = cost.jaxpr_dying_inputs(closed, [0, 1, 2])
    assert 0 in dying and 2 in dying and 1 not in dying


# ---------------------------------------------------------------------------
# checkpoint round-trip across the scan boundary
# ---------------------------------------------------------------------------

def test_checkpoint_roundtrip_across_scan_boundary(tmp_path):
    X, y = _fc_data()
    a1, _, fused, mod = _train(_stacked_fc(6), X, y, scan_on=True,
                               steps=3, momentum=0)
    assert fused.scan_runs
    prefix = str(tmp_path / "scan_ckpt")
    mod.save_checkpoint(prefix, 0)

    # params saved from the scan-lowered run keep per-layer layout:
    # a scan-off module loads them bit-identically
    mod2 = mx.mod.Module.load(prefix, 0, context=mx.cpu())
    mod2.bind(data_shapes=[("data", (16, 32))],
              label_shapes=[("softmax_label", (16,))])
    mod2.init_params(mx.initializer.Xavier())   # overridden by loaded
    a2, _ = mod2.get_params()
    for k in a1:
        assert np.array_equal(a1[k], a2[k].asnumpy()), \
            "checkpoint must round-trip per-layer params (%s)" % k

    # resume on BOTH sides of the boundary: identical continuations
    os.environ["MXNET_FUSED_TRAIN_STEP"] = "1"
    try:
        conts = {}
        for scan_on in (True, False):
            os.environ["MXNET_FUSED_SCAN"] = "1" if scan_on else "0"
            m = mx.mod.Module.load(prefix, 0, context=mx.cpu())
            m.bind(data_shapes=[("data", (16, 32))],
                   label_shapes=[("softmax_label", (16,))])
            m.init_params(mx.initializer.Xavier())
            m.init_optimizer(optimizer="sgd",
                             optimizer_params={"learning_rate": 0.1})
            it = io.NDArrayIter(X, y, batch_size=16, shuffle=False,
                                label_name="softmax_label")
            metric = mx.metric.create("acc")
            for b in list(it)[:2]:
                m.fit_step(b, metric)
            args, _ = m.get_params()
            conts[scan_on] = {k: v.asnumpy() for k, v in args.items()}
    finally:
        os.environ.pop("MXNET_FUSED_TRAIN_STEP", None)
        os.environ.pop("MXNET_FUSED_SCAN", None)
    for k in conts[True]:
        assert np.array_equal(conts[True][k], conts[False][k]), \
            "resume across the scan boundary diverged (%s)" % k


# ---------------------------------------------------------------------------
# compile-phase stats + budget gates
# ---------------------------------------------------------------------------

def test_compile_phase_stats_shape():
    X, y = _fc_data()
    _, _, fused, _ = _train(_stacked_fc(4), X, y, scan_on=True, steps=2)
    st = fused.compile_phase_stats()
    assert st["trace_s"] > 0
    assert st["jaxpr_eqns"] > 0
    assert st["scan_runs"], "scan run must be reported"
    assert st["autodonate"] is True
    assert st["programs"], "unified-cache program entries must appear"
    p = st["programs"][0]
    assert {"label", "compiles", "disk_hits", "lower_s",
            "compile_s"} <= set(p)
    assert p["compiles"] >= 1 and p["compile_s"] > 0


def test_program_cache_compile_timing_stats():
    from incubator_mxnet_tpu import compile as mxc

    st = mxc.stats()
    assert "lower_s_total" in st["counters"]
    assert "compile_s_total" in st["counters"]
    assert "disk_misses" in st["counters"]
    # this process compiled fused programs in the tests above
    assert st["counters"]["compile_s_total"] >= 0.0
    for prog in st["programs"]:
        assert {"disk_misses", "lower_s", "compile_s"} <= set(prog)


def test_check_measured_regression_and_missing():
    base = {"measured": {
        "p": {"compile_s": 1.0, "peak_hbm_mb": 100.0}}}
    ok, _ = budgets.check_measured(
        {"p": {"compile_s": 1.2, "peak_hbm_mb": 108.0}}, base)
    assert not [f for f in ok if f.severity == "error"]
    bad, deltas = budgets.check_measured(
        {"p": {"compile_s": 1.0, "peak_hbm_mb": 120.0}}, base)
    errs = [f for f in bad if f.severity == "error"]
    assert errs and "peak_hbm_mb" in errs[0].message
    assert deltas["p"]["peak_hbm_mb"]["ok"] is False
    miss, _ = budgets.check_measured({"q": {"compile_s": 1.0}}, base)
    assert [f for f in miss if f.code == "budget-missing"]


def test_check_measured_ratio_cap_and_informational():
    base = {"measured": {"f": {
        "compile_ratio_vs_jax": 1.5, "jaxpr_eqns": 141,
        "jax_control_compile_s": 0.1}}}
    # under the pinned cap: no error AND no slack noise
    rep, _ = budgets.check_measured(
        {"f": {"compile_ratio_vs_jax": 1.05, "jaxpr_eqns": 141,
               "jax_control_compile_s": 99.0,
               "peak_hbm_source": "estimated"}}, base)
    assert not list(rep), [f.format() for f in rep]
    # over the cap: hard error; eqn growth: hard error
    rep, _ = budgets.check_measured(
        {"f": {"compile_ratio_vs_jax": 1.6, "jaxpr_eqns": 150}}, base)
    codes = [(f.code, f.severity) for f in rep]
    assert codes.count(("budget-regression", "error")) == 2


def test_snapshot_measured_floors_and_merge():
    b = budgets.snapshot_measured(
        {"f": {"compile_ratio_vs_jax": 0.9, "compile_s": 0.05,
               "peak_hbm_mb": 10.0, "peak_hbm_source": "estimated"}})
    entry = b["measured"]["f"]
    assert entry["compile_ratio_vs_jax"] == 1.5   # contract floor
    assert entry["compile_s"] == 0.5              # noise floor
    assert entry["peak_hbm_mb"] == 10.0
    assert "peak_hbm_source" not in entry         # non-numeric skipped
    b2 = budgets.snapshot_measured({"g": {"compile_s": 2.0}}, b)
    assert b2["measured"]["f"]["peak_hbm_mb"] == 10.0   # merge keeps f
    assert b2["measured"]["g"]["compile_s"] == 2.0
    assert b2["measured_tolerances"]["peak_hbm_mb"] == 0.15


def test_cost_budgets_json_has_measured_section():
    import importlib.util
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    committed = budgets.load(os.path.join(root, "COST_BUDGETS.json"))
    measured = committed.get("measured") or {}
    spec = importlib.util.spec_from_file_location(
        "warmup_tool", os.path.join(root, "tools", "warmup.py"))
    warmup = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(warmup)
    for name in warmup.REQUIRED_MEASURED:
        assert name in measured, "budget entry missing: %s" % name
        assert "compile_s" in measured[name]
    assert "peak_hbm_mb" in measured["quantization.convnet_fp32"]
    assert measured["fused.convnet_step"]["compile_ratio_vs_jax"] <= 1.5
    assert committed["measured_tolerances"]["peak_hbm_mb"] == \
        pytest.approx(0.15)
