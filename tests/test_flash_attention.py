"""Flash-attention kernel: math parity with plain softmax attention, the
Pallas kernel itself (interpreter mode on the CPU mesh), gradients through
the custom VJP, and the ring-attention integration."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from incubator_mxnet_tpu.ops.flash_attention import (flash_attention,
                                                     flash_attention_partial)
from incubator_mxnet_tpu import test_utils as tu

requires_shard_map = pytest.mark.skipif(
    not tu.has_stable_shard_map(),
    reason="this jax build lacks the stable jax.shard_map API the "
           "ring-attention integration is written against")


def _naive(q, k, v, causal=False):
    B, T, H, D = q.shape
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D)
    if causal:
        mask = jnp.arange(T)[:, None] >= jnp.arange(k.shape[1])[None, :]
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _qkv(B=2, T=64, H=2, D=16, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(B, T, H, D).astype("f4"))
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_naive(causal):
    q, k, v = _qkv()
    out = flash_attention(q, k, v, causal, 32, 16)
    ref = _naive(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients_match_naive(causal):
    q, k, v = _qkv(T=32)
    tgt = jnp.asarray(np.random.RandomState(1)
                      .randn(*q.shape).astype("f4"))

    def loss_flash(q, k, v):
        return jnp.sum((flash_attention(q, k, v, causal, 16, 16) - tgt) ** 2)

    def loss_naive(q, k, v):
        return jnp.sum((_naive(q, k, v, causal) - tgt) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gn = jax.grad(loss_naive, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gn, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4, err_msg=name)


@pytest.mark.parametrize("causal", [False, True])
def test_pallas_kernel_interpreted_matches_ref(monkeypatch, causal):
    """Run the ACTUAL Pallas kernel (interpreter mode) against the jnp
    fallback — this is what validates the kernel itself off-TPU."""
    monkeypatch.setenv("MXNET_FLASH_INTERPRET", "1")
    q, k, v = _qkv(T=32, D=8)
    o_k, m_k, l_k = flash_attention_partial(q, k, v, 0, 0, causal, 16, 16)
    monkeypatch.delenv("MXNET_FLASH_INTERPRET")
    o_r, m_r, l_r = flash_attention_partial(q, k, v, 0, 0, causal, 16, 16)
    np.testing.assert_allclose(np.asarray(o_k), np.asarray(o_r),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(m_k), np.asarray(m_r),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(l_k), np.asarray(l_r),
                               rtol=2e-5, atol=2e-5)


@requires_shard_map
@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_pallas_path(causal):
    """ring_attention(use_pallas=True) must equal the plain path and full
    attention on the 8-device mesh."""
    from jax.sharding import PartitionSpec as P
    from jax import shard_map
    from incubator_mxnet_tpu import parallel as par
    from incubator_mxnet_tpu.parallel.ring_attention import ring_attention

    import jax as _jax
    mesh = par.make_mesh({"sp": 4}, devices=_jax.devices()[:4])
    q, k, v = _qkv(B=2, T=64, H=2, D=16)

    def run(use_pallas):
        fn = shard_map(
            lambda q, k, v: ring_attention(q, k, v, "sp", causal=causal,
                                           use_pallas=use_pallas),
            mesh=mesh, in_specs=(P(None, "sp"),) * 3,
            out_specs=P(None, "sp"), check_vma=False)
        return jax.jit(fn)(q, k, v)

    ref = _naive(q, k, v, causal)
    for use_pallas in (False, True):
        out = run(use_pallas)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5,
                                   err_msg=f"use_pallas={use_pallas}")
