"""Parallelism tests on the virtual 8-device CPU mesh: data-parallel SPMD
step, tensor-parallel sharding, ring attention, pipeline schedule.
(The reference's analogues are the multi-GPU nightly tests,
tests/nightly/multi_lenet.py / dist_sync_kvstore.py.)"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import parallel as par
from incubator_mxnet_tpu import test_utils as tu

# capability guard, not an xfail: these tests exercise the stable
# `jax.shard_map` API (and the collective numerics of that jax
# generation); a container whose jax predates it skips with the missing
# capability named instead of failing tier-1 red
requires_shard_map = pytest.mark.skipif(
    not tu.has_stable_shard_map(),
    reason="this jax build lacks the stable jax.shard_map API the "
           "parallel subsystem is written against")


def test_make_mesh():
    mesh = par.make_mesh({"dp": 4, "tp": 2})
    assert mesh.shape == {"dp": 4, "tp": 2}
    mesh2 = par.make_mesh()
    assert mesh2.shape["dp"] == len(jax.devices())
    with pytest.raises(mx.MXNetError):
        par.make_mesh({"dp": 5})


@requires_shard_map
def test_data_parallel_step_matches_single_device():
    """DP-8 training must match single-device training on the full batch."""
    mesh = par.make_mesh({"dp": 8})
    rng = np.random.RandomState(0)
    W = jnp.asarray(rng.rand(5, 3).astype("f4"))
    b = jnp.zeros(3, "f4")
    params = {"w": W, "b": b}
    X = jnp.asarray(rng.rand(16, 5).astype("f4"))
    Y = jnp.asarray((rng.rand(16, 3) > 0.5).astype("f4"))

    def loss_fn(p, batch):
        x, y = batch
        pred = x @ p["w"] + p["b"]
        return jnp.mean((pred - y) ** 2)

    update = par.data_parallel_step.__wrapped__ if False else None
    from incubator_mxnet_tpu.parallel.data_parallel import sgd_tree_update
    opt_state = jax.tree_util.tree_map(jnp.zeros_like, params)
    step = par.data_parallel_step(loss_fn, sgd_tree_update(momentum=0.0),
                                  mesh, donate=False)
    p1, o1, loss1 = step(params, opt_state, (X, Y), jnp.float32(0.1))

    # single-device reference
    g = jax.grad(loss_fn)(params, (X, Y))
    ref_w = params["w"] - 0.1 * g["w"]
    np.testing.assert_allclose(np.asarray(p1["w"]), np.asarray(ref_w),
                               rtol=1e-5, atol=1e-6)


@requires_shard_map
def test_collectives_in_shard_map():
    from jax import shard_map
    from jax.sharding import PartitionSpec as P
    mesh = par.make_mesh({"dp": 8})

    def f(x):
        return par.all_reduce(x, "dp"), par.all_gather(x, "dp")

    x = jnp.arange(8.0).reshape(8, 1)
    s, g = jax.jit(shard_map(f, mesh=mesh, in_specs=P("dp"),
                             out_specs=(P("dp"), P("dp"))))(x)
    np.testing.assert_allclose(np.asarray(s), np.full((8, 1), 28.0))


@requires_shard_map
def test_ring_attention_matches_full():
    """Ring attention over 4 sequence shards == exact full attention."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P
    mesh = par.make_mesh({"sp": 4}, devices=jax.devices()[:4])
    B, T, H, D = 2, 16, 2, 8
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.rand(B, T, H, D).astype("f4"))
    k = jnp.asarray(rng.rand(B, T, H, D).astype("f4"))
    v = jnp.asarray(rng.rand(B, T, H, D).astype("f4"))

    def full_attn(q, k, v):
        scale = 1.0 / np.sqrt(D)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v)

    ref = full_attn(q, k, v)

    ring = shard_map(
        lambda q, k, v: par.ring_attention(q, k, v, "sp"),
        mesh=mesh, in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"), check_vma=False)
    out = jax.jit(ring)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3,
                               atol=2e-5)


@requires_shard_map
def test_ring_attention_causal():
    from jax import shard_map
    from jax.sharding import PartitionSpec as P
    mesh = par.make_mesh({"sp": 4}, devices=jax.devices()[:4])
    B, T, H, D = 1, 8, 1, 4
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.rand(B, T, H, D).astype("f4"))
    k = jnp.asarray(rng.rand(B, T, H, D).astype("f4"))
    v = jnp.asarray(rng.rand(B, T, H, D).astype("f4"))

    def full_causal(q, k, v):
        scale = 1.0 / np.sqrt(D)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        mask = np.tril(np.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v)

    ref = full_causal(q, k, v)
    ring = shard_map(
        lambda q, k, v: par.ring_attention(q, k, v, "sp", causal=True),
        mesh=mesh, in_specs=(P(None, "sp"),) * 3,
        out_specs=P(None, "sp"), check_vma=False)
    out = jax.jit(ring)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3,
                               atol=2e-5)


def test_blockwise_attention():
    B, T, H, D = 2, 32, 2, 8
    rng = np.random.RandomState(2)
    q = jnp.asarray(rng.rand(B, T, H, D).astype("f4"))
    k = jnp.asarray(rng.rand(B, T, H, D).astype("f4"))
    v = jnp.asarray(rng.rand(B, T, H, D).astype("f4"))
    full = par.blockwise_attention(q, k, v, block_size=None)
    blocked = par.blockwise_attention(q, k, v, block_size=8)
    np.testing.assert_allclose(np.asarray(blocked), np.asarray(full),
                               rtol=2e-3, atol=2e-5)
    causal_full = par.blockwise_attention(q, k, v, causal=True)
    causal_blk = par.blockwise_attention(q, k, v, block_size=8, causal=True)
    np.testing.assert_allclose(np.asarray(causal_blk),
                               np.asarray(causal_full), rtol=2e-3, atol=2e-5)


def test_tensor_parallel_sharding():
    mesh = par.make_mesh({"dp": 2, "tp": 4})
    rules = par.ShardingRules.megatron("tp")
    params = {
        "layer0.qkv_weight": jnp.zeros((64, 32)),
        "layer0.out_proj_weight": jnp.zeros((32, 64)),
        "layer0.bias": jnp.zeros((64,)),
    }
    sharded = par.shard_params(params, mesh, rules)
    qkv = sharded["layer0.qkv_weight"]
    assert qkv.sharding.spec == jax.sharding.PartitionSpec("tp", None)
    proj = sharded["layer0.out_proj_weight"]
    assert proj.sharding.spec == jax.sharding.PartitionSpec(None, "tp")


@requires_shard_map
def test_pipeline_step():
    from jax import shard_map
    from jax.sharding import PartitionSpec as P
    mesh = par.make_mesh({"pp": 4}, devices=jax.devices()[:4])
    n_micro = 8

    def stage_fn(params, x):
        # every stage adds its (replicated) parameter value
        return x + params

    fwd = par.pipeline_step(stage_fn, n_micro, "pp")
    microbatches = jnp.arange(n_micro, dtype=jnp.float32).reshape(n_micro, 1, 1)
    run = shard_map(fwd, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
                    check_vma=False)
    out = jax.jit(run)(jnp.float32(1.0), microbatches)
    # each of 4 stages adds 1.0
    np.testing.assert_allclose(np.asarray(out).reshape(-1),
                               np.arange(n_micro) + 4.0)


@requires_shard_map
def test_pipeline_train_step_decreases_loss_and_matches_sequential():
    """GPipe training over pp=2: forward == sequential stage composition,
    and the fused train step drives the loss down."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P
    mesh = par.make_mesh({"pp": 2}, devices=jax.devices()[:2])
    n_micro, mb, h = 4, 8, 6
    rng = np.random.RandomState(3)
    # stacked per-stage params, sharded over pp on the leading dim
    W = jnp.asarray(rng.randn(2, h, h).astype("f4") * 0.5)
    B = jnp.asarray(np.zeros((2, 1, h), "f4"))
    X = jnp.asarray(rng.randn(n_micro, mb, h).astype("f4"))
    T = jnp.asarray(rng.randn(n_micro, mb, h).astype("f4") * 0.1)

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"][0] + p["b"][0])

    def loss_fn(out, tgt):
        return jnp.mean((out - tgt) ** 2)

    # forward parity vs sequential composition
    fwd = par.pipeline_step(stage_fn, n_micro, "pp")
    run = shard_map(fwd, mesh=mesh, in_specs=({"w": P("pp"), "b": P("pp")},
                                              P()),
                    out_specs=P(), check_vma=False)
    out = jax.jit(run)({"w": W, "b": B}, X)
    ref = np.tanh(np.tanh(np.asarray(X) @ np.asarray(W[0]) + np.asarray(B[0]))
                  @ np.asarray(W[1]) + np.asarray(B[1]))
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)

    # training: loss decreases
    step = par.pipeline_train_step(stage_fn, loss_fn, n_micro,
                                   lambda p, g: p - 0.5 * g, "pp")
    train = jax.jit(shard_map(
        step, mesh=mesh,
        in_specs=({"w": P("pp"), "b": P("pp")}, P(), P()),
        out_specs=({"w": P("pp"), "b": P("pp")}, P()), check_vma=False))
    params = {"w": W, "b": B}
    losses = []
    for _ in range(12):
        params, loss = train(params, X, T)
        losses.append(float(loss))
    assert losses[-1] < 0.5 * losses[0], losses

    # gradient parity vs non-pipelined autodiff on the composed function
    def composed_loss(p):
        y = np.asarray(X)
        a1 = jnp.tanh(jnp.asarray(y) @ p["w"][0] + p["b"][0])
        a2 = jnp.tanh(a1 @ p["w"][1] + p["b"][1])
        return jnp.mean((a2 - T) ** 2)

    g_ref = jax.grad(composed_loss)({"w": W, "b": B})
    step1 = jax.jit(shard_map(
        par.pipeline_train_step(stage_fn, loss_fn, n_micro,
                                lambda p, g: g, "pp"),  # returns grads
        mesh=mesh,
        in_specs=({"w": P("pp"), "b": P("pp")}, P(), P()),
        out_specs=({"w": P("pp"), "b": P("pp")}, P()), check_vma=False))
    g_pipe, _ = step1({"w": W, "b": B}, X, T)
    np.testing.assert_allclose(np.asarray(g_pipe["w"]), np.asarray(g_ref["w"]),
                               rtol=1e-4, atol=1e-5)


@requires_shard_map
def test_zero_sharded_optimizer_matches_replicated_adam():
    """ZeRO dp-8 adam == replicated adam; state lives sharded 1/N."""
    from incubator_mxnet_tpu.parallel.zero import (
        zero_train_step, zero_init_state, adam_shard_update)
    mesh = par.make_mesh({"dp": 8})
    n = 8
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.rand(5, 3).astype("f4")),
              "b": jnp.zeros(3, "f4")}
    X = jnp.asarray(rng.rand(16, 5).astype("f4"))
    Y = jnp.asarray(rng.rand(16, 3).astype("f4"))

    def loss_fn(p, batch):
        x, y = batch
        return jnp.mean((x @ p["w"] + p["b"] - y) ** 2)

    state = zero_init_state(
        params, n,
        lambda s, d: (jnp.zeros(s, d), jnp.zeros(s, d), jnp.zeros(n, d)))
    step = zero_train_step(loss_fn, adam_shard_update(lr=0.05), mesh,
                           donate=False)

    # replicated adam reference
    ref_p = {k: np.asarray(v, "f4") for k, v in params.items()}
    ref_m = {k: np.zeros_like(v) for k, v in ref_p.items()}
    ref_v = {k: np.zeros_like(v) for k, v in ref_p.items()}

    p, s = params, state
    for t in range(1, 4):
        p, s, loss = step(p, s, (X, Y))
        g = jax.grad(loss_fn)({k: jnp.asarray(v) for k, v in ref_p.items()},
                              (X, Y))
        for k in ref_p:
            gk = np.asarray(g[k], "f4")
            ref_m[k] = 0.9 * ref_m[k] + 0.1 * gk
            ref_v[k] = 0.999 * ref_v[k] + 0.001 * gk * gk
            mhat = ref_m[k] / (1 - 0.9 ** t)
            vhat = ref_v[k] / (1 - 0.999 ** t)
            ref_p[k] = ref_p[k] - 0.05 * mhat / (np.sqrt(vhat) + 1e-8)
    np.testing.assert_allclose(np.asarray(p["w"]), ref_p["w"], rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(p["b"]), ref_p["b"], rtol=1e-4,
                               atol=1e-5)

    # per-device state is 1/N: global m for w is padded ceil(15/8)*8 = 16,
    # each device holds 2 elements
    m_w = s["w"][0]
    assert m_w.shape == (16,)
    shard_shapes = {sh.data.shape for sh in m_w.addressable_shards}
    assert shard_shapes == {(2,)}
