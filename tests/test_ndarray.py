"""NDArray basics (reference tests/python/unittest/test_ndarray.py)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd


def test_creation():
    a = nd.zeros((2, 3))
    assert a.shape == (2, 3)
    assert a.dtype == np.float32
    assert (a.asnumpy() == 0).all()

    b = nd.ones((4,), dtype="int32")
    assert b.dtype == np.int32
    assert (b.asnumpy() == 1).all()

    c = nd.full((2, 2), 7.5)
    assert (c.asnumpy() == 7.5).all()

    d = nd.array([[1, 2], [3, 4]])
    assert d.shape == (2, 2)
    assert d.dtype == np.float32  # MXNet default dtype

    e = nd.arange(0, 10, 2)
    np.testing.assert_allclose(e.asnumpy(), [0, 2, 4, 6, 8])


def test_arithmetic():
    a = nd.array([[1.0, 2.0], [3.0, 4.0]])
    b = nd.array([[10.0, 20.0], [30.0, 40.0]])
    np.testing.assert_allclose((a + b).asnumpy(), [[11, 22], [33, 44]])
    np.testing.assert_allclose((b - a).asnumpy(), [[9, 18], [27, 36]])
    np.testing.assert_allclose((a * 2).asnumpy(), [[2, 4], [6, 8]])
    np.testing.assert_allclose((2 * a).asnumpy(), [[2, 4], [6, 8]])
    np.testing.assert_allclose((1 / a).asnumpy(), 1.0 / a.asnumpy())
    np.testing.assert_allclose((a ** 2).asnumpy(), a.asnumpy() ** 2)
    np.testing.assert_allclose((-a).asnumpy(), -a.asnumpy())
    np.testing.assert_allclose((a > 2).asnumpy(), (a.asnumpy() > 2).astype("f4"))


def test_broadcast():
    a = nd.ones((2, 1, 3))
    b = nd.ones((1, 4, 3))
    assert (a + b).shape == (2, 4, 3)
    c = nd.broadcast_to(nd.ones((1, 3)), shape=(4, 3))
    assert c.shape == (4, 3)


def test_reshape_special_codes():
    x = nd.zeros((2, 3, 4))
    assert x.reshape((6, 4)).shape == (6, 4)
    assert x.reshape((-1,)).shape == (24,)
    assert x.reshape((0, -1)).shape == (2, 12)
    assert x.reshape((-2,)).shape == (2, 3, 4)
    assert x.reshape((-3, 4)).shape == (6, 4)
    assert x.reshape((-4, 1, 2, -2)).shape == (1, 2, 3, 4)
    assert x.reshape((0, 0, -1)).shape == (2, 3, 4)


def test_indexing():
    x = nd.array(np.arange(24).reshape(2, 3, 4))
    np.testing.assert_allclose(x[1].asnumpy(), np.arange(24).reshape(2, 3, 4)[1])
    np.testing.assert_allclose(x[:, 1].asnumpy(),
                               np.arange(24).reshape(2, 3, 4)[:, 1])
    np.testing.assert_allclose(x[0, 1, 2].asnumpy(), 6)
    x[0] = 0
    assert (x.asnumpy()[0] == 0).all()
    x[:] = 5
    assert (x.asnumpy() == 5).all()


def test_reduce_ops():
    x = nd.array(np.arange(12, dtype="f4").reshape(3, 4))
    np.testing.assert_allclose(x.sum().asnumpy(), 66)
    np.testing.assert_allclose(nd.sum(x, axis=0).asnumpy(),
                               x.asnumpy().sum(axis=0))
    np.testing.assert_allclose(nd.sum(x, axis=1, keepdims=True).asnumpy(),
                               x.asnumpy().sum(axis=1, keepdims=True))
    np.testing.assert_allclose(nd.mean(x).asnumpy(), x.asnumpy().mean())
    np.testing.assert_allclose(nd.max(x, axis=1).asnumpy(),
                               x.asnumpy().max(axis=1))
    np.testing.assert_allclose(nd.argmax(x, axis=1).asnumpy(),
                               x.asnumpy().argmax(axis=1).astype("f4"))
    # exclude=True reduces over the complement
    np.testing.assert_allclose(nd.sum(x, axis=0, exclude=True).asnumpy(),
                               x.asnumpy().sum(axis=1))


def test_dot():
    a = nd.array(np.random.rand(3, 4).astype("f4"))
    b = nd.array(np.random.rand(4, 5).astype("f4"))
    np.testing.assert_allclose(nd.dot(a, b).asnumpy(),
                               a.asnumpy() @ b.asnumpy(), rtol=1e-5)
    np.testing.assert_allclose(
        nd.dot(a, b.T, transpose_b=True).asnumpy()[0, 0],
        (a.asnumpy() @ b.asnumpy())[0, 0], rtol=1e-5)


def test_concat_split_stack():
    a = nd.ones((2, 3))
    b = nd.zeros((2, 3))
    c = nd.concat(a, b, dim=0)
    assert c.shape == (4, 3)
    parts = nd.split(c, num_outputs=2, axis=0)
    assert len(parts) == 2 and parts[0].shape == (2, 3)
    s = nd.stack(a, b, axis=0)
    assert s.shape == (2, 2, 3)


def test_astype_copy_context():
    a = nd.ones((2, 2))
    b = a.astype("float64")
    assert b.dtype == np.float64
    c = a.copyto(mx.cpu())
    assert c.shape == (2, 2)
    d = a.as_in_context(mx.cpu())
    assert d.context.device_type == "cpu"


def test_take_embedding_onehot():
    w = nd.array(np.arange(12, dtype="f4").reshape(4, 3))
    idx = nd.array([0, 2], dtype="int32")
    out = nd.take(w, idx)
    np.testing.assert_allclose(out.asnumpy(),
                               w.asnumpy()[[0, 2]])
    emb = nd.Embedding(idx, w, input_dim=4, output_dim=3)
    np.testing.assert_allclose(emb.asnumpy(), w.asnumpy()[[0, 2]])
    oh = nd.one_hot(idx, depth=4)
    np.testing.assert_allclose(oh.asnumpy(), np.eye(4, dtype="f4")[[0, 2]])


def test_topk_sort():
    x = nd.array([[3.0, 1.0, 2.0], [0.0, 5.0, 4.0]])
    idx = nd.topk(x, k=1)
    np.testing.assert_allclose(idx.asnumpy().reshape(-1), [0, 1])
    v = nd.topk(x, k=2, ret_typ="value")
    np.testing.assert_allclose(v.asnumpy(), [[3, 2], [5, 4]])
    s = nd.sort(x)
    np.testing.assert_allclose(s.asnumpy(), np.sort(x.asnumpy()))


def test_save_load(tmp_path):
    fname = str(tmp_path / "arrays.params")
    data = {"w": nd.ones((2, 2)), "b": nd.zeros((3,))}
    nd.save(fname, data)
    loaded = nd.load(fname)
    assert set(loaded) == {"w", "b"}
    np.testing.assert_allclose(loaded["w"].asnumpy(), 1)

    nd.save(fname, [nd.ones((2,))])
    loaded = nd.load(fname)
    assert isinstance(loaded, list) and len(loaded) == 1


def test_random():
    mx.random.seed(42)
    a = nd.random.uniform(0, 1, shape=(100,))
    mx.random.seed(42)
    b = nd.random.uniform(0, 1, shape=(100,))
    np.testing.assert_allclose(a.asnumpy(), b.asnumpy())
    assert 0 <= a.asnumpy().min() and a.asnumpy().max() <= 1
    n = nd.random.normal(0, 1, shape=(1000,))
    assert abs(n.asnumpy().mean()) < 0.2


def test_waitall_and_engine():
    a = nd.ones((10, 10))
    for _ in range(5):
        a = a * 2
    nd.waitall()
    assert a.asnumpy()[0, 0] == 32


def test_scalar_conversion():
    a = nd.array([3.5])
    assert float(a) == 3.5
    assert a.asscalar() == np.float32(3.5)
    with pytest.raises(ValueError):
        bool(nd.ones((2,)))
