"""Autograd tape tests (reference tests/python/unittest/test_autograd.py)."""
import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, autograd


def test_simple_grad():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x + 2 * x
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 2 * x.asnumpy() + 2)


def test_chain_and_broadcast():
    x = nd.array(np.random.rand(3, 4).astype("f4"))
    w = nd.array(np.random.rand(5, 4).astype("f4"))
    x.attach_grad()
    w.attach_grad()
    with autograd.record():
        y = nd.dot(x, w, transpose_b=True)
        z = nd.sum(nd.relu(y))
    z.backward()
    # reference grads via numpy
    yv = x.asnumpy() @ w.asnumpy().T
    dz = (yv > 0).astype("f4")
    np.testing.assert_allclose(x.grad.asnumpy(), dz @ w.asnumpy(), rtol=1e-5)
    np.testing.assert_allclose(w.grad.asnumpy(), dz.T @ x.asnumpy(), rtol=1e-5)


def test_head_gradient():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 3
    y.backward(nd.array([10.0, 100.0]))
    np.testing.assert_allclose(x.grad.asnumpy(), [30.0, 300.0])


def test_grad_req_add():
    x = nd.array([2.0])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with autograd.record():
            y = x * x
        y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [12.0])


def test_pause_and_detach():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        with autograd.pause():
            z = y * 2  # not recorded
        w = y + 1
    w.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [4.0])

    x2 = nd.array([3.0])
    x2.attach_grad()
    with autograd.record():
        y2 = (x2 * x2).detach() * x2
    y2.backward()
    np.testing.assert_allclose(x2.grad.asnumpy(), [9.0])


def test_training_flags():
    assert not autograd.is_recording()
    assert not autograd.is_training()
    with autograd.record():
        assert autograd.is_recording()
        assert autograd.is_training()
        with autograd.predict_mode():
            assert not autograd.is_training()
    with autograd.record(train_mode=False):
        assert not autograd.is_training()


def test_autograd_grad_api():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = nd.sum(x * x)
    (gx,) = autograd.grad([y], [x])
    np.testing.assert_allclose(gx.asnumpy(), [2.0, 4.0])


def test_stop_gradient():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x + nd.BlockGrad(x * 5)
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [4.0])


def test_multi_output_op_grad():
    x = nd.array(np.arange(8, dtype="f4").reshape(2, 4))
    x.attach_grad()
    with autograd.record():
        parts = nd.split(x, num_outputs=2, axis=1)
        y = nd.sum(parts[0] * 2) + nd.sum(parts[1] * 3)
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(),
                               [[2, 2, 3, 3], [2, 2, 3, 3]])


def test_function():
    class Sigmoid(autograd.Function):
        def forward(self, x):
            y = nd.sigmoid(x)
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            (y,) = self.saved_tensors
            return dy * y * (1 - y)

    f = Sigmoid()
    x = nd.array(np.random.rand(5).astype("f4"))
    x.attach_grad()
    with autograd.record():
        y = f(x)
    y.backward()
    s = 1 / (1 + np.exp(-x.asnumpy()))
    np.testing.assert_allclose(x.grad.asnumpy(), s * (1 - s), rtol=1e-5)


def test_dropout_respects_mode():
    x = nd.ones((100, 100))
    with autograd.record(train_mode=False):
        y = nd.Dropout(x, p=0.5)
    assert (y.asnumpy() == 1).all()
    with autograd.record(train_mode=True):
        y = nd.Dropout(x, p=0.5)
    frac = (y.asnumpy() == 0).mean()
    assert 0.3 < frac < 0.7


def test_batchnorm_backward_with_aux():
    """Regression: vjp through ops with aux-state outputs (BatchNorm train)."""
    x = nd.array(np.random.rand(4, 3, 2, 2).astype("f4"))
    x.attach_grad()
    gamma, beta = nd.ones((3,)), nd.zeros((3,))
    mmean, mvar = nd.zeros((3,)), nd.ones((3,))
    with autograd.record():
        y = nd.BatchNorm(x, gamma, beta, mmean, mvar, fix_gamma=False)
        z = nd.sum(y)
    z.backward()
    assert x.grad is not None
    assert np.isfinite(x.grad.asnumpy()).all()


def test_slicing_gradient_flows():
    """Regression: basic and advanced indexing must be recorded on the tape."""
    x = nd.array(np.arange(6, dtype="f4").reshape(3, 2))
    x.attach_grad()
    with autograd.record():
        y = nd.sum(x[0:2] * 2.0)
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [[2, 2], [2, 2], [0, 0]])

    x2 = nd.array(np.arange(6, dtype="f4").reshape(3, 2))
    x2.attach_grad()
    idx = nd.array([0, 2], dtype="int32")
    with autograd.record():
        y2 = nd.sum(x2[idx] * 3.0)
    y2.backward()
    np.testing.assert_allclose(x2.grad.asnumpy(), [[3, 3], [0, 0], [3, 3]])


def test_out_kwarg_rejected_under_recording():
    import pytest
    x = nd.ones((2,))
    x.attach_grad()
    y = nd.zeros((2,))
    with pytest.raises(mx.MXNetError):
        with autograd.record():
            nd.relu(x, out=y)


def test_boolean_mask_index_raises():
    import pytest
    x = nd.array([1.0, -1.0, 2.0])
    mask = np.array([True, False, True])
    with pytest.raises(mx.MXNetError):
        x[mask]


def test_tape_cleared_on_new_record_scope():
    """Forward-only record() scopes must not leak tape entries
    (a fresh outermost record starts a new graph)."""
    x = nd.ones((2,))
    x.attach_grad()
    for _ in range(5):
        with autograd.record():
            y = nd.relu(x) * 2
    from incubator_mxnet_tpu.autograd import _st
    assert len(_st().tape) == 2  # only the last scope's entries survive
    y.backward()  # standard pattern: backward after scope exit still works
    np.testing.assert_allclose(x.grad.asnumpy(), [2.0, 2.0])


def test_higher_order_grad_through_backward():
    # d/dx of (dy/dx)^2 where y = x^3: dy/dx = 3x^2, z = 9x^4, dz/dx = 36x^3
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x * x
        dy_dx = autograd.grad(y, [x], create_graph=True, retain_graph=True)[0]
        z = nd.sum(dy_dx * dy_dx)
    z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 36 * x.asnumpy() ** 3,
                               rtol=1e-5)


def test_second_derivative_two_grad_calls():
    # d2/dx2 sin(x) = -sin(x)
    x = nd.array([0.3, 1.1, -0.7])
    x.attach_grad()
    with autograd.record():
        y = nd.sin(x)
        g1 = autograd.grad(y, [x], create_graph=True, retain_graph=True)[0]
        g2 = autograd.grad(g1, [x], create_graph=False, retain_graph=False)[0]
    np.testing.assert_allclose(g1.asnumpy(), np.cos(x.asnumpy()), rtol=1e-5)
    np.testing.assert_allclose(g2.asnumpy(), -np.sin(x.asnumpy()), rtol=1e-5)
