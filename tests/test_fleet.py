"""Cross-host serving fleet (the ISSUE-12 acceptance gates).

Covers: the autoscaler's decision logic in ISOLATION — seeded est-wait
traces over an injected clock drive scale-up on sustained breach,
scale-down on sustained idle, hysteresis (a flapping signal decides
nothing), cooldown rate-limiting, and the min/max budget clamps, all
deterministically with no threads or subprocesses; anti-affinity
placement over the host registry; host death marking every replica on
the host dead at once with backfill on survivors (and its latency
recorded); the `fleet.spawn` fault site + per-host spawn breakers;
`stats()`/`runtime_report()` surfacing; the `fixed-fleet` lint; the
`ReplicaSpec` wire round-trip and membership host labels; and one
real-subprocess host-kill -> re-placement e2e over `serving.hostd`
process groups.
"""
import os
import signal
import time

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import analysis, io, sym
from incubator_mxnet_tpu.base import MXNetError
from incubator_mxnet_tpu.dist.membership import MembershipTable
from incubator_mxnet_tpu.resilience import faults
from incubator_mxnet_tpu.serving import (AgentHost, Autoscaler,
                                         FleetManager, InProcessHost,
                                         LocalReplica, ReplicaSpec,
                                         ServedModel)
from incubator_mxnet_tpu.serving.fleet import reset_findings


@pytest.fixture(autouse=True)
def _clean():
    faults.clear()
    reset_findings()
    yield
    faults.clear()
    reset_findings()


class _Clock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def tick(self, dt=1.0):
        self.t += dt
        return self.t


def _scaler(clock, **kw):
    cfg = dict(up_after_s=2.0, down_after_s=5.0, cooldown_s=10.0,
               min_replicas=1, max_replicas=4, idle_fraction=0.1,
               clock=clock)
    cfg.update(kw)
    return Autoscaler(100.0, **cfg)


# -- autoscaler decision logic, no threads, no subprocesses ------------------

def test_autoscaler_scale_up_needs_sustained_breach():
    clock = _Clock()
    a = _scaler(clock)
    # one-tick burst: no decision (the streak is 0s old)
    assert a.observe(500.0, 1, False) == (None, None)
    clock.tick(1.0)
    assert a.observe(500.0, 1, False) == (None, None)   # 1s < up_after 2s
    clock.tick(1.5)
    action, reason = a.observe(500.0, 1, False)
    assert action == "up"
    assert "500 ms > SLO 100" in reason and "sustained" in reason


def test_autoscaler_none_signal_is_a_breach():
    # est-wait None = no live capacity at all — the strongest breach
    clock = _Clock()
    a = _scaler(clock)
    a.observe(None, 0, False)
    clock.tick(2.5)
    action, reason = a.observe(None, 0, False)
    assert action == "up"
    assert "no live capacity" in reason


def test_autoscaler_cooldown_rate_limits():
    clock = _Clock()
    a = _scaler(clock, cooldown_s=10.0)
    a.observe(500.0, 1, False)
    clock.tick(2.5)
    assert a.observe(500.0, 1, False)[0] == "up"
    # breach continues: inside the cooldown NOTHING fires, even with the
    # streak re-accumulated far past up_after_s
    for _ in range(9):
        clock.tick(1.0)
        assert a.observe(500.0, 2, False) == (None, None)
    clock.tick(1.5)
    assert a.observe(500.0, 2, False)[0] == "up"


def test_autoscaler_scale_down_needs_sustained_idle_and_not_busy():
    clock = _Clock()
    a = _scaler(clock, down_after_s=5.0, cooldown_s=0.0)
    a.observe(2.0, 3, False)
    clock.tick(4.0)
    assert a.observe(2.0, 3, False) == (None, None)     # 4s < 5s
    clock.tick(2.0)
    action, reason = a.observe(2.0, 3, False)
    assert action == "down"
    assert "idle threshold sustained" in reason
    # in-flight work vetoes idleness no matter how low the estimate is
    a2 = _scaler(clock, down_after_s=1.0, cooldown_s=0.0)
    a2.observe(2.0, 3, True)
    clock.tick(50.0)
    assert a2.observe(2.0, 3, True) == (None, None)


def test_autoscaler_hysteresis_dead_band_resets_streaks():
    clock = _Clock()
    a = _scaler(clock, cooldown_s=0.0)
    # breach accumulates 1.5s, then one dead-band sample (between the
    # idle threshold 10ms and the SLO 100ms) resets it — the next
    # breach starts from zero
    a.observe(500.0, 1, False)
    clock.tick(1.5)
    assert a.observe(50.0, 1, False) == (None, None)
    clock.tick(1.5)
    assert a.observe(500.0, 1, False) == (None, None)   # streak only 0s
    clock.tick(1.0)
    assert a.observe(500.0, 1, False) == (None, None)   # 1.0s < 2s
    clock.tick(1.5)
    assert a.observe(500.0, 1, False)[0] == "up"


def test_autoscaler_flapping_signal_never_thrashes():
    # a square wave around the SLO, sampled every second for 10 minutes:
    # zero decisions, because neither streak ever reaches its window
    clock = _Clock()
    a = _scaler(clock, cooldown_s=1.0)
    decisions = []
    for i in range(600):
        clock.tick(1.0)
        act, _ = a.observe(500.0 if i % 2 else 50.0, 2, False)
        if act:
            decisions.append(act)
    assert decisions == []


def test_autoscaler_budget_clamps_and_counts():
    clock = _Clock()
    a = _scaler(clock, min_replicas=2, max_replicas=3, cooldown_s=0.0)
    a.observe(500.0, 3, False)
    clock.tick(3.0)
    assert a.observe(500.0, 3, False) == (None, None)   # at max
    assert a.clamped_at_max >= 1
    a.observe(1.0, 2, False)
    clock.tick(6.0)
    assert a.observe(1.0, 2, False) == (None, None)     # at min
    assert a.clamped_at_min >= 1
    with pytest.raises(MXNetError, match="budget"):
        Autoscaler(100.0, up_after_s=1, down_after_s=1, cooldown_s=1,
                   min_replicas=3, max_replicas=2)


def test_autoscaler_seeded_trace_is_deterministic():
    # the same seeded est-wait trace must produce the identical decision
    # sequence — the property the chaos/bench gates lean on
    def run():
        rng = np.random.RandomState(7)
        clock = _Clock()
        a = _scaler(clock, cooldown_s=5.0)
        live, out = 1, []
        for i in range(400):
            clock.tick(1.0)
            wait = float(rng.choice([2.0, 60.0, 500.0, 800.0]))
            act, _ = a.observe(wait, live, False)
            if act == "up":
                live += 1
            elif act == "down":
                live -= 1
            out.append((i, act))
        return out
    first, second = run(), run()
    assert first == second
    assert any(act == "up" for _, act in first)


# -- fleet manager over in-process hosts -------------------------------------

def _model_parts(in_dim=6, hidden=16, seed=0):
    np.random.seed(seed)
    mx.random.seed(seed)
    net = sym.Variable("data")
    net = sym.FullyConnected(net, num_hidden=hidden, name="fc0")
    net = sym.Activation(net, act_type="tanh")
    net = sym.FullyConnected(net, num_hidden=3, name="head")
    net = sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[io.DataDesc("data", (4, in_dim))],
             label_shapes=[io.DataDesc("softmax_label", (4,))],
             for_training=False, grad_req="null")
    mod.init_params(mx.initializer.Xavier())
    args, auxs = mod.get_params()
    return net, args, auxs


def _local_spawner(net, args, auxs, in_dim=6, buckets=(1, 2)):
    def spawn(spec, replica_id):
        model = ServedModel(net, args, auxs,
                            data_shapes=[("data", (1, in_dim))],
                            buckets=buckets, ctx=mx.cpu(), name=spec.name)
        return LocalReplica(model, replica_id=replica_id)
    return spawn


def _fleet(n_hosts=2, fail_spawn_on=(), **fleet_kw):
    net, args, auxs = _model_parts()
    spawn = _local_spawner(net, args, auxs)

    def maybe_failing(host_id):
        if host_id not in fail_spawn_on:
            return spawn

        def failing(spec, replica_id):
            raise MXNetError(f"host {host_id} cannot spawn")
        return failing

    hosts = [InProcessHost(f"host-{i}", maybe_failing(f"host-{i}"))
             for i in range(n_hosts)]
    cfg = dict(target_replicas=2, min_replicas=1, max_replicas=4,
               slo_ms=50.0, tick_s=0.05, up_after_s=0.2,
               down_after_s=0.4, cooldown_s=0.3, host_heartbeat_s=0.1,
               host_deadline_s=0.6)
    cfg.update(fleet_kw)
    spec = ReplicaSpec(data_shapes=[("data", (1, 6))], name="m",
                       buckets=(1, 2))
    return FleetManager(hosts, spec, **cfg), hosts


def _wait_for(pred, timeout=15.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def test_placement_anti_affinity_spreads_hosts():
    fm, hosts = _fleet(n_hosts=3, target_replicas=3, max_replicas=6)
    with fm:
        st = fm.stats()
        assert sorted(st["placement"].values()) == \
            ["host-0", "host-1", "host-2"]
        assert all(h["replicas"] == 1 for h in st["hosts"].values())
        x = np.random.randn(2, 6).astype(np.float32)
        assert len(fm.router.predict({"data": x}, timeout_ms=10000)) == 1


def test_host_down_marks_all_its_replicas_and_backfills():
    fm, hosts = _fleet(n_hosts=2, target_replicas=4, min_replicas=4,
                       max_replicas=6, down_after_s=60.0)
    with fm:
        st = fm.stats()
        assert all(h["replicas"] == 2 for h in st["hosts"].values())
        hosts[1].fail()
        assert _wait_for(lambda: fm.stats()["hosts_lost"] == 1)
        assert _wait_for(lambda: fm.stats()["backfills"] == 1)
        st = fm.stats()
        # all capacity re-placed on the survivor, latency recorded
        assert st["live_replicas"] == 4
        assert set(st["placement"].values()) == {"host-0"}
        assert st["backfill_latency_s"] is not None
        assert st["hosts"]["host-1"]["alive"] is False
        downs = [e for e in st["events"] if e["action"] == "host_down"]
        assert len(downs) == 1 and downs[0]["host"] == "host-1"
        assert downs[0]["replicas"] == 2
        assert "silence" in downs[0]["reason"]
        # both replicas died AT ONCE (router saw two losses), and the
        # fleet still serves
        assert fm.router.stats()["replicas_lost"] >= 2
        x = np.random.randn(1, 6).astype(np.float32)
        assert len(fm.router.predict({"data": x}, timeout_ms=10000)) == 1


def test_host_rejoin_after_recovery():
    fm, hosts = _fleet(n_hosts=2, target_replicas=2, min_replicas=2,
                       down_after_s=60.0)
    with fm:
        hosts[0].fail()
        assert _wait_for(lambda: fm.stats()["hosts_lost"] == 1)
        hosts[0].recover()
        assert _wait_for(
            lambda: fm.stats()["hosts"]["host-0"]["alive"])
        st = fm.stats()
        assert any(e["action"] == "host_rejoined" for e in st["events"])


def test_autoscaler_drives_fleet_up_and_down():
    fm, hosts = _fleet(n_hosts=2, target_replicas=1, min_replicas=1,
                       max_replicas=3, up_after_s=0.15, down_after_s=0.3,
                       cooldown_s=0.1)
    wait = [0.0]
    with fm:
        fm.router.estimated_wait_s = lambda: wait[0]
        wait[0] = 1.0    # 1000ms >> 50ms SLO
        assert _wait_for(lambda: fm.stats()["live_replicas"] == 3, 10)
        st = fm.stats()
        assert st["scale_ups"] >= 2
        ups = [e for e in st["events"] if e["action"] == "scale_up"
               and "SLO" in str(e.get("reason"))]
        assert ups, st["events"]
        # anti-affinity held through the scale-up
        assert len(set(st["placement"].values())) == 2
        wait[0] = 0.0    # idle: back to the floor through the drain path
        assert _wait_for(lambda: fm.stats()["live_replicas"] == 1, 10)
        # the counter lands AFTER the drain completes — poll it too
        assert _wait_for(lambda: fm.stats()["scale_downs"] >= 2, 10)
        st = fm.stats()
        assert st["signal"]["est_wait_ms"] == 0.0


def test_scale_up_never_lowers_target_mid_backfill():
    # a host loss drops live under target while the flood keeps the
    # signal breached: the resulting "up" must not shrink the backfill
    # goal to live+1 (the bug: target=min(live+1, max) could drop a
    # 4-target fleet to 3 forever, violating the min floor)
    fm, hosts = _fleet(n_hosts=2, target_replicas=4, min_replicas=4,
                       max_replicas=6, down_after_s=600.0)
    with fm:
        assert _wait_for(lambda: fm.stats()["live_replicas"] == 4)
        # force the autoscaler into an actionable breach NOW, with
        # live transiently under target (as right after a host death)
        fm.router.estimated_wait_s = lambda: 10.0   # 10s >> 50ms SLO
        fm.autoscaler._breach_since = time.monotonic() - 100.0
        fm.autoscaler._cooldown_until = 0.0
        live = fm._live_replicas()
        fm.router.remove_replica(live[0], drain=False)
        fm.router.remove_replica(live[1], drain=False)
        with fm._lock:
            fm._placement.pop(live[0], None)
            fm._placement.pop(live[1], None)
        fm._autoscale_tick()
        assert fm.target >= 4, fm.target   # goal never shrank
        assert _wait_for(lambda: fm.stats()["live_replicas"] >= 4)


def test_host_death_declared_while_spawn_in_progress():
    # the watch loop must declare a dead host while the placer is deep
    # in a slow spawn — actuation never blocks liveness (one control
    # loop doing both would defer declare_lost by the whole spawn)
    net, args, auxs = _model_parts()
    base = _local_spawner(net, args, auxs)

    def slow(spec, rid):
        time.sleep(3.0)
        return base(spec, rid)

    hosts = [InProcessHost("host-0", slow), InProcessHost("host-1", base)]
    spec = ReplicaSpec(data_shapes=[("data", (1, 6))], name="m",
                       buckets=(1, 2))
    fm = FleetManager(hosts, spec, target_replicas=2, min_replicas=2,
                      max_replicas=4, slo_ms=50.0, tick_s=0.05,
                      up_after_s=0.2, down_after_s=600.0, cooldown_s=0.3,
                      host_heartbeat_s=0.1, host_deadline_s=0.5)
    with fm:
        assert _wait_for(lambda: fm.stats()["live_replicas"] == 2)
        fm.router.estimated_wait_s = lambda: 10.0   # sustained breach
        assert _wait_for(lambda: fm.stats()["target"] >= 3, 10)
        time.sleep(0.3)   # the placer is inside host-0's 3s spawn now
        t0 = time.monotonic()
        hosts[1].fail()
        assert _wait_for(lambda: fm.stats()["hosts_lost"] == 1, 5)
        assert time.monotonic() - t0 < 2.0   # deadline 0.5s, not 3s+


def test_scale_down_cancels_pending_backfill_measurement():
    # a backfill that cannot complete (all spawns failing) followed by
    # an idle scale-down: target meets the SHRUNKEN live count, which
    # must NOT be reported as a successful backfill with idle-period
    # latency
    fm, hosts = _fleet(n_hosts=2, target_replicas=2, min_replicas=0,
                       max_replicas=4, down_after_s=0.3)
    with fm:
        assert _wait_for(lambda: fm.stats()["live_replicas"] == 2)

        def no_spawn(spec, rid):
            raise MXNetError("host wedged")

        for h in hosts:
            h._spawn = no_spawn
        fm.router.declare_lost(fm._live_replicas()[0])
        assert _wait_for(lambda: fm.stats()["live_replicas"] == 1, 10)
        assert _wait_for(lambda: fm._backfill_started is not None, 5)
        fm.router.estimated_wait_s = lambda: 0.0    # idle
        assert _wait_for(lambda: fm.stats()["scale_downs"] >= 1, 10)
        time.sleep(0.4)   # a few placer ticks with live >= target
        st = fm.stats()
        assert st["backfills"] == 0
        assert st["backfill_latency_s"] is None
        assert not [e for e in st["events"]
                    if e["action"] == "backfill_complete"]


def test_hostd_spawn_is_idempotent_by_rid(monkeypatch):
    # a timed-out / lost spawn reply is RESENT by the channel: the
    # daemon must answer with the live worker's endpoint, not launch an
    # orphan second worker for the same replica id
    from incubator_mxnet_tpu.serving import hostd as hostd_mod
    from incubator_mxnet_tpu.serving import replica as replica_mod

    class _FakeProc:
        def __init__(self, pid):
            self.pid = pid

        def poll(self):
            return None

    launches = []

    def fake_launch_worker(cmd, **kw):
        launches.append(cmd)
        return _FakeProc(1000 + len(launches)), 9000 + len(launches), \
            {"compiles": 0}

    monkeypatch.setattr(replica_mod, "launch_worker", fake_launch_worker)
    daemon = hostd_mod.HostDaemon("host-x")
    try:
        spec = ReplicaSpec(data_shapes=[("data", (1, 6))], name="m")
        msg = {"cmd": "spawn", "spec": spec.to_msg(), "replica_id": "r1"}
        first = daemon._handle(dict(msg))
        resend = daemon._handle(dict(msg))
        assert first["port"] == resend["port"] == 9001
        assert first["pid"] == resend["pid"]
        assert len(launches) == 1          # exactly one real worker
        other = daemon._handle({"cmd": "spawn", "spec": spec.to_msg(),
                                "replica_id": "r2"})
        assert other["port"] == 9002 and len(launches) == 2
    finally:
        daemon._server.server_close()


def test_launch_worker_kills_silent_child_at_deadline():
    # a worker that stays ALIVE but never prints its handshake (wedged
    # model load) must not hang launch_worker past ready_timeout
    import sys
    from incubator_mxnet_tpu.serving.replica import launch_worker
    t0 = time.monotonic()
    with pytest.raises(MXNetError, match="readiness handshake"):
        launch_worker([sys.executable, "-c",
                       "import time; time.sleep(600)"],
                      name="wedged", ready_timeout=1.0)
    assert time.monotonic() - t0 < 30.0


def test_fleet_spawn_fault_site_and_breaker():
    # the first two spawn attempts die via the fleet.spawn site: the
    # fleet records the failures and still reaches target by retrying
    faults.configure("seed=51;fleet.spawn:error(at=1-2)")
    fm, hosts = _fleet(n_hosts=2, target_replicas=2, min_replicas=2,
                       down_after_s=60.0)
    with fm:
        assert _wait_for(lambda: fm.stats()["live_replicas"] == 2)
        st = fm.stats()
        assert st["spawn_failures"] == 2
        fails = [e for e in st["events"] if e["action"] == "spawn_failed"]
        assert len(fails) == 2
        assert all("fault-injected" in e["reason"] for e in fails)
        fired = [e for e in faults.trace()
                 if e.get("site") == "fleet.spawn"]
        assert len(fired) == 2


def test_spawn_breaker_skips_broken_host():
    # host-0 cannot spawn at all: its breaker opens and placement lands
    # everything on host-1 instead of wedging
    fm, hosts = _fleet(n_hosts=2, fail_spawn_on=("host-0",),
                       target_replicas=2, min_replicas=2,
                       down_after_s=60.0)
    with fm:
        assert _wait_for(lambda: fm.stats()["live_replicas"] == 2)
        st = fm.stats()
        assert set(st["placement"].values()) == {"host-1"}
        assert st["spawn_failures"] >= 1
        assert st["hosts"]["host-0"]["spawn_breaker"] in ("open",
                                                          "half-open")


def test_host_down_probe_drop_burst_does_not_kill_host():
    # a drop burst on the host.down site SHORTER than the deadline: the
    # host must stay alive (silence, not failure count, is death)
    faults.configure("seed=52;host.down:drop(at=2-4)")
    fm, hosts = _fleet(n_hosts=1, target_replicas=1, min_replicas=1,
                       host_heartbeat_s=0.05, host_deadline_s=2.0,
                       down_after_s=60.0)
    with fm:
        time.sleep(0.6)   # let the burst play out
        st = fm.stats()
        fired = [e for e in faults.trace() if e.get("site") == "host.down"]
        assert len(fired) >= 3
        assert st["hosts_lost"] == 0
        assert st["hosts"]["host-0"]["alive"] is True
        assert st["hosts"]["host-0"]["hb_failures"] == 0   # recovered


def test_fleet_stats_and_runtime_report():
    fm, hosts = _fleet(n_hosts=2, target_replicas=2, min_replicas=2,
                       down_after_s=60.0)
    with fm:
        hosts[1].fail()
        assert _wait_for(lambda: fm.stats()["backfills"] == 1)
        st = fm.stats()
        for key in ("fleet", "target", "live_replicas", "placement",
                    "hosts", "events", "scale_ups", "scale_downs",
                    "hosts_lost", "backfills", "backfill_latency_s",
                    "signal"):
            assert key in st, key
        assert set(st["signal"]) >= {"est_wait_ms", "slo_ms", "breach_s",
                                     "idle_s", "cooldown_remaining_s"}
        report = analysis.runtime_report()
        codes = {f.code for f in report
                 if f.pass_name == "serving.fleet"}
        assert "host-lost" in codes
        assert "backfill" in codes
        assert "summary" in codes


def test_replica_spec_wire_roundtrip():
    spec = ReplicaSpec(data_shapes=[("data", (1, 6)), ("mask", (1, 3))],
                       name="m", prefix="/tmp/m", epoch=3,
                       buckets=(1, 4), env={"A": "1"}, concurrency=3)
    back = ReplicaSpec.from_msg(spec.to_msg())
    assert back.data_shapes == spec.data_shapes
    assert back.prefix == spec.prefix and back.epoch == 3
    assert back.buckets == (1, 4)
    assert back.env == {"A": "1"} and back.concurrency == 3


def test_membership_labels_in_view():
    clock = _Clock()
    table = MembershipTable(2, deadline_s=5.0, clock=clock)
    table.heartbeat(0, 0, label="host-a")
    table.heartbeat(1, 0, label="host-b")
    view = table.view()
    assert view["labels"] == {0: "host-a", 1: "host-b"}


def test_agent_host_connect_by_endpoint():
    # the production cross-host path: hostd already running somewhere,
    # the fleet attaches by endpoint (every parse_endpoint spelling)
    from incubator_mxnet_tpu.dist.transport import parse_endpoint
    from incubator_mxnet_tpu.serving.hostd import HostDaemon
    assert parse_endpoint("10.0.0.1:9000") == ("10.0.0.1", 9000)
    assert parse_endpoint(":9000") == ("127.0.0.1", 9000)
    assert parse_endpoint("9000") == ("127.0.0.1", 9000)
    with pytest.raises(ValueError):
        parse_endpoint("nonsense")
    daemon = HostDaemon("host-x").start()
    try:
        agents = [AgentHost.connect("host-x", f"127.0.0.1:{daemon.port}"),
                  AgentHost.connect("host-x", str(daemon.port))]
        for agent in agents:
            hb = agent.heartbeat()
            assert hb["host_id"] == "host-x" and hb["workers"] == 0
            # close channels only: agent.close() sends the daemon
            # "stop", which exits the PROCESS — ours, in this test
            agent._control.close()
            agent._spawn_chan.close()
    finally:
        daemon.shutdown()


def test_fixed_fleet_lint_fixtures():
    flagged = analysis.check_source(
        "router = ReplicaRouter([r0, r1, r2])\n"
        "fm = FleetManager(hosts, spec, router=router)\n", "t.py")
    assert [f.code for f in flagged] == ["fixed-fleet"]
    comp = analysis.check_source(
        "router = ReplicaRouter([spawn(i) for i in range(3)])\n"
        "a = Autoscaler(100.0)\n", "t.py")
    assert [f.code for f in comp] == ["fixed-fleet"]
    # a fixed list WITHOUT fleet config is the plain PR-8 idiom: clean
    assert not list(analysis.check_source(
        "router = ReplicaRouter([r0, r1])\n", "t.py"))
    # the blessed idiom: the manager owns membership
    assert not list(analysis.check_source(
        "fm = FleetManager(hosts, spec)\nout = fm.router.predict(x)\n",
        "t.py"))
    # suppression works
    assert not list(analysis.check_source(
        "router = ReplicaRouter([r0])  # mxlint: disable=fixed-fleet\n"
        "fm = FleetManager(hosts, spec, router=router)\n", "t.py"))


def test_fleet_knobs_registered():
    from incubator_mxnet_tpu import config
    for knob in ("MXNET_FLEET_TICK_S", "MXNET_FLEET_SLO_MS",
                 "MXNET_FLEET_UP_AFTER_S", "MXNET_FLEET_DOWN_AFTER_S",
                 "MXNET_FLEET_IDLE_FRACTION", "MXNET_FLEET_COOLDOWN_S",
                 "MXNET_FLEET_MIN_REPLICAS", "MXNET_FLEET_MAX_REPLICAS",
                 "MXNET_FLEET_HOST_HEARTBEAT_S",
                 "MXNET_FLEET_HOST_DEADLINE_S"):
        assert knob in config.KNOBS, knob
        assert config.KNOBS[knob][2] == "honored", knob


# -- the real-subprocess host-kill e2e ---------------------------------------

@pytest.mark.slow
def test_host_kill_replacement_e2e(tmp_path):
    """Two real `serving.hostd` host daemons (process groups), one
    replica each; SIGKILLing one whole host group mid-traffic loses
    ZERO requests, the fleet detects the host via membership silence,
    fails its replica over, and backfills on the survivor with zero
    XLA compiles (the shared program-cache warm-spinup cert)."""
    net, args, auxs = _model_parts()
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[io.DataDesc("data", (4, 6))],
             label_shapes=[io.DataDesc("softmax_label", (4,))],
             for_training=False, grad_req="null")
    mod.set_params(args, auxs)
    prefix = str(tmp_path / "m")
    mod.save_checkpoint(prefix, 0)
    env = {"MXNET_PROGRAM_CACHE_DIR": str(tmp_path / "pcache"),
           "JAX_PLATFORMS": "cpu"}
    hosts = [AgentHost.launch_local("host-a", env=env),
             AgentHost.launch_local("host-b", env=env)]
    spec = ReplicaSpec(data_shapes=[("data", (1, 6))], name="m",
                       prefix=prefix, epoch=0, buckets=(1, 2), env=env)
    fm = FleetManager(hosts, spec, target_replicas=2, min_replicas=2,
                      max_replicas=4, slo_ms=50.0, tick_s=0.1,
                      up_after_s=0.3, down_after_s=60.0, cooldown_s=0.5,
                      host_heartbeat_s=0.2, host_deadline_s=1.5)
    try:
        st = fm.stats()
        assert sorted(st["placement"].values()) == ["host-a", "host-b"]
        x = np.random.randn(2, 6).astype(np.float32)
        import threading
        errors, results = [], []
        stop = threading.Event()

        def traffic():
            while not stop.is_set():
                try:
                    results.append(fm.router.predict(
                        {"data": x}, timeout_ms=30000))
                except Exception as exc:
                    errors.append(repr(exc))

        threads = [threading.Thread(target=traffic,
                                    name=f"mx-test-fleet-{i}")
                   for i in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.3)
        hosts[1].kill()   # SIGKILL the whole host process group
        assert _wait_for(lambda: fm.stats()["hosts_lost"] == 1, 20)
        assert _wait_for(lambda: fm.stats()["backfills"] == 1, 30)
        stop.set()
        for t in threads:
            t.join()
        assert not errors, errors[:5]          # zero lost requests
        assert len(results) > 0
        st = fm.stats()
        assert st["live_replicas"] == 2
        assert set(st["placement"].values()) == {"host-a"}  # re-placed
        backfills = [e for e in st["events"]
                     if e["action"] == "scale_up"
                     and "backfill" in str(e.get("reason"))]
        assert backfills
        assert backfills[-1]["spinup_compiles"] == 0   # warm spinup
        # the killed daemon really is gone (whole process group)
        assert hosts[1].process.poll() is not None \
            or _wait_for(lambda: hosts[1].process.poll() is not None, 10)
    finally:
        try:
            fm.shutdown(drain=False, close_hosts=True)
        except Exception:
            pass
        for h in hosts:
            try:
                os.killpg(h.process.pid, signal.SIGKILL)
            except Exception:
                pass
