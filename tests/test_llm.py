"""Transformer LM (llm/): symbol construction, scan-over-layers dedup,
megatron sharding coverage, dp×tp fused-step training with guardian and
h2d ring active, bit-identical checkpoint/resume, and decode-plane
parity against the training graph."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import io, nd
from incubator_mxnet_tpu.io import DataBatch
from incubator_mxnet_tpu.llm import (LMConfig, lm_symbol, lm_block_op_count,
                                     stack_lm_params, init_kv_cache,
                                     DecodePrograms)


def _cfg(**kw):
    base = dict(vocab_size=40, num_layers=2, num_heads=2, hidden=16,
                max_len=48, eos_id=0)
    base.update(kw)
    return LMConfig(**base)


def _lm_data(cfg, n=64, bs=8, t=12, seed=3):
    """Synthetic periodic token stream: learnable next-token structure
    so the loss measurably falls within a few epochs."""
    rng = np.random.default_rng(seed)
    base = rng.integers(1, cfg.vocab_size, t + 1)
    x = np.empty((n, t), np.float32)
    y = np.empty((n, t), np.float32)
    for i in range(n):
        roll = np.roll(base, i % (t + 1))
        x[i] = roll[:t]
        y[i] = roll[1:]
    return io.NDArrayIter(x, y, batch_size=bs, shuffle=False,
                          label_name="softmax_label")


def _bind_lm(cfg, bs=8, t=12, ctxs=None):
    mod = mx.mod.Module(lm_symbol(cfg), context=ctxs or mx.cpu())
    mod.bind(data_shapes=[io.DataDesc("data", (bs, t))],
             label_shapes=[io.DataDesc("softmax_label", (bs, t))])
    mod.init_params(mx.initializer.Xavier())
    return mod


def _loss_on(mod, cfg, X, Y):
    b = DataBatch(data=[nd.array(X)], label=[nd.array(Y)])
    mod.forward(b, is_train=False)
    probs = mod.get_outputs()[0].asnumpy().reshape(-1, cfg.vocab_size)
    p = probs[np.arange(Y.size), Y.reshape(-1).astype(int)]
    return float(-np.log(p + 1e-12).mean())


# ---------------------------------------------------------------------------
# graph structure
# ---------------------------------------------------------------------------

def test_scan_plan_groups_transformer_stack():
    """Satellite check: `scan_plan` must group the N identical
    attention+MLP blocks as ONE run with the block's full multi-op
    period — the deduped-compile path for the LM.  (No rejection to
    record: the stack is clean-cut groupable.)"""
    from incubator_mxnet_tpu.analysis.graph_passes import scan_plan
    cfg = _cfg(num_layers=4)
    plan = scan_plan(lm_symbol(cfg), min_run=2)
    assert plan["rejected"] == []
    assert len(plan["runs"]) == 1
    run = plan["runs"][0]
    assert run["length"] == 4
    assert len(run["segments"][0]) == lm_block_op_count()


def test_fused_step_uses_scan_dedup():
    """The training-side lock on the deduped path: the fused step built
    from the LM symbol reports the 4-block stack as one scan run."""
    cfg = _cfg(num_layers=4)
    mod = _bind_lm(cfg)
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.05})
    it = _lm_data(cfg, n=16)
    metric = mx.metric.create("acc")
    for batch in it:
        mod.fit_step(batch, metric)
        break
    fs = mod._fused_step
    assert fs is not None and not fs.broken
    assert [l for _, l in fs.scan_runs] == [4], fs.scan_runs


def test_megatron_rules_cover_lm_params():
    """Every weight the LM declares lands on the intended megatron
    partition purely by name."""
    from incubator_mxnet_tpu.parallel.tensor_parallel import ShardingRules
    from jax.sharding import PartitionSpec as P
    rules = ShardingRules.megatron()
    cfg = _cfg()
    args = lm_symbol(cfg).list_arguments()
    col = [a for a in args if a.endswith(("qkv_weight", "fc1_weight"))]
    row = [a for a in args if a.endswith(("out_proj_weight", "fc2_weight"))]
    embed = [a for a in args if a.endswith("embed_weight")]
    assert col and row and len(embed) == 1
    for name in col + embed:
        assert rules.spec_for(name) == P("tp", None), name
    for name in row:
        assert rules.spec_for(name) == P(None, "tp"), name
    for name in args:
        if name.endswith("_bias"):
            assert rules.spec_for(name) == P(), name


# ---------------------------------------------------------------------------
# training
# ---------------------------------------------------------------------------

def test_lm_fit_composed_mesh_guardian_and_ring(monkeypatch):
    """The flagship train path: `Module.fit` fused steps on a composed
    dp×tp mesh, fed by the h2d ring, watched by the guardian — and the
    loss actually falls."""
    monkeypatch.setenv("MXNET_IO_RING", "1")
    monkeypatch.setenv("MXNET_GUARDIAN", "1")
    from incubator_mxnet_tpu import io_plane
    cfg = _cfg()
    ctxs = [mx.cpu(i) for i in range(8)]
    mod = mx.mod.Module(lm_symbol(cfg), context=ctxs)
    it = _lm_data(cfg, n=64, bs=8)
    X, Y = np.asarray(it.data[0][1]), np.asarray(it.label[0][1])
    ring_before = io_plane.stats()["batches"]
    mod.fit(it, num_epoch=4, kvstore="device", optimizer="sgd",
            optimizer_params={"learning_rate": 0.05},
            eval_metric="acc", initializer=mx.initializer.Xavier(),
            mesh="dp=4,tp=2")
    fs = mod._fused_step
    assert fs is not None and not fs.broken
    assert fs._dp_size == 4
    assert tuple(fs._mesh.axis_names) == ("dp", "tp")
    # guardian rode along and observed real steps
    g = mod._guardian
    assert g is not None and g.stats()["steps_observed"] > 0
    # the h2d staging ring fed the fit
    assert io_plane.stats()["batches"] > ring_before
    # loss fell vs the untrained init
    fresh = _bind_lm(cfg, bs=X.shape[0], t=X.shape[1])
    init_loss = _loss_on(fresh, cfg, X, Y)
    mod2 = mx.mod.Module(lm_symbol(cfg), context=mx.cpu())
    mod2.bind(data_shapes=[io.DataDesc("data", X.shape)],
              label_shapes=[io.DataDesc("softmax_label", Y.shape)],
              for_training=False, grad_req="null")
    args, auxs = mod.get_params()
    mod2.set_params(args, auxs)
    trained_loss = _loss_on(mod2, cfg, X, Y)
    assert trained_loss < init_loss * 0.9, (init_loss, trained_loss)
    for k, v in args.items():
        assert np.isfinite(v.asnumpy()).all(), k


class _Crash(Exception):
    pass


def _fit_lm(cfg, ckpt_dir=None, crash_at=None, resume=False, num_epoch=2):
    mx.random.seed(11)
    np.random.seed(11)
    mod = mx.mod.Module(lm_symbol(cfg), context=mx.cpu())
    cb = None
    if crash_at is not None:
        hits = {"n": 0}

        def cb(param):
            hits["n"] += 1
            if hits["n"] == crash_at:
                raise _Crash()
    try:
        mod.fit(_lm_data(cfg), num_epoch=num_epoch, optimizer="sgd",
                optimizer_params={"learning_rate": 0.05,
                                  "momentum": 0.9},
                eval_metric="acc", initializer=mx.initializer.Xavier(),
                checkpoint_dir=ckpt_dir, checkpoint_period=1,
                resume=resume, batch_end_callback=cb)
    except _Crash:
        pass
    return mod


def test_lm_checkpoint_resume_bit_identical(tmp_path):
    """Crash the LM fit mid-epoch under the elastic checkpointer,
    resume, and land bit-identical to the uninterrupted run."""
    cfg = _cfg()
    full = _fit_lm(cfg)
    _fit_lm(cfg, ckpt_dir=str(tmp_path), crash_at=9)
    resumed = _fit_lm(cfg, ckpt_dir=str(tmp_path), resume=True)
    fa, _ = full.get_params()
    ra, _ = resumed.get_params()
    assert fa.keys() == ra.keys()
    for k in fa:
        np.testing.assert_array_equal(fa[k].asnumpy(), ra[k].asnumpy(),
                                      err_msg=k)


# ---------------------------------------------------------------------------
# decode plane
# ---------------------------------------------------------------------------

def _trained(cfg, steps=10):
    mod = _bind_lm(cfg)
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.05})
    rng = np.random.default_rng(0)
    X = rng.integers(1, cfg.vocab_size, (8, 12)).astype(np.float32)
    Y = np.roll(X, -1, axis=1)
    b = DataBatch(data=[nd.array(X)], label=[nd.array(Y)])
    for _ in range(steps):
        mod.forward_backward(b)
        mod.update()
    return mod


def test_stack_lm_params_shapes_and_errors():
    cfg = _cfg()
    mod = _bind_lm(cfg)
    args, _ = mod.get_params()
    sp = stack_lm_params(args, cfg)
    L, C, H = cfg.num_layers, cfg.hidden, cfg.num_heads
    assert sp["embed"].shape == (cfg.vocab_size, C)
    assert sp["layers"]["qkv_weight"].shape == (L, 3 * C, C)
    assert sp["layers"]["fc2_weight"].shape == (L, C, cfg.ffn_mult * C)
    broken = dict(args)
    broken.pop([k for k in broken if k.endswith("block0_qkv_weight")][0])
    with pytest.raises(mx.MXNetError, match="qkv_weight"):
        stack_lm_params(broken, cfg)


def test_prefill_matches_training_graph():
    """The serving plane is the SAME function the training graph
    computes: prefill's next-token logits equal the full-sequence
    forward at the last position."""
    import jax
    import jax.numpy as jnp
    from incubator_mxnet_tpu import fused
    cfg = _cfg()
    mod = _trained(cfg)
    args, _ = mod.get_params()
    progs = DecodePrograms(cfg, stack_lm_params(args, cfg), label="t-par")
    ck, cv = fused.reown_for_donation(init_kv_cache(cfg, 2))
    rng = np.random.default_rng(5)
    prompt = rng.integers(1, cfg.vocab_size, (1, 8)).astype(np.int32)
    ck, cv, tok, logits = progs.prefill(
        progs.params, ck, cv, jnp.asarray(prompt), jnp.int32(0),
        jnp.int32(8))
    ref = mx.mod.Module(lm_symbol(cfg), context=mx.cpu())
    ref.bind(data_shapes=[io.DataDesc("data", (1, 8))],
             label_shapes=[io.DataDesc("softmax_label", (1, 8))],
             for_training=False, grad_req="null")
    ref.set_params(args, {})
    ref.forward(DataBatch(data=[nd.array(prompt)],
                          label=[nd.array(np.zeros((1, 8), np.float32))]),
                is_train=False)
    probs = ref.get_outputs()[0].asnumpy().reshape(8, cfg.vocab_size)
    want = np.log(probs[7] + 1e-30)
    got = np.asarray(jax.nn.log_softmax(np.asarray(logits)))
    np.testing.assert_allclose(got - got.mean(), want - want.mean(),
                               rtol=1e-4, atol=1e-4)
    assert int(np.asarray(tok)) == int(np.argmax(want))


def test_decode_step_matches_prefill():
    """Incremental decode against the KV cache is exact: stepping one
    token equals prefilling the extended prompt."""
    import jax.numpy as jnp
    from incubator_mxnet_tpu import fused
    cfg = _cfg()
    mod = _trained(cfg)
    args, _ = mod.get_params()
    progs = DecodePrograms(cfg, stack_lm_params(args, cfg), label="t-inc")
    rng = np.random.default_rng(9)
    prompt = rng.integers(1, cfg.vocab_size, (1, 6)).astype(np.int32)
    ck, cv = fused.reown_for_donation(init_kv_cache(cfg, 3))
    ck, cv, tok, _ = progs.prefill(progs.params, ck, cv,
                                   jnp.asarray(np.pad(prompt,
                                                      ((0, 0), (0, 2)))),
                                   jnp.int32(1), jnp.int32(6))
    toks = jnp.zeros((3,), jnp.int32).at[1].set(int(tok))
    poss = jnp.zeros((3,), jnp.int32).at[1].set(6)
    ck, cv, _, logits_step = progs.step(progs.params, ck, cv, toks, poss)
    ext = np.concatenate([prompt, [[int(tok)]]], axis=1)
    ck2, cv2 = fused.reown_for_donation(init_kv_cache(cfg, 3))
    ck2, cv2, _, logits_pre = progs.prefill(
        progs.params, ck2, cv2,
        jnp.asarray(np.pad(ext, ((0, 0), (0, 1)))), jnp.int32(0),
        jnp.int32(7))
    np.testing.assert_allclose(np.asarray(logits_step)[1],
                               np.asarray(logits_pre), rtol=1e-5,
                               atol=1e-5)
