"""ONNX export/import round-trip tests (reference
tests/python-pytest/onnx/ strategy: numerical equivalence after
interchange)."""
import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd
from incubator_mxnet_tpu.contrib import onnx as mx_onnx


def _convnet():
    data = mx.sym.Variable("data")
    x = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                           name="conv0")
    x = mx.sym.BatchNorm(x, fix_gamma=False, name="bn0")
    x = mx.sym.Activation(x, act_type="relu")
    x = mx.sym.Pooling(x, kernel=(2, 2), stride=(2, 2), pool_type="max")
    x = mx.sym.Flatten(x)
    x = mx.sym.FullyConnected(x, num_hidden=10, name="fc0")
    return mx.sym.softmax(x)


def _init(sym, data_shape):
    arg_shapes, _, aux_shapes = sym.infer_shape(data=data_shape)
    rng = np.random.RandomState(0)
    args, auxs = {}, {}
    for name, s in zip(sym.list_arguments(), arg_shapes):
        if name != "data":
            args[name] = nd.array(rng.normal(0, 0.5, s).astype("f4"))
    for name, s in zip(sym.list_auxiliary_states(), aux_shapes):
        auxs[name] = nd.array(
            np.abs(rng.normal(1.0, 0.1, s)).astype("f4"))
    return args, auxs


def _forward(sym, args, auxs, x):
    exe = sym.simple_bind(ctx=mx.cpu(), grad_req="null", data=x.shape)
    exe.copy_params_from(args, auxs, allow_extra_params=True)
    return exe.forward(is_train=False, data=nd.array(x))[0].asnumpy()


def test_roundtrip_convnet(tmp_path):
    sym = _convnet()
    x = np.random.RandomState(1).normal(0, 1, (2, 3, 8, 8)).astype("f4")
    args, auxs = _init(sym, x.shape)
    ref = _forward(sym, args, auxs, x)

    path = str(tmp_path / "m.onnx")
    mx_onnx.export_model(sym, {**args, **auxs}, in_shapes=[x.shape],
                         onnx_file_path=path)
    sym2, args2, auxs2 = mx_onnx.import_model(path)
    out = _forward(sym2, args2, auxs2, x)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_roundtrip_mlp_and_ops(tmp_path):
    a = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(a, num_hidden=16, name="l1")
    h = mx.sym.Activation(h, act_type="tanh")
    h2 = mx.sym.FullyConnected(h, num_hidden=16, name="l2", no_bias=True)
    s = mx.sym.broadcast_add(h, h2)
    s = mx.sym.Reshape(s, shape=(-1, 4, 4))
    s = mx.sym.transpose(s, axes=(0, 2, 1))
    out = mx.sym.Reshape(s, shape=(0, -1))
    x = np.random.RandomState(2).normal(0, 1, (4, 6)).astype("f4")
    args, auxs = _init(out, x.shape)
    ref = _forward(out, args, auxs, x)

    path = str(tmp_path / "mlp.onnx")
    mx_onnx.export_model(out, args, in_shapes=[x.shape],
                         onnx_file_path=path)
    sym2, args2, auxs2 = mx_onnx.import_model(path)
    got = _forward(sym2, args2, auxs2, x)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_exported_file_is_valid_onnx_wire_format(tmp_path):
    """Parse the file with a FRESH protobuf read and verify the official
    field layout (ir_version, opset, graph nodes)."""
    from incubator_mxnet_tpu.contrib.onnx import onnx_subset_pb2 as OP
    sym = _convnet()
    args, auxs = _init(sym, (2, 3, 8, 8))
    path = str(tmp_path / "w.onnx")
    mx_onnx.export_model(sym, {**args, **auxs}, in_shapes=[(2, 3, 8, 8)],
                         onnx_file_path=path)
    m = OP.ModelProto()
    m.ParseFromString(open(path, "rb").read())
    assert m.ir_version == 8
    assert m.opset_import[0].version == 13
    ops = [n.op_type for n in m.graph.node]
    assert "Conv" in ops and "BatchNormalization" in ops and "Gemm" in ops
    assert m.graph.input[0].name == "data"
    dims = [d.dim_value for d in
            m.graph.input[0].type.tensor_type.shape.dim]
    assert dims == [2, 3, 8, 8]
