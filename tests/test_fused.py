"""Fused public train path: Module.fit / Trainer.step must run as one
donated XLA program AND match the unfused reference semantics exactly.

This is the round-3 contract (bulk-exec + fused optimizer parity with
reference `graph_executor.cc:1194-1316` / `optimizer_op.cc`): the numbers a
user gets from the fast path are the numbers the per-op path produces.
"""
import os

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, fused, gluon, io, nd, sym


def _make_symbol():
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=8, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=4, name="fc2")
    return sym.SoftmaxOutput(net, name="softmax")


def _data(n=64, d=16, k=4):
    rng = np.random.RandomState(0)
    return rng.randn(n, d).astype("f4"), \
        rng.randint(0, k, n).astype("f4")


def _run_module(fused_on, optimizer, opt_params, contexts=None, steps=6,
                metric_name="acc"):
    os.environ["MXNET_FUSED_TRAIN_STEP"] = "1" if fused_on else "0"
    try:
        np.random.seed(7)
        mx.random.seed(7)
        X, y = _data()
        it = io.NDArrayIter(X, y, batch_size=32, shuffle=False,
                            label_name="softmax_label")
        mod = mx.mod.Module(_make_symbol(),
                            context=contexts or mx.cpu())
        mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
        mod.init_params(mx.initializer.Xavier())
        mod.init_optimizer(kvstore="device", optimizer=optimizer,
                           optimizer_params=opt_params)
        metric = mx.metric.create(metric_name)
        batches = list(it)
        for s in range(steps):
            mod.fit_step(batches[s % len(batches)], metric)
        args, _ = mod.get_params()
        return ({k: v.asnumpy() for k, v in args.items()},
                dict(metric.get_name_value()), mod)
    finally:
        os.environ.pop("MXNET_FUSED_TRAIN_STEP", None)


@pytest.mark.parametrize("optimizer,opt_params", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}),
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-3}),
    ("adam", {"learning_rate": 0.01}),
    ("rmsprop", {"learning_rate": 0.01}),
    ("ftml", {"learning_rate": 0.01}),
    ("nag", {"learning_rate": 0.05, "momentum": 0.9}),
])
def test_fused_matches_unfused(optimizer, opt_params):
    a, ma, mod = _run_module(True, optimizer, opt_params)
    b, mb, _ = _run_module(False, optimizer, opt_params)
    assert mod._fused_step is not None and not mod._fused_step.broken, \
        "fused step must actually engage"
    for k in a:
        np.testing.assert_allclose(a[k], b[k], rtol=2e-5, atol=2e-6,
                                   err_msg=k)
    for k in ma:
        assert abs(ma[k] - mb[k]) < 1e-6, (k, ma, mb)


def test_fused_multi_device_matches_single():
    ctxs = [mx.cpu(i) for i in range(4)]
    a, ma, mod = _run_module(True, "sgd",
                             {"learning_rate": 0.1, "momentum": 0.9},
                             contexts=ctxs)
    assert mod._fused_step is not None and not mod._fused_step.broken
    b, mb, _ = _run_module(True, "sgd",
                           {"learning_rate": 0.1, "momentum": 0.9})
    for k in a:
        np.testing.assert_allclose(a[k], b[k], rtol=1e-5, atol=1e-6,
                                   err_msg=k)
    assert ma == mb


def test_fused_lr_scheduler_is_dynamic():
    """A per-step lr schedule must take effect WITHOUT retriggering
    compilation (lr is a traced input, not a baked constant)."""
    sched = mx.lr_scheduler.FactorScheduler(step=2, factor=0.5)
    a, _, mod = _run_module(True, "sgd",
                            {"learning_rate": 0.2, "lr_scheduler": sched})
    sched2 = mx.lr_scheduler.FactorScheduler(step=2, factor=0.5)
    b, _, _ = _run_module(False, "sgd",
                          {"learning_rate": 0.2, "lr_scheduler": sched2})
    assert mod._fused_step is not None and not mod._fused_step.broken
    for k in a:
        np.testing.assert_allclose(a[k], b[k], rtol=2e-5, atol=1e-6,
                                   err_msg=k)


def test_fused_metric_composite_in_graph():
    comp = mx.metric.CompositeEvalMetric(
        metrics=[mx.metric.Accuracy(), mx.metric.CrossEntropy()])
    os.environ["MXNET_FUSED_TRAIN_STEP"] = "1"
    try:
        np.random.seed(7)
        mx.random.seed(7)
        X, y = _data()
        it = io.NDArrayIter(X, y, batch_size=32, shuffle=False,
                            label_name="softmax_label")
        mod = mx.mod.Module(_make_symbol(), context=mx.cpu())
        mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
        mod.init_params(mx.initializer.Xavier())
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.05})
        batches = list(it)
        # host-side reference accumulation on identical outputs
        ref_acc, ref_ce = mx.metric.Accuracy(), mx.metric.CrossEntropy()
        for b in batches[:4]:
            mod.fit_step(b, comp)
            ref_acc.update(b.label, mod.get_outputs())
            ref_ce.update(b.label, mod.get_outputs())
        got = dict(comp.get_name_value())
        assert abs(got["accuracy"] - ref_acc.get()[1]) < 1e-6
        assert abs(got["cross-entropy"] - ref_ce.get()[1]) < 1e-4
        assert mod._fused_step is not None and not mod._fused_step.broken
    finally:
        os.environ.pop("MXNET_FUSED_TRAIN_STEP", None)


def test_fused_optimizer_state_save_load_roundtrip():
    a, _, mod = _run_module(True, "adam", {"learning_rate": 0.01}, steps=3)
    assert mod._fused_step is not None and not mod._fused_step.broken
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        f = os.path.join(d, "opt.states")
        mod.save_optimizer_states(f)
        mod.load_optimizer_states(f)
    # states survived the round trip and training continues
    X, y = _data()
    it = io.NDArrayIter(X, y, batch_size=32, label_name="softmax_label")
    m = mx.metric.create("acc")
    mod.fit_step(next(iter(it)), m)
    assert not mod._fused_step.broken


def test_trainer_fused_update_matches_manual_sgd():
    """gluon.Trainer.step applies every update in ONE program
    (fused.FusedOptimizer) and must equal hand-computed SGD-momentum."""
    np.random.seed(3)
    mx.random.seed(3)
    net = gluon.nn.Dense(4, in_units=8)
    net.initialize(mx.initializer.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    x = nd.array(np.random.randn(16, 8).astype("f4"))
    params = {p.name: p for p in net.collect_params().values()}
    ref = {k: (p.data().asnumpy().copy(),
               np.zeros_like(p.data().asnumpy()))
           for k, p in params.items()}
    for _ in range(3):
        with autograd.record():
            out = net(x)
            loss = (out * out).sum()
        loss.backward()
        trainer.step(1)
        for k, p in params.items():
            w, mom = ref[k]
            g = p.grad().asnumpy()
            mom = 0.9 * mom - 0.1 * g
            w = w + mom
            ref[k] = (w, mom)
    assert trainer._fused is not None and not trainer._fused[0]._broken, \
        "Trainer must use the fused multi-tensor apply"
    for k, p in params.items():
        np.testing.assert_allclose(p.data().asnumpy(), ref[k][0],
                                   rtol=1e-5, atol=1e-6, err_msg=k)


def test_fused_optimizer_fallback_is_safe():
    """An untraceable optimizer must fall back to the per-parameter path
    and still produce the correct result."""

    @mx.optimizer.register
    class HostRng(mx.optimizer.Optimizer):
        def update(self, index, weight, grad, state):
            self._update_count(index)
            # host-side numpy draw: cannot trace -> must fall back
            noise = float(np.random.RandomState(0).rand())
            weight -= self._get_lr(index) * (grad + 0 * noise)

    opt = HostRng(learning_rate=0.5)
    fo = fused.FusedOptimizer(opt)
    w = nd.array(np.ones(4, "f4"))
    g = nd.array(np.full(4, 2.0, "f4"))
    fo([0], [w], [g], [None])
    np.testing.assert_allclose(w.asnumpy(), np.zeros(4), atol=1e-6)
    del mx.optimizer.Optimizer.opt_registry["hostrng"]


def test_fused_metric_swap_mid_training():
    """Changing the eval metric after steady-state steps must rebuild the
    program WITHOUT touching the donated (deleted) exec buffers: the
    deferred write-backs flush first, training continues, and both metric
    objects report sane values (regression: the metric-change path once
    demoted to the cold path after the flush decision was made)."""
    os.environ["MXNET_FUSED_TRAIN_STEP"] = "1"
    try:
        np.random.seed(7)
        mx.random.seed(7)
        X, y = _data()
        it = io.NDArrayIter(X, y, batch_size=32, shuffle=False,
                            label_name="softmax_label")
        mod = mx.mod.Module(_make_symbol(), context=mx.cpu())
        mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
        mod.init_params(mx.initializer.Xavier())
        mod.init_optimizer(kvstore=None, optimizer="sgd",
                           optimizer_params={"learning_rate": 0.1})
        batches = list(it)
        m1 = mx.metric.create("acc")
        for s in range(3):   # step 1 cold+flush, 2-3 steady (deferred)
            mod.fit_step(batches[s % len(batches)], m1)
        assert not mod._fused_step.broken
        m2 = mx.metric.create("ce")   # new metric object: program rebuild
        for s in range(3):
            mod.fit_step(batches[s % len(batches)], m2)
        assert not mod._fused_step.broken, \
            "metric swap must not break the fused step"
        assert np.isfinite(dict(m2.get_name_value())["cross-entropy"])
        args, _ = mod.get_params()
        for k, v in args.items():
            assert np.isfinite(v.asnumpy()).all(), k
    finally:
        os.environ.pop("MXNET_FUSED_TRAIN_STEP", None)


def test_fused_bf16_multiprecision_derived_masters():
    """bf16 weights with fp32 masters: the fused program derives the
    low-precision weights from the masters in-graph (no weight args on
    the dispatch), and matches the unfused multi-precision path."""
    import ml_dtypes

    def run(fused_on):
        os.environ["MXNET_FUSED_TRAIN_STEP"] = "1" if fused_on else "0"
        try:
            np.random.seed(3)
            mx.random.seed(3)
            X, y = _data()
            Xb = X.astype(ml_dtypes.bfloat16)
            it = io.NDArrayIter(Xb, y, batch_size=32, shuffle=False,
                                label_name="softmax_label")
            mod = mx.mod.Module(_make_symbol(), context=mx.cpu())
            mod.bind(data_shapes=it.provide_data,
                     label_shapes=it.provide_label)
            mod.init_params(mx.initializer.Xavier())
            mod.init_optimizer(
                kvstore=None, optimizer="sgd",
                optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                                  "multi_precision": True})
            metric = mx.metric.create("acc")
            batches = list(it)
            for s in range(5):
                mod.fit_step(batches[s % len(batches)], metric)
            args, _ = mod.get_params()
            return ({k: np.asarray(v.asnumpy(), np.float32)
                     for k, v in args.items()}, mod)
        finally:
            os.environ.pop("MXNET_FUSED_TRAIN_STEP", None)

    w_fused, mod = run(True)
    assert mod._fused_step is not None and not mod._fused_step.broken
    assert mod._fused_step._derive_ws, \
        "all-bf16 multi-precision training must use derived masters"
    w_eager, _ = run(False)
    for k in w_fused:
        np.testing.assert_allclose(w_fused[k], w_eager[k], rtol=2e-2,
                                   atol=1e-2, err_msg=k)


def test_fused_prestage_matches_direct():
    """Module.prepare pre-stages the NEXT batch's transfer; results must be
    identical to calling fit_step without any prestage."""
    def run(with_prepare):
        os.environ["MXNET_FUSED_TRAIN_STEP"] = "1"
        try:
            np.random.seed(5)
            mx.random.seed(5)
            X, y = _data()
            it = io.NDArrayIter(X, y, batch_size=32, shuffle=False,
                                label_name="softmax_label")
            mod = mx.mod.Module(_make_symbol(), context=mx.cpu())
            mod.bind(data_shapes=it.provide_data,
                     label_shapes=it.provide_label)
            mod.init_params(mx.initializer.Xavier())
            mod.init_optimizer(kvstore=None, optimizer="sgd",
                               optimizer_params={"learning_rate": 0.1})
            metric = mx.metric.create("acc")
            batches = list(it)
            for s in range(4):
                b = batches[s % len(batches)]
                mod.fit_step(b, metric)
                if with_prepare:
                    nb = batches[(s + 1) % len(batches)]
                    mod.prepare(nb)  # pre-stage next batch mid-flight
            args, _ = mod.get_params()
            return {k: v.asnumpy() for k, v in args.items()}
        finally:
            os.environ.pop("MXNET_FUSED_TRAIN_STEP", None)

    w_pre = run(True)
    w_direct = run(False)
    for k in w_pre:
        np.testing.assert_array_equal(w_pre[k], w_direct[k], err_msg=k)


def test_fused_lr_mult_change_invalidates_hyper_cache():
    """Freezing a layer mid-training via lr_mult must take effect on the
    very next fused step (the hyper-vector cache keys on multipliers)."""
    os.environ["MXNET_FUSED_TRAIN_STEP"] = "1"
    try:
        np.random.seed(6)
        mx.random.seed(6)
        X, y = _data()
        it = io.NDArrayIter(X, y, batch_size=32, shuffle=False,
                            label_name="softmax_label")
        mod = mx.mod.Module(_make_symbol(), context=mx.cpu())
        mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
        mod.init_params(mx.initializer.Xavier())
        mod.init_optimizer(kvstore=None, optimizer="sgd",
                           optimizer_params={"learning_rate": 0.1})
        metric = mx.metric.create("acc")
        batches = list(it)
        for s in range(3):
            mod.fit_step(batches[s % len(batches)], metric)
        frozen = mod.get_params()[0]["fc1_weight"].asnumpy().copy()
        mod._optimizer.lr_mult = {"fc1_weight": 0.0}   # freeze fc1
        for s in range(3):
            mod.fit_step(batches[s % len(batches)], metric)
        after = mod.get_params()[0]["fc1_weight"].asnumpy()
        np.testing.assert_array_equal(after, frozen,
                                      err_msg="lr_mult=0 must freeze fc1")
    finally:
        os.environ.pop("MXNET_FUSED_TRAIN_STEP", None)


def _fit_with_block(block_k, reset_at=None, num_epoch=1):
    """Run Module.fit at a given MXNET_FUSED_STEP_BLOCK, recording what
    every batch-end callback observes; optionally reset the metric
    inside the callback at batch `reset_at` (Speedometer auto_reset)."""
    os.environ["MXNET_FUSED_STEP_BLOCK"] = str(block_k)
    try:
        np.random.seed(7)
        mx.random.seed(7)
        X, y = _data()
        it = io.NDArrayIter(X, y, batch_size=8, shuffle=False,
                            label_name="softmax_label")
        mod = mx.mod.Module(_make_symbol())
        seen = []

        def cb(param):
            _name, val = param.eval_metric.get()
            seen.append((param.nbatch, val))
            if reset_at is not None and param.nbatch == reset_at:
                param.eval_metric.reset()

        mod.fit(it, num_epoch=num_epoch, optimizer="sgd",
                optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
                eval_metric="acc", initializer=mx.initializer.Xavier(),
                batch_end_callback=cb, kvstore=None)
        assert mod._fused_step is not None and not mod._fused_step.broken
        return seen
    finally:
        os.environ.pop("MXNET_FUSED_STEP_BLOCK", None)


def test_block_callbacks_fire_per_logical_step():
    """K>1 fused blocks: each batch-end callback must observe BATCH-j
    metric state — identical to per-batch (K=1) dispatch — not the
    block-final totals (round-5 VERDICT/ADVICE)."""
    ref = _fit_with_block(1)
    blocked = _fit_with_block(4)
    assert [b for b, _ in ref] == [b for b, _ in blocked]
    for (nb, v1), (_nb2, vk) in zip(ref, blocked):
        np.testing.assert_allclose(vk, v1, rtol=1e-6, atol=1e-7,
                                   err_msg=f"batch {nb}")
    # the per-step values must actually differ across the burst (a
    # constant block-final value would also pass a weaker check)
    assert len({round(v, 6) for _, v in blocked}) > 1


def test_block_callback_metric_reset_mid_burst():
    """A callback that RESETS the metric mid-burst (Speedometer
    auto_reset) must see post-reset windows identical to per-batch
    dispatch — the old burst semantics silently dropped the rest of the
    block from the next window."""
    ref = _fit_with_block(1, reset_at=1)
    blocked = _fit_with_block(4, reset_at=1)
    for (nb, v1), (_nb2, vk) in zip(ref, blocked):
        np.testing.assert_allclose(vk, v1, rtol=1e-6, atol=1e-7,
                                   err_msg=f"batch {nb}")


def test_block_metric_view_touched_before_first_expose():
    """Defensive paths of the per-step metric view: a reader that
    materializes (get) or resets the metric BETWEEN the block dispatch
    and the first burst callback must still land exact per-step totals
    — and must never touch the donated entry-carry buffers."""
    import jax.numpy as jnp
    from incubator_mxnet_tpu.fused import _BlockMetricView

    def build():
        m = mx.metric.Accuracy()
        # cumulative carries C_{-1}..C_1 = (0,0),(1,1),(2,2); final (3,3)
        pre = [(jnp.asarray([0., 1., 2.]), jnp.asarray([0, 1, 2]))]
        finals = [(jnp.asarray(3.), jnp.asarray(3))]
        view = _BlockMetricView([m], pre, finals)
        m._device_totals = finals[0]
        view.arm()
        return m, view

    # materialize before the burst: host absorbed the block-final totals
    m, view = build()
    assert m.get()[1] == 1.0          # 3/3 (armed finals)
    for j, want in enumerate([(1, 1), (2, 2), (3, 3)]):
        view.expose(j)
        s, n = want
        name, v = m.get()
        assert abs(v - s / n) < 1e-6, (j, v)
    assert m.num_inst == 3            # block-final state after the burst

    # reset before the burst: the new window starts at batch 0's delta
    m, view = build()
    m.reset()
    view.expose(0)
    assert m.get()[1] == 1.0 and m.num_inst == 1   # delta_0 = (1, 1)
    view.expose(1)
    assert m.get()[1] == 1.0 and m.num_inst == 2   # + delta_1
