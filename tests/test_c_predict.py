"""C predict ABI smoke test: export a model from Python, then drive it
from a REAL C program (compiled here with g++) through libmxtpu_predict.so
— the reference's standalone-inference contract (`c_predict_api.h`)."""
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import io, sym

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SRC = os.path.join(REPO, "src")

C_MAIN = r"""
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include "c_predict_api.h"

static char *read_file(const char *path, size_t *size) {
  FILE *f = fopen(path, "rb");
  if (!f) return NULL;
  fseek(f, 0, SEEK_END);
  long n = ftell(f);
  fseek(f, 0, SEEK_SET);
  char *buf = (char *)malloc((size_t)n + 1);
  fread(buf, 1, (size_t)n, f);
  buf[n] = 0;
  if (size) *size = (size_t)n;
  fclose(f);
  return buf;
}

int main(int argc, char **argv) {
  size_t psize = 0;
  char *json = read_file(argv[1], NULL);
  char *params = read_file(argv[2], &psize);
  if (!json || !params) { fprintf(stderr, "read failed\n"); return 2; }

  const char *keys[] = {"data"};
  uint32_t indptr[] = {0, 2};
  uint32_t shape[] = {4, 6};
  PredictorHandle h = NULL;
  if (MXTPUPredCreate(json, params, psize, 1, 0, 1, keys, indptr, shape,
                      &h) != 0) {
    fprintf(stderr, "create: %s\n", MXTPUGetLastError());
    return 3;
  }
  float input[24];
  for (int i = 0; i < 24; ++i) input[i] = (float)i * 0.1f - 1.0f;
  if (MXTPUPredSetInput(h, "data", input, 24) != 0) {
    fprintf(stderr, "set_input: %s\n", MXTPUGetLastError());
    return 4;
  }
  if (MXTPUPredForward(h) != 0) {
    fprintf(stderr, "forward: %s\n", MXTPUGetLastError());
    return 5;
  }
  uint32_t *oshape = NULL, ondim = 0;
  if (MXTPUPredGetOutputShape(h, 0, &oshape, &ondim) != 0) return 6;
  uint32_t n = 1;
  for (uint32_t i = 0; i < ondim; ++i) n *= oshape[i];
  float *out = (float *)malloc(n * sizeof(float));
  if (MXTPUPredGetOutput(h, 0, out, n) != 0) {
    fprintf(stderr, "get_output: %s\n", MXTPUGetLastError());
    return 7;
  }
  printf("shape %u", oshape[0]);
  for (uint32_t i = 1; i < ondim; ++i) printf("x%u", oshape[i]);
  printf("\n");
  for (uint32_t i = 0; i < n; ++i) printf("%.6f ", out[i]);
  printf("\n");
  MXTPUPredFree(h);
  return 0;
}
"""


@pytest.mark.skipif(not os.path.exists("/usr/bin/g++") and
                    not os.path.exists("/usr/local/bin/g++"),
                    reason="no C++ toolchain")
def test_c_predict_end_to_end(tmp_path):
    # 1. train-free model export from Python
    np.random.seed(0)
    mx.random.seed(0)
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=8, name="fc1")
    net = sym.Activation(net, act_type="tanh")
    net = sym.FullyConnected(net, num_hidden=3, name="fc2")
    net = sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    it_shapes = [io.DataDesc("data", (4, 6))]
    mod.bind(data_shapes=it_shapes,
             label_shapes=[io.DataDesc("softmax_label", (4,))],
             for_training=False, grad_req="null")
    mod.init_params(mx.initializer.Xavier())
    prefix = str(tmp_path / "model")
    mod.save_checkpoint(prefix, 0)

    # 2. expected output from the Python side
    x = (np.arange(24, dtype=np.float32) * 0.1 - 1.0).reshape(4, 6)
    mod.forward(io.DataBatch(data=[mx.nd.array(x)],
                             label=[mx.nd.zeros((4,))]), is_train=False)
    expect = mod.get_outputs()[0].asnumpy()

    # 3. build the predict library + the C driver, run it
    subprocess.run(["make", "-C", SRC, "predict", "-s"], check=True,
                   timeout=120)
    cfile = tmp_path / "smoke.c"
    cfile.write_text(C_MAIN)
    exe = tmp_path / "smoke"
    subprocess.run(
        ["g++", "-x", "c++", str(cfile), "-o", str(exe), "-I", SRC,
         "-L", SRC, "-lmxtpu_predict", f"-Wl,-rpath,{SRC}"],
        check=True, timeout=120)
    env = dict(os.environ,
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH",
                                                             ""),
               JAX_PLATFORMS="cpu")
    res = subprocess.run(
        [str(exe), prefix + "-symbol.json", prefix + "-0000.params"],
        capture_output=True, text=True, timeout=300, env=env)
    assert res.returncode == 0, res.stderr + res.stdout
    lines = res.stdout.strip().splitlines()
    assert lines[0] == "shape 4x3", lines
    got = np.array([float(v) for v in lines[1].split()]).reshape(4, 3)
    # the embedded interpreter may resolve a different default backend
    # (real chip vs this process's x64 CPU mesh): compare within the
    # cross-backend matmul envelope, and structurally (softmax rows)
    np.testing.assert_allclose(got.sum(1), 1.0, rtol=1e-4)
    np.testing.assert_allclose(got, expect, rtol=2e-2, atol=2e-3)
