"""FeedForward legacy API, AttrScope/group2ctx, config knobs (reference
model.py:451, attribute.py, docs/faq/env_var.md)."""
import os

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd
from incubator_mxnet_tpu.model import FeedForward


def _net():
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=3, name="fc2")
    return mx.sym.SoftmaxOutput(h, name="softmax")


def _data():
    rng = np.random.RandomState(0)
    X = rng.randn(96, 8).astype("f4")
    W = rng.randn(8, 3).astype("f4")
    y = (X @ W).argmax(1).astype("f4")
    return X, y


def test_feedforward_fit_predict_save_load(tmp_path):
    X, y = _data()
    model = FeedForward(_net(), ctx=mx.cpu(), num_epoch=12,
                        optimizer="sgd", learning_rate=0.5,
                        rescale_grad=1.0 / 32, numpy_batch_size=32)
    model.fit(X, y)
    preds = model.predict(X)
    acc = (preds.argmax(1) == y).mean()
    assert acc > 0.8, acc
    # classic create() one-shot
    m2 = FeedForward.create(_net(), X, y, ctx=mx.cpu(), num_epoch=5,
                            learning_rate=0.5, rescale_grad=1.0 / 32)
    assert m2.arg_params

    prefix = str(tmp_path / "ff")
    model.save(prefix, 12)
    loaded = FeedForward.load(prefix, 12, ctx=mx.cpu())
    preds2 = loaded.predict(X)
    np.testing.assert_allclose(preds2, preds, rtol=1e-5, atol=1e-6)


def test_attr_scope_and_group2ctx():
    with mx.AttrScope(ctx_group="embed", lr_mult=2.0):
        data = mx.sym.Variable("data")
        w = mx.sym.Variable("w")
    out = mx.sym.FullyConnected(data, w, no_bias=True, num_hidden=4,
                                name="fc")
    node = [n for n in out._topo() if n.name == "w"][0]
    assert node._extra_attrs["__ctx_group__"] == "embed"
    assert node._extra_attrs["__lr_mult__"] == "2.0"

    # group2ctx places the group's params on the mapped device
    import jax
    exe = out.simple_bind(ctx=mx.cpu(0), group2ctx={"embed": mx.cpu(1)},
                          data=(2, 6))
    assert exe.arg_dict["w"].context.device_id == 1
    assert exe.arg_dict["data"].context.device_id == 1  # also in scope
    res = exe.forward(data=nd.array(np.ones((2, 6), "f4")))
    assert res[0].shape == (2, 4)


def test_group2ctx_shardings_bridge():
    from incubator_mxnet_tpu import parallel as par
    from jax.sharding import PartitionSpec as P
    with mx.AttrScope(ctx_group="tp_group"):
        w = mx.sym.Variable("w")
    data = mx.sym.Variable("data")
    out = mx.sym.FullyConnected(data, w, no_bias=True, num_hidden=8)
    import jax
    mesh = par.make_mesh({"tp": 4}, devices=jax.devices()[:4])
    from incubator_mxnet_tpu.parallel.tensor_parallel import \
        group2ctx_shardings
    sh = group2ctx_shardings(out, {"tp_group": "tp"}, mesh)
    assert set(sh) == {"w"}
    assert sh["w"].spec == P("tp")


def test_config_knobs():
    from incubator_mxnet_tpu import config
    assert config.get("MXNET_CPU_WORKER_NTHREADS") >= 1
    os.environ["MXNET_CPU_WORKER_NTHREADS"] = "7"
    try:
        assert config.get("MXNET_CPU_WORKER_NTHREADS") == 7
    finally:
        del os.environ["MXNET_CPU_WORKER_NTHREADS"]
    with pytest.raises(KeyError):
        config.get("MXNET_NO_SUCH_KNOB")
    os.environ["MXNET_TYPO_KNOB"] = "1"
    try:
        assert "MXNET_TYPO_KNOB" in config.warn_unknown()
    finally:
        del os.environ["MXNET_TYPO_KNOB"]
    # every documented knob has an explicit status
    for name, (typ, default, status, note) in config.KNOBS.items():
        assert status in ("honored", "subsumed", "accepted"), name


def test_group2ctx_covers_auto_created_params():
    with mx.AttrScope(ctx_group="dev1"):
        fc = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=4,
                                   name="fc")
    node = [n for n in fc._topo() if n.name == "fc_weight"][0]
    assert node._extra_attrs.get("__ctx_group__") == "dev1"
    exe = fc.simple_bind(ctx=mx.cpu(0), group2ctx={"dev1": mx.cpu(1)},
                         data=(2, 6))
    assert exe.arg_dict["fc_weight"].context.device_id == 1
    assert exe.arg_dict["fc_bias"].context.device_id == 1


def test_profiler_per_op_stats(tmp_path):
    from incubator_mxnet_tpu import profiler, nd
    profiler.set_config(filename=str(tmp_path / "p.json"),
                        profile_imperative=True)
    profiler.set_state("run")
    try:
        a = nd.ones((8, 8))
        b = nd.dot(a, a)
        c = nd.relu(b)
        c.asnumpy()
    finally:
        profiler.set_state("stop")
    table = profiler.dumps(reset=True)
    assert "dot" in table and "count=" in table
    assert "relu" in table or "Activation" in table


def test_libinfo_and_contrib_shims():
    from incubator_mxnet_tpu import libinfo
    feats = libinfo.features()
    assert "BACKENDS" in feats and isinstance(libinfo.find_lib_path(), list)

    # contrib.io.DataLoaderIter feeds Module from a gluon DataLoader
    from incubator_mxnet_tpu import gluon
    from incubator_mxnet_tpu.contrib.io import DataLoaderIter
    rng = np.random.RandomState(0)
    X = nd.array(rng.randn(64, 6).astype("f4"))
    Y = nd.array(rng.randint(0, 3, 64).astype("f4"))
    loader = gluon.data.DataLoader(gluon.data.ArrayDataset(X, Y),
                                   batch_size=16)
    it = DataLoaderIter(loader)
    n = sum(b.data[0].shape[0] for b in it)
    assert n == 64
    it.reset()
    assert next(iter(it)).data[0].shape == (16, 6)

    # contrib.autograd legacy surface
    from incubator_mxnet_tpu.contrib import autograd as old_ag
    x = nd.array([2.0])
    x.attach_grad()
    with old_ag.train_section():
        y = x * x
    old_ag.backward([y])
    np.testing.assert_allclose(x.grad.asnumpy(), [4.0])
