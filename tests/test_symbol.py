"""Symbol + Executor tests (reference tests/python/unittest/test_symbol.py,
test_executor.py)."""
import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, sym


def _mlp_symbol():
    data = sym.Variable("data")
    fc1 = sym.FullyConnected(data, name="fc1", num_hidden=16)
    act1 = sym.Activation(fc1, name="relu1", act_type="relu")
    fc2 = sym.FullyConnected(act1, name="fc2", num_hidden=4)
    return sym.SoftmaxOutput(fc2, sym.Variable("softmax_label"), name="softmax")


def test_compose_and_listing():
    net = _mlp_symbol()
    args = net.list_arguments()
    assert args == ["data", "fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias",
                    "softmax_label"]
    assert net.list_outputs() == ["softmax_output"]
    assert net.list_auxiliary_states() == []


def test_infer_shape():
    net = _mlp_symbol()
    arg_shapes, out_shapes, aux_shapes = net.infer_shape(data=(8, 10),
                                                         softmax_label=(8,))
    d = dict(zip(net.list_arguments(), arg_shapes))
    assert d["fc1_weight"] == (16, 10)
    assert d["fc1_bias"] == (16,)
    assert d["fc2_weight"] == (4, 16)
    assert out_shapes == [(8, 4)]


def test_symbol_json_roundtrip():
    net = _mlp_symbol()
    js = net.tojson()
    net2 = sym.load_json(js)
    assert net2.list_arguments() == net.list_arguments()
    assert net2.list_outputs() == net.list_outputs()
    # same inference results
    s1 = net.infer_shape(data=(2, 6), softmax_label=(2,))[0]
    s2 = net2.infer_shape(data=(2, 6), softmax_label=(2,))[0]
    assert s1 == s2


def test_simple_bind_forward_backward():
    np.random.seed(0)
    net = _mlp_symbol()
    ex = net.simple_bind(ctx=mx.cpu(), data=(8, 10), softmax_label=(8,))
    # init params
    for name, arr in ex.arg_dict.items():
        if name not in ("data", "softmax_label"):
            arr._data = arr._data + np.random.uniform(
                -0.1, 0.1, arr.shape).astype("f4")
    x = np.random.rand(8, 10).astype("f4")
    y = np.random.randint(0, 4, 8).astype("f4")
    outs = ex.forward(is_train=True, data=x, softmax_label=y)
    o = outs[0].asnumpy()
    assert o.shape == (8, 4)
    np.testing.assert_allclose(o.sum(axis=1), 1.0, rtol=1e-5)
    ex.backward()
    gw = ex.grad_dict["fc1_weight"].asnumpy()
    assert np.abs(gw).sum() > 0


def test_executor_trains_xor():
    """End-to-end: symbolic MLP learns XOR via executor forward/backward."""
    np.random.seed(0)
    X = np.array([[0, 0], [0, 1], [1, 0], [1, 1]], dtype="f4")
    Y = np.array([0, 1, 1, 0], dtype="f4")
    data = sym.Variable("data")
    label = sym.Variable("label")
    h = sym.Activation(sym.FullyConnected(data, num_hidden=8, name="fc1"),
                       act_type="tanh")
    out = sym.FullyConnected(h, num_hidden=2, name="fc2")
    net = sym.SoftmaxOutput(out, label, name="sm")
    ex = net.simple_bind(ctx=mx.cpu(), data=(4, 2), label=(4,))
    rng = np.random.RandomState(5)
    for name, arr in ex.arg_dict.items():
        if name not in ("data", "label"):
            arr._data = (rng.uniform(-0.5, 0.5, arr.shape)).astype("f4") + arr._data * 0
    ex.arg_dict["data"]._data = ex.arg_dict["data"]._data * 0 + X
    ex.arg_dict["label"]._data = ex.arg_dict["label"]._data * 0 + Y
    for i in range(300):
        ex.forward_backward()
        for name in ("fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias"):
            w = ex.arg_dict[name]
            g = ex.grad_dict[name]
            w._data = w._data - 0.5 * g._data
    preds = ex.forward(is_train=False)[0].asnumpy().argmax(axis=1)
    assert (preds == Y).all(), preds


def test_batchnorm_symbolic_aux_update():
    data = sym.Variable("data")
    bn = sym.BatchNorm(data, name="bn", fix_gamma=False, momentum=0.5)
    ex = bn.simple_bind(ctx=mx.cpu(), data=(16, 3))
    assert set(ex.aux_dict) == {"bn_moving_mean", "bn_moving_var"}
    x = np.random.rand(16, 3).astype("f4") + 2.0
    ex.aux_dict["bn_moving_var"]._data = ex.aux_dict["bn_moving_var"]._data + 1.0
    ex.forward(is_train=True, data=x)
    mm = ex.aux_dict["bn_moving_mean"].asnumpy()
    np.testing.assert_allclose(mm, 0.5 * x.mean(axis=0), rtol=1e-4)


def test_group_and_internals():
    a = sym.Variable("a")
    b = a * 2
    c = b + 1
    g = sym.Group([b, c])
    assert len(g.list_outputs()) == 2
    internals = c.get_internals()
    assert len(internals.list_outputs()) >= 3
    ex = g.bind(mx.cpu(), {"a": nd.array([1.0, 2.0])})
    outs = ex.forward()
    np.testing.assert_allclose(outs[0].asnumpy(), [2, 4])
    np.testing.assert_allclose(outs[1].asnumpy(), [3, 5])


def test_grad_req_add_and_null():
    a = sym.Variable("a")
    b = sym.Variable("b")
    c = a * b
    ex = c.bind(mx.cpu(), {"a": nd.array([2.0]), "b": nd.array([3.0])},
                args_grad={"a": nd.zeros((1,)), "b": nd.zeros((1,))},
                grad_req={"a": "add", "b": "null"})
    ex.forward(is_train=True)
    ex.backward(nd.array([1.0]))
    ex.forward(is_train=True)
    ex.backward(nd.array([1.0]))
    np.testing.assert_allclose(ex.grad_dict["a"].asnumpy(), [6.0])


def test_scalar_ops_on_symbols():
    a = sym.Variable("a")
    expr = (2 * a + 1) / (a - 0.5)
    ex = expr.bind(mx.cpu(), {"a": nd.array([1.5])})
    np.testing.assert_allclose(ex.forward()[0].asnumpy(), [4.0])


def test_executor_backward_no_double_forward():
    """forward(is_train=True) stashes vjp residuals; backward() runs ONLY
    the linearized backward program (reference graph_executor.cc:63,76
    reuses activations the same way) — one device execution per phase."""
    import numpy as np
    import incubator_mxnet_tpu as mx

    x = mx.sym.Variable("x")
    w = mx.sym.Variable("w")
    y = mx.sym.sum(mx.sym.broadcast_mul(mx.sym.square(x), w))
    ex = y.bind(mx.cpu(),
                {"x": mx.nd.array(np.array([1.0, 2.0, 3.0], "f4")),
                 "w": mx.nd.array(np.array([2.0, 2.0, 2.0], "f4"))},
                args_grad={"x": mx.nd.zeros(3), "w": mx.nd.zeros(3)})
    ex._exec_count = 0
    ex.forward(is_train=True)
    assert ex._exec_count == 1, "forward must be one device execution"
    ex.backward()
    assert ex._exec_count == 2, \
        "backward must NOT re-run the forward (one execution, not two)"
    np.testing.assert_allclose(ex.grad_dict["x"].asnumpy(),
                               [4.0, 8.0, 12.0])
    np.testing.assert_allclose(ex.grad_dict["w"].asnumpy(),
                               [1.0, 4.0, 9.0])
    # the residuals are from forward TIME: mutating args between the
    # passes must not change the gradients (reference activation reuse)
    ex.forward(is_train=True)
    ex.arg_dict["x"][:] = 100.0
    ex.backward()
    np.testing.assert_allclose(ex.grad_dict["x"].asnumpy(),
                               [4.0, 8.0, 12.0])
