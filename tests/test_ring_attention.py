"""`parallel/ring_attention.blockwise_attention` correctness: parity
against a naive full-score-matrix softmax attention (causal and not),
invariance to the block size, and the packed `BlockwiseAttention`
registered op built on it."""
import numpy as np
import pytest

import jax.numpy as jnp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd
from incubator_mxnet_tpu.ops.attention import naive_attention
from incubator_mxnet_tpu.parallel.ring_attention import blockwise_attention


def _qkv(b=2, t=16, h=2, d=8, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    mk = lambda: rng.standard_normal((b, t, h, d)).astype(dtype)  # noqa: E731
    return mk(), mk(), mk()


def _naive_4d(q, k, v, causal):
    """(B, T, H, D) oracle via the packed naive_attention reference."""
    b, t, h, d = q.shape
    pack = lambda x: jnp.asarray(x.reshape(b, t, h * d))  # noqa: E731
    out = naive_attention(pack(q), pack(k), pack(v), num_heads=h,
                          causal=causal)
    return np.asarray(out).reshape(b, t, h, d)


@pytest.mark.parametrize("causal", [False, True])
def test_blockwise_matches_naive(causal):
    q, k, v = _qkv()
    got = np.asarray(blockwise_attention(jnp.asarray(q), jnp.asarray(k),
                                         jnp.asarray(v), causal=causal))
    want = _naive_4d(q, k, v, causal)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_block_size_invariance(causal):
    """The online-softmax recurrence is EXACT: every tiling (including
    degenerate 1-wide blocks and one full-T block) produces the same
    output."""
    q, k, v = _qkv(t=12)
    outs = []
    for bs in (None, 1, 2, 3, 4, 6, 12):
        outs.append(np.asarray(blockwise_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            block_size=bs, causal=causal)))
    for o in outs[1:]:
        np.testing.assert_allclose(o, outs[0], rtol=1e-6, atol=1e-6)


def test_non_divisible_block_size():
    """T not a multiple of block_size must still be exact (ragged tail
    block)."""
    q, k, v = _qkv(t=10)
    got = np.asarray(blockwise_attention(jnp.asarray(q), jnp.asarray(k),
                                         jnp.asarray(v), block_size=4,
                                         causal=True))
    np.testing.assert_allclose(got, _naive_4d(q, k, v, True),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_registered_op_packed_layout(causal):
    """The `BlockwiseAttention` OpDef (packed (B, T, C) face) matches
    the oracle and round-trips through the nd namespace."""
    q, k, v = _qkv(h=4, d=4)
    b, t, h, d = q.shape
    pack = lambda x: x.reshape(b, t, h * d)  # noqa: E731
    out = nd.BlockwiseAttention(nd.array(pack(q)), nd.array(pack(k)),
                                nd.array(pack(v)), num_heads=h,
                                causal=causal)
    want = _naive_4d(q, k, v, causal).reshape(b, t, h * d)
    np.testing.assert_allclose(out.asnumpy(), want, rtol=1e-5, atol=1e-5)


def test_registered_op_symbolic_and_grad():
    """Symbol-graph execution of the op (the LM training path) and a
    finite gradient through it."""
    from incubator_mxnet_tpu import sym, io
    q, k, v = _qkv(b=1, t=6, h=2, d=4)
    b, t, h, d = q.shape
    c = h * d
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=3 * c, flatten=False,
                             name="qkv")
    qs = sym.slice_axis(net, axis=-1, begin=0, end=c)
    ks = sym.slice_axis(net, axis=-1, begin=c, end=2 * c)
    vs = sym.slice_axis(net, axis=-1, begin=2 * c, end=3 * c)
    a = sym.BlockwiseAttention(qs, ks, vs, num_heads=h, causal=True)
    out = sym.Reshape(a, shape=(b, -1))
    out = sym.FullyConnected(out, num_hidden=2, name="head")
    net = sym.SoftmaxOutput(out, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[io.DataDesc("data", (b, t, c))],
             label_shapes=[io.DataDesc("softmax_label", (b,))])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    batch = io.DataBatch(
        data=[nd.array(q.reshape(b, t, c))],
        label=[nd.array(np.zeros((b,), np.float32))])
    for _ in range(3):
        mod.forward_backward(batch)
        mod.update()
    for k_, v_ in mod.get_params()[0].items():
        assert np.isfinite(v_.asnumpy()).all(), k_


def test_bfloat16_runs_and_tracks_fp32():
    """bf16 inputs stay bf16 out and approximate the fp32 result within
    bf16 tolerance — the mixed-precision serving configuration."""
    q, k, v = _qkv(t=8)
    to16 = lambda x: jnp.asarray(x, dtype=jnp.bfloat16)  # noqa: E731
    got = blockwise_attention(to16(q), to16(k), to16(v), causal=True)
    assert got.dtype == jnp.bfloat16
    want = _naive_4d(q, k, v, True)
    np.testing.assert_allclose(np.asarray(got, dtype=np.float32), want,
                               rtol=0.1, atol=0.1)
