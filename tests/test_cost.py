"""mxcost static cost & communication analysis (ISSUE-13 acceptance).

Gates: the dequantize-before-dot chain in the BENCH_OPS int8 convnet is
flagged with exact node names and the fp32/bf16 bench models produce
zero false positives; the static collective enumeration for a dp=8
bucketed plan matches `KVStore.stats()` measured bytes/dispatches
within 10%; `mxlint --cost-report --fail-on=warn` passes on HEAD
against COST_BUDGETS.json and fails on seeded regressions (extra
collectives from a shrunk bucket cap, a forced f32 upcast inside a
bf16 graph); plus roofline/FLOPs rules, liveness/peak-HBM, donation
opportunities, hidden host-transfer detection, the `--fail-on` CLI
contract, and the budget comparison logic.
"""
import importlib.util
import json
import os

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import analysis, nd, sym
from incubator_mxnet_tpu.analysis import budgets as mxbudgets
from incubator_mxnet_tpu.analysis import cost as mxcost

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BUDGETS_PATH = os.path.join(REPO, "COST_BUDGETS.json")


def _cli():
    spec = importlib.util.spec_from_file_location(
        "_mxlint_cli_cost", os.path.join(REPO, "tools", "mxlint.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _codes(report):
    return [f.code for f in report]


# ---------------------------------------------------------------------------
# dtype flow: the int8-slower-than-fp32 static signature
# ---------------------------------------------------------------------------

def test_int8_bench_convnet_dequant_chain_flagged_with_exact_nodes():
    qsym, shapes, dtypes = mxcost.build_bench_quantized_convnet()
    prog = mxcost.analyze_symbol(qsym, shapes=shapes, dtypes=dtypes,
                                 target="int8")
    chains = [f for f in prog.report if f.code == "dequant-fp32-dot"]
    assert len(chains) == 1
    f = chains[0]
    # exact node names: the dequantize source, the chain, and the dot
    assert f.node == "contrib_dequantize_0"
    assert "contrib_dequantize_0" in f.message
    assert "contrib_quantized_fully_connected_0" in f.message
    assert "flatten0" in f.message and "chain:" in f.message
    assert f.severity == "warn"
    # ... and the fp32-compute declaration on the quantized dot itself
    fp32c = [f for f in prog.report
             if f.code == "quantized-fp32-compute"]
    assert [f.node for f in fp32c] == \
        ["contrib_quantized_fully_connected_0"]
    assert prog.counters["dequant_fp32_dot"] == 1
    assert prog.counters["quantized_fp32_compute"] == 1


def test_fp32_and_bf16_bench_models_zero_false_positives():
    for dtype in ("float32", "bfloat16"):
        s, shapes = mxcost.build_bench_convnet(dtype)
        prog = mxcost.analyze_symbol(s, shapes=shapes, target=dtype)
        bad = [f for f in prog.report if f.severity in ("warn", "error")]
        assert bad == [], f"{dtype}: {[f.format() for f in bad]}"
        assert prog.counters["dequant_fp32_dot"] == 0
        assert prog.counters["f32_upcasts"] == 0
        assert prog.unknown_ops == 0
        # the bf16 model really is bf16 end to end
        if dtype == "bfloat16":
            assert prog.dominant_dtype() == "bfloat16"


def test_f32_upcast_in_bf16_graph_flagged_and_clean_without_cast():
    c, hw = 3, 16
    kw = {"dtype": "bfloat16"}
    data = sym.Variable("data", shape=(4, c, hw, hw), **kw)
    x = sym.Convolution(data,
                        sym.Variable("cw", shape=(8, c, 3, 3), **kw),
                        no_bias=True, kernel=(3, 3), num_filter=8,
                        pad=(1, 1), name="conv")
    x = sym.Cast(x, dtype="float32", name="upcast")
    x = sym.Flatten(x, name="flat")
    out = sym.FullyConnected(
        x, sym.Variable("fw", shape=(4, 8 * hw * hw)),
        sym.Variable("fb", shape=(4,)), num_hidden=4, name="fc")
    prog = mxcost.analyze_symbol(out, shapes={"data": (4, c, hw, hw)})
    hits = [f for f in prog.report if f.code == "f32-upcast-in-bf16"]
    assert len(hits) == 1 and hits[0].node == "upcast"
    assert "fc" in hits[0].message and "upcast" in hits[0].message
    assert prog.counters["f32_upcasts"] == 1


# ---------------------------------------------------------------------------
# FLOPs / roofline / liveness
# ---------------------------------------------------------------------------

def test_flops_rules_and_roofline_classification():
    # known matmul: (64,128) x (128,256)W' -> 2*64*128*256 flops
    data = sym.Variable("data")
    out = sym.FullyConnected(data, num_hidden=256, no_bias=True,
                             name="fc")
    prog = mxcost.analyze_symbol(out, shapes={"data": (64, 128)})
    fc = next(c for c in prog.per_op if c.node == "fc")
    assert fc.flops == 2 * 64 * 128 * 256
    # a big matmul is compute-bound on every profile; a tiny one is not
    big = mxcost.analyze_symbol(
        sym.FullyConnected(sym.Variable("data"), num_hidden=4096,
                           no_bias=True, name="big"),
        shapes={"data": (4096, 4096)}, profile="tpu-v3")
    assert next(c for c in big.per_op if c.node == "big").bound == \
        "compute"
    assert big.bound == "compute"
    assert big.step_time_lb_s() > 0
    d = big.as_dict()
    assert d["flops"] == 2 * 4096 ** 3
    assert d["dominant_dtype"] == "float32"


def test_peak_hbm_liveness_and_donation_opportunity(monkeypatch):
    # data (4 MB) dies at the first conv -> donation opportunity; peak
    # covers params + the widest transient
    shape = (32, 8, 64, 64)
    data = sym.Variable("data")
    x = sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                        no_bias=True, name="conv")
    out = sym.Activation(x, act_type="relu", name="relu")
    prog = mxcost.analyze_symbol(out, shapes={"data": shape})
    nbytes = int(np.prod(shape)) * 4
    assert prog.peak_hbm_bytes is not None
    assert prog.peak_hbm_bytes >= 2 * nbytes  # data + conv out alive
    don = [f for f in prog.report if f.code == "donation-opportunity"]
    assert [f.node for f in don] == ["data"]
    # below the size floor the hint stays quiet
    monkeypatch.setenv("MXNET_COST_DONATE_MIN_MB", "64")
    quiet = mxcost.analyze_symbol(out, shapes={"data": shape})
    assert not [f for f in quiet.report
                if f.code == "donation-opportunity"]


def test_jaxpr_analysis_scan_host_transfer_and_donation():
    import jax
    import jax.numpy as jnp

    def scan_fn(c, xs):
        def body(c, x):
            return jnp.dot(c, c) + x, None
        return jax.lax.scan(body, c, xs)[0]

    prog = mxcost.analyze_callable(
        scan_fn, [jax.ShapeDtypeStruct((64, 64), np.float32),
                  jax.ShapeDtypeStruct((10, 64, 64), np.float32)],
        name="scan")
    # body dot (2*64^3) x 10 trips dominates
    assert prog.flops >= 2 * 64 ** 3 * 10
    assert prog.counters["host_transfers"] == 0

    def bad(x):
        y = jax.pure_callback(
            lambda v: v, jax.ShapeDtypeStruct(x.shape, x.dtype), x)
        return y + 1.0

    hostful = mxcost.analyze_callable(
        bad, [jax.ShapeDtypeStruct((256, 256), np.float32)], name="bad")
    hits = [f for f in hostful.report
            if f.code == "hidden-host-transfer"]
    assert len(hits) == 1 and hostful.counters["host_transfers"] == 1
    assert hostful.bound == "host"

    # an undonated input matching an output aval -> donation hint
    def step(w):
        return w - 0.1 * w

    undonated = mxcost.analyze_callable(
        step, [jax.ShapeDtypeStruct((1024, 1024), np.float32)],
        name="step")
    assert [f.code for f in undonated.report
            if f.code == "donation-opportunity"]
    donated = mxcost.analyze_callable(
        step, [jax.ShapeDtypeStruct((1024, 1024), np.float32)],
        name="step", donate_argnums=(0,))
    assert not [f for f in donated.report
                if f.code == "donation-opportunity"]


def test_analyze_executor_costs_scan_body():
    T, B, H = 8, 4, 32
    data = sym.Variable("data")
    init = sym.Variable("init")
    w = sym.Variable("w")

    def body(x, s):
        out = sym.Activation(sym.broadcast_add(sym.dot(x, w), s),
                             act_type="tanh")
        return out, out

    outs, states = sym.contrib.foreach(body, data, init)
    g = sym.Group([outs, states])
    exe = g.simple_bind(ctx=mx.cpu(), grad_req="null", data=(T, B, H),
                        init=(B, H), w=(H, H))
    prog = mxcost.analyze_executor(exe, name="foreach")
    assert prog.flops >= 2 * B * H * H * T  # the per-step dot x T


# ---------------------------------------------------------------------------
# collective enumeration vs measured kvstore stats (<= 10%)
# ---------------------------------------------------------------------------

def test_static_collectives_match_measured_kvstore_stats(monkeypatch):
    # force a multi-bucket plan on KB-sized tensors
    monkeypatch.setenv("MXNET_KVSTORE_BUCKET_MB", "0.05")
    shapes = [(64, 32), (64,), (96, 64), (96,), (128, 64), (128,)]
    dtypes = [np.dtype("float32")] * len(shapes)
    kv = mx.kv.create("tpu")
    keys = [str(i) for i in range(len(shapes))]
    for k, s in zip(keys, shapes):
        kv.init(k, nd.zeros(s))
    devs = [mx.tpu(i) for i in range(8)]
    vals = [[nd.ones(s, ctx=d) for d in devs] for s in shapes]

    pred = kv.predicted_stats(shapes, dtypes=dtypes, ndev=8)
    kv.push(keys, vals)
    meas = kv.stats()

    assert pred["buckets"] > 1          # the plan is genuinely bucketed
    for metric, measured in (("allreduce_dispatches",
                              meas["allreduce_dispatches"]),
                             ("bytes_reduced", meas["bytes_reduced"])):
        predicted = pred[metric]
        assert abs(predicted - measured) <= 0.10 * max(1, measured), \
            f"{metric}: predicted {predicted} vs measured {measured}"
    assert pred["dispatch_complexity"] == "O(buckets)"

    # the enumerator is the SAME plan rule: byte-exact, not just <=10%
    stats = mxcost.enumerate_collectives(
        shapes, dtypes, dp=8, cap_bytes=kv._bucket_cap_bytes)
    assert stats["collectives_per_step"] == meas["allreduce_dispatches"]
    assert stats["bytes_per_step"] == meas["bytes_reduced"]


def test_pod_plan_prediction_matches_kvstore_rule():
    from incubator_mxnet_tpu import fused
    shapes = [(256, 128), (256,), (64, 256), (64,)]
    pred = fused.predict_pod_plan(shapes, cap_bytes=1 << 20, dp=8)
    # same rule, same priority order as the kvstore scheduler
    from incubator_mxnet_tpu.kvstore import plan_buckets
    sizes = [int(np.prod(s)) * 4 for s in shapes]
    plan = plan_buckets(list(reversed(range(len(shapes)))), sizes,
                        [np.dtype("float32")] * len(shapes), 1 << 20)
    assert pred["plan"] == [list(b) for b in plan]
    assert pred["collectives_per_step"] == len(plan)  # extras fold f32
    assert pred["bytes_per_step"] == sum(sizes)


def test_collective_o_params_warning_on_dtype_interleave():
    # alternating dtypes force one bucket per key: O(params) dispatch
    shapes = [(256,)] * 8
    dtypes = [np.dtype("float32"), np.dtype("float16")] * 4
    stats = mxcost.enumerate_collectives(shapes, dtypes, dp=8,
                                         cap_bytes=1 << 20,
                                         name="interleaved")
    assert stats["dispatch_complexity"] == "O(params)"
    rep = mxcost.collectives_report(stats)
    assert "collective-o-params" in _codes(rep)
    # a clean plan stays quiet
    ok = mxcost.enumerate_collectives([(256,)] * 8, None, dp=8,
                                      cap_bytes=1 << 20)
    assert ok["dispatch_complexity"] == "O(buckets)"
    assert "collective-o-params" not in _codes(
        mxcost.collectives_report(ok))


# ---------------------------------------------------------------------------
# budgets: the CI gate
# ---------------------------------------------------------------------------

def test_budget_check_regression_slack_missing_and_demotion():
    results = mxcost.analyze_bench_set(dp=8)
    budgets = mxbudgets.snapshot(results)

    # HEAD vs its own snapshot: no regressions, known defects demoted
    report, deltas = mxbudgets.check(results, budgets)
    assert not [f for f in report if f.severity == "error"]
    assert all(e["ok"] for progd in deltas.values()
               for e in progd.values())
    demoted = [f for f in report if f.code == "dequant-fp32-dot"]
    assert demoted and all(f.severity == "hint" for f in demoted)
    assert any("budgeted" in f.message for f in demoted)

    # seeded regression: the budget remembers fewer dequant chains
    tight = json.loads(json.dumps(budgets))
    tight["programs"]["quantization.convnet_int8"][
        "dequant_fp32_dot"] = 0
    report2, _ = mxbudgets.check(results, tight)
    errs = [f for f in report2 if f.code == "budget-regression"]
    assert any("dequant_fp32_dot" in f.message for f in errs)
    # the un-budgeted chain keeps its WARN severity
    assert [f for f in report2 if f.code == "dequant-fp32-dot"
            and f.severity == "warn"]

    # bytes over tolerance -> regression; far under -> slack hint
    tight2 = json.loads(json.dumps(budgets))
    tight2["programs"]["quantization.convnet_fp32"]["bytes_moved"] //= 2
    report3, _ = mxbudgets.check(results, tight2)
    assert any(f.code == "budget-regression" and
               "bytes_moved" in f.message for f in report3)
    loose = json.loads(json.dumps(budgets))
    loose["programs"]["quantization.convnet_fp32"]["bytes_moved"] *= 3
    report4, _ = mxbudgets.check(results, loose)
    assert any(f.code == "budget-slack" and "bytes_moved" in f.message
               for f in report4)

    # a program without a baseline entry -> budget-missing hint
    partial = json.loads(json.dumps(budgets))
    del partial["programs"]["quantization.convnet_bf16"]
    report5, _ = mxbudgets.check(results, partial)
    missing = [f for f in report5 if f.code == "budget-missing"]
    assert any("convnet_bf16" in f.message for f in missing)
    assert all(f.severity == "hint" for f in missing)


def test_committed_budgets_match_head_analysis():
    """The committed COST_BUDGETS.json is in sync with HEAD: zero
    budget regressions (the parity cost stage gates on exactly this)."""
    budgets = mxbudgets.load(BUDGETS_PATH)
    results = mxcost.analyze_bench_set(dp=8)
    report, _ = mxbudgets.check(results, budgets)
    errs = [f for f in report if f.severity == "error"]
    assert errs == [], [f.format() for f in errs]


# ---------------------------------------------------------------------------
# the CLI: --cost-report and --fail-on (the CI contract)
# ---------------------------------------------------------------------------

def test_mxlint_cost_report_passes_on_head_and_fails_on_regressions(
        tmp_path, capsys):
    cli = _cli()

    # HEAD against the committed budgets: clean at --fail-on=warn
    rc = cli.main(["--cost-report", "--budgets", BUDGETS_PATH,
                   "--fail-on", "warn", "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0 and out["failing"] == 0
    assert "quantization.convnet_int8" in out["programs"]
    assert out["budget_deltas"]["quantization.convnet_int8"][
        "dequant_fp32_dot"]["ok"]

    # seeded regression 1: a shrunk bucket cap = extra collectives/step
    rc = cli.main(["--cost-report", "--budgets", BUDGETS_PATH,
                   "--bucket-mb", "0.05", "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1 and out["failing"] >= 1
    assert not out["budget_deltas"]["dp8_bucketed_convnet"][
        "collectives_per_step"]["ok"]

    # seeded regression 2: a forced f32 upcast inside a bf16 graph
    kw = {"dtype": "bfloat16"}
    c, hw = 3, 32
    data = sym.Variable("data", shape=(8, c, hw, hw), **kw)
    x = sym.Convolution(data, sym.Variable("conv0_weight",
                                           shape=(16, c, 3, 3), **kw),
                        no_bias=True, kernel=(3, 3), num_filter=16,
                        pad=(1, 1), name="conv0")
    x = sym.Cast(x, dtype="float32", name="forced_upcast")
    x = sym.Flatten(x, name="flatten0")
    out_sym = sym.FullyConnected(
        x, sym.Variable("fc0_weight", shape=(32, 16 * hw * hw)),
        sym.Variable("fc0_bias", shape=(32,)), num_hidden=32, name="fc0")
    fixture = tmp_path / "upcast-symbol.json"
    fixture.write_text(out_sym.tojson())
    rc = cli.main(["--cost-report", "--budgets", BUDGETS_PATH,
                   str(fixture), "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    fixture_prog = out["programs"]["upcast-symbol.json"]
    assert fixture_prog["counters"]["f32_upcasts"] == 1
    assert any(f["code"] == "f32-upcast-in-bf16"
               and f["node"] == "forced_upcast"
               for f in fixture_prog["findings"])


def test_mxlint_fail_on_contract(tmp_path, capsys):
    """--fail-on={hint,warn,error} is the documented exit-code ladder:
    exit 1 iff a finding at/above the threshold survives --suppress."""
    cli = _cli()
    # a script whose only finding is a WARN (host-sync-in-loop)
    warn_py = tmp_path / "warny.py"
    warn_py.write_text("for b in it:\n    print(x.asnumpy())\n")
    # a graph whose only finding is a HINT (tpu-layout)
    hint_json = tmp_path / "hint-symbol.json"
    hint_json.write_text(sym.FullyConnected(
        sym.Variable("data"), num_hidden=100, no_bias=True,
        name="odd").tojson())

    assert cli.main([str(warn_py)]) == 1                  # default: warn
    capsys.readouterr()
    assert cli.main([str(warn_py), "--fail-on", "error"]) == 0
    capsys.readouterr()
    # suppression drains the gate
    assert cli.main([str(warn_py), "--fail-on", "warn",
                     "--suppress", "host-sync-in-loop"]) == 0
    capsys.readouterr()

    assert cli.main([str(hint_json)]) == 0                # hints pass...
    capsys.readouterr()
    rc = cli.main([str(hint_json), "--fail-on", "hint", "--json"])
    out = json.loads(capsys.readouterr().out)             # ...until asked
    assert rc == 1 and out["by_code"].get("tpu-layout", 0) >= 1
    assert cli.main([str(hint_json), "--fail-on", "hint",
                     "--suppress", "tpu-layout"]) == 0
    capsys.readouterr()


def test_host_transfer_in_graph_source_lint():
    src = ("import jax\n"
           "import numpy as np\n"
           "@jax.jit\n"
           "def step(w, x):\n"
           "    hw = np.asarray(w)\n"
           "    return x.asnumpy() + hw\n"
           "def host_side(w):\n"
           "    return np.asarray(w)\n")
    report = analysis.check_source(src, filename="t.py")
    hits = [f for f in report if f.code == "host-transfer-in-graph"]
    assert {f.location for f in hits} == {"t.py:5", "t.py:6"}
    # outside a traced function numpy coercion is fine
    assert not [f for f in hits if f.location == "t.py:8"]
