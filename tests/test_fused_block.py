"""K-step block mode of the fused train step (fused.py call_block via
Module.fit): one `lax.scan` dispatch per K batches must train identically
to per-step dispatch — the TPU-native form of the reference's bulk-exec
segments (`src/executor/graph_executor.cc:1194-1316`)."""
import os

import numpy as np
import pytest

import incubator_mxnet_tpu as mx


def _net():
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    h = mx.sym.BatchNorm(h, name="bn1")  # aux-state carry crosses the scan
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(h, name="softmax")


def _batches(n, bs=8, dim=6, seed=3):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        out.append((rng.randn(bs, dim).astype("f4"),
                    rng.randint(0, 4, bs).astype("f4")))
    return out


class _ListIter(mx.io.DataIter):
    def __init__(self, batches, bs):
        super().__init__(batch_size=bs)
        self._b = batches
        self._i = 0

    @property
    def provide_data(self):
        return [mx.io.DataDesc("data", self._b[0][0].shape, dtype=np.float32)]

    @property
    def provide_label(self):
        return [mx.io.DataDesc("softmax_label", self._b[0][1].shape,
                               dtype=np.float32)]

    def reset(self):
        self._i = 0

    def next(self):
        if self._i >= len(self._b):
            raise StopIteration
        d, l = self._b[self._i]
        self._i += 1
        return mx.io.DataBatch(
            data=[mx.nd.array(d)], label=[mx.nd.array(l)], pad=0,
            provide_data=self.provide_data,
            provide_label=self.provide_label)


def _fit(block_k, n_batches, ctx=None, sched=None, epochs=1):
    mx.random.seed(7)
    os.environ["MXNET_FUSED_STEP_BLOCK"] = str(block_k)
    try:
        batches = _batches(n_batches)
        it = _ListIter(batches, bs=8)
        mod = mx.mod.Module(_net(), context=ctx or mx.cpu())
        opt_params = {"learning_rate": 0.1, "momentum": 0.9}
        if sched is not None:
            opt_params["lr_scheduler"] = sched
        cb_batches = []
        mod.fit(it, num_epoch=epochs, optimizer="sgd",
                optimizer_params=opt_params, eval_metric="acc",
                initializer=mx.initializer.Xavier(),
                batch_end_callback=lambda p: cb_batches.append(p.nbatch),
                kvstore=None)
        assert mod._fused_step is not None and not mod._fused_step.broken
        args, auxs = mod.get_params()
        metric_val = None
        return ({k: v.asnumpy() for k, v in args.items()},
                {k: v.asnumpy() for k, v in auxs.items()},
                cb_batches, mod)
    finally:
        os.environ.pop("MXNET_FUSED_STEP_BLOCK", None)


def test_block_matches_per_step():
    """K=4 scan blocks over 9 batches (2 blocks + tail) == per-step."""
    a1, x1, cb1, _ = _fit(1, 9)
    a4, x4, cb4, mod = _fit(4, 9)
    assert cb1 == list(range(9)) and cb4 == list(range(9))
    for k in a1:
        np.testing.assert_allclose(a4[k], a1[k], rtol=2e-5, atol=2e-6,
                                   err_msg=k)
    for k in x1:
        np.testing.assert_allclose(x4[k], x1[k], rtol=2e-5, atol=2e-6,
                                   err_msg=k)
    # the block program actually ran (K=4 program exists, carry armed)
    assert 4 in mod._fused_step._jit_block
    assert mod._fused_step._carry is not None


def test_block_with_lr_schedule_mid_block():
    """An lr schedule stepping INSIDE a block must land per-step rows."""
    def mk():
        return mx.lr_scheduler.FactorScheduler(step=3, factor=0.5)
    a1, x1, _, _ = _fit(1, 8, sched=mk())
    a4, x4, _, _ = _fit(4, 8, sched=mk())
    for k in a1:
        np.testing.assert_allclose(a4[k], a1[k], rtol=2e-5, atol=2e-6,
                                   err_msg=k)


def test_block_multi_device():
    """Block mode over the 8-device dp mesh: scan + collective gradients."""
    ctx = [mx.cpu(i) for i in range(4)]
    a1, x1, _, _ = _fit(1, 4, ctx=ctx)
    a4, x4, _, mod = _fit(4, 4, ctx=ctx)
    for k in a1:
        np.testing.assert_allclose(a4[k], a1[k], rtol=2e-5, atol=2e-6,
                                   err_msg=k)
    assert 4 in mod._fused_step._jit_block


def test_block_multi_epoch_and_outputs():
    """Carry survives epoch boundaries (get_params flush between epochs);
    last_outputs stays readable after later dispatches."""
    a4, x4, cb, mod = _fit(4, 8, epochs=2)
    assert len(cb) == 16
    outs = mod.get_outputs()
    np.testing.assert_equal(np.isfinite(outs[0].asnumpy()).all(), True)
    for v in a4.values():
        assert np.isfinite(v).all()


def test_gluon_estimator_block_matches_per_step():
    """Estimator.fit block mode (gluon fused scan) == per-step fit."""
    from incubator_mxnet_tpu import gluon

    def run(block_k):
        os.environ["MXNET_FUSED_STEP_BLOCK"] = str(block_k)
        try:
            mx.random.seed(11)
            net = gluon.nn.HybridSequential()
            net.add(gluon.nn.Dense(16, activation="relu"),
                    gluon.nn.BatchNorm(), gluon.nn.Dense(4))
            net.initialize(mx.initializer.Xavier())
            trainer = gluon.Trainer(net.collect_params(), "sgd",
                                    {"learning_rate": 0.1, "momentum": 0.9})
            est = gluon.contrib.estimator.Estimator(
                net, gluon.loss.SoftmaxCrossEntropyLoss(),
                train_metrics=[mx.metric.Accuracy()], trainer=trainer)
            batches = [(mx.nd.array(d), mx.nd.array(l))
                       for d, l in _batches(9, bs=8, dim=6, seed=5)]
            ends = []

            class Rec(gluon.contrib.estimator.EventHandler):
                def batch_end(self, e):
                    ends.append(e.batch_idx)

            est.fit(iter(batches), epochs=1, event_handlers=[Rec()])
            assert est._fused is not None and not est._fused.broken
            # gluon name scopes increment per instantiation: compare by
            # position, not by (run-dependent) parameter name
            params = [v.data().asnumpy()
                      for v in net.collect_params().values()]
            return params, ends, est
        finally:
            os.environ.pop("MXNET_FUSED_STEP_BLOCK", None)

    p1, e1, _ = run(1)
    p4, e4, est = run(4)
    assert e1 == list(range(9)) and e4 == list(range(9))
    for i, (a, b) in enumerate(zip(p4, p1)):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6,
                                   err_msg=f"param {i}")
    assert 4 in est._fused._jit_block


def test_block_get_outputs_per_batch():
    """A batch-j callback reading get_outputs() must see batch j's outputs
    (the scan ys expose every step, cursor-driven), not the block-final
    ones."""
    os.environ["MXNET_FUSED_STEP_BLOCK"] = "4"
    try:
        mx.random.seed(7)
        batches = _batches(8)
        it = _ListIter(batches, bs=8)
        mod = mx.mod.Module(_net(), context=mx.cpu())
        seen = []

        def cb(p):
            seen.append((p.nbatch, mod.get_outputs()[0].asnumpy().copy()))

        mod.fit(it, num_epoch=1, optimizer="sgd",
                optimizer_params={"learning_rate": 0.0},  # frozen weights
                eval_metric="acc", initializer=mx.initializer.Xavier(),
                batch_end_callback=cb, kvstore=None)
        assert len(seen) == 8
        # lr=0 freezes weights except BN stats; batches differ, so outputs
        # must differ across the block — and must match a direct forward
        # of the same batch (weights frozen -> reproducible)
        outs = {n: o for n, o in seen}
        assert not np.allclose(outs[0], outs[3]), \
            "per-batch outputs must differ within a block"
        for j in (1, 2):
            assert not np.allclose(outs[j], outs[3]), \
                f"batch {j} callback saw block-final outputs"
    finally:
        os.environ.pop("MXNET_FUSED_STEP_BLOCK", None)


def test_fallback_block_keeps_per_batch_callbacks():
    """A block the fused path rejects (host-side metric) must run with
    CLASSIC per-batch callback timing: the batch-j callback sees the
    metric updated through batch j only."""

    class HostOnlyAcc(mx.metric.EvalMetric):
        def __init__(self):
            super().__init__("hostacc")

        def update(self, labels, preds):
            self.sum_metric += float(
                (preds[0].asnumpy().argmax(1) ==
                 labels[0].asnumpy()).sum())
            self.num_inst += labels[0].shape[0]

    os.environ["MXNET_FUSED_STEP_BLOCK"] = "4"
    try:
        mx.random.seed(7)
        it = _ListIter(_batches(8), bs=8)
        mod = mx.mod.Module(_net(), context=mx.cpu())
        seen = []

        def cb(p):
            seen.append((p.nbatch, p.eval_metric.num_inst))

        mod.fit(it, num_epoch=1, optimizer="sgd",
                optimizer_params={"learning_rate": 0.1},
                eval_metric=HostOnlyAcc(),
                initializer=mx.initializer.Xavier(),
                batch_end_callback=cb, kvstore=None)
        # metric must have been updated batch-by-batch at each callback
        assert seen == [(j, (j + 1) * 8) for j in range(8)], seen
    finally:
        os.environ.pop("MXNET_FUSED_STEP_BLOCK", None)


def test_gluon_block_bf16_cast_net():
    """A bf16-cast net's BN aux updates compute fp32 stats; the scan
    carry must pin them back to the stored aux dtype (regression: this
    broke lax.scan's carry-type invariance and silently dropped the
    Estimator to the eager loop)."""
    from incubator_mxnet_tpu import gluon

    os.environ["MXNET_FUSED_STEP_BLOCK"] = "4"
    try:
        mx.random.seed(13)
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(8, activation="relu"),
                gluon.nn.BatchNorm(), gluon.nn.Dense(4))
        net.initialize(mx.initializer.Xavier())
        net.cast("bfloat16")
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1, "momentum": 0.9,
                                 "multi_precision": True})
        est = gluon.contrib.estimator.Estimator(
            net, gluon.loss.SoftmaxCrossEntropyLoss(),
            train_metrics=[mx.metric.Accuracy()], trainer=trainer)
        batches = [(mx.nd.array(d).astype("bfloat16"), mx.nd.array(l))
                   for d, l in _batches(8, bs=8, dim=6, seed=2)]
        est.fit(iter(batches), epochs=1, event_handlers=[])
        assert est._fused is not None and not est._fused.broken, \
            "bf16 net must stay on the fused path"
        assert 4 in est._fused._jit_block, \
            "the K=4 scan block must have run for the bf16 net"
    finally:
        os.environ.pop("MXNET_FUSED_STEP_BLOCK", None)
