"""Operator correctness tests (reference tests/python/unittest/test_operator.py).

Forward checks against numpy references; gradients via the autograd tape
checked against finite differences for key ops (the reference's
check_numeric_gradient backbone, `python/mxnet/test_utils.py:790`).
"""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, autograd


def numeric_grad(f, x, eps=1e-4):
    """Central finite differences of scalar f at numpy x."""
    g = np.zeros_like(x)
    it = np.nditer(x, flags=["multi_index"])
    while not it.finished:
        idx = it.multi_index
        orig = x[idx]
        x[idx] = orig + eps
        fp = f(x)
        x[idx] = orig - eps
        fm = f(x)
        x[idx] = orig
        g[idx] = (fp - fm) / (2 * eps)
        it.iternext()
    return g


def check_grad(op_fn, np_x, rtol=1e-3, atol=1e-4):
    """Compare autograd gradient of sum(op(x)) with finite differences."""
    x = nd.array(np_x, dtype=np_x.dtype)
    x.attach_grad()
    with autograd.record():
        y = nd.sum(op_fn(x))
    y.backward()
    ng = numeric_grad(lambda v: float(nd.sum(op_fn(nd.array(v, dtype=v.dtype))).asnumpy()),
                      np_x.copy())
    np.testing.assert_allclose(x.grad.asnumpy(), ng, rtol=rtol, atol=atol)


def test_unary_forward():
    x = np.random.rand(3, 4).astype("float64") + 0.5
    cases = {
        "exp": np.exp, "log": np.log, "sqrt": np.sqrt, "square": np.square,
        "sigmoid": lambda v: 1 / (1 + np.exp(-v)), "tanh": np.tanh,
        "abs": np.abs, "relu": lambda v: np.maximum(v, 0),
    }
    for name, ref in cases.items():
        out = getattr(nd, name)(nd.array(x, dtype="float64")).asnumpy()
        np.testing.assert_allclose(out, ref(x), rtol=1e-6, err_msg=name)


def test_unary_grads():
    x = np.random.rand(2, 3).astype("float64") + 0.5
    for name in ["exp", "log", "sqrt", "square", "sigmoid", "tanh"]:
        check_grad(getattr(nd, name), x)


def test_fully_connected():
    x = np.random.rand(4, 10).astype("f4")
    w = np.random.rand(6, 10).astype("f4")
    b = np.random.rand(6).astype("f4")
    out = nd.FullyConnected(nd.array(x), nd.array(w), nd.array(b), num_hidden=6)
    np.testing.assert_allclose(out.asnumpy(), x @ w.T + b, rtol=1e-5)
    out2 = nd.FullyConnected(nd.array(x), nd.array(w), num_hidden=6, no_bias=True)
    np.testing.assert_allclose(out2.asnumpy(), x @ w.T, rtol=1e-5)
    # flatten semantics: (N, C, H, W) -> (N, C*H*W)
    x4 = np.random.rand(2, 3, 2, 2).astype("f4")
    w4 = np.random.rand(5, 12).astype("f4")
    out3 = nd.FullyConnected(nd.array(x4), nd.array(w4), num_hidden=5, no_bias=True)
    np.testing.assert_allclose(out3.asnumpy(), x4.reshape(2, -1) @ w4.T, rtol=1e-5)


def test_convolution_vs_reference():
    """Convolution forward against explicit im2col reference."""
    np.random.seed(1)
    x = np.random.rand(2, 3, 5, 5).astype("float64")
    w = np.random.rand(4, 3, 3, 3).astype("float64")
    b = np.random.rand(4).astype("float64")
    out = nd.Convolution(nd.array(x), nd.array(w), nd.array(b),
                         kernel=(3, 3), num_filter=4, stride=(1, 1), pad=(1, 1))
    xp = np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)))
    ref = np.zeros((2, 4, 5, 5))
    for n in range(2):
        for f in range(4):
            for i in range(5):
                for j in range(5):
                    ref[n, f, i, j] = np.sum(
                        xp[n, :, i:i + 3, j:j + 3] * w[f]) + b[f]
    np.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-5)


def test_convolution_grouped_strided():
    x = np.random.rand(1, 4, 8, 8).astype("f4")
    w = np.random.rand(8, 2, 3, 3).astype("f4")
    out = nd.Convolution(nd.array(x), nd.array(w), kernel=(3, 3), num_filter=8,
                         num_group=2, stride=(2, 2), pad=(1, 1), no_bias=True)
    assert out.shape == (1, 8, 4, 4)


def test_deconvolution_shape_and_grad_identity():
    x = np.random.rand(1, 3, 4, 4).astype("f4")
    w = np.random.rand(3, 5, 3, 3).astype("f4")
    out = nd.Deconvolution(nd.array(x), nd.array(w), kernel=(3, 3),
                           num_filter=5, stride=(2, 2), pad=(1, 1),
                           adj=(1, 1), no_bias=True)
    assert out.shape == (1, 5, 8, 8)
    # deconv(conv) shape round trip
    y = nd.Convolution(out, nd.array(np.random.rand(3, 5, 3, 3).astype("f4")),
                       kernel=(3, 3), num_filter=3, stride=(2, 2), pad=(1, 1),
                       no_bias=True)
    assert y.shape == (1, 3, 4, 4)


def test_pooling():
    x = np.arange(16, dtype="f4").reshape(1, 1, 4, 4)
    mp = nd.Pooling(nd.array(x), kernel=(2, 2), pool_type="max", stride=(2, 2))
    np.testing.assert_allclose(mp.asnumpy().reshape(2, 2), [[5, 7], [13, 15]])
    ap = nd.Pooling(nd.array(x), kernel=(2, 2), pool_type="avg", stride=(2, 2))
    np.testing.assert_allclose(ap.asnumpy().reshape(2, 2), [[2.5, 4.5], [10.5, 12.5]])
    gp = nd.Pooling(nd.array(x), kernel=(1, 1), pool_type="max", global_pool=True)
    assert gp.shape == (1, 1, 1, 1) and gp.asnumpy().item() == 15
    # ceil (full) convention
    fp = nd.Pooling(nd.array(x), kernel=(3, 3), stride=(2, 2), pool_type="max",
                    pooling_convention="full")
    assert fp.shape == (1, 1, 2, 2)


def test_batchnorm_train_and_inference():
    np.random.seed(2)
    x = np.random.rand(8, 3, 4, 4).astype("f4") * 5
    gamma = np.ones(3, dtype="f4")
    beta = np.zeros(3, dtype="f4")
    mmean = nd.zeros((3,))
    mvar = nd.ones((3,))
    with autograd.record(train_mode=True):
        out = nd.BatchNorm(nd.array(x), nd.array(gamma), nd.array(beta),
                           mmean, mvar, fix_gamma=False, momentum=0.9, eps=1e-5)
    o = out.asnumpy()
    # normalized per channel over (N,H,W)
    assert abs(o.mean(axis=(0, 2, 3))).max() < 1e-4
    np.testing.assert_allclose(o.std(axis=(0, 2, 3)), 1.0, rtol=1e-2)
    # moving stats updated
    batch_mean = x.mean(axis=(0, 2, 3))
    np.testing.assert_allclose(mmean.asnumpy(), 0.1 * batch_mean, rtol=1e-4)
    # inference path uses moving stats
    out_inf = nd.BatchNorm(nd.array(x), nd.array(gamma), nd.array(beta),
                           mmean, mvar, fix_gamma=False, eps=1e-5)
    ref = (x - mmean.asnumpy().reshape(1, 3, 1, 1)) / np.sqrt(
        mvar.asnumpy().reshape(1, 3, 1, 1) + 1e-5)
    np.testing.assert_allclose(out_inf.asnumpy(), ref, rtol=1e-4)


def test_layernorm():
    x = np.random.rand(4, 10).astype("f4")
    g = np.random.rand(10).astype("f4")
    b = np.random.rand(10).astype("f4")
    out = nd.LayerNorm(nd.array(x), nd.array(g), nd.array(b), eps=1e-5)
    mu = x.mean(-1, keepdims=True)
    sd = x.std(-1, keepdims=True)
    np.testing.assert_allclose(out.asnumpy(), (x - mu) / np.sqrt(sd**2 + 1e-5) * g + b,
                               rtol=1e-4)


def test_softmax_ops():
    x = np.random.rand(3, 5).astype("f4")
    s = nd.softmax(nd.array(x)).asnumpy()
    e = np.exp(x - x.max(-1, keepdims=True))
    np.testing.assert_allclose(s, e / e.sum(-1, keepdims=True), rtol=1e-5)
    ls = nd.log_softmax(nd.array(x)).asnumpy()
    np.testing.assert_allclose(ls, np.log(s), rtol=1e-4, atol=1e-6)


def test_softmax_output_grad():
    """SoftmaxOutput: backward must be softmax - onehot, ignoring head grad."""
    x = np.random.rand(4, 5).astype("f4")
    label = np.array([0, 2, 1, 4], dtype="f4")
    data = nd.array(x)
    data.attach_grad()
    with autograd.record():
        out = nd.SoftmaxOutput(data, nd.array(label))
    out.backward()
    sm = np.exp(x - x.max(-1, keepdims=True))
    sm = sm / sm.sum(-1, keepdims=True)
    onehot = np.eye(5, dtype="f4")[label.astype(int)]
    np.testing.assert_allclose(data.grad.asnumpy(), sm - onehot, rtol=1e-5)


def test_softmax_output_ignore_label():
    x = np.random.rand(3, 4).astype("f4")
    label = np.array([1, -1, 2], dtype="f4")
    data = nd.array(x)
    data.attach_grad()
    with autograd.record():
        out = nd.SoftmaxOutput(data, nd.array(label), use_ignore=True,
                               ignore_label=-1)
    out.backward()
    g = data.grad.asnumpy()
    assert (g[1] == 0).all() and (g[0] != 0).any()


def test_regression_outputs():
    x = np.random.rand(4, 3).astype("f4")
    lbl = np.random.rand(4, 3).astype("f4")
    d = nd.array(x)
    d.attach_grad()
    with autograd.record():
        out = nd.LinearRegressionOutput(d, nd.array(lbl))
    out.backward()
    np.testing.assert_allclose(out.asnumpy(), x)
    np.testing.assert_allclose(d.grad.asnumpy(), (x - lbl) / 3, rtol=1e-5)


def test_activation_types():
    x = np.linspace(-2, 2, 9, dtype="f4")
    a = nd.array(x)
    np.testing.assert_allclose(nd.Activation(a, act_type="relu").asnumpy(),
                               np.maximum(x, 0))
    np.testing.assert_allclose(nd.Activation(a, act_type="softrelu").asnumpy(),
                               np.log1p(np.exp(x)), rtol=1e-5)
    np.testing.assert_allclose(nd.LeakyReLU(a, act_type="leaky", slope=0.1).asnumpy(),
                               np.where(x > 0, x, 0.1 * x), rtol=1e-6)
    np.testing.assert_allclose(nd.LeakyReLU(a, act_type="elu", slope=1.0).asnumpy(),
                               np.where(x > 0, x, np.expm1(x)), rtol=1e-5)


def test_optimizer_ops():
    w = nd.array([1.0, 2.0])
    g = nd.array([0.1, 0.2])
    out = nd.sgd_update(w, g, lr=0.1, wd=0.0, out=w)
    np.testing.assert_allclose(w.asnumpy(), [0.99, 1.98], rtol=1e-6)

    # momentum
    w = nd.array([1.0])
    g = nd.array([1.0])
    mom = nd.zeros((1,))
    nd.sgd_mom_update(w, g, mom, lr=0.1, momentum=0.9, out=w)
    np.testing.assert_allclose(w.asnumpy(), [0.9], rtol=1e-6)
    np.testing.assert_allclose(mom.asnumpy(), [-0.1], rtol=1e-6)
    nd.sgd_mom_update(w, g, mom, lr=0.1, momentum=0.9, out=w)
    np.testing.assert_allclose(mom.asnumpy(), [-0.19], rtol=1e-6)

    # adam
    w = nd.array([1.0])
    mean = nd.zeros((1,))
    var = nd.zeros((1,))
    nd.adam_update(w, g, mean, var, lr=0.01, beta1=0.9, beta2=0.999,
                   epsilon=1e-8, out=w)
    assert w.asnumpy()[0] < 1.0


def test_rnn_lstm_shapes():
    T, B, I, H, L = 5, 3, 4, 6, 2
    from incubator_mxnet_tpu.ops.nn import rnn_param_size
    psize = rnn_param_size("lstm", I, H, L, False)
    data = nd.random.uniform(shape=(T, B, I))
    params = nd.random.uniform(-0.1, 0.1, shape=(psize,))
    h0 = nd.zeros((L, B, H))
    c0 = nd.zeros((L, B, H))
    out = nd.RNN(data, params, h0, c0, state_size=H, num_layers=L,
                 mode="lstm", state_outputs=True)
    assert out[0].shape == (T, B, H)
    assert out[1].shape == (L, B, H)
    assert out[2].shape == (L, B, H)
    # bidirectional
    psize_bi = rnn_param_size("lstm", I, H, L, True)
    params_bi = nd.random.uniform(-0.1, 0.1, shape=(psize_bi,))
    out_bi = nd.RNN(data, params_bi, nd.zeros((2 * L, B, H)),
                    nd.zeros((2 * L, B, H)), state_size=H, num_layers=L,
                    mode="lstm", bidirectional=True, state_outputs=True)
    assert out_bi[0].shape == (T, B, 2 * H)


def test_rnn_gru_matches_manual():
    """Single-layer GRU against a manual numpy step."""
    T, B, I, H = 3, 2, 4, 5
    from incubator_mxnet_tpu.ops.nn import rnn_param_size
    np.random.seed(3)
    psize = rnn_param_size("gru", I, H, 1, False)
    flat = np.random.uniform(-0.5, 0.5, psize).astype("f4")
    data = np.random.rand(T, B, I).astype("f4")
    out = nd.RNN(nd.array(data), nd.array(flat), nd.zeros((1, B, H)),
                 state_size=H, num_layers=1, mode="gru")
    # manual
    wx = flat[:3 * H * I].reshape(3 * H, I)
    wh = flat[3 * H * I:3 * H * I + 3 * H * H].reshape(3 * H, H)
    bx = flat[3 * H * (I + H):3 * H * (I + H) + 3 * H]
    bh = flat[3 * H * (I + H) + 3 * H:]
    h = np.zeros((B, H), dtype="f4")
    sig = lambda v: 1 / (1 + np.exp(-v))
    for t in range(T):
        xw = data[t] @ wx.T + bx
        hw = h @ wh.T + bh
        xr, xz, xn = np.split(xw, 3, -1)
        hr, hz, hn = np.split(hw, 3, -1)
        r = sig(xr + hr)
        z = sig(xz + hz)
        n = np.tanh(xn + r * hn)
        h = (1 - z) * n + z * h
    np.testing.assert_allclose(out.asnumpy()[-1], h, rtol=1e-4, atol=1e-5)


def test_linalg():
    a = np.random.rand(4, 4)
    spd = a @ a.T + 4 * np.eye(4)
    l = nd.linalg.potrf(nd.array(spd, dtype="float64"))
    np.testing.assert_allclose(l.asnumpy() @ l.asnumpy().T, spd, rtol=1e-6)
    sld = nd.linalg.sumlogdiag(nd.array(np.eye(3) * np.e))
    np.testing.assert_allclose(sld.asnumpy(), 3.0, rtol=1e-6)


def test_where_clip_misc():
    c = nd.array([1.0, 0.0, 1.0])
    x = nd.array([1.0, 2.0, 3.0])
    y = nd.array([10.0, 20.0, 30.0])
    np.testing.assert_allclose(nd.where(c, x, y).asnumpy(), [1, 20, 3])
    np.testing.assert_allclose(nd.clip(x, a_min=1.5, a_max=2.5).asnumpy(),
                               [1.5, 2.0, 2.5])


def test_sequence_ops():
    x = np.arange(24, dtype="f4").reshape(4, 3, 2)  # (T, B, D)
    seqlen = nd.array([2.0, 3.0, 4.0])
    masked = nd.SequenceMask(nd.array(x), seqlen, use_sequence_length=True,
                             value=-1.0)
    m = masked.asnumpy()
    assert (m[2, 0] == -1).all() and (m[1, 0] == x[1, 0]).all()
    last = nd.SequenceLast(nd.array(x), seqlen, use_sequence_length=True)
    np.testing.assert_allclose(last.asnumpy()[0], x[1, 0])
    np.testing.assert_allclose(last.asnumpy()[2], x[3, 2])
    rev = nd.SequenceReverse(nd.array(x), seqlen, use_sequence_length=True)
    np.testing.assert_allclose(rev.asnumpy()[0, 0], x[1, 0])
    np.testing.assert_allclose(rev.asnumpy()[2, 0], x[2, 0])


def test_softmax_output_default_mode_flattens():
    """Default mode (not multi_output, not preserve_shape) flattens trailing
    dims onto one class axis (reference softmax_output-inl.h)."""
    data = np.random.randn(2, 3, 4).astype("f4")
    out = nd.SoftmaxOutput(nd.array(data), nd.zeros((2,))).asnumpy()
    assert out.shape == (2, 3, 4)
    ref = np.exp(data.reshape(2, -1))
    ref = (ref / ref.sum(1, keepdims=True)).reshape(2, 3, 4)
    np.testing.assert_allclose(out, ref, rtol=1e-5)
    # softmax over the flattened axis sums to 1 per batch row
    np.testing.assert_allclose(out.reshape(2, -1).sum(1), [1.0, 1.0],
                               rtol=1e-5)
