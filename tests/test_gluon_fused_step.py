"""Gluon fused train step (gluon/fused_step.py via Estimator.fit): one
donated XLA program per signature, with exact parity against the eager
record/backward/step loop."""
import os

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, nd


def _data(n=64, d=12, k=3, seed=4):
    rng = np.random.RandomState(seed)
    return (rng.randn(n, d).astype("f4"),
            rng.randint(0, k, n).astype("f4"))


def _net_init(seed=9):
    rng = np.random.RandomState(seed)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"))
    net.add(gluon.nn.Dense(3))
    net.initialize()
    net(nd.array(np.zeros((2, 12), "f4")))
    for p in net.collect_params().values():
        p.set_data(nd.array(rng.randn(*p.shape).astype("f4") * 0.2))
    return net


def _run(fused_on, optimizer="sgd", opt_params=None, steps=6, bn=False):
    os.environ["MXNET_FUSED_TRAIN_STEP"] = "1" if fused_on else "0"
    try:
        rng = np.random.RandomState(9)
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(16))
        if bn:
            net.add(gluon.nn.BatchNorm())
        net.add(gluon.nn.Dense(3))
        net.initialize()
        net(nd.array(np.zeros((2, 12), "f4")))
        for p in net.collect_params().values():
            r = rng.randn(*p.shape) * 0.2 if p.shape else 0
            if p.name.endswith(("gamma", "running_var")):
                p.set_data(nd.array(np.ones(p.shape, "f4")))
            elif p.name.endswith(("beta", "running_mean", "bias")):
                p.set_data(nd.array(np.zeros(p.shape, "f4")))
            else:
                p.set_data(nd.array(r.astype("f4")))
        trainer = gluon.Trainer(net.collect_params(), optimizer,
                                opt_params or {"learning_rate": 0.1})
        est = gluon.contrib.estimator.Estimator(
            net, gluon.loss.SoftmaxCrossEntropyLoss(),
            train_metrics=[mx.metric.Accuracy()], trainer=trainer)
        X, y = _data()
        batches = [(nd.array(X[i:i + 16]), nd.array(y[i:i + 16]))
                   for i in range(0, 64, 16)] * (steps // 4 + 1)
        est.fit(iter(batches[:steps]), epochs=1,
                event_handlers=[])
        metric_val = dict(m.get_name_value()[0] if isinstance(
            m.get_name_value(), list) else [m.get_name_value()]
            for m in est.train_metrics)
        params = [p.data().asnumpy()
                  for p in net.collect_params().values()]
        states = None
        if 0 in trainer._updaters[0].states and \
                trainer._updaters[0].states[0] is not None:
            from incubator_mxnet_tpu.fused import _state_data
            import jax
            states = jax.tree_util.tree_leaves(
                _state_data(trainer._updaters[0].states[0]))
        return params, metric_val, est, states
    finally:
        os.environ.pop("MXNET_FUSED_TRAIN_STEP", None)


@pytest.mark.parametrize("optimizer,opt_params,bn", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}, False),
    ("adam", {"learning_rate": 0.01}, False),
    ("sgd", {"learning_rate": 0.1}, True),
])
def test_estimator_fused_matches_eager(optimizer, opt_params, bn):
    p_fused, m_fused, est, s_fused = _run(True, optimizer, opt_params, bn=bn)
    p_eager, m_eager, _, s_eager = _run(False, optimizer, opt_params, bn=bn)
    assert est._fused is not None and not est._fused.broken, \
        "Estimator must engage the fused Gluon step"
    for i, (a, b) in enumerate(zip(p_fused, p_eager)):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=1e-6,
                                   err_msg=f"param {i}")
    for k in m_eager:
        np.testing.assert_allclose(m_fused[k], m_eager[k], rtol=1e-6,
                                   err_msg=k)
    if s_eager is not None:
        for a, b in zip(s_fused, s_eager):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-5, atol=1e-6)


def test_estimator_fused_falls_back_on_dropout():
    """RNG-consuming nets (dropout) must fall back to the eager loop and
    still train."""
    os.environ["MXNET_FUSED_TRAIN_STEP"] = "1"
    try:
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(16, activation="relu"))
        net.add(gluon.nn.Dropout(0.5))
        net.add(gluon.nn.Dense(3))
        net.initialize(mx.initializer.Xavier())
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1})
        est = gluon.contrib.estimator.Estimator(
            net, gluon.loss.SoftmaxCrossEntropyLoss(), trainer=trainer)
        X, y = _data()
        batches = [(nd.array(X[:16]), nd.array(y[:16]))] * 4
        est.fit(iter(batches), epochs=1, event_handlers=[])
        for p in net.collect_params().values():
            assert np.isfinite(p.data().asnumpy()).all()
    finally:
        os.environ.pop("MXNET_FUSED_TRAIN_STEP", None)


def test_estimator_fused_then_eager_state_shared():
    """Switching to the eager path mid-training (new kvstore etc.) keeps
    optimizer state: both paths use the trainer's updater store."""
    p_fused, _, est, _ = _run(True, "sgd",
                              {"learning_rate": 0.1, "momentum": 0.9},
                              steps=3)
    upd = est.trainer._updaters[0]
    assert any(v is not None for v in upd.states.values()), \
        "fused path must keep state in the trainer's updater"


def _estimator_fit_with_block(block_k, steps=8):
    """Estimator.fit at a given block size, recording what each
    batch_end handler observes from the train metric."""
    os.environ["MXNET_FUSED_STEP_BLOCK"] = str(block_k)
    try:
        np.random.seed(4)
        mx.random.seed(4)
        net = _net_init()
        X, y = _data(n=64)
        loader = gluon.data.DataLoader(
            gluon.data.ArrayDataset(nd.array(X), nd.array(y)),
            batch_size=8, shuffle=False)
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.05})
        metric = mx.metric.Accuracy()
        est = gluon.contrib.estimator.Estimator(
            net, gluon.loss.SoftmaxCrossEntropyLoss(),
            train_metrics=[metric], trainer=trainer)
        seen = []

        class Probe:
            def train_begin(self, est):
                pass

            def epoch_begin(self, est):
                pass

            def batch_begin(self, est):
                pass

            def batch_end(self, est):
                seen.append((est.batch_idx, metric.get()[1]))

            def epoch_end(self, est):
                pass

            def train_end(self, est):
                pass

        est.fit(loader, epochs=1, event_handlers=[Probe()])
        assert est._fused is not None and not est._fused.broken, \
            "Estimator must engage the fused Gluon step"
        return seen
    finally:
        os.environ.pop("MXNET_FUSED_STEP_BLOCK", None)


def test_estimator_block_handlers_fire_per_logical_step():
    """K>1 Estimator blocks: batch-j handlers must observe batch-j
    metric state, matching per-batch dispatch exactly (round-5
    VERDICT/ADVICE K>1 callback semantics)."""
    ref = _estimator_fit_with_block(1)
    blocked = _estimator_fit_with_block(4)
    assert [b for b, _ in ref] == [b for b, _ in blocked]
    for (nb, v1), (_nb2, vk) in zip(ref, blocked):
        np.testing.assert_allclose(vk, v1, rtol=1e-6, atol=1e-7,
                                   err_msg=f"batch {nb}")
    assert len({round(v, 6) for _, v in blocked}) > 1
