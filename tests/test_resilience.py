"""Resilience: deterministic fault injection + retry/failover (ISSUE-5).

Covers: seeded fault schedules bit-for-bit reproducible; `at=`/`n=` firing
controls produce exact trace sequences; RetryPolicy backoff determinism,
deadline and budget exhaustion; CircuitBreaker scripted
open/half-open/close; a slow (not dead) server no longer poisons the
channel (seq-framing regression for the old 330s-timeout desync); push
survives a mid-message connection drop — both frame-torn-on-send and
reply-lost-after-apply (idempotent resend, no double apply) — with values
identical to a no-fault run; a dead server surfaces as a structured
ServerLostError naming server and keys; the overloaded batcher sheds only
requests whose deadlines cannot be met; the serving circuit breaker
opens, fails fast, half-open probes, and closes; execution retries land
in the metrics histogram; unload drain_timeout lists pending request ids;
a torn checkpoint write is never resumed from; and a killed-server
`Module.fit` run auto-resumes from checkpoint to the same final params as
an uninterrupted run.
"""
import os
import time

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, resilience, sym
from incubator_mxnet_tpu.base import MXNetError
from incubator_mxnet_tpu.io import NDArrayIter
from incubator_mxnet_tpu.resilience import (CircuitBreaker, RetryBudget,
                                            RetryPolicy, ServerLostError)


@pytest.fixture(autouse=True)
def _clean_faults():
    resilience.clear()
    yield
    resilience.clear()


@pytest.fixture()
def fast_failover(monkeypatch):
    """Failover diagnosis in well under a second (prod defaults wait
    seconds per reconnect so a GC pause is not declared a death)."""
    monkeypatch.setenv("MXNET_PS_RECONNECT_WAIT", "0.2")
    monkeypatch.setenv("MXNET_PS_MAX_RETRIES", "2")
    monkeypatch.setenv("MXNET_PS_BREAKER_THRESHOLD", "2")


def _dist_env(monkeypatch, port):
    for k, v in {"DMLC_PS_ROOT_URI": "127.0.0.1",
                 "DMLC_PS_ROOT_PORT": str(port), "DMLC_RANK": "0",
                 "DMLC_NUM_WORKER": "1", "DMLC_NUM_SERVER": "1",
                 "MXNET_KVSTORE_COLLECTIVE": "0"}.items():
        monkeypatch.setenv(k, v)


# -- fault injection engine ---------------------------------------------------

def test_seeded_fault_schedule_bit_for_bit_reproducible():
    spec = "seed=42;demo.site:error(p=0.4,n=5)"

    def run():
        resilience.configure(spec)
        fired = []
        for i in range(40):
            try:
                resilience.fire("demo.site", cmd="x")
            except MXNetError:
                fired.append(i)
        return fired, [(e["site"], e["kind"], e["hit"], e["seq"])
                       for e in resilience.trace()]
    first = run()
    second = run()
    assert first == second
    assert first[0], "seeded schedule fired nothing"
    assert len(first[1]) == 5    # n=5 cap respected
    # reset() (same clauses, counters rewound) reproduces it too
    resilience.reset()
    fired = []
    for i in range(40):
        try:
            resilience.fire("demo.site", cmd="x")
        except MXNetError:
            fired.append(i)
    assert fired == first[0]


def test_at_and_count_controls_exact_sequence():
    resilience.inject("a.b", "error", at=3)
    resilience.inject("c.d", "error", n=2)
    log = []
    for i in range(1, 6):
        for site in ("a.b", "c.d"):
            try:
                resilience.fire(site)
            except MXNetError:
                log.append((site, i))
    assert log == [("c.d", 1), ("c.d", 2), ("a.b", 3)]
    tr = resilience.trace()
    assert [(e["site"], e["hit"]) for e in tr] == \
        [("c.d", 1), ("c.d", 2), ("a.b", 3)]


def test_spec_parse_grammar_and_errors():
    from incubator_mxnet_tpu.resilience import faults
    clauses, seed = faults.parse_spec(
        "seed=7;transport.send:drop(at=3,cmd=push);server.dispatch:"
        "slow(ms=50,p=0.1)")
    assert seed == 7
    assert clauses[0] == ("transport.send", "drop",
                          {"at": "3", "cmd": "push"})
    assert clauses[1][1] == "slow"
    with pytest.raises(MXNetError, match="cannot parse"):
        faults.parse_spec("not a clause")
    with pytest.raises(MXNetError, match="unknown fault kind"):
        faults.configure("a.b:explode")


def test_cmd_filter_scopes_the_fault():
    resilience.inject("s.x", "error", cmd="push", at=1)
    resilience.fire("s.x", cmd="pull")       # filtered out, no fire
    with pytest.raises(MXNetError):
        resilience.fire("s.x", cmd="push")
    assert [e["ctx"]["cmd"] for e in resilience.trace()] == ["push"]


# -- retry policy / circuit breaker -------------------------------------------

def test_retry_policy_backoff_deterministic_and_bounded():
    a = RetryPolicy(max_attempts=5, base_delay=0.1, max_delay=0.5,
                    multiplier=2.0, jitter=0.5, seed=9)
    b = RetryPolicy(max_attempts=5, base_delay=0.1, max_delay=0.5,
                    multiplier=2.0, jitter=0.5, seed=9)
    da, db = list(a.delays()), list(b.delays())
    assert da == db and len(da) == 4
    # geometric growth capped at max_delay, jitter never exceeds +50%
    assert 0.1 <= da[0] <= 0.15 and 0.2 <= da[1] <= 0.3
    assert all(d <= 0.5 * 1.5 + 1e-9 for d in da)

    # overall deadline cuts the schedule short
    t = [0.0]
    p = RetryPolicy(max_attempts=10, base_delay=1.0, jitter=0.0,
                    deadline=2.5, clock=lambda: t[0])
    out = []
    for d in p.delays():
        out.append(d)
        t[0] += d
    assert out == [1.0, 2.0]   # at t=3.0 the 2.5s deadline has passed

    # budget exhaustion stops retries across policies sharing it
    budget = RetryBudget(capacity=3, refill_per_s=0.0, clock=lambda: 0.0)
    p = RetryPolicy(max_attempts=10, base_delay=0.0, jitter=0.0,
                    budget=budget)
    assert len(list(p.delays())) == 3
    assert len(list(p.delays())) == 0   # bucket is dry


def test_retry_policy_call_retries_then_raises():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionError("flaky")
        return "ok"
    p = RetryPolicy(max_attempts=4, base_delay=0.0, jitter=0.0)
    seen = []
    assert p.call(flaky, on_retry=lambda a, e: seen.append(a)) == "ok"
    assert calls["n"] == 3 and seen == [1, 2]
    calls["n"] = -100
    with pytest.raises(ConnectionError):
        RetryPolicy(max_attempts=2, base_delay=0.0).call(flaky)


def test_circuit_breaker_scripted_sequence():
    t = [0.0]
    br = CircuitBreaker(failure_threshold=3, reset_timeout=5.0,
                        clock=lambda: t[0])
    assert br.state == "closed" and br.allow()
    br.record_failure()
    br.record_failure()
    assert br.state == "closed"          # 2 < threshold
    br.record_success()                  # consecutive count resets
    br.record_failure()
    br.record_failure()
    assert br.record_failure() is True   # third consecutive: trips
    assert br.state == "open" and not br.allow()
    t[0] += 4.9
    assert not br.allow()                # still inside the open window
    t[0] += 0.2
    assert br.state == "half_open"
    assert br.allow()                    # the one probe
    assert not br.allow()                # everyone else fails fast
    br.record_failure()                  # probe failed -> open again
    assert br.state == "open"
    t[0] += 5.1
    assert br.allow()                    # next probe
    br.record_success()
    assert br.state == "closed" and br.allow()


# -- transport: slow server / mid-message drops -------------------------------

def test_slow_server_no_longer_poisons_the_channel():
    """Regression for the timeout desync: a request that times out against
    a SLOW (not dead) server leaves the channel usable; the late reply is
    discarded by sequence number instead of being misdelivered."""
    from incubator_mxnet_tpu.dist.server import ParameterServer
    from incubator_mxnet_tpu.dist.transport import Channel

    server = ParameterServer(num_workers=1).start()
    resilience.inject("server.dispatch", "slow", ms=400, at=2)
    chan = Channel("127.0.0.1", server.port, timeout=0.15)
    try:
        r = chan.request({"cmd": "init", "keys": ["a"],
                          "values": [np.ones(2, "f4")]})
        assert r.get("ok")
        with pytest.raises(TimeoutError, match="slow or wedged"):
            chan.request({"cmd": "pull", "key": "a"})   # hit 2: 400ms stall
        # the socket was dropped (a mid-frame timeout cannot be told
        # apart from a boundary one); the channel reconnects on the next
        # request and serves the RIGHT replies — no poisoning, no stale
        # delivery
        time.sleep(0.5)   # let the wedged handler finish with the old conn
        r = chan.request({"cmd": "init", "keys": ["b"],
                          "values": [np.full(3, 5, "f4")]})
        assert r.get("ok") and r["seq"] == chan._seq
        r = chan.request({"cmd": "pull", "key": "b"})
        np.testing.assert_array_equal(np.asarray(r["value"]),
                                      np.full(3, 5, "f4"))
    finally:
        chan.close()
        server.shutdown()


def _push_pull_run(monkeypatch, fault=None):
    """One single-worker dist round: 3 pushes then a pull.  Returns the
    pulled values + the server-side version counter."""
    from incubator_mxnet_tpu.dist.server import ParameterServer
    from incubator_mxnet_tpu.dist.kvstore_dist import KVStoreDist

    server = ParameterServer(num_workers=1).start()
    _dist_env(monkeypatch, server.port)
    kv = KVStoreDist("dist_sync")
    try:
        kv.init("w", nd.zeros((4,)))
        if fault is not None:
            resilience.inject(*fault[0], **fault[1])
        for i in range(3):
            kv.push("w", nd.ones((4,)) * (i + 1))
        out = nd.zeros((4,))
        kv.pull("w", out=out)
        values = out.asnumpy().copy()
        version = server._state.version["w"]
        resends = kv._chan.resends
        fault_trace = [e for e in resilience.trace()
                       if e["event"] == "fault"]
    finally:
        resilience.clear()
        kv.close()
        server.shutdown()
    return values, version, resends, fault_trace


def test_push_survives_mid_message_drop_on_send(monkeypatch, fast_failover):
    """The 2nd push's frame is torn mid-send (partial length prefix +
    socket close): the channel reconnects and resends; final values and
    round count are identical to the no-fault run."""
    clean_vals, clean_ver, _, _ = _push_pull_run(monkeypatch)
    vals, ver, resends, faults_fired = _push_pull_run(
        monkeypatch, fault=(("transport.send", "drop"),
                            {"cmd": "push", "at": 2}))
    np.testing.assert_array_equal(vals, clean_vals)
    assert ver == clean_ver
    assert resends >= 1
    # exactly one fault fired, at the declared site, on the push cmd
    assert [(e["site"], e["ctx"]["cmd"]) for e in faults_fired] == \
        [("transport.send", "push")]


def test_push_survives_reply_drop_without_double_apply(monkeypatch,
                                                       fast_failover):
    """The drop lands AFTER the server applied the push (reply lost):
    the resend must hit the server's (client, seq) dedup cache and replay
    the reply — a double-applied push would add a spurious round and
    change both the version counter and the pulled values."""
    clean_vals, clean_ver, _, _ = _push_pull_run(monkeypatch)
    # the clause is injected after init, so recv hits count from push1:
    # at=2 drops the connection while awaiting push2's reply
    vals, ver, resends, _ = _push_pull_run(
        monkeypatch, fault=(("transport.recv", "drop"), {"at": 2}))
    np.testing.assert_array_equal(vals, clean_vals)
    assert ver == clean_ver, "resend double-applied a push round"
    assert resends >= 1


def test_dead_server_raises_structured_server_lost_error(monkeypatch,
                                                         fast_failover):
    from incubator_mxnet_tpu.dist.server import ParameterServer
    from incubator_mxnet_tpu.dist.kvstore_dist import KVStoreDist

    server = ParameterServer(num_workers=1).start()
    _dist_env(monkeypatch, server.port)
    kv = KVStoreDist("dist_sync")
    try:
        kv.init("w", nd.ones((6,)))
        server._simulate_crash()     # listener closed, handlers refuse
        time.sleep(0.1)
        with pytest.raises(ServerLostError) as err:
            kv.push("w", nd.ones((6,)))
        assert err.value.server == 0
        assert "w" in err.value.keys
        assert f"127.0.0.1:{server.port}" in err.value.addr
        # breaker now open: the next call fails fast without wire time
        t0 = time.monotonic()
        with pytest.raises(ServerLostError, match="circuit breaker"):
            kv.push("w", nd.ones((6,)))
        assert time.monotonic() - t0 < 0.1
    finally:
        kv.close()
        server.shutdown()


def test_shadowed_clause_keeps_its_budget():
    """Two clauses matching the same site: the one shadowed on a hit must
    not burn its n= budget — it fires on the next hit instead."""
    resilience.inject("s.t", "error", at=1)
    resilience.inject("s.t", "slow", ms=1, n=1)
    with pytest.raises(MXNetError):
        resilience.fire("s.t")        # hit 1: error wins, slow shadowed
    resilience.fire("s.t")            # hit 2: slow's budget is intact
    assert [e["kind"] for e in resilience.trace()] == ["error", "slow"]


def test_breaker_probe_released_on_pre_execution_rejection():
    """A half-open probe admitted by allow() but rejected before it
    executes must be handed back, not leaked (a leaked probe wedges the
    breaker in half_open forever)."""
    t = [0.0]
    br = CircuitBreaker(failure_threshold=1, reset_timeout=5.0,
                        clock=lambda: t[0])
    br.record_failure()
    t[0] += 5.1
    assert br.allow()        # the probe
    assert not br.allow()    # probe out: everyone else fails fast
    br.release_probe()       # admission-time rejection hands it back
    assert br.allow()        # probe available again
    br.record_success()
    assert br.state == "closed"


def test_resend_last_replays_cached_reply_same_seq(monkeypatch):
    """The failover layer's outer retries resend the SAME frame: the
    server's dedup cache replays the reply instead of re-applying."""
    from incubator_mxnet_tpu.dist.server import ParameterServer
    from incubator_mxnet_tpu.dist.transport import Channel

    server = ParameterServer(num_workers=1).start()
    chan = Channel("127.0.0.1", server.port)
    try:
        chan.request({"cmd": "init", "keys": ["k"],
                      "values": [np.zeros(2, "f4")]})
        r1 = chan.request({"cmd": "push", "key": "k", "sync": True,
                           "rank": 0, "value": np.ones(2, "f4")})
        assert r1["version"] == 1
        r2 = chan.resend_last()
        assert r2.get("duplicate") and r2["version"] == 1
        assert server._state.version["k"] == 1, "resend re-applied the push"
    finally:
        chan.close()
        server.shutdown()


def test_three_server_drop_mid_push_then_permanent_crash(monkeypatch,
                                                         fast_failover):
    """The acceptance schedule on a 3-server run: one kvstore shard push
    is dropped mid-message (recovered transparently, values correct),
    then one server crashes permanently (structured failover: the error
    names the dead server and the keys whose ranges it owned)."""
    from incubator_mxnet_tpu.dist.server import (ParameterServer,
                                                 register_with_root)
    from incubator_mxnet_tpu.dist.kvstore_dist import KVStoreDist

    root = ParameterServer(num_workers=1, num_servers=3).start()
    secondaries = []
    for sid in (1, 2):
        srv = ParameterServer(num_workers=1, num_servers=3, port=0).start()
        register_with_root("127.0.0.1", root.port, sid, "127.0.0.1",
                           srv.port)
        secondaries.append(srv)
    _dist_env(monkeypatch, root.port)
    monkeypatch.setenv("DMLC_NUM_SERVER", "3")
    monkeypatch.setenv("MXNET_KVSTORE_BIGARRAY_BOUND", "16")
    kv = KVStoreDist("dist_sync")
    try:
        assert len(kv._chans) == 3
        big = np.arange(40, dtype="f4")
        kv.init("big", nd.zeros((40,)))
        # a push fans out one shard per server; drop the 2nd shard's send
        # mid-message — the resend must land exactly once
        resilience.inject("transport.send", "drop", cmd="push", at=2)
        kv.push("big", nd.array(big))
        out = nd.zeros((40,))
        kv.pull("big", out=out)
        np.testing.assert_array_equal(out.asnumpy(), big)
        fired = [e for e in resilience.trace() if e["event"] == "fault"]
        assert [(e["site"], e["ctx"]["cmd"]) for e in fired] == \
            [("transport.send", "push")]
        # now server 1 dies for good: the next round trips its breaker
        secondaries[0]._simulate_crash()
        time.sleep(0.1)
        with pytest.raises(ServerLostError) as err:
            kv.push("big", nd.array(big))
            kv.pull("big", out=out)
        assert err.value.server == 1
        assert "big" in err.value.keys
    finally:
        kv.close()
        root.shutdown()
        for srv in secondaries:
            srv.shutdown()


# -- serving: overload controller ---------------------------------------------

def _serving_model(in_dim=6, n_out=3, batch=4, seed=0):
    np.random.seed(seed)
    mx.random.seed(seed)
    net = sym.Variable("data")
    net = sym.FullyConnected(net, num_hidden=8, name="fc0")
    net = sym.FullyConnected(net, num_hidden=n_out, name="head")
    net = sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[mx.io.DataDesc("data", (batch, in_dim))],
             label_shapes=[mx.io.DataDesc("softmax_label", (batch,))],
             for_training=False, grad_req="null")
    mod.init_params(mx.initializer.Xavier())
    args, auxs = mod.get_params()
    return net, args, auxs


def test_overloaded_batcher_sheds_only_past_deadline_requests():
    net, args, auxs = _serving_model()
    with mx.serving.ModelServer(max_queue_latency_ms=0.0) as srv:
        srv.load_model("ovl", symbol=net, arg_params=args, aux_params=auxs,
                       data_shapes=[("data", (1, 6))], buckets=(1, 2, 4))
        batcher = srv.batcher("ovl")
        # prime the controller's estimate: recent batches took 50 ms
        batcher._metrics.record_batch(4, 4, 0.05)
        batcher.pause()
        x = np.zeros((1, 6), np.float32)
        futs = [srv.submit("ovl", {"data": x}) for _ in range(8)]
        # 8 queued 1-row requests, max batch 4, 50ms/batch -> ~150ms wait:
        # a 20ms deadline cannot be met and must be shed BEFORE queueing
        with pytest.raises(MXNetError, match="overloaded.*shed"):
            srv.submit("ovl", {"data": x}, timeout_ms=20)
        # a 10s deadline CAN be met: accepted, not shed
        ok = srv.submit("ovl", {"data": x}, timeout_ms=10000)
        batcher.resume()
        for f in futs + [ok]:
            assert len(f.result(30)) == 1
        snap = srv.stats()["ovl"]
        assert snap["shed"] == 1
        assert snap["responses"] == 9


def test_serving_circuit_breaker_opens_half_opens_closes():
    net, args, auxs = _serving_model()
    with mx.serving.ModelServer(max_queue_latency_ms=0.0) as srv:
        srv.load_model("brk", symbol=net, arg_params=args, aux_params=auxs,
                       data_shapes=[("data", (1, 6))], buckets=(1, 2),
                       breaker_threshold=2, breaker_reset_s=0.25)
        x = np.zeros((1, 6), np.float32)
        resilience.inject("serving.execute", "error", n=2)
        for _ in range(2):   # two consecutive failed batches trip it
            with pytest.raises(MXNetError, match="fault-injected"):
                srv.predict("brk", {"data": x})
        with pytest.raises(MXNetError, match="circuit breaker is open"):
            srv.submit("brk", {"data": x})
        snap = srv.stats()["brk"]
        assert snap["breaker_state"] == "open"
        assert snap["breaker_rejects"] == 1
        time.sleep(0.3)      # open window elapses -> half-open probe
        assert len(srv.predict("brk", {"data": x})) == 1
        assert srv.stats()["brk"]["breaker_state"] == "closed"
        assert len(resilience.trace()) == 2


def test_serving_execution_retries_land_in_histogram():
    net, args, auxs = _serving_model()
    with mx.serving.ModelServer(max_queue_latency_ms=0.0) as srv:
        srv.load_model("rty", symbol=net, arg_params=args, aux_params=auxs,
                       data_shapes=[("data", (1, 6))], buckets=(1, 2),
                       retry_policy=RetryPolicy(max_attempts=3,
                                                base_delay=0.01,
                                                jitter=0.0))
        resilience.inject("serving.execute", "error", n=2)
        x = np.zeros((1, 6), np.float32)
        out = srv.predict("rty", {"data": x})   # fails twice, 3rd succeeds
        assert len(out) == 1
        snap = srv.stats()["rty"]
        assert snap["retry_histogram"] == {1: 1, 2: 1}
        assert snap["breaker_state"] == "closed"
        assert snap["responses"] == 1


def test_unload_drain_timeout_lists_pending_request_ids():
    net, args, auxs = _serving_model()
    srv = mx.serving.ModelServer(max_queue_latency_ms=0.0)
    try:
        srv.load_model("wdg", symbol=net, arg_params=args, aux_params=auxs,
                       data_shapes=[("data", (1, 6))], buckets=(1, 2))
        # wedge the worker: the first batch stalls 1s inside execution
        resilience.inject("serving.execute", "slow", ms=1000, at=1)
        x = np.zeros((1, 6), np.float32)
        f1 = srv.submit("wdg", {"data": x})
        f2 = srv.submit("wdg", {"data": x})
        assert f1.request_id == "wdg-1" and f2.request_id == "wdg-2"
        with pytest.raises(MXNetError, match=r"drain timed out .* "
                                             r"pending: wdg-"):
            srv.unload_model("wdg", drain_timeout=0.2)
        assert "wdg" not in srv.models()   # unloaded despite the wedge
    finally:
        srv.shutdown(drain=False)


# -- checkpoint: torn writes --------------------------------------------------

def test_torn_checkpoint_write_is_never_resumed_from(tmp_path):
    from incubator_mxnet_tpu import checkpoint as ckpt

    resilience.inject("checkpoint.commit", "torn", at=2)
    mgr = ckpt.CheckpointManager(str(tmp_path), async_snapshots=False)
    for step in (1, 2):
        mgr.snapshot(arrays={"w": np.full((4,), step, "f4")}, step=step,
                     sync=True)
    # step 2's write tore (directory landed without a manifest) and the
    # run NOTICED NOTHING — exactly a killed writer's disk state
    assert os.path.isdir(os.path.join(tmp_path, "ckpt-0000000002"))
    assert ckpt.latest(str(tmp_path)).endswith("ckpt-0000000001")
    mgr.snapshot(arrays={"w": np.full((4,), 3, "f4")}, step=3, sync=True)
    mgr.close()
    data = ckpt.load(ckpt.latest(str(tmp_path)))
    assert data.step == 3
    np.testing.assert_array_equal(data.arrays["w"], np.full((4,), 3, "f4"))
    assert [e["kind"] for e in resilience.trace()] == ["torn"]


# -- end to end: killed-server training auto-resume ---------------------------

def _mlp():
    d = sym.Variable("data")
    f1 = sym.FullyConnected(d, num_hidden=8, name="fc1")
    a1 = sym.Activation(f1, act_type="relu")
    f2 = sym.FullyConnected(a1, num_hidden=4, name="fc2")
    return sym.SoftmaxOutput(f2, name="softmax")


def _fit_dist(port, ckpt_dir=None, kill_at=None, num_epoch=2):
    """One single-worker dist_sync training run against the server on
    `port`.  With `kill_at`, the server is crashed at that batch-end and
    a replacement (EMPTY) server is started on the same port — fit must
    diagnose ServerLostError and auto-resume from the checkpoint."""
    from incubator_mxnet_tpu.dist.server import ParameterServer

    mx.random.seed(11)
    np.random.seed(11)
    X = np.random.RandomState(2).randn(48, 10).astype("f4")
    y = (np.arange(48) % 4).astype("f4")
    it = NDArrayIter(X, y, batch_size=8, shuffle=False)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())

    replacement = []
    cb = None
    if kill_at is not None:
        hits = {"n": 0}

        def cb(param):
            hits["n"] += 1
            if hits["n"] == kill_at:
                _fit_dist.server._simulate_crash()
                for _ in range(200):   # rebind as soon as the port frees
                    try:
                        srv = ParameterServer(host="127.0.0.1", port=port,
                                              num_workers=1)
                        break
                    except OSError:
                        time.sleep(0.05)
                replacement.append(srv.start())
    mod.fit(it, kvstore="dist_sync", optimizer="sgd",
            optimizer_params={"learning_rate": 0.1}, num_epoch=num_epoch,
            checkpoint_dir=ckpt_dir, checkpoint_period=1,
            batch_end_callback=cb)
    args, auxs = mod.get_params()
    params = {k: v.asnumpy().copy() for k, v in args.items()}
    kv = getattr(mod, "_kvstore", None)
    if kv is not None:
        kv.close()
    return params, replacement


def test_killed_server_fit_auto_resumes_bit_identical(monkeypatch,
                                                      tmp_path,
                                                      fast_failover):
    """The acceptance gate: crash the parameter server mid-epoch (its
    replacement comes back EMPTY on the same address), and
    Module.fit(checkpoint_dir=...) restarts from the last checkpoint —
    final params bit-identical to an uninterrupted run."""
    from incubator_mxnet_tpu.dist.server import ParameterServer

    clean_server = ParameterServer(num_workers=1).start()
    _dist_env(monkeypatch, clean_server.port)
    clean_params, _ = _fit_dist(clean_server.port)
    clean_server.shutdown()

    server = ParameterServer(num_workers=1).start()
    _dist_env(monkeypatch, server.port)
    _fit_dist.server = server
    faulted_params, replacement = _fit_dist(
        server.port, ckpt_dir=str(tmp_path / "ckpts"), kill_at=7)
    assert replacement, "the kill callback never ran"
    assert sorted(faulted_params) == sorted(clean_params)
    for k in clean_params:
        np.testing.assert_array_equal(faulted_params[k], clean_params[k],
                                      err_msg=f"param {k} diverged")
    for srv in replacement:
        srv.shutdown()
    server.shutdown()


def test_fit_without_checkpoint_dir_still_dies_on_server_loss(monkeypatch,
                                                              fast_failover):
    """No checkpoint, no silent restart: ServerLostError propagates."""
    from incubator_mxnet_tpu.dist.server import ParameterServer

    server = ParameterServer(num_workers=1).start()
    _dist_env(monkeypatch, server.port)
    mx.random.seed(3)
    np.random.seed(3)
    X = np.random.randn(16, 10).astype("f4")
    y = (np.arange(16) % 4).astype("f4")
    it = NDArrayIter(X, y, batch_size=8, shuffle=False)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())

    def cb(param):
        server._simulate_crash()
    with pytest.raises(ServerLostError):
        mod.fit(it, kvstore="dist_sync", optimizer="sgd", num_epoch=2,
                batch_end_callback=cb)
    kv = getattr(mod, "_kvstore", None)
    if kv is not None:
        kv.close()
    server.shutdown()


def test_no_faults_means_zero_schedule_and_clean_trace():
    """With no schedule configured the hot-path gate stays off and the
    trace stays empty — the MXNET_FAULTS-unset contract."""
    from incubator_mxnet_tpu.resilience import faults
    resilience.clear()
    for _ in range(100):
        resilience.fire("transport.send", cmd="push")
    assert resilience.trace() == []
    assert faults.ACTIVE is False
