"""Continuous-batching decode engine: admission/eviction lifecycle,
priority ordering, the zero-steady-state-recompile contract, kill
semantics, hot weight swap, and router failover over `DecodeReplica`s."""
import time

import numpy as np
import pytest

from concurrent.futures import wait as _wait

from incubator_mxnet_tpu import analysis
from incubator_mxnet_tpu.base import MXNetError
from incubator_mxnet_tpu.llm import LMConfig
from incubator_mxnet_tpu.serving import (DecodeEngine, DecodeReplica,
                                         ReplicaLostError, ReplicaRouter)

BUCKETS = (4, 8)


def _cfg():
    return LMConfig(vocab_size=32, num_layers=2, num_heads=2, hidden=8,
                    ffn_mult=2, max_len=24, eos_id=0)


def _params(cfg, seed=0):
    """Random parameters under the llm.model naming scheme (the decode
    plane only needs names + shapes, not trained weights)."""
    rng = np.random.default_rng(seed)
    c, f = cfg.hidden, cfg.hidden * cfg.ffn_mult
    mk = lambda *s: rng.standard_normal(s).astype(np.float32) * 0.1  # noqa: E731
    p = {"lm_embed_weight": mk(cfg.vocab_size, c),
         "lm_final_ln_gamma": np.ones((c,), np.float32),
         "lm_final_ln_beta": np.zeros((c,), np.float32)}
    for i in range(cfg.num_layers):
        pre = "lm_block%d_" % i
        p[pre + "ln1_gamma"] = np.ones((c,), np.float32)
        p[pre + "ln1_beta"] = np.zeros((c,), np.float32)
        p[pre + "qkv_weight"] = mk(3 * c, c)
        p[pre + "qkv_bias"] = np.zeros((3 * c,), np.float32)
        p[pre + "out_proj_weight"] = mk(c, c)
        p[pre + "out_proj_bias"] = np.zeros((c,), np.float32)
        p[pre + "ln2_gamma"] = np.ones((c,), np.float32)
        p[pre + "ln2_beta"] = np.zeros((c,), np.float32)
        p[pre + "fc1_weight"] = mk(f, c)
        p[pre + "fc1_bias"] = np.zeros((f,), np.float32)
        p[pre + "fc2_weight"] = mk(c, f)
        p[pre + "fc2_bias"] = np.zeros((c,), np.float32)
    return p


def _engine(**kw):
    cfg = _cfg()
    kw.setdefault("slots", 4)
    kw.setdefault("buckets", BUCKETS)
    return cfg, DecodeEngine(cfg, _params(cfg), **kw)


def test_submit_resolves_generated_continuations():
    cfg, eng = _engine()
    try:
        futs = [eng.submit([1 + (i % 5), 2, 3], max_new_tokens=4,
                           rid="r%d" % i) for i in range(6)]
        done, not_done = _wait(futs, timeout=60.0)
        assert not not_done
        for i, f in enumerate(futs):
            out = f.result(0)
            assert out["rid"] == "r%d" % i
            assert 1 <= len(out["tokens"]) <= 4
            assert all(0 <= t < cfg.vocab_size for t in out["tokens"])
        st = eng.stats()
        assert st["admitted"] == st["evicted"] == 6
        assert sorted(st["executed_rids"]) == sorted(
            "r%d" % i for i in range(6))
    finally:
        eng.close(drain=False)


def test_ladder_reject_is_failed_future_not_engine_death():
    cfg, eng = _engine()
    try:
        too_long = eng.submit(list(range(1, 12)))   # > largest bucket
        with pytest.raises(MXNetError):
            too_long.result(5.0)
        no_room = eng.submit([1, 2], max_new_tokens=cfg.max_len)
        with pytest.raises(MXNetError):
            no_room.result(5.0)
        assert eng.stats()["rejected"] == 2
        ok = eng.submit([1, 2, 3], max_new_tokens=2)
        assert len(ok.result(30.0)["tokens"]) <= 2
    finally:
        eng.close(drain=False)


def test_priority_classes_order_the_queue():
    _, eng = _engine(start=False)   # no worker: inspect raw queue order
    eng.submit([1], 2, priority="best_effort", rid="be")
    eng.submit([1], 2, priority="batch", rid="b1")
    eng.submit([1], 2, priority="interactive", rid="i1")
    eng.submit([1], 2, priority="batch", rid="b2")
    eng.submit([1], 2, priority=0, rid="i2")   # router-style rank int
    assert [p.rid for p in eng._queue] == ["i1", "i2", "b1", "b2", "be"]


def test_zero_steady_state_recompiles():
    """Warmup compiles one prefill per bucket + one step; an arbitrary
    interleaving of prompt lengths afterwards adds ZERO compiles and
    ZERO recompile-auditor findings."""
    analysis.recompile.reset()
    cfg, eng = _engine()
    try:
        after_warmup = eng.programs.compile_count()
        assert eng.programs.program_count() == len(BUCKETS) + 1
        futs = [eng.submit([1 + (i % 7)] * (1 + (i * 3) % 8),
                           max_new_tokens=1 + (i % 6))
                for i in range(10)]
        done, not_done = _wait(futs, timeout=60.0)
        assert not not_done
        assert eng.programs.compile_count() == after_warmup
        assert eng.programs.program_count() == len(BUCKETS) + 1
        key = "decode:%s" % eng.name
        assert not [f for f in analysis.recompile.findings()
                    if f["key"] == key]
    finally:
        eng.close(drain=False)


def test_kill_fails_queued_and_inflight_with_replica_lost():
    _, eng = _engine(slots=2, admit_per_tick=1)
    futs = [eng.submit([1, 2], max_new_tokens=20, rid="k%d" % i)
            for i in range(6)]
    while eng.stats()["slots_active"] == 0:   # wait until decode started
        time.sleep(0.005)
    eng.kill()
    lost = 0
    for f in futs:
        try:
            f.result(10.0)
        except ReplicaLostError:
            lost += 1
    assert lost >= 1          # at least the in-flight slots died loudly
    assert eng.stats()["dead"]
    with pytest.raises(ReplicaLostError):
        eng.submit([1], max_new_tokens=2)


def test_replica_swap_is_zero_compile_and_bumps_version():
    cfg = _cfg()
    rep = DecodeReplica(cfg, _params(cfg), replica_id="swap0",
                        slots=2, buckets=BUCKETS)
    try:
        before = rep.engine.programs.compile_count()
        assert rep.probe()["tokens"]
        assert rep.swap(arg_params=_params(cfg, seed=7)) == 1
        assert rep.probe()["tokens"]   # serves on the new weights
        assert rep.engine.programs.compile_count() == before
        assert rep.stats()["version"] == 1
    finally:
        rep.close(drain=False)


def test_router_failover_replays_decode_on_survivor():
    """SIGKILL a decode replica mid-traffic: every admitted sequence is
    replayed on the survivor (prefill re-derives the lost KV state) and
    the completed-rid fence suppresses duplicate delivery."""
    cfg = _cfg()
    reps = [DecodeReplica(cfg, _params(cfg), replica_id="d%d" % i,
                          slots=2, buckets=BUCKETS) for i in range(2)]
    router = ReplicaRouter(reps, name="decode-rt",
                           health_interval_s=0.05, max_dispatches=4)
    try:
        futs = [router.submit({"tokens": [1 + (i % 5), 2],
                               "max_new_tokens": 6},
                              request_id="fo%d" % i, timeout_ms=60000)
                for i in range(12)]
        while reps[0].engine.stats()["slots_active"] == 0 \
                and not all(f.done() for f in futs):
            time.sleep(0.005)
        reps[0].kill()
        done, not_done = _wait(futs, timeout=60.0)
        assert not not_done
        outs = [f.result(0) for f in futs]
        assert len(outs) == 12 and all(o["tokens"] for o in outs)
        st = router.stats()
        assert st["replicas_lost"] >= 1
        # zero loss: every rid landed exactly once across the fleet
        executed = [r for rep in reps
                    for r in rep.engine.stats()["executed_rids"]]
        assert set("fo%d" % i for i in range(12)) <= set(executed)
    finally:
        router.shutdown(drain=False)


def test_load_signals_feed_the_autoscaler_contract():
    _, eng = _engine(start=False, slots=2)
    assert eng.outstanding() == 0
    assert eng.estimated_wait_s() == 0.0
    eng.submit([1, 2], 2, rid="w0")
    eng.submit([1, 2], 2, rid="w1")
    assert eng.outstanding() == 2
    eng._tick_s_ewma = 0.01    # pretend we have a measured tick rate
    assert eng.estimated_wait_s() > 0.0
