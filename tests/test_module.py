"""Module API tests (reference tests/python/unittest/test_module.py and
tests/python/train/test_mlp.py — the Module.fit e2e gate)."""
import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, sym
from incubator_mxnet_tpu.io import NDArrayIter
from incubator_mxnet_tpu.test_utils import get_mnist_like


def _lenet():
    data = sym.Variable("data")
    c1 = sym.Convolution(data, kernel=(5, 5), num_filter=8, name="conv1")
    a1 = sym.Activation(c1, act_type="tanh")
    p1 = sym.Pooling(a1, pool_type="max", kernel=(2, 2), stride=(2, 2))
    c2 = sym.Convolution(p1, kernel=(5, 5), num_filter=16, name="conv2")
    a2 = sym.Activation(c2, act_type="tanh")
    p2 = sym.Pooling(a2, pool_type="max", kernel=(2, 2), stride=(2, 2))
    fl = sym.Flatten(p2)
    f1 = sym.FullyConnected(fl, num_hidden=64, name="fc1")
    a3 = sym.Activation(f1, act_type="tanh")
    f2 = sym.FullyConnected(a3, num_hidden=10, name="fc2")
    return sym.SoftmaxOutput(f2, name="softmax")


def _mlp():
    data = sym.Variable("data")
    f1 = sym.FullyConnected(data, num_hidden=64, name="fc1")
    a1 = sym.Activation(f1, act_type="relu")
    f2 = sym.FullyConnected(a1, num_hidden=10, name="fc2")
    return sym.SoftmaxOutput(f2, name="softmax")


def test_module_fit_mnist_like():
    """Gate #1: LeNet-style training via mx.mod.Module reaches high accuracy
    on the synthetic MNIST stand-in (reference train_mnist.py contract)."""
    X, y = get_mnist_like(512)
    train = NDArrayIter(X, y, batch_size=64, shuffle=True)
    val = NDArrayIter(X, y, batch_size=64)
    mod = mx.mod.Module(_lenet(), context=mx.cpu())
    mod.fit(train, eval_data=val, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1},
            initializer=mx.initializer.Xavier(),
            num_epoch=5, batch_end_callback=None)
    score = mod.score(val, "acc")
    assert score[0][1] > 0.95, score


def test_module_basic_api():
    net = _mlp()
    mod = mx.mod.Module(net, context=mx.cpu())
    assert mod.data_names == ["data"]
    assert set(mod._param_names) == {"fc1_weight", "fc1_bias", "fc2_weight",
                                     "fc2_bias"}
    mod.bind(data_shapes=[("data", (8, 20))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.01})
    from incubator_mxnet_tpu.io import DataBatch
    batch = DataBatch(data=[nd.random.uniform(shape=(8, 20))],
                      label=[nd.array(np.arange(8) % 10)])
    mod.forward(batch, is_train=True)
    outs = mod.get_outputs()
    assert outs[0].shape == (8, 10)
    mod.backward()
    mod.update()
    arg_params, aux_params = mod.get_params()
    assert "fc1_weight" in arg_params


def test_module_save_load_checkpoint(tmp_path):
    net = _mlp()
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 20))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params()
    prefix = str(tmp_path / "mlp")
    mod.save_checkpoint(prefix, 3)
    mod2 = mx.mod.Module.load(prefix, 3, context=mx.cpu())
    mod2.bind(data_shapes=[("data", (4, 20))],
              label_shapes=[("softmax_label", (4,))])
    mod2.init_params()
    a1, _ = mod.get_params()
    a2, _ = mod2.get_params()
    for k in a1:
        np.testing.assert_allclose(a1[k].asnumpy(), a2[k].asnumpy(), rtol=1e-6)


def test_module_multi_device_data_parallel():
    """Reference test_multi_device_exec.py analogue on the virtual mesh."""
    import jax
    if len(jax.devices()) < 2:
        return
    X, y = get_mnist_like(256)
    X = X.reshape(256, -1)
    train = NDArrayIter(X, y, batch_size=64, shuffle=True)
    contexts = [mx.tpu(0), mx.tpu(1)]
    mod = mx.mod.Module(_mlp(), context=contexts)
    mod.fit(train, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1},
            initializer=mx.initializer.Xavier(),
            num_epoch=6, kvstore="device")
    score = mod.score(NDArrayIter(X, y, batch_size=64), "acc")
    assert score[0][1] > 0.9, score


def test_module_predict():
    net = _mlp()
    mod = mx.mod.Module(net, context=mx.cpu())
    X = np.random.rand(32, 20).astype("f4")
    it = NDArrayIter(X, np.zeros(32, "f4"), batch_size=8)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    out = mod.predict(it)
    assert out.shape == (32, 10)
    np.testing.assert_allclose(out.asnumpy().sum(1), 1.0, rtol=1e-5)


def test_bucketing_module():
    """Reference test_bucketing.py pattern: per-length graphs share params."""
    def sym_gen(seq_len):
        data = sym.Variable("data")
        label = sym.Variable("softmax_label")
        f = sym.FullyConnected(data, num_hidden=16, name="fc_shared",
                               flatten=False)
        f = sym.Reshape(sym.mean(f, axis=1), shape=(-1, 16))
        out = sym.FullyConnected(f, num_hidden=4, name="out_shared")
        return sym.SoftmaxOutput(out, label, name="softmax"), ("data",), \
            ("softmax_label",)

    from incubator_mxnet_tpu.io import DataBatch, DataDesc
    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=8,
                                 context=mx.cpu())
    mod.bind(data_shapes=[DataDesc("data", (4, 8, 12))],
             label_shapes=[DataDesc("softmax_label", (4,))])
    mod.init_params()
    mod.init_optimizer(optimizer="sgd")
    for key in (8, 4, 8, 12):
        batch = DataBatch(
            data=[nd.random.uniform(shape=(4, key, 12))],
            label=[nd.array(np.arange(4) % 4)],
            bucket_key=key,
            provide_data=[DataDesc("data", (4, key, 12))],
            provide_label=[DataDesc("softmax_label", (4,))])
        mod.forward_backward(batch)
        mod.update()
    assert set(mod._buckets) == {4, 8, 12}


def test_python_loss_module():
    """PythonLossModule (reference module/python_module.py): forward keeps
    scores, backward calls grad_func; chains after a symbol Module via
    SequentialModule-style manual wiring."""
    import numpy as np
    import incubator_mxnet_tpu as mx

    def grad_func(scores, labels):
        # d/ds of 0.5*(s - onehot)^2 = s - onehot
        s = scores.asnumpy()
        lab = labels.asnumpy().astype(int)
        one = np.zeros_like(s)
        one[np.arange(len(lab)), lab] = 1.0
        return s - one

    m = mx.mod.PythonLossModule(grad_func=grad_func)
    m.bind(data_shapes=[mx.io.DataDesc("data", (4, 3))],
           label_shapes=[mx.io.DataDesc("softmax_label", (4,))])
    m.init_params()
    m.init_optimizer()
    assert m.output_shapes == [("pyloss_output", (4, 3))]
    rng = np.random.RandomState(0)
    scores = mx.nd.array(rng.rand(4, 3).astype("f4"))
    labels = mx.nd.array(np.array([0, 1, 2, 1], "f4"))
    batch = mx.io.DataBatch(data=[scores], label=[labels])
    m.forward(batch)
    np.testing.assert_allclose(m.get_outputs()[0].asnumpy(),
                               scores.asnumpy())
    m.backward()
    g = m.get_input_grads()[0].asnumpy()
    np.testing.assert_allclose(g, grad_func(scores, labels), rtol=1e-6)
