"""Storage-manager tests (reference tests for storage.cc pooling)."""
import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.storage import (HostStagingPool, default_pool,
                                         memory_stats, device_memory_info)


def test_pool_recycles_buffers():
    pool = HostStagingPool()
    a = pool.acquire((16, 3, 32, 32), "float32")
    assert a.shape == (16, 3, 32, 32) and a.dtype == np.float32
    a[:] = 1.5
    assert pool.release(a)
    b = pool.acquire((16, 3, 32, 32), "float32")
    s = pool.stats()
    assert s["hits"] == 1 and s["misses"] == 1
    # different shape, same size class also reuses
    assert pool.release(b)
    c = pool.acquire((3, 16, 32, 32), "float32")
    assert pool.stats()["hits"] == 2


def test_pool_size_classes_and_bound():
    pool = HostStagingPool(max_bytes=1 << 16)
    small = pool.acquire((10,), "float32")
    assert pool.release(small)
    big = pool.acquire((1 << 16,), "float32")   # 256 KiB > bound
    assert not pool.release(big)                # pool refuses, gc takes it
    assert pool.stats()["held_bytes"] <= 1 << 16
    # foreign arrays are refused, not corrupted
    assert not pool.release(np.zeros((4, 4), "float64"))


def test_record_iter_zero_copy_batches(tmp_path):
    """The iterator hands each batch buffer to jax ZERO-COPY (cpu targets
    alias the freshly-built numpy buffer; it is never recycled), replacing
    the earlier pool-copy design whose memcpy dominated batch assembly."""
    import cv2
    from incubator_mxnet_tpu import recordio
    from incubator_mxnet_tpu.image import ImageRecordIterImpl
    rng = np.random.RandomState(0)
    rec = recordio.MXRecordIO(str(tmp_path / "p.rec"), "w")
    for i in range(20):
        ok, enc = cv2.imencode(".png", rng.randint(0, 255, (32, 32, 3),
                                                   np.uint8))
        rec.write(recordio.pack(recordio.IRHeader(0, float(i), i, 0),
                                enc.tobytes()))
    rec.close()
    it = ImageRecordIterImpl(path_imgrec=str(tmp_path / "p.rec"),
                             data_shape=(3, 32, 32), batch_size=5,
                             preprocess_threads=1)
    batches = list(it)
    assert sum(b.data[0].shape[0] for b in batches) == 20
    # every batch owns distinct device data (no recycled buffer aliasing)
    datas = [b.data[0].asnumpy() for b in batches]
    assert len({d.ctypes.data for d in datas}) == len(datas)


def test_memory_stats_shapes():
    stats = memory_stats(mx.cpu())
    assert isinstance(stats, dict)
    free, total = device_memory_info(mx.cpu())
    assert free <= total


def test_pool_double_release_guard():
    pool = HostStagingPool()
    a = pool.acquire((64,), "float32")
    assert pool.release(a)
    assert not pool.release(a)          # second release refused
    b = pool.acquire((64,), "float32")
    c = pool.acquire((64,), "float32")
    # b and c must not alias
    b[:] = 1.0
    c[:] = 2.0
    assert b[0] == 1.0 and c[0] == 2.0
