"""Failure-propagation semantics (reference exception_handling docs +
tests/python/unittest/test_exc_handling.py): errors surface at wait
points, failed ops don't poison subsequent work."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, autograd


def test_bad_shapes_raise_promptly():
    a = nd.ones((2, 3))
    w = nd.ones((4, 5))
    with pytest.raises(Exception):
        out = nd.FullyConnected(a, w, nd.zeros((4,)), num_hidden=4)
        out.asnumpy()          # wait point at the latest


def test_unknown_op_and_param_errors_name_the_problem():
    with pytest.raises(mx.MXNetError, match="not registered"):
        nd.imperative_invoke("NoSuchOperator", nd.ones((2,)))
    with pytest.raises(mx.MXNetError, match="bogus"):
        nd.FullyConnected(nd.ones((2, 3)), num_hidden=4, bogus=1)


def test_engine_recovers_after_failure():
    """A failed op must not wedge the engine: subsequent work succeeds
    (the reference's exception-propagation guarantee)."""
    a = nd.ones((2, 3))
    with pytest.raises(Exception):
        nd.dot(a, nd.ones((7, 2))).asnumpy()
    # engine still serves new work
    out = nd.dot(a, nd.ones((3, 2)))
    mx.engine.waitall()
    np.testing.assert_allclose(out.asnumpy(), 3.0)


def test_failure_inside_record_scope_keeps_autograd_usable():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with pytest.raises(Exception):
        with autograd.record():
            y = nd.dot(x.reshape((1, 2)), x.reshape((1, 2)))  # bad shapes
            y.backward()
    with autograd.record():
        z = (x * x).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 2 * x.asnumpy())


def test_symbolic_bind_failure_names_op():
    data = mx.sym.Variable("data")
    out = mx.sym.Reshape(data, shape=(7, 9))   # infeasible for input below
    with pytest.raises(mx.MXNetError):
        exe = out.simple_bind(ctx=mx.cpu(), data=(2, 3))
        exe.forward(data=nd.ones((2, 3)))
