"""Fused tape backward: `loss.backward()` compiles the whole reverse walk
into ONE jitted program per tape structure (reference counterpart: the
per-op `RunGraph` backward, `src/imperative/imperative.cc:270`, which is
cheap per-dispatch on GPU but a host round trip per op on TPU)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, nd


def _grads_with_env(flag, monkeypatch, seed=3):
    monkeypatch.setenv("MXNET_FUSED_BACKWARD", flag)
    rng = np.random.RandomState(seed)
    x = nd.array(rng.randn(4, 5).astype("f4"))
    w1 = nd.array(rng.randn(5, 6).astype("f4"))
    w2 = nd.array(rng.randn(6, 3).astype("f4"))
    for v in (x, w1, w2):
        v.attach_grad()
    with autograd.record():
        h = nd.dot(x, w1)
        h = nd.Activation(h, act_type="relu")
        y = nd.dot(h, w2)
        loss = nd.sum(y * y)
    loss.backward()
    return [v.grad.asnumpy() for v in (x, w1, w2)]


def test_fused_backward_matches_eager_walk(monkeypatch):
    fused = _grads_with_env("1", monkeypatch)
    eager = _grads_with_env("0", monkeypatch)
    for f, e in zip(fused, eager):
        np.testing.assert_allclose(f, e, rtol=1e-5, atol=1e-6)


def test_fused_backward_caches_by_structure(monkeypatch):
    monkeypatch.setenv("MXNET_FUSED_BACKWARD", "1")
    autograd._FUSED_BWD_CACHE.clear()
    for _ in range(3):   # same structure, different values
        _grads_with_env("1", monkeypatch)
    assert len(autograd._FUSED_BWD_CACHE) == 1, \
        "repeat steps with one tape structure must reuse ONE compiled program"
    # a different structure compiles a second program
    x = nd.array(np.ones((2, 2), "f4"))
    x.attach_grad()
    with autograd.record():
        y = nd.sum(x * 3.0)
    y.backward()
    assert len(autograd._FUSED_BWD_CACHE) == 2


def test_fused_backward_gluon_trainer_step(monkeypatch):
    """Whole Gluon train step parity: fused backward vs per-op walk."""
    from incubator_mxnet_tpu import gluon

    init_rng = np.random.RandomState(5)
    init = [init_rng.randn(16, 10) * 0.2, np.zeros(16),
            init_rng.randn(4, 16) * 0.2, np.zeros(4)]

    def run(flag):
        monkeypatch.setenv("MXNET_FUSED_BACKWARD", flag)
        net = gluon.nn.Sequential()
        net.add(gluon.nn.Dense(16, activation="relu"))
        net.add(gluon.nn.Dense(4))
        net.initialize(mx.initializer.Xavier())
        net(nd.array(np.zeros((8, 10), "f4")))  # shape-infer params
        for p, v in zip(net.collect_params().values(), init):
            p.set_data(nd.array(v.astype("f4")))
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1, "momentum": 0.9})
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
        rng = np.random.RandomState(11)
        data = nd.array(rng.randn(8, 10).astype("f4"))
        label = nd.array(rng.randint(0, 4, 8).astype("f4"))
        for _ in range(3):
            with autograd.record():
                out = net(data)
                loss = loss_fn(out, label)
            loss.backward()
            trainer.step(8)
        return [v.data().asnumpy() for v in net.collect_params().values()]

    fused = run("1")
    eager = run("0")
    assert len(fused) == len(eager)
    for i, (f, e) in enumerate(zip(fused, eager)):
        np.testing.assert_allclose(f, e, rtol=1e-5, atol=1e-6,
                                   err_msg=f"param {i}")


def test_fused_backward_custom_function_falls_back(monkeypatch):
    """Tapes containing a user autograd.Function keep the eager walk."""
    monkeypatch.setenv("MXNET_FUSED_BACKWARD", "1")

    class Square(autograd.Function):
        def forward(self, x):
            self.save_for_backward(x)
            return x * x

        def backward(self, dy):
            (x,) = self.saved_tensors
            return 2 * x * dy

    x = nd.array(np.arange(4, dtype="f4"))
    x.attach_grad()
    with autograd.record():
        y = Square()(x)
        z = nd.sum(y)
    z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(),
                               2 * np.arange(4, dtype="f4"))


def test_fused_backward_grad_api(monkeypatch):
    monkeypatch.setenv("MXNET_FUSED_BACKWARD", "1")
    x = nd.array(np.array([1.0, 2.0, 3.0], "f4"))
    x.attach_grad()
    with autograd.record():
        y = nd.sum(x * x)
    (g,) = autograd.grad([y], [x])
    np.testing.assert_allclose(g.asnumpy(), [2.0, 4.0, 6.0])
