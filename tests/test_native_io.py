"""Native IO kernel tests: C++ results must match the numpy fallback
bit-for-bit, and the threaded record iterator must deliver every sample."""
import os

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import native, recordio
from incubator_mxnet_tpu.image import (ImageRecordIterImpl, _index_records,
                                       _record_payload)


def _write_corpus(path, n=64, size=64):
    import cv2
    rng = np.random.RandomState(0)
    rec = recordio.MXRecordIO(str(path), "w")
    for i in range(n):
        img = rng.randint(0, 255, (size, size, 3), dtype=np.uint8)
        ok, enc = cv2.imencode(".png", img)   # lossless: exact comparisons
        assert ok
        rec.write(recordio.pack(recordio.IRHeader(0, float(i), i, 0),
                                enc.tobytes()))
    rec.close()


def test_native_index_matches_python(tmp_path):
    rec = tmp_path / "x.rec"
    _write_corpus(rec, n=17)
    buf = rec.read_bytes()
    got = _index_records(buf)
    assert len(got) == 17
    # cross-check against the sequential reader
    r = recordio.MXRecordIO(str(rec), "r")
    for segs in got:
        assert r.read() == _record_payload(buf, segs)


def test_multipart_records_roundtrip(tmp_path):
    """Payloads containing the magic word are split by the writer (cflag
    1/2/3) and must reassemble byte-exactly through every read path."""
    import struct
    magic = struct.pack("<I", 0xced7230a)
    payloads = [
        b"plain record",
        b"head" + magic + b"tail",                 # one split
        magic + b"starts with magic",              # empty first part
        b"ends with magic" + magic,                # empty last part
        b"a" + magic + b"b" + magic + b"c",        # two splits
    ]
    rec = tmp_path / "m.rec"
    w = recordio.MXRecordIO(str(rec), "w")
    for p in payloads:
        w.write(p)
    w.close()
    # sequential reader reassembles
    r = recordio.MXRecordIO(str(rec), "r")
    for p in payloads:
        assert r.read() == p
    assert r.read() is None
    r.close()
    # index scan (native + fallback) groups parts into logical records
    buf = rec.read_bytes()
    got = _index_records(buf)
    assert len(got) == len(payloads)
    for segs, p in zip(got, payloads):
        assert _record_payload(buf, segs) == p
    # force the pure-python fallback scan too
    import incubator_mxnet_tpu.image as image_mod
    orig = image_mod._native.lib
    image_mod._native.lib = lambda: None
    try:
        got_py = _index_records(buf)
    finally:
        image_mod._native.lib = orig
    assert got_py == got


def test_native_augment_matches_numpy():
    lib = native.lib()
    if lib is None:
        pytest.skip("no native toolchain")
    import ctypes
    rng = np.random.RandomState(1)
    img = np.ascontiguousarray(rng.randint(0, 255, (40, 50, 3), np.uint8))
    mean = np.array([123.7, 116.8, 103.9], np.float32)
    stdinv = (1.0 / np.array([58.4, 57.1, 57.4], np.float32))
    for mirror in (0, 1):
        out = np.empty((3, 32, 32), np.float32)
        for reverse in (0, 1):
            out = np.empty((3, 32, 32), np.float32)
            lib.mxtpu_augment_to_chw(
                img.ctypes.data_as(ctypes.c_void_p), 40, 50, 3, 5, 7, 32, 32,
                mirror, mean.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                stdinv.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), reverse)
            crop = img[5:5 + 32, 7:7 + 32]
            if reverse:
                crop = crop[:, :, ::-1]
            if mirror:
                crop = crop[:, ::-1]
            ref = ((crop.astype(np.float32) - mean) * stdinv) \
                .transpose(2, 0, 1)
            np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-5)


def test_record_iter_delivers_all_samples(tmp_path):
    rec = tmp_path / "c.rec"
    _write_corpus(rec, n=60, size=48)
    it = ImageRecordIterImpl(path_imgrec=str(rec), data_shape=(3, 32, 32),
                             batch_size=10, preprocess_threads=4,
                             shuffle=True)
    labels = []
    for batch in it:
        assert batch.data[0].shape == (10, 3, 32, 32)
        labels.extend(batch.label[0].asnumpy().tolist())
    assert sorted(labels) == [float(i) for i in range(60)]
    # second epoch after reset delivers again
    it.reset()
    n = sum(b.data[0].shape[0] for b in it)
    assert n == 60


def test_record_iter_center_crop_content(tmp_path):
    """Pixel-exact content check through decode + crop + normalize."""
    import cv2
    rng = np.random.RandomState(2)
    img = rng.randint(0, 255, (48, 48, 3), np.uint8)
    ok, enc = cv2.imencode(".png", img)
    rec = recordio.MXRecordIO(str(tmp_path / "one.rec"), "w")
    rec.write(recordio.pack(recordio.IRHeader(0, 7.0, 0, 0), enc.tobytes()))
    rec.close()
    it = ImageRecordIterImpl(path_imgrec=str(tmp_path / "one.rec"),
                             data_shape=(3, 32, 32), batch_size=1,
                             preprocess_threads=2)
    batch = next(iter(it))
    got = batch.data[0].asnumpy()[0]
    crop = img[8:40, 8:40]                   # center crop, RGB == decoded
    rgb = cv2.cvtColor(cv2.imdecode(enc, cv2.IMREAD_COLOR),
                       cv2.COLOR_BGR2RGB)[8:40, 8:40]
    ref = rgb.astype(np.float32).transpose(2, 0, 1)
    np.testing.assert_allclose(got, ref, atol=1e-5)
    assert batch.label[0].asnumpy()[0] == 7.0

def test_record_iter_partial_batch_pad(tmp_path):
    rec = tmp_path / "p.rec"
    _write_corpus(rec, n=25, size=48)
    it = ImageRecordIterImpl(path_imgrec=str(rec), data_shape=(3, 32, 32),
                             batch_size=10, preprocess_threads=2)
    batches = list(it)
    assert [b.pad for b in batches] == [0, 0, 5]
    assert sum(b.data[0].shape[0] - b.pad for b in batches) == 25


def test_record_iter_corrupt_record_skips_not_raises(tmp_path):
    """Guardian io tier: an undecodable record must not kill the epoch —
    it is substituted with zeros, counted on corrupt_records, and the
    rest of the file still trains (the old behavior raised mid-epoch)."""
    rec = recordio.MXRecordIO(str(tmp_path / "bad.rec"), "w")
    rec.write(recordio.pack(recordio.IRHeader(0, 0.0, 0, 0),
                            b"not an image at all"))
    rec.close()
    it = ImageRecordIterImpl(path_imgrec=str(tmp_path / "bad.rec"),
                             data_shape=(3, 32, 32), batch_size=1,
                             preprocess_threads=2)
    batch = next(iter(it))
    assert batch.data[0].shape == (1, 3, 32, 32)
    np.testing.assert_array_equal(batch.data[0].asnumpy(), 0.0)
    assert it.corrupt_records == 1


def test_record_iter_seed_reproducible(tmp_path):
    rec = tmp_path / "s.rec"
    _write_corpus(rec, n=20, size=48)

    def run(threads):
        it = ImageRecordIterImpl(path_imgrec=str(rec),
                                 data_shape=(3, 32, 32), batch_size=5,
                                 preprocess_threads=threads, shuffle=True,
                                 rand_crop=True, rand_mirror=True, seed=7)
        return np.concatenate([b.data[0].asnumpy() for b in it])

    np.testing.assert_array_equal(run(1), run(4))
