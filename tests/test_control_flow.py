"""Control-flow operators `_foreach` / `_while_loop` / `_cond`
(ops/control_flow.py, symbol/contrib.py builders) — reference
`src/operator/control_flow.cc:1255-1423` + `python/mxnet/symbol/contrib.py`.

Covers: symbolic vs imperative parity, gradients through the scan,
symbol JSON round trips, closure capture of outer symbols, and the
one-scan hybrid unroll of recurrent cells."""
import numpy as np

import incubator_mxnet_tpu as mx


def _bind_fwd(sym, args, grads=None):
    ex = sym.bind(mx.cpu(), {k: mx.nd.array(v) for k, v in args.items()},
                  args_grad={k: mx.nd.zeros(v.shape)
                             for k, v in grads.items()} if grads else None)
    return ex


def test_foreach_symbolic_imperative_parity():
    data = mx.sym.Variable("data")
    init = mx.sym.Variable("init")
    w = mx.sym.Variable("w")

    def body(x, s):
        out = mx.sym.broadcast_add(mx.sym.broadcast_mul(x, w), s)
        return out, out

    outs, states = mx.sym.contrib.foreach(body, data, init)
    g = mx.sym.Group([outs, states])
    rng = np.random.RandomState(0)
    dnp = rng.rand(5, 4).astype("f4")
    inp = rng.rand(4).astype("f4")
    wnp = rng.rand(4).astype("f4")
    ex = _bind_fwd(g, {"data": dnp, "init": inp, "w": wnp})
    o = ex.forward()

    wa = mx.nd.array(wnp)
    io_, is_ = mx.nd.contrib.foreach(
        lambda x, s: (x * wa + s, x * wa + s),
        mx.nd.array(dnp), mx.nd.array(inp))
    np.testing.assert_allclose(o[0].asnumpy(), io_.asnumpy(), rtol=1e-6)
    np.testing.assert_allclose(o[1].asnumpy(), is_.asnumpy(), rtol=1e-6)
    # one _foreach node, not 5 unrolled bodies
    cf = [n for n in g._topo() if not n.is_variable and
          n.op.name == "_foreach"]
    assert len(cf) == 1


def test_foreach_json_roundtrip():
    data = mx.sym.Variable("data")
    init = mx.sym.Variable("init")
    w = mx.sym.Variable("w")
    # closure includes a COMPUTED outer symbol (w * 2): the subgraph keeps
    # the upstream node and XLA hoists the loop-invariant multiply
    w2 = w * 2.0

    def body(x, s):
        return mx.sym.broadcast_add(mx.sym.broadcast_mul(x, w2), s), s + 1.0

    outs, _ = mx.sym.contrib.foreach(body, data, init)
    rng = np.random.RandomState(1)
    args = {"data": rng.rand(3, 4).astype("f4"),
            "init": rng.rand(4).astype("f4"),
            "w": rng.rand(4).astype("f4")}
    o1 = _bind_fwd(outs, args).forward()[0].asnumpy()
    g2 = mx.sym.load_json(outs.tojson())
    o2 = _bind_fwd(g2, args).forward()[0].asnumpy()
    np.testing.assert_allclose(o2, o1, rtol=1e-6)


def test_foreach_gradient_matches_static_unroll():
    """d/dw through the scan == d/dw through T unrolled bodies."""
    T, C = 4, 3
    rng = np.random.RandomState(2)
    dnp = rng.rand(T, C).astype("f4")
    inp = rng.rand(C).astype("f4")
    wnp = rng.rand(C).astype("f4")

    def build_scan():
        data = mx.sym.Variable("data")
        init = mx.sym.Variable("init")
        w = mx.sym.Variable("w")
        outs, states = mx.sym.contrib.foreach(
            lambda x, s: ((mx.sym.broadcast_mul(x, w) + s,
                           mx.sym.broadcast_mul(x, w) + s))[0:2],
            data, init)
        return mx.sym.sum(outs)

    def build_unrolled():
        data = mx.sym.Variable("data")
        init = mx.sym.Variable("init")
        w = mx.sym.Variable("w")
        s = init
        outs = []
        for t in range(T):
            x = mx.sym.squeeze(mx.sym.slice_axis(data, axis=0, begin=t,
                                                 end=t + 1), axis=0)
            s = mx.sym.broadcast_mul(x, w) + s
            outs.append(s)
        return mx.sym.sum(mx.sym.stack(*outs, axis=0, num_args=T))

    grads = {}
    for name, build in [("scan", build_scan), ("unrolled", build_unrolled)]:
        ex = mx.sym.Group([build()]).bind(
            mx.cpu(),
            {"data": mx.nd.array(dnp), "init": mx.nd.array(inp),
             "w": mx.nd.array(wnp)},
            args_grad={"w": mx.nd.zeros(C), "data": mx.nd.zeros((T, C)),
                       "init": mx.nd.zeros(C)})
        ex.forward(is_train=True)
        ex.backward([mx.nd.ones(())])
        grads[name] = {k: v.asnumpy().copy()
                       for k, v in ex.grad_dict.items()}
    for k in ("w", "data", "init"):
        np.testing.assert_allclose(grads["scan"][k], grads["unrolled"][k],
                                   rtol=1e-5, atol=1e-6, err_msg=k)


def test_while_loop_parity_and_padding():
    i = mx.sym.Variable("i")
    s = mx.sym.Variable("s")
    outs, fin = mx.sym.contrib.while_loop(
        cond=lambda i, s: i < 5,
        func=lambda i, s: ([i + s], [i + 1, s + i]),
        loop_vars=[i, s], max_iterations=10)
    g = mx.sym.Group(list(outs) + list(fin))
    ex = _bind_fwd(g, {"i": np.array([0.0], "f4"),
                       "s": np.array([1.0], "f4")})
    o = ex.forward()
    io_, if_ = mx.nd.contrib.while_loop(
        lambda i, s: (i < 5), lambda i, s: ([i + s], [i + 1, s + i]),
        [mx.nd.array([0.0]), mx.nd.array([1.0])], max_iterations=10)
    # symbolic output is padded to max_iterations (reference semantics);
    # the valid prefix must equal the imperative (sliced) output
    n = io_[0].shape[0]
    np.testing.assert_allclose(o[0].asnumpy()[:n], io_[0].asnumpy())
    np.testing.assert_allclose(o[0].asnumpy()[n:], 0.0)
    np.testing.assert_allclose(o[1].asnumpy(), if_[0].asnumpy())
    np.testing.assert_allclose(o[2].asnumpy(), if_[1].asnumpy())


def test_cond_both_branches():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    out = mx.sym.contrib.cond(mx.sym.sum(a * b) < 5,
                              lambda: (a + 5) * (b + 5),
                              lambda: (a - 5) * (b - 5))
    for av, bv, want in [(1.0, 2.0, 42.0), (3.0, 4.0, 2.0)]:
        ex = _bind_fwd(out, {"a": np.array([av], "f4"),
                             "b": np.array([bv], "f4")})
        got = ex.forward()[0].asnumpy()
        np.testing.assert_allclose(got, [want], rtol=1e-6)
        # imperative parity
        imp = mx.nd.contrib.cond(
            mx.nd.sum(mx.nd.array([av]) * mx.nd.array([bv])) < 5,
            lambda: (mx.nd.array([av]) + 5) * (mx.nd.array([bv]) + 5),
            lambda: (mx.nd.array([av]) - 5) * (mx.nd.array([bv]) - 5))
        np.testing.assert_allclose(got, imp.asnumpy())


def test_cell_unroll_emits_one_foreach():
    """A hybrid LSTM cell unroll over a symbolic sequence compiles to ONE
    scan, and matches the classic static unroll numerically."""
    T, N, C, H = 5, 2, 3, 4
    cell = mx.gluon.rnn.LSTMCell(H, input_size=C)
    cell.initialize()
    data = mx.sym.Variable("data")
    begin = [mx.sym.Variable("h0"), mx.sym.Variable("c0")]
    out_scan, st_scan = cell.unroll(T, data, begin_state=begin,
                                    layout="NTC", merge_outputs=True)
    g_scan = mx.sym.Group([out_scan] + list(st_scan))
    cf = [n for n in g_scan._topo() if not n.is_variable and
          n.op.name == "_foreach"]
    assert len(cf) == 1, "hybrid unroll must emit exactly one _foreach"

    # static unroll via pre-sliced inputs (the classic path)
    slices = list(mx.sym.split(data, num_outputs=T, axis=1,
                               squeeze_axis=True))
    out_st, st_st = cell.unroll(T, slices, begin_state=begin,
                                layout="NTC", merge_outputs=True)
    g_st = mx.sym.Group([out_st] + list(st_st))

    rng = np.random.RandomState(3)
    vals = {"data": rng.rand(N, T, C).astype("f4"),
            "h0": np.zeros((N, H), "f4"), "c0": np.zeros((N, H), "f4")}
    params = {k: v.data().asnumpy()
              for k, v in cell.collect_params().items()}
    args = dict(vals)
    for name in g_scan.list_arguments():
        if name in params:
            args[name] = params[name]
    o1 = _bind_fwd(g_scan, args).forward()
    args2 = dict(vals)
    for name in g_st.list_arguments():
        if name in params:
            args2[name] = params[name]
    o2 = _bind_fwd(g_st, args2).forward()
    np.testing.assert_allclose(o1[0].asnumpy(), o2[0].asnumpy(),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(o1[1].asnumpy(), o2[1].asnumpy(),
                               rtol=1e-5, atol=1e-6)


def test_foreach_multi_data_multi_state():
    d1 = mx.sym.Variable("d1")
    d2 = mx.sym.Variable("d2")
    s1 = mx.sym.Variable("s1")
    s2 = mx.sym.Variable("s2")

    def body(xs, ss):
        a, b = xs
        u, v = ss
        return [a + u, b * v], [u + 1.0, v * 2.0]

    outs, states = mx.sym.contrib.foreach(body, [d1, d2], [s1, s2])
    g = mx.sym.Group(list(outs) + list(states))
    rng = np.random.RandomState(4)
    args = {"d1": rng.rand(3, 2).astype("f4"),
            "d2": rng.rand(3, 2).astype("f4"),
            "s1": rng.rand(2).astype("f4"),
            "s2": rng.rand(2).astype("f4")}
    o = _bind_fwd(g, args).forward()
    # imperative parity
    io_, is_ = mx.nd.contrib.foreach(
        lambda xs, ss: ([xs[0] + ss[0], xs[1] * ss[1]],
                        [ss[0] + 1.0, ss[1] * 2.0]),
        [mx.nd.array(args["d1"]), mx.nd.array(args["d2"])],
        [mx.nd.array(args["s1"]), mx.nd.array(args["s2"])])
    np.testing.assert_allclose(o[0].asnumpy(), io_[0].asnumpy(), rtol=1e-6)
    np.testing.assert_allclose(o[1].asnumpy(), io_[1].asnumpy(), rtol=1e-6)
    np.testing.assert_allclose(o[2].asnumpy(), is_[0].asnumpy(), rtol=1e-6)
    np.testing.assert_allclose(o[3].asnumpy(), is_[1].asnumpy(), rtol=1e-6)


def test_unroll_honors_length():
    """unroll(length=3) over a T=5 symbolic sequence computes exactly 3
    steps (the scan path must not silently consume the full axis)."""
    T_data, T_req, N, C, H = 5, 3, 2, 3, 4
    cell = mx.gluon.rnn.LSTMCell(H, input_size=C)
    cell.initialize()
    data = mx.sym.Variable("data")
    begin = [mx.sym.Variable("h0"), mx.sym.Variable("c0")]
    outs, _ = cell.unroll(T_req, data, begin_state=begin, layout="NTC",
                          merge_outputs=True)
    args = {"data": np.random.RandomState(0).rand(N, T_data, C)
            .astype("f4"),
            "h0": np.zeros((N, H), "f4"), "c0": np.zeros((N, H), "f4")}
    params = {k: v.data().asnumpy() for k, v in cell.collect_params().items()}
    for name in outs.list_arguments():
        if name in params:
            args[name] = params[name]
    o = _bind_fwd(outs, args).forward()[0]
    assert o.shape == (N, T_req, H), o.shape


def test_while_loop_gradient_not_poisoned_past_termination():
    """Ops that are only safe while cond holds (e.g. sqrt of a shrinking
    value) must not inject NaN gradients from terminated-range steps —
    the func subgraph executes under lax.cond, like the reference stops
    executing outright."""
    x = mx.sym.Variable("x")
    i = mx.sym.Variable("i")
    # while i < 3: out = sqrt(x - i); i += 1   (x - i < 0 once i >= x:
    # executing past termination would produce NaN)
    outs, fin = mx.sym.contrib.while_loop(
        cond=lambda i, x: i < 3,
        func=lambda i, x: ([mx.sym.sqrt(x - i)], [i + 1, x]),
        loop_vars=[i, x], max_iterations=8)
    loss = mx.sym.sum(outs[0])
    ex = loss.bind(mx.cpu(),
                   {"i": mx.nd.array([0.0]), "x": mx.nd.array([3.5])},
                   args_grad={"x": mx.nd.zeros(1)})
    ex.forward(is_train=True)
    ex.backward([mx.nd.ones(())])
    g = ex.grad_dict["x"].asnumpy()
    assert np.isfinite(g).all(), g
    # d/dx sum_t sqrt(x - t) for t=0,1,2
    want = sum(0.5 / np.sqrt(3.5 - t) for t in range(3))
    np.testing.assert_allclose(g, [want], rtol=1e-5)


def test_foreach_lstm_module_fit_fused():
    """The lstm_bucketing shape end-to-end on CPU: a Module whose graph
    contains ONE _foreach trains through the fused scan-block fit loop
    (the PTB example's path), loss/perplexity improving."""
    import os
    from incubator_mxnet_tpu import rnn, io

    vocab, embed, hidden, seq, bs = 40, 8, 16, 6, 8
    stack = rnn.SequentialRNNCell()
    stack.add(rnn.LSTMCell(hidden, prefix="lstm_l0_"))
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    emb = mx.sym.Embedding(data, input_dim=vocab, output_dim=embed,
                           name="embed")
    stack.reset()
    outputs, _ = stack.unroll(seq, inputs=emb, merge_outputs=True)
    pred = mx.sym.Reshape(outputs, shape=(-1, hidden))
    pred = mx.sym.FullyConnected(pred, num_hidden=vocab, name="pred")
    net = mx.sym.SoftmaxOutput(pred, mx.sym.Reshape(label, shape=(-1,)),
                               name="softmax")
    assert sum(1 for n in net._topo()
               if not n.is_variable and n.op.name == "_foreach") == 1

    rng = np.random.RandomState(0)
    tokens = rng.randint(1, vocab, (64, seq)).astype("f4")
    it = mx.io.NDArrayIter({"data": tokens},
                           {"softmax_label": np.roll(tokens, -1, 1)},
                           batch_size=bs)
    mod = mx.mod.Module(net, context=mx.cpu())
    vals = []
    mod.fit(it, num_epoch=3, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5,
                              "rescale_grad": 1.0 / bs},
            eval_metric=mx.metric.Perplexity(0),
            initializer=mx.initializer.Xavier(),
            epoch_end_callback=lambda e, s, a, x: vals.append(None),
            kvstore=None)
    assert mod._fused_step is not None and not mod._fused_step.broken, \
        "the _foreach graph must train through the fused step"
    assert len(mod._fused_step._jit_block) >= 1, \
        "scan-block mode must engage"


def test_while_loop_early_termination_cost():
    """With num_out_data == 0 (no per-step outputs) the imperative
    while_loop lowers to a TRUE `lax.while_loop`: cost scales with the
    ACTUAL iteration count, not max_iterations (VERDICT Next #7).  The
    masked-scan lowering would run all max_iterations — at 5M that is
    seconds of wall time; the fast path finishes in milliseconds."""
    import time

    def run(max_iter):
        t0 = time.perf_counter()
        outs, fin = mx.nd.contrib.while_loop(
            lambda i, s: i < 5,
            lambda i, s: ([], [i + 1, s + i]),
            [mx.nd.array([0.0]), mx.nd.array([1.0])],
            max_iterations=max_iter)
        assert outs == []
        np.testing.assert_allclose(fin[0].asnumpy(), [5.0])
        np.testing.assert_allclose(fin[1].asnumpy(), [11.0])
        return time.perf_counter() - t0

    run(100)                      # compile warmup for the small signature
    t_small = run(100)
    t_big = run(5_000_000)        # includes ITS compile: still bounded
    # identical results, and 50,000x more max_iterations must not cost
    # 50,000x the time — allow generous CI jitter, catch the O(max_iter)
    # regression which would be seconds here
    assert t_big < max(50 * t_small, 2.0), (t_small, t_big)


def test_while_loop_fast_path_matches_masked_scan():
    """Fast-path numerics equal the masked-scan lowering (forced by
    requesting a per-step output) and the symbolic padded path."""
    cond = lambda i, s: i < 7
    body_out = lambda i, s: ([i * s], [i + 1, s + i])
    body_noout = lambda i, s: ([], [i + 1, s + i])
    init = lambda: [mx.nd.array([0.0]), mx.nd.array([2.0])]
    _, fin_fast = mx.nd.contrib.while_loop(cond, body_noout, init(),
                                           max_iterations=64)
    _, fin_scan = mx.nd.contrib.while_loop(cond, body_out, init(),
                                           max_iterations=64)
    for a, b in zip(fin_fast, fin_scan):
        np.testing.assert_allclose(a.asnumpy(), b.asnumpy())


def test_foreach_duplicate_closure_names_bind_correctly():
    """Two distinct outer Variables sharing one NAME (legal in the symbol
    API, and what nested loop bodies reusing inner names produce) must
    each bind their own closure slot.  The round-5 known issue: the
    rebuilt-from-JSON subgraph bound by name, collapsing both onto one
    slot and silently computing with the wrong input."""
    import jax
    import jax.numpy as jnp
    from incubator_mxnet_tpu.symbol.symbol import graph_eval_fn

    data = mx.sym.Variable("data")
    init = mx.sym.Variable("init")
    w1 = mx.sym.Variable("w")
    w2 = mx.sym.Variable("w")   # distinct node, same name

    def body(x, s):
        y = mx.sym.broadcast_add(mx.sym.broadcast_mul(x, w1),
                                 mx.sym.broadcast_mul(s, w2))
        return y, s + 1.0

    outs, _ = mx.sym.contrib.foreach(body, data, init)
    for sym in (outs, mx.sym.load_json(outs.tojson())):  # + JSON round trip
        gfn, arg_nodes, _aux, _nrng = graph_eval_fn(sym, False)
        names = [n.name for n in arg_nodes]
        assert names.count("w") == 2

        rng = np.random.RandomState(3)
        dnp = rng.rand(4, 3).astype("f4")
        inp = rng.rand(3).astype("f4")
        w1v = rng.rand(3).astype("f4")
        w2v = rng.rand(3).astype("f4")
        # positional feed (executor bind rejects duplicate top-level
        # names by design; the subgraph binding is what's under test)
        by_pos = {"data": dnp, "init": inp}
        vals, w_feed = [], [w1v, w2v]
        for n in arg_nodes:
            if n.name in by_pos:
                vals.append(jnp.asarray(by_pos[n.name]))
            else:
                vals.append(jnp.asarray(w_feed.pop(0)))
        (ys,), _ = gfn(tuple(vals), (), jax.random.PRNGKey(0))
        # reference: y_t = x_t * w1 + s_t * w2, s advancing by +1
        s = inp.copy()
        want = np.zeros_like(dnp)
        for t in range(dnp.shape[0]):
            want[t] = dnp[t] * w1v + s * w2v
            s = s + 1.0
        np.testing.assert_allclose(np.asarray(ys), want, rtol=1e-5,
                                   atol=1e-6)
