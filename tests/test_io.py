"""IO tests (reference tests/python/unittest/test_io.py, test_recordio)."""
import os

import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd
from incubator_mxnet_tpu.io import (NDArrayIter, ResizeIter, PrefetchingIter,
                                    CSVIter, DataBatch, DataDesc)
from incubator_mxnet_tpu import recordio


def test_ndarray_iter():
    data = np.arange(100).reshape(25, 4).astype("f4")
    labels = np.arange(25).astype("f4")
    it = NDArrayIter(data, labels, batch_size=10, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 3
    assert batches[0].data[0].shape == (10, 4)
    assert batches[2].pad == 5
    np.testing.assert_allclose(batches[0].data[0].asnumpy(), data[:10])
    np.testing.assert_allclose(batches[0].label[0].asnumpy(), labels[:10])

    it2 = NDArrayIter(data, labels, batch_size=10, last_batch_handle="discard")
    assert len(list(it2)) == 2

    # dict input and provide_data names
    it3 = NDArrayIter({"x": data}, {"y": labels}, batch_size=5)
    assert it3.provide_data[0].name == "x"
    assert it3.provide_label[0].name == "y"


def test_ndarray_iter_shuffle_reset():
    data = np.arange(20).astype("f4").reshape(20, 1)
    it = NDArrayIter(data, data[:, 0], batch_size=4, shuffle=True)
    seen1 = np.concatenate([b.data[0].asnumpy()[:, 0] for b in it])
    it.reset()
    seen2 = np.concatenate([b.data[0].asnumpy()[:, 0] for b in it])
    assert sorted(seen1) == sorted(seen2) == list(range(20))


def test_resize_iter():
    data = np.arange(40).reshape(10, 4).astype("f4")
    it = ResizeIter(NDArrayIter(data, np.zeros(10), batch_size=5), size=7)
    assert len(list(it)) == 7


def test_prefetching_iter():
    data = np.arange(80).reshape(20, 4).astype("f4")
    base = NDArrayIter(data, np.zeros(20), batch_size=5)
    it = PrefetchingIter(base)
    batches = list(it)
    assert len(batches) == 4
    it.reset()
    assert len(list(it)) == 4


def test_csv_iter(tmp_path):
    data_path = str(tmp_path / "data.csv")
    label_path = str(tmp_path / "label.csv")
    np.savetxt(data_path, np.arange(24).reshape(8, 3), delimiter=",")
    np.savetxt(label_path, np.arange(8), delimiter=",")
    it = CSVIter(data_csv=data_path, data_shape=(3,), label_csv=label_path,
                 batch_size=4)
    batches = list(it)
    assert len(batches) == 2
    np.testing.assert_allclose(batches[0].data[0].asnumpy(),
                               np.arange(12).reshape(4, 3))


def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "test.rec")
    writer = recordio.MXRecordIO(path, "w")
    for i in range(5):
        writer.write(b"record_%d" % i)
    writer.close()
    reader = recordio.MXRecordIO(path, "r")
    for i in range(5):
        assert reader.read() == b"record_%d" % i
    assert reader.read() is None
    reader.close()


def test_indexed_recordio(tmp_path):
    path = str(tmp_path / "test.rec")
    idx_path = str(tmp_path / "test.idx")
    writer = recordio.MXIndexedRecordIO(idx_path, path, "w")
    for i in range(5):
        writer.write_idx(i, b"record_%d" % i)
    writer.close()
    reader = recordio.MXIndexedRecordIO(idx_path, path, "r")
    assert reader.read_idx(3) == b"record_3"
    assert reader.read_idx(0) == b"record_0"
    assert reader.keys == list(range(5))
    reader.close()


def test_pack_unpack():
    header = recordio.IRHeader(0, 2.0, 7, 0)
    s = recordio.pack(header, b"imagebytes")
    h2, payload = recordio.unpack(s)
    assert payload == b"imagebytes"
    assert h2.label == 2.0 and h2.id == 7
    # multi-label
    header = recordio.IRHeader(0, [1.0, 2.0, 3.0], 7, 0)
    s = recordio.pack(header, b"x")
    h2, payload = recordio.unpack(s)
    np.testing.assert_allclose(h2.label, [1, 2, 3])
    assert payload == b"x"


def test_image_record_iter(tmp_path):
    """End-to-end: pack images into a .rec, read via ImageRecordIter."""
    from incubator_mxnet_tpu.io import ImageRecordIter
    path = str(tmp_path / "imgs.rec")
    idx_path = str(tmp_path / "imgs.idx")
    writer = recordio.MXIndexedRecordIO(idx_path, path, "w")
    rng = np.random.RandomState(0)
    for i in range(12):
        img = (rng.rand(24, 24, 3) * 255).astype("uint8")
        s = recordio.pack_img(recordio.IRHeader(0, float(i % 3), i, 0), img,
                              img_fmt=".png")
        writer.write_idx(i, s)
    writer.close()
    it = ImageRecordIter(path_imgrec=path, data_shape=(3, 20, 20),
                         batch_size=4, shuffle=True, rand_crop=True,
                         preprocess_threads=2)
    batch = it.next()
    assert batch.data[0].shape == (4, 3, 20, 20)
    assert batch.label[0].shape == (4,)
    n = 1 + len(list(it))
    assert n == 3
    it.reset()
    assert len(list(it)) == 3


def test_ndarray_iter_preserves_dtype():
    """Delivered batch dtype must match provide_data/provide_label."""
    X = np.random.randn(10, 3).astype("f4")
    y = np.arange(10, dtype="int32")
    it = mx.io.NDArrayIter(X, y, batch_size=5)
    batch = next(iter(it))
    assert batch.label[0].dtype == np.int32
    assert batch.data[0].dtype == np.float32
    assert it.provide_label[0].dtype == np.int32


def test_device_augment_mode_parity(tmp_path):
    """device_augment=True (uint8 NHWC out + in-graph ImageNormalize) must
    reproduce the classic host-normalized fp32 NCHW batches exactly: same
    seed -> same crops/mirrors, and the graph-side normalize matches the
    host kernel."""
    from incubator_mxnet_tpu.io import ImageRecordIter
    import incubator_mxnet_tpu as mx
    path = str(tmp_path / "imgs.rec")
    writer = recordio.MXRecordIO(path, "w")
    rng = np.random.RandomState(1)
    for i in range(8):
        img = (rng.rand(28, 30, 3) * 255).astype("uint8")
        writer.write(recordio.pack_img(
            recordio.IRHeader(0, float(i), i, 0), img, img_fmt=".png"))
    writer.close()
    kw = dict(path_imgrec=path, data_shape=(3, 24, 24), batch_size=4,
              rand_crop=True, rand_mirror=True, seed=5,
              mean_r=123.68, mean_g=116.78, mean_b=103.94,
              std_r=58.4, std_g=57.1, std_b=57.4, preprocess_threads=1)
    classic = ImageRecordIter(**kw)
    dev = ImageRecordIter(device_augment=True, **kw)
    got_any = False
    for bc, bd in zip(classic, dev):
        assert bd.data[0].dtype == np.uint8
        assert bd.data[0].shape == (4, 24, 24, 3)
        norm = mx.nd.ImageNormalize(
            bd.data[0], mean=(123.68, 116.78, 103.94),
            std=(58.4, 57.1, 57.4), input_layout="NHWC",
            output_layout="NCHW")
        np.testing.assert_allclose(norm.asnumpy(), bc.data[0].asnumpy(),
                                   rtol=1e-5, atol=1e-4)
        np.testing.assert_allclose(bd.label[0].asnumpy(),
                                   bc.label[0].asnumpy())
        got_any = True
    assert got_any
    # normalize_symbol composes the same thing symbolically
    data = mx.sym.Variable("data")
    out = dev.normalize_symbol(data)
    ex = out.bind(mx.cpu(), {"data": mx.nd.array(
        np.zeros((4, 24, 24, 3), np.uint8))})
    y = ex.forward()[0]
    assert y.shape == (4, 3, 24, 24)


def test_im2rec_and_rec2idx_tools(tmp_path):
    """tools/im2rec.py builds .lst/.rec/.idx the ImageRecordIter consumes;
    tools/rec2idx.py reproduces the index byte-for-byte (reference
    tools/im2rec.py + rec2idx.py)."""
    import subprocess
    import sys
    import cv2
    root = tmp_path / "imgs"
    for d in ("a", "b"):
        (root / d).mkdir(parents=True)
        rng = np.random.RandomState(0)
        for i in range(3):
            cv2.imwrite(str(root / d / f"{d}{i}.jpg"),
                        (rng.rand(36, 36, 3) * 255).astype("uint8"))
    tools = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools")
    prefix = str(tmp_path / "ds")
    subprocess.run([sys.executable, os.path.join(tools, "im2rec.py"),
                    prefix, str(root), "--list", "--recursive"],
                   check=True, capture_output=True)
    subprocess.run([sys.executable, os.path.join(tools, "im2rec.py"),
                    prefix, str(root), "--num-thread", "2"],
                   check=True, capture_output=True)
    rec, idx = prefix + ".rec", prefix + ".idx"
    assert os.path.exists(rec) and os.path.exists(idx)
    subprocess.run([sys.executable, os.path.join(tools, "rec2idx.py"),
                    rec, prefix + "2.idx"], check=True,
                   capture_output=True)
    assert sorted(open(idx).read().splitlines()) == \
        sorted(open(prefix + "2.idx").read().splitlines())
    from incubator_mxnet_tpu.io import ImageRecordIter
    it = ImageRecordIter(path_imgrec=rec, data_shape=(3, 32, 32),
                         batch_size=3, preprocess_threads=1)
    b = next(iter(it))
    assert b.data[0].shape == (3, 3, 32, 32)
    assert set(b.label[0].asnumpy().tolist()) <= {0.0, 1.0}
