"""Optimizer + metric + initializer + lr_scheduler tests
(reference tests/python/unittest/test_optimizer.py, test_metric.py)."""
import math

import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd


def _train_quadratic(opt, steps=60):
    """Minimize ||w - 3||^2 with the given optimizer; returns final w."""
    w = nd.array([0.0, 0.0])
    state = opt.create_state(0, w)
    for _ in range(steps):
        grad = 2 * (w - 3)
        opt.update(0, w, grad, state)
    return w.asnumpy()


def test_optimizers_converge():
    cases = [
        mx.optimizer.SGD(learning_rate=0.1),
        mx.optimizer.SGD(learning_rate=0.05, momentum=0.9),
        mx.optimizer.Adam(learning_rate=0.3),
        mx.optimizer.RMSProp(learning_rate=0.3),
        mx.optimizer.RMSProp(learning_rate=0.3, centered=True),
        mx.optimizer.AdaGrad(learning_rate=1.5),
        mx.optimizer.AdaDelta(rho=0.9, epsilon=1e-4),
        mx.optimizer.Adamax(learning_rate=0.5),
        mx.optimizer.Nadam(learning_rate=0.3),
        mx.optimizer.Ftrl(learning_rate=2.0),
        mx.optimizer.Signum(learning_rate=0.05),
        mx.optimizer.NAG(learning_rate=0.05, momentum=0.9),
        mx.optimizer.FTML(learning_rate=0.3),
    ]
    for opt in cases:
        w = _train_quadratic(opt, steps=200)
        assert np.abs(w - 3).max() < 0.5, (type(opt).__name__, w)


def test_sgd_matches_reference_formula():
    opt = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9, wd=0.01,
                           rescale_grad=0.5)
    w = nd.array([1.0])
    state = opt.create_state(0, w)
    g = nd.array([2.0])
    opt.update(0, w, g, state)
    # mom = 0.9*0 - 0.1*(0.5*2 + 0.01*1); w += mom
    exp_mom = -0.1 * (1.0 + 0.01)
    np.testing.assert_allclose(state.asnumpy(), [exp_mom], rtol=1e-6)
    np.testing.assert_allclose(w.asnumpy(), [1.0 + exp_mom], rtol=1e-6)


def test_optimizer_registry_and_lr():
    opt = mx.optimizer.create("sgd", learning_rate=0.3)
    assert isinstance(opt, mx.optimizer.SGD)
    assert opt._get_lr(0) == 0.3
    sched = mx.lr_scheduler.FactorScheduler(step=2, factor=0.5)
    opt2 = mx.optimizer.create("sgd", learning_rate=1.0, lr_scheduler=sched)
    w = nd.array([0.0])
    for _ in range(10):
        opt2.update(0, w, nd.array([0.0]), None)
    assert opt2._get_lr(0) < 1.0


def test_lr_schedulers():
    s = mx.lr_scheduler.FactorScheduler(step=10, factor=0.1)
    s.base_lr = 1.0
    assert s(5) == 1.0
    assert abs(s(15) - 0.1) < 1e-9
    m = mx.lr_scheduler.MultiFactorScheduler(step=[5, 10], factor=0.1)
    m.base_lr = 1.0
    assert m(2) == 1.0
    assert abs(m(7) - 0.1) < 1e-9
    assert abs(m(12) - 0.01) < 1e-9
    p = mx.lr_scheduler.PolyScheduler(max_update=100, base_lr=1.0, pwr=1)
    assert abs(p(50) - 0.5) < 1e-6
    c = mx.lr_scheduler.CosineScheduler(max_update=100, base_lr=1.0)
    assert abs(c(0) - 1.0) < 1e-6
    assert abs(c(100)) < 1e-6


def test_multi_precision_sgd():
    import ml_dtypes
    opt = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9,
                           multi_precision=True)
    w = nd.array(np.ones(4), dtype="bfloat16")
    state = opt.create_state_multi_precision(0, w)
    assert isinstance(state, tuple)
    mom, w32 = state
    assert w32.dtype == np.float32
    g = nd.array(np.ones(4) * 0.5, dtype="bfloat16")
    opt.update_multi_precision(0, w, g, state)
    assert w.dtype == np.dtype(ml_dtypes.bfloat16)
    assert abs(float(w32.asnumpy()[0]) - 0.95) < 1e-6


def test_metrics():
    pred = nd.array([[0.3, 0.7], [0.9, 0.1], [0.4, 0.6]])
    label = nd.array([1, 0, 0])
    acc = mx.metric.create("acc")
    acc.update([label], [pred])
    assert abs(acc.get()[1] - 2.0 / 3) < 1e-6

    mse = mx.metric.MSE()
    mse.update([nd.array([1.0, 2.0])], [nd.array([1.5, 2.5])])
    assert abs(mse.get()[1] - 0.25) < 1e-6

    f1 = mx.metric.F1()
    f1.update([nd.array([1, 0, 1, 1])],
              [nd.array([[0.2, 0.8], [0.8, 0.2], [0.1, 0.9], [0.9, 0.1]])])
    assert 0 < f1.get()[1] <= 1.0

    perp = mx.metric.Perplexity(ignore_label=None)
    perp.update([nd.array([0, 1])], [nd.array([[0.5, 0.5], [0.5, 0.5]])])
    assert abs(perp.get()[1] - 2.0) < 1e-3

    comp = mx.metric.create(["acc", "mse"])
    names, values = comp.get() if False else (None, None)
    comp.update([label], [pred])
    got = comp.get()
    assert len(got[0]) == 2

    custom = mx.metric.np(lambda l, p: float((l == p.argmax(1)).mean()),
                          name="mycustom")
    custom.update([label], [pred])
    assert abs(custom.get()[1] - 2.0 / 3) < 1e-6

    topk = mx.metric.TopKAccuracy(top_k=2)
    topk.update([label], [pred])
    assert topk.get()[1] == 1.0


def test_initializers():
    w = nd.zeros((64, 32))
    mx.initializer.Xavier(factor_type="avg", magnitude=3)("fc_weight", w)
    a = w.asnumpy()
    bound = math.sqrt(3.0 / ((64 + 32) / 2))
    assert abs(a).max() <= bound + 1e-6
    assert abs(a).std() > 0

    b = nd.zeros((10,))
    mx.initializer.Uniform(0.1)("some_bias", b)
    assert (b.asnumpy() == 0).all()  # bias pattern → zero init

    g = nd.zeros((10,))
    mx.initializer.Xavier()("bn_gamma", g)
    assert (g.asnumpy() == 1).all()

    c = nd.zeros((3, 3))
    mx.initializer.Constant(2.5)("c_weight", c)
    assert (c.asnumpy() == 2.5).all()

    o = nd.zeros((16, 16))
    mx.initializer.Orthogonal()("o_weight", o)
    q = o.asnumpy()
    np.testing.assert_allclose(q @ q.T, np.eye(16) * (1.414 ** 2), atol=1e-4)

    mixed = mx.initializer.Mixed([".*bias", ".*"],
                                 [mx.initializer.Zero(),
                                  mx.initializer.Uniform(0.1)])
    t = nd.zeros((4,))
    mixed("fc1_bias", t)
    assert (t.asnumpy() == 0).all()
