"""Gluon tests (reference tests/python/unittest/test_gluon.py)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, autograd, gluon
from incubator_mxnet_tpu.gluon import nn


def test_parameter_basics():
    p = gluon.Parameter("weight", shape=(4, 3))
    p.initialize(init=mx.initializer.Xavier(), ctx=mx.cpu())
    assert p.data().shape == (4, 3)
    assert p.grad().shape == (4, 3)
    assert p.list_ctx() == [mx.cpu()]
    p.zero_grad()
    assert (p.grad().asnumpy() == 0).all()


def test_parameter_deferred_init():
    p = gluon.Parameter("w", shape=(4, 0), allow_deferred_init=True)
    p.initialize(ctx=mx.cpu())
    with pytest.raises(gluon.DeferredInitializationError):
        p.data()
    p.shape = (4, 7)
    p._finish_deferred_init()
    assert p.data().shape == (4, 7)


def test_dense_eager_and_shape_inference():
    net = nn.Dense(5)
    net.initialize()
    x = nd.random.uniform(shape=(3, 8))
    out = net(x)
    assert out.shape == (3, 5)
    assert net.weight.shape == (5, 8)  # inferred from input


def test_sequential_train_eager():
    np.random.seed(0)
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(2))
    net.initialize(mx.initializer.Xavier())
    X = nd.array(np.random.randn(64, 10).astype("f4"))
    y_true = nd.array((np.random.randn(64) > 0).astype("f4"))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    losses = []
    for _ in range(40):
        with autograd.record():
            out = net(X)
            loss = loss_fn(out, y_true)
        loss.backward()
        trainer.step(64)
        losses.append(float(loss.asnumpy().mean()))
    assert losses[-1] < losses[0] * 0.7, losses[:3] + losses[-3:]


def test_hybridize_matches_eager():
    np.random.seed(1)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize(mx.initializer.Xavier())
    x = nd.array(np.random.rand(5, 12).astype("f4"))
    eager_out = net(x).asnumpy()
    net.hybridize()
    hybrid_out = net(x).asnumpy()
    np.testing.assert_allclose(eager_out, hybrid_out, rtol=1e-5)
    # gradients flow through the cached op
    for p in net.collect_params().values():
        p.zero_grad()
    with autograd.record():
        out = net(x)
        loss = nd.sum(out * out)
    loss.backward()
    w0 = list(net.collect_params().values())[0]
    assert np.abs(w0.grad().asnumpy()).sum() > 0


def test_hybridize_deferred_init():
    """Hybridized net with no explicit in_units: shapes inferred at first call."""
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(3))
    net.initialize()
    net.hybridize()
    out = net(nd.ones((2, 6)))
    assert out.shape == (2, 3)
    assert net[0].weight.shape == (8, 6)


def test_hybridize_batchnorm_updates_running_stats():
    net = nn.HybridSequential()
    net.add(nn.Dense(4), nn.BatchNorm())
    net.initialize()
    net.hybridize()
    x = nd.random.uniform(1, 2, shape=(16, 6))
    with autograd.record():
        net(x)
    bn = net[1]
    rm = bn.running_mean.data().asnumpy()
    assert np.abs(rm).sum() > 0  # moving mean moved away from zero


def test_conv_block_and_pooling():
    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, kernel_size=3, padding=1, activation="relu"),
            nn.MaxPool2D(2, 2),
            nn.Conv2D(16, kernel_size=3, padding=1),
            nn.GlobalAvgPool2D(),
            nn.Flatten(),
            nn.Dense(10))
    net.initialize()
    out = net(nd.random.uniform(shape=(2, 3, 16, 16)))
    assert out.shape == (2, 10)
    assert net[0].weight.shape == (8, 3, 3, 3)


def test_save_load_parameters(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Dense(4), nn.Dense(2))
    net.initialize()
    net(nd.ones((1, 6)))
    fname = str(tmp_path / "net.params")
    net.save_parameters(fname)

    net2 = nn.HybridSequential()
    net2.add(nn.Dense(4), nn.Dense(2))
    net2.load_parameters(fname)
    out1 = net(nd.ones((3, 6))).asnumpy()
    out2 = net2(nd.ones((3, 6))).asnumpy()
    np.testing.assert_allclose(out1, out2, rtol=1e-6)


def test_losses():
    pred = nd.array([[1.0, 2.0], [3.0, 4.0]])
    label = nd.array([[1.5, 2.5], [2.5, 3.5]])
    l2 = gluon.loss.L2Loss()
    np.testing.assert_allclose(l2(pred, label).asnumpy(), [0.125, 0.125],
                               rtol=1e-5)
    l1 = gluon.loss.L1Loss()
    np.testing.assert_allclose(l1(pred, label).asnumpy(), [0.5, 0.5],
                               rtol=1e-5)
    sce = gluon.loss.SoftmaxCrossEntropyLoss()
    out = sce(nd.array([[10.0, 0.0]]), nd.array([0.0]))
    assert out.asnumpy()[0] < 0.001
    bce = gluon.loss.SigmoidBCELoss()
    out = bce(nd.array([[10.0]]), nd.array([[1.0]]))
    assert out.asnumpy()[0] < 0.001
    huber = gluon.loss.HuberLoss()
    np.testing.assert_allclose(
        huber(nd.array([[0.5]]), nd.array([[0.0]])).asnumpy(), [0.125],
        rtol=1e-5)
    hinge = gluon.loss.HingeLoss()
    np.testing.assert_allclose(
        hinge(nd.array([[0.5]]), nd.array([[1.0]])).asnumpy(), [0.5],
        rtol=1e-5)


def test_ctc_loss():
    """CTC loss sanity: perfect prediction ≈ low loss (reference test_loss)."""
    T, N, C = 10, 2, 5
    pred = np.full((N, T, C), -10.0, dtype="f4")
    labels = np.array([[1, 2, 3, 0], [2, 4, 0, 0]], dtype="f4")
    # make the aligned path very likely: l1 b l2 b ...
    for n, seq in enumerate([[1, 1, 2, 2, 3, 3, 0, 0, 0, 0],
                             [2, 2, 4, 4, 0, 0, 0, 0, 0, 0]]):
        for t, c in enumerate(seq):
            pred[n, t, c] = 10.0
    ctc = gluon.loss.CTCLoss(layout="NTC", label_layout="NT")
    loss = ctc(nd.array(pred), nd.array(labels))
    assert loss.shape == (N,)
    assert (loss.asnumpy() < 2.0).all(), loss.asnumpy()


def test_lstm_layer_and_cells():
    lstm = gluon.rnn.LSTM(hidden_size=8, num_layers=2)
    lstm.initialize()
    x = nd.random.uniform(shape=(5, 3, 6))  # TNC
    out = lstm(x)
    assert out.shape == (5, 3, 8)
    # with states
    states = lstm.begin_state(batch_size=3)
    out, new_states = lstm(x, states)
    assert out.shape == (5, 3, 8)
    assert new_states[0].shape == (2, 3, 8)

    cell = gluon.rnn.LSTMCell(hidden_size=8)
    cell.initialize()
    outputs, states = cell.unroll(5, x.transpose(axes=(1, 0, 2)),
                                  layout="NTC")
    assert len(outputs) == 5
    assert outputs[0].shape == (3, 8)


def test_gru_bidirectional():
    gru = gluon.rnn.GRU(hidden_size=4, num_layers=1, bidirectional=True)
    gru.initialize()
    x = nd.random.uniform(shape=(7, 2, 5))
    out = gru(x)
    assert out.shape == (7, 2, 8)


def test_sequential_rnn_cells():
    stack = gluon.rnn.SequentialRNNCell()
    stack.add(gluon.rnn.LSTMCell(hidden_size=8))
    stack.add(gluon.rnn.GRUCell(hidden_size=4))
    stack.initialize()
    x = nd.random.uniform(shape=(2, 6, 10))
    outputs, states = stack.unroll(6, x, layout="NTC")
    assert outputs[-1].shape == (2, 4)


def test_dataloader_and_dataset():
    X = np.random.rand(20, 3).astype("f4")
    y = np.arange(20).astype("f4")
    dataset = gluon.data.ArrayDataset(X, y)
    assert len(dataset) == 20
    loader = gluon.data.DataLoader(dataset, batch_size=5)
    batches = list(loader)
    assert len(batches) == 4
    data, label = batches[0]
    assert data.shape == (5, 3)
    np.testing.assert_allclose(label.asnumpy(), y[:5])
    # shuffled, threaded
    loader = gluon.data.DataLoader(dataset, batch_size=5, shuffle=True,
                                   num_workers=2)
    seen = np.concatenate([b[1].asnumpy() for b in loader])
    assert sorted(seen) == sorted(y)


def test_transforms_and_synthetic_dataset():
    from incubator_mxnet_tpu.gluon.data.vision import (SyntheticImageDataset,
                                                       transforms)
    ds = SyntheticImageDataset(num_samples=32, shape=(8, 8, 3))
    tf = transforms.Compose([transforms.ToTensor(),
                             transforms.Normalize(0.5, 0.5)])
    ds_t = ds.transform_first(tf)
    img, label = ds_t[0]
    assert img.shape == (3, 8, 8)
    loader = gluon.data.DataLoader(ds_t, batch_size=8)
    data, labels = next(iter(loader))
    assert data.shape == (8, 3, 8, 8)


def test_model_zoo_smoke():
    from incubator_mxnet_tpu.gluon.model_zoo import get_model
    for name, shape in [("resnet18_v1", (1, 3, 32, 32)),
                        ("resnet18_v2", (1, 3, 32, 32)),
                        ("squeezenet1.1", (1, 3, 64, 64)),
                        ("mobilenet0.25", (1, 3, 32, 32))]:
        net = get_model(name, classes=10)
        net.initialize()
        out = net(nd.random.uniform(shape=shape))
        assert out.shape == (1, 10), name


def test_split_and_load():
    data = nd.arange(0, 16).reshape((8, 2))
    parts = gluon.split_and_load(data, [mx.cpu(), mx.cpu()])
    assert len(parts) == 2 and parts[0].shape == (4, 2)


def test_clip_global_norm():
    arrays = [nd.ones((2, 2)) * 3, nd.ones((3,)) * 4]
    norm = gluon.utils.clip_global_norm(arrays, 1.0)
    total = np.sqrt(sum((a.asnumpy() ** 2).sum() for a in arrays))
    assert abs(total - 1.0) < 1e-5
    assert norm > 1.0


def test_symbol_block(tmp_path):
    """export + SymbolBlock.imports round trip (reference block.py:986)."""
    net = nn.HybridSequential()
    net.add(nn.Dense(4, activation="relu"), nn.Dense(2))
    net.initialize()
    net.hybridize()
    x = nd.ones((1, 6))
    ref = net(x).asnumpy()
    path = str(tmp_path / "model")
    net.export(path)
    net2 = gluon.SymbolBlock.imports(path + "-symbol.json", "data",
                                     path + "-0000.params")
    out = net2(x).asnumpy()
    np.testing.assert_allclose(out, ref, rtol=1e-5)
