"""Elastic checkpointing & auto-resume.

Crash-consistency contract: a checkpoint a killed writer left behind —
truncated shard, missing/corrupt manifest, bad checksum — is NEVER
selected by ``checkpoint.latest``; resume lands on the last fully
committed write and reproduces an uninterrupted run bit-for-bit on CPU.
The end-to-end gate hard-kills a real training process with ``os._exit``
and compares params AND optimizer slots against the uninterrupted run.
"""
import os
import pickle
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import checkpoint as ckpt
from incubator_mxnet_tpu import nd, sym
from incubator_mxnet_tpu.io import NDArrayIter

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mlp(hidden=16, classes=4):
    d = sym.Variable("data")
    f1 = sym.FullyConnected(d, num_hidden=hidden, name="fc1")
    a1 = sym.Activation(f1, act_type="relu")
    f2 = sym.FullyConnected(a1, num_hidden=classes, name="fc2")
    return sym.SoftmaxOutput(f2, name="softmax")


# -- manifest / torn-checkpoint crash consistency ----------------------------

def test_latest_skips_torn_checkpoints(tmp_path):
    """A checkpoint with a truncated shard, a corrupted shard, a missing
    manifest, or a garbage manifest is never selected by latest()."""
    mgr = ckpt.CheckpointManager(str(tmp_path), keep_last=10)
    for step in range(1, 5):
        mgr.snapshot(arrays={"w": np.full((8,), step, "f4")},
                     blobs={"opt": b"state-%d" % step}, step=step,
                     epoch=0, nbatch=step, sync=True)
    mgr.close()
    assert ckpt.latest(str(tmp_path)).endswith("ckpt-0000000004")

    # truncated shard: newest falls back to step 3
    with open(os.path.join(tmp_path, "ckpt-0000000004", "arrays.npk"),
              "r+b") as f:
        f.truncate(max(0, os.path.getsize(f.name) - 7))
    assert ckpt.latest(str(tmp_path)).endswith("ckpt-0000000003")

    # same size but flipped bytes: checksum catches it -> step 2
    shard = os.path.join(tmp_path, "ckpt-0000000003", "opt.bin")
    blob = open(shard, "rb").read()
    with open(shard, "wb") as f:
        f.write(b"X" * len(blob))
    assert ckpt.latest(str(tmp_path)).endswith("ckpt-0000000002")

    # missing manifest -> step 1
    os.remove(os.path.join(tmp_path, "ckpt-0000000002", "manifest.json"))
    assert ckpt.latest(str(tmp_path)).endswith("ckpt-0000000001")

    # garbage manifest -> nothing valid left
    with open(os.path.join(tmp_path, "ckpt-0000000001", "manifest.json"),
              "w") as f:
        f.write("{not json")
    assert ckpt.latest(str(tmp_path)) is None
    with pytest.raises(mx.MXNetError):
        ckpt.load(os.path.join(str(tmp_path), "ckpt-0000000001"))


def test_retention_gc_and_roundtrip(tmp_path):
    mgr = ckpt.CheckpointManager(str(tmp_path), keep_last=2)
    rng = np.random.RandomState(3)
    payloads = {}
    for step in (1, 2, 3, 4, 5):
        payloads[step] = rng.randn(5, 3).astype("f4")
        mgr.snapshot(arrays={"w": payloads[step]}, blobs={"b": b"x" * step},
                     step=step, epoch=step, nbatch=1, sync=True)
    mgr.close()
    names = sorted(n for n in os.listdir(tmp_path) if n.startswith("ckpt-"))
    assert names == ["ckpt-0000000004", "ckpt-0000000005"]
    data = ckpt.load(ckpt.latest(str(tmp_path)))
    assert data.step == 5 and data.epoch == 5 and data.nbatch == 1
    np.testing.assert_array_equal(data.arrays["w"], payloads[5])
    assert data.blobs["b"] == b"x" * 5
    assert data.rng is not None  # RNG streams travel in the manifest


def test_rank_shard_layout(tmp_path):
    """dist layout: non-zero ranks publish side shards; rank 0's atomic
    commit adopts them, and a reader gets them back per rank."""
    w1 = ckpt.CheckpointManager(str(tmp_path), rank=1, num_ranks=2)
    w1.snapshot(arrays={"slice": np.arange(4, dtype="f4")},
                blobs={"opt": b"rank1-opt"}, step=7, sync=True)
    w1.close()
    assert ckpt.latest(str(tmp_path)) is None  # no commit without rank 0

    w0 = ckpt.CheckpointManager(str(tmp_path), rank=0, num_ranks=2)
    w0.snapshot(arrays={"w": np.ones((3,), "f4")}, step=7, sync=True)
    w0.close()
    data = ckpt.load(ckpt.latest(str(tmp_path)))
    shard = data.rank_shard(1)
    np.testing.assert_array_equal(shard["arrays"]["slice"],
                                  np.arange(4, dtype="f4"))
    assert shard["blobs"]["opt"] == b"rank1-opt"
    assert shard["rng"] is not None  # rank-local RNG rides the shard
    assert data.rank_shard(3) is None


def test_ndarray_iter_seek_and_state():
    X = np.arange(40, dtype="f4").reshape(20, 2)
    it = NDArrayIter(X, np.arange(20, dtype="f4"), batch_size=4,
                     shuffle=True)
    batches = [b.data[0].asnumpy().copy() for b in it]
    state = it.checkpoint_state()
    it.set_checkpoint_state(pickle.loads(pickle.dumps(state)), nbatch=3)
    np.testing.assert_array_equal(next(it).data[0].asnumpy(), batches[3])
    # generic reset+skip lands on the same batch (same permutation)
    it.seek(2)
    np.testing.assert_array_equal(next(it).data[0].asnumpy(), batches[2])


# -- save -> resume property (in-process) ------------------------------------

def _fit_toy(ckpt_dir=None, resume=False, crash_at=None, num_epoch=2,
             optimizer="sgd", opt_params=None):
    mx.random.seed(7)
    np.random.seed(7)
    X = np.random.RandomState(1).randn(64, 10).astype("f4")
    y = (np.arange(64) % 4).astype("f4")
    it = NDArrayIter(X, y, batch_size=8, shuffle=True)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())

    class _Crash(Exception):
        pass

    cb = None
    if crash_at is not None:
        hits = {"n": 0}

        def cb(param):
            hits["n"] += 1
            if hits["n"] == crash_at:
                raise _Crash()
    try:
        mod.fit(it, optimizer=optimizer,
                optimizer_params=opt_params or {"learning_rate": 0.1,
                                                "momentum": 0.9},
                num_epoch=num_epoch, checkpoint_dir=ckpt_dir,
                checkpoint_period=1, resume=resume, batch_end_callback=cb)
    except _Crash:
        pass
    return mod


def _states_np(mod):
    out = {}
    for k, s in mod._updater.states.items():
        if s is None:
            out[k] = None
        elif isinstance(s, (tuple, list)):
            out[k] = [x.asnumpy() if x is not None else None for x in s]
        else:
            out[k] = s.asnumpy()
    return out


@pytest.mark.parametrize("optimizer,opt_params,crash_at", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}, 11),
    ("adam", {"learning_rate": 0.01}, 5),
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}, 8),  # epoch boundary
])
def test_save_resume_reproduces_next_steps(monkeypatch, tmp_path,
                                           optimizer, opt_params, crash_at):
    """Property: crash anywhere, resume, and every subsequent step —
    params AND optimizer slots — matches the uninterrupted run exactly
    (shuffled iterator, momentum/Adam state, LR position all restored)."""
    monkeypatch.setenv("MXNET_FUSED_TRAIN_STEP", "0")
    full = _fit_toy(num_epoch=2, optimizer=optimizer, opt_params=opt_params)
    _fit_toy(ckpt_dir=str(tmp_path), crash_at=crash_at, num_epoch=2,
             optimizer=optimizer, opt_params=opt_params)
    assert ckpt.latest(str(tmp_path)) is not None
    resumed = _fit_toy(ckpt_dir=str(tmp_path), resume=True, num_epoch=2,
                       optimizer=optimizer, opt_params=opt_params)
    fa, _ = full.get_params()
    ra, _ = resumed.get_params()
    for k in fa:
        np.testing.assert_array_equal(fa[k].asnumpy(), ra[k].asnumpy(),
                                      err_msg=k)
    sf, sr = _states_np(full), _states_np(resumed)
    assert sf.keys() == sr.keys()
    for k in sf:
        np.testing.assert_array_equal(np.asarray(sf[k]), np.asarray(sr[k]),
                                      err_msg=f"optimizer state {k}")
    assert full._optimizer.num_update == resumed._optimizer.num_update


# -- end-to-end: hard process kill + relaunch --------------------------------

HARNESS = r"""
import os, pickle, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import sym
from incubator_mxnet_tpu.io import NDArrayIter

mode, ckpt_dir, out_path = sys.argv[1], sys.argv[2], sys.argv[3]
KILL_AT = int(os.environ.get("KILL_AT", "11"))

def build():
    d = sym.Variable("data")
    f1 = sym.FullyConnected(d, num_hidden=16, name="fc1")
    a1 = sym.Activation(f1, act_type="relu")
    f2 = sym.FullyConnected(a1, num_hidden=4, name="fc2")
    return sym.SoftmaxOutput(f2, name="softmax")

mx.random.seed(7); np.random.seed(7)
X = np.random.RandomState(1).randn(64, 10).astype("f4")
y = (np.arange(64) % 4).astype("f4")
it = NDArrayIter(X, y, batch_size=8, shuffle=True)
mod = mx.mod.Module(build(), context=mx.cpu())

cb = None
if mode == "crash":
    hits = {"n": 0}
    def cb(param):
        hits["n"] += 1
        if hits["n"] == KILL_AT:
            os._exit(9)   # hard kill: no flush, no atexit, writer may tear
mod.fit(it, optimizer="sgd",
        optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
        num_epoch=2,
        checkpoint_dir=(ckpt_dir if mode != "full" else None),
        checkpoint_period=1, resume=(mode == "resume"),
        batch_end_callback=cb)

states = {}
for k, s in mod._updater.states.items():
    states[k] = None if s is None else s.asnumpy()
arg, aux = mod.get_params()
with open(out_path, "wb") as f:
    pickle.dump({"params": {k: v.asnumpy() for k, v in arg.items()},
                 "states": states,
                 "num_update": mod._optimizer.num_update}, f)
print("DONE")
"""


def _run_harness(script, mode, ckpt_dir, out_path, env_extra=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu", MXNET_FUSED_TRAIN_STEP="0",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH",
                                                             ""))
    env.update(env_extra or {})
    return subprocess.run([sys.executable, str(script), mode,
                           str(ckpt_dir), str(out_path)],
                          env=env, capture_output=True, text=True,
                          timeout=240)


def test_e2e_hard_kill_resume_bit_for_bit(tmp_path):
    """Acceptance gate: train with async checkpointing, hard-kill the
    process (os._exit mid-epoch), relaunch with resume=True — final
    params and optimizer state match the uninterrupted run bit-for-bit
    at the same step count."""
    script = tmp_path / "harness.py"
    script.write_text(HARNESS)
    ckpt_dir = tmp_path / "ckpts"

    full = _run_harness(script, "full", ckpt_dir, tmp_path / "full.pkl")
    assert full.returncode == 0 and "DONE" in full.stdout, full.stdout + \
        full.stderr

    crash = _run_harness(script, "crash", ckpt_dir, tmp_path / "crash.pkl")
    assert crash.returncode == 9, (crash.returncode, crash.stdout,
                                   crash.stderr)
    assert ckpt.latest(str(ckpt_dir)) is not None, \
        "hard kill must leave at least one committed checkpoint"

    resume = _run_harness(script, "resume", ckpt_dir,
                          tmp_path / "resume.pkl")
    assert resume.returncode == 0 and "DONE" in resume.stdout, \
        resume.stdout + resume.stderr

    a = pickle.load(open(tmp_path / "full.pkl", "rb"))
    b = pickle.load(open(tmp_path / "resume.pkl", "rb"))
    assert a["num_update"] == b["num_update"] == 16
    for k in a["params"]:
        np.testing.assert_array_equal(a["params"][k], b["params"][k],
                                      err_msg=k)
    for k in a["states"]:
        np.testing.assert_array_equal(a["states"][k], b["states"][k],
                                      err_msg=f"optimizer state {k}")


# -- preemption hook ---------------------------------------------------------

PREEMPT_HARNESS = r"""
import os, sys, time
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import sym
from incubator_mxnet_tpu.io import NDArrayIter

ckpt_dir = sys.argv[1]
d = sym.Variable("data")
net = sym.SoftmaxOutput(sym.FullyConnected(d, num_hidden=4, name="fc"),
                        name="softmax")
mx.random.seed(0); np.random.seed(0)
X = np.random.randn(64, 6).astype("f4")
y = (np.arange(64) % 4).astype("f4")
it = NDArrayIter(X, y, batch_size=8)
mod = mx.mod.Module(net, context=mx.cpu())
def slow(param):
    time.sleep(0.05)
print("TRAINING", flush=True)
mod.fit(it, optimizer="sgd", num_epoch=1000, checkpoint_dir=ckpt_dir,
        checkpoint_period=100000, batch_end_callback=slow)
print("FINISHED-UNEXPECTEDLY")
"""


def test_preemption_sigterm_takes_final_snapshot(tmp_path):
    """SIGTERM mid-training -> one final synchronous snapshot, exit 143,
    and the committed checkpoint carries the preemption marker."""
    script = tmp_path / "preempt.py"
    script.write_text(PREEMPT_HARNESS)
    ckpt_dir = tmp_path / "ckpts"
    env = dict(os.environ, JAX_PLATFORMS="cpu", MXNET_FUSED_TRAIN_STEP="0",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH",
                                                             ""))
    proc = subprocess.Popen([sys.executable, str(script), str(ckpt_dir)],
                            env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    try:
        deadline = time.time() + 120
        # wait until training is demonstrably underway (first epoch-end
        # snapshot committed), then deliver the eviction notice
        while time.time() < deadline:
            if ckpt.latest(str(ckpt_dir), deep=False) is not None:
                break
            time.sleep(0.2)
        else:
            proc.kill()
            pytest.fail("no checkpoint appeared: " + proc.stdout.read())
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == 143, (proc.returncode, out)
    assert "FINISHED-UNEXPECTEDLY" not in out
    data = ckpt.load(ckpt.latest(str(ckpt_dir)))
    assert data.meta.get("preempted") is True
    assert data.arrays  # params made it out


# -- async overhead ----------------------------------------------------------

def test_async_snapshot_overhead_within_10pct(monkeypatch, tmp_path):
    """Acceptance gate: period=1 async checkpointing costs < 10% wall
    time over the no-checkpoint baseline — background serialization
    actually overlaps the train step.  The toy model is compute-heavy /
    param-light (conv) so the step, not the snapshot write, is the unit
    of work — the regime real training runs in."""
    monkeypatch.setenv("MXNET_FUSED_TRAIN_STEP", "0")

    def convnet():
        d = sym.Variable("data")
        c1 = sym.Convolution(d, kernel=(3, 3), num_filter=16, name="c1")
        a1 = sym.Activation(c1, act_type="relu")
        c2 = sym.Convolution(a1, kernel=(3, 3), num_filter=16, name="c2")
        a2 = sym.Activation(c2, act_type="relu")
        p = sym.Pooling(a2, pool_type="max", kernel=(2, 2), stride=(2, 2))
        f = sym.FullyConnected(sym.Flatten(p), num_hidden=10, name="fc")
        return sym.SoftmaxOutput(f, name="softmax")

    def build_and_fit(ckpt_dir, epochs):
        mx.random.seed(0)
        np.random.seed(0)
        X = np.random.RandomState(0).randn(256, 1, 28, 28).astype("f4")
        y = (np.arange(256) % 10).astype("f4")
        it = NDArrayIter(X, y, batch_size=64)
        mod = mx.mod.Module(convnet(), context=mx.cpu())
        mod.fit(it, optimizer="sgd",
                optimizer_params={"learning_rate": 0.05},
                num_epoch=epochs, checkpoint_dir=ckpt_dir,
                checkpoint_period=1)
        return mod

    def timed(ckpt_dir):
        t0 = time.perf_counter()
        build_and_fit(ckpt_dir, 5)
        return time.perf_counter() - t0

    build_and_fit(None, 1)                      # compile warmup
    # min of two runs per variant: the min is robust to one-off scheduler
    # stalls that a single seconds-long sample is not
    base = min(timed(None), timed(None))
    with_ckpt = min(timed(str(tmp_path)), timed(str(tmp_path / "b")))
    budget = max(0.10 * base, 0.2)
    assert with_ckpt - base < budget, \
        f"checkpoint overhead {with_ckpt - base:.3f}s over base " \
        f"{base:.3f}s exceeds {budget:.3f}s"
    assert ckpt.latest(str(tmp_path)) is not None


# -- gluon estimator handler -------------------------------------------------

def _make_estimator():
    from incubator_mxnet_tpu import gluon
    mx.random.seed(11)
    np.random.seed(11)
    rng = np.random.RandomState(0)
    X = nd.array(rng.randn(64, 10).astype("f4"))
    Y = nd.array((np.arange(64) % 3).astype("f4"))
    loader = gluon.data.DataLoader(gluon.data.ArrayDataset(X, Y),
                                   batch_size=16)
    # fixed prefixes: a resumed PROCESS rebuilds the same names, but within
    # one test process the global name counter would drift between nets
    net = gluon.nn.Sequential(prefix="net_")
    net.add(gluon.nn.Dense(16, activation="relu", prefix="h_"),
            gluon.nn.Dense(3, prefix="out_"))
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01})
    from incubator_mxnet_tpu.gluon.contrib.estimator import Estimator
    from incubator_mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss
    return Estimator(net, SoftmaxCrossEntropyLoss(), trainer=trainer), \
        loader


def test_estimator_elastic_handler_resume(monkeypatch, tmp_path):
    """ElasticCheckpointHandler restores net + trainer + position and
    continues mid-epoch after a crashed estimator run."""
    monkeypatch.setenv("MXNET_FUSED_TRAIN_STEP", "0")

    est_full, loader = _make_estimator()
    est_full.fit(loader, epochs=3, event_handlers=[])

    class Boom(Exception):
        pass

    from incubator_mxnet_tpu.gluon.contrib.estimator import EventHandler

    class CrashAt(EventHandler):
        def __init__(self, at):
            self.at, self.n = at, 0

        def batch_end(self, est):
            self.n += 1
            if self.n == self.at:
                raise Boom()

    est_crash, loader_c = _make_estimator()
    handler = ckpt.ElasticCheckpointHandler(str(tmp_path), period=1,
                                            resume=True,
                                            preemption_hook=False)
    with pytest.raises(Boom):
        est_crash.fit(loader_c, epochs=3,
                      event_handlers=[handler, CrashAt(6)])  # mid epoch 1
    handler.manager.flush()   # the in-flight async write would die with
    data = ckpt.load(ckpt.latest(str(tmp_path)))   # a real process; here
    # the test wants the deterministic newest snapshot
    assert (data.epoch, data.nbatch) == (1, 2)

    est_res, loader_r = _make_estimator()
    handler2 = ckpt.ElasticCheckpointHandler(str(tmp_path), period=1,
                                             resume=True,
                                             preemption_hook=False)
    est_res.fit(loader_r, epochs=3, event_handlers=[handler2])
    assert est_res.epoch == 2

    pf = {k: p.list_data()[0].asnumpy()
          for k, p in est_full.net.collect_params().items()}
    pr = {k: p.list_data()[0].asnumpy()
          for k, p in est_res.net.collect_params().items()}
    for k in pf:
        np.testing.assert_allclose(pf[k], pr[k], rtol=1e-6, atol=1e-7,
                                   err_msg=k)


def test_trainer_checkpoint_state_roundtrip():
    from incubator_mxnet_tpu import gluon
    mx.random.seed(2)
    net = gluon.nn.Dense(4, in_units=6)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    from incubator_mxnet_tpu import autograd
    x = nd.random.uniform(shape=(8, 6))
    for _ in range(3):
        with autograd.record():
            out = net(x)
            loss = (out * out).sum()
        loss.backward()
        trainer.step(8)
    blob = trainer.get_checkpoint_state()
    before = trainer._optimizer.num_update
    for _ in range(2):
        with autograd.record():
            loss = (net(x) ** 2).sum()
        loss.backward()
        trainer.step(8)
    trainer.set_checkpoint_state(blob)
    assert trainer._optimizer.num_update == before
    s0 = trainer._updaters[0].states
    assert s0, "momentum slots restored"


def test_lr_scheduler_state_roundtrip():
    sched = mx.lr_scheduler.FactorScheduler(step=4, factor=0.5)
    sched.base_lr = 0.8
    for i in range(10):
        sched(i)
    state = sched.state_dict()
    fresh = mx.lr_scheduler.FactorScheduler(step=4, factor=0.5)
    fresh.load_state_dict(state)
    assert fresh.base_lr == sched.base_lr and fresh.count == sched.count
    assert fresh(11) == sched(11)


def test_optimizer_state_dict_counters():
    opt = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9)
    w = nd.ones((3,))
    g = nd.ones((3,))
    st = opt.create_state(0, w)
    for _ in range(5):
        opt.update(0, w, g, st)
    d = opt.state_dict()
    assert d["num_update"] == 5
    fresh = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9)
    fresh.load_state_dict(d)
    assert fresh.num_update == 5
    assert fresh._index_update_count == {0: 5}


def test_resume_rebuilds_fused_step_with_restored_optimizer(tmp_path):
    """With the fused train step ON (default), resuming must not leave
    the fused program driving a stale pre-restore optimizer: after
    resume, the fused step, the Updater, and Module agree on ONE
    optimizer whose num_update continues from the checkpoint."""
    full = _fit_toy(num_epoch=2, optimizer="adam",
                    opt_params={"learning_rate": 0.01})
    _fit_toy(ckpt_dir=str(tmp_path), crash_at=11, num_epoch=2,
             optimizer="adam", opt_params={"learning_rate": 0.01})
    resumed = _fit_toy(ckpt_dir=str(tmp_path), resume=True, num_epoch=2,
                       optimizer="adam", opt_params={"learning_rate": 0.01})
    assert resumed._optimizer.num_update == full._optimizer.num_update == 16
    assert resumed._updater.optimizer is resumed._optimizer
    if resumed._fused_step is not None:
        assert resumed._fused_step._opt is resumed._optimizer, \
            "fused step must drive the RESTORED optimizer, not the stale one"


def test_ndarray_iter_roll_over_seek():
    """roll_over epochs start mid-stride (carried samples); seek must
    anchor at the epoch-start cursor, not assume n*batch_size."""
    X = np.arange(20, dtype="f4").reshape(10, 2)
    it = NDArrayIter(X, np.arange(10, dtype="f4"), batch_size=4,
                     shuffle=False, last_batch_handle="roll_over")
    for _ in it:     # consume epoch 1 (leaves a 2-sample carry)
        pass
    it.reset()       # epoch 2 starts with the carried samples
    wanted = [b.data[0].asnumpy().copy() for b in it]
    it.reset()
    state = it.checkpoint_state()
    it2 = NDArrayIter(X, np.arange(10, dtype="f4"), batch_size=4,
                      shuffle=False, last_batch_handle="roll_over")
    for _ in it2:
        pass
    it2.reset()
    it2.set_checkpoint_state(state, nbatch=1)
    np.testing.assert_array_equal(next(it2).data[0].asnumpy(), wanted[1])


def test_fresh_run_refuses_dir_with_old_checkpoints(tmp_path):
    """resume=False into a directory holding another run's checkpoints
    must fail loudly: the old run's higher step numbers would otherwise
    win latest() after this run's first crash and resume the ABANDONED
    run silently."""
    _fit_toy(ckpt_dir=str(tmp_path), num_epoch=1)
    assert ckpt.latest(str(tmp_path)) is not None
    with pytest.raises(mx.MXNetError, match="previous run"):
        _fit_toy(ckpt_dir=str(tmp_path), num_epoch=1)
    # resume=True is the sanctioned way to keep going
    _fit_toy(ckpt_dir=str(tmp_path), resume=True, num_epoch=2)
