"""Internal NHWC execution layout (ops/layout.py + the executor's layout
pass): results must match NCHW execution exactly — the pass only changes
the layout convolution/pooling/batchnorm execute in, never semantics.
(Reference counterpart: cuDNN/MKLDNN layout selection,
`src/operator/nn/mkldnn/mkldnn_base-inl.h`.)"""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import sym, nd


def _conv_graph():
    data = sym.Variable("data")
    h = sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                        name="c1")
    h = sym.BatchNorm(h, name="bn1")
    h = sym.Activation(h, act_type="relu")
    h = sym.Pooling(h, kernel=(2, 2), stride=(2, 2), pool_type="max")
    s = sym.Convolution(data, kernel=(1, 1), num_filter=8, stride=(2, 2),
                        name="ds")
    h = h + s                      # NHWC-tagged shortcut add
    h = sym.Pooling(h, global_pool=True, pool_type="avg")
    h = sym.Flatten(h)
    h = sym.FullyConnected(h, num_hidden=5, name="fc")
    return sym.SoftmaxOutput(h, name="softmax")


@pytest.mark.parametrize("train", [True, False])
def test_nhwc_pass_matches_nchw(monkeypatch, train):
    out = _conv_graph()
    rng = np.random.RandomState(0)
    shapes = {"data": (4, 3, 16, 16), "softmax_label": (4,)}

    def run(layout):
        monkeypatch.setenv("MXNET_INTERNAL_CONV_LAYOUT", layout)
        mx.random.seed(0)
        exe = out.simple_bind(mx.cpu(), grad_req="write" if train else "null",
                              **shapes)
        for name, arr in exe.arg_dict.items():
            # stable per-name seed: builtin hash() is randomized per
            # process (PYTHONHASHSEED), and unlucky draws made this
            # tolerance comparison flaky (~25% of hash seeds)
            import zlib
            r = np.random.RandomState(zlib.crc32(name.encode()) % (2**31))
            if name == "softmax_label":
                arr[:] = nd.array(r.randint(0, 5, arr.shape).astype("f4"))
            else:
                arr[:] = nd.array(r.randn(*arr.shape).astype("f4") * 0.1)
        outs = exe.forward(is_train=train)
        res = [o.asnumpy() for o in outs]
        grads = []
        if train:
            exe.backward(out_grads=None)
            grads = [exe.grad_dict[n].asnumpy()
                     for n in sorted(exe.grad_dict)
                     if exe.grad_dict[n] is not None]
        return res, grads

    (o_nchw, g_nchw) = run("NCHW")
    (o_nhwc, g_nhwc) = run("NHWC")
    for a, b in zip(o_nchw, o_nhwc):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)
    for a, b in zip(g_nchw, g_nhwc):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


def test_nhwc_module_fit_parity(monkeypatch):
    """A small conv Module trains to the same weights under both layouts."""
    from incubator_mxnet_tpu import io

    def run(layout):
        monkeypatch.setenv("MXNET_INTERNAL_CONV_LAYOUT", layout)
        mx.random.seed(0)
        net = _conv_graph()
        mod = mx.mod.Module(net, context=mx.cpu(),
                            label_names=("softmax_label",))
        rng = np.random.RandomState(1)
        x = rng.rand(16, 3, 16, 16).astype("f4")
        y = rng.randint(0, 5, 16).astype("f4")
        it = io.NDArrayIter(x, y, batch_size=8, label_name="softmax_label")
        mod.fit(it, num_epoch=2, optimizer="sgd",
                optimizer_params={"learning_rate": 0.1},
                initializer=mx.initializer.Xavier(), kvstore=None)
        args, _ = mod.get_params()
        return {k: v.asnumpy() for k, v in args.items()}

    w_nchw = run("NCHW")
    w_nhwc = run("NHWC")
    for k in w_nchw:
        np.testing.assert_allclose(w_nchw[k], w_nhwc[k], rtol=1e-4,
                                   atol=1e-5, err_msg=k)
