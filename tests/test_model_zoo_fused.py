"""Model-zoo architectures through the fused Gluon train step: every
family must either fuse (one donated program) or fall back transparently
(dropout nets), and in both cases train one step to finite params.
Covers depthwise convolutions (mobilenet), dense concatenation
(densenet), plain stacks (resnet v2), and dropout classifiers (alexnet)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, nd


@pytest.mark.parametrize("name,size,expect_fused", [
    ("mobilenet0.25", 64, True),     # depthwise conv path
    ("resnet18_v2", 32, True),       # pre-activation residual
    ("squeezenet1.0", 64, False),    # dropout classifier -> eager fallback
])
def test_zoo_family_trains_one_fused_step(name, size, expect_fused):
    from incubator_mxnet_tpu.gluon.model_zoo import vision

    mx.random.seed(0)
    net = vision.get_model(name, classes=10)
    net.initialize(mx.initializer.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05})
    est = gluon.contrib.estimator.Estimator(
        net, gluon.loss.SoftmaxCrossEntropyLoss(),
        train_metrics=[mx.metric.Accuracy()], trainer=trainer)
    rng = np.random.RandomState(0)
    data = nd.array(rng.rand(4, 3, size, size).astype("f4"))
    label = nd.array(rng.randint(0, 10, 4).astype("f4"))
    # two steps: step 1 materializes deferred params (eager), step 2 can fuse
    est.fit(iter([(data, label)] * 3), epochs=1, event_handlers=[])
    if expect_fused:
        assert est._fused is not None and not est._fused.broken and \
            est._fused._carry is not None, f"{name} must run fused"
    for p in net.collect_params().values():
        assert np.isfinite(p.data().asnumpy()).all(), p.name
