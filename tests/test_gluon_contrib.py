"""gluon.contrib tests (reference
tests/python/unittest/test_gluon_contrib.py)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, autograd, gluon
from incubator_mxnet_tpu.gluon import contrib


def test_conv_lstm_cell():
    cell = contrib.rnn.Conv2DLSTMCell(input_shape=(4, 8, 8),
                                      hidden_channels=6,
                                      i2h_kernel=(3, 3), h2h_kernel=(3, 3),
                                      i2h_pad=(1, 1))
    cell.initialize()
    x = nd.random.uniform(shape=(2, 4, 8, 8))
    states = cell.begin_state(batch_size=2)
    out, new_states = cell(x, states)
    assert out.shape == (2, 6, 8, 8)
    assert [s.shape for s in new_states] == [(2, 6, 8, 8)] * 2
    # unroll + gradient flows
    seq = nd.random.uniform(shape=(2, 3, 4, 8, 8))
    for p in cell.collect_params().values():
        p.grad_req = "write"
    with autograd.record():
        outputs, _ = cell.unroll(3, seq, layout="NTC", merge_outputs=True)
        loss = nd.sum(outputs)
    loss.backward()
    g = cell.i2h_weight.grad()
    assert np.abs(g.asnumpy()).sum() > 0


def test_conv_gru_and_rnn_cells():
    for cls, n_states in [(contrib.rnn.Conv2DGRUCell, 1),
                          (contrib.rnn.Conv2DRNNCell, 1)]:
        cell = cls(input_shape=(3, 6, 6), hidden_channels=4,
                   i2h_kernel=(3, 3), h2h_kernel=(3, 3), i2h_pad=(1, 1))
        cell.initialize()
        x = nd.random.uniform(shape=(2, 3, 6, 6))
        out, states = cell(x, cell.begin_state(batch_size=2))
        assert out.shape == (2, 4, 6, 6)
        assert len(states) == n_states


def test_conv1d_lstm_cell():
    cell = contrib.rnn.Conv1DLSTMCell(input_shape=(2, 10),
                                      hidden_channels=3,
                                      i2h_kernel=(3,), h2h_kernel=(3,),
                                      i2h_pad=(1,))
    cell.initialize()
    x = nd.random.uniform(shape=(2, 2, 10))
    out, _ = cell(x, cell.begin_state(batch_size=2))
    assert out.shape == (2, 3, 10)


def test_variational_dropout_cell():
    base = gluon.rnn.LSTMCell(8, input_size=5)
    cell = contrib.rnn.VariationalDropoutCell(base, drop_inputs=0.3,
                                              drop_outputs=0.3)
    cell.initialize()
    x = nd.random.uniform(shape=(4, 6, 5))
    with autograd.record(train_mode=True):
        outputs, _ = cell.unroll(6, x, layout="NTC", merge_outputs=False)
    # locked mask: the same units are dropped at every timestep
    o0 = outputs[0].asnumpy()
    o1 = outputs[1].asnumpy()
    dropped0 = set(zip(*np.where(o0 == 0)))
    # checking exact dropped-unit persistence across steps is too
    # strict (cell outputs can be zero); instead check determinism of the
    # mask by correlation of zero patterns
    assert outputs[0].shape == (4, 8)
    assert len(outputs) == 6


def test_lstmp_cell():
    cell = contrib.rnn.LSTMPCell(hidden_size=16, projection_size=6,
                                 input_size=5)
    cell.initialize()
    x = nd.random.uniform(shape=(3, 5))
    out, states = cell(x, cell.begin_state(batch_size=3))
    assert out.shape == (3, 6)                 # projected
    assert states[0].shape == (3, 6)
    assert states[1].shape == (3, 16)          # cell state unprojected


def test_concurrent_and_identity():
    net = contrib.nn.HybridConcurrent(axis=1)
    net.add(gluon.nn.Dense(4), gluon.nn.Dense(6), contrib.nn.Identity())
    net.initialize()
    x = nd.random.uniform(shape=(2, 3))
    out = net(x)
    assert out.shape == (2, 4 + 6 + 3)


def test_pixel_shuffle2d():
    ps = contrib.nn.PixelShuffle2D((2, 2))
    x = nd.array(np.arange(2 * 8 * 3 * 3, dtype="f4")
                 .reshape(2, 8, 3, 3))
    out = ps(x)
    assert out.shape == (2, 2, 6, 6)
    # parity with the numpy reference implementation
    xn = x.asnumpy().reshape(2, 2, 2, 2, 3, 3)
    ref = xn.transpose(0, 1, 4, 2, 5, 3).reshape(2, 2, 6, 6)
    np.testing.assert_array_equal(out.asnumpy(), ref)


def test_sync_batchnorm_matches_batchnorm():
    bn = contrib.nn.SyncBatchNorm(in_channels=4)
    bn.initialize()
    x = nd.random.uniform(shape=(2, 4, 5, 5))
    out = bn(x)
    assert out.shape == x.shape


def test_interval_sampler():
    s = contrib.data.IntervalSampler(10, 3)
    assert list(s) == [0, 3, 6, 9, 1, 4, 7, 2, 5, 8]
    s2 = contrib.data.IntervalSampler(10, 3, rollover=False)
    assert list(s2) == [0, 3, 6, 9]


def test_estimator_with_handlers(tmp_path):
    from incubator_mxnet_tpu.gluon.contrib.estimator import (
        Estimator, EarlyStoppingHandler, LoggingHandler, CheckpointHandler)

    rng = np.random.RandomState(0)
    X = nd.array(rng.randn(64, 10).astype("f4"))
    W = rng.randn(10, 3).astype("f4")
    Y = nd.array((rng.randn(64, 10) @ W).argmax(1).astype("f4"))
    dataset = gluon.data.ArrayDataset(X, Y)
    loader = gluon.data.DataLoader(dataset, batch_size=16)

    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(3))
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01})
    est = Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                    trainer=trainer)
    est.fit(loader, val_data=loader, epochs=3,
            event_handlers=[LoggingHandler(),
                            CheckpointHandler(str(tmp_path), monitor=None),
                            EarlyStoppingHandler("accuracy", mode="max",
                                                 patience=10)])
    assert est.epoch == 2
    import os
    assert os.path.exists(str(tmp_path / "model-epoch0.params"))


def test_pixel_shuffle_1d_3d():
    ps1 = contrib.nn.PixelShuffle1D(2)
    x1 = nd.array(np.arange(2 * 4 * 3, dtype="f4").reshape(2, 4, 3))
    out1 = ps1(x1)
    assert out1.shape == (2, 2, 6)
    xn = x1.asnumpy().reshape(2, 2, 2, 3)
    ref1 = xn.transpose(0, 1, 3, 2).reshape(2, 2, 6)
    np.testing.assert_array_equal(out1.asnumpy(), ref1)

    ps3 = contrib.nn.PixelShuffle3D((2, 2, 2))
    x3 = nd.array(np.arange(1 * 8 * 2 * 2 * 2, dtype="f4")
                  .reshape(1, 8, 2, 2, 2))
    out3 = ps3(x3)
    assert out3.shape == (1, 1, 4, 4, 4)
    xn3 = x3.asnumpy().reshape(1, 1, 2, 2, 2, 2, 2, 2)
    ref3 = xn3.transpose(0, 1, 5, 2, 6, 3, 7, 4).reshape(1, 1, 4, 4, 4)
    np.testing.assert_array_equal(out3.asnumpy(), ref3)


def test_estimator_metric_with_args():
    from incubator_mxnet_tpu.gluon.contrib.estimator import Estimator
    est = Estimator(gluon.nn.Dense(3), gluon.loss.SoftmaxCrossEntropyLoss(),
                    train_metrics=mx.metric.TopKAccuracy(top_k=5))
    assert est.val_metrics[0].get()[0] == est.train_metrics[0].get()[0]
