"""LogMetricsCallback bridge test (reference contrib/tensorboard.py)."""
import json
import os

import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.contrib.tensorboard import LogMetricsCallback


def test_log_metrics_callback(tmp_path):
    cb = LogMetricsCallback(str(tmp_path), prefix="train")
    metric = mx.metric.Accuracy()
    metric.update([mx.nd.array([1.0, 0.0])],
                  [mx.nd.array([[0.1, 0.9], [0.8, 0.2]])])
    from incubator_mxnet_tpu.model import BatchEndParam
    for i in range(3):
        cb(BatchEndParam(epoch=0, nbatch=i, eval_metric=metric, locals=None))
    cb.close()
    events = [json.loads(l) for l in
              open(tmp_path / "events.jsonl")] if \
        (tmp_path / "events.jsonl").exists() else None
    if events is not None:              # jsonl fallback path
        assert len(events) == 3
        assert events[0]["tag"] == "train-accuracy"
        assert events[0]["value"] == 1.0


def test_profiler_memory_eventing(tmp_path):
    """profile_memory: PJRT memory counters land in the dumped trace as
    Memory:* counter events (reference storage_profiler.h role)."""
    import json
    from incubator_mxnet_tpu import profiler

    out = tmp_path / "prof.json"
    profiler.set_config(profile_memory=True, filename=str(out))
    try:
        got = profiler.record_memory("unit")
        profiler.dump()
        data = json.loads(out.read_text())
        mems = [e for e in data["traceEvents"] if e.get("cat") == "memory"]
        if got is not None:
            assert mems and mems[-1]["args"]["bytes_in_use"] >= 0
        else:
            # the CPU backend reports no counters: clean None, no event
            assert mems == []
    finally:
        profiler.set_config(profile_memory=False, filename="profile.json")
