"""LogMetricsCallback bridge test (reference contrib/tensorboard.py)."""
import json
import os

import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.contrib.tensorboard import LogMetricsCallback


def test_log_metrics_callback(tmp_path):
    cb = LogMetricsCallback(str(tmp_path), prefix="train")
    metric = mx.metric.Accuracy()
    metric.update([mx.nd.array([1.0, 0.0])],
                  [mx.nd.array([[0.1, 0.9], [0.8, 0.2]])])
    from incubator_mxnet_tpu.model import BatchEndParam
    for i in range(3):
        cb(BatchEndParam(epoch=0, nbatch=i, eval_metric=metric, locals=None))
    cb.close()
    events = [json.loads(l) for l in
              open(tmp_path / "events.jsonl")] if \
        (tmp_path / "events.jsonl").exists() else None
    if events is not None:              # jsonl fallback path
        assert len(events) == 3
        assert events[0]["tag"] == "train-accuracy"
        assert events[0]["value"] == 1.0
