"""Subgraph partition framework + Pallas fused-kernel tests (reference
tests/python/unittest/test_subgraph_op.py strategy: partitioned graph is
numerically identical to the original)."""
import os

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, subgraph


def _mlp():
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    h = mx.sym.Activation(h, act_type="relu", name="relu1")
    h = mx.sym.FullyConnected(h, num_hidden=8, name="fc2")
    h = mx.sym.Activation(h, act_type="relu", name="relu2")
    return mx.sym.FullyConnected(h, num_hidden=4, name="fc3")


def _run(sym, x, args, grad=False):
    exe = sym.simple_bind(ctx=mx.cpu(), grad_req="write" if grad else "null",
                          data=x.shape)
    exe.copy_params_from(args, {})
    out = exe.forward(is_train=grad, data=nd.array(x))[0]
    if not grad:
        return out.asnumpy(), None
    exe.backward(nd.ones(out.shape))
    return out.asnumpy(), {k: v.asnumpy() for k, v in
                           exe.grad_dict.items() if v is not None}


def _init(sym, shape):
    rng = np.random.RandomState(0)
    arg_shapes, _, _ = sym.infer_shape(data=shape)
    return {n: nd.array(rng.normal(0, 0.5, s).astype("f4"))
            for n, s in zip(sym.list_arguments(), arg_shapes)
            if n != "data"}


def test_partition_replaces_chains():
    sym = _mlp()
    part = subgraph.partition_graph(sym, "TPU_PALLAS")
    js = part.tojson()
    assert js.count("_sg_pallas_fc_relu") == 2          # fc1/relu1, fc2/relu2
    assert "relu1" not in [n for n in part.get_internals().list_outputs()]
    # same parameter surface
    assert set(part.list_arguments()) == set(sym.list_arguments())


def test_partitioned_forward_and_grad_match():
    sym = _mlp()
    x = np.random.RandomState(1).normal(0, 1, (8, 10)).astype("f4")
    args = _init(sym, x.shape)
    ref_out, ref_grads = _run(sym, x, args, grad=True)
    part = subgraph.partition_graph(sym, "TPU_PALLAS")
    out, grads = _run(part, x, args, grad=True)
    np.testing.assert_allclose(out, ref_out, rtol=1e-5, atol=1e-5)
    for k in ref_grads:
        np.testing.assert_allclose(grads[k], ref_grads[k], rtol=1e-4,
                                   atol=1e-5, err_msg=k)


def test_convexity_guard():
    """A chain whose interior feeds an outside consumer must NOT fuse."""
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    relu = mx.sym.Activation(fc, act_type="relu")
    out = relu + fc                     # fc has a second consumer
    part = subgraph.partition_graph(out, "TPU_PALLAS")
    assert "_sg_pallas_fc_relu" not in part.tojson()


def test_env_var_bind_partition():
    sym = _mlp()
    x = np.random.RandomState(2).normal(0, 1, (4, 10)).astype("f4")
    args = _init(sym, x.shape)
    ref, _ = _run(sym, x, args)
    os.environ["MXNET_SUBGRAPH_BACKEND"] = "TPU_PALLAS"
    try:
        got, _ = _run(sym, x, args)
    finally:
        del os.environ["MXNET_SUBGRAPH_BACKEND"]
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_custom_property_registration():
    class NoopProp(subgraph.SubgraphProperty):
        name = "NOOP_TEST"

    subgraph.register_subgraph_property(NoopProp())
    assert "NOOP_TEST" in subgraph.list_backends()
    sym = _mlp()
    part = subgraph.partition_graph(sym, "NOOP_TEST")
    assert part.tojson() == sym.tojson()
    with pytest.raises(mx.MXNetError):
        subgraph.get_subgraph_property("NOT_REGISTERED")
