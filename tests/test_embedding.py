"""mxembed: the sharded sparse-embedding tier (ISSUE-19 gates).

Covers: partition correctness (range interval math + splitmix64 hash
balance), seeded deterministic shard init, push/pull round trips with
duplicate-id pre-aggregation, bit-identical parity between the
shard-side lazy optimizer step and a local row-sparse reference (SGD
momentum and Adam), the device-resident hot-row LRU cache (hits,
misses, evictions, refresh-resident-only, capacity overflow, ZERO
steady-state recompiles via program counts), structured shard-loss
diagnosis (`ServerLostError` naming the shard + owned rows; a server
that restarted empty), `replace_shard` recovery, chunked
checkpoint/restore bit-identity, Module.fit training through the
`EmbeddingFitAdapter`, the gluon `SparseEmbedding` autograd leaf with
exact duplicate-id updates, serving fan-out through `ReplicaRouter`
with mid-traffic shard failover and zero lost admitted requests, the
kvstore factory surfaces, the embedding cost model, and the
`embedding.*` obs namespace + `embedding.lookup` trace spans.
"""
import threading

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import embedding as mxembed
from incubator_mxnet_tpu import io, sym
from incubator_mxnet_tpu.base import MXNetError
from incubator_mxnet_tpu.embedding import (EmbeddingFitAdapter,
                                           EmbeddingServingPath,
                                           HotRowCache, ShardedEmbedding,
                                           shard_of_ids)
from incubator_mxnet_tpu.resilience import ServerLostError


@pytest.fixture(autouse=True)
def fast_failover(monkeypatch):
    """Shard-death diagnosis in well under a second (prod defaults wait
    seconds per reconnect so a GC pause is not declared a death)."""
    monkeypatch.setenv("MXNET_PS_RECONNECT_WAIT", "0.05")
    monkeypatch.setenv("MXNET_PS_MAX_RETRIES", "2")
    monkeypatch.setenv("MXNET_EMBED_BREAKER_THRESHOLD", "2")


def _spawn(n):
    from incubator_mxnet_tpu.dist.server import ParameterServer
    return [ParameterServer(num_workers=1).start() for _ in range(n)]


def _addrs(servers):
    return [("127.0.0.1", s.port) for s in servers]


def _teardown(table, servers):
    table.close()
    for s in servers:
        try:
            s.shutdown()
        except Exception:
            pass


# -- partitioning -------------------------------------------------------------

def test_shard_of_ids_range_partition():
    ids = np.arange(100)
    shards = shard_of_ids(ids, 100, 3, "range")
    # contiguous ps-lite value ranges: [0,33) [33,66) [66,100)
    assert (shards == np.repeat([0, 1, 2], [33, 33, 34])).all()
    # monotone: range partitioning preserves locality
    assert (np.diff(shards) >= 0).all()


def test_shard_of_ids_hash_partition_balanced_and_stable():
    ids = np.arange(10_000)
    shards = shard_of_ids(ids, 10_000, 4, "hash")
    assert shards.min() >= 0 and shards.max() < 4
    counts = np.bincount(shards, minlength=4)
    # splitmix64 spreads sequential hot ids: every shard within 20%
    assert counts.min() > 0.8 * 10_000 / 4
    # deterministic across calls (workers and servers must agree)
    assert (shards == shard_of_ids(ids, 10_000, 4, "hash")).all()


def test_unknown_partition_rejected():
    with pytest.raises(MXNetError, match="unknown partition"):
        ShardedEmbedding("t", 10, 2, [("127.0.0.1", 1)],
                         partition="modulo")


# -- init / pull --------------------------------------------------------------

@pytest.mark.parametrize("partition", ["range", "hash"])
def test_seeded_init_deterministic_and_init_values(partition):
    servers = _spawn(2)
    init = np.arange(40, dtype=np.float32).reshape(10, 4)
    t1 = ShardedEmbedding("det", 10, 4, _addrs(servers), seed=11,
                          partition=partition, cache_rows=0)
    a = t1.pull_rows(np.arange(10))
    servers2 = _spawn(2)
    t2 = ShardedEmbedding("det", 10, 4, _addrs(servers2), seed=11,
                          partition=partition, cache_rows=0)
    b = t2.pull_rows(np.arange(10))
    # same seed -> bit-identical rows regardless of process/server set
    assert np.array_equal(a, b)
    t3 = ShardedEmbedding("det2", 10, 4, _addrs(servers), seed=12,
                          partition=partition, cache_rows=0)
    assert not np.array_equal(a, t3.pull_rows(np.arange(10)))
    t4 = ShardedEmbedding("explicit", 10, 4, _addrs(servers),
                          partition=partition, cache_rows=0,
                          init_values=init)
    assert np.array_equal(t4.pull_rows(np.arange(10)), init)
    _teardown(t1, [])
    _teardown(t3, [])
    _teardown(t4, servers)
    _teardown(t2, servers2)


def test_lookup_shape_and_cache_hotness():
    servers = _spawn(2)
    table = ShardedEmbedding("shape", 64, 8, _addrs(servers), seed=3,
                             cache_rows=32)
    ids = np.array([[1, 40], [5, 1]])
    out = table.lookup(ids, out_np=True)
    assert out.shape == (2, 2, 8)
    # duplicate id 1 returns the same row both places
    assert np.array_equal(out[0, 0], out[1, 1])
    pulled_before = sum(table._pulled)
    again = table.lookup(ids, out_np=True)
    assert np.array_equal(again, out)
    # second lookup is fully cache-hot: no shard traffic at all
    assert sum(table._pulled) == pulled_before
    assert table.stats()["cache"]["hit_rate"] > 0
    _teardown(table, servers)


# -- training updates ---------------------------------------------------------

def test_push_grad_sgd_with_duplicate_id_aggregation():
    servers = _spawn(1)
    init = np.zeros((8, 2), dtype=np.float32)
    table = ShardedEmbedding("sgd", 8, 2, _addrs(servers), cache_rows=0,
                             init_values=init,
                             optimizer=mx.optimizer.SGD(learning_rate=0.5,
                                                        momentum=0.0))
    ids = np.array([3, 5, 3])            # id 3 appears twice
    grads = np.ones((3, 2), dtype=np.float32)
    table.push_grad(ids, grads)
    out = table.pull_rows(np.arange(8))
    # duplicates pre-sum: id 3 moves by -lr*2, id 5 by -lr*1
    assert np.allclose(out[3], -1.0)
    assert np.allclose(out[5], -0.5)
    assert np.allclose(out[[0, 1, 2, 4, 6, 7]], 0.0)
    # assign AFTER a lazy push (checkpoint restore over updated rows)
    table.assign_rows([3], np.full((1, 2), 7.0, dtype=np.float32))
    assert np.allclose(table.pull_rows([3]), 7.0)
    _teardown(table, servers)


@pytest.mark.parametrize("make_opt", [
    lambda: mx.optimizer.SGD(learning_rate=0.1, momentum=0.9),
    lambda: mx.optimizer.Adam(learning_rate=0.01),
], ids=["sgd_momentum", "adam"])
def test_shard_side_lazy_update_matches_local_reference(make_opt):
    """The shard applies optimizer.py's lazy row-sparse path on its
    local slice — bit-identical to the same updates run locally."""
    from incubator_mxnet_tpu.ndarray.sparse import RowSparseNDArray
    rng = np.random.RandomState(5)
    init = rng.randn(12, 3).astype(np.float32)
    servers = _spawn(1)
    table = ShardedEmbedding("parity", 12, 3, _addrs(servers),
                             cache_rows=0, init_values=init,
                             optimizer=make_opt())
    ref_w = mx.nd.array(init.copy())
    ref_upd = mx.optimizer.get_updater(make_opt())
    for step in range(3):
        ids = np.array([1, 7, 4])
        vals = rng.randn(3, 3).astype(np.float32)
        table.push_grad(ids, vals)
        ref_upd("embed:parity",
                RowSparseNDArray(vals, ids, (12, 3)), ref_w)
    assert np.array_equal(table.pull_rows(np.arange(12)),
                          ref_w.asnumpy())
    _teardown(table, servers)


def test_push_without_optimizer_is_structured_error():
    servers = _spawn(1)
    table = ShardedEmbedding("noopt", 4, 2, _addrs(servers), cache_rows=0)
    with pytest.raises(MXNetError, match="set_optimizer"):
        table.push_grad([1], np.ones((1, 2), dtype=np.float32))
    # op='assign' needs no optimizer (checkpoint restore path)
    table.assign_rows([1], np.full((1, 2), 9.0, dtype=np.float32))
    assert np.allclose(table.pull_rows([1]), 9.0)
    _teardown(table, servers)


def test_partition_disagreement_is_structured_error():
    servers = _spawn(2)
    table = ShardedEmbedding("oob", 10, 2, _addrs(servers), cache_rows=0)
    with pytest.raises(MXNetError, match="partition rules disagree"):
        # shard 0 owns [0,5): asking it for row 9 is a protocol bug
        table._request(0, {"cmd": "embed_pull", "table": "oob",
                           "ids": np.array([9])})
    _teardown(table, servers)


# -- hot-row cache ------------------------------------------------------------

def test_cache_hits_misses_evictions_and_lru_order():
    pulls = []

    def pull(ids):
        pulls.append(list(ids))
        return np.repeat(np.asarray(ids, np.float32)[:, None], 2, axis=1)

    c = HotRowCache(dim=2, capacity=3, name="t")
    rows, h, m = c.lookup(np.array([1, 2, 1]), pull)
    # occurrence accounting against batch-start residency: all three
    # occurrences missed (id 1 was not resident when the batch arrived)
    assert (h, m) == (0, 3)
    assert pulls == [[1, 2]]                 # distinct ids pulled once
    assert np.allclose(np.asarray(rows), [[1, 1], [2, 2], [1, 1]])
    c.lookup(np.array([3]), pull)            # cache now full: 1,2,3
    c.lookup(np.array([1]), pull)            # refresh 1 -> LRU is 2
    _, _, m = c.lookup(np.array([4]), pull)  # evicts 2
    assert m == 1
    st = c.stats()
    assert st["evictions"] == 1 and st["rows"] == 3
    _, _, m2 = c.lookup(np.array([3, 1, 4]), pull)   # all resident
    assert m2 == 0
    _, _, m3 = c.lookup(np.array([2]), pull)         # 2 was evicted
    assert m3 == 1
    assert 0 < c.stats()["hit_rate"] < 1


def test_cache_refresh_updates_resident_rows_only():
    c = HotRowCache(dim=2, capacity=4, name="t")
    c.insert([1, 2], np.zeros((2, 2), np.float32))
    c.refresh(np.array([2, 9]), np.ones((2, 2), np.float32))
    rows, _, m = c.lookup(np.array([1, 2]), None)   # both resident
    assert m == 0
    assert np.allclose(np.asarray(rows), [[0, 0], [1, 1]])
    # 9 was NOT pinned: a push must not cache rows nobody looked up
    assert c.stats()["rows"] == 2


def test_cache_capacity_overflow_is_explicit():
    c = HotRowCache(dim=2, capacity=2, name="t")
    with pytest.raises(ValueError, match="MXNET_EMBED_CACHE_ROWS"):
        c.lookup(np.array([1, 2, 3]),
                 lambda ids: np.zeros((len(ids), 2), np.float32))


def test_cache_overflow_with_resident_rows_raises_instead_of_looping():
    """Batch distinct > capacity while the MISSES alone fit used to
    livelock: the insert evicted the batch's own pinned rows, the
    post-insert check failed, and the re-pull looped forever hammering
    the shards.  The guard is on the whole batch, and pull_fn must not
    run at all."""
    pulls = []

    def pull(ids):
        pulls.append(list(ids))
        return np.repeat(np.asarray(ids, np.float32)[:, None], 2, axis=1)

    c = HotRowCache(dim=2, capacity=4, name="t")
    c.lookup(np.array([0, 1, 2]), pull)      # warm: [0,1,2] resident
    pulls.clear()
    with pytest.raises(ValueError, match="MXNET_EMBED_CACHE_ROWS"):
        c.lookup(np.arange(6), pull)         # 6 distinct, 3 misses
    assert pulls == []                       # no PS traffic, no retry


def test_cache_concurrent_lookups_return_correct_rows():
    """Disjoint hot sets churning a too-small cache from three threads:
    every lookup must still return exactly its own rows (the gather is
    dispatched under the lock so a racing insert can't swap the buffer
    between slot validation and the gather), and the bounded retry
    falls back to an uncached pull rather than spinning."""
    c = HotRowCache(dim=1, capacity=8, name="t")

    def pull(ids):
        return np.asarray(ids, np.float32)[:, None]

    errs = []

    def worker(base):
        try:
            rng = np.random.RandomState(base)
            for _ in range(60):
                ids = rng.randint(base, base + 100, size=6)
                rows, _, _ = c.lookup(ids, pull)
                got = np.asarray(rows)[:, 0]
                assert np.array_equal(got, ids.astype(np.float32)), \
                    f"lookup({ids}) returned rows for {got}"
        except Exception as e:               # pragma: no cover - failure
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(b,))
               for b in (0, 1000, 2000)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs[:1]


def test_cache_steady_state_has_zero_recompiles():
    """Fixed batch shape in steady state replays ONE executable: the
    padded gather/scatter signature set stops growing (the
    run_embed_bench zero-recompile gate)."""
    rng = np.random.RandomState(0)

    def pull(ids):
        return rng.randn(len(ids), 4).astype(np.float32)

    c = HotRowCache(dim=4, capacity=64, name="t")
    hot = rng.randint(0, 256, size=24)
    c.lookup(hot, pull)                      # cold fill compiles both
    warm = c.program_count()
    for _ in range(20):                      # steady state: all hits
        _, _, m = c.lookup(hot, pull)
        assert m == 0
    assert c.program_count() == warm
    # mixed cold traffic compiles at most the pow2 ladder, never per-batch
    for _ in range(40):
        c.lookup(rng.randint(0, 4096, size=24), pull)
    assert c.program_count() <= 2 * (int(np.log2(64)) + 1)


# -- failure semantics --------------------------------------------------------

def test_dead_shard_raises_server_lost_naming_shard_and_rows():
    servers = _spawn(2)
    table = ShardedEmbedding("loss", 100, 2, _addrs(servers),
                             cache_rows=0)
    servers[1]._simulate_crash()
    with pytest.raises(ServerLostError) as ei:
        table.pull_rows(np.array([80]))      # shard 1 owns [50,100)
    err = ei.value
    assert err.server == 1
    assert "loss[50:100]" in str(err.keys)
    # the healthy shard keeps serving through the other's death
    assert table.pull_rows(np.array([10])).shape == (1, 2)
    assert table.stats()["shards"]["1"]["breaker"] == "open"
    _teardown(table, servers)


def test_restarted_empty_shard_is_diagnosed():
    """A shard that answers but forgot an initialized table restarted
    empty — that is a data-loss ServerLostError, not a soft retry."""
    servers = _spawn(1)
    table = ShardedEmbedding("amnesia", 10, 2, _addrs(servers),
                             cache_rows=0)
    fresh = _spawn(1)
    from incubator_mxnet_tpu.dist.transport import Channel
    old = table._chans[0]
    table._chans[0] = Channel("127.0.0.1", fresh[0].port)
    with pytest.raises(ServerLostError, match="restarted without state"):
        table.pull_rows(np.array([1]))
    old.close()
    _teardown(table, servers + fresh)


def test_replace_shard_restores_rows_and_serving():
    servers = _spawn(2)
    table = ShardedEmbedding("heal", 20, 2, _addrs(servers), seed=4,
                             cache_rows=8,
                             optimizer=mx.optimizer.SGD(learning_rate=0.1))
    table.push_grad(np.array([3, 15]),
                    np.ones((2, 2), dtype=np.float32))
    ckpt = table.checkpoint_rows()
    servers[1]._simulate_crash()
    with pytest.raises(ServerLostError):
        table.pull_rows(np.array([15]))
    respawn = _spawn(1)
    table.replace_shard(1, "127.0.0.1", respawn[0].port, restore=ckpt)
    # bit-identical recovery, breaker re-closed, failover counted
    assert np.array_equal(table.checkpoint_rows(), ckpt)
    st = table.stats()
    assert st["failovers"] == 1
    assert st["shards"]["1"]["breaker"] == "closed"
    # the optimizer was re-shipped: grad pushes keep working post-heal
    table.push_grad(np.array([15]), np.ones((1, 2), dtype=np.float32))
    assert np.allclose(table.pull_rows([15]), ckpt[15] - 0.1)
    _teardown(table, servers + respawn)


def test_replace_shard_restore_overwrites_standby_server_rows():
    """replace_shard(restore=...) pointed at a STANDBY server that was
    already initialized must overwrite the stale rows — an idempotent
    no-op ack would silently defeat the checkpoint-restore recovery
    path.  Retried inits with no payload stay idempotent, and a
    conflicting shard spec is a structured error, never a silent keep."""
    servers = _spawn(2)
    init = np.arange(20, dtype=np.float32).reshape(10, 2)
    table = ShardedEmbedding("standby", 10, 2, _addrs(servers),
                             cache_rows=0, init_values=init)
    # re-point shard 0 at the SAME still-initialized server with a
    # restore payload: its rows must become the checkpoint's, not stay
    # at the stale init
    ckpt = init + 100.0
    table.replace_shard(0, "127.0.0.1", servers[0].port, restore=ckpt)
    out = table.pull_rows(np.arange(10))
    assert np.array_equal(out[:5], ckpt[:5])    # shard 0 owns [0,5)
    assert np.array_equal(out[5:], init[5:])    # shard 1 untouched
    # same spec, no payload: idempotent (a transport retry keeps rows)
    reply = table._request(0, {"cmd": "embed_init", "table": "standby",
                               "dim": 2, "row_start": 0, "row_end": 5})
    assert reply["ok"] and reply["rows"] == 5
    assert np.array_equal(table.pull_rows(np.arange(5)), ckpt[:5])
    # a different row range over existing state is a protocol bug
    with pytest.raises(MXNetError, match="different shard spec"):
        table._request(0, {"cmd": "embed_init", "table": "standby",
                           "dim": 2, "row_start": 0, "row_end": 7})
    _teardown(table, servers)


def test_checkpoint_restore_chunked_roundtrip(monkeypatch):
    monkeypatch.setenv("MXNET_EMBED_PULL_CHUNK", "7")   # force chunking
    servers = _spawn(2)
    t1 = ShardedEmbedding("ck1", 23, 3, _addrs(servers), seed=1,
                          cache_rows=0)
    ckpt = t1.checkpoint_rows()
    assert ckpt.shape == (23, 3)
    t2 = ShardedEmbedding("ck2", 23, 3, _addrs(servers), seed=2,
                          cache_rows=0)
    assert not np.array_equal(t2.checkpoint_rows(), ckpt)
    t2.restore_rows(ckpt)
    assert np.array_equal(t2.checkpoint_rows(), ckpt)
    with pytest.raises(MXNetError, match="checkpoint shape"):
        t2.restore_rows(np.zeros((5, 3), np.float32))
    _teardown(t1, [])
    _teardown(t2, servers)


# -- Module.fit integration ---------------------------------------------------

def _click_tower(hidden=16):
    emb = sym.Variable("emb")
    den = sym.Variable("dense")
    deep = sym.FullyConnected(emb, num_hidden=hidden, name="deep1")
    deep = sym.Activation(deep, act_type="relu")
    wide = sym.FullyConnected(den, num_hidden=hidden, name="wide1")
    out = sym.FullyConnected(deep + wide, num_hidden=2, name="head")
    return sym.SoftmaxOutput(out, name="softmax")


def test_module_fit_trains_sharded_table():
    """The wide-and-deep path: ids -> adapter lookup -> Module.fit with
    inputs_need_grad -> batch-end row-sparse push to the shards."""
    rows, dim, n, batch = 64, 4, 128, 16
    servers = _spawn(2)
    table = ShardedEmbedding("wd", rows, dim, _addrs(servers), seed=7,
                             cache_rows=32,
                             optimizer=mx.optimizer.SGD(learning_rate=0.1))
    before = table.checkpoint_rows()
    rng = np.random.RandomState(0)
    ids = rng.randint(0, rows, size=(n, 2)).astype(np.int64)
    dense = rng.randn(n, 4).astype(np.float32)
    label = ((ids[:, 0] + ids[:, 1]) % 2).astype(np.float32)
    base = io.NDArrayIter({"emb": ids.astype(np.float32), "dense": dense},
                          {"softmax_label": label}, batch_size=batch)
    adapter = EmbeddingFitAdapter(table, base, id_field=0)
    assert adapter.provide_data[0].shape == (batch, 2 * dim)

    mod = mx.mod.Module(_click_tower(), data_names=("emb", "dense"),
                        label_names=("softmax_label",), context=mx.cpu())
    mod.bind(data_shapes=adapter.provide_data,
             label_shapes=adapter.provide_label,
             for_training=True, inputs_need_grad=True)
    mod.fit(adapter, num_epoch=2, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1},
            batch_end_callback=adapter.make_callback(mod),
            eval_metric="acc")
    assert adapter.pushes == 2 * (n // batch)
    after = table.checkpoint_rows()
    # the embedding rows actually trained (moved off their init)
    assert not np.array_equal(before, after)
    assert np.isfinite(after).all()
    st = table.stats()
    assert st["cache"]["hit_rate"] > 0      # hot rows stayed device-hot
    assert sum(s["rows_pushed"] for s in st["shards"].values()) > 0
    _teardown(table, servers)


def test_gluon_sparse_embedding_exact_leaf_updates():
    from incubator_mxnet_tpu import autograd
    from incubator_mxnet_tpu.gluon import nn
    servers = _spawn(1)
    init = np.full((10, 3), 2.0, dtype=np.float32)
    table = ShardedEmbedding("gluon", 10, 3, _addrs(servers),
                             cache_rows=0, init_values=init,
                             optimizer=mx.optimizer.SGD(learning_rate=0.5,
                                                        momentum=0.0))
    emb = nn.SparseEmbedding(table)
    assert "10 -> 3" in repr(emb)
    with autograd.record():
        v = emb(mx.nd.array(np.array([[3, 7], [3, 0]], np.float32)))
        loss = (v * v).sum()
    loss.backward()
    emb.push_grads()
    out = table.pull_rows(np.arange(10))
    # dL/dv = 2v = 4; id 3 appears twice -> grad 8, step -0.5*8 = -4
    assert np.allclose(out[3], 2.0 - 4.0)
    assert np.allclose(out[7], 2.0 - 2.0)
    assert np.allclose(out[0], 2.0 - 2.0)
    assert np.allclose(out[[1, 2, 4, 5, 6, 8, 9]], 2.0)
    _teardown(table, servers)


# -- serving ------------------------------------------------------------------

def _emb_tower_fleet(in_dim, n_replicas=2):
    from incubator_mxnet_tpu.serving import LocalReplica
    np.random.seed(0)
    mx.random.seed(0)
    net = sym.FullyConnected(sym.Variable("emb"), num_hidden=3,
                             name="head")
    net = sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, data_names=("emb",),
                        label_names=("softmax_label",), context=mx.cpu())
    mod.bind(data_shapes=[io.DataDesc("emb", (2, in_dim))],
             label_shapes=[io.DataDesc("softmax_label", (2,))],
             for_training=False, grad_req="null")
    mod.init_params(mx.initializer.Xavier())
    args, auxs = mod.get_params()
    served = [mx.serving.ServedModel(net, args, auxs,
                                     data_shapes=[("emb", (1, in_dim))],
                                     buckets=(1, 2, 4), ctx=mx.cpu(),
                                     name="tower")
              for _ in range(n_replicas)]
    return [LocalReplica(s, replica_id=f"r{i}")
            for i, s in enumerate(served)]


def test_serving_path_fans_out_and_survives_shard_kill():
    """The chaos matrix's serving half, in-process: a shard SIGKILL
    mid-traffic is recovered by the on_shard_lost hook (respawn +
    replace_shard) with ZERO lost admitted requests."""
    from incubator_mxnet_tpu.serving import ReplicaRouter
    rows, dim, slots = 40, 4, 2
    servers = _spawn(2)
    table = ShardedEmbedding("serve", rows, dim, _addrs(servers), seed=9,
                             cache_rows=0)     # every lookup hits shards
    ckpt = table.checkpoint_rows()
    state = {"spawned": None}

    def on_shard_lost(err):
        state["spawned"] = _spawn(1)[0]
        table.replace_shard(err.server, "127.0.0.1",
                            state["spawned"].port, restore=ckpt)
        return True

    reps = _emb_tower_fleet(slots * dim)
    with ReplicaRouter(reps, health_interval_s=0.2) as router:
        path = EmbeddingServingPath(table, router, embed_input="emb",
                                    on_shard_lost=on_shard_lost)
        ids = np.array([[1, 30], [5, 25]])
        baseline = path.predict(ids, timeout_ms=10000)[0].asnumpy()
        servers[0]._simulate_crash()          # kill shard 0 mid-traffic
        results = [path.predict(ids, timeout_ms=10000)[0].asnumpy()
                   for _ in range(4)]
        for got in results:
            assert np.allclose(got, baseline)
    st = path.stats()
    assert st["shard_failovers"] >= 1
    assert st["completed"] == st["requests"] == 5   # zero lost
    assert table.stats()["failovers"] == 1
    _teardown(table, [s for s in servers + [state["spawned"]] if s])


def test_serving_path_without_hook_propagates():
    from incubator_mxnet_tpu.serving import ReplicaRouter
    servers = _spawn(1)
    table = ShardedEmbedding("nohook", 8, 4, _addrs(servers),
                             cache_rows=0)
    reps = _emb_tower_fleet(4, n_replicas=1)
    with ReplicaRouter(reps, health_interval_s=0.2) as router:
        path = EmbeddingServingPath(table, router, embed_input="emb")
        servers[0]._simulate_crash()
        with pytest.raises(ServerLostError):
            path.predict(np.array([[1], [2]]), timeout_ms=2000)
    _teardown(table, servers)


# -- kvstore surfaces ---------------------------------------------------------

def test_local_kvstore_has_no_embedding_plane():
    with pytest.raises(MXNetError, match="parameter-server plane"):
        mx.kv.create("local").embedding("t", 10, 2)


def test_dist_kvstore_embedding_factory(monkeypatch):
    servers = _spawn(1)
    for k, v in {"DMLC_PS_ROOT_URI": "127.0.0.1",
                 "DMLC_PS_ROOT_PORT": str(servers[0].port),
                 "DMLC_RANK": "0", "DMLC_NUM_WORKER": "1",
                 "MXNET_KVSTORE_COLLECTIVE": "0"}.items():
        monkeypatch.setenv(k, v)
    kv = mx.kv.create("dist_async")
    assert kv.server_addresses() == [("127.0.0.1", servers[0].port)]
    init = np.arange(12, dtype=np.float32).reshape(6, 2)
    table = kv.embedding("kvfac", 6, 2, cache_rows=0, init_values=init)
    assert np.array_equal(table.pull_rows(np.arange(6)), init)
    # dense keys and the embedding shard share the same server
    kv.init(1, mx.nd.ones((3,)))
    _teardown(table, servers)


# -- cost model / obs ---------------------------------------------------------

def test_embedding_cost_model():
    from incubator_mxnet_tpu.analysis import cost as mxcost
    look = mxcost.analyze_embedding(1_000_000, 128, 4096, kind="lookup")
    op = look.per_op[0]
    row = 128 * 4
    assert op.flops == 0
    assert op.bytes_out == 4096 * row
    assert op.bytes_in == 4096 * row + 4096 * 8
    # rows-touched scaling: the dense table size never enters the traffic
    assert look.param_bytes == 1_000_000 * row
    adam = mxcost.analyze_embedding(1_000_000, 128, 4096, kind="adam")
    aop = adam.per_op[0]
    assert aop.flops == 14 * 4096 * 128
    assert aop.bound == "memory"            # sparse updates stream rows
    assert aop.bytes_in > 3 * 4096 * row    # w + m + v + grad
    with pytest.raises(ValueError, match="kind"):
        mxcost.analyze_embedding(10, 2, 1, kind="nope")


def test_obs_namespace_and_lookup_trace_span():
    from incubator_mxnet_tpu.obs import metrics, trace as obs_trace
    servers = _spawn(2)
    table = ShardedEmbedding("scrape", 30, 2, _addrs(servers), seed=1)
    obs_trace.reset()
    obs_trace.enable()                      # file-less: spans buffer
    try:
        table.lookup(np.array([1, 20, 1]))
        table.lookup(np.array([1, 20, 1]))   # second pass: all hot
    finally:
        obs_trace.disable()
    spans = [s for s in obs_trace.buffered()
             if s["name"] == "embedding.lookup"]
    assert len(spans) == 2 and spans[0]["args"]["rows"] == 3
    flat = metrics.registry().collect()
    assert flat["embedding.scrape.lookups"] == 2
    assert flat["embedding.scrape.lookup_rows"] == 6
    assert flat["embedding.scrape.cache.hit_rate"] == pytest.approx(0.5)
    pulled = sum(flat[f"embedding.scrape.shards.{s}.rows_pulled"]
                 for s in ("0", "1"))
    assert pulled == 2                      # distinct ids only
    assert flat["embedding.scrape.over_hbm_ratio"] >= 0
    metrics.unregister_producer("embedding.scrape")
    _teardown(table, servers)
