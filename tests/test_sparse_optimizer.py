"""Lazy row-sparse optimizer updates (reference `optimizer_op.cc`
sgd/adam lazy_update kernels): touched rows get the exact dense update,
untouched rows keep weight AND state untouched, and the work scales with
the number of touched rows, not the table size."""
import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd
from incubator_mxnet_tpu.ndarray.sparse import RowSparseNDArray


def _row_sparse(rows, vals, shape):
    return RowSparseNDArray(vals, rows, shape)


def test_sgd_momentum_lazy_row_sparse():
    rng = np.random.RandomState(0)
    V, D = 20, 8
    w0 = rng.randn(V, D).astype("f4")
    m0 = rng.randn(V, D).astype("f4") * 0.1
    rows = np.array([2, 5, 11], np.int64)
    gvals = rng.randn(3, D).astype("f4")

    opt = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9, wd=0.01,
                           rescale_grad=0.5, lazy_update=True)
    w = nd.array(w0)
    mom = nd.array(m0)
    opt.update(0, w, _row_sparse(rows, gvals, (V, D)), mom)
    got_w, got_m = w.asnumpy(), mom.asnumpy()

    # reference lazy semantics, computed by hand
    exp_w, exp_m = w0.copy(), m0.copy()
    g = gvals * 0.5 + 0.01 * w0[rows]
    new_m = 0.9 * m0[rows] - 0.1 * g
    exp_m[rows] = new_m
    exp_w[rows] = w0[rows] + new_m
    np.testing.assert_allclose(got_w, exp_w, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(got_m, exp_m, rtol=1e-5, atol=1e-6)
    # untouched rows: bitwise identical (no momentum decay — lazy contract)
    untouched = [i for i in range(V) if i not in rows]
    np.testing.assert_array_equal(got_w[untouched], w0[untouched])
    np.testing.assert_array_equal(got_m[untouched], m0[untouched])


def test_adam_lazy_row_sparse():
    rng = np.random.RandomState(1)
    V, D = 16, 4
    w0 = rng.randn(V, D).astype("f4")
    rows = np.array([0, 7], np.int64)
    gvals = rng.randn(2, D).astype("f4")

    opt = mx.optimizer.Adam(learning_rate=0.01, lazy_update=True)
    w = nd.array(w0)
    mean = nd.zeros((V, D))
    var = nd.zeros((V, D))
    opt.update(0, w, _row_sparse(rows, gvals, (V, D)), (mean, var))
    got_w = w.asnumpy()

    # dense-equivalent math on touched rows (t=1 bias correction)
    lr = 0.01 * np.sqrt(1 - 0.999) / (1 - 0.9)
    m1 = 0.1 * gvals
    v1 = 0.001 * np.square(gvals)
    exp_rows = w0[rows] - lr * m1 / (np.sqrt(v1) + 1e-8)
    np.testing.assert_allclose(got_w[rows], exp_rows, rtol=1e-4, atol=1e-5)
    untouched = [i for i in range(V) if i not in rows]
    np.testing.assert_array_equal(got_w[untouched], w0[untouched])
    np.testing.assert_array_equal(mean.asnumpy()[untouched],
                                  np.zeros((V - 2, D), "f4"))


def test_lazy_empty_grad_is_noop():
    """A row-sparse grad with zero touched rows must change NOTHING —
    neither weights nor momentum decay (the lazy contract)."""
    V, D = 5, 3
    w0 = np.ones((V, D), "f4")
    m0 = np.full((V, D), 0.5, "f4")
    opt = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9, wd=0.01,
                           lazy_update=True)
    w = nd.array(w0)
    mom = nd.array(m0)
    empty = _row_sparse(np.zeros((0,), np.int64), np.zeros((0, D), "f4"),
                        (V, D))
    opt.update(0, w, empty, mom)
    np.testing.assert_array_equal(w.asnumpy(), w0)
    np.testing.assert_array_equal(mom.asnumpy(), m0)


def test_lazy_update_does_not_invalidate_aliases():
    """detach()'d views of the weight must stay readable after a lazy
    step (no buffer donation on this path)."""
    V, D = 6, 2
    w = nd.array(np.ones((V, D), "f4"))
    snap = w.detach()
    opt = mx.optimizer.SGD(learning_rate=0.1, lazy_update=True)
    g = _row_sparse(np.array([1], np.int64), np.ones((1, D), "f4"), (V, D))
    opt.update(0, w, g, None)
    np.testing.assert_array_equal(snap.asnumpy(), np.ones((V, D), "f4"))


def test_lazy_update_off_densifies():
    """lazy_update=False keeps the reference's dense behavior: momentum
    decays on EVERY row."""
    V, D = 6, 3
    w0 = np.ones((V, D), "f4")
    m0 = np.full((V, D), 0.5, "f4")
    rows = np.array([1], np.int64)
    gvals = np.ones((1, D), "f4")

    opt = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9,
                           lazy_update=False)
    w = nd.array(w0)
    mom = nd.array(m0)
    opt.update(0, w, _row_sparse(rows, gvals, (V, D)), mom)
    got_m = mom.asnumpy()
    # untouched rows decayed: m = 0.9 * 0.5 = 0.45
    assert np.allclose(got_m[0], 0.45), got_m[0]
