"""INT8 quantization tests (reference tests/python/quantization/
test_quantization.py strategy: quantized graph stays close to fp32)."""
import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd
from incubator_mxnet_tpu.contrib.quantization import (quantize_model,
                                                      _kl_optimal_threshold)


def _convnet():
    data = mx.sym.Variable("data")
    c = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                           name="conv0")
    c = mx.sym.Activation(c, act_type="relu")
    p = mx.sym.Pooling(c, kernel=(2, 2), stride=(2, 2), pool_type="max",
                       name="pool0")
    f = mx.sym.Flatten(p)
    out = mx.sym.FullyConnected(f, num_hidden=10, name="fc0")
    return out


def _init_params(sym, data_shape):
    arg_shapes, _, aux_shapes = sym.infer_shape(data=data_shape)
    rng = np.random.RandomState(0)
    args = {}
    for name, s in zip(sym.list_arguments(), arg_shapes):
        if name == "data":
            continue
        args[name] = nd.array(rng.normal(0, 0.5, s).astype("f4"))
    auxs = {name: nd.zeros(s) for name, s in
            zip(sym.list_auxiliary_states(), aux_shapes)}
    return args, auxs


def _fp32_out(sym, args, auxs, x):
    exe = sym.simple_bind(ctx=mx.cpu(), grad_req="null", data=x.shape)
    exe.copy_params_from(args, auxs)
    return exe.forward(is_train=False, data=nd.array(x))[0].asnumpy()


def _q_out(qsym, qargs, auxs, x):
    exe = qsym.simple_bind(ctx=mx.cpu(), grad_req="null", data=x.shape)
    exe.copy_params_from(qargs, auxs, allow_extra_params=True)
    return exe.forward(is_train=False, data=nd.array(x))[0].asnumpy()


def test_quantized_convnet_close_to_fp32():
    sym = _convnet()
    x = np.random.RandomState(1).normal(0, 1, (4, 3, 8, 8)).astype("f4")
    args, auxs = _init_params(sym, x.shape)
    ref = _fp32_out(sym, args, auxs, x)

    qsym, qargs, qauxs = quantize_model(sym, args, auxs, calib_mode="none")
    out = _q_out(qsym, qargs, qauxs, x)
    # int8 tolerance: relative to the dynamic range of the output
    scale = np.abs(ref).max()
    assert np.abs(out - ref).max() / scale < 0.1, \
        (np.abs(out - ref).max(), scale)
    # int8 logits keep the argmax on most samples
    agree = (out.argmax(1) == ref.argmax(1)).mean()
    assert agree >= 0.75, agree


def test_quantized_calibrated_modes():
    sym = _convnet()
    rng = np.random.RandomState(2)
    x = rng.normal(0, 1, (4, 3, 8, 8)).astype("f4")
    args, auxs = _init_params(sym, x.shape)
    ref = _fp32_out(sym, args, auxs, x)
    calib = mx.io.NDArrayIter(rng.normal(0, 1, (16, 3, 8, 8)).astype("f4"),
                              batch_size=4)
    for mode in ("naive", "entropy"):
        calib.reset()
        qsym, qargs, qauxs = quantize_model(
            sym, args, auxs, calib_mode=mode, calib_data=calib,
            num_calib_examples=16)
        out = _q_out(qsym, qargs, qauxs, x)
        scale = np.abs(ref).max()
        assert np.abs(out - ref).max() / scale < 0.15, mode
        # calibrated graphs carry static ranges: no dynamic min/max in sym
        js = qsym.tojson()
        assert "min_calib_range" in js, mode


def test_kl_threshold_clips_outliers():
    rng = np.random.RandomState(3)
    arr = rng.normal(0, 1, 20000)
    arr[0] = 100.0    # one extreme outlier
    thr = _kl_optimal_threshold(arr)
    assert thr < 50.0, thr       # the KL optimum clips the outlier
    assert thr > 1.0, thr        # but keeps the bulk of the distribution
